"""Deferred op bulking — the engine's answer to per-dispatch latency.

Reference: the dependency engine's op-bulking API (include/mxnet/engine.h:310-317
``Engine::{Start,Stop}Bulk``) and the CachedOp bulking knob
(src/imperative/cached_op.h:330): consecutive imperative ops are batched into one
engine op because per-op dispatch overhead — not compute — bounds imperative-mode
throughput.

TPU-native design: consecutive ``invoke()`` calls accumulate into a ``Segment`` —
a small SSA graph of *pending* jax calls, shape-checked immediately via
``jax.eval_shape`` (so user errors still surface at the call site) but not
executed. When a value is materialized (``asnumpy``, ``wait_to_read``,
``item``, crossing into non-traced code), the whole segment flushes as ONE
jitted XLA program. The compiled replay is cached on a structural key (per-op
identity keys + argument avals + output liveness), so a steady-state training
loop pays O(1) dispatches per iteration regardless of op count — the same
amortization the reference's engine bulking buys, but with full XLA fusion
across the bulk instead of mere queue batching.

Op identity keys are derived automatically from the dispatched callable:
``functools.partial`` over a stable function with hashable statics, or a
closure whose cells canonicalize to hashables (code objects are per-definition-
site constants, so ``(code, cells, defaults)`` fully determines the
computation). Anything unkeyable — closures over arrays, value-dependent
shapes — falls back to the immediate eager path, preserving semantics.

Staleness contract: statics that canonicalize by object identity (callables,
functors, bound-method receivers) follow the same rules as ``jax.jit`` /
``hybridize``: the computation is cached against the object's identity, so
mutating such an object's attributes after the first call does not retrace.
This is exactly the reference CachedOp contract (re-hybridize after mutating
a block); use ``engine.set_bulk_size(0)`` or NaiveEngine for fully dynamic
closures.
"""
from __future__ import annotations

import functools
import threading
import types
import weakref
from collections import OrderedDict

import numpy as _np

from ..base import get_env
from ..telemetry.registry import stats_group as _stats_group

__all__ = ["enabled", "enqueue", "derive_key", "derive_key_cached",
           "flush_all", "current_size", "Reject", "canon", "DISPATCH_STATS"]

# Dispatch observability (ROADMAP open item 6): one flat counter dict shared
# by the whole dispatch stack. segment.py owns it because it is the lowest
# module in the ops dependency chain — registry.py (fast-path / key / vjp
# counters) and this module (bulking-cache counters) both increment it, and
# profiler.dispatch_stats() / engine.stats() read it. Plain int += under the
# GIL: the counters are diagnostics, exact cross-thread interleaving does
# not matter. Adopted into the telemetry registry as the `dispatch` stats
# group (telemetry/registry.py StatsGroup): the hot path is still a native
# dict write — the group only adds atomic snapshot(reset) and membership in
# telemetry.snapshot()/prometheus_text().
DISPATCH_STATS = _stats_group("dispatch", {
    "dispatch": 0,            # total ops.registry.invoke() calls
    "bulked": 0,              # invokes deferred into a Segment
    "fast_path": 0,           # immediate invokes served by a cached compiled kernel
    "eager_fallback": 0,      # immediate invokes executed op-by-op (unkeyed/unjittable)
    "key_cache_hit": 0, "key_cache_miss": 0,       # derive_key memo
    "jit_cache_hit": 0, "jit_cache_miss": 0,       # compiled immediate kernels
    "vjp_cache_hit": 0, "vjp_cache_miss": 0,       # cached VJP kernels (backward)
    "vjp_trace": 0,           # python-level jax.vjp (re)traces actually run
    "amp_wrap_cache_hit": 0, "amp_wrap_cache_miss": 0,
    "replay_cache_hit": 0, "replay_cache_miss": 0,  # bulked-segment replays
    "aval_cache_hit": 0, "aval_cache_miss": 0,      # eval_shape memo
    "segment_flush": 0,
}, help="eager-dispatch counters (profiler.dispatch_stats)")

_MAX_OPS_DEFAULT = 4096
# Replay entries hold a jitted callable whose closure carries no array
# buffers (call sites strip them), so the cap guards compile-cache count,
# not device memory — size it well above what a few workloads' steady-state
# segment variants need, or LRU thrashing recompiles every iteration.
_REPLAY_CACHE_CAP = 512
_AVAL_CACHE_CAP = 65536


class Reject(Exception):
    """Raised when a value cannot be canonicalized into a stable cache key."""


_jax_data_classes = None


def _jax_data_types():
    global _jax_data_classes
    if _jax_data_classes is None:
        import jax
        _jax_data_classes = (jax.Array, jax.core.Tracer)
    return _jax_data_classes


# ---------------------------------------------------------------------------
# key derivation
# ---------------------------------------------------------------------------
_HASHABLE_LEAVES = (type(None), bool, int, float, complex, str, bytes, type,
                    _np.dtype, range, frozenset)


def canon(x):
    """Canonicalize a static value into a hashable key token, or Reject.

    Arrays (NDArray / jax / numpy) are rejected: a closure capturing an array
    is a hidden data dependency that must be traced, never baked into a key.
    Functions and other identity-hashable objects key by identity — safe
    because identity implies the same behavior (and the cache holds a strong
    reference, so ids cannot be reused).
    """
    if isinstance(x, _HASHABLE_LEAVES):
        return x
    tx = type(x)
    if tx is slice:
        # slice objects are unhashable before py3.12 — tokenize components
        # (getitem/setitem closures carry them; this keeps slicing bulkable)
        return ("sl", canon(x.start), canon(x.stop), canon(x.step))
    if tx in (tuple, list):
        return (tx.__name__, tuple(canon(v) for v in x))
    if tx is dict:
        return ("d", tuple(sorted((k, canon(v)) for k, v in x.items())))
    if tx in (set, frozenset):
        return ("s", tuple(sorted(map(canon, x), key=repr)))
    if isinstance(x, _np.generic):  # numpy scalar: hashable, value-stable
        return x
    if isinstance(x, _np.ndarray):
        raise Reject
    if isinstance(x, functools.partial):
        # partials captured as *statics* (vjp closures, per-call wrappers)
        # typically wrap residual buffers and are one-shot: identity-keying
        # them would recompile every call AND pin device memory in the caches
        raise Reject
    if isinstance(x, _jax_data_types()):  # jax.Array / tracers: must be traced
        raise Reject
    if hasattr(x, "_entry") and hasattr(x, "_data"):  # duck-typed NDArray
        raise Reject
    try:
        hash(x)
    except TypeError:
        raise Reject from None
    return x


def derive_key(fn):
    """Best-effort stable identity key for a dispatched callable, or None."""
    if isinstance(fn, functools.partial):
        fk = derive_key(fn.func)
        if fk is None:
            return None
        try:
            return ("p", fk, canon(fn.args), canon(fn.keywords))
        except Reject:
            return None
    if isinstance(fn, types.MethodType):
        try:
            return ("m", fn.__func__.__code__, canon(fn.__self__))
        except Reject:
            return None
    if isinstance(fn, types.FunctionType):
        try:
            cells = tuple(canon(c.cell_contents)
                          for c in (fn.__closure__ or ()))
            dflts = canon(fn.__defaults__)
        except (Reject, ValueError):  # ValueError: empty cell
            return None
        return ("f", fn.__code__, cells, dflts)
    if isinstance(fn, types.BuiltinFunctionType):
        return ("b", fn)
    if callable(fn):
        # callable object (jitted wrapper, functor): identity key. Safe:
        # same object => same behavior; cache strong-refs it so the id
        # cannot be recycled.
        try:
            hash(fn)
        except TypeError:
            return None
        return ("o", fn)
    return None


# derive_key memo. Only plain functions WITHOUT closure cells are memoized:
# their key (code, (), defaults) cannot drift (rebinding a cell must change
# the key, so closures stay on the uncached path) and cannot reference fn
# itself. Identity-keyed callables and builtins are deliberately NOT
# memoized: their keys ("o", fn) / ("b", fn) strong-ref fn, and a WeakKey
# entry whose value strong-refs its key is immortal — while deriving those
# keys is a hash() away regardless. partials recurse so their (usually
# module-level) .func hits the memo even though the partial itself is fresh
# per call. Sentinel distinguishes "cached as unkeyable" from "not cached".
_KEY_MEMO = weakref.WeakKeyDictionary()
_NO_KEY = object()


def _key_memoizable(fn):
    return isinstance(fn, types.FunctionType) and not fn.__closure__


def derive_key_cached(fn):
    """derive_key with a WeakKey memo for drift-free callables."""
    if isinstance(fn, functools.partial):
        fk = derive_key_cached(fn.func)
        if fk is None:
            return None
        try:
            return ("p", fk, canon(fn.args), canon(fn.keywords))
        except Reject:
            return None
    try:
        k = _KEY_MEMO.get(fn)
    except TypeError:        # unhashable callable
        k = None
    if k is not None:
        DISPATCH_STATS["key_cache_hit"] += 1
        return None if k is _NO_KEY else k
    DISPATCH_STATS["key_cache_miss"] += 1
    k = derive_key(fn)
    if _key_memoizable(fn):
        try:
            # memo write is idempotent (same fn -> same key) and the hot
            # path tolerates a lost race: GIL-atomic dict store by design,
            # like the DISPATCH_STATS increments around it
            _KEY_MEMO[fn] = _NO_KEY if k is None else k  # mxlint: disable=lock-shared-mutation -- idempotent GIL-atomic memo store on the per-op hot path
        except TypeError:
            pass
    return k


# ---------------------------------------------------------------------------
# segment machinery
# ---------------------------------------------------------------------------
class _LazyVal:
    """A pending op output: aval now, concrete buffer after flush."""

    __slots__ = ("seg", "op_idx", "leaf_idx", "aval", "value", "__weakref__")

    def __init__(self, seg, op_idx, leaf_idx, aval):
        self.seg = seg
        self.op_idx = op_idx
        self.leaf_idx = leaf_idx
        self.aval = aval
        self.value = None

    def force(self):
        if self.value is None:
            self.seg.flush()
            if self.value is None:
                raise self.seg.error or RuntimeError(
                    "deferred op output was garbage-collected before flush")
        return self.value


class _PendingOp:
    __slots__ = ("key", "fn", "handles", "desc", "baked", "out_refs", "name")

    def __init__(self, key, fn, handles, desc, baked, name):
        self.key = key
        self.fn = fn
        self.handles = handles    # ('c', slot) | ('s', op, leaf) | ('b', i)
        self.desc = desc          # hashable per-arg descriptors for seg_key
        self.baked = baked
        self.out_refs = []        # weakrefs to _LazyVals
        self.name = name


class Segment:
    __slots__ = ("ops", "consts", "const_ids", "flushed", "error", "lock",
                 "__weakref__")

    def __init__(self):
        self.ops = []
        self.consts = []
        self.const_ids = {}
        self.flushed = False
        self.error = None
        self.lock = threading.RLock()
        with _registry_lock:
            _live_segments.add(self)

    def const_slot(self, a, dedupe_id=None):
        if dedupe_id is not None:
            slot = self.const_ids.get(dedupe_id)
            if slot is not None:
                return slot
        slot = len(self.consts)
        self.consts.append(a)
        if dedupe_id is not None:
            self.const_ids[dedupe_id] = slot
        return slot

    def flush(self):
        with self.lock:
            return self._flush_locked()

    def _flush_locked(self):
        if self.flushed:
            if self.error is not None:
                raise self.error
            return
        self.flushed = True
        _maybe_clear_current(self)
        if not self.ops:
            return
        DISPATCH_STATS["segment_flush"] += 1
        import jax
        import jax.tree_util as jtu

        outs_spec = []
        strong = []
        key_parts = []
        for i, op in enumerate(self.ops):
            mask = []
            for j, wr in enumerate(op.out_refs):
                lv = wr()
                alive = lv is not None
                mask.append(alive)
                if alive:
                    outs_spec.append((i, j))
                    strong.append(lv)
            key_parts.append((op.key, tuple(op.desc), tuple(mask)))

        # Donate consts nothing else owns (old param/state/activation
        # buffers the update chain replaced): without donation the program
        # holds every input alive across execution, doubling peak memory —
        # ruinous on small-HBM slices. Sole ownership == the consts list is
        # the only reference (getrefcount: consts entry + local + arg = 3).
        # Optional refcount-based donation of sole-owned consts
        # (MXNET_BULK_DONATE=1). Default OFF: the donate mask depends on
        # buffer lifetimes, and any per-iteration flicker becomes a new
        # compile-cache key — a compile storm. The structural wins (the
        # optimizer update joining the segment + layout-pinned compiles)
        # don't need it.
        import sys
        consts = self.consts
        if get_env("MXNET_BULK_DONATE", "0") in ("1", "true"):
            donate = []
            for c in consts:
                donate.append(isinstance(c, jax.Array)
                              and not isinstance(c, jax.core.Tracer)
                              and sys.getrefcount(c) == 3)
        else:
            donate = [False] * len(consts)
        slot_map = []          # const slot -> (donated?, index within list)
        n_d = n_k = 0
        for d in donate:
            if d:
                slot_map.append((True, n_d))
                n_d += 1
            else:
                slot_map.append((False, n_k))
                n_k += 1
        # Boundary layouts: every replay is a plain jax.jit, so its inputs
        # and outputs use DEFAULT device layouts. Steady-state loops feed
        # replay outputs back as the next replay's consts (the optimizer
        # update joins the segment), so the boundary is default-to-default:
        # no PJRT relayout copies, and — critically — no layout-signature
        # chase in the cache key (keying on concrete layouts never
        # converges when producing executables pick fresh layouts).
        seg_key = (tuple(key_parts), tuple(donate))

        entry = _replay_cache_get(seg_key)
        if entry is None:
            ops_snap = list(self.ops)
            spec = list(outs_spec)
            smap = list(slot_map)

            def replay(dons, keeps):
                env = {}
                for i, op in enumerate(ops_snap):
                    args = []
                    for h in op.handles:
                        k = h[0]
                        if k == "c":
                            d, j = smap[h[1]]
                            args.append(dons[j] if d else keeps[j])
                        elif k == "s":
                            args.append(env[(h[1], h[2])])
                        else:
                            args.append(op.baked[h[1]])
                    out = op.fn(*args)
                    for j, leaf in enumerate(jtu.tree_leaves(out)):
                        env[(i, j)] = leaf
                return [env[s] for s in spec]

            entry = jax.jit(replay, donate_argnums=(0,))
            _replay_cache_put(seg_key, entry)

        dons = [c for c, d in zip(consts, donate) if d]
        keeps = [c for c, d in zip(consts, donate) if not d]
        _tls.suspended = getattr(_tls, "suspended", 0) + 1
        try:
            from ..fault import inject as _fault_inject
            _fault_inject("engine.flush")
            results = entry(dons, keeps)
        except Exception as e:  # deferred-error semantics (SURVEY §5.3):
            self.error = e      # the error surfaces at the wait point
            self.ops = None
            self.consts = None
            self.const_ids = None
            raise
        finally:
            _tls.suspended -= 1
        for lv, r in zip(strong, results):
            lv.value = r
        # release the graph so intermediate buffers free eagerly
        self.ops = None
        self.consts = None
        self.const_ids = None


# ---------------------------------------------------------------------------
# module state
# ---------------------------------------------------------------------------
_tls = threading.local()
_registry_lock = threading.Lock()
_live_segments = weakref.WeakSet()  # every unflushed segment, any thread
_replay_cache = OrderedDict()   # seg_key -> jitted replay
_aval_cache = OrderedDict()     # (op_key, arg aval keys) -> (treedef, leaf avals)


def _replay_cache_get(key):
    entry = _replay_cache.get(key)
    if entry is not None:
        _replay_cache.move_to_end(key)
        DISPATCH_STATS["replay_cache_hit"] += 1
    else:
        DISPATCH_STATS["replay_cache_miss"] += 1
    return entry


def _replay_cache_put(key, entry):
    _replay_cache[key] = entry
    while len(_replay_cache) > _REPLAY_CACHE_CAP:
        _replay_cache.popitem(last=False)


def _current(create=True):
    seg = getattr(_tls, "seg", None)
    if (seg is None or seg.flushed) and create:
        seg = Segment()
        _tls.seg = seg
    return seg


def _maybe_clear_current(seg):
    if getattr(_tls, "seg", None) is seg:
        _tls.seg = None


def flush_all():
    """Flush every thread's pending segment (≙ Engine::WaitForAll prefix).
    Like the reference's WaitForAll, ops pushed concurrently after this call
    starts are not covered."""
    with _registry_lock:
        segs = list(_live_segments)
    err = None
    for seg in segs:
        if not seg.flushed:
            try:
                seg.flush()
            # flush() has already restored by the time this handler runs:
            # it clears its op/const refs and records self.error before
            # re-raising (the SURVEY §5.3 deferred-error contract), so
            # deferring `err` here cannot leak a donated buffer.
            # mxlint: disable=donation-unrestored-on-error -- restored above
            except Exception as e:   # surface after flushing the rest
                err = e
    if err is not None:
        raise err


def current_size():
    seg = getattr(_tls, "seg", None)
    return 0 if seg is None or seg.flushed or seg.ops is None else len(seg.ops)


def enabled():
    """Bulking active? Controlled by the engine facade (set_bulk_size /
    MXNET_ENGINE_BULK_SIZE; 0 disables), forced off under NaiveEngine and
    while abstract evaluation / replay tracing is in flight (re-entrant
    invokes — e.g. a custom Function's python backward — must run
    immediately)."""
    if getattr(_tls, "suspended", 0):
        return False
    from .. import engine
    return engine.effective_bulk_size() > 0


def _max_ops():
    from .. import engine
    return engine.effective_bulk_size()


# ---------------------------------------------------------------------------
# enqueue
# ---------------------------------------------------------------------------
_SCALAR_TYPES = (bool, int, float, complex)


def _is_float0(a):
    import jax
    return isinstance(a, _np.ndarray) and a.dtype == jax.dtypes.float0


def enqueue(fn, raw, key, name=""):
    """Append one op to the current segment.

    `raw`: positional args — concrete jax/numpy arrays, _LazyVal handles,
    python scalars, or canonicalizable statics. Returns (treedef,
    lazy_nd_leaves) on success, or None when the op cannot be deferred
    (caller falls back to immediate execution).
    """
    import jax

    seg = _current()
    if seg.ops is not None and len(seg.ops) >= _max_ops():
        seg.flush()
        seg = _current()
    with seg.lock:
        return _enqueue_locked(seg, fn, raw, key, name)


def _enqueue_locked(seg, fn, raw, key, name):
    import jax

    handles, desc, baked, eval_args, akeys = [], [], [], [], []
    try:
        for a in raw:
            if type(a) is _LazyVal:
                if a.value is not None:
                    a = a.value
                elif a.seg is not seg:
                    a = a.force()   # cross-segment: materialize
            if type(a) is _LazyVal:
                handles.append(("s", a.op_idx, a.leaf_idx))
                desc.append(("s", a.op_idx, a.leaf_idx))
                sh, dt = tuple(a.aval.shape), a.aval.dtype
                eval_args.append(jax.ShapeDtypeStruct(sh, dt))
                akeys.append(("s", sh, str(dt)))
            elif isinstance(a, jax.Array):
                slot = seg.const_slot(a, dedupe_id=id(a))
                sh, dt = tuple(a.shape), a.dtype
                weak = bool(getattr(a, "weak_type", False))
                handles.append(("c", slot))
                desc.append(("c", slot, sh, str(dt), weak))
                # abstract for every rank — a concrete 0-d arg would let
                # value-dependent-shape ops cache shapes keyed only by aval,
                # so a later call with a different scalar value would read
                # stale shapes; such ops now fail eval_shape and fall back
                # to immediate execution instead
                eval_args.append(jax.ShapeDtypeStruct(sh, dt,
                                                      weak_type=weak))
                akeys.append(("a", sh, str(dt), weak))
            elif isinstance(a, _np.ndarray):
                if a.dtype == jax.dtypes.float0:
                    # symbolic-zero cotangent: always zeros — bake as static
                    bidx = len(baked)
                    baked.append(a)
                    tok = ("f0", tuple(a.shape))
                    handles.append(("b", bidx))
                    desc.append(tok)
                    eval_args.append(a)
                    akeys.append(tok)
                else:
                    slot = seg.const_slot(a, dedupe_id=id(a))
                    sh = tuple(a.shape)
                    handles.append(("c", slot))
                    desc.append(("c", slot, sh, str(a.dtype), False))
                    eval_args.append(jax.ShapeDtypeStruct(sh, a.dtype))
                    akeys.append(("a", sh, str(a.dtype), False))
            elif type(a) in _SCALAR_TYPES:
                # runtime scalar arg: weak-typed under jit exactly as in
                # the eager call, and value changes don't recompile
                slot = seg.const_slot(a)
                handles.append(("c", slot))
                desc.append(("c", slot, "py", type(a)))
                eval_args.append(a)
                akeys.append(("py", type(a)))
            elif isinstance(a, _np.generic):
                slot = seg.const_slot(a)
                handles.append(("c", slot))
                desc.append(("c", slot, "npg", a.dtype.str))
                eval_args.append(a)
                akeys.append(("npg", a.dtype.str))
            else:
                tok = ("bk", canon(a))
                bidx = len(baked)
                baked.append(a)
                handles.append(("b", bidx))
                desc.append(tok)
                eval_args.append(a)
                akeys.append(tok)
    except Reject:
        return None

    aval_key = (key, tuple(akeys))
    cached = _aval_cache.get(aval_key)
    DISPATCH_STATS["aval_cache_hit" if cached is not None
                   else "aval_cache_miss"] += 1
    if cached is None:
        import jax.tree_util as jtu
        _tls.suspended = getattr(_tls, "suspended", 0) + 1
        try:
            out_struct = jax.eval_shape(fn, *eval_args)
        except Exception:
            return None   # not abstractly evaluable (value-dependent shape,
            # genuine user error, ...): the eager fallback re-raises for real
        finally:
            _tls.suspended -= 1
        leaves, treedef = jtu.tree_flatten(out_struct)
        if not all(hasattr(l, "shape") and hasattr(l, "dtype")
                   for l in leaves):
            return None
        cached = (treedef, tuple(leaves))
        _aval_cache[aval_key] = cached
        while len(_aval_cache) > _AVAL_CACHE_CAP:
            _aval_cache.popitem(last=False)
    treedef, leaf_avals = cached

    if seg.flushed:
        # a re-entrant materialization during abstract eval flushed the
        # segment under us; symbolic handles are stale — the caller falls
        # back to immediate execution (lazy args are concrete now)
        return None

    op = _PendingOp(key, fn, handles, desc, baked, name)
    op_idx = len(seg.ops)
    lazies = []
    for j, aval in enumerate(leaf_avals):
        lv = _LazyVal(seg, op_idx, j, aval)
        op.out_refs.append(weakref.ref(lv))
        lazies.append(lv)
    seg.ops.append(op)
    return treedef, lazies
