"""Single-choke-point op dispatch with autograd taping and op bulking.

Reference: Imperative::Invoke → SetShapeType → PushFCompute
(src/imperative/imperative.cc:49-140, imperative_utils.h:648) plus the
engine's op-bulking API (include/mxnet/engine.h:310-317,
src/imperative/cached_op.h:330). TPU-native: `invoke(fn, args)` unwraps
NDArrays and either

  * defers the jax call into the current bulking Segment (ops/segment.py) —
    consecutive eager ops compile and dispatch as ONE cached XLA program at
    the next materialization point, amortizing per-dispatch latency the way
    the reference's engine bulking does; or
  * runs the jax function immediately (NaiveEngine, bulking disabled, or the
    op is not deferrable). PR2 fast path: keyed immediate dispatches go
    through a per-key cache of `jax.jit`-compiled kernels — the eager analog
    of the reference's CachedOp (cached_op.cc:665), so a bulking-disabled
    loop pays one compiled-dispatch per op instead of an op-by-op jax eager
    walk through fn's python body. Unkeyable or unjittable callables fall
    back to the plain eager call (semantics preserved; the key is
    blacklisted so the probe happens once).

When autograd is recording, keyed ops (bulked OR immediate) tape the forward
callable + inputs and re-linearize at backward time: the `jax.vjp` runs
inside a cached compiled kernel keyed by (op key, single, n_in), so repeat
(key, avals) backwards never retrace in Python (≙ CachedOp's cached backward
graph). Unkeyed immediate ops capture a per-call `jax.vjp` closure as before
(≙ Imperative::RecordOp, imperative.cc:210).

Dispatch-stats counters live in segment.DISPATCH_STATS; read them via
`dispatch_stats()` here, `profiler.dispatch_stats()`, or `engine.stats()`.
"""
from __future__ import annotations

import threading
import types as _types
from collections import OrderedDict

import numpy as _np

from .. import autograd
from ..base import MXNetError, get_env
from . import segment as _seg

_OP_REGISTRY = {}
_STATS = _seg.DISPATCH_STATS

# Compiled immediate kernels: op key -> (jax.jit(fn), fn). The strong fn ref
# pins identity-keyed callables so their ids cannot recycle (same contract as
# the segment replay cache). Keys whose fn proves jit-hostile (trace errors)
# land in _JIT_BAD and dispatch eagerly from then on. All three LRU caches
# share one lock (get/move_to_end/popitem sequences are not atomic, and
# DataLoader/prefetch worker threads dispatch concurrently with training);
# kernel EXECUTION happens outside the lock.
_cache_lock = threading.Lock()
_JIT_CACHE_CAP = 1024
_JIT_CACHE = OrderedDict()
_JIT_BAD_CAP = 4096
_JIT_BAD = OrderedDict()            # key -> True (LRU-capped set)
# AMP-wrapped forward variants: (key, dtype, cast_pos) -> wrapped fn, so the
# per-call closure allocation happens once per (op, autocast shape) instead
# of every dispatch.
_AMP_WRAP_CAP = 2048
_AMP_WRAP_CACHE = OrderedDict()

_jit_enabled_override = [None]      # None = follow MXNET_DISPATCH_JIT


def _jit_enabled():
    if _jit_enabled_override[0] is not None:
        return _jit_enabled_override[0]
    on = get_env("MXNET_DISPATCH_JIT", "1") not in ("0", "false")
    _jit_enabled_override[0] = on    # snapshot; set_dispatch_jit() overrides
    return on


def set_dispatch_jit(flag):
    """Toggle the compiled-kernel immediate fast path at runtime (knob for
    debugging / A-B measurement; env: MXNET_DISPATCH_JIT). Returns previous
    effective setting; pass None to re-read the env var."""
    prev = _jit_enabled()
    _jit_enabled_override[0] = None if flag is None else bool(flag)
    return prev


def dispatch_stats(reset=False):
    """Snapshot of the dispatch counters (dispatch count, fast-path hits,
    key/jit/vjp-cache hits, bulking-cache hits, flush count). Observable via
    profiler.dispatch_stats() and engine.stats(); the same counters surface
    in telemetry.snapshot() as `dispatch.*` (the dict is a registry-adopted
    StatsGroup). snapshot+zero is one atomic step."""
    return _STATS.snapshot(reset=reset)


class OpInfo:
    """Registry entry ≙ nnvm::Op attrs — PR2: a slotted dispatch record.

    Built once at register_op time so call-time dispatch does no per-call
    policy work: `key` is the stable bulking/jit-cache identity derived from
    `fn`, and `amp` is the registration-declared AMP class ('safe' = run in
    the autocast low-precision dtype, 'unsafe' = pin fp32, 'neutral' = no
    class of its own — note the amp/lists.py name lists always take
    precedence when they know the op name, whatever the class here).

    `key` is only precomputed for callables whose key cannot drift
    (closures/bound methods may rebind cells, so freezing their key at
    registration would serve stale kernels — they derive per call instead,
    same as the derive_key_cached memo policy).

    `layout` records the data layout of layout-sensitive ops (conv/pool/
    fused kernels): the last layout the op dispatched with ("NHWC"/"NCHW"
    ...), written by the npx wrappers via `note_layout`. Introspection for
    the layout-autotune lever (ROADMAP item 2): `get_op(name).layout`
    shows which layout a model actually ran, and the bench `fused_sweep`
    phase records its NHWC/NCHW A-B winner next to it."""

    __slots__ = ("name", "fn", "amp", "doc", "key", "layout")

    def __init__(self, name, fn, amp="neutral", doc=""):
        self.name = name
        self.fn = fn
        self.amp = amp
        self.doc = doc
        self.layout = None
        drift_free = not (
            (isinstance(fn, _types.FunctionType) and fn.__closure__)
            or isinstance(fn, _types.MethodType))
        self.key = _seg.derive_key_cached(fn) if drift_free else None


def register_op(name, fn=None, amp="neutral", doc=""):
    """Register an op (decorator or direct). ≙ NNVM_REGISTER_OP."""
    def _reg(f):
        _OP_REGISTRY[name] = OpInfo(name, f, amp, doc or (f.__doc__ or ""))
        return f
    if fn is not None:
        return _reg(fn)
    return _reg


def note_layout(op, layout):
    """Record the layout a layout-sensitive op dispatched with on its
    dispatch record (a single benign attribute write — last writer wins;
    the record is introspection, not dispatch state)."""
    if op is not None and layout is not None:
        op.layout = layout


def get_op(name):
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops():
    return sorted(_OP_REGISTRY)


def record_key(base_key, kwargs):
    """Dispatch key for a record's precomputed base key + call kwargs —
    exactly derive_key's `functools.partial` form (same tokens, so wrapper
    call sites and apply_op share one kernel per (op, kwargs))."""
    if base_key is None:
        return None
    if not kwargs:
        return base_key
    try:
        return ("p", base_key, ("tuple", ()), _seg.canon(kwargs))
    except _seg.Reject:
        return None


def apply_op(name, *args, **kwargs):
    """Invoke a registered op by name on NDArray/array args. Uses the
    record's precomputed key so keyword variants derive only the kwargs
    part."""
    import functools
    info = get_op(name)
    fn = functools.partial(info.fn, **kwargs) if kwargs else info.fn
    return invoke(fn, args, name=name, key=record_key(info.key, kwargs),
                  op=info)


# ---------------------------------------------------------------------------
# AMP resolution — name lists first (user overrides win), the record's
# declared class only for names the lists don't know; both memoized
# ---------------------------------------------------------------------------
_amp_mod = [None]
_amp_name_cache = {}                # name -> (lists_version, dtype-or-None)


def _amp_dtype(name, op=None):
    """AMP policy lookup (lazy import so amp stays optional).

    Name lists first (so amp.init(fp32_ops=...) user overrides keep
    winning), memoized per (name, lists version); the dispatch record's
    registration-declared class covers ops the lists don't know."""
    amp = _amp_mod[0]
    if amp is None:
        import sys
        amp = sys.modules.get("incubator_mxnet_tpu.amp")
        if amp is None:
            return None
        _amp_mod[0] = amp
    if not amp.is_active():
        return None
    ver = amp.lists_version()
    hit = _amp_name_cache.get(name)
    if hit is None or hit[0] != ver:
        hit = (ver, amp.amp_dtype_for(name))
        _amp_name_cache[name] = hit
    dt = hit[1]
    if dt is None and op is not None and op.amp != "neutral":
        return amp.target_dtype() if op.amp == "safe" else "float32"
    return dt


def _amp_cast(r, dtype):
    if isinstance(r, (_jax.Array, _np.ndarray)) and _is_float_dtype(r.dtype) \
            and str(r.dtype) != dtype:
        return r.astype(dtype)
    return r


def _cast_positions(raw, amp_dt):
    """Positions the eager autocast loop would cast (handles _LazyVal
    placeholders on the bulked path; raw lazies are forced before the
    immediate path uses this)."""
    return tuple(
        i for i, r in enumerate(raw)
        if ((type(r) is _seg._LazyVal and _aval_is_float(r.aval)
             and str(r.aval.dtype) != amp_dt)
            or (isinstance(r, (_jax.Array, _np.ndarray))
                and not (isinstance(r, _np.ndarray)
                         and r.dtype == _jax.dtypes.float0)
                and _is_float_dtype(r.dtype)
                and str(r.dtype) != amp_dt)))


def _amp_wrap(fn, k, dtype, cast_pos):
    """Memoized autocast-inside-the-callable variant: casts the exact
    positions the eager `_amp_cast` loop would cast. Cached per
    (key, dtype, cast_pos) — equal keys imply identical computations, so
    reusing the first-seen fn is the documented bulking contract."""
    ck = (k, dtype, cast_pos)
    with _cache_lock:
        ent = _AMP_WRAP_CACHE.get(ck)
        if ent is not None:
            _AMP_WRAP_CACHE.move_to_end(ck)
            _STATS["amp_wrap_cache_hit"] += 1
            return ent
        _STATS["amp_wrap_cache_miss"] += 1

    def wrapped(*xs):
        xs = list(xs)
        for i in cast_pos:
            xs[i] = xs[i].astype(dtype)
        return fn(*xs)

    with _cache_lock:
        _AMP_WRAP_CACHE[ck] = wrapped
        while len(_AMP_WRAP_CACHE) > _AMP_WRAP_CAP:
            _AMP_WRAP_CACHE.popitem(last=False)
    return wrapped


# ---------------------------------------------------------------------------
# lazy heavyweight imports — resolved once, then module-global fast lookups
# ---------------------------------------------------------------------------
_jax = None
_Tracer = None
_NDArray = None
_wrap = None
_wrap_lazy = None


def _lazy_init():
    global _jax, _Tracer, _NDArray, _wrap, _wrap_lazy
    import jax
    from ..ndarray import NDArray, _wrap as w, _wrap_lazy as wl
    _jax = jax
    _Tracer = jax.core.Tracer
    _NDArray = NDArray
    _wrap = w
    _wrap_lazy = wl


_engine_mod = None


def _engine_naive():
    """NaiveEngine check — one source of truth (engine module state, which
    snapshots MXNET_ENGINE_TYPE at import and is togglable via set_naive).
    engine.py is dependency-light, so importing it here costs nothing."""
    global _engine_mod
    if _engine_mod is None:
        from .. import engine as _engine_mod_imported
        _engine_mod = _engine_mod_imported
    return _engine_mod.is_naive()


def _is_float_dtype(dtype):
    if str(dtype) in ("bfloat16", "float8_e4m3fn", "float8_e5m2"):
        return True  # ml_dtypes extension floats are not np.floating subtypes
    try:
        return _np.issubdtype(_np.dtype(dtype), _np.floating)
    except TypeError:
        return False


def _aval_is_float(aval):
    return _is_float_dtype(aval.dtype)


# ---------------------------------------------------------------------------
# compiled-kernel cache (the eager CachedOp)
# ---------------------------------------------------------------------------
def _jit_for(k, fn):
    """Cached jax.jit kernel for key k, or None when k is blacklisted."""
    is_vjp = type(k) is tuple and k and k[0] in ("vjp", "cvjp")
    with _cache_lock:
        ent = _JIT_CACHE.get(k)
        if ent is not None:
            _JIT_CACHE.move_to_end(k)
            _STATS["vjp_cache_hit" if is_vjp else "jit_cache_hit"] += 1
            return ent[0]
        if k in _JIT_BAD:
            return None
        _STATS["vjp_cache_miss" if is_vjp else "jit_cache_miss"] += 1
        jfn = _jax.jit(fn)
        _JIT_CACHE[k] = (jfn, fn)
        while len(_JIT_CACHE) > _JIT_CACHE_CAP:
            _JIT_CACHE.popitem(last=False)
    return jfn


def _trace_errors():
    """Exception types that mean 'fn's python body cannot be traced' —
    the only failures that justify blacklisting a key. Runtime/compile
    failures (XlaRuntimeError, RESOURCE_EXHAUSTED, ...) may be transient
    and must NOT permanently demote a hot op to the eager path."""
    e = _jax.errors
    return (TypeError, e.ConcretizationTypeError, e.TracerArrayConversionError,
            e.TracerBoolConversionError, e.TracerIntegerConversionError,
            e.UnexpectedTracerError, e.NonConcreteBooleanIndexError)


def _run_immediate(fn, k, raw):
    """Execute fn(*raw), through the compiled-kernel cache when keyed.

    A failed jit call falls back to the plain eager call. Only when the
    eager call SUCCEEDS and the jit failure was a trace error (untraceable
    python, value-dependent shapes) is the key blacklisted; a genuine user
    error re-raises with eager semantics, and transient runtime/compile
    failures retry the kernel next call — neither can permanently disable
    an op's fast path."""
    if k is not None and k is not False and _jit_enabled():
        jfn = _jit_for(k, fn)
        if jfn is not None:
            try:
                out = jfn(*raw)
                _STATS["fast_path"] += 1
                return out
            except Exception as jit_err:
                _STATS["eager_fallback"] += 1
                out = fn(*raw)          # user error re-raises right here
                if isinstance(jit_err, _trace_errors()):
                    with _cache_lock:   # eager worked: fn is jit-hostile
                        _JIT_BAD[k] = True
                        while len(_JIT_BAD) > _JIT_BAD_CAP:
                            _JIT_BAD.popitem(last=False)
                        _JIT_CACHE.pop(k, None)
                return out
    _STATS["eager_fallback"] += 1
    return fn(*raw)


def invoke(fn, args, name="", multi_out=False, _vjp_tuple=False,
           cached_vjp=None, key=None, op=None):
    """Execute `fn` on arrays, wrapping results and taping when recording.

    `fn` is a pure jax function of the array-positional args (static/scalar
    params must be closed over by the caller). Returns NDArray or tuple.

    cached_vjp: optional pre-built backward `(raw_args, cts) -> grads`
    aligned with `args`. When given, the recording path skips the per-call
    jax.vjp (which re-traces + transposes in Python on EVERY call — ruinous
    for large cached graphs) and tapes this callable instead. Used by
    HybridBlock's cached op, where the backward is a jitted
    recompute-based VJP compiled once per shape.

    key: optional stable identity key for the op (hashable). Enables the
    bulking path AND the immediate compiled-kernel fast path even when
    `fn`'s identity cannot be derived automatically; callers guarantee equal
    keys imply identical computations for equal-shaped args. Pass key=False
    to force plain immediate dispatch (one-shot callables that must never
    enter the dispatch caches).

    op: optional OpInfo dispatch record (apply_op passes it); provides the
    registration-declared AMP class without a name-list lookup.
    """
    if _jax is None:
        _lazy_init()
    _STATS["dispatch"] += 1

    raw = []
    tracked_any = False
    lazy_any = False
    tracer_any = False
    parents = []
    for a in args:
        if isinstance(a, _NDArray):
            if a._base is not None:
                d = a._arr   # view: force refresh against its base
            else:
                d = a._data
                if type(d) is _seg._LazyVal:
                    if d.value is not None:
                        a._data = d = d.value
                    else:
                        lazy_any = True
            raw.append(d)
            if a._var is not None:
                parents.append(("var", a))
                tracked_any = True
            elif a._entry is not None:
                parents.append(("node", a._entry[0], a._entry[1]))
                tracked_any = True
            else:
                parents.append(None)
        else:
            raw.append(a)
            parents.append(None)
        if isinstance(d if isinstance(a, _NDArray) else a, _Tracer):
            tracer_any = True

    if _vjp_tuple:
        inner = fn
        fn = lambda *xs: inner(tuple(xs))

    amp_dt = _amp_dtype(name, op)
    recording = autograd.is_recording() and tracked_any
    naive = _engine_naive()

    # ------------------------------------------------------------------
    # key resolution. Tracer args mean we're already inside someone else's
    # trace (hybridize cache build, replay tracing, eval_shape) — compose
    # into that trace via the plain immediate path instead of deferring or
    # re-jitting.
    # ------------------------------------------------------------------
    k = False
    if key is not False and not tracer_any:
        k = key if key is not None else _seg.derive_key_cached(fn)

    # AMP autocast (≙ the reference's list-driven wrapper injection,
    # amp/amp.py:105-176): keyed dispatches fold the casts into the
    # dispatched callable once, here — the bulked path enqueues the wrapped
    # variant and the immediate path compiles it, under the same amp-tagged
    # key. Unkeyed dispatches cast eagerly per input (below). cast_pos from
    # lazy avals stays valid after forcing: same args, same positions.
    if amp_dt is not None and k is not None and k is not False:
        cast_pos = _cast_positions(raw, amp_dt)
        if cast_pos:
            fn = _amp_wrap(fn, k, amp_dt, cast_pos)
        k = (k, "amp", amp_dt, cast_pos)

    # ------------------------------------------------------------------
    # bulked (deferred) path
    # ------------------------------------------------------------------
    if k is not False and k is not None and not naive and _seg.enabled():
        res = _seg.enqueue(fn, raw, k, name=name)
        if res is not None:
            _STATS["bulked"] += 1
            treedef, lazies = res
            return _finish_bulked(treedef, lazies, fn, k, args, parents,
                                  recording, cached_vjp, raw, name,
                                  multi_out)
    if lazy_any:
        for i, r in enumerate(raw):
            if type(r) is _seg._LazyVal:
                raw[i] = r.force()

    # ------------------------------------------------------------------
    # immediate path
    # ------------------------------------------------------------------
    if amp_dt is not None and (k is None or k is False):
        raw = [_amp_cast(r, amp_dt) for r in raw]

    if not recording:
        out = _run_immediate(fn, k, raw)
        if naive:  # MXNET_ENGINE_TYPE=NaiveEngine: block per op
            _jax.block_until_ready(out)
        if isinstance(out, (tuple, list)):
            # None entries = symbolic-zero cotangents from a cached vjp
            # (non-differentiable slots); pass through unchanged
            res = tuple(_wrap(o) if o is not None else None for o in out)
            return res if (multi_out or len(res) != 1) else res[0]
        return (_wrap(out),) if multi_out else _wrap(out)

    tape_fn = None
    fast_tape = False
    if cached_vjp is not None:
        outs = _run_immediate(fn, k, raw)
        raw_t = tuple(raw)
        tape_fn = lambda cts: cached_vjp(raw_t, tuple(cts))
    elif k is not None and k is not False and _jit_enabled():
        # fast recorded path: compiled forward now, re-linearize at backward
        # time through the cached VJP kernel keyed by (op key, single, n_in)
        # — no python jax.vjp retrace on repeat (key, avals) pairs. Same
        # recompute-based taping contract as the bulked path (Node.key).
        outs = _run_immediate(fn, k, raw)
        fast_tape = True
    else:
        _STATS["vjp_trace"] += 1
        outs, vjp_fn = _jax.vjp(fn, *raw)
    if naive:
        _jax.block_until_ready(outs)
    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)

    any_float = any(_is_float_dtype(o.dtype) for o in outs_t)
    wrapped = tuple(_wrap(o) for o in outs_t)
    if any_float:
        if fast_tape:
            # keyed: tape for re-linearization (vjp_fn=None + key) exactly
            # like a bulked op — apply_vjp routes backward through invoke,
            # which serves it from the compiled-kernel cache
            node = autograd.Node(None, parents,
                                 [(o.shape, o.dtype) for o in outs_t],
                                 name=name, fn=fn, inputs=tuple(args),
                                 single_out=single, key=k,
                                 inputs_raw=tuple(raw))
        else:
            if tape_fn is None:
                if single:
                    tape_fn = lambda cts: vjp_fn(cts[0])
                else:
                    tape_fn = lambda cts: vjp_fn(tuple(cts))
            node = autograd.Node(tape_fn, parents,
                                 [(o.shape, o.dtype) for o in outs_t],
                                 name=name, fn=fn,
                                 inputs=tuple(args), single_out=single)
        for i, w in enumerate(wrapped):
            w._entry = (node, i)
    if single and not multi_out:
        return wrapped[0]
    return wrapped


def _finish_bulked(treedef, lazies, bfn, k, args, parents, recording,
                   cached_vjp, raw, name, multi_out):
    """Wrap a deferred op's lazy outputs and tape it when recording."""
    import jax.tree_util as jtu

    single = treedef.num_leaves == 1 and jtu.treedef_is_leaf(treedef)
    wrapped = [_wrap_lazy(lv) for lv in lazies]

    if recording:
        any_float = any(_aval_is_float(lv.aval) for lv in lazies)
        if any_float:
            node = autograd.Node(
                None, parents,
                [(tuple(lv.aval.shape), lv.aval.dtype) for lv in lazies],
                name=name, fn=bfn, inputs=tuple(args), single_out=single,
                key=k, cached_vjp=cached_vjp, inputs_raw=tuple(raw))
            for i, w in enumerate(wrapped):
                w._entry = (node, i)

    if single:
        return (wrapped[0],) if multi_out else wrapped[0]
    # rebuild the output structure (tuple/list, with None passthrough)
    out = jtu.tree_unflatten(treedef, wrapped)
    if isinstance(out, (tuple, list)):
        res = tuple(out)
        return res if (multi_out or len(res) != 1) else res[0]
    return (out,) if multi_out else out
