"""Single-choke-point op dispatch with autograd taping and op bulking.

Reference: Imperative::Invoke → SetShapeType → PushFCompute
(src/imperative/imperative.cc:49-140, imperative_utils.h:648) plus the
engine's op-bulking API (include/mxnet/engine.h:310-317,
src/imperative/cached_op.h:330). TPU-native: `invoke(fn, args)` unwraps
NDArrays and either

  * defers the jax call into the current bulking Segment (ops/segment.py) —
    consecutive eager ops compile and dispatch as ONE cached XLA program at
    the next materialization point, amortizing per-dispatch latency the way
    the reference's engine bulking does; or
  * runs the jax function immediately (NaiveEngine, bulking disabled, or the
    op is not deferrable), where PJRT dispatch is already async.

When autograd is recording, the tape node for a bulked op stores the forward
callable + inputs and re-linearizes at backward time (`jax.vjp` inside the
backward segment — recompute-based, XLA CSEs the duplicated forward); the
immediate path captures a `jax.vjp` closure as before (≙ Imperative::RecordOp,
imperative.cc:210).
"""
from __future__ import annotations

import numpy as _np

from .. import autograd
from ..base import MXNetError
from . import segment as _seg

_OP_REGISTRY = {}


class OpInfo:
    """Registry entry: name, callable, AMP behavior, docs (≙ nnvm::Op attrs)."""

    __slots__ = ("name", "fn", "amp", "doc")

    def __init__(self, name, fn, amp="neutral", doc=""):
        self.name = name
        self.fn = fn
        self.amp = amp  # 'safe' (run bf16) | 'unsafe' (keep f32) | 'neutral'
        self.doc = doc


def register_op(name, fn=None, amp="neutral", doc=""):
    """Register an op (decorator or direct). ≙ NNVM_REGISTER_OP."""
    def _reg(f):
        _OP_REGISTRY[name] = OpInfo(name, f, amp, doc or (f.__doc__ or ""))
        return f
    if fn is not None:
        return _reg(fn)
    return _reg


def get_op(name):
    try:
        return _OP_REGISTRY[name]
    except KeyError:
        raise MXNetError(f"operator {name!r} is not registered") from None


def list_ops():
    return sorted(_OP_REGISTRY)


def apply_op(name, *args, **kwargs):
    """Invoke a registered op by name on NDArray/array args."""
    import functools
    info = get_op(name)
    fn = functools.partial(info.fn, **kwargs) if kwargs else info.fn
    return invoke(fn, args, name=name)


def _amp_dtype(name):
    """AMP policy lookup (lazy import so amp stays optional)."""
    import sys
    amp_mod = sys.modules.get("incubator_mxnet_tpu.amp")
    if amp_mod is None or not amp_mod.is_active():
        return None
    return amp_mod.amp_dtype_for(name)


def _amp_cast(r, dtype):
    import jax
    import jax.numpy as jnp
    if isinstance(r, (jax.Array, _np.ndarray)) and _is_float_dtype(r.dtype) \
            and str(r.dtype) != dtype:
        return r.astype(dtype)
    return r


def _amp_wrap(fn, dtype, cast_pos):
    """Move the autocast inside the traced callable (bulked path): casts the
    exact positions the eager `_amp_cast` loop would cast."""
    def wrapped(*xs):
        xs = list(xs)
        for i in cast_pos:
            xs[i] = xs[i].astype(dtype)
        return fn(*xs)
    return wrapped


_engine_mod = None


def _engine_naive():
    """NaiveEngine check — one source of truth (engine module state, which
    snapshots MXNET_ENGINE_TYPE at import and is togglable via set_naive).
    engine.py is dependency-light, so importing it here costs nothing."""
    global _engine_mod
    if _engine_mod is None:
        from .. import engine as _engine_mod_imported
        _engine_mod = _engine_mod_imported
    return _engine_mod.is_naive()


def _is_float_dtype(dtype):
    if str(dtype) in ("bfloat16", "float8_e4m3fn", "float8_e5m2"):
        return True  # ml_dtypes extension floats are not np.floating subtypes
    try:
        return _np.issubdtype(_np.dtype(dtype), _np.floating)
    except TypeError:
        return False


def _aval_is_float(aval):
    return _is_float_dtype(aval.dtype)


def invoke(fn, args, name="", multi_out=False, _vjp_tuple=False,
           cached_vjp=None, key=None):
    """Execute `fn` on arrays, wrapping results and taping when recording.

    `fn` is a pure jax function of the array-positional args (static/scalar
    params must be closed over by the caller). Returns NDArray or tuple.

    cached_vjp: optional pre-built backward `(raw_args, cts) -> grads`
    aligned with `args`. When given, the recording path skips the per-call
    jax.vjp (which re-traces + transposes in Python on EVERY call — ruinous
    for large cached graphs) and tapes this callable instead. Used by
    HybridBlock's cached op, where the backward is a jitted
    recompute-based VJP compiled once per shape.

    key: optional stable identity key for the op (hashable). Enables the
    bulking path even when `fn`'s identity cannot be derived automatically;
    callers guarantee equal keys imply identical computations for
    equal-shaped args. Pass key=False to force immediate dispatch (one-shot
    callables that must never enter the bulking caches).
    """
    import jax
    from ..ndarray import NDArray, _wrap, _wrap_lazy

    raw = []
    tracked_any = False
    lazy_any = False
    parents = []
    for a in args:
        if isinstance(a, NDArray):
            if a._base is not None:
                raw.append(a._arr)   # view: force refresh against its base
            else:
                d = a._data
                if type(d) is _seg._LazyVal:
                    if d.value is not None:
                        a._data = d = d.value
                    else:
                        lazy_any = True
                raw.append(d)
            if a._var is not None:
                parents.append(("var", a))
                tracked_any = True
            elif a._entry is not None:
                parents.append(("node", a._entry[0], a._entry[1]))
                tracked_any = True
            else:
                parents.append(None)
        else:
            raw.append(a)
            parents.append(None)

    if _vjp_tuple:
        inner = fn
        fn = lambda *xs: inner(tuple(xs))

    amp_dt = _amp_dtype(name)
    recording = autograd.is_recording() and tracked_any
    naive = _engine_naive()

    # ------------------------------------------------------------------
    # bulked (deferred) path. Tracer args mean we're already inside someone
    # else's trace (hybridize cache build, replay tracing, eval_shape) —
    # compose into that trace via the immediate path instead of deferring.
    # ------------------------------------------------------------------
    if key is not False and not naive and _seg.enabled() \
            and not any(isinstance(r, jax.core.Tracer) for r in raw):
        k = key if key is not None else _seg.derive_key(fn)
        if k is not None:
            bfn = fn
            if amp_dt is not None:
                cast_pos = tuple(
                    i for i, r in enumerate(raw)
                    if ((type(r) is _seg._LazyVal and _aval_is_float(r.aval)
                         and str(r.aval.dtype) != amp_dt)
                        or (isinstance(r, (jax.Array, _np.ndarray))
                            and not (isinstance(r, _np.ndarray)
                                     and r.dtype == jax.dtypes.float0)
                            and _is_float_dtype(r.dtype)
                            and str(r.dtype) != amp_dt)))
                if cast_pos:
                    bfn = _amp_wrap(fn, amp_dt, cast_pos)
                k = (k, "amp", amp_dt, cast_pos)
            res = _seg.enqueue(bfn, raw, k, name=name)
            if res is not None:
                treedef, lazies = res
                return _finish_bulked(treedef, lazies, bfn, k, args, parents,
                                      recording, cached_vjp, raw, name,
                                      multi_out)
        if lazy_any:
            for i, r in enumerate(raw):
                if type(r) is _seg._LazyVal:
                    raw[i] = r.force()
    elif lazy_any:
        for i, r in enumerate(raw):
            if type(r) is _seg._LazyVal:
                raw[i] = r.force()

    # ------------------------------------------------------------------
    # immediate path
    # ------------------------------------------------------------------
    # AMP autocast: cast float inputs per the op's list classification
    # (≙ the reference's list-driven wrapper injection, amp/amp.py:105-176)
    if amp_dt is not None:
        raw = [_amp_cast(r, amp_dt) for r in raw]

    if not recording:
        out = fn(*raw)
        if naive:  # MXNET_ENGINE_TYPE=NaiveEngine: block per op
            jax.block_until_ready(out)
        if isinstance(out, (tuple, list)):
            # None entries = symbolic-zero cotangents from a cached vjp
            # (non-differentiable slots); pass through unchanged
            res = tuple(_wrap(o) if o is not None else None for o in out)
            return res if (multi_out or len(res) != 1) else res[0]
        return (_wrap(out),) if multi_out else _wrap(out)

    if cached_vjp is not None:
        outs = fn(*raw)
        raw_t = tuple(raw)
        tape_fn = lambda cts: cached_vjp(raw_t, tuple(cts))
    else:
        outs, vjp_fn = jax.vjp(fn, *raw)
    if naive:
        jax.block_until_ready(outs)
    single = not isinstance(outs, (tuple, list))
    outs_t = (outs,) if single else tuple(outs)

    any_float = any(_is_float_dtype(o.dtype) for o in outs_t)
    wrapped = tuple(_wrap(o) for o in outs_t)
    if any_float:
        if cached_vjp is None:
            if single:
                tape_fn = lambda cts: vjp_fn(cts[0])
            else:
                tape_fn = lambda cts: vjp_fn(tuple(cts))
        node = autograd.Node(tape_fn, parents,
                             [(o.shape, o.dtype) for o in outs_t], name=name,
                             fn=fn,
                             inputs=tuple(args), single_out=single)
        for i, w in enumerate(wrapped):
            w._entry = (node, i)
    if single and not multi_out:
        return wrapped[0]
    return wrapped


def _finish_bulked(treedef, lazies, bfn, k, args, parents, recording,
                   cached_vjp, raw, name, multi_out):
    """Wrap a deferred op's lazy outputs and tape it when recording."""
    import jax.tree_util as jtu
    from ..ndarray import _wrap_lazy

    single = treedef.num_leaves == 1 and jtu.treedef_is_leaf(treedef)
    wrapped = [_wrap_lazy(lv) for lv in lazies]

    if recording:
        any_float = any(_aval_is_float(lv.aval) for lv in lazies)
        if any_float:
            node = autograd.Node(
                None, parents,
                [(tuple(lv.aval.shape), lv.aval.dtype) for lv in lazies],
                name=name, fn=bfn, inputs=tuple(args), single_out=single,
                key=k, cached_vjp=cached_vjp, inputs_raw=tuple(raw))
            for i, w in enumerate(wrapped):
                w._entry = (node, i)

    if single:
        return (wrapped[0],) if multi_out else wrapped[0]
    # rebuild the output structure (tuple/list, with None passthrough)
    out = jtu.tree_unflatten(treedef, wrapped)
    if isinstance(out, (tuple, list)):
        res = tuple(out)
        return res if (multi_out or len(res) != 1) else res[0]
    return (out,) if multi_out else out
