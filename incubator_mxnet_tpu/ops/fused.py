"""mx.ops.fused — offender-driven fused op tier (Pallas + jnp fallback).

Reference: MXNet's `MXNET_USE_FUSION` pointwise RTC fusion
(src/operator/fusion/fused_op.cu) and the oneDNN/AMP graph passes fused
exactly these chains on GPU/CPU. TPU-native: the `mx.inspect` roofline
attribution (PR 7) ranks the compiled step's fusions by bytes moved, and
this module hand-fuses the top memory-bound classes it found
(benchmark/results/offenders_resnet18_r09.json — 86.7% of step bytes are
0.18–0.62-intensity fusions):

  op                      kills offender class              kernel
  ----------------------  --------------------------------  ----------------
  norm_act_residual       multiply_multiply_fusion (0.26    apply_scale_
                          FLOP/B, 59 instances: BN apply +  shift_act
                          relu + residual-add chains)
  bias_act                convert/select pointwise chains   apply_scale_
                          after dense/conv                  shift_act
  bn_inference            folded BN-inference scale/shift   apply_scale_
                          (+ optional act/residual)         shift_act
  batch_norm              training BN: batch stats + ONE    apply_scale_
                          fused apply pass                  shift_act
  avg_pool2d              reduce-window (0.18 FLOP/B, 35    avg_pool2d_fwd /
                          instances) — non-overlapping avg  avg_pool2d_bwd
                          pool incl. GlobalAvgPool, with a  (VMEM-tiled
                          broadcast backward                backward)

Each op is a Pallas TPU kernel (ops/pallas_kernels.py) with a
mathematically identical `jnp` composition fallback off-TPU — the
`*_ref` functions here ARE the fallback, so CPU gradient parity is exact
by construction and the kernels are interpret-mode tested against them.
On the kernel path the backward is a hand-derived custom_vjp (one
recompute of the pre-activation, then the analytic chain).

Gating: the gluon rewrites (nn.Dense/_Conv/BatchNorm/_Pool, model-zoo
residual blocks) engage only when `fusion_enabled()` — an explicit
`fusion_scope(True)` / `set_fusion_default(True)` AND the
`MXNET_USE_FUSION` env knob (default on). `FusedTrainStep` /
`FusedInferStep` enter the scope automatically, so the flagship fused
step gets the kernel tier by default while eager paths stay unchanged
unless opted in. `MXNET_FUSION_INTERPRET=1` runs the Pallas kernels in
interpret mode everywhere (CI exercises the kernel path on CPU).

Counters: `profiler.fused_stats()` / telemetry `fused.*` —
`pallas_calls` (kernel-path dispatches) vs `fallback_calls` (jnp
composition). Inside a jitted step these count per TRACE (path choices
baked into the program), eagerly they count per call.
"""
from __future__ import annotations

import functools
import threading
from contextlib import contextmanager

import numpy as _np

from ..base import get_env
from ..telemetry.registry import stats_group as _stats_group
from . import pallas_kernels as _pk

__all__ = ["bias_act", "norm_act_residual", "bn_inference", "batch_norm",
           "avg_pool2d", "image_augment", "paged_attention",
           "bias_act_ref",
           "norm_act_residual_ref", "bn_inference_ref", "avg_pool2d_ref",
           "paged_attention_ref",
           "fusion_scope",
           "fusion_enabled", "set_fusion_default", "set_use_fusion",
           "set_interpret", "fused_stats", "FUSED_STATS", "FUSABLE_ACTS"]

FUSABLE_ACTS = _pk.ACTS

FUSED_STATS = _stats_group("fused", {
    "pallas_calls": 0,       # dispatches that took a Pallas kernel path
    "fallback_calls": 0,     # dispatches served by the jnp composition
    "device_augment_calls": 0,  # image_augment programs built (per trace)
    "paged_attention_calls": 0,  # paged_attention dispatches (per trace
                                 # inside the jitted decode programs)
})
_STATS = FUSED_STATS


def fused_stats(reset=False):
    """Snapshot of the fused-tier path counters (see module docstring for
    the trace-time caveat). Also via profiler.fused_stats()."""
    return _STATS.snapshot(reset=reset)


# ---------------------------------------------------------------------------
# gating: scope/default AND the MXNET_USE_FUSION env knob
# ---------------------------------------------------------------------------
_SCOPE = threading.local()
_DEFAULT = [False]
_ENV_FUSION = [None]       # None = re-read MXNET_USE_FUSION
_INTERPRET = [None]        # None = re-read MXNET_FUSION_INTERPRET


def _env_use_fusion():
    if _ENV_FUSION[0] is None:
        _ENV_FUSION[0] = bool(get_env("MXNET_USE_FUSION", True, bool))
    return _ENV_FUSION[0]


def set_use_fusion(flag):
    """Override the MXNET_USE_FUSION kill switch at runtime (None =
    re-read the env). Returns the previous effective setting."""
    prev = _env_use_fusion()
    _ENV_FUSION[0] = None if flag is None else bool(flag)
    return prev


@contextmanager
def fusion_scope(active=True):
    """Enable (or force-disable) the fused-op rewrites for the dynamic
    extent — the hook FusedTrainStep/FusedInferStep use around tracing."""
    prev = getattr(_SCOPE, "value", None)
    _SCOPE.value = bool(active)
    try:
        yield
    finally:
        _SCOPE.value = prev


def set_fusion_default(flag):
    """Process-wide default outside any fusion_scope (eager opt-in).
    Returns the previous default."""
    prev = _DEFAULT[0]
    _DEFAULT[0] = bool(flag)
    return prev


def fusion_enabled():
    """True when gluon blocks should route through the fused ops: an
    active scope (or the process default) AND MXNET_USE_FUSION."""
    v = getattr(_SCOPE, "value", None)
    if v is None:
        v = _DEFAULT[0]
    return bool(v) and _env_use_fusion()


def set_interpret(flag):
    """Run the Pallas kernels in interpret mode (tests/CI; env:
    MXNET_FUSION_INTERPRET). None = re-read the env. Returns previous."""
    prev = _interpret()
    _INTERPRET[0] = None if flag is None else bool(flag)
    return prev


def _interpret():
    if _INTERPRET[0] is None:
        _INTERPRET[0] = bool(get_env("MXNET_FUSION_INTERPRET", False, bool))
    return _INTERPRET[0]


def _on_tpu():
    # actual TPU platforms only ('tpu'/'axon'): a CUDA/ROCm accelerator
    # must use the jnp fallback, not the TPU-shaped Pallas kernels
    from ..device import tpu_platform_available
    return tpu_platform_available()


# ---------------------------------------------------------------------------
# reference compositions — the off-TPU fallback AND the parity oracle
# ---------------------------------------------------------------------------
def _jnp():
    import jax.numpy as jnp
    return jnp


def _act32(u, act_type):
    import jax
    return _pk._act_f32(jax, _jnp(), u, act_type)


def _bshape(ndim, axis, c):
    shape = [1] * ndim
    shape[axis] = c
    return tuple(shape)


def _ref_apply(x, scale, shift, residual, act_type, axis):
    """act(x [*scale] + shift [+ residual]) — f32 internal, cast out."""
    jnp = _jnp()
    axis = axis % x.ndim
    c = x.shape[axis]
    bshape = _bshape(x.ndim, axis, c)
    u = x.astype(jnp.float32)
    if scale is not None:
        u = u * scale.reshape(bshape).astype(jnp.float32)
    u = u + shift.reshape(bshape).astype(jnp.float32)
    if residual is not None:
        u = u + residual.astype(jnp.float32)
    return _act32(u, act_type).astype(x.dtype)


def bias_act_ref(x, bias, act_type="relu", axis=-1):
    """Unfused composition of bias_act (the fallback and parity oracle)."""
    return _ref_apply(x, None, bias, None, act_type, axis)


def norm_act_residual_ref(x, scale, shift, residual, act_type="relu",
                          axis=-1):
    """Unfused composition of norm_act_residual."""
    return _ref_apply(x, scale, shift, residual, act_type, axis)


def _fold_bn(gamma, beta, mean, var, eps):
    """(scale, shift) f32 fold of the BN affine: scale = gamma*rsqrt(var
    + eps), shift = beta - mean*scale (gamma/beta optional)."""
    import jax
    jnp = _jnp()
    inv = jax.lax.rsqrt(var.astype(jnp.float32) + eps)
    scale = inv if gamma is None else gamma.astype(jnp.float32) * inv
    shift = -mean.astype(jnp.float32) * scale
    if beta is not None:
        shift = shift + beta.astype(jnp.float32)
    return scale, shift


def bn_inference_ref(x, gamma, beta, mean, var, eps=1e-5, axis=-1,
                     act_type=None, residual=None):
    """Unfused composition of bn_inference."""
    scale, shift = _fold_bn(gamma, beta, mean, var, eps)
    return _ref_apply(x, scale, shift, residual, act_type, axis)


def paged_attention_ref(q, k_slab, v_slab, lengths, layer,
                        k_scale=None, v_scale=None):
    """Unfused composition of paged decode attention over the serve
    KV-pool slab — the fallback and parity oracle. Reads the WHOLE
    (S, T) page per lane and masks to `[0, lengths + j]` per chunk
    query j (the O(max_len) path the Pallas kernel's block-sparse
    clamped reads replace).

    `q`: (S, C, H, D) — C chunk queries per lane at positions
    `lengths[s] + j`. `k_slab`/`v_slab`: (rows, layers, T, H, D) with
    rows > S (lane s reads row s). `k_scale`/`v_scale`: optional
    per-position f32 dequant scales (rows, layers, T) for int8 slabs."""
    import jax
    jnp = _jnp()
    s_lanes, c, _h, d = q.shape
    t = k_slab.shape[2]
    kk = k_slab[:s_lanes, layer]
    vv = v_slab[:s_lanes, layer]
    if k_scale is not None:
        kk = kk.astype(jnp.float32) * k_scale[:s_lanes, layer][..., None,
                                                               None]
    if v_scale is not None:
        vv = vv.astype(jnp.float32) * v_scale[:s_lanes, layer][..., None,
                                                               None]
    scores = jnp.einsum("schd,sthd->shct", q, kk) * (1.0 / float(d) ** 0.5)
    pos = jnp.arange(t)
    mask = pos[None, None, :] <= (lengths[:, None, None]
                                  + jnp.arange(c)[None, :, None])
    scores = jnp.where(mask[:, None], scores, -1e30)
    att = jnp.einsum("shct,sthd->schd",
                     jax.nn.softmax(scores, axis=-1), vv)
    return att.astype(q.dtype)


def paged_attention(q, k_slab, v_slab, lengths, layer,
                    k_scale=None, v_scale=None, interpret=None):
    """Paged decode attention over the slotted KV slab — the serve
    engine's per-layer attention read, in place (no per-layer copy of
    the cache). Routes to the Pallas block-sparse kernel on TPU (or in
    interpret mode for CPU CI) and to the identical masked-einsum
    composition otherwise; the choice is static per trace. Honors the
    MXNET_USE_FUSION kill switch (falls back, never fails)."""
    interpret = _interpret() if interpret is None else interpret
    _STATS["paged_attention_calls"] += 1
    if (_on_tpu() or interpret) and _env_use_fusion():
        out = _pk.paged_attention_fwd(q, k_slab, v_slab, lengths, layer,
                                      k_scale=k_scale, v_scale=v_scale,
                                      interpret=interpret)
        if out is not None:
            _STATS["pallas_calls"] += 1
            return out
    _STATS["fallback_calls"] += 1
    return paged_attention_ref(q, k_slab, v_slab, lengths, layer,
                               k_scale=k_scale, v_scale=v_scale)


def avg_pool2d_ref(x, pool_size, layout="NHWC"):
    """Unfused composition of the non-overlapping NHWC average pool
    (f32-accumulated reshape+mean)."""
    jnp = _jnp()
    ph, pw = pool_size
    n, h, w, c = x.shape
    xf = x.astype(jnp.float32).reshape(n, h // ph, ph, w // pw, pw, c)
    return jnp.mean(xf, axis=(2, 4)).astype(x.dtype)


# ---------------------------------------------------------------------------
# custom_vjp kernels over the (M, C) view — one builder per arity, memoized
# per static config so repeat traces reuse one callable identity
# ---------------------------------------------------------------------------
def _bwd_core(xf, scale32, g32):
    """Shared backward tail: (dx_f32, dscale_f32, dshift_f32) given the
    f32 input, f32 scale (or None) and the post-activation cotangent."""
    jnp = _jnp()
    dx = g32 if scale32 is None else g32 * scale32
    dscale = None if scale32 is None else jnp.sum(g32 * xf, axis=0)
    dshift = jnp.sum(g32, axis=0)
    return dx, dscale, dshift


def _act_grad(u, ct, act_type):
    """d(act)/du applied to ct, both f32, via jax.vjp of the f32 act —
    exactly the derivative jax AD of the reference composition uses."""
    import jax
    if act_type is None:
        return ct
    _, vjp = jax.vjp(lambda v: _act32(v, act_type), u)
    return vjp(ct)[0]


@functools.lru_cache(maxsize=None)
def _kernel_bias_act(act_type, interpret):
    import jax
    jnp = _jnp()

    @jax.custom_vjp
    def f(x2d, shift):
        out = _pk.apply_scale_shift_act(x2d, None, shift, None, act_type,
                                        interpret)
        if out is None:       # static-shape tiling miss: same math in jnp
            out = _ref_apply(x2d, None, shift, None, act_type, -1)
        return out

    def f_fwd(x2d, shift):
        return f(x2d, shift), (x2d, shift)

    def f_bwd(saved, ct):
        x2d, shift = saved
        xf = x2d.astype(jnp.float32)
        u = xf + shift.reshape(1, -1).astype(jnp.float32)
        g = _act_grad(u, ct.astype(jnp.float32), act_type)
        dx, _, dshift = _bwd_core(xf, None, g)
        return dx.astype(x2d.dtype), dshift.astype(shift.dtype)

    f.defvjp(f_fwd, f_bwd)
    return f


@functools.lru_cache(maxsize=None)
def _kernel_scale_shift_act(act_type, interpret):
    import jax
    jnp = _jnp()

    @jax.custom_vjp
    def f(x2d, scale, shift):
        out = _pk.apply_scale_shift_act(x2d, scale, shift, None, act_type,
                                        interpret)
        if out is None:
            out = _ref_apply(x2d, scale, shift, None, act_type, -1)
        return out

    def f_fwd(x2d, scale, shift):
        return f(x2d, scale, shift), (x2d, scale, shift)

    def f_bwd(saved, ct):
        x2d, scale, shift = saved
        xf = x2d.astype(jnp.float32)
        s32 = scale.reshape(1, -1).astype(jnp.float32)
        u = xf * s32 + shift.reshape(1, -1).astype(jnp.float32)
        g = _act_grad(u, ct.astype(jnp.float32), act_type)
        dx, dscale, dshift = _bwd_core(xf, s32, g)
        return (dx.astype(x2d.dtype), dscale.astype(scale.dtype),
                dshift.astype(shift.dtype))

    f.defvjp(f_fwd, f_bwd)
    return f


@functools.lru_cache(maxsize=None)
def _kernel_scale_shift_act_residual(act_type, interpret):
    import jax
    jnp = _jnp()

    @jax.custom_vjp
    def f(x2d, scale, shift, res):
        out = _pk.apply_scale_shift_act(x2d, scale, shift, res, act_type,
                                        interpret)
        if out is None:
            out = _ref_apply(x2d, scale, shift, res, act_type, -1)
        return out

    def f_fwd(x2d, scale, shift, res):
        return f(x2d, scale, shift, res), (x2d, scale, shift, res)

    def f_bwd(saved, ct):
        x2d, scale, shift, res = saved
        xf = x2d.astype(jnp.float32)
        s32 = scale.reshape(1, -1).astype(jnp.float32)
        u = (xf * s32 + shift.reshape(1, -1).astype(jnp.float32)
             + res.astype(jnp.float32))
        g = _act_grad(u, ct.astype(jnp.float32), act_type)
        dx, dscale, dshift = _bwd_core(xf, s32, g)
        return (dx.astype(x2d.dtype), dscale.astype(scale.dtype),
                dshift.astype(shift.dtype), g.astype(res.dtype))

    f.defvjp(f_fwd, f_bwd)
    return f


def _apply(x, scale, shift, residual, act_type, axis, interpret):
    """Route one apply through the Pallas kernel when viable (TPU or
    interpret mode, channels minor, VMEM-tileable, supported act), else
    the identical jnp composition. The decision is static per trace."""
    if act_type is not None and not _pk.supported_act(act_type):
        raise ValueError(f"unsupported fused activation {act_type!r}; "
                         f"supported: {FUSABLE_ACTS}")
    interpret = _interpret() if interpret is None else interpret
    axis_n = axis % x.ndim
    kernel_ok = (_on_tpu() or interpret) and axis_n == x.ndim - 1
    if kernel_ok:
        c = x.shape[-1]
        m = 1
        for d in x.shape[:-1]:
            m *= d
        n_bufs = 2 + (1 if residual is not None else 0)
        bm = _pk._block_rows(m, c, n_bufs)
        kernel_ok = bm > 0 and m % bm == 0
    if not kernel_ok:
        _STATS["fallback_calls"] += 1
        return _ref_apply(x, scale, shift, residual, act_type, axis)
    _STATS["pallas_calls"] += 1
    c = x.shape[-1]
    x2d = x.reshape(-1, c)
    if scale is None:
        out = _kernel_bias_act(act_type, interpret)(x2d, shift)
    elif residual is None:
        out = _kernel_scale_shift_act(act_type, interpret)(x2d, scale,
                                                           shift)
    else:
        out = _kernel_scale_shift_act_residual(act_type, interpret)(
            x2d, scale, shift, residual.reshape(-1, c))
    return out.reshape(x.shape)


# ---------------------------------------------------------------------------
# public fused ops (raw jax arrays in/out; npx wrappers own NDArray glue)
# ---------------------------------------------------------------------------
def bias_act(x, bias, act_type="relu", axis=-1, interpret=None):
    """Fused y = act(x + bias) with per-channel bias on `axis`."""
    return _apply(x, None, bias, None, act_type, axis, interpret)


def norm_act_residual(x, scale, shift, residual, act_type="relu", axis=-1,
                      interpret=None):
    """Fused y = act(x*scale + shift + residual) — the normalize-apply /
    activation / residual-add tail of a residual block in ONE pass
    (scale/shift are the folded norm affine; see `bn_inference` for the
    fold). The 0.26-intensity `multiply_multiply_fusion` killer."""
    return _apply(x, scale, shift, residual, act_type, axis, interpret)


def bn_inference(x, gamma, beta, mean, var, eps=1e-5, axis=-1,
                 act_type=None, residual=None, interpret=None):
    """Folded BN-inference scale/shift (+ optional act/residual): the
    running stats fold into ONE per-channel affine at trace time, then a
    single fused apply pass."""
    scale, shift = _fold_bn(gamma, beta, mean, var, eps)
    return _apply(x, scale, shift, residual, act_type, axis, interpret)


def batch_norm(x, gamma, beta, running_mean, running_var, momentum=0.9,
               eps=1e-5, training=True, axis=1, use_global_stats=False,
               sync_axis_name=None, act_type=None, residual=None,
               interpret=None):
    """Batch norm with the apply stage routed through the fused kernel.

    Identical stats protocol to ops.nn.batch_norm (same f32 moments, same
    pmean sync, same running-stat update; returns (out, new_rm, new_rv))
    but the normalize/scale/shift(/act/residual) applies as ONE fused
    pass instead of the chain XLA splits into memory-bound fusions.
    Gradients flow through the batch moments exactly as in the unfused
    composition — scale/shift are traced functions of x, and the apply's
    custom_vjp chains through them."""
    import jax
    jnp = _jnp()
    lax = jax.lax
    reduce_axes = tuple(i for i in range(x.ndim) if i != (axis % x.ndim))
    if training and not use_global_stats:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=reduce_axes)
        mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
        if sync_axis_name is not None:
            mean = lax.pmean(mean, sync_axis_name)
            mean_sq = lax.pmean(mean_sq, sync_axis_name)
        var = mean_sq - jnp.square(mean)
        new_rm = momentum * running_mean + (1 - momentum) * mean
        new_rv = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    scale, shift = _fold_bn(gamma, beta, mean, var, eps)
    out = _apply(x, scale, shift, residual, act_type, axis, interpret)
    return out, new_rm, new_rv


def image_augment(images, key, mean=None, std=None, crop_hw=None,
                  rand_mirror=False, out_dtype="float32", interpret=None):
    """Device-side half of the input pipeline as ONE jitted batched kernel:
    optional per-image random crop (when the staged images are larger than
    `crop_hw`), optional per-image horizontal mirror, [0,1] scale +
    per-channel mean/std normalize, cast — the work `ImageRecordIter`'s
    float32 path used to burn host cores on (uint8 handoff moves it here,
    behind the 4x-smaller H2D transfer).

    `images`: (N, H, W, 3) NHWC — uint8 raw pixels (scaled by 1/255) or a
    float array already in [0, 1] (gradients flow through the affine for
    float inputs; the crop/mirror randomness does not block them).
    `key`: PRNGKey DATA as a uint32 (2,) array — an array argument, not a
    static seed, so per-(epoch, batch) keys swap without a retrace (the
    zero-retrace contract io_bench asserts). `mean`/`std` are static
    per-channel tuples in [0, 1] units; `crop_hw`/`rand_mirror`/`out_dtype`
    are static too.

    jnp-only by design: every stage is pointwise/slice-shaped and XLA
    fuses the chain into one kernel on any backend — there is no separate
    Pallas path, so `interpret` is accepted for tier uniformity and
    ignored. Counted per program build in `fused.device_augment_calls`
    (inside jit the body runs at trace time only)."""
    import jax
    jnp = _jnp()
    _STATS["device_augment_calls"] += 1
    x = images
    if jnp.issubdtype(x.dtype, jnp.integer):
        x = x.astype(jnp.float32) * (1.0 / 255.0)
    elif x.dtype != jnp.float32:
        x = x.astype(jnp.float32)
    kc, km = jax.random.split(jnp.asarray(key))
    if crop_hw is not None:
        ch, cw = int(crop_hw[0]), int(crop_hw[1])
        n, h, w = x.shape[0], x.shape[1], x.shape[2]
        if (h, w) != (ch, cw):
            ky, kx = jax.random.split(kc)
            y0 = jax.random.randint(ky, (n,), 0, h - ch + 1)
            x0 = jax.random.randint(kx, (n,), 0, w - cw + 1)
            x = jax.vmap(
                lambda img, yy, xx: jax.lax.dynamic_slice(
                    img, (yy, xx, 0), (ch, cw, 3)))(x, y0, x0)
    if rand_mirror:
        flips = jax.random.bernoulli(km, 0.5, (x.shape[0],))
        x = jnp.where(flips[:, None, None, None], x[:, :, ::-1, :], x)
    if mean is not None:
        x = x - jnp.asarray(mean, jnp.float32)
    if std is not None:
        x = x / jnp.asarray(std, jnp.float32)
    return x.astype(out_dtype)


# bounded: the key includes the pooled SHAPE, and each entry pins a
# custom_vjp callable whose identity also keys jax's compiled-program
# caches — unbounded growth under variable-resolution workloads (same
# rationale as the telemetry model_flops FIFO bound)
@functools.lru_cache(maxsize=64)
def _kernel_avg_pool(h, w, ph, pw, dtype, interpret):
    import jax
    jnp = _jnp()

    @jax.custom_vjp
    def f(x):
        out = _pk.avg_pool2d_fwd(x, ph, pw, interpret)
        if out is None:
            out = avg_pool2d_ref(x, (ph, pw))
        return out

    def f_fwd(x):
        return f(x), ()

    def f_bwd(_res, dy):
        dx = _pk.avg_pool2d_bwd(dy, h, w, ph, pw, interpret)
        if dx is None:   # same math: broadcast the mean gradient
            n, ho, wo, c = dy.shape
            g = dy.astype(jnp.float32) * (1.0 / (ph * pw))
            g = jnp.broadcast_to(g[:, :, None, :, None, :],
                                 (n, ho, ph, wo, pw, c))
            dx = g.reshape(n, h, w, c)
        return (dx.astype(dtype),)

    f.defvjp(f_fwd, f_bwd)
    return f


def avg_pool2d(x, pool_size, layout="NHWC", interpret=None):
    """Non-overlapping (kernel == stride, no padding) NHWC average pool
    with a VMEM-tiled Pallas backward — covers AvgPool2D(k, k) and the
    GlobalAvgPool2D shape (pool_size = spatial dims, keepdims output).
    Falls back to the f32 reshape+mean composition off-TPU (whose XLA
    gradient is already a broadcast, not a reduce-window scatter)."""
    ph, pw = (pool_size, pool_size) if isinstance(pool_size, int) \
        else tuple(pool_size)
    if layout != "NHWC" or x.ndim != 4:
        raise ValueError("fused avg_pool2d is NHWC 2-D only "
                         f"(got layout={layout!r}, ndim={x.ndim})")
    n, h, w, c = x.shape
    if h % ph or w % pw:
        raise ValueError(f"pool {ph}x{pw} must divide spatial dims "
                         f"{h}x{w} (non-overlapping pooling)")
    interpret = _interpret() if interpret is None else interpret
    if not (_on_tpu() or interpret) \
            or _pk._pool_blocks(n, h, w, c, ph, pw) is None:
        _STATS["fallback_calls"] += 1
        return avg_pool2d_ref(x, (ph, pw))
    _STATS["pallas_calls"] += 1
    return _kernel_avg_pool(h, w, ph, pw, str(x.dtype), interpret)(x)


# Dispatch-record AMP classes (PR2 metadata; picked up by register_op in
# numpy_extension): the apply ops compute in f32 internally and are safe
# to FEED in the autocast dtype — except the stats-bearing batch_norm
# family, pinned f32 like ops.nn.batch_norm. Pooling matches nn.pooling.
for _f, _cls in ((bias_act, "safe"), (norm_act_residual, "unsafe"),
                 (bn_inference, "unsafe"), (batch_norm, "unsafe"),
                 (avg_pool2d, "safe"), (image_augment, "neutral"),
                 (paged_attention, "safe")):
    _f._amp_class = _cls
del _f, _cls
