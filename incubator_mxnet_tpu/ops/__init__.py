"""Operator layer: autograd-aware invoke machinery + op registry.

Reference equivalent: the NNVM op registry + Imperative::Invoke dispatch
(src/imperative/imperative.cc:105, src/imperative/imperative_utils.h:177-288).
On TPU there is no FCompute/FComputeEx split, no DispatchMode, and no manual
shape/dtype inference pass: every op is a pure jax-traceable function; XLA does
inference, fusion and memory planning. What survives from the reference design
is (1) a single choke-point `invoke` that handles NDArray unwrap/wrap and
autograd taping, and (2) a name registry for introspection/AMP lists.
"""
from .registry import invoke, register_op, get_op, list_ops, apply_op

__all__ = ["invoke", "register_op", "get_op", "list_ops", "apply_op"]
