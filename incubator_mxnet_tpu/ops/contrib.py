"""Contrib detection/vision ops (≙ src/operator/contrib: bounding_box.cc
box_nms/box_iou, roi_align.cc, bilinear_resize.cc, multibox_*).

TPU-native: everything is fixed-shape and vectorized — box_nms returns the
standard MXNet convention (suppressed entries get score -1) with a
lax.fori_loop greedy sweep instead of the reference's CUDA sort+mask kernel,
so it compiles under jit with static shapes.
"""
from __future__ import annotations

import functools

import numpy as _np

__all__ = ["box_iou", "box_nms", "roi_align", "bilinear_resize2d",
           "multibox_prior", "multibox_target", "multibox_detection",
           "proposal", "deformable_convolution", "psroi_pooling"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def box_iou(lhs, rhs, fmt="corner"):
    """Pairwise IoU (≙ _contrib_box_iou). lhs (..., N, 4), rhs (..., M, 4)."""
    jnp = _jnp()
    if fmt == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    lx1, ly1, lx2, ly2 = [lhs[..., :, None, i] for i in range(4)]
    rx1, ry1, rx2, ry2 = [rhs[..., None, :, i] for i in range(4)]
    ix1 = jnp.maximum(lx1, rx1)
    iy1 = jnp.maximum(ly1, ry1)
    ix2 = jnp.minimum(lx2, rx2)
    iy2 = jnp.minimum(ly2, ry2)
    iw = jnp.clip(ix2 - ix1, 0, None)
    ih = jnp.clip(iy2 - iy1, 0, None)
    inter = iw * ih
    area_l = jnp.clip(lx2 - lx1, 0, None) * jnp.clip(ly2 - ly1, 0, None)
    area_r = jnp.clip(rx2 - rx1, 0, None) * jnp.clip(ry2 - ry1, 0, None)
    union = area_l + area_r - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_to_corner(b):
    jnp = _jnp()
    x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False):
    """Greedy NMS (≙ _contrib_box_nms). data (..., N, K) with K >= 6:
    [class_id, score, x1, y1, x2, y2, ...]. Suppressed/invalid entries get
    score -1 (reference convention); order preserved by descending score."""
    import jax
    jnp = _jnp()

    def one(batch):  # (N, K)
        n = batch.shape[0]
        scores = batch[:, score_index]
        ids = batch[:, id_index] if id_index >= 0 else jnp.zeros(n)
        boxes = jax.lax.dynamic_slice_in_dim(batch, coord_start, 4, axis=1)
        order = jnp.argsort(-scores)
        sorted_batch = batch[order]
        sorted_scores = scores[order]
        sorted_boxes = boxes[order]
        sorted_ids = ids[order]
        valid = sorted_scores > valid_thresh
        if topk > 0:
            valid = valid & (jnp.arange(n) < topk)
        iou = box_iou(sorted_boxes, sorted_boxes)
        same_class = (sorted_ids[:, None] == sorted_ids[None, :]) \
            if (id_index >= 0 and not force_suppress) else jnp.ones((n, n), bool)

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & same_class[i] \
                & (jnp.arange(n) > i) & keep[i] & valid
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, valid)
        out_scores = jnp.where(keep, sorted_scores, -1.0)
        return sorted_batch.at[:, score_index].set(out_scores)

    if data.ndim == 2:
        return one(data)
    flat = data.reshape((-1,) + data.shape[-2:])
    out = jax.vmap(one)(flat)
    return out.reshape(data.shape)


def roi_align(data, rois, pooled_size, spatial_scale=1.0, sample_ratio=2):
    """ROI Align (≙ _contrib_ROIAlign, src/operator/contrib/roi_align.cc).

    data (N, C, H, W); rois (R, 5) = [batch_idx, x1, y1, x2, y2] in image
    coords. Returns (R, C, ph, pw). Bilinear sampling, avg over samples.
    """
    import jax
    jnp = _jnp()
    data = jnp.asarray(data)  # host arrays must not be indexed by tracers
    rois = jnp.asarray(rois)
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else pooled_size
    N, C, H, W = data.shape
    s = sample_ratio if sample_ratio > 0 else 2

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: (ph*s, pw*s)
        ys = y1 + (jnp.arange(ph * s) + 0.5) * (bin_h / s)
        xs = x1 + (jnp.arange(pw * s) + 0.5) * (bin_w / s)
        img = data[bidx]  # (C, H, W)
        vals = _bilinear_sample(img, ys, xs)          # (C, ph*s, pw*s)
        vals = vals.reshape(C, ph, s, pw, s)
        return vals.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


def _bilinear_sample(img, ys, xs):
    """img (C, H, W); sample at the grid ys x xs with border clamping."""
    jnp = _jnp()
    C, H, W = img.shape
    y = jnp.clip(ys, 0.0, H - 1.0)
    x = jnp.clip(xs, 0.0, W - 1.0)
    y0 = jnp.clip(jnp.floor(y).astype(jnp.int32), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = (y - y0)[:, None]
    wx = (x - x0)[None, :]
    v00 = img[:, y0][:, :, x0]
    v01 = img[:, y0][:, :, x1]
    v10 = img[:, y1][:, :, x0]
    v11 = img[:, y1][:, :, x1]
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def bilinear_resize2d(data, height, width, layout="NCHW"):
    """≙ _contrib_BilinearResize2D (bilinear_resize.cc)."""
    import jax
    if layout == "NCHW":
        shape = data.shape[:2] + (height, width)
    else:
        shape = (data.shape[0], height, width, data.shape[-1])
    return jax.image.resize(data, shape, method="linear")


# ---------------------------------------------------------------------------
# SSD detection tail (≙ src/operator/contrib/multibox_prior.cc,
# multibox_target.cc, multibox_detection.cc). Re-designed fixed-shape and
# batched: the reference's per-anchor C loops become vectorized IoU tables,
# a lax.fori_loop bipartite matcher, and argsort-based compaction, all of
# which compile under jit.
# ---------------------------------------------------------------------------

def multibox_prior(data, sizes=(1.0,), ratios=(1.0,), clip=False,
                   steps=(-1.0, -1.0), offsets=(0.5, 0.5), layout="NCHW"):
    """Generate SSD prior (anchor) boxes from a feature map
    (≙ multibox_prior.cc:31-75). Returns (1, H*W*K, 4) corner boxes in
    normalized [0,1] coords, K = len(sizes) + len(ratios) - 1, ordered
    (per cell): each size with ratios[0], then sizes[0] with ratios[1:]."""
    jnp = _jnp()
    if layout == "NCHW":
        in_h, in_w = int(data.shape[2]), int(data.shape[3])
    else:
        in_h, in_w = int(data.shape[1]), int(data.shape[2])
    step_y = steps[0] if steps[0] > 0 else 1.0 / in_h
    step_x = steps[1] if steps[1] > 0 else 1.0 / in_w
    cy = (jnp.arange(in_h, dtype=jnp.float32) + offsets[0]) * step_y
    cx = (jnp.arange(in_w, dtype=jnp.float32) + offsets[1]) * step_x

    # per-cell half-sizes: sizes x sqrt(ratios[0]), then sizes[0] x ratios[1:]
    hw, hh = [], []
    r0 = float(_np.sqrt(ratios[0])) if len(ratios) else 1.0
    for s in sizes:
        hw.append(s * in_h / in_w * r0 / 2)
        hh.append(s / r0 / 2)
    for r in ratios[1:]:
        sr = float(_np.sqrt(r))
        hw.append(sizes[0] * in_h / in_w * sr / 2)
        hh.append(sizes[0] / sr / 2)
    hw = jnp.asarray(hw, jnp.float32)   # (K,)
    hh = jnp.asarray(hh, jnp.float32)

    cyg, cxg = jnp.meshgrid(cy, cx, indexing="ij")      # (H, W)
    cxg = cxg[:, :, None]
    cyg = cyg[:, :, None]
    boxes = jnp.stack([cxg - hw, cyg - hh, cxg + hw, cyg + hh], axis=-1)
    boxes = boxes.reshape(1, in_h * in_w * hw.shape[0], 4)
    if clip:
        boxes = jnp.clip(boxes, 0.0, 1.0)
    return boxes


def _encode_loc(anchor, gt, variances):
    """(≙ AssignLocTargets, multibox_target.cc:32-60)"""
    jnp = _jnp()
    aw = anchor[..., 2] - anchor[..., 0]
    ah = anchor[..., 3] - anchor[..., 1]
    ax = (anchor[..., 0] + anchor[..., 2]) * 0.5
    ay = (anchor[..., 1] + anchor[..., 3]) * 0.5
    gw = gt[..., 2] - gt[..., 0]
    gh = gt[..., 3] - gt[..., 1]
    gx = (gt[..., 0] + gt[..., 2]) * 0.5
    gy = (gt[..., 1] + gt[..., 3]) * 0.5
    eps = 1e-12
    return jnp.stack([
        (gx - ax) / jnp.maximum(aw, eps) / variances[0],
        (gy - ay) / jnp.maximum(ah, eps) / variances[1],
        jnp.log(jnp.maximum(gw, eps) / jnp.maximum(aw, eps)) / variances[2],
        jnp.log(jnp.maximum(gh, eps) / jnp.maximum(ah, eps)) / variances[3],
    ], axis=-1)


@functools.lru_cache(maxsize=None)
def _multibox_target_impl(overlap_threshold, ignore_label,
                          negative_mining_ratio, negative_mining_thresh,
                          minimum_negative_samples, variances):
    """jit-compiled matcher, cached per hyperparameter tuple (eager calls
    would otherwise re-trace the fori_loop every training step)."""
    import jax
    jnp = _jnp()

    def impl(anchor, label, cls_pred):
        anc = anchor.reshape(-1, 4)
        A = anc.shape[0]
        G = label.shape[1]

        def one(lab, cpred):
            valid = jnp.cumprod(lab[:, 0] != -1.0).astype(bool)   # (G,)
            ious = box_iou(anc, lab[:, 1:5])                       # (A, G)
            ious = jnp.where(valid[None, :], ious, -1.0)

            def body(_, st):
                match, flags, iou_m = st
                flat = jnp.argmax(iou_m)
                aj, gk = flat // G, flat % G
                best = iou_m[aj, gk]
                take = best > 1e-6
                match = jnp.where(take, match.at[aj].set(gk), match)
                flags = jnp.where(take, flags.at[aj].set(1), flags)
                iou_m = jnp.where(take, iou_m.at[aj, :].set(-1.0), iou_m)
                iou_m = jnp.where(take, iou_m.at[:, gk].set(-1.0), iou_m)
                return match, flags, iou_m

            match0 = jnp.full((A,), -1, jnp.int32)
            flags0 = jnp.full((A,), -1, jnp.int32)  # -1 ign, 0 neg, 1 pos
            match, flags, _ = jax.lax.fori_loop(
                0, G, body, (match0, flags0, ious))

            best_gt = jnp.argmax(ious, axis=1).astype(jnp.int32)
            best_iou = jnp.max(ious, axis=1)
            thr_pos = (flags != 1) & (best_iou > overlap_threshold)
            if overlap_threshold > 0:
                match = jnp.where(thr_pos, best_gt, match)
                flags = jnp.where(thr_pos, 1, flags)

            num_pos = jnp.sum(flags == 1)

            if negative_mining_ratio > 0:
                # rank by LOWEST background softmax prob = anchors the
                # classifier most confidently calls foreground — the hard
                # negatives (≙ multibox_target.cc:221-235: sort by -prob
                # of class 0)
                bg_prob = jax.nn.softmax(cpred, axis=0)[0]
                neg_cand = ((flags != 1)
                            & (best_iou < negative_mining_thresh))
                num_neg = jnp.minimum(
                    (num_pos * negative_mining_ratio).astype(jnp.int32),
                    A - num_pos)
                num_neg = jnp.maximum(num_neg, minimum_negative_samples)
                score = jnp.where(neg_cand, -bg_prob, -jnp.inf)
                order = jnp.argsort(-score)
                rank = jnp.zeros((A,), jnp.int32).at[order].set(
                    jnp.arange(A, dtype=jnp.int32))
                sel = neg_cand & (rank < num_neg)
                flags = jnp.where(sel, 0, flags)
            else:
                flags = jnp.where(flags != 1, 0, flags)

            safe_gt = jnp.clip(match, 0, G - 1)
            gt_rows = lab[safe_gt]
            loc_t = _encode_loc(anc, gt_rows[:, 1:5],
                                jnp.asarray(variances, jnp.float32))
            pos = (flags == 1)
            loc_t = jnp.where(pos[:, None], loc_t, 0.0)
            loc_m = jnp.where(pos[:, None], 1.0, 0.0) * jnp.ones((A, 4))
            cls_t = jnp.where(
                pos, gt_rows[:, 0] + 1.0,
                jnp.where(flags == 0, 0.0, float(ignore_label)))
            return (loc_t.reshape(-1), loc_m.reshape(-1),
                    cls_t.astype(anc.dtype))

        return jax.vmap(one)(label, cls_pred)

    return jax.jit(impl)


def multibox_target(anchor, label, cls_pred, overlap_threshold=0.5,
                    ignore_label=-1.0, negative_mining_ratio=-1.0,
                    negative_mining_thresh=0.5, minimum_negative_samples=0,
                    variances=(0.1, 0.1, 0.2, 0.2)):
    """SSD training-target assignment
    (≙ MultiBoxTargetForward, multibox_target.cc:76-287).

    anchor (1,A,4) or (A,4); label (B,G,5) rows [cls,xmin,ymin,xmax,ymax]
    with -1 rows as padding; cls_pred (B,num_cls,A) (used by negative
    mining). Returns (loc_target (B,A*4), loc_mask (B,A*4),
    cls_target (B,A)). Matching = bipartite (each gt grabs its best free
    anchor, highest IoU pairs first) then threshold matching; optional
    hard-negative mining ranks unmatched anchors by peak class logit.
    Non-differentiable (targets are labels — reference semantics)."""
    import jax
    fn = _multibox_target_impl(
        float(overlap_threshold), float(ignore_label),
        float(negative_mining_ratio), float(negative_mining_thresh),
        int(minimum_negative_samples), tuple(variances))
    return fn(jax.lax.stop_gradient(anchor), jax.lax.stop_gradient(label),
              jax.lax.stop_gradient(cls_pred))


@functools.lru_cache(maxsize=None)
def _multibox_detection_impl(clip, threshold, nms_threshold, force_suppress,
                             variances, nms_topk, background_id):
    import jax
    jnp = _jnp()

    def impl(cls_prob, loc_pred, anchor):
        return _multibox_detection_body(
            jnp, jax, cls_prob, loc_pred, anchor, clip, threshold,
            nms_threshold, force_suppress, variances, nms_topk,
            background_id)

    return jax.jit(impl)


def multibox_detection(cls_prob, loc_pred, anchor, clip=True, threshold=0.01,
                       background_id=0, nms_threshold=0.5,
                       force_suppress=False,
                       variances=(0.1, 0.1, 0.2, 0.2), nms_topk=-1):
    """SSD decode + per-class NMS
    (≙ MultiBoxDetectionForward, multibox_detection.cc:87-190).

    cls_prob (B,num_cls,A) softmax probs (class 0 = background),
    loc_pred (B,A*4), anchor (1,A,4). Returns (B,A,6) rows
    [class_id, score, xmin, ymin, xmax, ymax]; invalid rows have id -1
    and are compacted after the valid ones (stable order, like the
    reference's valid_count compaction). Non-differentiable (inference
    op, reference semantics); jitted + cached per hyperparameter set."""
    import jax
    fn = _multibox_detection_impl(
        bool(clip), float(threshold), float(nms_threshold),
        bool(force_suppress), tuple(variances), int(nms_topk),
        int(background_id))
    return fn(jax.lax.stop_gradient(cls_prob),
              jax.lax.stop_gradient(loc_pred),
              jax.lax.stop_gradient(anchor))


def _multibox_detection_body(jnp, jax, cls_prob, loc_pred, anchor, clip,
                             threshold, nms_threshold, force_suppress,
                             variances, nms_topk, background_id):
    anc = anchor.reshape(-1, 4)
    A = anc.shape[0]

    aw = anc[:, 2] - anc[:, 0]
    ah = anc[:, 3] - anc[:, 1]
    ax = (anc[:, 0] + anc[:, 2]) * 0.5
    ay = (anc[:, 1] + anc[:, 3]) * 0.5

    def one(cprob, lpred):
        lp = lpred.reshape(A, 4)
        # mask the background row, take the best remaining class (the
        # reference declares background_id but hardcodes 0 — here it's
        # honored; out ids renumber with the background removed)
        fg = cprob.at[background_id].set(-jnp.inf)
        score = jnp.max(fg, axis=0)                  # best fg prob (A,)
        cls = jnp.argmax(fg, axis=0)                 # true class index
        cid = cls - (cls > background_id).astype(cls.dtype) + 1
        cid = jnp.where(score < threshold, 0, cid)   # ≙ id>0 && score<thr
        ox = lp[:, 0] * variances[0] * aw + ax
        oy = lp[:, 1] * variances[1] * ah + ay
        ow = jnp.exp(lp[:, 2] * variances[2]) * aw / 2
        oh = jnp.exp(lp[:, 3] * variances[3]) * ah / 2
        boxes = jnp.stack([ox - ow, oy - oh, ox + ow, oy + oh], axis=-1)
        if clip:
            boxes = jnp.clip(boxes, 0.0, 1.0)
        out_id = cid.astype(jnp.float32) - 1.0       # background -> -1

        # NMS sweep in score order (suppress same class unless
        # force_suppress), optional topk
        order = jnp.argsort(-jnp.where(out_id >= 0, score, -1.0))
        s_id = out_id[order]
        s_score = score[order]
        s_boxes = boxes[order]
        if nms_topk > 0:
            in_topk = jnp.arange(A) < nms_topk
            s_id = jnp.where(in_topk, s_id, -1.0)

        def body(i, alive_id):
            me_valid = alive_id[i] >= 0
            iou = box_iou(s_boxes[i][None, :], s_boxes)[0]        # (A,)
            same_cls = (alive_id == alive_id[i]) if not force_suppress \
                else jnp.ones_like(alive_id, bool)
            later = jnp.arange(A) > i
            kill = me_valid & later & same_cls & (iou > nms_threshold) \
                & (alive_id >= 0)
            return jnp.where(kill, -1.0, alive_id)

        s_id = jax.lax.fori_loop(0, A, body, s_id)

        # compact valid rows to the front, stable
        invalid = s_id < 0
        comp = jnp.argsort(invalid, stable=True)
        rows = jnp.concatenate(
            [s_id[:, None], jnp.where(invalid, -1.0, s_score)[:, None],
             s_boxes], axis=-1)
        return rows[comp]

    return jax.vmap(one)(cls_prob, loc_pred)


# ---------------------------------------------------------------------------
# Faster-RCNN tail (≙ src/operator/contrib/proposal.cc,
# deformable_convolution.cc, psroi_pooling.cc, deformable_psroi_pooling.cc)
# ---------------------------------------------------------------------------

def _generate_base_anchors(base_size, ratios, scales):
    """(≙ utils::GenerateAnchors, proposal.cc) ratio then scale enumeration
    around a base_size x base_size window, area-preserving with rounding."""
    # host-side anchor precompute on static config ints (reference idiom);
    # base_size is never a traced value
    base = _np.array([0, 0, base_size - 1, base_size - 1], _np.float32)  # mxlint: disable=trace-host-capture
    w = base[2] - base[0] + 1
    h = base[3] - base[1] + 1
    cx = base[0] + 0.5 * (w - 1)
    cy = base[1] + 0.5 * (h - 1)
    out = []
    for r in ratios:
        size = w * h
        size_r = size / r
        ws = _np.round(_np.sqrt(size_r))
        hs = _np.round(ws * r)
        for s in scales:
            wss, hss = ws * s, hs * s
            out.append([cx - 0.5 * (wss - 1), cy - 0.5 * (hss - 1),
                        cx + 0.5 * (wss - 1), cy + 0.5 * (hss - 1)])
    return _np.asarray(out, _np.float32)


def proposal(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n=6000,
             rpn_post_nms_top_n=300, threshold=0.7, rpn_min_size=16,
             scales=(4, 8, 16, 32), ratios=(0.5, 1, 2), feature_stride=16,
             output_score=False, iou_loss=False):
    """RPN proposal generation (≙ ProposalOp::Forward, proposal.cc:275-430).

    cls_prob (1, 2K, H, W) [background scores first, foreground second],
    bbox_pred (1, 4K, H, W), im_info (1, 3) [height, width, scale].
    Returns (post_nms, 5) rows [batch_idx, x1, y1, x2, y2] (+ (post_nms, 1)
    scores when output_score). Fixed-shape: NMS survivors are compacted,
    short results padded by repeating the best proposal (reference pads the
    tail the same way). Non-differentiable; jitted + cached per config."""
    import jax
    fn = _proposal_impl(
        int(rpn_pre_nms_top_n), int(rpn_post_nms_top_n), float(threshold),
        int(rpn_min_size), tuple(scales), tuple(ratios),
        int(feature_stride), bool(output_score), bool(iou_loss))
    return fn(jax.lax.stop_gradient(cls_prob),
              jax.lax.stop_gradient(bbox_pred),
              jax.lax.stop_gradient(im_info))


@functools.lru_cache(maxsize=None)
def _proposal_impl(rpn_pre_nms_top_n, rpn_post_nms_top_n, threshold,
                   rpn_min_size, scales, ratios, feature_stride,
                   output_score, iou_loss):
    import jax

    def impl(cls_prob, bbox_pred, im_info):
        return _proposal_body(cls_prob, bbox_pred, im_info,
                              rpn_pre_nms_top_n, rpn_post_nms_top_n,
                              threshold, rpn_min_size, scales, ratios,
                              feature_stride, output_score, iou_loss)

    return jax.jit(impl)


def _proposal_body(cls_prob, bbox_pred, im_info, rpn_pre_nms_top_n,
                   rpn_post_nms_top_n, threshold, rpn_min_size, scales,
                   ratios, feature_stride, output_score, iou_loss):
    import jax
    jnp = _jnp()
    K = cls_prob.shape[1] // 2
    H, W = int(cls_prob.shape[2]), int(cls_prob.shape[3])
    count = K * H * W
    pre_n = min(rpn_pre_nms_top_n if rpn_pre_nms_top_n > 0 else count, count)
    post_n = min(rpn_post_nms_top_n, pre_n)

    base = jnp.asarray(
        _generate_base_anchors(feature_stride, ratios, scales))   # (K,4)
    sy = jnp.arange(H, dtype=jnp.float32) * feature_stride
    sx = jnp.arange(W, dtype=jnp.float32) * feature_stride
    syg, sxg = jnp.meshgrid(sy, sx, indexing="ij")                # (H,W)
    shift = jnp.stack([sxg, syg, sxg, syg], axis=-1)              # (H,W,4)
    anchors = (base[None, None, :, :] + shift[:, :, None, :])     # (H,W,K,4)
    anchors = anchors.reshape(-1, 4)                              # (HWK,4)

    fg = cls_prob[0, K:].transpose(1, 2, 0).reshape(-1)           # (HWK,)
    deltas = bbox_pred[0].reshape(K, 4, H, W).transpose(
        2, 3, 0, 1).reshape(-1, 4)

    aw = anchors[:, 2] - anchors[:, 0] + 1.0
    ah = anchors[:, 3] - anchors[:, 1] + 1.0
    ax = anchors[:, 0] + 0.5 * (aw - 1.0)
    ay = anchors[:, 1] + 0.5 * (ah - 1.0)
    if iou_loss:
        x1 = anchors[:, 0] + deltas[:, 0]
        y1 = anchors[:, 1] + deltas[:, 1]
        x2 = anchors[:, 2] + deltas[:, 2]
        y2 = anchors[:, 3] + deltas[:, 3]
    else:
        px = deltas[:, 0] * aw + ax
        py = deltas[:, 1] * ah + ay
        pw = jnp.exp(jnp.clip(deltas[:, 2], -10, 10)) * aw
        ph = jnp.exp(jnp.clip(deltas[:, 3], -10, 10)) * ah
        x1 = px - 0.5 * (pw - 1.0)
        y1 = py - 0.5 * (ph - 1.0)
        x2 = px + 0.5 * (pw - 1.0)
        y2 = py + 0.5 * (ph - 1.0)
    im_h, im_w, im_scale = im_info[0, 0], im_info[0, 1], im_info[0, 2]
    x1 = jnp.clip(x1, 0.0, im_w - 1.0)
    y1 = jnp.clip(y1, 0.0, im_h - 1.0)
    x2 = jnp.clip(x2, 0.0, im_w - 1.0)
    y2 = jnp.clip(y2, 0.0, im_h - 1.0)

    min_size = rpn_min_size * im_scale
    keep = ((x2 - x1 + 1.0) >= min_size) & ((y2 - y1 + 1.0) >= min_size)
    score = jnp.where(keep, fg, -1.0)

    order = jnp.argsort(-score)
    take = order[:pre_n]
    boxes = jnp.stack([x1, y1, x2, y2], -1)[take]
    score = score[take]

    def body(i, alive):
        me = alive[i] > -1.0
        xx1 = jnp.maximum(boxes[i, 0], boxes[:, 0])
        yy1 = jnp.maximum(boxes[i, 1], boxes[:, 1])
        xx2 = jnp.minimum(boxes[i, 2], boxes[:, 2])
        yy2 = jnp.minimum(boxes[i, 3], boxes[:, 3])
        inter = (jnp.maximum(0.0, xx2 - xx1 + 1.0)
                 * jnp.maximum(0.0, yy2 - yy1 + 1.0))
        a_i = ((boxes[i, 2] - boxes[i, 0] + 1.0)
               * (boxes[i, 3] - boxes[i, 1] + 1.0))
        a_all = ((boxes[:, 2] - boxes[:, 0] + 1.0)
                 * (boxes[:, 3] - boxes[:, 1] + 1.0))
        iou = inter / (a_i + a_all - inter)
        kill = me & (jnp.arange(pre_n) > i) & (iou > threshold)
        return jnp.where(kill, -1.0, alive)

    alive = jax.lax.fori_loop(0, pre_n, body, score)
    comp = jnp.argsort(alive <= -1.0, stable=True)[:post_n]
    out_boxes = boxes[comp]
    out_score = alive[comp]
    # pad suppressed tail rows by repeating the top proposal
    bad = (out_score <= -1.0)
    out_boxes = jnp.where(bad[:, None], out_boxes[0][None, :], out_boxes)
    out_score = jnp.where(bad, out_score[0], out_score)
    rois = jnp.concatenate(
        [jnp.zeros((post_n, 1), out_boxes.dtype), out_boxes], axis=-1)
    if output_score:
        return rois, out_score[:, None]
    return rois


def deformable_convolution(data, offset, weight, bias=None, kernel=(3, 3),
                           stride=(1, 1), pad=(0, 0), dilate=(1, 1),
                           num_deformable_group=1):
    """Deformable convolution v1
    (≙ deformable_convolution.cc / deformable_im2col.h, Dai et al. 2017).

    data (B,C,H,W); offset (B, 2*G*kh*kw, Ho, Wo) ordered (g, kh, kw,
    [dy,dx]); weight (Co, C, kh, kw). TPU-native: the deformable im2col
    becomes a batched bilinear gather building (B, Ho, Wo, C*kh*kw), and
    the conv collapses into ONE (BHoWo, Ckhkw) x (Ckhkw, Co) matmul on the
    MXU. Fully differentiable (jax AD through the gather weights); jitted
    + cached per (kernel, stride, pad, dilate, groups)."""
    fn = _deformable_conv_impl(tuple(kernel), tuple(stride), tuple(pad),
                               tuple(dilate), int(num_deformable_group),
                               bias is not None)
    if bias is not None:
        return fn(data, offset, weight, bias)
    return fn(data, offset, weight)


@functools.lru_cache(maxsize=None)
def _deformable_conv_impl(kernel, stride, pad, dilate, num_deformable_group,
                          has_bias):
    import jax

    if has_bias:
        def impl(data, offset, weight, bias):
            return _deformable_conv_body(data, offset, weight, bias, kernel,
                                         stride, pad, dilate,
                                         num_deformable_group)
    else:
        def impl(data, offset, weight):
            return _deformable_conv_body(data, offset, weight, None, kernel,
                                         stride, pad, dilate,
                                         num_deformable_group)
    return jax.jit(impl)


def _deformable_conv_body(data, offset, weight, bias, kernel, stride, pad,
                          dilate, num_deformable_group):
    import jax
    jnp = _jnp()
    B, C, H, W = data.shape
    kh, kw = kernel
    Co = weight.shape[0]
    G = num_deformable_group
    Ho = (H + 2 * pad[0] - dilate[0] * (kh - 1) - 1) // stride[0] + 1
    Wo = (W + 2 * pad[1] - dilate[1] * (kw - 1) - 1) // stride[1] + 1

    # base sampling grid (kh*kw taps per output position)
    oy = jnp.arange(Ho) * stride[0] - pad[0]
    ox = jnp.arange(Wo) * stride[1] - pad[1]
    ky = jnp.arange(kh) * dilate[0]
    kx = jnp.arange(kw) * dilate[1]
    base_y = oy[:, None, None, None] + ky[None, None, :, None]  # (Ho,1,kh,1)
    base_x = ox[None, :, None, None] + kx[None, None, None, :]  # (1,Wo,1,kw)
    base_y = jnp.broadcast_to(base_y, (Ho, Wo, kh, kw)).astype(jnp.float32)
    base_x = jnp.broadcast_to(base_x, (Ho, Wo, kh, kw)).astype(jnp.float32)

    off = offset.reshape(B, G, kh, kw, 2, Ho, Wo)
    dy = off[:, :, :, :, 0].transpose(0, 1, 4, 5, 2, 3)  # (B,G,Ho,Wo,kh,kw)
    dx = off[:, :, :, :, 1].transpose(0, 1, 4, 5, 2, 3)
    sy = base_y[None, None] + dy                          # (B,G,Ho,Wo,kh,kw)
    sx = base_x[None, None] + dx

    y0 = jnp.floor(sy)
    x0 = jnp.floor(sx)
    wy = sy - y0
    wx = sx - x0

    def gather(img_g, yy, xx):
        """img_g (Cg,H,W); yy/xx (Ho,Wo,kh,kw) -> (Ho,Wo,kh,kw,Cg)"""
        yi = jnp.clip(yy, 0, H - 1).astype(jnp.int32)
        xi = jnp.clip(xx, 0, W - 1).astype(jnp.int32)
        inb = ((yy >= 0) & (yy <= H - 1) & (xx >= 0)
               & (xx <= W - 1)).astype(img_g.dtype)
        vals = img_g[:, yi, xi]                      # (Cg,Ho,Wo,kh,kw)
        return (vals * inb[None]).transpose(1, 2, 3, 4, 0)

    Cg = C // G

    def one(img, syb, sxb, y0b, x0b, wyb, wxb):
        # img (C,H,W); per deformable group
        cols = []
        for g in range(G):
            ig = img[g * Cg:(g + 1) * Cg]
            v00 = gather(ig, y0b[g], x0b[g])
            v01 = gather(ig, y0b[g], x0b[g] + 1)
            v10 = gather(ig, y0b[g] + 1, x0b[g])
            v11 = gather(ig, y0b[g] + 1, x0b[g] + 1)
            wyg = wyb[g][..., None]
            wxg = wxb[g][..., None]
            v = (v00 * (1 - wyg) * (1 - wxg) + v01 * (1 - wyg) * wxg
                 + v10 * wyg * (1 - wxg) + v11 * wyg * wxg)
            cols.append(v)                            # (Ho,Wo,kh,kw,Cg)
        return jnp.concatenate(cols, axis=-1)         # (Ho,Wo,kh,kw,C)

    cols = jax.vmap(one)(data, sy, sx, y0, x0, wy, wx)  # (B,Ho,Wo,kh,kw,C)
    # one MXU matmul: (B*Ho*Wo, kh*kw*C) x (kh*kw*C, Co)
    cols2 = cols.reshape(B * Ho * Wo, kh * kw * C)
    wmat = weight.transpose(2, 3, 1, 0).reshape(kh * kw * C, Co)
    out = cols2 @ wmat
    out = out.reshape(B, Ho, Wo, Co).transpose(0, 3, 1, 2)
    if bias is not None:
        out = out + bias.reshape(1, Co, 1, 1)
    return out


def psroi_pooling(data, rois, spatial_scale, output_dim, pooled_size,
                  group_size=0):
    """Position-sensitive ROI pooling (≙ psroi_pooling.cc, R-FCN).

    data (B, output_dim*group*group, H, W); rois (R, 5)
    [batch_idx, x1, y1, x2, y2] in image coords. Returns
    (R, output_dim, pooled, pooled): bin (i,j) of output channel c
    average-pools input channel (c*group + i)*group + j over its bin.
    Differentiable w.r.t. data; jitted + cached per config."""
    fn = _psroi_impl(float(spatial_scale), int(output_dim), int(pooled_size),
                     int(group_size))
    return fn(data, rois)


@functools.lru_cache(maxsize=None)
def _psroi_impl(spatial_scale, output_dim, pooled_size, group_size):
    import jax

    def impl(data, rois):
        return _psroi_body(data, rois, spatial_scale, output_dim,
                           pooled_size, group_size)

    return jax.jit(impl)


def _psroi_body(data, rois, spatial_scale, output_dim, pooled_size,
                group_size):
    import jax
    jnp = _jnp()
    B, C, H, W = data.shape
    P = pooled_size
    G = group_size if group_size > 0 else P

    # fixed sampling lattice per bin (avoids dynamic bin extents under jit):
    # 4x4 samples per bin, bilinear, averaged — dense enough to match the
    # reference's exact-sum averaging closely and fully vectorizable
    S = 4
    frac = (jnp.arange(S, dtype=jnp.float32) + 0.5) / S

    def one_roi(roi):
        b = roi[0].astype(jnp.int32)
        x1 = jnp.round(roi[1]) * spatial_scale
        y1 = jnp.round(roi[2]) * spatial_scale
        x2 = jnp.round(roi[3] + 1.0) * spatial_scale
        y2 = jnp.round(roi[4] + 1.0) * spatial_scale
        rw = jnp.maximum(x2 - x1, 0.1)
        rh = jnp.maximum(y2 - y1, 0.1)
        bw = rw / P
        bh = rh / P
        img = data[b]                                  # (C,H,W)

        iy = jnp.arange(P, dtype=jnp.float32)
        ix = jnp.arange(P, dtype=jnp.float32)
        ys = y1 + (iy[:, None] + frac[None, :]) * bh   # (P,S)
        xs = x1 + (ix[:, None] + frac[None, :]) * bw   # (P,S)
        yi = jnp.clip(ys, 0, H - 1)
        xi = jnp.clip(xs, 0, W - 1)
        y0 = jnp.floor(yi).astype(jnp.int32)
        x0 = jnp.floor(xi).astype(jnp.int32)
        y1i = jnp.minimum(y0 + 1, H - 1)
        x1i = jnp.minimum(x0 + 1, W - 1)
        wy = (yi - y0)[:, :, None, None]               # (P,S,1,1)
        wx = (xi - x0)[None, None, :, :]               # (1,1,P,S)

        # channel map: out channel c, bin (i,j) -> in channel (c*G+gi)*G+gj
        gi = jnp.minimum((iy).astype(jnp.int32) * G // P, G - 1)   # (P,)
        gj = jnp.minimum((ix).astype(jnp.int32) * G // P, G - 1)
        co = jnp.arange(output_dim)
        cin = (co[:, None, None] * G + gi[None, :, None]) * G \
            + gj[None, None, :]                        # (O,P,P)

        # gather the 4 corners ONLY for the channel each (c, bin_y, bin_x)
        # actually pools (indexing the channel map in the same gather
        # avoids the G^2-times overcompute of sampling all C channels)
        ch = cin[:, :, None, :, None]                  # (O,P,1,P,1)

        def corner(yc, xc):
            # (O, P,S, P,S): channel, y-sample, x-sample advanced-indexed
            return img[ch, yc[None, :, :, None, None],
                       xc[None, None, None, :, :]]

        v00 = corner(y0, x0)
        v01 = corner(y0, x1i)
        v10 = corner(y1i, x0)
        v11 = corner(y1i, x1i)
        wyb = wy[None]                                 # (1,P,S,1,1)
        wxb = wx[None]                                 # (1,1,1,P,S)
        val = (v00 * (1 - wyb) * (1 - wxb) + v01 * (1 - wyb) * wxb
               + v10 * wyb * (1 - wxb) + v11 * wyb * wxb)  # (O,P,S,P,S)
        return val.mean(axis=(2, 4))                   # (O,P,P)

    return jax.vmap(one_roi)(rois)


# ---------------------------------------------------------------------------
# Dispatch-record metadata (PR2, same contract as ops/nn.py): AMP classes
# for the contrib surface. Box/anchor/proposal coordinate math and pooled
# sampling accumulate — pin fp32 under autocast; deformable conv is
# MXU-bound like regular conv.
# ---------------------------------------------------------------------------
for _f, _cls in ((deformable_convolution, "safe"),
                 (box_iou, "unsafe"), (box_nms, "unsafe"),
                 (multibox_prior, "unsafe"), (multibox_target, "unsafe"),
                 (multibox_detection, "unsafe"), (proposal, "unsafe"),
                 (roi_align, "unsafe"), (psroi_pooling, "unsafe"),
                 (bilinear_resize2d, "unsafe")):
    _f._amp_class = _cls
del _f, _cls
