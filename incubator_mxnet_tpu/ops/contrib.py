"""Contrib detection/vision ops (≙ src/operator/contrib: bounding_box.cc
box_nms/box_iou, roi_align.cc, bilinear_resize.cc, multibox_*).

TPU-native: everything is fixed-shape and vectorized — box_nms returns the
standard MXNet convention (suppressed entries get score -1) with a
lax.fori_loop greedy sweep instead of the reference's CUDA sort+mask kernel,
so it compiles under jit with static shapes.
"""
from __future__ import annotations

import functools

import numpy as _np

__all__ = ["box_iou", "box_nms", "roi_align", "bilinear_resize2d"]


def _jnp():
    import jax.numpy as jnp
    return jnp


def box_iou(lhs, rhs, fmt="corner"):
    """Pairwise IoU (≙ _contrib_box_iou). lhs (..., N, 4), rhs (..., M, 4)."""
    jnp = _jnp()
    if fmt == "center":
        lhs = _center_to_corner(lhs)
        rhs = _center_to_corner(rhs)
    lx1, ly1, lx2, ly2 = [lhs[..., :, None, i] for i in range(4)]
    rx1, ry1, rx2, ry2 = [rhs[..., None, :, i] for i in range(4)]
    ix1 = jnp.maximum(lx1, rx1)
    iy1 = jnp.maximum(ly1, ry1)
    ix2 = jnp.minimum(lx2, rx2)
    iy2 = jnp.minimum(ly2, ry2)
    iw = jnp.clip(ix2 - ix1, 0, None)
    ih = jnp.clip(iy2 - iy1, 0, None)
    inter = iw * ih
    area_l = jnp.clip(lx2 - lx1, 0, None) * jnp.clip(ly2 - ly1, 0, None)
    area_r = jnp.clip(rx2 - rx1, 0, None) * jnp.clip(ry2 - ry1, 0, None)
    union = area_l + area_r - inter
    return jnp.where(union > 0, inter / union, 0.0)


def _center_to_corner(b):
    jnp = _jnp()
    x, y, w, h = b[..., 0], b[..., 1], b[..., 2], b[..., 3]
    return jnp.stack([x - w / 2, y - h / 2, x + w / 2, y + h / 2], axis=-1)


def box_nms(data, overlap_thresh=0.5, valid_thresh=0.0, topk=-1,
            coord_start=2, score_index=1, id_index=-1, force_suppress=False):
    """Greedy NMS (≙ _contrib_box_nms). data (..., N, K) with K >= 6:
    [class_id, score, x1, y1, x2, y2, ...]. Suppressed/invalid entries get
    score -1 (reference convention); order preserved by descending score."""
    import jax
    jnp = _jnp()

    def one(batch):  # (N, K)
        n = batch.shape[0]
        scores = batch[:, score_index]
        ids = batch[:, id_index] if id_index >= 0 else jnp.zeros(n)
        boxes = jax.lax.dynamic_slice_in_dim(batch, coord_start, 4, axis=1)
        order = jnp.argsort(-scores)
        sorted_batch = batch[order]
        sorted_scores = scores[order]
        sorted_boxes = boxes[order]
        sorted_ids = ids[order]
        valid = sorted_scores > valid_thresh
        if topk > 0:
            valid = valid & (jnp.arange(n) < topk)
        iou = box_iou(sorted_boxes, sorted_boxes)
        same_class = (sorted_ids[:, None] == sorted_ids[None, :]) \
            if (id_index >= 0 and not force_suppress) else jnp.ones((n, n), bool)

        def body(i, keep):
            sup = (iou[i] > overlap_thresh) & same_class[i] \
                & (jnp.arange(n) > i) & keep[i] & valid
            return keep & ~sup

        keep = jax.lax.fori_loop(0, n, body, valid)
        out_scores = jnp.where(keep, sorted_scores, -1.0)
        return sorted_batch.at[:, score_index].set(out_scores)

    if data.ndim == 2:
        return one(data)
    flat = data.reshape((-1,) + data.shape[-2:])
    out = jax.vmap(one)(flat)
    return out.reshape(data.shape)


def roi_align(data, rois, pooled_size, spatial_scale=1.0, sample_ratio=2):
    """ROI Align (≙ _contrib_ROIAlign, src/operator/contrib/roi_align.cc).

    data (N, C, H, W); rois (R, 5) = [batch_idx, x1, y1, x2, y2] in image
    coords. Returns (R, C, ph, pw). Bilinear sampling, avg over samples.
    """
    import jax
    jnp = _jnp()
    data = jnp.asarray(data)  # host arrays must not be indexed by tracers
    rois = jnp.asarray(rois)
    ph, pw = (pooled_size, pooled_size) if isinstance(pooled_size, int) \
        else pooled_size
    N, C, H, W = data.shape
    s = sample_ratio if sample_ratio > 0 else 2

    def one_roi(roi):
        bidx = roi[0].astype(jnp.int32)
        x1, y1, x2, y2 = roi[1] * spatial_scale, roi[2] * spatial_scale, \
            roi[3] * spatial_scale, roi[4] * spatial_scale
        rw = jnp.maximum(x2 - x1, 1.0)
        rh = jnp.maximum(y2 - y1, 1.0)
        bin_w = rw / pw
        bin_h = rh / ph
        # sample grid: (ph*s, pw*s)
        ys = y1 + (jnp.arange(ph * s) + 0.5) * (bin_h / s)
        xs = x1 + (jnp.arange(pw * s) + 0.5) * (bin_w / s)
        img = data[bidx]  # (C, H, W)
        vals = _bilinear_sample(img, ys, xs)          # (C, ph*s, pw*s)
        vals = vals.reshape(C, ph, s, pw, s)
        return vals.mean(axis=(2, 4))

    return jax.vmap(one_roi)(rois)


def _bilinear_sample(img, ys, xs):
    """img (C, H, W); sample at the grid ys x xs with border clamping."""
    jnp = _jnp()
    C, H, W = img.shape
    y = jnp.clip(ys, 0.0, H - 1.0)
    x = jnp.clip(xs, 0.0, W - 1.0)
    y0 = jnp.clip(jnp.floor(y).astype(jnp.int32), 0, H - 1)
    x0 = jnp.clip(jnp.floor(x).astype(jnp.int32), 0, W - 1)
    y1 = jnp.clip(y0 + 1, 0, H - 1)
    x1 = jnp.clip(x0 + 1, 0, W - 1)
    wy = (y - y0)[:, None]
    wx = (x - x0)[None, :]
    v00 = img[:, y0][:, :, x0]
    v01 = img[:, y0][:, :, x1]
    v10 = img[:, y1][:, :, x0]
    v11 = img[:, y1][:, :, x1]
    top = v00 * (1 - wx) + v01 * wx
    bot = v10 * (1 - wx) + v11 * wx
    return top * (1 - wy) + bot * wy


def bilinear_resize2d(data, height, width, layout="NCHW"):
    """≙ _contrib_BilinearResize2D (bilinear_resize.cc)."""
    import jax
    if layout == "NCHW":
        shape = data.shape[:2] + (height, width)
    else:
        shape = (data.shape[0], height, width, data.shape[-1])
    return jax.image.resize(data, shape, method="linear")
