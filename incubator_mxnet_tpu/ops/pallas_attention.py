"""Flash attention as a Pallas TPU kernel.

Reference contrast: MXNet's attention kernels are fused strided-batch-GEMMs
(`_contrib_interleaved_matmul_selfatt_*`, src/operator/contrib/
transformer.cc:676-869) that materialize the full (T, T) score matrix. This
kernel is the TPU-first replacement: blockwise online-softmax attention
(flash attention) that keeps O(block_q x block_k) tiles in VMEM, never
materializing the score matrix — the HBM-bandwidth win that matters at long
sequence length (SURVEY §5.7: the capability gap this framework fills).

Layout: q,k,v are (batch*heads, T, head_dim). Grid = (bh, nq, nk) with the
k loop innermost; accumulators (m, l, acc) persist in VMEM scratch across
the nk steps (TPU grids iterate sequentially).

Falls back to the jnp composition off-TPU (tests run interpret=True or the
fallback — same math, tolerances in tests/test_attention.py).
"""
from __future__ import annotations

import functools
import math

import numpy as _np

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, scale,
            causal, block_q, block_k, nk, causal_offset=0):
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            # end-aligned (≙ tril with k = tk - tq): query i attends keys
            # up to i + (tk - tq)
            q_pos = qi * block_q + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:]                          # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = alpha * acc_ref[:] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # skip fully-masked k blocks (block entirely above the diagonal)
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        import jax.numpy as jnp
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _blockwise(q, k, v, scale, causal, block_k=512):
    """Differentiable blockwise attention: lax.scan over k blocks with
    online-softmax merging. Same math as the Pallas kernel, O(T·block_k)
    memory in BOTH directions (jax AD through scan recomputes per block) —
    this is the training path backing flash_attention's custom_vjp."""
    import jax
    import jax.numpy as jnp
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_k = min(block_k, tk)
    if tk % block_k:
        return _reference(q, k, v, scale, causal)
    nk = tk // block_k
    kb = k.reshape(bh, nk, block_k, d).transpose(1, 0, 2, 3)
    vb = v.reshape(bh, nk, block_k, d).transpose(1, 0, 2, 3)
    q32 = q.astype(jnp.float32)
    q_pos = jnp.arange(tq)[:, None] + (tk - tq)  # end-aligned causal

    def step(carry, blk):
        m_run, l_run, acc = carry
        k_cur, v_cur, j = blk
        s = jnp.einsum("bqd,bkd->bqk", q32, k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_run, m_blk)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_run - m_new)
        l_new = alpha * l_run + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bqk,bkd->bqd", p, v_cur.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((bh, tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, tq, 1), jnp.float32)
    acc0 = jnp.zeros((bh, tq, d), jnp.float32)
    # remat: without it, AD through the scan saves the (bh, tq, block_k)
    # probabilities of every step — O(tq*tk), defeating blockwise memory
    (m, l, acc), _ = jax.lax.scan(
        jax.checkpoint(step), (m0, l0, acc0), (kb, vb, jnp.arange(nk)))
    denom = jnp.where(l == 0.0, 1.0, l)
    return (acc / denom).astype(q.dtype)


def _reference(q, k, v, scale, causal):
    import jax
    import jax.numpy as jnp
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _flash_forward_kernel(q, k, v, causal, scale, block_q, block_k,
                          interpret):
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = tq // block_q
    nk = tk // block_k
    grid = (bh, nq, nk)
    kernel = functools.partial(_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk,
                               causal_offset=tk - tq)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # m (running max)
            pltpu.VMEM((block_q, 1), jnp.float32),   # l (running denom)
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
        ],
        interpret=interpret,
    )(q, k, v)


def flash_attention(q, k, v, causal=False, scale=None, block_q=512,
                    block_k=512, interpret=False):
    """Blockwise attention. q: (bh, Tq, d), k/v: (bh, Tk, d) raw jax arrays.

    Forward uses the Pallas kernel on TPU (or interpret=True anywhere);
    reverse-mode AD routes through a custom_vjp whose backward differentiates
    the blockwise lax.scan formulation — O(T·block) memory both ways.
    Falls back to the einsum composition off-TPU / on ragged shapes.
    """
    import jax

    bh, tq, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    on_tpu = any(dev.platform != "cpu" for dev in jax.devices())
    if not (on_tpu or interpret):
        return _blockwise(q, k, v, scale, causal, block_k)

    block_q = min(block_q, tq)
    block_k = min(block_k, tk)
    if tq % block_q or tk % block_k:
        # ragged tails: fall back (padding support comes with masked loads)
        return _reference(q, k, v, scale, causal)

    @jax.custom_vjp
    def _fa(q, k, v):
        return _flash_forward_kernel(q, k, v, causal, scale, block_q,
                                     block_k, interpret)

    def _fa_fwd(q, k, v):
        return _fa(q, k, v), (q, k, v)

    def _fa_bwd(res, ct):
        q, k, v = res
        _, vjp = jax.vjp(
            lambda a, b, c: _blockwise(a, b, c, scale, causal, block_k),
            q, k, v)
        return vjp(ct)

    _fa.defvjp(_fa_fwd, _fa_bwd)
    return _fa(q, k, v)
