"""Flash attention as a Pallas TPU kernel.

Reference contrast: MXNet's attention kernels are fused strided-batch-GEMMs
(`_contrib_interleaved_matmul_selfatt_*`, src/operator/contrib/
transformer.cc:676-869) that materialize the full (T, T) score matrix. This
kernel is the TPU-first replacement: blockwise online-softmax attention
(flash attention) that keeps O(block_q x block_k) tiles in VMEM, never
materializing the score matrix — the HBM-bandwidth win that matters at long
sequence length (SURVEY §5.7: the capability gap this framework fills).

Layout: q,k,v are (batch*heads, T, head_dim). Grid = (bh, nq, nk) with the
k loop innermost; accumulators (m, l, acc) persist in VMEM scratch across
the nk steps (TPU grids iterate sequentially).

Falls back to the jnp composition off-TPU (tests run interpret=True or the
fallback — same math, tolerances in tests/test_attention.py).
"""
from __future__ import annotations

import functools
import math

import numpy as _np

__all__ = ["flash_attention"]

_NEG_INF = -1e30


def _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, *, scale,
            causal, block_q, block_k, nk, causal_offset=0):
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        m_ref[:] = jnp.full_like(m_ref, _NEG_INF)
        l_ref[:] = jnp.zeros_like(l_ref)
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0].astype(jnp.float32)          # (block_q, d)
        k = k_ref[0].astype(jnp.float32)          # (block_k, d)
        v = v_ref[0].astype(jnp.float32)          # (block_k, d)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            # end-aligned (≙ tril with k = tk - tq): query i attends keys
            # up to i + (tk - tq)
            q_pos = qi * block_q + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_prev = m_ref[:]                          # (block_q, 1)
        m_cur = jnp.max(s, axis=1, keepdims=True)
        m_new = jnp.maximum(m_prev, m_cur)
        p = jnp.exp(s - m_new)                     # (block_q, block_k)
        alpha = jnp.exp(m_prev - m_new)
        l_ref[:] = alpha * l_ref[:] + jnp.sum(p, axis=1, keepdims=True)
        acc_ref[:] = alpha * acc_ref[:] + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        m_ref[:] = m_new

    if causal:
        # skip fully-masked k blocks (block entirely above the diagonal)
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        import jax.numpy as jnp
        denom = jnp.where(l_ref[:] == 0.0, 1.0, l_ref[:])
        o_ref[0] = (acc_ref[:] / denom).astype(o_ref.dtype)


def _kernel_with_lse(q_ref, k_ref, v_ref, o_ref, lse_ref, m_ref, l_ref,
                     acc_ref, *, scale, causal, block_q, block_k, nk,
                     causal_offset=0):
    """Forward kernel that also emits the log-sum-exp per query row — the
    residual the flash backward kernels consume."""
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    _kernel(q_ref, k_ref, v_ref, o_ref, m_ref, l_ref, acc_ref, scale=scale,
            causal=causal, block_q=block_q, block_k=block_k, nk=nk,
            causal_offset=causal_offset)

    ki = pl.program_id(2)

    @pl.when(ki == nk - 1)
    def _emit_lse():
        lse = jnp.where(l_ref[:] > 0.0,
                        m_ref[:] + jnp.log(jnp.maximum(l_ref[:], 1e-37)),
                        _NEG_INF)
        lse_ref[0] = lse.astype(lse_ref.dtype)


def _bwd_dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
                   acc_ref, *, scale, causal, block_q, block_k, nk,
                   causal_offset=0):
    """dq = sum_k  ds @ k * scale,  ds = p * (dO v^T - delta),
    p = exp(s - lse). Grid (bh, nq, nk), k innermost; dq accumulates in
    VMEM scratch (standard flash attention backward, Dao et al. 2022)."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    qi = pl.program_id(1)
    ki = pl.program_id(2)

    @pl.when(ki == 0)
    def _init():
        acc_ref[:] = jnp.zeros_like(acc_ref)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        # all-masked query rows carry the _NEG_INF lse sentinel: s - lse
        # would be 0 there (both -1e30), turning exp into 1 — zero p
        # explicitly so fully-masked rows contribute no gradient
        lse_row = lse_ref[0]
        p = jnp.where(lse_row > _NEG_INF / 2,
                      jnp.exp(s - lse_row), 0.0)        # (bq, bk)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        acc_ref[:] += jax.lax.dot_general(
            ds, k, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(ki == nk - 1)
    def _finalize():
        dq_ref[0] = acc_ref[:].astype(dq_ref.dtype)


def _bwd_dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                    dk_ref, dv_ref, dk_acc, dv_acc, *, scale, causal,
                    block_q, block_k, nq, causal_offset=0):
    """dv = sum_q p^T @ dO;  dk = sum_q ds^T @ q * scale.
    Grid (bh, nk, nq), q innermost; dk/dv accumulate in VMEM scratch."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    ki = pl.program_id(1)
    qi = pl.program_id(2)

    @pl.when(qi == 0)
    def _init():
        dk_acc[:] = jnp.zeros_like(dk_acc)
        dv_acc[:] = jnp.zeros_like(dv_acc)

    def _compute():
        q = q_ref[0].astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)
        v = v_ref[0].astype(jnp.float32)
        do = do_ref[0].astype(jnp.float32)
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            q_pos = qi * block_q + causal_offset + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 0)
            k_pos = ki * block_k + jax.lax.broadcasted_iota(
                jnp.int32, (block_q, block_k), 1)
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        # all-masked query rows carry the _NEG_INF lse sentinel: s - lse
        # would be 0 there (both -1e30), turning exp into 1 — zero p
        # explicitly so fully-masked rows contribute no gradient
        lse_row = lse_ref[0]
        p = jnp.where(lse_row > _NEG_INF / 2,
                      jnp.exp(s - lse_row), 0.0)        # (bq, bk)
        dv_acc[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)           # (bk, d)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - delta_ref[0])
        dk_acc[:] += jax.lax.dot_general(
            ds, q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # (bk, d)

    if causal:
        @pl.when(ki * block_k <= qi * block_q + block_q - 1 + causal_offset)
        def _():
            _compute()
    else:
        _compute()

    @pl.when(qi == nq - 1)
    def _finalize():
        dk_ref[0] = dk_acc[:].astype(dk_ref.dtype)
        dv_ref[0] = dv_acc[:].astype(dv_ref.dtype)


def _blockwise(q, k, v, scale, causal, block_k=512):
    """Differentiable blockwise attention: lax.scan over k blocks with
    online-softmax merging. Same math as the Pallas kernel, O(T·block_k)
    memory in BOTH directions (jax AD through scan recomputes per block) —
    this is the training path backing flash_attention's custom_vjp."""
    import jax
    import jax.numpy as jnp
    bh, tq, d = q.shape
    tk = k.shape[1]
    block_k = min(block_k, tk)
    if tk % block_k:
        return _reference(q, k, v, scale, causal)
    nk = tk // block_k
    kb = k.reshape(bh, nk, block_k, d).transpose(1, 0, 2, 3)
    vb = v.reshape(bh, nk, block_k, d).transpose(1, 0, 2, 3)
    q32 = q.astype(jnp.float32)
    q_pos = jnp.arange(tq)[:, None] + (tk - tq)  # end-aligned causal

    def step(carry, blk):
        m_run, l_run, acc = carry
        k_cur, v_cur, j = blk
        s = jnp.einsum("bqd,bkd->bqk", q32, k_cur.astype(jnp.float32)) * scale
        if causal:
            k_pos = j * block_k + jnp.arange(block_k)[None, :]
            s = jnp.where(q_pos >= k_pos, s, _NEG_INF)
        m_blk = jnp.max(s, axis=-1, keepdims=True)
        m_new = jnp.maximum(m_run, m_blk)
        p = jnp.exp(s - m_new)
        alpha = jnp.exp(m_run - m_new)
        l_new = alpha * l_run + jnp.sum(p, axis=-1, keepdims=True)
        acc_new = alpha * acc + jnp.einsum(
            "bqk,bkd->bqd", p, v_cur.astype(jnp.float32))
        return (m_new, l_new, acc_new), None

    m0 = jnp.full((bh, tq, 1), _NEG_INF, jnp.float32)
    l0 = jnp.zeros((bh, tq, 1), jnp.float32)
    acc0 = jnp.zeros((bh, tq, d), jnp.float32)
    # remat: without it, AD through the scan saves the (bh, tq, block_k)
    # probabilities of every step — O(tq*tk), defeating blockwise memory
    (m, l, acc), _ = jax.lax.scan(
        jax.checkpoint(step), (m0, l0, acc0), (kb, vb, jnp.arange(nk)))
    denom = jnp.where(l == 0.0, 1.0, l)
    return (acc / denom).astype(q.dtype)


def _reference(q, k, v, scale, causal):
    import jax
    import jax.numpy as jnp
    s = jnp.einsum("bqd,bkd->bqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        s = jnp.where(mask, s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bqk,bkd->bqd", p, v.astype(jnp.float32)).astype(q.dtype)


def _flash_forward_kernel(q, k, v, causal, scale, block_q, block_k,
                          interpret):
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = tq // block_q
    nk = tk // block_k
    grid = (bh, nq, nk)
    kernel = functools.partial(_kernel, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk,
                               causal_offset=tk - tq)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),   # m (running max)
            pltpu.VMEM((block_q, 1), jnp.float32),   # l (running denom)
            pltpu.VMEM((block_q, d), jnp.float32),   # acc
        ],
        interpret=interpret,
    )(q, k, v)


def _flash_forward_lse(q, k, v, causal, scale, block_q, block_k, interpret):
    """Forward returning (o, lse) — the training-path entry."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = tq // block_q
    nk = tk // block_k
    kernel = functools.partial(_kernel_with_lse, scale=scale, causal=causal,
                               block_q=block_q, block_k=block_k, nk=nk,
                               causal_offset=tk - tq)
    return pl.pallas_call(
        kernel,
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
            jax.ShapeDtypeStruct((bh, tq, 1), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, 1), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v)


def _flash_backward(q, k, v, do, lse, delta, causal, scale, block_q,
                    block_k, interpret):
    """Pallas dq + dkv kernels (flash attention backward as two sweeps)."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    bh, tq, d = q.shape
    tk = k.shape[1]
    nq = tq // block_q
    nk = tk // block_k
    off = tk - tq

    dq = pl.pallas_call(
        functools.partial(_bwd_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nk=nk,
                          causal_offset=off),
        grid=(bh, nq, nk),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, i, j: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, i, j: (b, i, 0)),
        ],
        out_specs=pl.BlockSpec((1, block_q, d), lambda b, i, j: (b, i, 0)),
        out_shape=jax.ShapeDtypeStruct((bh, tq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(q, k, v, do, lse, delta)

    dk, dv = pl.pallas_call(
        functools.partial(_bwd_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, nq=nq,
                          causal_offset=off),
        grid=(bh, nk, nq),
        in_specs=[
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_q, d), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
            pl.BlockSpec((1, block_q, 1), lambda b, j, i: (b, i, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
            pl.BlockSpec((1, block_k, d), lambda b, j, i: (b, j, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((bh, tk, d), k.dtype),
            jax.ShapeDtypeStruct((bh, tk, d), v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_k, d), jnp.float32),
            pltpu.VMEM((block_k, d), jnp.float32),
        ],
        interpret=interpret,
    )(q, k, v, do, lse, delta)
    return dq, dk, dv


def _auto_blocks(tq, tk, d, vmem_budget=8 * 1024 * 1024):
    """Pick (block_q, block_k): the largest power-of-two tiles that DIVIDE
    the sequence lengths (halving preserves divisibility, so the kernel —
    not the dense fallback — runs for any even-pow2-factor length) and
    whose working set — q/k/v/do tiles, the (bq, bk) score tile, and f32
    accumulators — fits the VMEM budget. Bigger tiles amortize HBM
    traffic; the cap keeps double-buffering viable."""
    def fits(bq, bk):
        tiles = (bq * d * 4 * 2          # q tile + do tile
                 + bk * d * 4 * 4        # k, v tiles + dk/dv accums
                 + bq * bk * 4 * 2       # score + ds tiles
                 + bq * d * 4)           # acc
        return tiles * 2 <= vmem_budget  # x2: double buffering headroom

    def pow2_divisor(n, cap=1024):
        return min(n & -n, cap)          # largest 2^k dividing n

    bq = pow2_divisor(tq)
    while bq > 8:
        bk = pow2_divisor(tk)
        while bk > 8 and not fits(bq, bk):
            bk //= 2
        if fits(bq, bk):
            return bq, bk
        bq //= 2
    bk = pow2_divisor(tk)
    while bk > 8 and not fits(bq, bk):
        bk //= 2
    return bq, bk


def flash_attention(q, k, v, causal=False, scale=None, block_q=None,
                    block_k=None, interpret=False):
    """Blockwise attention. q: (bh, Tq, d), k/v: (bh, Tk, d) raw jax arrays.

    Forward AND backward are Pallas kernels on TPU (or interpret=True
    anywhere): forward emits (o, lse); backward runs the two-sweep flash
    gradient (dq sweep over k blocks, dk/dv sweep over q blocks) — no
    (T, T) score matrix in either direction. Block sizes default to the
    VMEM-budget autotune (_auto_blocks); pass block_q/block_k to pin.
    Falls back to the differentiable blockwise scan off-TPU and to the
    einsum composition on ragged shapes."""
    import jax
    import jax.numpy as jnp

    bh, tq, d = q.shape
    tk = k.shape[1]
    if scale is None:
        scale = 1.0 / math.sqrt(d)

    from ..device import tpu_platform_available
    on_tpu = tpu_platform_available()
    if not (on_tpu or interpret):
        return _blockwise(q, k, v, scale, causal,
                          block_k if block_k else 512)

    auto_q, auto_k = _auto_blocks(tq, tk, d)
    block_q = min(block_q or auto_q, tq)
    block_k = min(block_k or auto_k, tk)
    if tq % block_q or tk % block_k:
        # ragged tails: fall back (padding support comes with masked loads)
        return _reference(q, k, v, scale, causal)

    @jax.custom_vjp
    def _fa(q, k, v):
        # inference/primal path: the lse-free kernel (no wasted residual
        # output); the vjp fwd below runs the lse-emitting twin
        return _flash_forward_kernel(q, k, v, causal, scale, block_q,
                                     block_k, interpret)

    def _fa_fwd(q, k, v):
        o, lse = _flash_forward_lse(q, k, v, causal, scale, block_q,
                                    block_k, interpret)
        return o, (q, k, v, o, lse)

    def _fa_bwd(res, ct):
        q, k, v, o, lse = res
        # delta = rowsum(dO * O) per query (the softmax-normalizer term)
        delta = jnp.sum(ct.astype(jnp.float32) * o.astype(jnp.float32),
                        axis=-1, keepdims=True)
        return _flash_backward(q, k, v, ct, lse, delta, causal, scale,
                               block_q, block_k, interpret)

    _fa.defvjp(_fa_fwd, _fa_bwd)
    return _fa(q, k, v)
