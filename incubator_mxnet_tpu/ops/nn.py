"""Pure functional NN ops on raw jax arrays — the kernel layer.

Reference equivalents: src/operator/nn/* (23k LoC: conv/FC/pool/norm/softmax/
dropout/activation C++ & CUDA kernels), src/operator/nn/cudnn/* and
src/operator/nn/mkldnn/* backend dispatch. TPU-native: each op is a jax/lax
composition that XLA lowers straight onto the MXU/VPU; the cuDNN/oneDNN
descriptor + algo-autotune machinery (cudnn_algoreg-inl.h) has no equivalent
because XLA picks conv algorithms during compilation. All functions here take
and return raw jax arrays; NDArray wrapping/taping happens in the `npx`/gluon
wrappers via ops.registry.invoke.

Layouts: accepts NCHW (reference default) or NHWC; on TPU NHWC is the
MXU-friendly layout and is used by the model zoo's hybridized path.
"""
from __future__ import annotations

import functools

import numpy as _np


def _jx():
    import jax
    return jax


def _jnp():
    import jax.numpy as jnp
    return jnp


# ---------------------------------------------------------------------------
# dense / linear (reference: src/operator/nn/fully_connected.cc:252-323)
# ---------------------------------------------------------------------------
def dense(x, weight, bias=None, flatten=True):
    """y = x @ W^T + b. `flatten=True` collapses trailing dims (reference
    FullyConnectedParam.flatten)."""
    jnp = _jnp()
    if flatten and x.ndim > 2:
        x = x.reshape(x.shape[0], -1)
    y = jnp.matmul(x, weight.T)
    if bias is not None:
        y = y + bias
    return y


# ---------------------------------------------------------------------------
# convolution (reference: src/operator/nn/convolution*.cc + im2col;
# cudnn_convolution-inl.h collapses into lax.conv_general_dilated)
# ---------------------------------------------------------------------------
def _tuplize(v, n):
    if isinstance(v, (tuple, list)):
        return tuple(v)
    return (v,) * n


def conv(x, weight, bias=None, stride=1, padding=0, dilation=1, groups=1,
         layout="NCHW"):
    """N-D convolution. weight layout follows the data layout
    (OIHW for NCHW, HWIO for NHWC)."""
    lax = _jx().lax
    nd = x.ndim - 2
    stride = _tuplize(stride, nd)
    dilation = _tuplize(dilation, nd)
    padding = _tuplize(padding, nd)
    pads = [(p, p) for p in padding]
    if layout.startswith("NC"):  # NCW / NCHW / NCDHW
        spatial = layout[2:]
        dn = lax.conv_dimension_numbers(
            x.shape, weight.shape,
            (layout, "OI" + spatial, layout))
    else:  # NWC / NHWC / NDHWC
        spatial = layout[1:-1]
        dn = lax.conv_dimension_numbers(
            x.shape, weight.shape,
            (layout, spatial + "IO", layout))
    y = lax.conv_general_dilated(
        x, weight, stride, pads, rhs_dilation=dilation,
        dimension_numbers=dn, feature_group_count=groups,
        preferred_element_type=None)
    if bias is not None:
        if layout.startswith("NC"):
            y = y + bias.reshape((1, -1) + (1,) * nd)
        else:
            y = y + bias
    return y


def conv_transpose(x, weight, bias=None, stride=1, padding=0, dilation=1,
                   output_padding=0, groups=1, layout="NCHW"):
    """Transposed convolution (reference: src/operator/nn/deconvolution*).
    Implemented as lax.conv_transpose-equivalent via input dilation."""
    lax = _jx().lax
    nd = x.ndim - 2
    stride = _tuplize(stride, nd)
    dilation = _tuplize(dilation, nd)
    padding = _tuplize(padding, nd)
    output_padding = _tuplize(output_padding, nd)
    if groups != 1:
        # grouped deconv = per-group deconv over channel slices, concat on
        # the channel axis (≙ deconvolution-inl.h group handling). The
        # group count is a trace-time constant, so the unrolled convs fuse.
        jnp = _jnp()
        ch_axis = 1 if layout.startswith("NC") else x.ndim - 1
        cin = x.shape[ch_axis]
        if cin % groups or weight.shape[0 if layout.startswith("NC")
                                        else -1] % groups:
            raise ValueError("channels not divisible by groups")
        xs = jnp.split(x, groups, axis=ch_axis)
        # deconv weight carries in_channels on dim 0 (NC) / last (NHWC-style)
        w_axis = 0 if layout.startswith("NC") else weight.ndim - 1
        ws = jnp.split(weight, groups, axis=w_axis)
        ys = [conv_transpose(xg, wg, None, stride, padding, dilation,
                             output_padding, 1, layout)
              for xg, wg in zip(xs, ws)]
        y = jnp.concatenate(ys, axis=ch_axis)
        if bias is not None:
            nd_ = x.ndim - 2
            if layout.startswith("NC"):
                y = y + bias.reshape((1, -1) + (1,) * nd_)
            else:
                y = y + bias
        return y
    if layout.startswith("NC"):
        spatial = layout[2:]
        # deconv weight layout in the reference is (in, out, *k)
        dn = lax.conv_dimension_numbers(
            x.shape, weight.shape, (layout, "IO" + spatial, layout))
        kdims = [weight.shape[2 + i] for i in range(nd)]
    else:
        spatial = layout[1:-1]
        dn = lax.conv_dimension_numbers(
            x.shape, weight.shape, (layout, spatial + "OI", layout))
        kdims = [weight.shape[i] for i in range(nd)]
    pads = []
    for i in range(nd):
        k = (kdims[i] - 1) * dilation[i] + 1
        lo = k - 1 - padding[i]
        hi = k - 1 - padding[i] + output_padding[i]
        pads.append((lo, hi))
    y = lax.conv_general_dilated(
        x, weight, window_strides=(1,) * nd, padding=pads,
        lhs_dilation=stride, rhs_dilation=dilation, dimension_numbers=dn)
    if bias is not None:
        if layout.startswith("NC"):
            y = y + bias.reshape((1, -1) + (1,) * nd)
        else:
            y = y + bias
    return y


# ---------------------------------------------------------------------------
# pooling (reference: src/operator/nn/pooling*.cc; cudnn_pooling-inl.h)
# ---------------------------------------------------------------------------
def pooling(x, kernel, pool_type="max", stride=None, padding=0,
            global_pool=False, count_include_pad=True, layout="NCHW",
            ceil_mode=False):
    lax = _jx().lax
    jnp = _jnp()
    nd = x.ndim - 2
    channel_last = not layout.startswith("NC")
    if global_pool:
        axes = tuple(range(1, 1 + nd)) if channel_last else tuple(range(2, 2 + nd))
        if pool_type == "max":
            return jnp.max(x, axis=axes, keepdims=True)
        return jnp.mean(x, axis=axes, keepdims=True)
    kernel = _tuplize(kernel, nd)
    stride = _tuplize(stride if stride is not None else kernel, nd)
    padding = _tuplize(padding, nd)
    spatial = x.shape[1:1 + nd] if channel_last else x.shape[2:2 + nd]
    # ceil_mode (reference pooling_convention='full'): extend right padding so
    # the last partial window is included: out = ceil((in+2p-k)/s)+1
    pad_pairs = []
    for size, k, s, p in zip(spatial, kernel, stride, padding):
        hi = p
        if ceil_mode:
            out = -(-(size + 2 * p - k) // s) + 1
            needed = (out - 1) * s + k - size - p
            hi = max(p, needed)
        pad_pairs.append((p, hi))
    if channel_last:
        window = (1,) + kernel + (1,)
        strides = (1,) + stride + (1,)
        pads = ((0, 0),) + tuple(pad_pairs) + ((0, 0),)
    else:
        window = (1, 1) + kernel
        strides = (1, 1) + stride
        pads = ((0, 0), (0, 0)) + tuple(pad_pairs)
    if pool_type == "max":
        init = -jnp.inf if jnp.issubdtype(x.dtype, jnp.floating) else jnp.iinfo(x.dtype).min
        return lax.reduce_window(x, init, lax.max, window, strides, pads)
    if pool_type in ("avg", "sum"):
        # init must be a python literal: an array init breaks reverse-mode
        # linearization of reduce_window under jit (jax 0.9)
        zero = 0.0 if jnp.issubdtype(x.dtype, jnp.floating) else 0
        s = lax.reduce_window(x, zero, lax.add, window, strides, pads)
        if pool_type == "sum":
            return s
        if count_include_pad or all(lo == 0 and hi == 0
                                    for lo, hi in pad_pairs):
            denom = _np.prod(kernel)
            return s / _np.asarray(denom, dtype=_np.float32).astype(x.dtype)
        ones = jnp.ones_like(x)
        denom = lax.reduce_window(ones, 0.0, lax.add, window, strides, pads)
        return s / denom
    raise ValueError(f"unknown pool_type {pool_type!r}")


def adaptive_avg_pool2d(x, output_size, layout="NCHW"):
    """reference: src/operator/contrib/adaptive_avg_pooling.cc"""
    jnp = _jnp()
    if isinstance(output_size, int):
        output_size = (output_size, output_size)
    if layout == "NCHW":
        n, c, h, w = x.shape
    else:
        n, h, w, c = x.shape
    oh, ow = output_size
    if h % oh == 0 and w % ow == 0:
        kh, kw = h // oh, w // ow
        return pooling(x, (kh, kw), "avg", stride=(kh, kw), layout=layout)
    # fallback: mean over fractional windows via resize-style gather
    hi = _np.floor(_np.arange(oh + 1) * h / oh).astype(int)
    wi = _np.floor(_np.arange(ow + 1) * w / ow).astype(int)
    rows = []
    for i in range(oh):
        cols = []
        for j in range(ow):
            if layout == "NCHW":
                patch = x[:, :, hi[i]:hi[i + 1], wi[j]:wi[j + 1]]
                cols.append(jnp.mean(patch, axis=(2, 3)))
            else:
                patch = x[:, hi[i]:hi[i + 1], wi[j]:wi[j + 1], :]
                cols.append(jnp.mean(patch, axis=(1, 2)))
        rows.append(jnp.stack(cols, axis=-1))
    out = jnp.stack(rows, axis=-2)
    if layout == "NCHW":
        return out  # (n, c, oh, ow)
    return jnp.moveaxis(out, 1, -1)


# ---------------------------------------------------------------------------
# normalization (reference: src/operator/nn/batch_norm*, layer_norm*,
# group_norm*, instance_norm.cc; SyncBatchNorm in contrib)
# ---------------------------------------------------------------------------
def batch_norm(x, gamma, beta, running_mean, running_var, momentum=0.9,
               eps=1e-5, training=True, axis=1, use_global_stats=False,
               sync_axis_name=None):
    """Returns (out, new_running_mean, new_running_var). When
    `sync_axis_name` is set and we're inside shard_map/pmap, batch statistics
    are allreduced over that mesh axis (≙ contrib SyncBatchNorm,
    src/operator/contrib/sync_batch_norm-inl.h — cross-device moments)."""
    jnp = _jnp()
    lax = _jx().lax
    reduce_axes = tuple(i for i in range(x.ndim) if i != (axis % x.ndim))
    bshape = [1] * x.ndim
    bshape[axis % x.ndim] = x.shape[axis % x.ndim]
    if training and not use_global_stats:
        xf = x.astype(jnp.float32)
        mean = jnp.mean(xf, axis=reduce_axes)
        mean_sq = jnp.mean(jnp.square(xf), axis=reduce_axes)
        if sync_axis_name is not None:
            mean = lax.pmean(mean, sync_axis_name)
            mean_sq = lax.pmean(mean_sq, sync_axis_name)
        var = mean_sq - jnp.square(mean)
        new_rm = momentum * running_mean + (1 - momentum) * mean
        new_rv = momentum * running_var + (1 - momentum) * var
    else:
        mean, var = running_mean, running_var
        new_rm, new_rv = running_mean, running_var
    inv = lax.rsqrt(var.astype(jnp.float32) + eps)
    out = (x.astype(jnp.float32) - mean.reshape(bshape)) * inv.reshape(bshape)
    # scale/shift IN f32, one cast at the end: casting first would promote
    # back to f32 against the f32 gamma/beta, making every BN output f32
    # under AMP and doubling activation HBM traffic (bandwidth-bound nets)
    if gamma is not None:
        out = out * gamma.reshape(bshape).astype(jnp.float32)
    if beta is not None:
        out = out + beta.reshape(bshape).astype(jnp.float32)
    return out.astype(x.dtype), new_rm, new_rv


def layer_norm(x, gamma, beta, axis=-1, eps=1e-5):
    """reference: src/operator/nn/layer_norm*.cc"""
    jnp = _jnp()
    lax = _jx().lax
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axis, keepdims=True)
    var = jnp.var(xf, axis=axis, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    if gamma is not None:
        bshape = [1] * x.ndim
        bshape[axis % x.ndim] = x.shape[axis % x.ndim]
        out = (out * gamma.reshape(bshape).astype(jnp.float32)
               + beta.reshape(bshape).astype(jnp.float32))
    return out.astype(x.dtype)


def group_norm(x, gamma, beta, num_groups, eps=1e-5):
    """reference: src/operator/nn/group_norm*.cc (NCHW layout)"""
    jnp = _jnp()
    lax = _jx().lax
    n, c = x.shape[0], x.shape[1]
    rest = x.shape[2:]
    xg = x.reshape((n, num_groups, c // num_groups) + rest).astype(jnp.float32)
    axes = tuple(range(2, xg.ndim))
    mean = jnp.mean(xg, axis=axes, keepdims=True)
    var = jnp.var(xg, axis=axes, keepdims=True)
    out = ((xg - mean) * lax.rsqrt(var + eps)).reshape(x.shape)
    if gamma is not None:
        bshape = (1, c) + (1,) * len(rest)
        out = (out * gamma.reshape(bshape).astype(jnp.float32)
               + beta.reshape(bshape).astype(jnp.float32))
    return out.astype(x.dtype)


def instance_norm(x, gamma, beta, eps=1e-5):
    """reference: src/operator/instance_norm.cc (normalize over spatial dims)"""
    jnp = _jnp()
    lax = _jx().lax
    axes = tuple(range(2, x.ndim))
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=axes, keepdims=True)
    var = jnp.var(xf, axis=axes, keepdims=True)
    out = (xf - mean) * lax.rsqrt(var + eps)
    bshape = (1, x.shape[1]) + (1,) * (x.ndim - 2)
    out = (out * gamma.reshape(bshape).astype(jnp.float32)
           + beta.reshape(bshape).astype(jnp.float32))
    return out.astype(x.dtype)


def l2_normalize(x, axis=-1, eps=1e-10):
    """reference: src/operator/l2_normalization.cc"""
    jnp = _jnp()
    return x / jnp.sqrt(jnp.sum(jnp.square(x), axis=axis, keepdims=True) + eps)


def rms_norm(x, gamma, axis=-1, eps=1e-6):
    """RMSNorm — beyond-reference op for modern transformer parity."""
    jnp = _jnp()
    lax = _jx().lax
    xf = x.astype(jnp.float32)
    ms = jnp.mean(jnp.square(xf), axis=axis, keepdims=True)
    out = xf * lax.rsqrt(ms + eps)
    if gamma is not None:
        out = out * gamma.astype(jnp.float32)
    return out.astype(x.dtype)


# ---------------------------------------------------------------------------
# dropout (reference: src/operator/nn/dropout*.cc — mask cached for backward)
# ---------------------------------------------------------------------------
def dropout(x, rate, key, training=True, axes=None):
    jnp = _jnp()
    jr = _jx().random
    if not training or rate <= 0.0:
        return x
    shape = x.shape if not axes else tuple(
        x.shape[i] if i in axes else 1 for i in range(x.ndim))
    keep = 1.0 - rate
    mask = jr.bernoulli(key, keep, shape)
    return jnp.where(mask, x / keep, jnp.zeros((), x.dtype)).astype(x.dtype)


# ---------------------------------------------------------------------------
# softmax family (reference: src/operator/nn/softmax*.cc, log_softmax, softmin)
# ---------------------------------------------------------------------------
def softmax(x, axis=-1, temperature=None, length=None):
    jax = _jx()
    jnp = _jnp()
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    if length is not None:
        x = sequence_mask_axis(x, length, axis, -_np.inf)
        out = jax.nn.softmax(x, axis=axis)
        return jnp.where(jnp.isnan(out), jnp.zeros((), out.dtype), out)
    return jax.nn.softmax(x, axis=axis)


def log_softmax(x, axis=-1, temperature=None):
    if temperature is not None and temperature != 1.0:
        x = x / temperature
    return _jx().nn.log_softmax(x, axis=axis)


def softmin(x, axis=-1):
    return _jx().nn.softmax(-x, axis=axis)


def masked_softmax(x, mask, axis=-1, temperature=1.0):
    jnp = _jnp()
    x = jnp.where(mask, x / temperature, jnp.full((), -1e30, x.dtype))
    out = _jx().nn.softmax(x, axis=axis)
    return jnp.where(mask, out, jnp.zeros((), out.dtype))


def sequence_mask_axis(x, length, axis, value):
    """Mask positions >= length along `axis` (helper for softmax(length=...))."""
    jnp = _jnp()
    n = x.shape[axis]
    idx_shape = [1] * x.ndim
    idx_shape[axis] = n
    idx = jnp.arange(n).reshape(idx_shape)
    len_shape = [1] * x.ndim
    len_shape[0] = x.shape[0]
    lb = length.reshape(len_shape)
    return jnp.where(idx < lb, x, jnp.full((), value, x.dtype))


# ---------------------------------------------------------------------------
# activations (reference: src/operator/nn/activation.cc, leaky_relu.cc zoo)
# ---------------------------------------------------------------------------
def activation(x, act_type):
    jax = _jx()
    jnp = _jnp()
    if act_type == "relu":
        return jax.nn.relu(x)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(x)
    if act_type == "tanh":
        return jnp.tanh(x)
    if act_type == "softrelu":
        return jax.nn.softplus(x)
    if act_type == "softsign":
        return jax.nn.soft_sign(x)
    if act_type == "log_sigmoid":
        return jax.nn.log_sigmoid(x)
    if act_type == "mish":
        return x * jnp.tanh(jax.nn.softplus(x))
    raise ValueError(f"unknown activation {act_type!r}")


def leaky_relu(x, act_type="leaky", slope=0.25, gamma=None, upper=0.334,
               lower=0.125, key=None, training=False):
    """reference: src/operator/leaky_relu.cc (leaky/prelu/rrelu/elu/selu/gelu)"""
    jax = _jx()
    jnp = _jnp()
    if act_type == "leaky":
        return jax.nn.leaky_relu(x, slope)
    if act_type == "prelu":
        return jnp.where(x >= 0, x, gamma * x)
    if act_type == "elu":
        return jax.nn.elu(x, slope)
    if act_type == "selu":
        return jax.nn.selu(x)
    if act_type == "gelu":
        return jax.nn.gelu(x, approximate=False)
    if act_type == "gelu_tanh":
        return jax.nn.gelu(x, approximate=True)
    if act_type == "rrelu":
        if training and key is not None:
            u = jax.random.uniform(key, x.shape, jnp.float32, lower, upper)
            return jnp.where(x >= 0, x, (u * x.astype(jnp.float32)).astype(x.dtype))
        return jax.nn.leaky_relu(x, (lower + upper) / 2)
    raise ValueError(f"unknown leaky_relu type {act_type!r}")


def silu(x):
    return _jx().nn.silu(x)


swish = silu


# ---------------------------------------------------------------------------
# indexing helpers (reference: src/operator/tensor/indexing_op.*)
# ---------------------------------------------------------------------------
def embedding(indices, weight):
    """reference: Embedding op (indexing_op.h) — gather rows."""
    return weight[indices.astype("int32")]


def one_hot(indices, depth, on_value=1.0, off_value=0.0, dtype="float32"):
    jax = _jx()
    return jax.nn.one_hot(indices, depth, dtype=dtype) * (on_value - off_value) \
        + off_value


def pick(x, index, axis=-1, keepdims=False, mode="clip"):
    """reference: pick op — select one element along axis per position."""
    jnp = _jnp()
    idx = jnp.clip(index.astype("int32"), 0, x.shape[axis] - 1)
    picked = jnp.take_along_axis(x, jnp.expand_dims(idx, axis), axis=axis)
    return picked if keepdims else jnp.squeeze(picked, axis)


def topk(x, k=1, axis=-1, ret_typ="indices", is_ascend=False):
    """reference: src/operator/tensor/ordering_op-inl.h"""
    jax = _jx()
    jnp = _jnp()
    xm = jnp.moveaxis(x, axis, -1)
    vals, idx = jax.lax.top_k(-xm if is_ascend else xm, k)
    if is_ascend:
        vals = -vals
    vals = jnp.moveaxis(vals, -1, axis)
    idx = jnp.moveaxis(idx, -1, axis)
    if ret_typ == "indices":
        return idx
    if ret_typ == "value":
        return vals
    return vals, idx


def sequence_mask(x, sequence_length=None, use_sequence_length=False, value=0.0,
                  axis=0):
    """reference: src/operator/sequence_mask.cc (time-major default)"""
    jnp = _jnp()
    if not use_sequence_length or sequence_length is None:
        return x
    n = x.shape[axis]
    batch_axis = 1 - axis
    idx_shape = [1] * x.ndim
    idx_shape[axis] = n
    idx = jnp.arange(n).reshape(idx_shape)
    len_shape = [1] * x.ndim
    len_shape[batch_axis] = x.shape[batch_axis]
    lb = sequence_length.reshape(len_shape)
    return jnp.where(idx < lb, x, jnp.full((), value, x.dtype))


# ---------------------------------------------------------------------------
# fused RNN (reference: src/operator/rnn.cc + rnn_impl.h — LSTM/GRU/vanilla,
# cuDNN-backed on GPU). TPU-native: lax.scan over time, weights packed per
# layer/direction like the reference's flat parameter vector.
# ---------------------------------------------------------------------------
def lstm_cell(x, h, c, wx, wh, b):
    jnp = _jnp()
    jax = _jx()
    gates = jnp.matmul(x, wx.T) + jnp.matmul(h, wh.T) + b
    i, f, g, o = jnp.split(gates, 4, axis=-1)
    i = jax.nn.sigmoid(i)
    f = jax.nn.sigmoid(f)
    g = jnp.tanh(g)
    o = jax.nn.sigmoid(o)
    c_new = f * c + i * g
    h_new = o * jnp.tanh(c_new)
    return h_new, c_new


def gru_cell(x, h, wx, wh, bx, bh):
    jnp = _jnp()
    jax = _jx()
    gx = jnp.matmul(x, wx.T) + bx
    gh = jnp.matmul(h, wh.T) + bh
    rx, zx, nx = jnp.split(gx, 3, axis=-1)
    rh, zh, nh = jnp.split(gh, 3, axis=-1)
    r = jax.nn.sigmoid(rx + rh)
    z = jax.nn.sigmoid(zx + zh)
    n = jnp.tanh(nx + r * nh)
    return (1 - z) * n + z * h


def rnn_relu_cell(x, h, wx, wh, b, act="tanh"):
    jnp = _jnp()
    pre = jnp.matmul(x, wx.T) + jnp.matmul(h, wh.T) + b
    return _jx().nn.relu(pre) if act == "relu" else jnp.tanh(pre)


def _scan_layer(cell_step, xs, carry_init, reverse=False):
    lax = _jx().lax
    carry, ys = lax.scan(cell_step, carry_init, xs, reverse=reverse)
    return carry, ys


def rnn(x, params, state, mode="lstm", num_layers=1, hidden_size=None,
        bidirectional=False, dropout_rate=0.0, key=None, training=False):
    """Multi-layer (bi)directional RNN over time-major input (T, N, C).

    `params` is a dict  {(layer, direction): {"wx","wh","bx","bh"}};
    `state` is (h0,) or (h0, c0) with shape (L*D, N, H).
    Returns (output (T,N,H*D), new_state tuple). ≙ the fused `rnn` op
    (src/operator/rnn.cc) that rnn_layer.py lowers to.
    """
    jnp = _jnp()
    ndir = 2 if bidirectional else 1
    h0 = state[0]
    c0 = state[1] if mode == "lstm" else None
    out = x
    h_list, c_list = [], []
    for layer in range(num_layers):
        dir_outs = []
        for d in range(ndir):
            p = params[(layer, d)]
            idx = layer * ndir + d
            hh = h0[idx]
            if mode == "lstm":
                cc = c0[idx]

                def step(carry, xt, p=p):
                    h, c = carry
                    hn, cn = lstm_cell(xt, h, c, p["wx"], p["wh"],
                                       p["bx"] + p["bh"])
                    return (hn, cn), hn

                (hT, cT), ys = _scan_layer(step, out, (hh, cc), reverse=(d == 1))
                c_list.append(cT)
            elif mode == "gru":
                def step(h, xt, p=p):
                    hn = gru_cell(xt, h, p["wx"], p["wh"], p["bx"], p["bh"])
                    return hn, hn

                hT, ys = _scan_layer(step, out, hh, reverse=(d == 1))
            else:  # rnn_tanh / rnn_relu
                act = "relu" if mode == "rnn_relu" else "tanh"

                def step(h, xt, p=p, act=act):
                    hn = rnn_relu_cell(xt, h, p["wx"], p["wh"],
                                       p["bx"] + p["bh"], act)
                    return hn, hn

                hT, ys = _scan_layer(step, out, hh, reverse=(d == 1))
            h_list.append(hT)
            dir_outs.append(ys)
        out = dir_outs[0] if ndir == 1 else jnp.concatenate(dir_outs, axis=-1)
        if dropout_rate > 0 and training and key is not None and layer < num_layers - 1:
            import jax.random as jr
            key, sub = jr.split(key)
            out = dropout(out, dropout_rate, sub, training=True)
    h_out = jnp.stack(h_list, axis=0)
    if mode == "lstm":
        return out, (h_out, jnp.stack(c_list, axis=0))
    return out, (h_out,)


# ---------------------------------------------------------------------------
# attention (reference: src/operator/contrib/transformer.cc:676-869 —
# interleaved_matmul_selfatt fused attention). TPU-native: jnp einsum which XLA
# fuses onto the MXU; flash/ring variants live in ops/pallas & parallel/.
# ---------------------------------------------------------------------------
def scaled_dot_product_attention(q, k, v, mask=None, scale=None, causal=False):
    """q,k,v: (..., T, H). Returns attention output (..., T, H)."""
    jnp = _jnp()
    jax = _jx()
    d = q.shape[-1]
    if scale is None:
        scale = 1.0 / _np.sqrt(d)
    logits = jnp.einsum("...qd,...kd->...qk", q, k) * scale
    if causal:
        tq, tk = logits.shape[-2], logits.shape[-1]
        cm = jnp.tril(jnp.ones((tq, tk), bool), k=tk - tq)
        logits = jnp.where(cm, logits, jnp.full((), -1e30, logits.dtype))
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.full((), -1e30, logits.dtype))
    w = jax.nn.softmax(logits.astype(jnp.float32), axis=-1).astype(q.dtype)
    return jnp.einsum("...qk,...kd->...qd", w, v)


# ---------------------------------------------------------------------------
# Dispatch-record metadata (PR2). `_amp_class` rides into the OpInfo record
# at register_op time (ops/registry.py): the registering wrapper passes
# amp=<class>, and invoke's policy lookup uses it for op names the
# amp/lists.py name lists don't cover (the lists, including user overrides
# via amp.init(...), always win when they know the name). 'safe' = run in
# the autocast low-precision dtype (MXU-bound FLOPs), 'unsafe' = pin fp32
# (accumulations / precision cliffs), untagged = 'neutral' (widest-type).
# ---------------------------------------------------------------------------
for _f, _cls in ((dense, "safe"), (conv, "safe"), (conv_transpose, "safe"),
                 (scaled_dot_product_attention, "safe"),
                 (lstm_cell, "safe"), (gru_cell, "safe"),
                 (rnn_relu_cell, "safe"), (pooling, "safe"),
                 (softmax, "unsafe"), (log_softmax, "unsafe"),
                 (softmin, "unsafe"), (masked_softmax, "unsafe"),
                 (batch_norm, "unsafe"), (layer_norm, "unsafe"),
                 (group_norm, "unsafe"), (instance_norm, "unsafe"),
                 (rms_norm, "unsafe"), (l2_normalize, "unsafe")):
    _f._amp_class = _cls
del _f, _cls
