"""Pallas TPU kernels for the fused-op tier (ops/fused.py).

Reference contrast: MXNet's `USE_FUSION` RTC machinery generated pointwise
CUDA kernels at runtime (src/operator/fusion/fused_op.cu); here the worst
memory-bound offender classes the `mx.inspect` roofline attribution ranks
(benchmark/results/offenders_resnet18_r09.json) get hand-written TPU
kernels instead:

  * `apply_scale_shift_act` — ONE pass of `act(x*scale + shift [+ res])`
    over a (rows, channels) view: the normalize-scale-shift(-residual-relu)
    chains XLA splits into several 0.26-intensity `multiply_multiply`
    fusions become a single VMEM-resident sweep (one read of x/residual,
    one write of out — the roofline floor for this op class).
  * `avg_pool2d_fwd` / `avg_pool2d_bwd` — non-overlapping average pooling
    (kernel == stride, no padding; the GlobalAvgPool shape included) with a
    VMEM-tiled backward: the gradient is an in-register broadcast of the
    upstream tile instead of XLA's generic reduce-window gradient scatter
    (the 0.18-intensity `reduce-window` offender class).
  * `paged_attention_fwd` — decode attention over the serve.kv_pool
    slotted KV slab, read IN PLACE (no per-layer gather/copy of the
    `(slots, max_len, ...)` cache). Block-sparse: per-lane `lengths`
    are scalar-prefetched so the token-block index map CLAMPS to each
    lane's `[0, cur_len + C)` — blocks past a lane's live prefix are
    never fetched from HBM (the clamped index revisits the last live
    block, whose copy is elided) and their compute is `pl.when`-skipped.
    Online-softmax VMEM accumulators carry across the sequential token
    grid. Optional per-position f32 scales dequantize int8 slabs on the
    fly (serve.kv_pool `dtype="int8"`).

Everything here takes and returns raw jax arrays and is shape-strict: the
caller (ops/fused.py) owns fallback policy, custom_vjp wiring and layout
handling. Kernels compute in float32 internally and cast to the input
dtype on the way out, matching ops/nn.py norm semantics under AMP.

Layout: channels-minor (the TPU-preferred NHWC family) — `x` is reshaped
by the caller to (M, C) for the apply kernel and kept (N, H, W, C) for
pooling. Tile sizes come from a VMEM budget (see `_block_rows`).
"""
from __future__ import annotations

import functools

__all__ = ["apply_scale_shift_act", "avg_pool2d_fwd", "avg_pool2d_bwd",
           "paged_attention_fwd", "supported_act", "ACTS"]

# activation set the kernels (and their hand-derived VJPs) support; None
# means identity. Kept in sync with ops/fused.py's dispatch tables.
ACTS = (None, "relu", "sigmoid", "tanh", "silu", "gelu")

_VMEM_BUDGET = 4 * 1024 * 1024   # bytes of f32 working set per program


def supported_act(act_type):
    return act_type in ACTS


def _act_f32(jax, jnp, u, act_type):
    if act_type is None:
        return u
    if act_type == "relu":
        return jax.nn.relu(u)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(u)
    if act_type == "tanh":
        return jnp.tanh(u)
    if act_type == "silu":
        return jax.nn.silu(u)
    if act_type == "gelu":
        return jax.nn.gelu(u, approximate=False)
    raise ValueError(f"unsupported fused activation {act_type!r}")


def _block_rows(m, c, n_row_bufs, cap=1024):
    """Largest power-of-two row tile that divides `m` and keeps
    `n_row_bufs` (M, C)-shaped f32 buffers inside the VMEM budget.
    Returns 0 when even a single row of C floats cannot fit."""
    if c * 4 * n_row_bufs > _VMEM_BUDGET:
        return 0
    bm = min(m & -m, cap)                     # largest 2^k dividing m
    while bm > 1 and bm * c * 4 * n_row_bufs > _VMEM_BUDGET:
        bm //= 2
    if bm * c * 4 * n_row_bufs > _VMEM_BUDGET:
        return 0
    return bm


# ---------------------------------------------------------------------------
# fused scale/shift/activation/residual apply over (M, C)
# ---------------------------------------------------------------------------
def _apply_kernel(*refs, act_type, has_scale, has_residual):
    """out = act(x [*scale] + shift [+ residual]) on one (bm, C) tile.
    scale/shift are (1, C) rows broadcast down the tile."""
    import jax
    import jax.numpy as jnp

    it = iter(refs)
    x_ref = next(it)
    scale_ref = next(it) if has_scale else None
    shift_ref = next(it)
    res_ref = next(it) if has_residual else None
    o_ref = next(it)

    u = x_ref[...].astype(jnp.float32)
    if has_scale:
        u = u * scale_ref[...].astype(jnp.float32)
    u = u + shift_ref[...].astype(jnp.float32)
    if has_residual:
        u = u + res_ref[...].astype(jnp.float32)
    o_ref[...] = _act_f32(jax, jnp, u, act_type).astype(o_ref.dtype)


def apply_scale_shift_act(x2d, scale, shift, residual, act_type,
                          interpret=False):
    """Pallas apply pass. x2d/residual: (M, C); scale (optional): (C,);
    shift: (C,). Returns act(x*scale + shift + residual) in x2d.dtype, or
    None when the shape does not tile (caller falls back)."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    m, c = x2d.shape
    n_bufs = 2 + (1 if residual is not None else 0)
    bm = _block_rows(m, c, n_bufs)
    if bm == 0 or m % bm:
        return None
    grid = (m // bm,)
    row_spec = pl.BlockSpec((bm, c), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    in_specs = [row_spec]
    args = [x2d]
    if scale is not None:
        in_specs.append(vec_spec)
        args.append(scale.reshape(1, c))
    in_specs.append(vec_spec)
    args.append(shift.reshape(1, c))
    if residual is not None:
        in_specs.append(row_spec)
        args.append(residual)
    kernel = functools.partial(_apply_kernel, act_type=act_type,
                               has_scale=scale is not None,
                               has_residual=residual is not None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# non-overlapping average pooling, NHWC
# ---------------------------------------------------------------------------
def _pool_fwd_kernel(x_ref, o_ref, *, ph, pw):
    import jax.numpy as jnp
    x = x_ref[0].astype(jnp.float32)          # (bh*ph, W, C)
    hh, w, c = x.shape
    x = x.reshape(hh // ph, ph, w // pw, pw, c)
    o_ref[0] = jnp.mean(x, axis=(1, 3)).astype(o_ref.dtype)


def _pool_bwd_kernel(dy_ref, dx_ref, *, ph, pw):
    """dX tile = upstream tile broadcast over each window / (ph*pw):
    the entire reduce-window gradient becomes an in-VMEM broadcast."""
    import jax.numpy as jnp
    dy = dy_ref[0].astype(jnp.float32)        # (bh, Wo, C)
    bh, wo, c = dy.shape
    g = dy * (1.0 / (ph * pw))
    g = jnp.broadcast_to(g[:, None, :, None, :], (bh, ph, wo, pw, c))
    dx_ref[0] = g.reshape(bh * ph, wo * pw, c).astype(dx_ref.dtype)


def _pool_blocks(n, h, w, c, ph, pw):
    """(grid, bh) row tiling for the pooling kernels, or None."""
    if h % ph or w % pw:
        return None
    ho = h // ph
    # in + out tiles: (bh*ph, W, C) + (bh, W/pw, C) floats
    bm = _block_rows(ho, w * c * ph + (w // pw) * c, 1)
    if bm == 0 or ho % bm:
        return None
    return (n, ho // bm), bm


def avg_pool2d_fwd(x, ph, pw, interpret=False):
    """Forward non-overlapping NHWC average pool, or None (no tiling)."""
    import jax
    import jax.experimental.pallas as pl

    n, h, w, c = x.shape
    blocks = _pool_blocks(n, h, w, c, ph, pw)
    if blocks is None:
        return None
    grid, bh = blocks
    return pl.pallas_call(
        functools.partial(_pool_fwd_kernel, ph=ph, pw=pw),
        grid=grid,
        in_specs=[pl.BlockSpec((1, bh * ph, w, c), lambda b, i: (b, i, 0, 0))],
        out_specs=pl.BlockSpec((1, bh, w // pw, c), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h // ph, w // pw, c), x.dtype),
        interpret=interpret,
    )(x)


# ---------------------------------------------------------------------------
# paged decode attention over the slotted KV slab
# ---------------------------------------------------------------------------
def _paged_attn_kernel(lens_ref, *refs, bt, n_blocks, chunk, scale,
                       quantized):
    """One (lane, token-block) grid step of paged decode attention.

    Grid is (S, nT) with the token dimension minor, so the VMEM scratch
    accumulators (running max `m`, normalizer `l`, weighted sum `acc`)
    persist across a lane's sequential token blocks — classic online
    softmax. `lens_ref` is scalar-prefetched: block `t` only computes
    when `t*bt <= len + chunk - 1` (the index map already clamped its
    HBM fetch to the live prefix)."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    it = iter(refs)
    q_ref = next(it)
    k_ref = next(it)
    v_ref = next(it)
    ks_ref = next(it) if quantized else None
    vs_ref = next(it) if quantized else None
    o_ref = next(it)
    m_ref = next(it)
    l_ref = next(it)
    acc_ref = next(it)

    s = pl.program_id(0)
    t = pl.program_id(1)
    lane_len = lens_ref[s]

    @pl.when(t == 0)
    def _init():
        m_ref[...] = jnp.full_like(m_ref, -1e30)
        l_ref[...] = jnp.zeros_like(l_ref)
        acc_ref[...] = jnp.zeros_like(acc_ref)

    @pl.when(t * bt <= lane_len + chunk - 1)
    def _accumulate():
        qf = q_ref[0].astype(jnp.float32)          # (C, H, D)
        kf = k_ref[0, 0].astype(jnp.float32)       # (bt, H, D)
        vf = v_ref[0, 0].astype(jnp.float32)
        if quantized:
            kf = kf * ks_ref[0, 0].astype(jnp.float32)[:, None, None]
            vf = vf * vs_ref[0, 0].astype(jnp.float32)[:, None, None]
        sco = jnp.einsum("chd,thd->hct", qf, kf) * scale
        # query j (the j-th chunk position) may read KV positions
        # [0, lane_len + j]: the in-chunk causal extension of the
        # engine's `t <= lengths` decode mask
        pos = t * bt + jax.lax.broadcasted_iota(jnp.int32, (chunk, bt), 1)
        qoff = jax.lax.broadcasted_iota(jnp.int32, (chunk, bt), 0)
        valid = pos <= lane_len + qoff
        sco = jnp.where(valid[None], sco, -1e30)
        m_prev = m_ref[...]                        # (H, C)
        m_new = jnp.maximum(m_prev, jnp.max(sco, axis=-1))
        p = jnp.exp(sco - m_new[..., None])
        alpha = jnp.exp(m_prev - m_new)
        l_ref[...] = l_ref[...] * alpha + jnp.sum(p, axis=-1)
        acc_ref[...] = (acc_ref[...] * alpha[..., None]
                        + jnp.einsum("hct,thd->hcd", p, vf))
        m_ref[...] = m_new

    @pl.when(t == n_blocks - 1)
    def _finalize():
        acc = acc_ref[...]
        l = l_ref[...]
        o_ref[0] = (acc / l[..., None]).transpose(1, 0, 2) \
            .astype(o_ref.dtype)


def _paged_blocks(t, c, h, d):
    """Token-block size for paged attention: the largest power-of-two
    divisor of `t` whose k+v(+scale) working set stays inside the VMEM
    budget alongside the per-lane q/out/accumulator buffers, or 0."""
    fixed = (3 * c * h * d + 2 * c * h) * 4     # q, out, acc, m, l
    if fixed + 2 * h * d * 4 > _VMEM_BUDGET:
        return 0
    bt = t & -t                                  # largest 2^k dividing t
    while bt > 1 and fixed + 2 * bt * h * (d + 1) * 4 > _VMEM_BUDGET:
        bt //= 2
    if fixed + 2 * bt * h * (d + 1) * 4 > _VMEM_BUDGET:
        return 0
    return bt


def paged_attention_fwd(q, k_slab, v_slab, lengths, layer,
                        k_scale=None, v_scale=None, interpret=False):
    """Pallas paged decode attention. `q`: (S, C, H, D) — C queries per
    lane at positions `lengths[s] + j` (C == 1 plain decode, C == k+1
    speculative verify). `k_slab`/`v_slab`: the whole KV pool slab
    (rows, layers, T, H, D); lane s reads row s of layer `layer`,
    positions clamped to `[0, lengths[s] + j]`. `k_scale`/`v_scale`:
    per-position f32 dequant scales (rows, layers, T) for int8 slabs.
    Returns (S, C, H, D) in q.dtype, or None when the shape does not
    tile (caller falls back)."""
    import jax
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu
    import jax.numpy as jnp

    s_lanes, c, h, d = q.shape
    t = k_slab.shape[2]
    if k_slab.shape[0] <= s_lanes or k_slab.shape[1] <= layer:
        return None
    quantized = k_scale is not None
    bt = _paged_blocks(t, c, h, d)
    if bt == 0 or t % bt:
        return None
    n_blocks = t // bt
    scale = 1.0 / float(d) ** 0.5

    def qidx(s, tt, lens_ref):
        return (s, 0, 0, 0)

    def kidx(s, tt, lens_ref):
        # clamp the fetched block to the lane's live prefix: out-of-range
        # grid steps re-name the last live block (copy elided) and their
        # compute is skipped in the kernel body
        need = (lens_ref[s] + c - 1) // bt
        return (s, layer, jnp.minimum(tt, need), 0, 0)

    def sidx(s, tt, lens_ref):
        need = (lens_ref[s] + c - 1) // bt
        return (s, layer, jnp.minimum(tt, need))

    in_specs = [
        pl.BlockSpec((1, c, h, d), qidx),
        pl.BlockSpec((1, 1, bt, h, d), kidx),
        pl.BlockSpec((1, 1, bt, h, d), kidx),
    ]
    args = [lengths, q, k_slab, v_slab]
    if quantized:
        in_specs.append(pl.BlockSpec((1, 1, bt), sidx))
        in_specs.append(pl.BlockSpec((1, 1, bt), sidx))
        args.extend([k_scale, v_scale])
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(s_lanes, n_blocks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, c, h, d), qidx),
        scratch_shapes=[
            pltpu.VMEM((h, c), jnp.float32),
            pltpu.VMEM((h, c), jnp.float32),
            pltpu.VMEM((h, c, d), jnp.float32),
        ],
    )
    kernel = functools.partial(
        _paged_attn_kernel, bt=bt, n_blocks=n_blocks,
        chunk=c, scale=scale, quantized=quantized)
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((s_lanes, c, h, d), q.dtype),
        interpret=interpret,
    )(*args)


def avg_pool2d_bwd(dy, h, w, ph, pw, interpret=False):
    """VMEM-tiled backward of the non-overlapping NHWC average pool:
    dX (N, h, w, C) from dY (N, h/ph, w/pw, C), or None (no tiling)."""
    import jax
    import jax.experimental.pallas as pl

    n, ho, wo, c = dy.shape
    blocks = _pool_blocks(n, h, w, c, ph, pw)
    if blocks is None:
        return None
    grid, bh = blocks
    return pl.pallas_call(
        functools.partial(_pool_bwd_kernel, ph=ph, pw=pw),
        grid=grid,
        in_specs=[pl.BlockSpec((1, bh, wo, c), lambda b, i: (b, i, 0, 0))],
        out_specs=pl.BlockSpec((1, bh * ph, w, c), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, c), dy.dtype),
        interpret=interpret,
    )(dy)
