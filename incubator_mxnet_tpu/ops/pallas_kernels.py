"""Pallas TPU kernels for the fused-op tier (ops/fused.py).

Reference contrast: MXNet's `USE_FUSION` RTC machinery generated pointwise
CUDA kernels at runtime (src/operator/fusion/fused_op.cu); here the worst
memory-bound offender classes the `mx.inspect` roofline attribution ranks
(benchmark/results/offenders_resnet18_r09.json) get hand-written TPU
kernels instead:

  * `apply_scale_shift_act` — ONE pass of `act(x*scale + shift [+ res])`
    over a (rows, channels) view: the normalize-scale-shift(-residual-relu)
    chains XLA splits into several 0.26-intensity `multiply_multiply`
    fusions become a single VMEM-resident sweep (one read of x/residual,
    one write of out — the roofline floor for this op class).
  * `avg_pool2d_fwd` / `avg_pool2d_bwd` — non-overlapping average pooling
    (kernel == stride, no padding; the GlobalAvgPool shape included) with a
    VMEM-tiled backward: the gradient is an in-register broadcast of the
    upstream tile instead of XLA's generic reduce-window gradient scatter
    (the 0.18-intensity `reduce-window` offender class).

Everything here takes and returns raw jax arrays and is shape-strict: the
caller (ops/fused.py) owns fallback policy, custom_vjp wiring and layout
handling. Kernels compute in float32 internally and cast to the input
dtype on the way out, matching ops/nn.py norm semantics under AMP.

Layout: channels-minor (the TPU-preferred NHWC family) — `x` is reshaped
by the caller to (M, C) for the apply kernel and kept (N, H, W, C) for
pooling. Tile sizes come from a VMEM budget (see `_block_rows`).
"""
from __future__ import annotations

import functools

__all__ = ["apply_scale_shift_act", "avg_pool2d_fwd", "avg_pool2d_bwd",
           "supported_act", "ACTS"]

# activation set the kernels (and their hand-derived VJPs) support; None
# means identity. Kept in sync with ops/fused.py's dispatch tables.
ACTS = (None, "relu", "sigmoid", "tanh", "silu", "gelu")

_VMEM_BUDGET = 4 * 1024 * 1024   # bytes of f32 working set per program


def supported_act(act_type):
    return act_type in ACTS


def _act_f32(jax, jnp, u, act_type):
    if act_type is None:
        return u
    if act_type == "relu":
        return jax.nn.relu(u)
    if act_type == "sigmoid":
        return jax.nn.sigmoid(u)
    if act_type == "tanh":
        return jnp.tanh(u)
    if act_type == "silu":
        return jax.nn.silu(u)
    if act_type == "gelu":
        return jax.nn.gelu(u, approximate=False)
    raise ValueError(f"unsupported fused activation {act_type!r}")


def _block_rows(m, c, n_row_bufs, cap=1024):
    """Largest power-of-two row tile that divides `m` and keeps
    `n_row_bufs` (M, C)-shaped f32 buffers inside the VMEM budget.
    Returns 0 when even a single row of C floats cannot fit."""
    if c * 4 * n_row_bufs > _VMEM_BUDGET:
        return 0
    bm = min(m & -m, cap)                     # largest 2^k dividing m
    while bm > 1 and bm * c * 4 * n_row_bufs > _VMEM_BUDGET:
        bm //= 2
    if bm * c * 4 * n_row_bufs > _VMEM_BUDGET:
        return 0
    return bm


# ---------------------------------------------------------------------------
# fused scale/shift/activation/residual apply over (M, C)
# ---------------------------------------------------------------------------
def _apply_kernel(*refs, act_type, has_scale, has_residual):
    """out = act(x [*scale] + shift [+ residual]) on one (bm, C) tile.
    scale/shift are (1, C) rows broadcast down the tile."""
    import jax
    import jax.numpy as jnp

    it = iter(refs)
    x_ref = next(it)
    scale_ref = next(it) if has_scale else None
    shift_ref = next(it)
    res_ref = next(it) if has_residual else None
    o_ref = next(it)

    u = x_ref[...].astype(jnp.float32)
    if has_scale:
        u = u * scale_ref[...].astype(jnp.float32)
    u = u + shift_ref[...].astype(jnp.float32)
    if has_residual:
        u = u + res_ref[...].astype(jnp.float32)
    o_ref[...] = _act_f32(jax, jnp, u, act_type).astype(o_ref.dtype)


def apply_scale_shift_act(x2d, scale, shift, residual, act_type,
                          interpret=False):
    """Pallas apply pass. x2d/residual: (M, C); scale (optional): (C,);
    shift: (C,). Returns act(x*scale + shift + residual) in x2d.dtype, or
    None when the shape does not tile (caller falls back)."""
    import jax
    import jax.numpy as jnp
    import jax.experimental.pallas as pl

    m, c = x2d.shape
    n_bufs = 2 + (1 if residual is not None else 0)
    bm = _block_rows(m, c, n_bufs)
    if bm == 0 or m % bm:
        return None
    grid = (m // bm,)
    row_spec = pl.BlockSpec((bm, c), lambda i: (i, 0))
    vec_spec = pl.BlockSpec((1, c), lambda i: (0, 0))
    in_specs = [row_spec]
    args = [x2d]
    if scale is not None:
        in_specs.append(vec_spec)
        args.append(scale.reshape(1, c))
    in_specs.append(vec_spec)
    args.append(shift.reshape(1, c))
    if residual is not None:
        in_specs.append(row_spec)
        args.append(residual)
    kernel = functools.partial(_apply_kernel, act_type=act_type,
                               has_scale=scale is not None,
                               has_residual=residual is not None)
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=in_specs,
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((m, c), x2d.dtype),
        interpret=interpret,
    )(*args)


# ---------------------------------------------------------------------------
# non-overlapping average pooling, NHWC
# ---------------------------------------------------------------------------
def _pool_fwd_kernel(x_ref, o_ref, *, ph, pw):
    import jax.numpy as jnp
    x = x_ref[0].astype(jnp.float32)          # (bh*ph, W, C)
    hh, w, c = x.shape
    x = x.reshape(hh // ph, ph, w // pw, pw, c)
    o_ref[0] = jnp.mean(x, axis=(1, 3)).astype(o_ref.dtype)


def _pool_bwd_kernel(dy_ref, dx_ref, *, ph, pw):
    """dX tile = upstream tile broadcast over each window / (ph*pw):
    the entire reduce-window gradient becomes an in-VMEM broadcast."""
    import jax.numpy as jnp
    dy = dy_ref[0].astype(jnp.float32)        # (bh, Wo, C)
    bh, wo, c = dy.shape
    g = dy * (1.0 / (ph * pw))
    g = jnp.broadcast_to(g[:, None, :, None, :], (bh, ph, wo, pw, c))
    dx_ref[0] = g.reshape(bh * ph, wo * pw, c).astype(dx_ref.dtype)


def _pool_blocks(n, h, w, c, ph, pw):
    """(grid, bh) row tiling for the pooling kernels, or None."""
    if h % ph or w % pw:
        return None
    ho = h // ph
    # in + out tiles: (bh*ph, W, C) + (bh, W/pw, C) floats
    bm = _block_rows(ho, w * c * ph + (w // pw) * c, 1)
    if bm == 0 or ho % bm:
        return None
    return (n, ho // bm), bm


def avg_pool2d_fwd(x, ph, pw, interpret=False):
    """Forward non-overlapping NHWC average pool, or None (no tiling)."""
    import jax
    import jax.experimental.pallas as pl

    n, h, w, c = x.shape
    blocks = _pool_blocks(n, h, w, c, ph, pw)
    if blocks is None:
        return None
    grid, bh = blocks
    return pl.pallas_call(
        functools.partial(_pool_fwd_kernel, ph=ph, pw=pw),
        grid=grid,
        in_specs=[pl.BlockSpec((1, bh * ph, w, c), lambda b, i: (b, i, 0, 0))],
        out_specs=pl.BlockSpec((1, bh, w // pw, c), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h // ph, w // pw, c), x.dtype),
        interpret=interpret,
    )(x)


def avg_pool2d_bwd(dy, h, w, ph, pw, interpret=False):
    """VMEM-tiled backward of the non-overlapping NHWC average pool:
    dX (N, h, w, C) from dY (N, h/ph, w/pw, C), or None (no tiling)."""
    import jax
    import jax.experimental.pallas as pl

    n, ho, wo, c = dy.shape
    blocks = _pool_blocks(n, h, w, c, ph, pw)
    if blocks is None:
        return None
    grid, bh = blocks
    return pl.pallas_call(
        functools.partial(_pool_bwd_kernel, ph=ph, pw=pw),
        grid=grid,
        in_specs=[pl.BlockSpec((1, bh, wo, c), lambda b, i: (b, i, 0, 0))],
        out_specs=pl.BlockSpec((1, bh * ph, w, c), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((n, h, w, c), dy.dtype),
        interpret=interpret,
    )(dy)
