"""Optimized-HLO text parser for `mx.inspect` (fusion-level attribution).

The compiled module's post-optimization HLO (`jax.stages.Compiled.as_text()`)
is the only backend-portable view of what the chip will actually run: XLA's
fusion passes have already grouped the program into the units that map 1:1
onto kernel launches, so *fusion-level* attribution is the XLA-era analogue
of the reference profiler's per-engine-op attribution (PAPER.md layers 4-6:
`USE_FUSION`, AMP passes decide these boundaries). This parser extracts just
enough structure for the roofline model in `roofline.py`:

  * computations (ENTRY + %fused_computation.* + call wrappers + scan
    bodies), each a list of instructions;
  * per instruction: name, opcode, result shape(s) with dtype, operand
    names + shapes, and the attributes that carry cost information
    (`calls=` for fusions, `to_apply=` for reduce/call, contracting/batch
    dims for dot, `dim_labels` + kernel shape for convolution,
    `metadata.op_name` for attribution back to model code).

No jax import: parsing is plain text so the report/CLI layers stay usable
on artifacts (`--hlo-file dump.txt`) without an accelerator attached.
"""
from __future__ import annotations

import re

__all__ = ["HloInstruction", "HloComputation", "HloModule", "parse_module",
           "parse_shape", "shape_bytes", "DTYPE_BYTES"]

# element width in bytes per HLO primitive type (pred is byte-addressed)
DTYPE_BYTES = {
    "pred": 1, "s2": 1, "s4": 1, "s8": 1, "u2": 1, "u4": 1, "u8": 1,
    "s16": 2, "u16": 2, "s32": 4, "u32": 4, "s64": 8, "u64": 8,
    "f8e4m3fn": 1, "f8e5m2": 1, "f8e4m3b11fnuz": 1, "f8e4m3fnuz": 1,
    "f8e5m2fnuz": 1, "bf16": 2, "f16": 2, "f32": 4, "f64": 8,
    "c64": 8, "c128": 16, "token": 0, "opaque": 0,
}

# `f32[128,512]{1,0}` / `bf16[]` / `pred[4]{0:T(256)}` (layout tail ignored)
_SHAPE_RE = re.compile(
    r"([a-z][a-z0-9]*)\[([0-9,\s]*)\](?:\{[^}]*\})?")
# one instruction: `[ROOT ]%name = <shape> opcode(<operands>)<attrs>`
_INSTR_RE = re.compile(
    r"^\s*(?:ROOT\s+)?%?([\w.\-]+)\s*=\s*(\(?.*?\)?)\s+"
    r"([\w\-]+)\((.*)$")
_COMP_RE = re.compile(r"^\s*(ENTRY\s+)?%?([\w.\-]+)\s*\(.*\)\s*->\s*.*\{")
_METADATA_OP_RE = re.compile(r'op_name="([^"]*)"')
_CALLS_RE = re.compile(r"(?:calls|to_apply|body|condition)=%?([\w.\-]+)")
_DIM_LABELS_RE = re.compile(r"dim_labels=([\w?]+_[\w?]+->[\w?]+)")
_DIMS_RE = re.compile(r"(\w+_dims)=\{([0-9,\s]*)\}")
_FEATURE_GROUPS_RE = re.compile(r"feature_group_count=(\d+)")
_OPERAND_NAME_RE = re.compile(r"%([\w.\-]+)")
# sigil-less dumps (newer XLA ToString forms drop the '%'): the operand
# name is the trailing identifier after the optional shape text
_BARE_OPERAND_RE = re.compile(r"([A-Za-z_][\w.\-]*)\s*$")


def parse_shape(text):
    """`f32[4,8,8,16]{3,2,1,0}` -> ("f32", (4, 8, 8, 16)). Tuple shapes
    return a list of leaves. Returns None for unparseable text."""
    text = text.strip()
    if text.startswith("("):
        leaves = []
        for m in _SHAPE_RE.finditer(text):
            leaves.append(_leaf(m))
        return leaves or None
    m = _SHAPE_RE.match(text)
    return _leaf(m) if m else None


def _leaf(m):
    dims = tuple(int(d) for d in m.group(2).replace(" ", "").split(",")
                 if d != "")
    return (m.group(1), dims)


def _leaf_bytes(leaf):
    dtype, dims = leaf
    n = 1
    for d in dims:
        n *= d
    return n * DTYPE_BYTES.get(dtype, 4)


def shape_bytes(shape):
    """Total buffer bytes of a parsed shape (tuple shapes sum leaves)."""
    if shape is None:
        return 0
    if isinstance(shape, list):
        return sum(_leaf_bytes(leaf) for leaf in shape)
    return _leaf_bytes(shape)


def num_elements(shape):
    """Element count of a parsed shape (tuples sum leaves; scalars = 1)."""
    if shape is None:
        return 0
    if isinstance(shape, list):
        return sum(num_elements(leaf) for leaf in shape)
    n = 1
    for d in shape[1]:
        n *= d
    return n


class HloInstruction:
    """One parsed HLO instruction (a line of a computation body)."""

    __slots__ = ("name", "opcode", "shape", "operands", "operand_shapes",
                 "called", "op_name", "attrs_text", "is_root")

    def __init__(self, name, opcode, shape, operands, operand_shapes,
                 called, op_name, attrs_text, is_root):
        self.name = name
        self.opcode = opcode
        self.shape = shape                  # parsed result shape
        self.operands = operands            # operand instruction names
        self.operand_shapes = operand_shapes
        self.called = called                # computations this instr calls
        self.op_name = op_name              # metadata op_name (jax source)
        self.attrs_text = attrs_text        # raw attr tail for dims parsing
        self.is_root = is_root

    @property
    def out_bytes(self):
        return shape_bytes(self.shape)

    @property
    def out_elements(self):
        return num_elements(self.shape)

    def dims_attr(self, key):
        """`lhs_contracting_dims` -> (1,) parsed from the attr tail."""
        for m in _DIMS_RE.finditer(self.attrs_text):
            if m.group(1) == key:
                return tuple(int(d) for d in
                             m.group(2).replace(" ", "").split(",")
                             if d != "")
        return ()

    @property
    def dim_labels(self):
        m = _DIM_LABELS_RE.search(self.attrs_text)
        return m.group(1) if m else None

    @property
    def feature_group_count(self):
        m = _FEATURE_GROUPS_RE.search(self.attrs_text)
        return int(m.group(1)) if m else 1

    def __repr__(self):
        return (f"HloInstruction({self.name}: {self.opcode} -> "
                f"{self.shape})")


class HloComputation:
    __slots__ = ("name", "instructions", "is_entry")

    def __init__(self, name, is_entry=False):
        self.name = name
        self.is_entry = is_entry
        self.instructions = []

    @property
    def root(self):
        for ins in self.instructions:
            if ins.is_root:
                return ins
        return self.instructions[-1] if self.instructions else None

    def __repr__(self):
        return (f"HloComputation({self.name}, "
                f"{len(self.instructions)} instrs)")


class HloModule:
    __slots__ = ("name", "computations", "entry_name")

    def __init__(self, name):
        self.name = name
        self.computations = {}
        self.entry_name = None

    @property
    def entry(self):
        if self.entry_name:
            return self.computations.get(self.entry_name)
        return None

    def computation(self, name):
        return self.computations.get(name)

    def __repr__(self):
        return (f"HloModule({self.name}, "
                f"{len(self.computations)} computations)")


def _split_operands(body):
    """Split the operand list at the instruction's top-level closing paren,
    returning (operand_text, attr_tail). Handles nested parens/braces in
    shapes and constants."""
    depth = 1
    for i, ch in enumerate(body):
        if ch == "(":
            depth += 1
        elif ch == ")":
            depth -= 1
            if depth == 0:
                return body[:i], body[i + 1:]
    return body, ""


def parse_module(text):
    """Parse optimized HLO text (`Compiled.as_text()`) into an HloModule."""
    header = text.splitlines()[0] if text else ""
    mname = "module"
    hm = re.match(r"HloModule\s+([\w.\-]+)", header)
    if hm:
        mname = hm.group(1)
    module = HloModule(mname)
    current = None
    for raw in text.splitlines():
        line = raw.rstrip()
        stripped = line.strip()
        if not stripped or stripped.startswith("//"):
            continue
        if stripped.startswith("HloModule"):
            continue
        if stripped == "}":
            current = None
            continue
        cm = _COMP_RE.match(stripped)
        if cm and stripped.endswith("{") and "=" not in stripped.split(
                "->")[0]:
            comp = HloComputation(cm.group(2), is_entry=bool(cm.group(1)))
            module.computations[comp.name] = comp
            if comp.is_entry:
                module.entry_name = comp.name
            current = comp
            continue
        if current is None:
            continue
        im = _INSTR_RE.match(stripped)
        if not im:
            continue
        name, shape_text, opcode, body = im.groups()
        operand_text, attr_tail = _split_operands(body)
        shape = parse_shape(shape_text)
        operands, opshapes = [], []
        # operand entries look like `f32[4,8]{1,0} %name` or `%name`;
        # constants may inline literals — those carry no %name and are
        # skipped (their bytes are trace constants, not HBM traffic)
        for part in _split_top_level(operand_text):
            nm = _OPERAND_NAME_RE.search(part) or \
                _BARE_OPERAND_RE.search(part)
            if not nm:
                continue
            operands.append(nm.group(1))
            opshapes.append(parse_shape(part))
        called = [c for c in _CALLS_RE.findall(attr_tail)]
        opm = _METADATA_OP_RE.search(attr_tail)
        current.instructions.append(HloInstruction(
            name, opcode, shape, operands, opshapes, called,
            opm.group(1) if opm else None, attr_tail,
            stripped.startswith("ROOT")))
    return module


def _split_top_level(text):
    """Split an operand list on top-level commas (shapes contain commas
    inside brackets/braces)."""
    parts, depth, start = [], 0, 0
    for i, ch in enumerate(text):
        if ch in "([{":
            depth += 1
        elif ch in ")]}":
            depth -= 1
        elif ch == "," and depth == 0:
            parts.append(text[start:i])
            start = i + 1
    tail = text[start:]
    if tail.strip():
        parts.append(tail)
    return parts
