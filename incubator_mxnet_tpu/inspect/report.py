"""Offender attribution reports: rank a compiled step's fusions for humans
and for the bench trend.

`inspect_step(obj, *args)` lowers+compiles whatever it is handed — a
`gluon.contrib.FusedTrainStep`, a `deploy.ExportedModel` bucket program, a
bare `jax.jit` function, or an already lowered/compiled stage — walks the
optimized HLO through `roofline.analyze_compiled`, and produces the ranked
work-list the Pallas-kernel tier consumes ("worst offenders") at two
granularities:

  offenders        individual kernel units (fusions/dots/convs), ranked by
                   estimated time share — "which launch is slow";
  offender_groups  fusion CLASSES: units aggregated under their
                   de-instanced HLO name (`multiply_multiply_fusion.18
                   .clone` -> `multiply_multiply_fusion` — XLA names a
                   fusion after its constituent ops, so same pattern
                   across 20 ResNet layers = one class). A custom kernel
                   replaces a *class*, so this is the actionable ranking
                   and the one the coverage/trend numbers gate.

Trend scalars (bench.py `offenders` phase, benchdiff TREND_KEYS):

  offender_top1_share       est. time share of the worst fusion class
  memory_bound_byte_share   fraction of step bytes in memory-bound units
  est_step_mfu_ceiling      total flops / (sum of roofline unit times x
                            peak flops) — the MFU the CURRENT fusion
                            structure could reach if every unit hit its
                            roofline bound; the honest target for kernel
                            work, diffable round over round

Measured mode (`MXNET_INSPECT_MEASURED=1` + an `execute=` callback):
attempts a `jax.profiler` device trace around real executions. When the
backend/toolchain cannot produce a readable device trace (CPU containers),
the report keeps the cost-model estimate and says so — `measured: false`
with the reason — rather than inventing numbers; wall-clock timing of the
executions is reported either way (`measured_wall_ms`).
"""
from __future__ import annotations

import json
import os
import re

from ..base import MXNetError, get_env, _register_env
from ..telemetry import REGISTRY, span
from . import roofline as _roofline

__all__ = ["inspect_step", "inspect_compiled", "render_markdown",
           "lower_any", "class_name", "INSPECT_RUNS", "INSPECT_UNITS"]

_register_env("MXNET_INSPECT_TOP_K", int, 10,
              "Offender-report depth: fusions listed by tools/offenders.py "
              "and the bench offenders phase (totals always cover the "
              "whole module)")
_register_env("MXNET_INSPECT_MEASURED", bool, False,
              "1 = inspect_step attempts a jax.profiler device trace "
              "around real executions; falls back to the cost-model "
              "estimate (measured: false) when the backend cannot trace")
_register_env("MXNET_INSPECT_CALIB", str, None,
              "Path to a roofline calibration JSON overriding "
              "benchmark/results/roofline_calib.json "
              "(see tools/bandwidth.py --calib)")

# inspection runs land in the registry so dashboards see profiling activity
INSPECT_RUNS = REGISTRY.counter(
    "inspect.runs", help="offender-attribution analyses performed")
INSPECT_UNITS = REGISTRY.counter(
    "inspect.units", help="kernel units (fusions/dots/convs) analyzed")
_TOP1 = REGISTRY.gauge(
    "inspect.top1_share", help="est. time share of the worst fusion in "
    "the most recent inspection")
_MEM_BYTES = REGISTRY.gauge(
    "inspect.memory_bound_byte_share", help="byte share in memory-bound "
    "units in the most recent inspection")
_MFU_CEIL = REGISTRY.gauge(
    "inspect.mfu_ceiling", help="roofline MFU ceiling of the most recent "
    "inspected program")


def lower_any(obj, *args):
    """Lower+compile any inspectable object to a `jax.stages.Compiled`.

    Accepts: FusedTrainStep / FusedInferStep (via `.lowered(*args)`),
    deploy.ExportedModel (via `.lowered()`), jitted functions and
    `jax.stages.Lowered` (via `.lower(...)`/`.compile()`), and
    already-compiled stages (pass-through)."""
    if hasattr(obj, "lowered"):                      # our framework objects
        lowered = obj.lowered(*args)
        return lowered.compile()
    # order matters below: jax.stages.Lowered also exposes as_text() +
    # cost_analysis(), but its text is pre-optimization StableHLO the
    # parser cannot use — anything still compilable must compile first
    if hasattr(obj, "compile") and not hasattr(obj, "lower"):
        return obj.compile()                         # jax.stages.Lowered
    if hasattr(obj, "lower"):                        # jitted callable
        return obj.lower(*args).compile()
    if hasattr(obj, "as_text") and hasattr(obj, "cost_analysis"):
        return obj                                   # already Compiled
    if callable(obj):
        import jax
        return jax.jit(obj).lower(*args).compile()
    raise MXNetError(
        f"don't know how to lower {type(obj).__name__} for inspection: "
        "pass a FusedTrainStep, ExportedModel, jitted function, or a "
        "lowered/compiled stage")


def inspect_step(obj, *args, name=None, top_k=None, calib=None,
                 measured=None, execute=None):
    """Offender report for one compiled step. See module docstring.

    `execute`: zero-arg callable running the program once on real buffers;
    enables measured mode and `measured_wall_ms`."""
    compiled = lower_any(obj, *args)
    return inspect_compiled(compiled, name=name or _name_of(obj),
                            top_k=top_k, calib=calib, measured=measured,
                            execute=execute)


def _name_of(obj):
    n = type(obj).__name__
    return getattr(obj, "__name__", n)


def inspect_compiled(compiled, name="step", top_k=None, calib=None,
                     measured=None, execute=None):
    """Report dict for an already compiled stage (json.dumps-safe)."""
    if top_k is None:
        top_k = get_env("MXNET_INSPECT_TOP_K", 10, typ=int)
    if measured is None:
        measured = get_env("MXNET_INSPECT_MEASURED", False, typ=bool)
    if calib is None:
        calib = _roofline.load_calibration()
    with span("inspect.analyze", target=name):
        records, totals, _module = _roofline.analyze_compiled(
            compiled, calib=calib)
        ca = _roofline.cost_analysis_summary(compiled)
        # the memory side of the same program, right next to the roofline
        # ranking: predicted peak HBM + argument/output/temp/alias split
        # (inspect/memory.py; degrades per its own contract, never raises)
        from . import memory as _memory
        memplan = _memory.plan_from_compiled(compiled, name=name)
    # degradation contract: no byte estimates anywhere (shape parse failed
    # AND cost analysis silent) -> flops-only ranking, flagged, no crash
    have_bytes = totals["bytes"] > 0 or ca["bytes_estimated"]
    if not have_bytes:
        records.sort(key=lambda r: r["flops"], reverse=True)
    groups = _group_records(records, have_bytes,
                            calib["ridge_flop_per_byte"])
    report = {
        "name": name,
        "platform": _platform(),
        "n_units": totals["units"],
        "top_k": top_k,
        "ranking": "est_time" if have_bytes else "flops_only",
        "bytes_estimated": have_bytes,
        "calibration": {
            "peak_flops": calib["peak_flops"],
            "peak_bytes_per_sec": calib["peak_bytes_per_sec"],
            "ridge_flop_per_byte": calib["ridge_flop_per_byte"],
            "source": calib.get("source", "unknown"),
        },
        "totals": totals,
        "cost_analysis": ca,
        "memory": memplan,
        "offenders": records[:top_k],
        "n_groups": len(groups),
        "offender_groups": groups[:top_k],
        "offender_top1_share": (groups[0]["time_share"]
                                if groups else 0.0),
        "memory_bound_byte_share": totals["memory_bound_byte_share"],
        "est_step_mfu_ceiling": _mfu_ceiling(totals, calib),
        "top10_byte_coverage": _byte_coverage(groups, 10, totals),
        "topk_byte_coverage": _byte_coverage(groups, top_k, totals),
        "topk_time_coverage": round(
            sum(g["time_share"] for g in groups[:top_k]), 6),
        "measured": False,
    }
    if ca["flops"] is not None and totals["flops"] > 0:
        report["model_vs_xla_flops"] = round(
            totals["flops"] / ca["flops"], 4) if ca["flops"] else None
    if execute is not None:
        report.update(_measure(execute, measured))
    elif measured:
        report["measured_unavailable_reason"] = (
            "measured mode needs an execute= callback with real buffers")
    INSPECT_RUNS.inc()
    INSPECT_UNITS.inc(totals["units"])
    _TOP1.set(report["offender_top1_share"])
    _MEM_BYTES.set(report["memory_bound_byte_share"])
    _MFU_CEIL.set(report["est_step_mfu_ceiling"])
    return report


_INSTANCE_RE = re.compile(r"\.(clone|remat|\d+)")


def class_name(instr_name):
    """De-instanced fusion-class name: XLA names a fusion after its
    constituent ops and suffixes instances with `.N`/`.clone`/`.remat`,
    so stripping those folds the same pattern across layers into one
    class (`multiply_multiply_fusion.18.clone` ->
    `multiply_multiply_fusion`)."""
    return _INSTANCE_RE.sub("", instr_name)


def _group_records(records, have_bytes, ridge):
    """Aggregate unit records into ranked fusion-class groups."""
    groups = {}
    for r in records:
        cls = class_name(r["name"])
        g = groups.get(cls)
        if g is None:
            g = groups[cls] = {
                "class": cls, "opcode": r["opcode"], "count": 0,
                "flops": 0.0, "bytes": 0.0, "transcendentals": 0.0,
                "est_time_s": 0.0, "example": r["name"],
                "example_op_name": r["op_name"],
            }
        g["count"] += 1
        g["flops"] += r["flops"]
        g["bytes"] += r["bytes"]
        g["transcendentals"] += r["transcendentals"]
        g["est_time_s"] += r["est_time_s"]
    out = list(groups.values())
    total_time = sum(g["est_time_s"] for g in out) or 1.0
    for g in out:
        intensity = (g["flops"] / g["bytes"]) if g["bytes"] \
            else float("inf")
        g["intensity"] = (round(intensity, 4)
                          if intensity != float("inf") else None)
        g["bound"] = "compute" if intensity >= ridge else "memory"
        g["time_share"] = round(g["est_time_s"] / total_time, 6)
    out.sort(key=lambda g: (g["est_time_s"] if have_bytes
                            else g["flops"]), reverse=True)
    return out


def _platform():
    return _roofline._ambient_platform(default="unknown")


def _mfu_ceiling(totals, calib):
    """MFU if every unit ran exactly at its roofline bound: the ceiling
    the CURRENT fusion structure imposes. 0 when the module has no
    modelled flops (degenerate/opaque programs)."""
    t = totals["est_time_s"]
    if not t or not totals["flops"]:
        return 0.0
    return round(totals["flops"] / t / float(calib["peak_flops"]), 6)


def _byte_coverage(records, k, totals):
    if not totals["bytes"]:
        return 0.0
    return round(sum(r["bytes"] for r in records[:k]) / totals["bytes"], 6)


def _measure(execute, measured, reps=3):
    """Wall-clock the executions always; attempt a device trace when
    measured mode is on. A backend that cannot produce a readable trace
    (CPU containers without the profiler toolchain) degrades to the
    cost-model numbers with `measured: false` + the reason."""
    import time as _time
    out = {}
    execute()                                   # warm (compile outside clock)
    t0 = _time.perf_counter()
    for _ in range(reps):
        execute()
    out["measured_wall_ms"] = round(
        (_time.perf_counter() - t0) / reps * 1e3, 3)
    if not measured:
        return out
    import glob
    import tempfile
    try:
        import jax
        with tempfile.TemporaryDirectory() as d:
            with jax.profiler.trace(d):
                execute()
            planes = glob.glob(
                os.path.join(d, "**", "*.xplane.pb"), recursive=True)
            if not planes:
                raise RuntimeError("profiler produced no device trace")
            # device-plane attribution needs the xplane toolchain; absent
            # (no tensorflow/xprof in this runtime) the honest answer is
            # the estimate, flagged unmeasured — never fabricated timings
            out["measured"] = False
            out["measured_trace_files"] = len(planes)
            out["measured_unavailable_reason"] = (
                "device trace captured but no xplane parser available in "
                "this runtime; per-fusion shares remain cost-model "
                "estimates")
    except Exception as e:
        out["measured"] = False
        out["measured_unavailable_reason"] = (
            f"device trace unavailable on this backend: "
            f"{type(e).__name__}: {e}")
    return out


def render_markdown(report):
    """Human-readable offender table (what `tools/offenders.py` prints)."""
    lines = []
    cal = report["calibration"]
    lines.append(f"# Offender attribution — {report['name']} "
                 f"({report['platform']})")
    lines.append("")
    lines.append(
        f"Roofline: peak {cal['peak_flops'] / 1e12:.1f} TFLOP/s, "
        f"{cal['peak_bytes_per_sec'] / 1e9:.1f} GB/s "
        f"(ridge {cal['ridge_flop_per_byte']:.1f} FLOP/B, "
        f"calibration: {cal['source']})")
    t = report["totals"]
    lines.append(
        f"Program: {t['units']} kernel units, "
        f"{t['flops'] / 1e9:.2f} GFLOP, {t['bytes'] / 1e6:.2f} MB moved, "
        f"{t['memory_bound_units']} memory-bound units "
        f"({report['memory_bound_byte_share'] * 100:.1f}% of bytes)")
    lines.append(
        f"MFU ceiling for this fusion structure: "
        f"{report['est_step_mfu_ceiling']:.3f}  |  top-1 class share: "
        f"{report['offender_top1_share'] * 100:.1f}%  |  measured: "
        f"{report['measured']}")
    lines.append("")
    lines.append(f"## Offender classes ({report['n_groups']} total)")
    lines.append("")
    lines.append("| # | fusion class | op | n | bound | GFLOP | MB | "
                 "FLOP/B | time share |")
    lines.append("|---|---|---|---|---|---|---|---|---|")
    for i, g in enumerate(report["offender_groups"], 1):
        inten = ("inf" if g["intensity"] is None
                 else f"{g['intensity']:.1f}")
        lines.append(
            f"| {i} | `{g['class']}` | {g['opcode']} | {g['count']} | "
            f"{g['bound']} | {g['flops'] / 1e9:.3f} | "
            f"{g['bytes'] / 1e6:.3f} | {inten} | "
            f"{g['time_share'] * 100:.1f}% |")
    lines.append("")
    lines.append(
        f"Top-{report['top_k']} classes cover "
        f"{report['topk_time_coverage'] * 100:.1f}% of estimated time, "
        f"{report['topk_byte_coverage'] * 100:.1f}% of bytes "
        f"(top-10: {report['top10_byte_coverage'] * 100:.1f}%).")
    lines.append("")
    lines.append("## Worst individual kernel units")
    lines.append("")
    lines.append("| # | unit | op | bound | GFLOP | MB | FLOP/B | "
                 "time share | source op |")
    lines.append("|---|---|---|---|---|---|---|---|---|")
    for i, r in enumerate(report["offenders"], 1):
        inten = ("inf" if r["intensity"] is None
                 else f"{r['intensity']:.1f}")
        src = (r["op_name"] or "")[-48:]
        lines.append(
            f"| {i} | `{r['name']}` | {r['opcode']} | {r['bound']} | "
            f"{r['flops'] / 1e9:.3f} | {r['bytes'] / 1e6:.3f} | {inten} | "
            f"{r['time_share'] * 100:.1f}% | `{src}` |")
    return "\n".join(lines)


def inspect_hlo_text(text, name="module", top_k=None, calib=None):
    """Offline path: analyze a saved HLO dump (no jax, no backend)."""
    class _Precompiled:
        def as_text(self):
            return text

        def cost_analysis(self):
            raise RuntimeError("offline HLO text carries no cost analysis")

    return inspect_compiled(_Precompiled(), name=name, top_k=top_k,
                            calib=calib)


def dump_json(report, path):
    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(report, f, indent=1, sort_keys=True)
    os.replace(tmp, path)
