"""mx.inspect.memory — device-memory observability.

The time side of the observability story is rich (StepTimeline, the HLO
roofline in `inspect.roofline`, request tracing, the flight recorder); the
MEMORY side was an opaque `RESOURCE_EXHAUSTED` with no record of which
subsystem owned the bytes. The reference answered this with its storage
profiler and pooled `StorageManager` accounting (`MXNET_PROFILER_MODE`
memory lanes — PAPER.md layers 2 and 8); the XLA-era equivalent here is
four connected pieces:

  * **Memory plans** — `memory_plan(obj, *args)` extracts the compiled
    program's buffer-assignment totals (`argument_size` / `output_size` /
    `temp_size` / `alias_size` / `generated_code_size`, via
    `Compiled.memory_analysis()`) from every surface that already exposes
    `.lowered()` — `FusedTrainStep` / `FusedInferStep`,
    `deploy.ExportedModel` bucket programs, the continuous engine's
    prefill + decode programs (`ContinuousEngine.memory_plans()`), and
    the elastic bucketed collectives (`collective_memory_plans()`).
    `peak_bytes = argument + output + temp - alias` is the predicted peak
    HBM of one execution. Degradation contract (the PR-7 rule): a jax/
    backend without `memory_analysis()` falls back to an HLO-shape lower
    bound (`source: "hlo_shapes"`, `complete: false`) and an unparseable
    program degrades to zeros (`source: "unavailable"`) — never a crash.
    `assert_donation(plan, params_bytes)` proves buffer donation actually
    aliased: with donation on, `alias_size` covers the donated buffers;
    with it off the assertion raises — a remat×donate regression that
    doubles peak HBM is a failing number, not a vibe.

  * **Attributed census** — a lightweight ownership registry:
    subsystems `register(array_or_tree, owner="kv_pool")` their long-lived
    device buffers (KVCachePool slabs, ShardedOptimizer shards,
    DeviceFeed/ImageRecordIter staging, FusedTrainStep weights), or wrap a
    region in `with tag("my_subsystem"):` so inner `register(tree)` calls
    inherit the owner. `census()` then groups `jax.live_arrays()` into
    owner -> {count, bytes, shapes} with an honest `untagged` bucket —
    attribution is by registration, never inference. `census_diff(a, b)`
    is the leak detector's primitive and `leakcheck(fn, rounds=N)` fails
    when untagged live bytes grow monotonically across rounds.

  * **OOM forensics** — `on_oom(error)` recognizes
    RESOURCE_EXHAUSTED/out-of-memory errors and dumps census + the active
    memory plans + the flight-recorder ring as one JSON black box before
    the error re-raises, wired into `run_resilient` / `run_elastic` /
    the serve engines next to the existing flightrec arm hooks
    (`install_oom_hook()` additionally chains `sys.excepthook` so an
    UNCAUGHT OOM still leaves the dump). `StepTimeline` gains a
    `peak_hbm_bytes` lane from the same `profiler.read_memory_sample()`
    the MemoryMonitor uses (honest `device` vs `host_rss` source stamp).

  * **Trend gating** — the bench `memory` phase emits
    `train_peak_hbm_mb` / `serve_kv_slab_mb` /
    `mem_plan_vs_measured_ratio` / `leakcheck_growth_mb`, gated in
    `tools/benchdiff.py`; `tools/memscope.py` is the operator CLI.

Owner names are flat `[a-z0-9_]+` tokens ON PURPOSE: dotted names would
collide with the telemetry metric namespace in the docs tables, and
mxlint's `mem-owner-*` rules hold the code <-> OBSERVABILITY.md owner
table consistent both directions.

Census accounting note: `bytes` is `Array.nbytes` — the GLOBAL logical
size of a sharded array (on the in-process CPU mesh that equals the
host bytes actually held; on a multi-host mesh divide by the process
count for the per-host share).

Knobs: `MXNET_MEM_SAMPLE_INTERVAL`, `MXNET_MEM_OOM_DUMP`,
`MXNET_MEM_CENSUS_DEPTH` (docs/ENV_VARS.md). Metric catalog (`mem.*`):
docs/OBSERVABILITY.md "Device memory".
"""
from __future__ import annotations

import contextvars
import json
import os
import re
import sys
import threading
import weakref
from collections import OrderedDict

from ..base import MXNetError, get_env, _register_env
from ..telemetry import REGISTRY
from ..telemetry import trace as _trace

__all__ = [
    "memory_plan", "plan_from_compiled", "assert_donation",
    "collective_memory_plans", "active_plans", "note_plan",
    "tag", "register", "current_tag", "census", "census_diff",
    "leakcheck", "live_bytes", "MemoryLeakError",
    "is_oom_error", "on_oom", "oom_report", "dump_oom",
    "install_oom_hook",
]

_register_env("MXNET_MEM_SAMPLE_INTERVAL", float, 0.05,
              "Default sampling interval (seconds) of "
              "profiler.MemoryMonitor — the device-memory timeline lane")
_register_env("MXNET_MEM_OOM_DUMP", str, None,
              "OOM black-box dumps: unset/1 = enabled (files land in "
              "MXNET_FLIGHTREC_DIR, else the cwd), 0 = disabled, any "
              "other value = the dump directory")
_register_env("MXNET_MEM_CENSUS_DEPTH", int, 5,
              "Distinct shapes listed per owner in census() reports "
              "(counts/bytes always cover everything)")

# -- metrics (docs/OBSERVABILITY.md "Device memory" catalog) ----------------
MEM_PLANS = REGISTRY.counter(
    "mem.plans", help="compiled-program memory plans computed")
MEM_CENSUS_RUNS = REGISTRY.counter(
    "mem.census_runs", help="live-buffer census passes")
MEM_TAGGED = REGISTRY.gauge(
    "mem.tagged_bytes", help="live device bytes attributed to a named "
    "owner in the most recent census")
MEM_UNTAGGED = REGISTRY.gauge(
    "mem.untagged_bytes", help="live device bytes with no registered "
    "owner in the most recent census")
MEM_OOM_DUMPS = REGISTRY.counter(
    "mem.oom_dumps", help="OOM black-box dump files written")


# ---------------------------------------------------------------------------
# memory plans
# ---------------------------------------------------------------------------
_PLAN_FIELDS = (
    ("argument_size", "argument_size_in_bytes"),
    ("output_size", "output_size_in_bytes"),
    ("temp_size", "temp_size_in_bytes"),
    ("alias_size", "alias_size_in_bytes"),
    ("generated_code_size", "generated_code_size_in_bytes"),
)

# name -> plan of the most recent plans computed in this process: what an
# OOM dump reports as "what was supposed to fit". Bounded (a sweep over
# many bucket programs must not grow without limit).
_plans_lock = threading.Lock()
_ACTIVE_PLANS = OrderedDict()
_ACTIVE_PLANS_CAP = 32


def note_plan(name, plan):
    """Record `plan` in the active-plan table the OOM dump reports."""
    with _plans_lock:
        _ACTIVE_PLANS.pop(name, None)
        _ACTIVE_PLANS[name] = plan
        while len(_ACTIVE_PLANS) > _ACTIVE_PLANS_CAP:
            _ACTIVE_PLANS.popitem(last=False)


def active_plans():
    """{name: plan} snapshot of the plans computed in this process."""
    with _plans_lock:
        return dict(_ACTIVE_PLANS)


def _shape_fallback(compiled, plan):
    """HLO-shape lower bound when memory_analysis() is unavailable: sum
    the entry computation's parameter and root-output shapes. `temp_size`
    is honestly unknown (0) — the plan says so via `complete: false`."""
    from . import hlo as _hlo
    try:
        module = _hlo.parse_module(compiled.as_text())
        entry = module.entry or next(iter(module.computations.values()))
        arg = out = 0
        for ins in entry.instructions:
            if ins.opcode == "parameter":
                arg += _hlo.shape_bytes(ins.shape)
        root = entry.root
        if root is not None:
            out = _hlo.shape_bytes(root.shape)
        plan.update(argument_size=int(arg), output_size=int(out),
                    temp_size=0, alias_size=0, generated_code_size=0,
                    peak_bytes=int(arg + out),
                    source="hlo_shapes", complete=False)
    except Exception as e:
        # last resort: an unparseable program still yields a plan object,
        # flagged unusable — never a crash (the PR-7 degradation contract)
        plan.update(argument_size=0, output_size=0, temp_size=0,
                    alias_size=0, generated_code_size=0, peak_bytes=0,
                    source="unavailable", complete=False,
                    error=f"{type(e).__name__}: {e}")
    return plan


def plan_from_compiled(compiled, name="program"):
    """Memory plan of an already-compiled stage (json.dumps-safe dict).

    `source` says where the numbers came from: `memory_analysis` (XLA's
    buffer assignment — authoritative, includes temporaries and donation
    aliasing), `hlo_shapes` (argument/output lower bound only), or
    `unavailable`. `peak_bytes = argument + output + temp - alias` is the
    predicted device high-water of one execution (aliased argument bytes
    are reused for outputs, so they never exist twice)."""
    plan = {"name": name}
    ma = None
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None and hasattr(ma, "argument_size_in_bytes"):
        try:
            for key, attr in _PLAN_FIELDS:
                plan[key] = int(getattr(ma, attr, 0) or 0)
            plan["peak_bytes"] = max(0, plan["argument_size"]
                                     + plan["output_size"]
                                     + plan["temp_size"]
                                     - plan["alias_size"])
            plan["source"] = "memory_analysis"
            plan["complete"] = True
        except Exception:
            plan = _shape_fallback(compiled, {"name": name})
    else:
        plan = _shape_fallback(compiled, plan)
    MEM_PLANS.inc()
    note_plan(name, plan)
    return plan


def memory_plan(obj, *args, name=None):
    """Memory plan for any inspectable surface: FusedTrainStep /
    FusedInferStep (`memory_plan(step, x, y)`), `deploy.ExportedModel`
    (per bucket program), jitted callables, `jax.stages.Lowered` /
    `Compiled` stages — the same `lower_any` resolution the roofline
    profiler uses, so everything `inspect_step` can rank, this can
    size."""
    from .report import lower_any, _name_of
    compiled = lower_any(obj, *args)
    return plan_from_compiled(compiled, name=name or _name_of(obj))


def assert_donation(plan, params_bytes, slack=0.02):
    """Prove the plan actually aliased (donated) at least `params_bytes`
    of its arguments. Raises MXNetError when it did not — the guard that
    turns a donate=off (or remat-policy-broke-donation) regression into a
    failing number. `slack` tolerates sub-percent layout padding."""
    params_bytes = int(params_bytes)
    if plan.get("source") != "memory_analysis":
        raise MXNetError(
            f"cannot prove donation for plan {plan.get('name')!r}: "
            f"buffer-assignment stats unavailable "
            f"(source={plan.get('source')!r})")
    aliased = int(plan.get("alias_size", 0))
    if aliased + slack * params_bytes < params_bytes:
        raise MXNetError(
            f"donation check failed for {plan.get('name')!r}: "
            f"{aliased} bytes aliased < {params_bytes} bytes of donated "
            f"buffers — donation did not take (peak HBM pays the "
            f"buffers twice)")
    return aliased


def collective_memory_plans():
    """Memory plans of every cached elastic bucketed-collective program
    (`kvstore.reduce_scatter_buckets` / `allgather_buckets`): run a
    trainer step first so the programs exist, then call this. Returns
    {name: plan}; a program whose lowering fails (dead mesh) degrades to
    a `source: "unavailable"` entry, never a crash."""
    from ..kvstore import collective_compiled_surfaces
    plans = {}
    for i, s in enumerate(collective_compiled_surfaces()):
        name = f"kvstore.{s['kind']}[{i}]"
        try:
            lowered = s["fn"].lower(*s["avals"])
            plans[name] = plan_from_compiled(lowered.compile(), name=name)
        except Exception as e:
            plans[name] = {"name": name, "source": "unavailable",
                           "complete": False, "peak_bytes": 0,
                           "error": f"{type(e).__name__}: {e}"}
    return plans


# ---------------------------------------------------------------------------
# ownership registry + census
# ---------------------------------------------------------------------------
_OWNER_RE = re.compile(r"^[a-z0-9_]+$")
_reg_lock = threading.Lock()
_owned = {}          # id(raw array) -> (weakref, owner)
_tag_ctx = contextvars.ContextVar("mx_mem_tag", default=None)


class MemoryLeakError(MXNetError):
    """leakcheck() observed monotonically growing untagged live bytes."""


def _check_owner(owner):
    if not isinstance(owner, str) or not _OWNER_RE.match(owner):
        raise MXNetError(
            f"memory owner must be a flat [a-z0-9_]+ token (dots would "
            f"collide with the metric namespace), got {owner!r}")
    return owner


class tag:
    """`with mem.tag("my_subsystem"):` — ambient owner for `register`
    calls in the block (thread/context-local; nesting shadows)."""

    __slots__ = ("owner", "_token")

    def __init__(self, owner):
        self.owner = _check_owner(owner)
        self._token = None

    def __enter__(self):
        self._token = _tag_ctx.set(self.owner)
        return self

    def __exit__(self, *exc):
        _tag_ctx.reset(self._token)
        return False


def current_tag():
    """The ambient owner set by an enclosing `tag(...)`, or None."""
    return _tag_ctx.get()


def _register_leaf(raw, owner):
    key = id(raw)

    def _gone(ref, key=key):
        # only delete OUR entry: a recycled id may already belong to a
        # newer registration by the time this callback fires
        with _reg_lock:
            ent = _owned.get(key)
            if ent is not None and ent[0] is ref:
                del _owned[key]

    try:
        ref = weakref.ref(raw, _gone)
    except TypeError:
        return                       # unweakrefable leaf: skip silently
    with _reg_lock:
        _owned[key] = (ref, owner)


def register(tree, owner=None):
    """Attribute `tree`'s array leaves to `owner` (or the ambient
    `tag(...)` owner). Idempotent and cheap — a weakref per leaf; dead
    arrays drop their entries automatically, and re-registering under a
    new owner overwrites (the donated-buffer-swap idiom re-registers the
    fresh buffers each step). Returns `tree` so call sites can wrap
    in-line. Never raises for odd leaves — attribution must not be able
    to break the subsystem it observes."""
    owner = _check_owner(owner if owner is not None
                         else (_tag_ctx.get() or _no_owner()))
    _walk_register(tree, owner)
    return tree


def _no_owner():
    raise MXNetError("register() needs owner= (or an enclosing "
                     "`with mem.tag(...):` block)")


def _walk_register(node, owner):
    if node is None:
        return
    if isinstance(node, dict):
        for v in node.values():
            _walk_register(v, owner)
        return
    if isinstance(node, (list, tuple)):
        for v in node:
            _walk_register(v, owner)
        return
    raw = getattr(node, "_arr", node)    # NDArray unwraps to its buffer
    if hasattr(raw, "nbytes") and hasattr(raw, "shape"):
        _register_leaf(raw, owner)


def registered_count():
    """Live registry entries (test/diagnostic aid)."""
    with _reg_lock:
        return len(_owned)


def live_bytes():
    """Total bytes of every live jax array (census totals without the
    grouping — the cheap measured-peak probe the bench phase samples)."""
    import jax
    total = 0
    for arr in jax.live_arrays():
        try:
            total += int(arr.nbytes)
        except Exception:
            continue
    return total


def census(depth=None):
    """Group `jax.live_arrays()` by registered owner.

    Returns a json-safe report::

        {"owners": {name: {"count", "bytes", "shapes": {repr: count}}},
         "total_bytes", "tagged_bytes", "untagged_bytes",
         "tagged_fraction", "n_arrays"}

    Attribution is honest: only explicitly registered buffers get a
    name; everything else lands in `untagged` (jit caches, constants,
    user arrays). `depth` bounds the distinct shapes listed per owner
    (`MXNET_MEM_CENSUS_DEPTH`; counts and bytes always cover all)."""
    import jax
    if depth is None:
        depth = get_env("MXNET_MEM_CENSUS_DEPTH", 5, typ=int)
    with _reg_lock:
        snapshot = dict(_owned)
    owners = {}
    total = tagged = n = 0
    for arr in jax.live_arrays():
        try:
            nb = int(arr.nbytes)
        except Exception:
            continue
        n += 1
        total += nb
        ent = snapshot.get(id(arr))
        name = "untagged"
        if ent is not None and ent[0]() is arr:
            name = ent[1]
            tagged += nb
        g = owners.get(name)
        if g is None:
            g = owners[name] = {"count": 0, "bytes": 0, "shapes": {}}
        g["count"] += 1
        g["bytes"] += nb
        srep = f"{arr.dtype}{list(arr.shape)}"
        if srep in g["shapes"] or len(g["shapes"]) < depth:
            g["shapes"][srep] = g["shapes"].get(srep, 0) + 1
    ordered = OrderedDict(sorted(owners.items(),
                                 key=lambda kv: -kv[1]["bytes"]))
    untagged = total - tagged
    MEM_CENSUS_RUNS.inc()
    MEM_TAGGED.set(tagged)
    MEM_UNTAGGED.set(untagged)
    return {"owners": ordered, "total_bytes": total,
            "tagged_bytes": tagged, "untagged_bytes": untagged,
            "tagged_fraction": round(tagged / total, 6) if total else 0.0,
            "n_arrays": n}


def census_diff(before, after):
    """Per-owner growth between two census() reports: the leak
    detector's primitive. Positive `bytes` = grew."""
    owners = {}
    names = set(before["owners"]) | set(after["owners"])
    for name in sorted(names):
        a = before["owners"].get(name, {"count": 0, "bytes": 0})
        b = after["owners"].get(name, {"count": 0, "bytes": 0})
        db, dc = b["bytes"] - a["bytes"], b["count"] - a["count"]
        if db or dc:
            owners[name] = {"bytes": db, "count": dc}
    return {"owners": owners,
            "total_bytes": after["total_bytes"] - before["total_bytes"],
            "untagged_bytes": (after["untagged_bytes"]
                               - before["untagged_bytes"])}


def leakcheck(fn, rounds=4, raise_on_leak=True, min_growth_bytes=4096):
    """Run `fn()` `rounds` times and fail when untagged live bytes grow
    MONOTONICALLY across every round — the signature of a per-round leak
    (a dropped reference cycle, an accumulating cache, a buffer pinned
    per call). One extra warmup execution runs first and is NOT counted:
    first-call allocation (jit compile caches, pool carves) is expected
    growth, not a leak.

    Returns the report; with `raise_on_leak` (default) a detected leak
    raises `MemoryLeakError` carrying it. `min_growth_bytes` filters
    allocator jitter: total growth below it never fails."""
    if rounds < 2:
        raise MXNetError("leakcheck needs rounds >= 2")
    fn()                                     # warmup: first-call allocs
    series_untagged, series_total = [], []
    baseline = census()
    for _ in range(rounds):
        fn()
        c = census()
        series_untagged.append(c["untagged_bytes"])
        series_total.append(c["total_bytes"])
    growth = series_untagged[-1] - baseline["untagged_bytes"]
    monotone = all(b > a for a, b in zip(series_untagged,
                                         series_untagged[1:]))
    leak = bool(monotone and growth >= min_growth_bytes)
    report = {"rounds": rounds, "leak": leak,
              "untagged_bytes": series_untagged,
              "total_bytes": series_total,
              "baseline_untagged_bytes": baseline["untagged_bytes"],
              "growth_bytes": int(growth),
              "growth_mb": round(growth / 2**20, 3),
              "per_round_bytes": int(growth / rounds)}
    if leak and raise_on_leak:
        err = MemoryLeakError(
            f"untagged live bytes grew monotonically across {rounds} "
            f"rounds (+{growth} bytes, ~{report['per_round_bytes']} "
            f"bytes/round) — something allocates per call and never "
            f"frees")
        err.report = report
        raise err
    return report


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------
_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "allocation failure")


def is_oom_error(error):
    """Does this exception look like a device/host OOM? Matches the XLA
    RESOURCE_EXHAUSTED family (`XlaRuntimeError`, RuntimeError text) and
    plain MemoryError — by message, because jaxlib's exception types vary
    across versions."""
    if error is None:
        return False
    if isinstance(error, MemoryError):
        return True
    msg = f"{type(error).__name__}: {error}".lower()
    return any(m in msg for m in _OOM_MARKERS)


def _oom_dump_dir():
    v = get_env("MXNET_MEM_OOM_DUMP", typ=str)
    if v and v not in ("0", "1"):
        return v
    d = _trace.FLIGHTREC._spool_dir()
    return d or "."


def _oom_dump_enabled():
    return get_env("MXNET_MEM_OOM_DUMP", typ=str) != "0"


def oom_report(error=None):
    """The black-box payload: census + active memory plans + the
    flight-recorder ring + device memory info. Every piece degrades
    independently (a dump on the crash path must never raise)."""
    from .. import profiler as _profiler
    rep = {"pid": os.getpid(),
           "error": None if error is None else
           f"{type(error).__name__}: {error}"}
    try:
        rep["census"] = census()
    except Exception as e:
        rep["census_error"] = f"{type(e).__name__}: {e}"
    rep["plans"] = active_plans()
    try:
        sample, source = _profiler.read_memory_sample()
        rep["bytes_in_use"] = sample
        rep["memory_source"] = source
    except Exception:
        pass
    try:
        from ..device import device_memory_info
        info = device_memory_info()
        rep["device_memory"] = {"free": info.free, "total": info.total,
                                "known": info.known}
    except Exception:
        pass
    try:
        rep["flightrec"] = _trace.flightrec_events()
    except Exception:
        pass
    return rep


def dump_oom(error=None, path=None, reason="oom"):
    """Write the OOM black box as one JSON file; returns the path or
    None (crash-path code: never raises). Default location:
    `<dir>/oomdump-<pid>.json` under MXNET_MEM_OOM_DUMP / the flightrec
    dir / the cwd — newest dump wins (atomic replace)."""
    try:
        rep = oom_report(error)
        rep["reason"] = reason
        if path is None:
            d = _oom_dump_dir()
            os.makedirs(d, exist_ok=True)
            path = os.path.join(d, f"oomdump-{os.getpid()}.json")
        tmp = path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            json.dump(rep, f, default=str)
        os.replace(tmp, path)
        MEM_OOM_DUMPS.inc()
        return path
    except Exception:
        return None


def on_oom(error, where=""):
    """The OOM handler the drivers call before re-raising: if `error` is
    OOM-shaped (and dumps are enabled), record it in the flight recorder
    and write the black box. Returns the dump path, or None when the
    error is not an OOM / dumping is off. Never raises."""
    try:
        if not is_oom_error(error) or not _oom_dump_enabled():
            return None
        _trace.flightrec_record("oom", where or "oom",
                                error=str(error)[:400])
        _trace.flightrec_maybe_dump("oom")
        return dump_oom(error=error, reason=where or "oom")
    except Exception:
        return None


_hook_lock = threading.Lock()
_hook_installed = [False]


def install_oom_hook():
    """Idempotent: chain `sys.excepthook` so an UNCAUGHT OOM writes the
    black box on the way down. Armed by `run_resilient` / `run_elastic`
    / `Server.start` / `ContinuousEngine.start` next to the flight
    recorder's crash hooks; a no-op beyond the first call."""
    with _hook_lock:
        if _hook_installed[0]:
            return
        _hook_installed[0] = True
    prev = sys.excepthook

    def _hook(tp, val, tb):
        try:
            on_oom(val, where="uncaught")
        except Exception:
            pass
        prev(tp, val, tb)

    sys.excepthook = _hook
