"""Roofline cost model over parsed HLO: per-fusion flops, bytes, intensity.

The MFU push (ROADMAP item 2) needs to know *which* fused regions are
memory-bound. XLA's `Compiled.cost_analysis()` answers only in aggregate
(total flops / total "bytes accessed"), so this module walks the optimized
module's kernel units — fusions, dots, convolutions, reduces, custom calls —
and models each one:

  flops       dot/conv from contraction shapes (MAC = 2, the chip-spec
              convention every MFU number in this repo already uses),
              elementwise = one flop per output element, reduce = input
              elements; fusions sum their called computation.
  bytes       the fusion BOUNDARY traffic: unique operand buffers read +
              output buffers written. Inner intermediates live in
              registers/vmem — that is the whole point of fusion — so the
              boundary is the HBM story.
  intensity   flops / bytes (arithmetic intensity, FLOP/B).
  class       compute-bound when intensity >= ridge point
              (peak_flops / peak_bytes_per_sec), memory-bound below it.
  est_time_s  max(flops / peak_flops, bytes / peak_bw) — the roofline
              execution-time estimate used to rank offenders.

Peaks come from a calibration artifact (`benchmark/results/
roofline_calib.json`, written by `tools/bandwidth.py --calib`) so the ridge
point tracks the attached hardware, with spec-table fallbacks when no
calibration ran (the bench-trend 22.4 bf16 TFLOP/s attainable for TPU v5e).
"""
from __future__ import annotations

import json
import os

from ..base import get_env
from . import hlo as _hlo

__all__ = ["instr_flops", "unit_cost", "kernel_units", "analyze_module",
           "analyze_compiled", "load_calibration", "classify",
           "cost_analysis_summary", "callable_cost", "CALIB_PATH",
           "DEFAULT_CALIBRATIONS"]

# repo-relative home of the calibration artifact (tools/bandwidth.py --calib)
CALIB_PATH = os.path.join("benchmark", "results", "roofline_calib.json")

# spec fallbacks by platform when no measured calibration exists. TPU row:
# the repo's measured attainable 22.4 bf16 TFLOP/s (bench.py calib phase,
# BENCH_r03+) and the v5e HBM spec 819 GB/s. CPU row: deliberately modest
# figures so CPU-only smoke runs classify sanely; real numbers come from
# the calib artifact.
DEFAULT_CALIBRATIONS = {
    "tpu": {"peak_flops": 22.4e12, "peak_bytes_per_sec": 819e9,
            "source": "spec-fallback"},
    "cpu": {"peak_flops": 1.0e11, "peak_bytes_per_sec": 20e9,
            "source": "spec-fallback"},
    "gpu": {"peak_flops": 100e12, "peak_bytes_per_sec": 900e9,
            "source": "spec-fallback"},
}

# opcodes that move/relabel data without arithmetic: zero flops, and when
# they appear standalone (outside a fusion) they are pure-bandwidth units
_ZERO_FLOP = frozenset((
    "parameter", "constant", "iota", "copy", "copy-start", "copy-done",
    "bitcast", "bitcast-convert", "reshape", "transpose", "broadcast",
    "tuple", "get-tuple-element", "slice", "dynamic-slice",
    "dynamic-update-slice", "concatenate", "pad", "reverse", "gather",
    "after-all", "partition-id", "replica-id", "rng-get-and-update-state",
    "convert", "real", "imag", "infeed", "outfeed", "send", "recv",
    "send-done", "recv-done", "domain", "opt-barrier",
))

# one flop per output element (comparisons/selects count like the
# reference profiler counted them: a lane op is a lane op)
_ELEMENTWISE = frozenset((
    "add", "subtract", "multiply", "divide", "maximum", "minimum",
    "and", "or", "xor", "not", "negate", "abs", "sign", "compare",
    "select", "clamp", "floor", "ceil", "round-nearest-afz",
    "round-nearest-even", "shift-left", "shift-right-arithmetic",
    "shift-right-logical", "remainder", "is-finite", "popcnt", "clz",
    "stochastic-convert", "map",
))

# transcendental lanes: still one flop per element in the MAC=2 accounting
# (matching XLA's own cost analysis, which counts them separately under
# "transcendentals"), tracked so the report can show them
_TRANSCENDENTAL = frozenset((
    "exponential", "exponential-minus-one", "log", "log-plus-one", "tanh",
    "sqrt", "rsqrt", "cbrt", "power", "sine", "cosine", "tan", "atan2",
    "logistic", "erf", "expm1", "log1p",
))


def _prod(dims):
    n = 1
    for d in dims:
        n *= d
    return n


def instr_flops(instr, module=None):
    """Modelled FLOPs of one instruction (MAC = 2 for dot/conv). Fusions,
    calls, and while loops recurse into their called computations (while
    bodies count ONCE — scan trip counts are not in the HLO text; the
    caller decides whether to scale)."""
    op = instr.opcode
    if op in _ZERO_FLOP:
        return 0.0
    if op == "dot":
        out = instr.out_elements
        lhs = instr.operand_shapes[0] if instr.operand_shapes else None
        contract = 1
        if lhs and not isinstance(lhs, list):
            for d in instr.dims_attr("lhs_contracting_dims"):
                if d < len(lhs[1]):
                    contract *= lhs[1][d]
        return 2.0 * out * contract
    if op == "convolution":
        return _conv_flops(instr)
    if op in ("reduce", "reduce-window", "select-and-scatter"):
        # ~one reducer application per input element (window ops touch
        # each input element once per covering window; stride==size for
        # the pooling shapes we care about)
        in_elems = sum(_hlo.num_elements(s)
                       for s in instr.operand_shapes[:1])
        return float(max(in_elems, instr.out_elements))
    if op in ("scatter",):
        return float(instr.out_elements)
    if op in ("rng", "rng-bit-generator"):
        return float(instr.out_elements)
    if op in ("fusion", "call", "async-start"):
        return _called_flops(instr, module)
    if op == "while":
        return _called_flops(instr, module)
    if op == "conditional":
        return _called_flops(instr, module)
    if op == "custom-call":
        return 0.0       # opaque: bytes still counted, flops unknowable
    if op in _ELEMENTWISE or op in _TRANSCENDENTAL:
        return float(instr.out_elements)
    # unknown opcode: assume one lane op per output element rather than
    # silently dropping it from the model
    return float(instr.out_elements)


def _called_flops(instr, module):
    if module is None:
        return 0.0
    total = 0.0
    for cname in instr.called:
        comp = module.computation(cname)
        if comp is None:
            continue
        for inner in comp.instructions:
            total += instr_flops(inner, module)
    return total


def _conv_flops(instr):
    """2 * output elements * (kernel spatial taps * input channels):
    kernel shape is operand 1; its output-feature dim comes from
    `dim_labels` (`b01f_01io->b01f` -> kernel layout `01io`, 'o' at
    position 3); feature groups divide the per-output input channels —
    the kernel shape already reflects that, so flops are simply
    2 * out * prod(kernel) / kernel_out_channels."""
    out = instr.out_elements
    if len(instr.operand_shapes) < 2:
        return 2.0 * out
    ker = instr.operand_shapes[1]
    if ker is None or isinstance(ker, list):
        return 2.0 * out
    kdims = ker[1]
    labels = instr.dim_labels
    out_ch = None
    if labels:
        try:
            kpart = labels.split("_")[1].split("-")[0]
            out_ch = kdims[kpart.index("o")]
        except (IndexError, ValueError):
            out_ch = None
    if out_ch is None:
        out_ch = kdims[-1] if kdims else 1
    return 2.0 * out * (_prod(kdims) / max(out_ch, 1))


def instr_transcendentals(instr, module=None):
    """Transcendental lane count (reported, not added to flops twice)."""
    op = instr.opcode
    if op in _TRANSCENDENTAL:
        return float(instr.out_elements)
    if op in ("fusion", "call", "while", "conditional"):
        total = 0.0
        if module is not None:
            for cname in instr.called:
                comp = module.computation(cname)
                if comp is None:
                    continue
                for inner in comp.instructions:
                    total += instr_transcendentals(inner, module)
        return total
    return 0.0


def unit_cost(instr, module=None):
    """Boundary cost of one kernel unit: flops (modelled), bytes (unique
    operand buffers read + output written), transcendentals."""
    seen = set()
    in_bytes = 0
    for name, shape in zip(instr.operands, instr.operand_shapes):
        if name in seen:      # the same buffer read twice is one read
            continue
        seen.add(name)
        in_bytes += _hlo.shape_bytes(shape)
    out_bytes = instr.out_bytes
    flops = instr_flops(instr, module)
    return {"flops": flops, "bytes": float(in_bytes + out_bytes),
            "in_bytes": float(in_bytes), "out_bytes": float(out_bytes),
            "transcendentals": instr_transcendentals(instr, module)}


# kernel units: instructions that map onto device kernel launches. A
# standalone zero-flop op (big copy/transpose outside any fusion) is still
# a unit — it moves bytes — but parameters/constants/tuples are free.
_NON_UNITS = frozenset((
    "parameter", "constant", "tuple", "get-tuple-element", "bitcast",
    "after-all", "iota", "partition-id", "replica-id", "domain",
    "opt-barrier", "get-dimension-size",
))


def kernel_units(module, computation=None, _seen=None):
    """Top-level kernel units of a computation (default: entry),
    transparently descending through `call` wrappers (the CPU backend
    wraps each fusion in a parallel-call shim) and while/conditional
    bodies (counted once; scan trip counts are not in the HLO)."""
    comp = computation or module.entry
    if comp is None:
        return []
    if _seen is None:
        _seen = set()
    if comp.name in _seen:
        return []
    _seen.add(comp.name)
    units = []
    for ins in comp.instructions:
        if ins.opcode in ("call", "while", "conditional"):
            for cname in ins.called:
                sub = module.computation(cname)
                if sub is not None:
                    units.extend(kernel_units(module, sub, _seen))
            continue
        if ins.opcode in _NON_UNITS:
            continue
        units.append(ins)
    return units


def classify(intensity, ridge):
    """'compute' above the ridge point (FLOP/B), 'memory' below it."""
    return "compute" if intensity >= ridge else "memory"


def load_calibration(path=None, platform=None):
    """Resolve the roofline peaks: explicit path > MXNET_INSPECT_CALIB >
    the committed `benchmark/results/roofline_calib.json` > the platform
    spec fallback. Returns a dict with at least `peak_flops`,
    `peak_bytes_per_sec`, `ridge_flop_per_byte`, `source`."""
    if platform is None:
        platform = _ambient_platform()
    candidates = []
    if path:
        candidates.append((path, True))      # explicit: trust the caller
    envp = get_env("MXNET_INSPECT_CALIB", None, typ=str)
    if envp:
        candidates.append((envp, True))
    root = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    candidates.append((os.path.join(root, CALIB_PATH), False))
    calib = None
    for cand, explicit in candidates:
        try:
            with open(cand) as f:
                data = json.load(f)
        except (OSError, ValueError):
            continue
        if not (data.get("peak_flops") and data.get("peak_bytes_per_sec")):
            continue
        # the committed artifact may have been calibrated on a different
        # backend (a CPU-container calib must not set a TPU run's ridge);
        # explicit paths (arg / env) override the check
        if not explicit and data.get("platform") \
                and data["platform"] != platform:
            continue
        calib = dict(data)
        calib.setdefault("source", cand)
        break
    if calib is None:
        calib = dict(DEFAULT_CALIBRATIONS.get(
            platform, DEFAULT_CALIBRATIONS["cpu"]))
    calib["ridge_flop_per_byte"] = (
        float(calib["peak_flops"]) / float(calib["peak_bytes_per_sec"]))
    return calib


def _ambient_platform(default="cpu"):
    try:
        import jax
        return jax.devices()[0].platform
    except Exception:
        return default


def analyze_module(module, calib=None):
    """Roofline records for every kernel unit of a parsed module, ranked
    by estimated time share (descending). Returns (records, totals)."""
    if calib is None:
        calib = load_calibration()
    peak_f = float(calib["peak_flops"])
    peak_b = float(calib["peak_bytes_per_sec"])
    ridge = peak_f / peak_b
    records = []
    for ins in kernel_units(module):
        cost = unit_cost(ins, module)
        flops, nbytes = cost["flops"], cost["bytes"]
        intensity = flops / nbytes if nbytes else float("inf")
        t_flops = flops / peak_f
        t_bytes = nbytes / peak_b
        records.append({
            "name": ins.name,
            "opcode": ins.opcode,
            "op_name": ins.op_name,
            "flops": flops,
            "bytes": nbytes,
            "in_bytes": cost["in_bytes"],
            "out_bytes": cost["out_bytes"],
            "transcendentals": cost["transcendentals"],
            "intensity": round(intensity, 4)
            if intensity != float("inf") else None,
            "bound": classify(intensity, ridge),
            "est_time_s": max(t_flops, t_bytes),
            "est_time_flops_s": t_flops,
            "est_time_bytes_s": t_bytes,
        })
    total_time = sum(r["est_time_s"] for r in records) or 1.0
    for r in records:
        r["time_share"] = round(r["est_time_s"] / total_time, 6)
    records.sort(key=lambda r: r["est_time_s"], reverse=True)
    totals = {
        "units": len(records),
        "flops": sum(r["flops"] for r in records),
        "bytes": sum(r["bytes"] for r in records),
        "est_time_s": sum(r["est_time_s"] for r in records),
        "memory_bound_units": sum(1 for r in records
                                  if r["bound"] == "memory"),
        "memory_bound_byte_share": round(
            sum(r["bytes"] for r in records if r["bound"] == "memory")
            / max(sum(r["bytes"] for r in records), 1.0), 6),
        "ridge_flop_per_byte": round(ridge, 3),
    }
    return records, totals


def analyze_compiled(compiled, calib=None):
    """`jax.stages.Compiled` (or anything with `.as_text()`) -> (records,
    totals, module)."""
    module = _hlo.parse_module(compiled.as_text())
    records, totals = analyze_module(module, calib=calib)
    return records, totals, module


# ---------------------------------------------------------------------------
# aggregate cost-analysis access with the degradation contract: backends
# whose cost_analysis() lacks bytes-accessed keys (or raises outright) must
# yield a usable flops-only summary, never a crash.
# ---------------------------------------------------------------------------

def cost_analysis_summary(compiled):
    """{'flops', 'bytes_accessed', 'bytes_estimated'} from
    `compiled.cost_analysis()`. `bytes_estimated` is True iff the
    bytes-accessed figure came from XLA itself; when the key is absent or
    the call raises, `bytes_accessed` is None and `bytes_estimated` is
    False — callers degrade to flops-only ranking."""
    out = {"flops": None, "bytes_accessed": None, "bytes_estimated": False}
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return out
    if isinstance(ca, (list, tuple)):        # older jax returns [dict]
        ca = ca[0] if ca else None
    if not ca:
        return out
    try:
        if "flops" in ca:
            out["flops"] = float(ca["flops"])
        if "bytes accessed" in ca:
            out["bytes_accessed"] = float(ca["bytes accessed"])
            out["bytes_estimated"] = True
    except (TypeError, ValueError):
        pass
    return out


def callable_cost(fn, *args, calib=None):
    """Estimated cost of one execution of `fn(*args)` for the per-op
    tables (benchmark/opperf.py): flops + bytes + arithmetic intensity +
    roofline class. Prefers XLA's own cost analysis; falls back to the
    HLO shape model for bytes when the backend does not report them
    (`bytes_source: "hlo-model"`), and to the HLO model for flops when
    cost analysis is entirely absent (`flops_source: "hlo-model"`).
    An already-jitted `fn` is lowered directly, so a caller that timed
    `jax.jit(op)` hits the jit cache instead of recompiling."""
    import jax
    if calib is None:
        calib = load_calibration()
    jitted = fn if hasattr(fn, "lower") else jax.jit(fn)
    compiled = jitted.lower(*args).compile()
    summary = cost_analysis_summary(compiled)
    flops, bytes_ = summary["flops"], summary["bytes_accessed"]
    flops_source = "xla-cost-analysis" if flops is not None else None
    bytes_source = "xla-cost-analysis" if bytes_ is not None else None
    if flops is None or bytes_ is None:
        try:
            _, totals, _ = analyze_compiled(compiled, calib=calib)
        except Exception:
            totals = None
        if totals is not None:
            if flops is None:
                flops, flops_source = totals["flops"], "hlo-model"
            if bytes_ is None:
                bytes_, bytes_source = totals["bytes"], "hlo-model"
    out = {"est_flops": flops, "est_bytes": bytes_,
           "flops_source": flops_source, "bytes_source": bytes_source,
           "bytes_estimated": bytes_source is not None}
    if flops is not None and bytes_:
        intensity = flops / bytes_
        out["intensity"] = round(intensity, 4)
        out["bound"] = classify(intensity, calib["ridge_flop_per_byte"])
    else:
        out["intensity"] = None
        out["bound"] = None
    return out
