"""mx.inspect — HLO roofline profiler and fusion-level offender attribution.

The XLA-era answer to the reference profiler's per-engine-op attribution
(PAPER.md layers 4-6): lower+compile any jitted step — `FusedTrainStep`,
`deploy.ExportedModel` bucket programs, bare `jax.jit` functions — walk the
optimized module's fusions, model each one's flops / bytes / arithmetic
intensity, classify compute- vs memory-bound against calibrated peaks, and
rank offenders by estimated time share:

    from incubator_mxnet_tpu import inspect as mxinspect
    report = mxinspect.inspect_step(step, x, y)   # FusedTrainStep + batch
    print(mxinspect.render_markdown(report))

CLI: `python tools/offenders.py --model resnet18 --json out.json`.
Calibration: `python tools/bandwidth.py --calib` writes
`benchmark/results/roofline_calib.json` (see docs/PERF.md). Knobs:
`MXNET_INSPECT_TOP_K`, `MXNET_INSPECT_MEASURED`, `MXNET_INSPECT_CALIB`.
Catalog of the `inspect.*` registry metrics: docs/OBSERVABILITY.md.
"""
from __future__ import annotations

from .hlo import (HloInstruction, HloComputation, HloModule, parse_module,
                  parse_shape, shape_bytes)
from .roofline import (analyze_compiled, analyze_module, callable_cost,
                       classify, cost_analysis_summary, instr_flops,
                       kernel_units, load_calibration, unit_cost)
from .report import (inspect_step, inspect_compiled, inspect_hlo_text,
                     render_markdown, lower_any, class_name, dump_json)
from .memory import (memory_plan, plan_from_compiled, assert_donation,
                     collective_memory_plans, active_plans, note_plan,
                     tag, register, current_tag, census, census_diff,
                     leakcheck, live_bytes, MemoryLeakError,
                     is_oom_error, on_oom, oom_report, dump_oom,
                     install_oom_hook)

__all__ = [
    "HloInstruction", "HloComputation", "HloModule", "parse_module",
    "parse_shape", "shape_bytes",
    "analyze_compiled", "analyze_module", "callable_cost", "classify",
    "cost_analysis_summary", "instr_flops", "kernel_units",
    "load_calibration", "unit_cost",
    "inspect_step", "inspect_compiled", "inspect_hlo_text",
    "render_markdown", "lower_any", "class_name", "dump_json",
    "memory_plan", "plan_from_compiled", "assert_donation",
    "collective_memory_plans", "active_plans", "note_plan",
    "tag", "register", "current_tag", "census", "census_diff",
    "leakcheck", "live_bytes", "MemoryLeakError",
    "is_oom_error", "on_oom", "oom_report", "dump_oom",
    "install_oom_hook",
]
