"""Base utilities: error hierarchy, dtype system, environment-flag layer.

TPU-native re-design of the reference's foundations:
  - error hierarchy  <- python/mxnet/error.py + src/nnvm/error.h (typed MXNetError tree)
  - dtype table      <- 3rdparty/mshadow/mshadow/base.h:355-365 (MSHADOW_TYPE_SWITCH)
  - env flags        <- dmlc::GetEnv use sites; docs/static_site/src/pages/api/faq/env_var.md

Nothing here touches jax at import time beyond numpy dtypes, so the flag layer can be
used to configure XLA before the first device touch.
"""
from __future__ import annotations

import os
import numpy as _np

__all__ = [
    "MXNetError", "NotImplementedForSymbol", "InternalError", "ValueError_",
    "TypeError_", "IndexError_", "AttributeError_", "NotImplementedError_",
    "string_types", "numeric_types", "integer_types",
    "DTYPE_NAMES", "name_to_dtype", "dtype_to_name",
    "get_env", "set_env", "env_flags",
]


# ---------------------------------------------------------------------------
# Error hierarchy (reference: python/mxnet/error.py register() pattern)
# ---------------------------------------------------------------------------
class MXNetError(RuntimeError):
    """Base error for all framework errors (reference: python/mxnet/error.py:27)."""


class InternalError(MXNetError):
    """Framework-internal invariant violation."""


class NotImplementedForSymbol(MXNetError):
    """Operation unavailable in traced/deferred mode (reference: mxnet/base.py)."""


class ValueError_(MXNetError, ValueError):
    pass


class TypeError_(MXNetError, TypeError):
    pass


class IndexError_(MXNetError, IndexError):
    pass


class AttributeError_(MXNetError, AttributeError):
    pass


class NotImplementedError_(MXNetError, NotImplementedError):
    pass


ERROR_TYPES = {
    "ValueError": ValueError_,
    "TypeError": TypeError_,
    "IndexError": IndexError_,
    "AttributeError": AttributeError_,
    "NotImplementedError": NotImplementedError_,
    "InternalError": InternalError,
}

string_types = (str,)
numeric_types = (float, int, _np.generic)
integer_types = (int, _np.integer)


# ---------------------------------------------------------------------------
# Dtype system (reference: mshadow/base.h dtype enum; bf16 is first-class on TPU)
# ---------------------------------------------------------------------------
# Names follow the reference's python-visible dtype strings.
DTYPE_NAMES = (
    "float32", "float64", "float16", "bfloat16",
    "uint8", "int8", "int16", "int32", "int64", "bool",
)

_NAME_TO_DTYPE = {
    "float32": _np.dtype("float32"),
    "float64": _np.dtype("float64"),
    "float16": _np.dtype("float16"),
    "uint8": _np.dtype("uint8"),
    "int8": _np.dtype("int8"),
    "int16": _np.dtype("int16"),
    "int32": _np.dtype("int32"),
    "int64": _np.dtype("int64"),
    "bool": _np.dtype("bool"),
}


def _bfloat16():
    # ml_dtypes ships with jax; resolved lazily so base.py imports stay cheap.
    import ml_dtypes
    return _np.dtype(ml_dtypes.bfloat16)


def name_to_dtype(name):
    """Resolve a dtype name/object to a numpy dtype (bf16 aware)."""
    if name is None:
        return _np.dtype("float32")
    if isinstance(name, str):
        if name == "bfloat16":
            return _bfloat16()
        if name in _NAME_TO_DTYPE:
            return _NAME_TO_DTYPE[name]
    return _np.dtype(name)


def dtype_to_name(dtype):
    d = _np.dtype(dtype) if not isinstance(dtype, _np.dtype) else dtype
    if d.name == "bfloat16":
        return "bfloat16"
    return d.name


# ---------------------------------------------------------------------------
# Environment flag layer (reference: 103 documented MXNET_* knobs, env_var.md)
# ---------------------------------------------------------------------------
# Central registry: name -> (type, default, help). Unknown flags still work via
# get_env(); registering gives introspection parity with the reference's doc page.
_ENV_REGISTRY = {}


def _register_env(name, typ, default, doc):
    _ENV_REGISTRY[name] = (typ, default, doc)
    return name


def env_flags():
    """Return {name: (type, default, doc)} of registered flags (≙ env_var.md)."""
    return dict(_ENV_REGISTRY)


def get_env(name, default=None, typ=None):
    """dmlc::GetEnv equivalent: typed environment lookup with registry defaults."""
    if name in _ENV_REGISTRY:
        rtyp, rdefault, _ = _ENV_REGISTRY[name]
        typ = typ or rtyp
        if default is None:
            default = rdefault
    raw = os.environ.get(name)
    if raw is None:
        return default
    if typ is bool:
        return raw not in ("0", "false", "False", "")
    if typ is None:
        return raw
    return typ(raw)


def set_env(name, value):
    """Mirror of mx.util.set_env."""
    os.environ[name] = str(value)


# Registered flags (TPU-native equivalents of the reference's engine/memory knobs;
# the ThreadedEngine/GPU-pool knobs collapse into XLA/PJRT configuration).
_register_env("MXNET_TEST_SEED", int, None, "Fixed seed for test reproducibility")
_register_env("MXNET_MODULE_SEED", int, None, "Module-level test seed")
_register_env("MXNET_ENGINE_TYPE", str, "XLA",
              "Execution engine; only 'XLA' (async PJRT dispatch) and 'Naive' "
              "(block after every op) are meaningful on TPU")
_register_env("MXNET_EXEC_BULK_EXEC_TRAIN", bool, True,
              "Whether hybridized training steps fuse fwd+bwd+update into one XLA program")
_register_env("MXNET_USE_FUSION", bool, True,
              "Gate the fused kernel tier (ops/fused.py Pallas kernels + "
              "gluon rewrites; default on for FusedTrainStep/FusedInferStep, "
              "eager paths opt in via fused.fusion_scope)")
_register_env("MXNET_FUSION_INTERPRET", bool, False,
              "Run the fused tier's Pallas kernels in interpret mode on "
              "any backend (CI kernel-path coverage on CPU)")
_register_env("MXNET_SAFE_ACCUMULATION", bool, True,
              "Accumulate bf16/fp16 reductions in float32")
_register_env("MXNET_PROFILER_AUTOSTART", bool, False, "Start profiler at import")
_register_env("MXNET_PROFILER_MODE", str, "symbolic", "Profiler mode")
_register_env("MXNET_STORAGE_FALLBACK_LOG_VERBOSE", bool, True,
              "Log when an op falls back to host (numpy) execution")
_register_env("MXNET_KVSTORE_BIGARRAY_BOUND", int, 1 << 19,
              "Arrays above this many elements use flat-bucket allreduce")
_register_env("MXNET_DEFAULT_DEVICE", str, None,
              "Override default device, e.g. 'tpu(0)' or 'cpu(0)'")
_register_env("MXNET_FAULT_SPEC", str, None,
              "Arm fault injection: 'point:hit:kind[:arg],...' "
              "(see mx.fault and docs/RESILIENCE.md)")
_register_env("MXNET_PREFETCH_RESTARTS", int, 3,
              "Bounded in-place retries for transient PrefetchingIter "
              "worker errors")
_register_env("MXNET_DATALOADER_RETRIES", int, 3,
              "Max attempts for a gluon DataLoader batch fetch on "
              "transient I/O errors")
_register_env("MXNET_PREFETCH_TO_DEVICE", bool, False,
              "Route estimator.fit / gluon DataLoader batches through "
              "io.DeviceFeed: async H2D prefetch overlapping the train "
              "step (≙ iter_prefetcher.h hiding input latency)")
_register_env("MXNET_DEVICE_FEED_DEPTH", int, 2,
              "io.DeviceFeed buffer depth (batches staged ahead; "
              "2 = double buffering)")
_register_env("MXNET_KVSTORE_BARRIER_TIMEOUT", float, None,
              "Seconds before a dist kvstore barrier aborts with a typed "
              "BarrierTimeout naming the missing ranks instead of "
              "hanging on a dead peer")
_register_env("MXNET_KV_BARRIER_TIMEOUT", float, None,
              "Legacy alias for MXNET_KVSTORE_BARRIER_TIMEOUT "
              "(consulted when the new knob is unset)")
