"""mx.models — flagship end-to-end model definitions.

The gluon.model_zoo carries the reference's CNN catalog; this package holds
the TPU-first flagship models used for benchmarking and the multi-chip
parallelism demonstrations (transformer LM with dp/tp/sp shardings — the
capability the reference lacks entirely, SURVEY §2.3/5.7).
"""
from . import transformer
