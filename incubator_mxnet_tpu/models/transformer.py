"""Transformer language model — TPU-first flagship.

Pure functional JAX (params as a pytree) so the full training step compiles
to ONE XLA computation over a `jax.sharding.Mesh`. Parallelism follows the
scaling-book recipe: name mesh axes (dp/tp/sp), annotate parameter and
activation shardings, let GSPMD insert the collectives (all-gather along tp
for the attention/MLP matmuls, psum for gradient reduction along dp,
all-to-all/collective-permute along sp for sequence-parallel attention).

Reference contrast: MXNet's only attention kernels are the fused CUDA
interleaved_matmul ops (src/operator/contrib/transformer.cc:676-869) with NO
tensor/sequence parallelism anywhere (SURVEY §2.3). This module is the
green-field replacement: the same BERT-class capability, sharded natively.

Sharding plan (Megatron-style TP + sequence sharding):
  embedding  (V, D)    -> P('tp', None)       row-parallel vocab
  attn qkv   (D, 3D)   -> P(None, 'tp')       column parallel
  attn out   (D, D)    -> P('tp', None)       row parallel
  mlp in     (D, F)    -> P(None, 'tp')
  mlp out    (F, D)    -> P('tp', None)
  activations (B, T, D)-> P('dp', 'sp', None)
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as _np

__all__ = ["TransformerConfig", "init_params", "forward", "loss_fn",
           "make_train_step", "param_shardings", "TransformerLM"]


@dataclass
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 2048
    dtype: str = "bfloat16"
    use_ring_attention: bool = False  # pallas ring attention over 'sp'
    tie_embeddings: bool = True


def _dtype(cfg):
    import jax.numpy as jnp
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16}[cfg.dtype]


def init_params(key, cfg: TransformerConfig):
    """Initialize the parameter pytree (all fp32 masters; cast at use)."""
    import jax
    import jax.numpy as jnp
    keys = jax.random.split(key, cfg.num_layers + 2)
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size

    def dense_init(k, shape, scale=None):
        scale = scale or (1.0 / math.sqrt(shape[0]))
        return jax.random.normal(k, shape, jnp.float32) * scale

    params = {
        "embedding": dense_init(keys[0], (v, d), scale=0.02),
        "pos_embedding": dense_init(keys[1], (cfg.max_seq_len, d),
                                    scale=0.02),
        "final_ln_scale": jnp.ones((d,), jnp.float32),
        "layers": [],
    }
    for i in range(cfg.num_layers):
        lk = jax.random.split(keys[2 + i], 4)
        params["layers"].append({
            "ln1_scale": jnp.ones((d,), jnp.float32),
            "ln2_scale": jnp.ones((d,), jnp.float32),
            "qkv": dense_init(lk[0], (d, 3 * d)),
            "attn_out": dense_init(lk[1], (d, d),
                                   scale=1.0 / math.sqrt(d * 2 * cfg.num_layers)),
            "mlp_in": dense_init(lk[2], (d, f)),
            "mlp_out": dense_init(lk[3], (f, d),
                                  scale=1.0 / math.sqrt(f * 2 * cfg.num_layers)),
        })
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(keys[1], (d, v), scale=0.02)
    return params


def param_shardings(cfg: TransformerConfig, mesh):
    """PartitionSpec pytree matching init_params (see module docstring)."""
    from jax.sharding import PartitionSpec as P
    layer = {
        "ln1_scale": P(), "ln2_scale": P(),
        "qkv": P(None, "tp"),
        "attn_out": P("tp", None),
        "mlp_in": P(None, "tp"),
        "mlp_out": P("tp", None),
    }
    specs = {
        "embedding": P("tp", None),
        "pos_embedding": P(),
        "final_ln_scale": P(),
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def _rms_norm(x, scale, eps=1e-6):
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax_rsqrt(var + eps)).astype(x.dtype) * scale


def jax_rsqrt(x):
    import jax
    return jax.lax.rsqrt(x)


def _attention(x, layer, cfg, mask=None):
    import jax
    import jax.numpy as jnp
    B, T, D = x.shape
    H = cfg.num_heads
    hd = D // H
    qkv = jnp.einsum("btd,de->bte", x, layer["qkv"].astype(x.dtype))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    from ..ops import nn as _nn
    o = _nn.scaled_dot_product_attention(q, k, v, causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, D)
    return jnp.einsum("btd,de->bte", o, layer["attn_out"].astype(x.dtype))


def _mlp(x, layer):
    import jax
    import jax.numpy as jnp
    h = jnp.einsum("btd,df->btf", x, layer["mlp_in"].astype(x.dtype))
    h = jax.nn.gelu(h)
    return jnp.einsum("btf,fd->btd", h, layer["mlp_out"].astype(x.dtype))


def forward(params, tokens, cfg: TransformerConfig, mesh=None):
    """tokens (B, T) int32 -> logits (B, T, V)."""
    import jax
    import jax.numpy as jnp
    dt = _dtype(cfg)
    B, T = tokens.shape
    x = params["embedding"].astype(dt)[tokens]
    x = x + params["pos_embedding"].astype(dt)[:T][None]
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        x = jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, P("dp", "sp", None)))
    for layer in params["layers"]:
        h = _rms_norm(x, layer["ln1_scale"].astype(dt))
        x = x + _attention(h, layer, cfg)
        h = _rms_norm(x, layer["ln2_scale"].astype(dt))
        x = x + _mlp(h, layer)
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            x = jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, P("dp", "sp", None)))
    x = _rms_norm(x, params["final_ln_scale"].astype(dt))
    head = (params["embedding"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(dt)
    return jnp.einsum("btd,dv->btv", x, head)


def loss_fn(params, batch, cfg: TransformerConfig, mesh=None):
    """Next-token cross-entropy. batch: {tokens (B,T+1)}."""
    import jax
    import jax.numpy as jnp
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits = forward(params, inputs, cfg, mesh).astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    return jnp.mean(logz - gold)


def make_train_step(cfg: TransformerConfig, mesh=None, learning_rate=3e-4,
                    weight_decay=0.01, b1=0.9, b2=0.95, eps=1e-8):
    """Build a jitted AdamW train step: (params, opt_state, batch, step)
    -> (params, opt_state, loss). With a mesh, params/batch shardings are
    applied and gradient psum over dp is inserted by GSPMD automatically."""
    import jax
    import jax.numpy as jnp

    def step_fn(params, opt_state, batch, step):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh))(params)
        mu, nu = opt_state
        t = step + 1

        def upd(p, g, m, v):
            m = b1 * m + (1 - b1) * g
            v = b2 * v + (1 - b2) * g * g
            mhat = m / (1 - b1 ** t.astype(jnp.float32))
            vhat = v / (1 - b2 ** t.astype(jnp.float32))
            p = p - learning_rate * (mhat / (jnp.sqrt(vhat) + eps)
                                     + weight_decay * p)
            return p, m, v

        flat_p, treedef = jax.tree_util.tree_flatten(params)
        flat_g = jax.tree_util.tree_leaves(grads)
        flat_m = jax.tree_util.tree_leaves(mu)
        flat_v = jax.tree_util.tree_leaves(nu)
        out = [upd(p, g, m, v) for p, g, m, v in
               zip(flat_p, flat_g, flat_m, flat_v)]
        new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
        new_m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
        new_v = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
        return new_p, (new_m, new_v), loss

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0, 1))

    from jax.sharding import NamedSharding, PartitionSpec as P
    pspecs = param_shardings(cfg, mesh)
    p_shard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    batch_shard = {"tokens": NamedSharding(mesh, P("dp", None))}
    step_shard = NamedSharding(mesh, P())
    return jax.jit(step_fn,
                   in_shardings=(p_shard, (p_shard, p_shard), batch_shard,
                                 step_shard),
                   out_shardings=(p_shard, (p_shard, p_shard), step_shard),
                   donate_argnums=(0, 1))


def init_opt_state(params):
    import jax
    import jax.numpy as jnp
    zeros = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)
    return (zeros, jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params))


class TransformerLM:
    """Object wrapper tying config+params together (gluon-style ergonomics
    over the functional core)."""

    def __init__(self, cfg: TransformerConfig = None, **kwargs):
        self.cfg = cfg or TransformerConfig(**kwargs)
        self.params = None

    def initialize(self, seed=0):
        import jax
        self.params = init_params(jax.random.PRNGKey(seed), self.cfg)
        return self

    def __call__(self, tokens):
        from ..ndarray import NDArray, _wrap
        raw = tokens._arr if isinstance(tokens, NDArray) else tokens
        return _wrap(forward(self.params, raw, self.cfg))
