"""Transformer language model — TPU-first flagship.

Pure functional JAX (params as a pytree) so the full training step compiles
to ONE XLA computation over a `jax.sharding.Mesh`. Parallelism follows the
scaling-book recipe: name mesh axes (dp/tp/sp), annotate parameter and
activation shardings, let GSPMD insert the collectives (all-gather along tp
for the attention/MLP matmuls, psum for gradient reduction along dp,
all-to-all/collective-permute along sp for sequence-parallel attention).

Reference contrast: MXNet's only attention kernels are the fused CUDA
interleaved_matmul ops (src/operator/contrib/transformer.cc:676-869) with NO
tensor/sequence parallelism anywhere (SURVEY §2.3). This module is the
green-field replacement: the same BERT-class capability, sharded natively.

Sharding plan (Megatron-style TP + sequence sharding):
  embedding  (V, D)    -> P('tp', None)       row-parallel vocab
  attn qkv   (D, 3D)   -> P(None, 'tp')       column parallel
  attn out   (D, D)    -> P('tp', None)       row parallel
  mlp in     (D, F)    -> P(None, 'tp')
  mlp out    (F, D)    -> P('tp', None)
  activations (B, T, D)-> P('dp', 'sp', None)
"""
from __future__ import annotations

import math
from dataclasses import dataclass, field

import numpy as _np

__all__ = ["TransformerConfig", "init_params", "forward", "loss_fn",
           "make_train_step", "param_shardings", "TransformerLM",
           "stack_pipeline_params", "make_pipeline_train_step",
           "init_opt_state"]


@dataclass
class TransformerConfig:
    vocab_size: int = 32000
    num_layers: int = 12
    d_model: int = 768
    num_heads: int = 12
    d_ff: int = 3072
    max_seq_len: int = 2048
    dtype: str = "bfloat16"
    use_ring_attention: bool = False  # ring attention over 'sp' (shard_map)
    ring_flash: bool = False          # flash kernels per ring hop (TPU)
    tie_embeddings: bool = True
    # Mixture-of-experts FFN (0 = dense MLP). In a sharded step the experts
    # live one-per-rank along `ep_axis` (DeepSpeed-MoE style co-location on
    # the data-parallel axis), so num_experts must equal that axis size.
    num_experts: int = 0
    ep_axis: str = "dp"
    moe_capacity_factor: float = 2.0
    moe_aux_weight: float = 0.01


def _dtype(cfg):
    import jax.numpy as jnp
    return {"bfloat16": jnp.bfloat16, "float32": jnp.float32,
            "float16": jnp.float16}[cfg.dtype]


def init_params(key, cfg: TransformerConfig):
    """Initialize the parameter pytree (all fp32 masters; cast at use)."""
    import jax
    import jax.numpy as jnp
    keys = jax.random.split(key, cfg.num_layers + 2)
    d, f, v = cfg.d_model, cfg.d_ff, cfg.vocab_size

    def dense_init(k, shape, scale=None):
        scale = scale or (1.0 / math.sqrt(shape[0]))
        return jax.random.normal(k, shape, jnp.float32) * scale

    params = {
        "embedding": dense_init(keys[0], (v, d), scale=0.02),
        "pos_embedding": dense_init(keys[1], (cfg.max_seq_len, d),
                                    scale=0.02),
        "final_ln_scale": jnp.ones((d,), jnp.float32),
        "layers": [],
    }
    for i in range(cfg.num_layers):
        lk = jax.random.split(keys[2 + i], 5)
        layer = {
            "ln1_scale": jnp.ones((d,), jnp.float32),
            "ln2_scale": jnp.ones((d,), jnp.float32),
            "qkv": dense_init(lk[0], (d, 3 * d)),
            "attn_out": dense_init(lk[1], (d, d),
                                   scale=1.0 / math.sqrt(d * 2 * cfg.num_layers)),
        }
        if cfg.num_experts > 0:
            E = cfg.num_experts
            out_scale = 1.0 / math.sqrt(f * 2 * cfg.num_layers)
            ek_in = jax.random.split(lk[2], E)
            ek_out = jax.random.split(lk[3], E)
            layer["gate"] = dense_init(lk[4], (d, E), scale=0.02)
            layer["mlp_in"] = jnp.stack(
                [dense_init(ek_in[e], (d, f)) for e in range(E)])
            layer["mlp_out"] = jnp.stack(
                [dense_init(ek_out[e], (f, d), scale=out_scale)
                 for e in range(E)])
        else:
            layer["mlp_in"] = dense_init(lk[2], (d, f))
            layer["mlp_out"] = dense_init(
                lk[3], (f, d), scale=1.0 / math.sqrt(f * 2 * cfg.num_layers))
        params["layers"].append(layer)
    if not cfg.tie_embeddings:
        params["lm_head"] = dense_init(keys[1], (d, v), scale=0.02)
    return params


def param_shardings(cfg: TransformerConfig, mesh):
    """PartitionSpec pytree matching init_params (see module docstring)."""
    from jax.sharding import PartitionSpec as P
    layer = {
        "ln1_scale": P(), "ln2_scale": P(),
        "qkv": P(None, "tp"),
        "attn_out": P("tp", None),
    }
    if cfg.num_experts > 0:
        # one expert per ep_axis rank; expert FFN weights replicated over tp
        # (the MoE shard_map body keeps expert matmuls rank-local)
        layer["gate"] = P()
        layer["mlp_in"] = P(cfg.ep_axis, None, None)
        layer["mlp_out"] = P(cfg.ep_axis, None, None)
    else:
        layer["mlp_in"] = P(None, "tp")
        layer["mlp_out"] = P("tp", None)
    specs = {
        "embedding": P("tp", None),
        "pos_embedding": P(),
        "final_ln_scale": P(),
        "layers": [dict(layer) for _ in range(cfg.num_layers)],
    }
    if not cfg.tie_embeddings:
        specs["lm_head"] = P(None, "tp")
    return specs


def _rms_norm(x, scale, eps=1e-6):
    import jax.numpy as jnp
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    return (xf * jax_rsqrt(var + eps)).astype(x.dtype) * scale


def jax_rsqrt(x):
    import jax
    return jax.lax.rsqrt(x)


def _use_ring(cfg, mesh):
    return (cfg.use_ring_attention and mesh is not None
            and "sp" in mesh.axis_names and mesh.shape["sp"] > 1)


def _attention(x, layer, cfg, mask=None, mesh=None):
    import jax
    import jax.numpy as jnp
    B, T, D = x.shape
    H = cfg.num_heads
    hd = D // H
    qkv = jnp.einsum("btd,de->bte", x, layer["qkv"].astype(x.dtype))
    q, k, v = jnp.split(qkv, 3, axis=-1)
    q = q.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    k = k.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    v = v.reshape(B, T, H, hd).transpose(0, 2, 1, 3)
    if _use_ring(cfg, mesh):
        # Sequence parallelism: the time axis stays sharded over 'sp'; k/v
        # shards rotate the ring via ppermute (ICI neighbor links) while each
        # rank accumulates online-softmax attention against its local q.
        # Heads ride 'tp' (column-parallel qkv), batch rides 'dp'.
        from jax.sharding import PartitionSpec as P
        from ..parallel import shard_map as _shard_map
        from ..parallel.ring import ring_attention

        spec = P("dp", "tp", "sp", None)
        o = _shard_map(
            lambda q_, k_, v_: ring_attention(
                q_, k_, v_, axis_name="sp", causal=True,
                use_flash=cfg.ring_flash),
            mesh, in_specs=(spec, spec, spec), out_specs=spec,
            check_rep=False)(q, k, v)
    else:
        from ..ops import nn as _nn
        o = _nn.scaled_dot_product_attention(q, k, v, causal=True)
    o = o.transpose(0, 2, 1, 3).reshape(B, T, D)
    return jnp.einsum("btd,de->bte", o, layer["attn_out"].astype(x.dtype))


def _mlp(x, layer):
    import jax
    import jax.numpy as jnp
    h = jnp.einsum("btd,df->btf", x, layer["mlp_in"].astype(x.dtype))
    h = jax.nn.gelu(h)
    return jnp.einsum("btf,fd->btd", h, layer["mlp_out"].astype(x.dtype))


def _moe_mlp_dense(x, layer, cfg):
    """Single-device MoE reference: top-1 routing, no capacity drops.

    Numerically equals the sharded all-to-all dispatch whenever capacity is
    not exceeded (moe_dispatch's overflow rule passes tokens through).
    """
    import jax
    import jax.numpy as jnp
    probs = jax.nn.softmax(
        jnp.einsum("btd,de->bte", x.astype(jnp.float32),
                   layer["gate"].astype(jnp.float32)), axis=-1)
    eidx = jnp.argmax(probs, axis=-1)                       # (B, T)
    gate = jnp.take_along_axis(probs, eidx[..., None], -1)[..., 0]
    # every expert over every token, then select (fine at test scale; the
    # sharded path is the production one)
    h = jnp.einsum("btd,edf->betf", x, layer["mlp_in"].astype(x.dtype))
    h = jax.nn.gelu(h)
    y_all = jnp.einsum("betf,efd->betd", h, layer["mlp_out"].astype(x.dtype))
    onehot = jax.nn.one_hot(eidx, cfg.num_experts, dtype=x.dtype)  # (B,T,E)
    y = jnp.einsum("betd,bte->btd", y_all, onehot)
    E = cfg.num_experts
    frac_tokens = jnp.mean(
        jax.nn.one_hot(eidx, E, dtype=jnp.float32), axis=(0, 1))
    frac_probs = jnp.mean(probs, axis=(0, 1))
    aux = E * jnp.sum(frac_tokens * frac_probs)
    return gate[..., None].astype(x.dtype) * y, aux


def _moe_mlp(x, layer, cfg, mesh=None):
    """MoE FFN: all-to-all dispatch over `cfg.ep_axis` when sharded, dense
    reference path otherwise. Returns (y, aux_loss)."""
    import jax
    import jax.numpy as jnp

    if (mesh is None or cfg.ep_axis not in mesh.axis_names
            or mesh.shape[cfg.ep_axis] == 1):
        return _moe_mlp_dense(x, layer, cfg)

    E = cfg.num_experts
    if mesh.shape[cfg.ep_axis] != E:
        raise ValueError(
            f"num_experts={E} must equal mesh axis {cfg.ep_axis!r} size "
            f"{mesh.shape[cfg.ep_axis]} (one expert per rank)")
    from jax.sharding import PartitionSpec as P
    from ..parallel import shard_map as _shard_map
    from ..parallel.moe import moe_dispatch

    ep = cfg.ep_axis
    B, T, D = x.shape
    t_local = T // mesh.shape.get("sp", 1) if "sp" in mesh.axis_names else T
    b_local = B // mesh.shape[ep]
    cap = max(int(cfg.moe_capacity_factor * b_local * t_local / E), 1)

    def body(x_loc, gate_w, w_in, w_out):
        bl, tl, _ = x_loc.shape
        flat = x_loc.reshape(bl * tl, D)
        logits = flat.astype(jnp.float32) @ gate_w.astype(jnp.float32)
        w_in_l, w_out_l = w_in[0], w_out[0]   # this rank's expert

        def expert_fn(toks):
            h = jax.nn.gelu(toks @ w_in_l.astype(toks.dtype))
            return h @ w_out_l.astype(toks.dtype)

        # average the load fractions over every token-sharded axis (ep and
        # sp; tp holds replicas so it's a no-op) BEFORE the nonlinear aux
        # product -> the Switch eq.4 objective over the global batch, and
        # the scalar comes out replicated so out_spec P() is sound
        stats = tuple(ax for ax in mesh.axis_names)
        y, aux = moe_dispatch(flat, logits, expert_fn, axis_name=ep,
                              capacity=cap, stats_axes=stats)
        return y.reshape(bl, tl, D), aux

    act_spec = (P(ep, "sp", None) if "sp" in mesh.axis_names
                else P(ep, None, None))
    y, aux = _shard_map(
        body, mesh,
        in_specs=(act_spec, P(), P(ep, None, None), P(ep, None, None)),
        out_specs=(act_spec, P()), check_rep=False)(
            x, layer["gate"], layer["mlp_in"], layer["mlp_out"])
    return y, aux


def forward(params, tokens, cfg: TransformerConfig, mesh=None,
            return_aux=False):
    """tokens (B, T) int32 -> logits (B, T, V) [, moe aux loss scalar]."""
    import jax
    import jax.numpy as jnp
    mesh = getattr(mesh, "jax_mesh", mesh)  # accept parallel.Mesh or jax Mesh
    dt = _dtype(cfg)
    B, T = tokens.shape
    x = params["embedding"].astype(dt)[tokens]
    x = x + params["pos_embedding"].astype(dt)[:T][None]
    if mesh is not None:
        from jax.sharding import PartitionSpec as P
        x = jax.lax.with_sharding_constraint(
            x, jax.sharding.NamedSharding(mesh, P("dp", "sp", None)))
    aux_total = jnp.zeros((), jnp.float32)
    for layer in params["layers"]:
        h = _rms_norm(x, layer["ln1_scale"].astype(dt))
        x = x + _attention(h, layer, cfg, mesh=mesh)
        h = _rms_norm(x, layer["ln2_scale"].astype(dt))
        if cfg.num_experts > 0:
            y, aux = _moe_mlp(h, layer, cfg, mesh)
            aux_total = aux_total + aux.astype(jnp.float32)
            x = x + y
        else:
            x = x + _mlp(h, layer)
        if mesh is not None:
            from jax.sharding import PartitionSpec as P
            x = jax.lax.with_sharding_constraint(
                x, jax.sharding.NamedSharding(mesh, P("dp", "sp", None)))
    x = _rms_norm(x, params["final_ln_scale"].astype(dt))
    head = (params["embedding"].T if cfg.tie_embeddings
            else params["lm_head"]).astype(dt)
    logits = jnp.einsum("btd,dv->btv", x, head)
    if return_aux:
        return logits, aux_total
    return logits


def loss_fn(params, batch, cfg: TransformerConfig, mesh=None):
    """Next-token cross-entropy (+ MoE load-balance aux when configured).
    batch: {tokens (B,T+1)}."""
    import jax
    import jax.numpy as jnp
    tokens = batch["tokens"]
    inputs, targets = tokens[:, :-1], tokens[:, 1:]
    logits, aux = forward(params, inputs, cfg, mesh, return_aux=True)
    logits = logits.astype(jnp.float32)
    logz = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, targets[..., None], axis=-1)[..., 0]
    ce = jnp.mean(logz - gold)
    if cfg.num_experts > 0:
        return ce + cfg.moe_aux_weight * aux
    return ce


def _adamw_update(params, grads, opt_state, t, learning_rate, weight_decay,
                  b1, b2, eps):
    """Bias-corrected AdamW over a pytree (shared by both step builders)."""
    import jax
    import jax.numpy as jnp
    mu, nu = opt_state

    def upd(p, g, m, v):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mhat = m / (1 - b1 ** t.astype(jnp.float32))
        vhat = v / (1 - b2 ** t.astype(jnp.float32))
        p = p - learning_rate * (mhat / (jnp.sqrt(vhat) + eps)
                                 + weight_decay * p)
        return p, m, v

    flat_p, treedef = jax.tree_util.tree_flatten(params)
    out = [upd(p, g, m, v) for p, g, m, v in
           zip(flat_p, jax.tree_util.tree_leaves(grads),
               jax.tree_util.tree_leaves(mu),
               jax.tree_util.tree_leaves(nu))]
    new_p = jax.tree_util.tree_unflatten(treedef, [o[0] for o in out])
    new_m = jax.tree_util.tree_unflatten(treedef, [o[1] for o in out])
    new_v = jax.tree_util.tree_unflatten(treedef, [o[2] for o in out])
    return new_p, (new_m, new_v)


def make_train_step(cfg: TransformerConfig, mesh=None, learning_rate=3e-4,
                    weight_decay=0.01, b1=0.9, b2=0.95, eps=1e-8):
    """Build a jitted AdamW train step: (params, opt_state, batch, step)
    -> (params, opt_state, loss). With a mesh, params/batch shardings are
    applied and gradient psum over dp is inserted by GSPMD automatically."""
    import jax

    def step_fn(params, opt_state, batch, step):
        loss, grads = jax.value_and_grad(
            lambda p: loss_fn(p, batch, cfg, mesh))(params)
        new_p, new_opt = _adamw_update(params, grads, opt_state, step + 1,
                                       learning_rate, weight_decay, b1, b2,
                                       eps)
        return new_p, new_opt, loss

    if mesh is None:
        return jax.jit(step_fn, donate_argnums=(0, 1))

    from jax.sharding import NamedSharding, PartitionSpec as P
    pspecs = param_shardings(cfg, mesh)
    p_shard = jax.tree_util.tree_map(
        lambda s: NamedSharding(mesh, s), pspecs,
        is_leaf=lambda x: isinstance(x, P))
    batch_shard = {"tokens": NamedSharding(mesh, P("dp", None))}
    step_shard = NamedSharding(mesh, P())
    return jax.jit(step_fn,
                   in_shardings=(p_shard, (p_shard, p_shard), batch_shard,
                                 step_shard),
                   out_shardings=(p_shard, (p_shard, p_shard), step_shard),
                   donate_argnums=(0, 1))


def stack_pipeline_params(params, cfg: TransformerConfig, num_stages):
    """Restack per-layer param dicts into stage-major stacked leaves.

    layers[i][k] of shape s  ->  stacked[k] of shape (S, L/S, *s), ready to
    shard P('pp', ...) so each pipeline rank holds its stage's L/S layers.
    Embedding/head/final-norm are copied (not aliased): the pipeline step
    donates its inputs, and a donated alias would silently invalidate the
    caller's original params.
    """
    import jax.numpy as jnp
    L = cfg.num_layers
    if L % num_stages:
        raise ValueError(f"num_layers={L} not divisible by pp={num_stages}")
    keys = params["layers"][0].keys()
    stacked = {k: jnp.stack([params["layers"][i][k] for i in range(L)])
               .reshape((num_stages, L // num_stages)
                        + params["layers"][0][k].shape)
               for k in keys}
    out = {k: jnp.array(v, copy=True) for k, v in params.items()
           if k != "layers"}
    out["layers"] = stacked
    return out


def make_pipeline_train_step(cfg: TransformerConfig, mesh, num_microbatches,
                             learning_rate=3e-4, weight_decay=0.01,
                             b1=0.9, b2=0.95, eps=1e-8):
    """GPipe pipeline-parallel AdamW train step over a ('pp','dp') mesh.

    Params must be in stacked form (stack_pipeline_params). Each pp rank
    holds L/S contiguous layers; microbatches stream around the ring via
    ppermute (parallel/pipeline.py) and the whole fwd+bwd+update compiles to
    one XLA program. Differentiable through the schedule: ppermute's
    transpose runs the reverse ring, so backward is pipelined too.

    Green-field vs the reference: MXNet has no pipeline parallelism at all
    (SURVEY §2.3); its closest analogue is manual per-device placement.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ..parallel import shard_map as _shard_map
    from ..parallel.pipeline import pipeline_apply

    jmesh = getattr(mesh, "jax_mesh", mesh)
    S = jmesh.shape["pp"]
    dp = jmesh.shape["dp"]
    M = num_microbatches
    dt = _dtype(cfg)
    if cfg.num_experts > 0 or cfg.use_ring_attention:
        raise ValueError("pipeline step composes with dp only (attention/"
                         "FFN run rank-local inside each stage)")

    def stage_fn(stage_layers, x):
        # stage_layers leaves: (L/S, ...) — scan over this stage's layers
        def body(h, lp):
            h = h + _attention(_rms_norm(h, lp["ln1_scale"].astype(dt)),
                               lp, cfg)
            h = h + _mlp(_rms_norm(h, lp["ln2_scale"].astype(dt)), lp)
            return h, None
        x, _ = jax.lax.scan(body, x, stage_layers)
        return x

    def local_loss(params, tokens):
        # tokens: (B_local, T+1) — this dp rank's shard, replicated over pp
        inputs, targets = tokens[:, :-1], tokens[:, 1:]
        B, T = inputs.shape
        x = params["embedding"].astype(dt)[inputs]
        x = x + params["pos_embedding"].astype(dt)[:T][None]
        x = x.reshape((M, B // M, T, cfg.d_model))
        stage_layers = jax.tree_util.tree_map(lambda a: a[0],
                                              params["layers"])
        y = pipeline_apply(lambda w, h: stage_fn(w, h), stage_layers, x,
                           axis_name="pp")
        # outputs are banked on the last pp rank, zeros elsewhere -> psum
        # broadcasts them to every rank
        y = jax.lax.psum(y, "pp")
        x = _rms_norm(y.reshape(B, T, cfg.d_model),
                      params["final_ln_scale"].astype(dt))
        head = (params["embedding"].T if cfg.tie_embeddings
                else params["lm_head"]).astype(dt)
        logits = jnp.einsum("btd,dv->btv", x, head).astype(jnp.float32)
        logz = jax.nn.logsumexp(logits, axis=-1)
        gold = jnp.take_along_axis(logits, targets[..., None], -1)[..., 0]
        # pmean over 'pp' too: every pp rank recomputes the same head/loss
        # (redundant but tiny), and the 1/S in the pmean's transpose cancels
        # the S-way psum of cotangents into the replicated embedding/head —
        # without it those grads would be S× overcounted
        return jax.lax.pmean(jnp.mean(logz - gold), ("dp", "pp"))

    rep = P()  # replicated leaves (embedding/head/norm)
    stage = {k: P("pp") for k in ("ln1_scale", "ln2_scale", "qkv",
                                  "attn_out", "mlp_in", "mlp_out")}
    pspec = {"embedding": rep, "pos_embedding": rep, "final_ln_scale": rep,
             "layers": stage}
    if not cfg.tie_embeddings:
        pspec["lm_head"] = rep

    sharded_loss = _shard_map(
        local_loss, jmesh, in_specs=(pspec, P("dp", None)), out_specs=P(),
        check_rep=False)

    def step_fn(params, opt_state, batch, step):
        loss, grads = jax.value_and_grad(
            lambda p: sharded_loss(p, batch["tokens"]))(params)
        new_p, new_opt = _adamw_update(params, grads, opt_state, step + 1,
                                       learning_rate, weight_decay, b1, b2,
                                       eps)
        return new_p, new_opt, loss

    shard_of = jax.tree_util.tree_map(
        lambda s: NamedSharding(jmesh, s), pspec,
        is_leaf=lambda x: isinstance(x, P))
    batch_shard = {"tokens": NamedSharding(jmesh, P("dp", None))}
    scalar = NamedSharding(jmesh, P())
    return jax.jit(step_fn,
                   in_shardings=(shard_of, (shard_of, shard_of), batch_shard,
                                 scalar),
                   out_shardings=(shard_of, (shard_of, shard_of), scalar),
                   donate_argnums=(0, 1))


def init_opt_state(params):
    import jax
    import jax.numpy as jnp
    zeros = jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params)
    return (zeros, jax.tree_util.tree_map(lambda p: jnp.zeros_like(p), params))


class TransformerLM:
    """Object wrapper tying config+params together (gluon-style ergonomics
    over the functional core)."""

    def __init__(self, cfg: TransformerConfig = None, **kwargs):
        self.cfg = cfg or TransformerConfig(**kwargs)
        self.params = None

    def initialize(self, seed=0):
        import jax
        self.params = init_params(jax.random.PRNGKey(seed), self.cfg)
        return self

    def __call__(self, tokens):
        from ..ndarray import NDArray, _wrap
        raw = tokens._arr if isinstance(tokens, NDArray) else tokens
        return _wrap(forward(self.params, raw, self.cfg))
