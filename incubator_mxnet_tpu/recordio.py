"""mx.recordio — RecordIO container format (≙ python/mxnet/recordio.py +
3rdparty/dmlc-core recordio).

Binary-compatible with the reference format so datasets packed by the
reference's im2rec tooling load directly:

  record  := magic(u32=0x3ed7230a) | lrecord(u32) | data | pad to 4B
  lrecord := cflag(u29 in upper 3 bits... reference packs cflag<<29 | length)
  IRHeader := flag(u32) label(f32) id(u64) id2(u64)   (struct IRHeader)

Continuation records (cflag 1/2/3) support data containing the magic.
"""
from __future__ import annotations

import ctypes
import os
import struct
import numpy as _np

from .base import MXNetError

__all__ = ["MXRecordIO", "MXIndexedRecordIO", "IRHeader", "pack", "unpack",
           "pack_img", "unpack_img"]

_MAGIC = 0x3ed7230a
_LFLAG_BITS = 29
_LMASK = (1 << _LFLAG_BITS) - 1


class MXRecordIO:
    """Sequential RecordIO reader/writer (≙ mx.recordio.MXRecordIO)."""

    def __init__(self, uri, flag):
        self.uri = uri
        self.flag = flag
        self.pid = None
        self.record = None
        self.open()

    def open(self):
        if self.flag == "w":
            self.record = open(self.uri, "wb")
            self.writable = True
        elif self.flag == "r":
            self.record = open(self.uri, "rb")
            self.writable = False
        else:
            raise MXNetError(f"invalid flag {self.flag}")
        self.pid = os.getpid()

    def close(self):
        if self.record is not None:
            self.record.close()
            self.record = None

    def reset(self):
        self.close()
        self.open()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()

    def _check_pid(self):
        if self.pid != os.getpid():
            # reopen after fork (≙ reference's is_mx_rec pid check)
            self.reset()

    def tell(self):
        return self.record.tell()

    def seek(self, pos):
        """Reposition the read cursor to a byte offset previously obtained
        from tell() (≙ MXRecordIOReaderSeek)."""
        self._check_pid()
        if self.writable:
            raise MXNetError("seek is for readers")
        self.record.seek(pos)

    def write(self, buf):
        """Write one record."""
        self._check_pid()
        if not self.writable:
            raise MXNetError("not opened for writing")
        # split payload at magic occurrences like dmlc recordio
        data = bytes(buf)
        # simple single-chunk write with cflag=0 (dmlc only needs chunking
        # when data embeds the magic; scan and chunk if needed)
        chunks = _split_on_magic(data)
        n = len(chunks)
        for i, chunk in enumerate(chunks):
            if n == 1:
                cflag = 0
            elif i == 0:
                cflag = 1
            elif i == n - 1:
                cflag = 3
            else:
                cflag = 2
            lrec = (cflag << _LFLAG_BITS) | len(chunk)
            self.record.write(struct.pack("<II", _MAGIC, lrec))
            self.record.write(chunk)
            pad = (4 - (len(chunk) % 4)) % 4
            if pad:
                self.record.write(b"\x00" * pad)

    def read(self):
        """Read one record; None at EOF."""
        self._check_pid()
        if self.writable:
            raise MXNetError("not opened for reading")
        out = b""
        while True:
            head = self.record.read(8)
            if len(head) < 8:
                return out if out else None
            magic, lrec = struct.unpack("<II", head)
            if magic != _MAGIC:
                raise MXNetError("invalid record magic")
            cflag = lrec >> _LFLAG_BITS
            length = lrec & _LMASK
            data = self.record.read(length)
            pad = (4 - (length % 4)) % 4
            if pad:
                self.record.read(pad)
            if cflag == 0:
                return data
            if cflag == 1:
                out = data
            elif cflag == 2:
                out += struct.pack("<I", _MAGIC) + data
            elif cflag == 3:
                return out + struct.pack("<I", _MAGIC) + data


def _split_on_magic(data):
    magic_bytes = struct.pack("<I", _MAGIC)
    parts = data.split(magic_bytes)
    return parts if len(parts) > 1 else [data]


class MXIndexedRecordIO(MXRecordIO):
    """Indexed RecordIO with .idx sidecar (≙ mx.recordio.MXIndexedRecordIO)."""

    def __init__(self, idx_path, uri, flag, key_type=int):
        self.idx_path = idx_path
        self.idx = {}
        self.keys = []
        self.key_type = key_type
        super().__init__(uri, flag)

    def open(self):
        super().open()
        self.idx = {}
        self.keys = []
        if not self.writable and os.path.isfile(self.idx_path):
            with open(self.idx_path) as f:
                for line in f:
                    parts = line.strip().split("\t")
                    if len(parts) < 2:
                        continue
                    key = self.key_type(parts[0])
                    self.idx[key] = int(parts[1])
                    self.keys.append(key)

    def close(self):
        if self.writable and self.idx:
            with open(self.idx_path, "w") as f:
                for k in self.keys:
                    f.write(f"{k}\t{self.idx[k]}\n")
        super().close()

    def seek(self, idx):
        self.record.seek(self.idx[idx])

    def read_idx(self, idx):
        self.seek(idx)
        return self.read()

    def write_idx(self, idx, buf):
        key = self.key_type(idx)
        pos = self.tell()
        self.write(buf)
        self.idx[key] = pos
        self.keys.append(key)


# IRHeader: flag, label, id, id2 (≙ mx.recordio.IRHeader struct)
_IR_FORMAT = "<IfQQ"
_IR_SIZE = struct.calcsize(_IR_FORMAT)


class IRHeader:
    __slots__ = ("flag", "label", "id", "id2")

    def __init__(self, flag, label, id, id2):
        self.flag = flag
        self.label = label
        self.id = id
        self.id2 = id2


def pack(header, s):
    """Pack IRHeader + payload into a record buffer (≙ mx.recordio.pack)."""
    label = header.label
    if isinstance(label, (list, tuple, _np.ndarray)):
        label = _np.asarray(label, dtype=_np.float32)
        header = IRHeader(len(label), 0.0, header.id, header.id2)
        payload = struct.pack(_IR_FORMAT, header.flag, header.label,
                              header.id, header.id2) + label.tobytes() + s
        return payload
    return struct.pack(_IR_FORMAT, header.flag, float(label), header.id,
                       header.id2) + s


def unpack(s):
    """Unpack a record buffer into (IRHeader, payload)."""
    flag, label, id_, id2 = struct.unpack(_IR_FORMAT, s[:_IR_SIZE])
    payload = s[_IR_SIZE:]
    if flag > 0:
        labels = _np.frombuffer(payload[:4 * flag], dtype=_np.float32)
        return IRHeader(flag, labels, id_, id2), payload[4 * flag:]
    return IRHeader(flag, label, id_, id2), payload


def pack_img(header, img, quality=95, img_fmt=".jpg"):
    raise MXNetError("pack_img requires an image codec (OpenCV) which is not "
                     "bundled; pack raw arrays with pack() instead")


def unpack_img(s, iscolor=-1):
    raise MXNetError("unpack_img requires an image codec; use unpack() and "
                     "decode with PIL/your codec of choice")
