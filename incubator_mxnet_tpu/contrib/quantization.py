"""INT8 quantization (≙ python/mxnet/contrib/quantization.py:383,755 +
src/operator/quantization/*: quantize_v2/dequantize/requantize ops, min-max
& KL-entropy calibration, quantize_net graph conversion).

TPU-native: symmetric per-tensor int8. `quantize_net` swaps Dense/Conv2D
children for Int8 wrappers whose forward runs an int8×int8→int32 matmul/conv
(XLA lowers to the MXU's integer path) with f32 rescale — the oneDNN int8
subgraph fusion collapses into XLA fusion. Calibration: run sample batches
through `CalibrationCollector` hooks, min-max or entropy (KL) thresholds.
"""
from __future__ import annotations

import numpy as _np

from ..base import MXNetError
from ..ndarray import NDArray, _as_nd, _wrap
from ..ops.registry import invoke

__all__ = ["quantize", "dequantize", "quantize_v2", "requantize",
           "quantize_net", "calibrate_net", "CalibrationCollector",
           "Int8Dense", "Int8Conv2D"]


def quantize_v2(data, min_calib_range=None, max_calib_range=None):
    """f32 -> (int8, min, max) symmetric (≙ _contrib_quantize_v2).

    With explicit calib ranges min/max come back as the floats given. In
    auto-calibration mode the range is computed ON DEVICE inside the same
    op (≙ the reference op's min/max outputs, which are NDArrays too) and
    min/max come back as 0-d NDArrays — no host sync in the op path, so
    eager chains stay inside one bulked segment (VERDICT-r3 Weak #4);
    `float()` them when a Python number is needed."""
    data = _as_nd(data)
    if min_calib_range is not None and max_calib_range is not None:
        scale = 127.0 / max(abs(min_calib_range), abs(max_calib_range),
                            1e-12)

        def f(x):
            import jax.numpy as jnp
            return jnp.clip(jnp.round(x * scale), -127, 127).astype(jnp.int8)
        q = invoke(f, (data,), name="quantize_v2")
        return q, min_calib_range, max_calib_range

    def f_auto(x):
        import jax.numpy as jnp
        amax = jnp.maximum(jnp.max(jnp.abs(x)).astype(jnp.float32),
                           jnp.float32(1e-12))
        q = jnp.clip(jnp.round(x * (127.0 / amax)),
                     -127, 127).astype(jnp.int8)
        return q, -amax, amax
    q, mn, mxr = invoke(f_auto, (data,), name="quantize_v2")
    return q, mn, mxr


quantize = quantize_v2


def dequantize(qdata, min_range, max_range):
    """int8 -> f32 (≙ _contrib_dequantize). Accepts float or 0-d NDArray
    ranges (the latter from auto-calibrated quantize_v2)."""
    scale = max(abs(float(min_range)), abs(float(max_range))) / 127.0

    def f(q):
        import jax.numpy as jnp
        return q.astype(jnp.float32) * scale
    return invoke(f, (_as_nd(qdata),), name="dequantize")


def requantize(qdata32, min_range, max_range):
    """int32 accum -> int8 using the CALIBRATED real-value range
    (≙ _contrib_requantize): min/max describe the real values the int32 data
    spans; no data-dependent host sync."""
    arr = _as_nd(qdata32)
    amax = max(abs(float(min_range)), abs(float(max_range)), 1e-12)
    in_scale = amax / float(2 ** 31 - 1)   # real units per int32 step

    def f(q):
        import jax.numpy as jnp
        real = q.astype(jnp.float32) * in_scale
        return jnp.clip(jnp.round(real * (127.0 / amax)),
                        -127, 127).astype(jnp.int8)
    return invoke(f, (arr,), name="requantize"), -amax, amax


# ---------------------------------------------------------------------------
# calibration (≙ quantization.py _LayerOutputCollector / KL calibration)
# ---------------------------------------------------------------------------
class CalibrationCollector:
    """Collects per-layer activation ranges via forward hooks."""

    def __init__(self, mode="naive", num_bins=2048):
        if mode not in ("naive", "entropy"):
            raise MXNetError("calib mode must be 'naive' (min-max) or "
                             "'entropy' (KL)")
        self.mode = mode
        self.num_bins = num_bins
        self.stats = {}   # name -> dict
        self._handles = []

    def attach(self, net):
        for name, child in _iter_named_blocks(net):
            reg = getattr(child, "register_forward_hook", None)
            if reg is None:   # folded-away / already-converted stand-ins
                continue
            h = reg(self._make_hook(name))
            self._handles.append(h)
        return self

    def detach(self):
        for h in self._handles:
            h.detach()
        self._handles = []

    def _make_hook(self, name):
        def hook(block, inputs, output):
            x = inputs[0]
            if not isinstance(x, NDArray):
                return
            a = x.asnumpy()
            st = self.stats.setdefault(
                name, {"amax": 0.0, "hist": _np.zeros(self.num_bins)})
            amax = float(_np.abs(a).max() or 0.0)
            if amax > st["amax"] and st["amax"] > 0 and self.mode == "entropy":
                # rebin the accumulated histogram onto the widened range so
                # bin widths stay consistent across batches
                old_edges = _np.linspace(0, st["amax"], self.num_bins + 1)
                centers = (old_edges[:-1] + old_edges[1:]) / 2
                st["hist"], _ = _np.histogram(
                    centers, bins=self.num_bins, range=(0, amax),
                    weights=st["hist"])
            st["amax"] = max(st["amax"], amax)
            if self.mode == "entropy" and st["amax"] > 0:
                h, _ = _np.histogram(_np.abs(a), bins=self.num_bins,
                                     range=(0, st["amax"]))
                st["hist"] = st["hist"] + h
        return hook

    def threshold(self, name):
        st = self.stats.get(name)
        if st is None or st["amax"] == 0:
            return None
        if self.mode == "naive":
            return st["amax"]
        return _kl_threshold(st["hist"], st["amax"])


def _kl_threshold(hist, amax, target_bins=128):
    """KL-divergence-minimizing clip threshold (≙ calibrate.cc entropy)."""
    hist = hist.astype(_np.float64)
    total = hist.sum()
    if total == 0:
        return amax
    n = len(hist)
    best_kl, best_i = _np.inf, n
    for i in range(target_bins, n + 1, max((n - target_bins) // 32, 1)):
        p = hist[:i].copy()
        p[-1] += hist[i:].sum()  # clip outliers into the last bin
        p /= p.sum()
        # quantize the i bins down to target_bins
        factor = i / target_bins
        q = _np.zeros(i)
        for j in range(target_bins):
            lo, hi = int(j * factor), max(int((j + 1) * factor), int(j * factor) + 1)
            mass = hist[lo:hi].sum()
            nz = (hist[lo:hi] > 0).sum()
            if nz:
                q[lo:hi] = _np.where(hist[lo:hi] > 0, mass / nz, 0)
        qs = q.sum()
        if qs == 0:
            continue
        q /= qs
        mask = p > 0
        kl = float(_np.sum(p[mask] * _np.log(p[mask] / _np.maximum(q[mask], 1e-12))))
        if kl < best_kl:
            best_kl, best_i = kl, i
    return amax * best_i / n


# ---------------------------------------------------------------------------
# int8 layers + net conversion (≙ quantize_net)
# ---------------------------------------------------------------------------
class Int8Dense:
    """Quantized Dense: int8 weights, int8 activations, int32 accumulate."""

    def __init__(self, dense, act_amax=None):
        import jax.numpy as jnp
        w = dense.weight.data().asnumpy()
        self._w_amax = float(_np.abs(w).max() or 1.0)
        wq = _np.clip(_np.round(w * 127.0 / self._w_amax), -127, 127
                      ).astype(_np.int8)
        self._wq = _wrap(jnp.asarray(wq))
        self._bias = dense.bias.data() if dense.bias is not None else None
        self._act_amax = act_amax
        self._flatten = dense._flatten
        self._act_type = dense._act_type

    def __call__(self, x):
        x = _as_nd(x)
        w_scale = self._w_amax / 127.0
        act_amax = self._act_amax  # None → dynamic in-graph quantization
        flatten = self._flatten

        def f(xr, wq, *maybe_bias):
            import jax
            import jax.numpy as jnp
            if flatten and xr.ndim > 2:
                xr = xr.reshape(xr.shape[0], -1)
            a_scale = (act_amax / 127.0 if act_amax is not None
                       else jnp.maximum(jnp.max(jnp.abs(xr)), 1e-6) / 127.0)
            xq = jnp.clip(jnp.round(xr / a_scale), -127, 127).astype(jnp.int8)
            # contract the LAST input axis (matches fp32 dense: x @ W.T)
            acc = jax.lax.dot_general(
                xq, wq, (((xq.ndim - 1,), (1,)), ((), ())),
                preferred_element_type=jnp.int32)
            y = acc.astype(jnp.float32) * (w_scale * a_scale)
            if maybe_bias:
                y = y + maybe_bias[0]
            return y

        args = (x, self._wq) + (() if self._bias is None else (self._bias,))
        y = invoke(f, args, name="int8_dense")
        if self._act_type:
            from .. import numpy_extension as npx
            y = npx.activation(y, act_type=self._act_type)
        return y


class Int8Conv2D:
    """Quantized Conv2D (int8 conv, int32 accumulate, f32 rescale)."""

    def __init__(self, conv, act_amax=None):
        import jax.numpy as jnp
        w = conv.weight.data().asnumpy()
        self._w_amax = float(_np.abs(w).max() or 1.0)
        wq = _np.clip(_np.round(w * 127.0 / self._w_amax), -127, 127
                      ).astype(_np.int8)
        self._wq = _wrap(jnp.asarray(wq))
        self._bias = conv.bias.data() if conv.bias is not None else None
        # copy only the conv hyperparams: keeping the block alive would pin
        # the fp32 weights the conversion is meant to free
        self._strides = conv._strides
        self._padding = conv._padding
        self._dilation = conv._dilation
        self._groups = conv._groups
        self._layout = conv._layout
        self._act_type = conv._act_type
        self._act_amax = act_amax

    def __call__(self, x):
        from ..ops import nn as _nn
        x = _as_nd(x)
        w_scale = self._w_amax / 127.0
        act_amax = self._act_amax
        stride, pad, dil = self._strides, self._padding, self._dilation
        groups, layout = self._groups, self._layout

        def f(xr, wq, *maybe_bias):
            import jax.numpy as jnp
            a_scale = (act_amax / 127.0 if act_amax is not None
                       else jnp.maximum(jnp.max(jnp.abs(xr)), 1e-6) / 127.0)
            xq = jnp.clip(jnp.round(xr / a_scale), -127, 127).astype(jnp.int8)
            # integer conv accumulates in int32 on the MXU integer path
            y = _nn.conv(xq.astype(jnp.int32), wq.astype(jnp.int32),
                         None, stride=stride, padding=pad,
                         dilation=dil, groups=groups, layout=layout)
            y = y.astype(jnp.float32) * (w_scale * a_scale)
            if maybe_bias:
                b = maybe_bias[0]
                if layout.startswith("NC"):
                    y = y + b.reshape((1, -1) + (1,) * (y.ndim - 2))
                else:  # channels-last layouts (NHWC...)
                    y = y + b
            return y

        args = (x, self._wq) + (() if self._bias is None else (self._bias,))
        y = invoke(f, args, name="int8_conv")
        if self._act_type:
            from .. import numpy_extension as npx
            y = npx.activation(y, act_type=self._act_type)
        return y


def _iter_named_blocks(net, prefix=""):
    for name, child in net._children.items():
        full = f"{prefix}{name}"
        yield full, child
        yield from _iter_named_blocks(child, full + ".")


def calibrate_net(net, calib_data, mode="naive", num_batches=10):
    """Run calibration batches, return {layer_name: threshold}. Hybridized
    caches are temporarily deactivated: the cached whole-graph op bypasses
    per-child forward hooks."""
    collector = CalibrationCollector(mode).attach(net)
    from .. import autograd
    saved = []
    for blk in _walk_blocks(net):
        if getattr(blk, "_active", False):
            saved.append(blk)
            blk._active = False
    try:
        for i, batch in enumerate(calib_data):
            if i >= num_batches:
                break
            x = batch[0] if isinstance(batch, (list, tuple)) else batch
            with autograd.predict_mode():
                net(x)
    finally:
        for blk in saved:
            blk._active = True
        collector.detach()
    return {name: collector.threshold(name)
            for name in collector.stats}


def _walk_blocks(net):
    yield net
    for child in net._children.values():
        yield from _walk_blocks(child)


def quantize_net(net, calib_data=None, calib_mode="naive", num_batches=10,
                 exclude_layers=None, fold_bn=True):
    """≙ contrib.quantization.quantize_net: fold inference BatchNorms into
    their preceding Conv2D/Dense (the quantize_graph_pass.cc rewrite), then
    swap Dense/Conv2D children for int8 versions (in place), calibrating
    activation ranges if data given."""
    from ..gluon import nn
    exclude = set(exclude_layers or [])
    if fold_bn:
        fold_batch_norm(net)
    thresholds = {}
    if calib_data is not None:
        thresholds = calibrate_net(net, calib_data, calib_mode, num_batches)

    def convert(block, prefix=""):
        for name, child in list(block._children.items()):
            full = f"{prefix}{name}"
            if full in exclude:
                continue
            amax = thresholds.get(full)
            if isinstance(child, nn.Dense) and child.weight._data is not None:
                block._children[name] = _BlockAdapter(Int8Dense(child, amax))
            elif type(child) is nn.Conv2D and child.weight._data is not None:
                block._children[name] = _BlockAdapter(Int8Conv2D(child, amax))
            else:
                convert(child, full + ".")

    convert(net)
    if hasattr(net, "reset_cache"):
        net.reset_cache()
    return net


class _BlockAdapter:
    """Minimal Block-like wrapper so converted children slot into the tree."""

    def __init__(self, impl):
        self._impl = impl
        self._children = {}
        self._reg_params = {}
        self._forward_hooks = {}
        self._forward_pre_hooks = {}

    def __call__(self, x, *args):
        return self._impl(x)

    def hybridize(self, *a, **kw):
        pass

    def _iter_params(self, prefix):
        return iter(())

    def apply(self, fn):
        fn(self)

    def __repr__(self):
        return f"Int8({type(self._impl).__name__})"


# ---------------------------------------------------------------------------
# quantized op family (≙ src/operator/quantization/quantized_*.cc): each op
# consumes (int8 data, min, max) and produces (int8, min, max), so chains of
# quantized ops stay on the integer path between layers — the reference's
# int8 graph. Ranges are python floats (calibration-time constants baked
# into the XLA program, like the reference's calibrated graph).
# ---------------------------------------------------------------------------

def _amax_of(mn, mx):
    # ranges may be 0-d NDArrays (auto-calibrated quantize_v2)
    return max(abs(float(mn)), abs(float(mx)), 1e-12)


def quantized_act(qdata, min_range, max_range, act_type="relu"):
    """≙ quantized_activation.cc — relu directly on int8 codes (symmetric
    scale fixes code 0 at real 0, so clip-at-zero is exact)."""
    if act_type != "relu":
        raise MXNetError("quantized activation supports relu only "
                         "(reference quantized_activation.cc is relu-only)")

    def f(q):
        import jax.numpy as jnp
        return jnp.maximum(q, 0).astype(jnp.int8)
    return invoke(f, (_as_nd(qdata),), name="quantized_act"), \
        0.0, _amax_of(min_range, max_range)


def quantized_pooling(qdata, min_range, max_range, pool_type="max",
                      kernel=(2, 2), stride=None, pad=(0, 0),
                      layout="NCHW"):
    """≙ quantized_pooling.cc — max pool stays pure int8; avg pool
    accumulates in int32 and rounds back (range unchanged)."""
    from ..ops import nn as _nn
    stride = stride or kernel

    def f(q):
        import jax.numpy as jnp
        if pool_type == "max":
            # reduce_window wants matching init dtype; widen + narrow back
            return _nn.pooling(q.astype(jnp.int32), kernel,
                               pool_type="max", stride=stride,
                               padding=pad, layout=layout).astype(jnp.int8)
        acc = _nn.pooling(q.astype(jnp.float32), kernel, pool_type="avg",
                          stride=stride, padding=pad, layout=layout,
                          count_include_pad=True)
        return jnp.clip(jnp.round(acc), -127, 127).astype(jnp.int8)
    return invoke(f, (_as_nd(qdata),), name="quantized_pooling"), \
        min_range, max_range


def quantized_flatten(qdata, min_range, max_range):
    """≙ quantized_flatten.cc."""
    q = _as_nd(qdata)
    return q.reshape((q.shape[0], -1)), min_range, max_range


def quantized_concat(inputs, ranges, axis=1):
    """≙ quantized_concat.cc: rescale every input onto the widest range,
    then concat in int8. inputs: list of int8 NDArrays; ranges: list of
    (min, max)."""
    amaxes = [_amax_of(mn, mx) for mn, mx in ranges]
    out_amax = max(amaxes)
    factors = [a / out_amax for a in amaxes]

    def f(*qs):
        import jax.numpy as jnp
        parts = [jnp.clip(jnp.round(q.astype(jnp.float32) * fac),
                          -127, 127).astype(jnp.int8)
                 for q, fac in zip(qs, factors)]
        return jnp.concatenate(parts, axis=axis)
    out = invoke(f, tuple(_as_nd(q) for q in inputs),
                 name="quantized_concat")
    return out, -out_amax, out_amax


def quantized_elemwise_add(qa, range_a, qb, range_b):
    """≙ quantized_elemwise_add.cc: align scales, add in int32,
    requantize to the sum's range."""
    amax_a = _amax_of(*range_a)
    amax_b = _amax_of(*range_b)
    out_amax = amax_a + amax_b        # exact bound of the sum
    sa = amax_a / 127.0
    sb = amax_b / 127.0
    so = out_amax / 127.0

    def f(a, b):
        import jax.numpy as jnp
        real = a.astype(jnp.float32) * sa + b.astype(jnp.float32) * sb
        return jnp.clip(jnp.round(real / so), -127, 127).astype(jnp.int8)
    out = invoke(f, (_as_nd(qa), _as_nd(qb)), name="quantized_elemwise_add")
    return out, -out_amax, out_amax


def quantized_elemwise_mul(qa, range_a, qb, range_b):
    """≙ quantized_elemwise_mul.cc: int32 product, range = product of
    ranges."""
    amax_a = _amax_of(*range_a)
    amax_b = _amax_of(*range_b)
    out_amax = amax_a * amax_b

    def f(a, b):
        import jax.numpy as jnp
        prod = a.astype(jnp.int32) * b.astype(jnp.int32)   # |p| <= 127^2
        return jnp.clip(jnp.round(prod.astype(jnp.float32) / 127.0),
                        -127, 127).astype(jnp.int8)
    out = invoke(f, (_as_nd(qa), _as_nd(qb)), name="quantized_elemwise_mul")
    return out, -out_amax, out_amax


def quantized_batch_norm(qdata, min_range, max_range, gamma, beta,
                         running_mean, running_var, eps=1e-5,
                         min_calib=None, max_calib=None):
    """≙ quantized_batch_norm.cc: inference BN over int8 input, int8
    output on the calibrated range. The affine transform runs fused in
    f32 inside the program (XLA keeps it on-chip); output requantizes to
    [min_calib, max_calib] (defaults: input range)."""
    in_amax = _amax_of(min_range, max_range)
    out_amax = _amax_of(min_calib, max_calib) \
        if (min_calib is not None and max_calib is not None) else in_amax
    s_in = in_amax / 127.0
    s_out = out_amax / 127.0
    args = tuple(_as_nd(a) for a in
                 (qdata, gamma, beta, running_mean, running_var))

    def f(q, g, b, mu, var):
        import jax.numpy as jnp
        shape = (1, -1) + (1,) * (q.ndim - 2)      # NCHW channel axis
        real = q.astype(jnp.float32) * s_in
        y = ((real - mu.reshape(shape))
             / jnp.sqrt(var.reshape(shape) + eps)) * g.reshape(shape) \
            + b.reshape(shape)
        return jnp.clip(jnp.round(y / s_out), -127, 127).astype(jnp.int8)
    out = invoke(f, args, name="quantized_batch_norm")
    return out, -out_amax, out_amax


def quantized_embedding(indices, weight_q, w_min, w_max):
    """≙ quantized_indexing_op.cc (EmbeddingLookup over an int8 table):
    gather in int8, dequantize the gathered rows only."""
    scale = _amax_of(w_min, w_max) / 127.0

    def f(idx, wq):
        import jax.numpy as jnp
        rows = jnp.take(wq, idx.astype(jnp.int32), axis=0)
        return rows.astype(jnp.float32) * scale
    return invoke(f, (_as_nd(indices), _as_nd(weight_q)),
                  name="quantized_embedding")


def quantized_fully_connected(qx, range_x, qw, range_w, bias=None,
                              min_calib=None, max_calib=None):
    """≙ quantized_fully_connected.cc: int8 x int8 -> int32 on the MXU
    integer path; int8 out on the calibrated range (f32 out when no
    calib range is given)."""
    ax = _amax_of(*range_x)
    aw = _amax_of(*range_w)
    sx, sw = ax / 127.0, aw / 127.0
    out_amax = (_amax_of(min_calib, max_calib)
                if (min_calib is not None and max_calib is not None)
                else None)
    args = (_as_nd(qx), _as_nd(qw)) + \
        (() if bias is None else (_as_nd(bias),))

    def f(x, w, *maybe_bias):
        import jax
        import jax.numpy as jnp
        acc = jax.lax.dot_general(
            x, w, (((x.ndim - 1,), (1,)), ((), ())),
            preferred_element_type=jnp.int32)
        y = acc.astype(jnp.float32) * (sx * sw)
        if maybe_bias:
            y = y + maybe_bias[0]
        if out_amax is None:
            return y
        return jnp.clip(jnp.round(y * (127.0 / out_amax)),
                        -127, 127).astype(jnp.int8)
    out = invoke(f, args, name="quantized_fully_connected")
    if out_amax is None:
        return out
    return out, -out_amax, out_amax


# ---------------------------------------------------------------------------
# graph passes (≙ quantize_graph_pass.cc)
# ---------------------------------------------------------------------------

def fold_batch_norm(net, aggressive=False):
    """Fold inference-mode BatchNorm into the preceding Conv2D/Dense
    (≙ the BN-fold rewrite in quantize_graph_pass.cc / oneDNN's
    conv+bn fusion): w' = w * g/sqrt(var+eps), b' = (b-mu)*g/sqrt(var+eps)
    + beta. By default folds only inside (Hybrid)Sequential containers,
    where child order IS the dataflow; `aggressive=True` extends the
    adjacency heuristic to custom blocks (caller asserts their forward()
    consumes the conv output only through the BN). BatchNormReLU folds to
    a ReLU stand-in; other BatchNorm subclasses are left alone. Returns
    the count of folded BNs."""
    from ..gluon import nn
    folded = 0

    def fold_pair(prev, bn):
        g = bn.gamma.data().asnumpy() if bn.gamma is not None else 1.0
        b = bn.beta.data().asnumpy() if bn.beta is not None else 0.0
        mu = bn.running_mean.data().asnumpy()
        var = bn.running_var.data().asnumpy()
        f = g / _np.sqrt(var + bn._eps)
        w = prev.weight.data().asnumpy()
        if isinstance(prev, nn.Dense):
            out_axis = 0                       # Dense weight (O, I)
        elif prev._layout.startswith("NC"):
            out_axis = 0                       # OIHW
        else:
            out_axis = w.ndim - 1              # HWIO (channels-last conv)
        bshape = [1] * w.ndim
        bshape[out_axis] = -1
        w2 = w * f.reshape(bshape)
        from .. import np as mxnp
        prev.weight.set_data(mxnp.array(w2))
        old_b = (prev.bias.data().asnumpy() if prev.bias is not None
                 else _np.zeros(w.shape[out_axis], w.dtype))
        new_b = (old_b - mu) * f + b
        if prev.bias is not None:
            prev.bias.set_data(mxnp.array(new_b.astype(w.dtype)))
        else:
            # conv created with use_bias=False: materialize the folded bias
            # (attribute assignment auto-registers the Parameter)
            from ..gluon.parameter import Parameter
            prev.bias = Parameter(shape=(w.shape[out_axis],), name="bias")
            prev.bias.set_data(mxnp.array(new_b.astype(w.dtype)))

    def replace_everywhere(block, name, old, ident):
        """Swap the folded BN out of BOTH registries: _children (container
        dispatch) and any instance attribute holding it (custom forward()
        that calls self.bn directly)."""
        block._children[name] = ident
        for attr, val in list(vars(block).items()):
            if val is old:
                object.__setattr__(block, attr, ident)

    def can_fold(prev, child):
        # exact BatchNorm / BatchNormReLU only (other subclasses may carry
        # extra behavior); `prev` must feed the BN unmodified (no baked
        # activation) and the BN axis must be prev's channel axis
        if type(child) not in (nn.BatchNorm, nn.BatchNormReLU):
            return False
        if not isinstance(prev, (nn.Dense, nn.Conv2D)):
            return False
        if getattr(prev, "_act_type", None) is not None:
            return False
        if prev.weight._data is None or child.running_mean._data is None:
            return False
        if isinstance(prev, nn.Dense):
            prev_axis, nd = 1, 2
        else:
            prev_axis, nd = prev._channel_axis(), len(prev._layout)
        return child._axis % nd == prev_axis

    def walk(block):
        nonlocal folded
        # adjacency in _children == dataflow only for sequential
        # containers; elsewhere a custom forward() may reuse the pre-BN
        # value, so fold only inside HybridSequential unless aggressive
        here_ok = aggressive or isinstance(
            block, (nn.HybridSequential, nn.Sequential))
        names = list(block._children.keys())
        for i, name in enumerate(names):
            child = block._children[name]
            if here_ok and i > 0 \
                    and can_fold(block._children[names[i - 1]], child):
                prev = block._children[names[i - 1]]
                is_bn_relu = type(child) is nn.BatchNormReLU
                fold_pair(prev, child)
                stand_in = _ReLU() if is_bn_relu else _Identity()
                replace_everywhere(block, name, child, stand_in)
                folded += 1
                continue
            walk(child)

    walk(net)
    if hasattr(net, "reset_cache"):
        net.reset_cache()
    return folded


class _Identity(_BlockAdapter):
    """Stand-in for a folded-away block."""

    def __init__(self):
        super().__init__(lambda x: x)

    def __repr__(self):
        return "Identity(folded BatchNorm)"


class _ReLU(_BlockAdapter):
    """Stand-in for a folded-away BatchNormReLU (affine part folded into
    the conv; the activation half survives here)."""

    def __init__(self):
        def relu(x):
            from .. import numpy_extension as npx
            return npx.relu(x)
        super().__init__(relu)

    def __repr__(self):
        return "ReLU(folded BatchNormReLU)"


__all__ += ["quantized_act", "quantized_pooling", "quantized_flatten",
            "quantized_concat", "quantized_elemwise_add",
            "quantized_elemwise_mul", "quantized_batch_norm",
            "quantized_embedding", "quantized_fully_connected",
            "fold_batch_norm"]
