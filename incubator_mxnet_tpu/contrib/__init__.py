"""mx.contrib — quantization + contrib op surface."""
from . import quantization
