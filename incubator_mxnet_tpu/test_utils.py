"""mx.test_utils (≙ python/mxnet/test_utils.py ~3.5k LoC).

The reference's numeric-checking toolkit: assert_almost_equal with
dtype-aware tolerances, finite-difference gradient checking against
autograd, cross-context consistency, random array helpers, default_context.
"""
from __future__ import annotations

import numpy as _np

from .base import MXNetError
from .device import cpu, current_device

__all__ = [
    "default_context", "default_device", "set_default_context",
    "assert_almost_equal", "almost_equal", "same", "rand_ndarray",
    "rand_shape_2d", "rand_shape_3d", "rand_shape_nd", "random_arrays",
    "check_numeric_gradient", "check_consistency", "numeric_grad",
    "default_rtols", "default_atols", "effective_dtype",
]

_default_ctx = [None]


def default_context():
    """≙ test_utils.default_context()."""
    return _default_ctx[0] or current_device()


default_device = default_context


def set_default_context(ctx):
    _default_ctx[0] = ctx


def _dtype_of(x):
    return _np.dtype(getattr(x, "dtype", _np.float64))


def default_rtols(dtype):
    """Per-dtype relative tolerance (≙ test_utils.py default_rtols)."""
    name = str(dtype)
    return {"float16": 1e-2, "bfloat16": 1.6e-2, "float32": 1e-4,
            "float64": 1e-7}.get(name, 0.0)


def default_atols(dtype):
    name = str(dtype)
    return {"float16": 1e-3, "bfloat16": 3.2e-3, "float32": 1e-5,
            "float64": 1e-9}.get(name, 0.0)


def effective_dtype(x):
    return _dtype_of(x)


def _to_np(x):
    if hasattr(x, "asnumpy"):
        return x.asnumpy()
    return _np.asarray(x)


def same(a, b):
    return _np.array_equal(_to_np(a), _to_np(b))


def almost_equal(a, b, rtol=None, atol=None, equal_nan=False):
    a, b = _to_np(a), _to_np(b)
    rtol = rtol if rtol is not None else max(default_rtols(a.dtype),
                                             default_rtols(b.dtype))
    atol = atol if atol is not None else max(default_atols(a.dtype),
                                             default_atols(b.dtype))
    return _np.allclose(a.astype(_np.float64), b.astype(_np.float64),
                        rtol=rtol, atol=atol, equal_nan=equal_nan)


def assert_almost_equal(a, b, rtol=None, atol=None, names=("a", "b"),
                        equal_nan=False):
    """≙ test_utils.assert_almost_equal with dtype-aware tolerances."""
    an, bn = _to_np(a), _to_np(b)
    rtol = rtol if rtol is not None else max(default_rtols(an.dtype),
                                             default_rtols(bn.dtype))
    atol = atol if atol is not None else max(default_atols(an.dtype),
                                             default_atols(bn.dtype))
    _np.testing.assert_allclose(
        an.astype(_np.float64), bn.astype(_np.float64), rtol=rtol, atol=atol,
        equal_nan=equal_nan,
        err_msg=f"{names[0]} vs {names[1]} mismatch (rtol={rtol}, atol={atol})")


def rand_shape_2d(dim0=10, dim1=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1))


def rand_shape_3d(dim0=10, dim1=10, dim2=10):
    return (_np.random.randint(1, dim0 + 1), _np.random.randint(1, dim1 + 1),
            _np.random.randint(1, dim2 + 1))


def rand_shape_nd(num_dim, dim=10):
    return tuple(_np.random.randint(1, dim + 1, size=num_dim))


def rand_ndarray(shape, stype="default", density=None, dtype="float32",
                 device=None, ctx=None):
    """≙ test_utils.rand_ndarray (dense only: no sparse storage on TPU)."""
    if stype != "default":
        raise MXNetError("sparse stypes unsupported on TPU")
    from .ndarray import array
    return array(_np.random.uniform(-1, 1, shape).astype(dtype),
                 device=device or ctx)


def random_arrays(*shapes):
    arrays = [_np.random.randn(*s).astype(_np.float64) if s else
              _np.asarray(_np.random.randn()) for s in shapes]
    return arrays if len(arrays) > 1 else arrays[0]


def numeric_grad(f, xs, eps=1e-4):
    """Central finite differences of scalar f w.r.t. list of numpy arrays."""
    grads = []
    for i, x in enumerate(xs):
        g = _np.zeros_like(x, dtype=_np.float64)
        flat = x.reshape(-1)
        gf = g.reshape(-1)
        for j in range(flat.size):
            orig = flat[j]
            flat[j] = orig + eps
            fp = float(f(*xs))
            flat[j] = orig - eps
            fm = float(f(*xs))
            flat[j] = orig
            gf[j] = (fp - fm) / (2 * eps)
        grads.append(g)
    return grads


def check_numeric_gradient(fn, inputs, rtol=1e-2, atol=1e-4, eps=1e-3):
    """≙ test_utils.check_numeric_gradient: autograd vs finite differences.

    `fn` maps NDArrays -> scalar NDArray loss.
    """
    from . import autograd
    from .ndarray import array
    nds = [array(x.astype(_np.float64)) for x in inputs]
    for nd in nds:
        nd.attach_grad()
    with autograd.record():
        loss = fn(*nds)
    loss.backward()
    analytic = [nd.grad.asnumpy() for nd in nds]

    def host_f(*xs):
        return fn(*[array(x) for x in xs]).asnumpy()

    numeric = numeric_grad(host_f, [x.astype(_np.float64) for x in inputs],
                           eps)
    for a, n in zip(analytic, numeric):
        _np.testing.assert_allclose(a, n, rtol=rtol, atol=atol)


def check_consistency(sym_fn, ctx_list, inputs, rtol=1e-4, atol=1e-5):
    """≙ test_utils.check_consistency(ctx_list): run the same function on a
    list of devices and compare outputs (CPU interpreter vs TPU)."""
    from .ndarray import array
    results = []
    for ctx in ctx_list:
        nds = [array(x, device=ctx) for x in inputs]
        results.append(_to_np(sym_fn(*nds)))
    for r in results[1:]:
        _np.testing.assert_allclose(results[0], r, rtol=rtol, atol=atol)
    return results
