"""Global random state: seedable, trace-aware PRNG key plumbing.

Reference: mx.random + per-device RandGenerator resources
(include/mxnet/random_generator.h, src/resource.cc kRandom/kParallelRandom).
TPU-native: JAX's functional threefry keys. Eager ops draw from a process-global
key (split per call). Inside a traced/hybridized computation, a *trace key
scope* supplies a traced key instead, keeping the trace pure: the jit wrapper
passes a fresh key argument each call (≙ the reference re-seeding per-forward
dropout through the resource manager).
"""
from __future__ import annotations

import threading

from .base import get_env

_state = threading.local()
_global = {"key": None, "seed": 0}
_lock = threading.Lock()


def _key_module():
    import jax
    return jax.random


def seed(seed_state=None, ctx="all"):
    """Seed the global generator (≙ mx.random.seed)."""
    if seed_state is None:
        import os
        seed_state = int.from_bytes(os.urandom(4), "little")
    with _lock:
        _global["seed"] = int(seed_state)
        _global["key"] = _key_module().PRNGKey(int(seed_state))


class _TraceKeyScope:
    """Context supplying a traced PRNG key for use inside jit traces."""

    def __init__(self, key):
        self.holder = [key]

    def __enter__(self):
        stack = getattr(_state, "stack", None)
        if stack is None:
            stack = _state.stack = []
        stack.append(self.holder)
        return self

    def __exit__(self, *exc):
        _state.stack.pop()


def trace_key_scope(key):
    return _TraceKeyScope(key)


def next_key():
    """Return a fresh PRNG key (splitting trace key or the global key)."""
    jr = _key_module()
    stack = getattr(_state, "stack", None)
    if stack:
        holder = stack[-1]
        holder[0], sub = jr.split(holder[0])
        return sub
    with _lock:
        if _global["key"] is None:
            test_seed = get_env("MXNET_TEST_SEED", typ=int)
            _global["key"] = jr.PRNGKey(test_seed if test_seed is not None else 0)
        _global["key"], sub = jr.split(_global["key"])
    return sub
