"""Attribute scoping for the symbolic API (≙ python/mxnet/attribute.py:1).

`AttrScope` attaches string attributes to every symbol created inside the
scope (the reference uses it for group markers, ctx hints, and
__wd_mult__-style per-symbol knobs). Scopes nest and merge."""
from __future__ import annotations

import threading

from .base import MXNetError

__all__ = ["AttrScope", "current"]

_state = threading.local()


def current():
    stack = getattr(_state, "stack", None)
    if not stack:
        _state.stack = [AttrScope()]
        stack = _state.stack
    return stack[-1]


class AttrScope:
    """≙ attribute.py AttrScope: attributes must be strings; nested scopes
    merge (inner wins on conflicts)."""

    def __init__(self, **kwargs):
        for k, v in kwargs.items():
            if not isinstance(v, str):
                raise MXNetError(
                    f"attribute {k!r} must be a string, got {type(v).__name__}")
        self._attrs = dict(kwargs)

    def get(self, attrs=None):
        """Merge scope attributes with explicitly-given ones (explicit
        wins), returning a plain dict."""
        out = dict(self._attrs)
        if attrs:
            out.update(attrs)
        return out

    def __enter__(self):
        merged = AttrScope()
        merged._attrs = {**current()._attrs, **self._attrs}
        _state.stack.append(merged)
        return merged

    def __exit__(self, *exc):
        _state.stack.pop()
        return False
