"""ONNX export (opset 13): structural round-trip + numeric agreement.

≙ the reference's ONNX test strategy (tests/python-pytest/onnx/: export a
model, run it in onnxruntime, compare outputs). Here the runtime half is the
bundled numpy evaluator (onnx/_runtime.py) since onnxruntime is not in the
image; a protoc --decode_raw round-trip additionally proves the wire format
is valid protobuf.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu import onnx as mxonnx
from incubator_mxnet_tpu.onnx import _runtime


def _export_and_run(net, x, tmp_path, name):
    path = str(tmp_path / f"{name}.onnx")
    mxonnx.export_model(net, x, path)
    ref = net(x).asnumpy()
    got = _runtime.run(path, {"data": x.asnumpy()})
    return path, ref, got


def test_export_mlp_numeric(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(8, activation="tanh"),
            gluon.nn.Dense(4))
    net.initialize()
    x = mx.np.array(np.random.RandomState(0).randn(2, 12).astype(np.float32))
    net(x)
    path, ref, got = _export_and_run(net, x, tmp_path, "mlp")
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    meta = mxonnx.get_model_metadata(path)
    assert meta["input_tensor_data"][0][1] == [2, 12]


def test_export_conv_bn_pool_numeric(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, strides=2, padding=1, layout="NHWC"),
            gluon.nn.BatchNorm(axis=3),
            gluon.nn.Activation("relu"),
            gluon.nn.MaxPool2D(2, layout="NHWC"),
            gluon.nn.GlobalAvgPool2D(layout="NHWC"),
            gluon.nn.Dense(5))
    net.initialize()
    x = mx.np.array(
        np.random.RandomState(1).randn(2, 16, 16, 3).astype(np.float32))
    net(x)  # init + freeze BN stats (inference mode at export)
    path, ref, got = _export_and_run(net, x, tmp_path, "convnet")
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_export_resnet18_numeric(tmp_path):
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1(layout="NHWC")
    net.initialize()
    x = mx.np.array(
        np.random.RandomState(2).randn(1, 64, 64, 3).astype(np.float32))
    net(x)
    path, ref, got = _export_and_run(net, x, tmp_path, "resnet18")
    assert got.shape == ref.shape == (1, 1000)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.skipif(shutil.which("protoc") is None,
                    reason="protoc not available")
def test_wire_format_is_valid_protobuf(tmp_path):
    net = gluon.nn.Dense(3)
    net.initialize()
    x = mx.np.array(np.ones((1, 4), np.float32))
    net(x)
    path = str(tmp_path / "m.onnx")
    mxonnx.export_model(net, x, path)
    with open(path, "rb") as f:
        r = subprocess.run(["protoc", "--decode_raw"], stdin=f,
                           capture_output=True, text=True)
    assert r.returncode == 0
    assert "7 {" in r.stdout          # GraphProto field present
    assert "8 {" in r.stdout          # opset_import present


def test_unsupported_primitive_raises(tmp_path):
    import jax.numpy as jnp

    def weird(x):
        return jnp.sort(x)            # 'sort' has no translation

    with pytest.raises(mx.MXNetError, match="no ONNX translation"):
        mxonnx.export_model(weird, np.ones((4,), np.float32),
                            str(tmp_path / "x.onnx"))


def test_export_isfinite_semantics(tmp_path):
    """is_finite must be false for ±inf AND NaN (a bare IsInf inverts it)."""
    def fn(x):
        import jax.numpy as jnp
        return jnp.isfinite(x).astype(jnp.float32)

    x = np.array([1.0, np.inf, -np.inf, np.nan, 0.0], np.float32)
    path = str(tmp_path / "fin.onnx")
    mxonnx.export_model(fn, x, path)
    got = _runtime.run(path, {"data": x})
    np.testing.assert_array_equal(got, [1.0, 0.0, 0.0, 0.0, 1.0])


def test_export_resnet50_numeric(tmp_path):
    """VERDICT-r3 Next #8: the flagship CNN exports (64px input keeps the
    numpy-evaluator runtime bounded; the graph is identical to 224px)."""
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize()
    x = mx.np.array(
        np.random.RandomState(5).randn(1, 64, 64, 3).astype(np.float32))
    net(x)
    path, ref, got = _export_and_run(net, x, tmp_path, "resnet50")
    assert got.shape == ref.shape == (1, 1000)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_export_embedding_gather(tmp_path):
    """Embedding exports as ONNX Gather (jax gather axis-pattern)."""
    net = gluon.nn.Embedding(30, 8)
    net.initialize()
    t = mx.np.array(np.array([[1, 5, 7], [2, 0, 29]], np.int32))
    net(t)
    ref = net(t).asnumpy()
    path = str(tmp_path / "emb.onnx")
    mxonnx.export_model(net, t, path)
    got = _runtime.run(path, {"data": t.asnumpy()})
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_export_lstm_lm_numeric(tmp_path):
    """VERDICT-r3 Next #8: the LSTM LM exports — Embedding (gather) +
    lax.scan (static unroll) + gate splits — and the numpy evaluator
    reproduces the source logits."""
    from incubator_mxnet_tpu.gluon import nn, rnn

    class LM(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(50, 16)
            self.lstm = rnn.LSTM(32, num_layers=1)
            self.out = nn.Dense(50, flatten=False)

        def forward(self, t):
            e = self.emb(t)
            h = self.lstm(e.transpose(1, 0, 2))
            return self.out(h.transpose(1, 0, 2))

    lm = LM()
    lm.initialize()
    t = mx.np.array(np.random.RandomState(3).randint(0, 50, (2, 12)))
    ref = lm(t).asnumpy()
    path = str(tmp_path / "lm.onnx")
    mxonnx.export_model(lm, t, path)
    got = _runtime.run(path, {"data": t.asnumpy()})
    assert got.shape == ref.shape == (2, 12, 50)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)


def test_export_scan_unroll_bound(tmp_path):
    import jax
    import jax.numpy as jnp

    def fn(x):
        return jax.lax.scan(lambda c, t: (c + t, c), x[0], x)[1]

    with pytest.raises(mx.MXNetError, match="unroll bound"):
        mxonnx.export_model(fn, np.ones((600, 4), np.float32),
                            str(tmp_path / "big.onnx"))
