"""ONNX export (opset 13): structural round-trip + numeric agreement.

≙ the reference's ONNX test strategy (tests/python-pytest/onnx/: export a
model, run it in onnxruntime, compare outputs). Here the runtime half is the
bundled numpy evaluator (onnx/_runtime.py) since onnxruntime is not in the
image; a protoc --decode_raw round-trip additionally proves the wire format
is valid protobuf.
"""
import os
import shutil
import subprocess

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu import onnx as mxonnx
from incubator_mxnet_tpu.onnx import _runtime


def _export_and_run(net, x, tmp_path, name):
    path = str(tmp_path / f"{name}.onnx")
    mxonnx.export_model(net, x, path)
    ref = net(x).asnumpy()
    got = _runtime.run(path, {"data": x.asnumpy()})
    return path, ref, got


def test_export_mlp_numeric(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu"),
            gluon.nn.Dense(8, activation="tanh"),
            gluon.nn.Dense(4))
    net.initialize()
    x = mx.np.array(np.random.RandomState(0).randn(2, 12).astype(np.float32))
    net(x)
    path, ref, got = _export_and_run(net, x, tmp_path, "mlp")
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    meta = mxonnx.get_model_metadata(path)
    assert meta["input_tensor_data"][0][1] == [2, 12]


def test_export_conv_bn_pool_numeric(tmp_path):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Conv2D(8, 3, strides=2, padding=1, layout="NHWC"),
            gluon.nn.BatchNorm(axis=3),
            gluon.nn.Activation("relu"),
            gluon.nn.MaxPool2D(2, layout="NHWC"),
            gluon.nn.GlobalAvgPool2D(layout="NHWC"),
            gluon.nn.Dense(5))
    net.initialize()
    x = mx.np.array(
        np.random.RandomState(1).randn(2, 16, 16, 3).astype(np.float32))
    net(x)  # init + freeze BN stats (inference mode at export)
    path, ref, got = _export_and_run(net, x, tmp_path, "convnet")
    np.testing.assert_allclose(got, ref, rtol=1e-3, atol=1e-4)


def test_export_resnet18_numeric(tmp_path):
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet18_v1(layout="NHWC")
    net.initialize()
    x = mx.np.array(
        np.random.RandomState(2).randn(1, 64, 64, 3).astype(np.float32))
    net(x)
    path, ref, got = _export_and_run(net, x, tmp_path, "resnet18")
    assert got.shape == ref.shape == (1, 1000)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


@pytest.mark.skipif(shutil.which("protoc") is None,
                    reason="protoc not available")
def test_wire_format_is_valid_protobuf(tmp_path):
    net = gluon.nn.Dense(3)
    net.initialize()
    x = mx.np.array(np.ones((1, 4), np.float32))
    net(x)
    path = str(tmp_path / "m.onnx")
    mxonnx.export_model(net, x, path)
    with open(path, "rb") as f:
        r = subprocess.run(["protoc", "--decode_raw"], stdin=f,
                           capture_output=True, text=True)
    assert r.returncode == 0
    assert "7 {" in r.stdout          # GraphProto field present
    assert "8 {" in r.stdout          # opset_import present


def test_unsupported_primitive_raises(tmp_path):
    import jax.numpy as jnp

    def weird(x):
        return jnp.sort(x)            # 'sort' has no translation

    with pytest.raises(mx.MXNetError, match="no ONNX translation"):
        mxonnx.export_model(weird, np.ones((4,), np.float32),
                            str(tmp_path / "x.onnx"))


def test_export_isfinite_semantics(tmp_path):
    """is_finite must be false for ±inf AND NaN (a bare IsInf inverts it)."""
    def fn(x):
        import jax.numpy as jnp
        return jnp.isfinite(x).astype(jnp.float32)

    x = np.array([1.0, np.inf, -np.inf, np.nan, 0.0], np.float32)
    path = str(tmp_path / "fin.onnx")
    mxonnx.export_model(fn, x, path)
    got = _runtime.run(path, {"data": x})
    np.testing.assert_array_equal(got, [1.0, 0.0, 0.0, 0.0, 1.0])


@pytest.mark.slow  # nightly-grade: full resnet50 export + runtime (~25s)
def test_export_resnet50_numeric(tmp_path):
    """VERDICT-r3 Next #8: the flagship CNN exports (64px input keeps the
    numpy-evaluator runtime bounded; the graph is identical to 224px)."""
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    net = vision.resnet50_v1(layout="NHWC")
    net.initialize()
    x = mx.np.array(
        np.random.RandomState(5).randn(1, 64, 64, 3).astype(np.float32))
    net(x)
    path, ref, got = _export_and_run(net, x, tmp_path, "resnet50")
    assert got.shape == ref.shape == (1, 1000)
    np.testing.assert_allclose(got, ref, rtol=2e-3, atol=2e-3)


def test_export_embedding_gather(tmp_path):
    """Embedding exports as ONNX Gather (jax gather axis-pattern)."""
    net = gluon.nn.Embedding(30, 8)
    net.initialize()
    t = mx.np.array(np.array([[1, 5, 7], [2, 0, 29]], np.int32))
    net(t)
    ref = net(t).asnumpy()
    path = str(tmp_path / "emb.onnx")
    mxonnx.export_model(net, t, path)
    got = _runtime.run(path, {"data": t.asnumpy()})
    np.testing.assert_allclose(got, ref, rtol=1e-6)


def test_export_lstm_lm_numeric(tmp_path):
    """VERDICT-r3 Next #8 + r4 Next #7: the LSTM LM exports — Embedding
    (gather) + lax.scan as a TRUE ONNX Loop (no static unroll) + gate
    splits — and the numpy evaluator reproduces the source logits."""
    from incubator_mxnet_tpu.gluon import nn, rnn

    class LM(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.emb = nn.Embedding(50, 16)
            self.lstm = rnn.LSTM(32, num_layers=1)
            self.out = nn.Dense(50, flatten=False)

        def forward(self, t):
            e = self.emb(t)
            h = self.lstm(e.transpose(1, 0, 2))
            return self.out(h.transpose(1, 0, 2))

    lm = LM()
    lm.initialize()
    t = mx.np.array(np.random.RandomState(3).randint(0, 50, (2, 12)))
    ref = lm(t).asnumpy()
    path = str(tmp_path / "lm.onnx")
    mxonnx.export_model(lm, t, path)
    got = _runtime.run(path, {"data": t.asnumpy()})
    assert got.shape == ref.shape == (2, 12, 50)
    np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)
    # WITHOUT unroll: one Loop node, and the graph does not scale with
    # sequence length (an unrolled T=12 LSTM would emit hundreds of nodes)
    g = _runtime.load_graph(path)
    loops = [n for n in g.nodes if n.op == "Loop"]
    assert len(loops) == 1
    assert len(g.nodes) < 60, f"{len(g.nodes)} nodes — looks unrolled"


def test_export_long_scan_as_loop(tmp_path):
    """r4's 512-step unroll bound is gone: a 600-step scan exports as a
    dynamic Loop and evaluates correctly (carry AND ys outputs)."""
    import jax

    def fn(x):
        return jax.lax.scan(lambda c, t: (c + t, c * 2), x[0], x)[1]

    x = np.random.RandomState(0).rand(600, 4).astype(np.float32)
    path = str(tmp_path / "big.onnx")
    mxonnx.export_model(fn, x, path)
    got = _runtime.run(path, {"data": x})
    want = np.asarray(fn(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)
    g = _runtime.load_graph(path)
    assert sum(1 for n in g.nodes if n.op == "Loop") == 1


def test_export_zero_length_scan(tmp_path):
    """Loop with trip count 0 yields an empty scan output, not a crash."""
    import jax

    def fn(x):
        c, ys = jax.lax.scan(lambda c, t: (c + t, c * 3), x.sum(0), x)
        return ys

    x = np.zeros((0, 4), np.float32)
    path = str(tmp_path / "zero.onnx")
    mxonnx.export_model(fn, x, path)
    got = _runtime.run(path, {"data": x})
    assert got.shape == (0, 4)


def test_detection_metadata_lists_all_outputs(tmp_path):
    """Multi-output graphs: metadata reports every output, and the NMS
    row count is a dim_param (dynamic), not a bogus fixed 0."""
    import jax

    def fn(x):
        return x + 1, x * 2

    x = np.ones((2, 3), np.float32)
    path = str(tmp_path / "multi.onnx")
    mxonnx.export_model(fn, x, path)
    meta = mxonnx.get_model_metadata(path)
    assert [n for n, _ in meta["output_tensor_data"]] == ["output",
                                                         "output1"]
    a, b = _runtime.run(path, {"data": x})
    np.testing.assert_allclose(a, x + 1)
    np.testing.assert_allclose(b, x * 2)


def test_export_cond_as_if(tmp_path):
    """lax.cond exports as ONNX If with branch subgraphs capturing the
    operands from outer scope; both branch outcomes evaluate correctly."""
    import jax

    def fn(x):
        return jax.lax.cond(x.sum() > 0, lambda o: o * 2.0,
                            lambda o: o - 1.0, x)

    path = str(tmp_path / "if.onnx")
    mxonnx.export_model(fn, np.ones((3,), np.float32), path)
    g = _runtime.load_graph(path)
    assert sum(1 for n in g.nodes if n.op == "If") == 1
    for x in (np.ones((3,), np.float32), -np.ones((3,), np.float32)):
        got = _runtime.run(path, {"data": x})
        np.testing.assert_allclose(got, np.asarray(fn(x)), rtol=1e-6)


def test_export_while_loop(tmp_path):
    """lax.while_loop exports as a cond-driven ONNX Loop (no trip
    limit); the iteration count is data-dependent at runtime."""
    import jax

    def fn(x):
        c = jax.lax.while_loop(lambda c: c[0] < 10.0,
                               lambda c: (c[0] + 1.0, c[1] * 1.5),
                               (x.sum(), x))
        return c[1]

    path = str(tmp_path / "while.onnx")
    mxonnx.export_model(fn, np.full((3,), 0.5, np.float32), path)
    g = _runtime.load_graph(path)
    loops = [n for n in g.nodes if n.op == "Loop"]
    assert len(loops) == 1 and loops[0].inputs[0] == ""  # no trip limit
    for fill in (0.5, -2.0, 20.0):   # 9, 12, and 0 iterations
        x = np.full((3,), fill, np.float32)
        got = _runtime.run(path, {"data": x})
        np.testing.assert_allclose(got, np.asarray(fn(x)), rtol=1e-5)


def test_export_npx_control_flow(tmp_path):
    """The npx control-flow surface (while_loop here) rides the same
    export path when traced through a gluon block."""
    from incubator_mxnet_tpu import npx

    class Pow(gluon.HybridBlock):
        def forward(self, x):
            _, states = npx.while_loop(
                lambda i, acc: i < 4,
                lambda i, acc: (i + 1, acc * x),
                (mx.np.array(0), mx.np.ones((2,))))
            return states[1]

    net = Pow()
    x = mx.np.array(np.array([1.1, 0.9], np.float32))
    ref = net(x).asnumpy()
    path = str(tmp_path / "npxwhile.onnx")
    mxonnx.export_model(net, x, path)
    got = _runtime.run(path, {"data": x.asnumpy()})
    np.testing.assert_allclose(got, ref, rtol=1e-5)


def test_export_reverse_scan_as_loop(tmp_path):
    import jax

    def fn(x):
        c, ys = jax.lax.scan(lambda c, t: (c + t, c + 0.5 * t), x[0], x,
                             reverse=True)
        return ys

    x = np.random.RandomState(1).rand(7, 3).astype(np.float32)
    path = str(tmp_path / "rev.onnx")
    mxonnx.export_model(fn, x, path)
    got = _runtime.run(path, {"data": x})
    np.testing.assert_allclose(got, np.asarray(fn(x)), rtol=1e-5,
                               atol=1e-6)


def test_export_detection_model_roundtrip(tmp_path):
    """r4 Next #7: a detection graph (SSD-preset contract) exports with a
    real ONNX NonMaxSuppression node and the bundled evaluator's kept
    detections match npx.multibox_detection's valid rows."""
    from incubator_mxnet_tpu import npx
    from incubator_mxnet_tpu.gluon import nn
    import incubator_mxnet_tpu.numpy as mxnp

    class TinySSD(gluon.HybridBlock):
        """Two tiny feature maps -> multibox_prior anchors + heads,
        forward() returning the (anchors, cls_preds, loc_preds) SSD
        contract."""

        def __init__(self, classes=3, na=2):
            super().__init__()
            self._classes, self._na = classes, na
            self.stem = nn.Conv2D(8, 3, padding=1)
            self.down = nn.Conv2D(8, 3, strides=2, padding=1)
            self.cls1 = nn.Conv2D(na * (classes + 1), 1)
            self.loc1 = nn.Conv2D(na * 4, 1)
            self.cls2 = nn.Conv2D(na * (classes + 1), 1)
            self.loc2 = nn.Conv2D(na * 4, 1)

        def _flat(self, p, per):
            p = p.transpose(0, 2, 3, 1)
            return p.reshape(p.shape[0], -1, per)

        def forward(self, x):
            f1 = self.stem(x)
            f2 = self.down(f1)
            anchors = mxnp.concatenate(
                [npx.multibox_prior(f1, sizes=(0.4, 0.6), ratios=(1.0,)),
                 npx.multibox_prior(f2, sizes=(0.7,), ratios=(1.0, 2.0))],
                axis=1)
            cls = mxnp.concatenate(
                [self._flat(self.cls1(f1), self._classes + 1),
                 self._flat(self.cls2(f2), self._classes + 1)], axis=1)
            loc = mxnp.concatenate(
                [self._flat(self.loc1(f1), 4),
                 self._flat(self.loc2(f2), 4)], axis=1)
            return anchors, cls, loc.reshape(loc.shape[0], -1)

    net = TinySSD()
    net.initialize()
    x = mx.np.array(np.random.RandomState(0).rand(1, 3, 16, 16)
                    .astype(np.float32))
    net(x)
    path = str(tmp_path / "ssd.onnx")
    mxonnx.export_detection_model(net, x, path, nms_threshold=0.45,
                                  score_threshold=0.1)
    g = _runtime.load_graph(path)
    assert any(n.op == "NonMaxSuppression" for n in g.nodes)
    boxes, scores, selected = _runtime.run(path, {"data": x.asnumpy()})

    # reference detections from the framework's own multibox pipeline
    anchors, cls_preds, loc_preds = net(x)
    probs = npx.softmax(cls_preds, axis=-1).transpose(0, 2, 1)
    ref = npx.multibox_detection(
        probs, loc_preds, anchors, nms_threshold=0.45,
        threshold=0.1).asnumpy()[0]
    ref_kept = ref[ref[:, 0] >= 0]

    got = np.array(sorted(
        ([float(c), float(scores[b, c, k]), *boxes[b, k]]
         for b, c, k in selected), key=lambda r: -r[1]), np.float64)
    assert got.shape == ref_kept.shape, (got.shape, ref_kept.shape)
    np.testing.assert_allclose(got[:, 1], ref_kept[:, 1], rtol=1e-4)
    np.testing.assert_allclose(got[:, 2:], ref_kept[:, 2:], rtol=1e-3,
                               atol=1e-4)
    np.testing.assert_array_equal(got[:, 0], ref_kept[:, 0])


def test_export_switch_as_nested_if(tmp_path):
    """lax.switch (N=3 branches) exports as a nested-If chain; every
    branch and the clamp-at-bounds behavior round-trip."""
    import jax

    def fn(x):
        idx = jax.numpy.clip(x[0].astype(jax.numpy.int32), 0, 2)
        return jax.lax.switch(idx, [lambda o: o + 1.0,
                                    lambda o: o * 3.0,
                                    lambda o: -o], x)

    path = str(tmp_path / "switch.onnx")
    mxonnx.export_model(fn, np.zeros((3,), np.float32), path)
    for lead in (0.0, 1.0, 2.0, 7.0):   # 7 clamps to branch 2
        x = np.array([lead, 4.0, 5.0], np.float32)
        got = _runtime.run(path, {"data": x})
        np.testing.assert_allclose(got, np.asarray(fn(x)), rtol=1e-6)
