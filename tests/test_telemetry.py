"""mx.telemetry — unified metrics registry, step-timeline attribution, and
the hermetic bench runner (ISSUE 6).

Covers: counter/gauge/histogram semantics under an 8-thread hammer,
snapshot(reset) conservation, Prometheus exposition golden text, span
nesting + Chrome-trace round-trip, MFU against a hand-counted matmul,
legacy *_stats() shim parity (keys + reset semantics, registry-backed),
StepTimeline data-stall attribution, the /metrics endpoint, per-phase
bench subprocess isolation incl. the BENCH_r04 dtype crash class, and
benchdiff regression/ok/missing-file exits.
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import profiler, telemetry
from incubator_mxnet_tpu.telemetry.registry import Registry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------
def test_counter_gauge_histogram_semantics():
    reg = Registry()
    c = reg.counter("t.hits", help="hits")
    c.inc()
    c.inc(4)
    assert c.get() == 5
    with pytest.raises(ValueError):
        c.inc(-1)                      # counters are monotonic
    g = reg.gauge("t.depth")
    g.set(7)
    g.dec(2)
    assert g.get() == 5.0
    h = reg.histogram("t.lat_us", buckets=(10, 100))
    for v in (5, 50, 500):
        h.observe(v)
    s = h.get()
    assert s["count"] == 3 and s["sum"] == 555.0
    assert s["min"] == 5.0 and s["max"] == 500.0
    assert s["buckets"] == [1, 1, 1]   # <=10, <=100, +Inf


def test_registry_type_collision_is_an_error():
    reg = Registry()
    reg.counter("t.x")
    with pytest.raises(ValueError):
        reg.gauge("t.x")
    c = reg.counter("t.y", labels=("op",))
    with pytest.raises(ValueError):
        reg.counter("t.y")             # same name, different labels
    with pytest.raises(ValueError):
        c.labels(wrong="k")


def test_labeled_metrics_key_independently():
    reg = Registry()
    c = reg.counter("t.by_op", labels=("op",))
    c.labels(op="add").inc(2)
    c.labels(op="mul").inc(3)
    snap = reg.snapshot()
    assert snap['t.by_op{op="add"}'] == 2
    assert snap['t.by_op{op="mul"}'] == 3


def test_eight_thread_hammer_exact_counts():
    """8 threads x 1000 increments each on counter + histogram + a
    StatsGroup: exact totals — the one-lock discipline loses nothing."""
    reg = Registry()
    c = reg.counter("t.hammer")
    h = reg.histogram("t.hammer_lat")
    grp = reg.stats_group("hammer", {"hits": 0})
    N, T = 1000, 8
    barrier = threading.Barrier(T)

    def work():
        barrier.wait()
        for _ in range(N):
            c.inc()
            h.observe(1.0)
            with grp._owner_lock:
                grp["hits"] += 1

    threads = [threading.Thread(target=work) for _ in range(T)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.get() == N * T
    assert h.get()["count"] == N * T
    assert grp.snapshot()["hits"] == N * T


def test_snapshot_reset_conservation():
    """Windowed snapshot(reset=True) reads sum to the un-windowed total:
    no increment is lost between copy and zero, and gauges (levels)
    survive the reset."""
    reg = Registry()
    c = reg.counter("t.flow")
    g = reg.gauge("t.level")
    g.set(42)
    grp = reg.stats_group("win", {"n": 0})
    total, seen = 600, 0
    stop = threading.Event()

    def incs():
        for _ in range(total):
            c.inc()
            with grp._owner_lock:
                grp["n"] += 1
        stop.set()

    t = threading.Thread(target=incs)
    t.start()
    while not stop.is_set():
        s = reg.snapshot(reset=True)
        seen += s["t.flow"] + s["win.n"]
    t.join()
    s = reg.snapshot(reset=True)
    seen += s["t.flow"] + s["win.n"]
    assert seen == 2 * total
    assert reg.snapshot()["t.level"] == 42.0   # gauge kept its level


def test_prometheus_exposition_golden():
    reg = Registry()
    c = reg.counter("demo.hits", help="demo hits")
    c.inc(3)
    g = reg.gauge("demo.depth")
    g.set(2)
    h = reg.histogram("demo.lat_us", labels=("op",), buckets=(10.0, 100.0))
    h.labels(op="add").observe(5)
    h.labels(op="add").observe(50)
    grp = reg.stats_group("demo_grp", {"k": 0}, help="demo group")
    with grp._owner_lock:
        grp["k"] += 7
    assert reg.prometheus_text() == """\
# TYPE mx_demo_depth gauge
mx_demo_depth 2
# HELP mx_demo_hits demo hits
# TYPE mx_demo_hits counter
mx_demo_hits 3
# TYPE mx_demo_lat_us histogram
mx_demo_lat_us_bucket{op="add",le="10"} 1
mx_demo_lat_us_bucket{op="add",le="100"} 2
mx_demo_lat_us_bucket{op="add",le="+Inf"} 2
mx_demo_lat_us_sum{op="add"} 55
mx_demo_lat_us_count{op="add"} 2
# HELP mx_demo_grp demo group
mx_demo_grp_k 7
"""


def test_snapshot_json_round_trips():
    reg = Registry()
    reg.counter("t.a").inc()
    assert json.loads(reg.snapshot_json()) == {"t.a": 1.0}


# ---------------------------------------------------------------------------
# legacy shim parity: keys and reset semantics, registry-backed
# ---------------------------------------------------------------------------
def test_dispatch_stats_shim_parity():
    from incubator_mxnet_tpu.ops import segment
    profiler.dispatch_stats(reset=True)
    x = mx.np.ones((4, 4))
    (x * 2 + 1).asnumpy()
    s = profiler.dispatch_stats()
    assert set(s) == set(segment.DISPATCH_STATS)
    assert s["dispatch"] >= 1
    # the SAME counters through the registry pane
    assert telemetry.snapshot()["dispatch.dispatch"] == s["dispatch"]
    # reset zeroes both views atomically
    profiler.dispatch_stats(reset=True)
    assert profiler.dispatch_stats()["dispatch"] == 0
    assert telemetry.snapshot()["dispatch.dispatch"] == 0


def test_serve_and_feed_stats_shim_parity():
    from incubator_mxnet_tpu.io.device_feed import FEED_STATS
    from incubator_mxnet_tpu.serve.metrics import SERVE_STATS
    sv = profiler.serve_stats()
    assert set(sv) == set(SERVE_STATS)
    fd = profiler.feed_stats()
    assert set(fd) == set(FEED_STATS) | {"occupancy_mean"}
    # registry carries both groups under their family prefixes
    snap = telemetry.snapshot()
    assert all(f"serve.{k}" in snap for k in SERVE_STATS)
    assert all(f"feed.{k}" in snap for k in FEED_STATS)
    # reset-window conservation through the shim (the old hand-rolled
    # semantics, now StatsGroup.snapshot)
    base = profiler.serve_stats(reset=True)  # noqa: F841  (zero the window)
    SERVE_STATS.snapshot(reset=True)
    from incubator_mxnet_tpu.serve.metrics import ServeMetrics
    m = ServeMetrics()
    m.observe_batch(bucket=2, occupancy=2, exec_ms=1.0, queue_depth=0)
    win = profiler.serve_stats(reset=True)
    assert win["batches"] == 1
    assert profiler.serve_stats()["batches"] == 0


# ---------------------------------------------------------------------------
# spans
# ---------------------------------------------------------------------------
def test_span_nesting_and_chrome_trace_round_trip(tmp_path):
    profiler._events.clear()
    profiler.start()
    try:
        with telemetry.span("outer.step", step=1):
            assert telemetry.current_span() == "outer.step"
            with telemetry.span("inner.op"):
                assert telemetry.current_span() == "inner.op"
                time.sleep(0.001)
        assert telemetry.current_span() is None
    finally:
        profiler.stop()
    path = str(tmp_path / "trace.json")
    profiler.dump(filename=path)
    with open(path) as f:
        trace = json.load(f)
    by_name = {e["name"]: e for e in trace["traceEvents"]}
    assert "outer.step" in by_name and "inner.op" in by_name
    # nesting recorded: the child carries its parent's name
    assert by_name["inner.op"]["args"]["parent"] == "outer.step"
    assert by_name["outer.step"]["args"]["step"] == 1
    # the child's window is inside the parent's
    o, i = by_name["outer.step"], by_name["inner.op"]
    assert o["ts"] <= i["ts"] and i["ts"] + i["dur"] <= o["ts"] + o["dur"]
    # registry aggregates ride along in the trace artifact
    tele = trace["otherData"]["telemetry"]
    assert tele['span.count{name="inner.op"}'] >= 1
    # and the span histograms exist under their registered names
    snap = telemetry.snapshot()
    assert 'span.duration_us{name="outer.step"}' in snap
    assert snap['span.duration_us{name="inner.op"}']["count"] >= 1


def test_span_metric_names_registered():
    # the two object metrics of the span layer (lint: metric catalog)
    names = telemetry.REGISTRY.names()
    assert "span.duration_us" in names
    assert "span.count" in names


def test_record_event_timestamps_monotonic_across_threads():
    """_now_us is one process-wide monotonic clock: events recorded
    after a cross-thread join can never carry earlier timestamps."""
    assert profiler._now_us() == pytest.approx(
        time.perf_counter_ns() // 1000, abs=200000)
    stamps = []

    def worker():
        stamps.append(profiler._now_us())

    t0 = profiler._now_us()
    th = threading.Thread(target=worker)
    th.start()
    th.join()
    t1 = profiler._now_us()
    assert t0 <= stamps[0] <= t1


def test_spans_disabled_by_env(monkeypatch):
    monkeypatch.setenv("MXNET_TELEMETRY", "0")
    telemetry.trace._expire_env_memo()   # the knob is TTL-cached (50ms)
    before = telemetry.snapshot().get('span.count{name="off.span"}', 0)
    with telemetry.span("off.span"):
        pass
    after = telemetry.snapshot().get('span.count{name="off.span"}', 0)
    assert after == before


def test_profiler_dumps_includes_telemetry_sections():
    telemetry.REGISTRY.counter("t.dumps_probe").inc(3)
    with telemetry.span("dumps.span"):
        pass
    table = profiler.dumps()
    assert "Span (telemetry)" in table
    assert "Telemetry metric" in table
    assert "t.dumps_probe" in table
    j = json.loads(profiler.dumps(format="json"))
    assert j["telemetry"]["t.dumps_probe"] == 3.0


# ---------------------------------------------------------------------------
# MFU: XLA-counted flops vs hand math
# ---------------------------------------------------------------------------
def test_model_flops_matches_hand_counted_matmul():
    import jax.numpy as jnp
    m, k, n = 32, 64, 16
    a = jnp.ones((m, k), jnp.float32)
    b = jnp.ones((k, n), jnp.float32)
    fl = telemetry.model_flops(lambda x, y: x @ y, a, b)
    assert fl == pytest.approx(2 * m * k * n, rel=0.01)  # MAC = 2 flops
    # memoized: the second call is a dict hit (same id + avals)
    assert telemetry.model_flops(lambda x, y: x @ y, a, b) >= 0  # no crash


def test_block_fwd_flops_dense_net_within_10pct_of_hand_math():
    from incubator_mxnet_tpu import gluon
    bs, din, dout = 16, 32, 64
    net = gluon.nn.Dense(dout, in_units=din)
    net.initialize()
    x = mx.np.array(np.random.rand(bs, din).astype(np.float32))
    net(x)
    hand = 2 * bs * din * dout + bs * dout    # matmul + bias add
    xla = telemetry.block_fwd_flops(net, x)
    assert abs(xla - hand) / hand < 0.10


def test_steptimeline_mfu_and_stall_attribution():
    """A loop fed by a deliberately slow source: the timeline's
    data_stall dominates, and the reported MFU equals hand math from the
    same counters within 10%."""
    from incubator_mxnet_tpu.io import DeviceFeed

    def slow_source():
        for i in range(4):
            time.sleep(0.02)          # the feed can't keep up
            yield np.full((4, 4), i, np.float32)

    flops = 1e6
    peak = 1e9
    tl = telemetry.StepTimeline(flops_per_step=flops, peak_flops=peak)
    for batch in DeviceFeed(slow_source(), depth=1):
        with tl.step():
            float(np.asarray(batch.asnumpy()).sum())
    rep = tl.report()
    assert rep["steps"] == 4
    assert rep["data_stall_us"] > 0
    assert 0 < rep["stall_pct"] <= 100
    assert rep["compute_us"] == pytest.approx(
        rep["total_us"] - rep["data_stall_us"] - rep["allreduce_us"],
        abs=1.0)
    hand_mfu = flops * rep["steps"] / (rep["total_us"] * 1e-6) / peak
    assert rep["mfu"] == pytest.approx(hand_mfu, rel=0.10)
    # the feeder-side staging clock advanced too (overlapped H2D lane)
    assert profiler.feed_stats()["stage_us"] > 0


def test_estimator_fit_reports_step_timeline_with_live_mfu():
    """Acceptance: an estimator train run reports a step timeline with
    data-stall vs compute attribution and a live-counter MFU within 10%
    of the hand-computed value."""
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        Estimator, StepTimelineHandler)
    bs, din, dout = 8, 16, 10
    net = gluon.nn.Dense(dout, in_units=din)
    net.initialize()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.np.array(np.random.rand(bs, din).astype(np.float32))
    y = mx.np.array(np.random.randint(0, dout, (bs,)))
    data = [(x, y)] * 3
    hand_fwd = 2 * bs * din * dout + bs * dout
    peak = 1e9
    est = Estimator(net, loss, train_metrics=gluon.metric.Accuracy())
    est.fit(data, epochs=1, event_handlers=[
        StepTimelineHandler(flops_per_batch=3 * hand_fwd,
                            peak_flops=peak)])
    rep = est.step_timeline
    assert rep is not None and rep["steps"] == 3
    for key in ("data_stall_us", "compute_us", "stall_pct", "compute_pct",
                "h2d_stage_us", "allreduce_us"):
        assert key in rep
    hand_mfu = (3 * hand_fwd) * rep["steps"] / (rep["total_us"] * 1e-6) \
        / peak
    assert rep["mfu"] == pytest.approx(hand_mfu, rel=0.10)
    # auto_flops path: XLA-counts the forward on the first batch
    est2 = Estimator(net, loss, train_metrics=gluon.metric.Accuracy())
    est2.fit(data, epochs=1, event_handlers=[
        StepTimelineHandler(auto_flops=True, peak_flops=peak)])
    rep2 = est2.step_timeline
    assert rep2["mfu"] == pytest.approx(
        3 * telemetry.block_fwd_flops(net, x) * rep2["steps"]
        / (rep2["total_us"] * 1e-6) / peak, rel=0.10)


def test_fused_step_flops_per_call_counts_fwd_bwd_update():
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.contrib import FusedTrainStep
    bs, din, dout = 8, 16, 10
    net = gluon.nn.Dense(dout, in_units=din)
    net.initialize()
    loss = gluon.loss.SoftmaxCrossEntropyLoss()
    x = mx.np.array(np.random.rand(bs, din).astype(np.float32))
    y = mx.np.array(np.random.randint(0, dout, (bs,)))
    net(x)
    step = FusedTrainStep(net, lambda n, a, b: loss(n(a), b).sum(), "sgd")
    fl = step.flops_per_call(x, y)
    fwd = 2 * bs * din * dout
    # fwd + bwd(2x fwd-class matmuls) + update: at least 2x the forward,
    # bounded by a generous 6x (loss/softmax/update overheads ride along)
    assert 2 * fwd <= fl <= 6 * fwd + 1e4


def test_kvstore_allreduce_timings_feed_the_registry():
    from incubator_mxnet_tpu.kvstore import KV_STATS, create
    kv = create("local")
    base = dict(KV_STATS.snapshot())
    many = kv._cross_process_sum_many(
        [mx.np.ones((64,)), mx.np.ones((32,))])
    assert len(many) == 2
    snap = KV_STATS.snapshot()
    assert snap["allreduce_us"] > base["allreduce_us"]
    assert snap["allreduce_buckets"] > base["allreduce_buckets"]
    assert snap["allreduce_bytes"] >= base["allreduce_bytes"] + (64 + 32) * 4
    # the same clock surfaces through the registry pane
    assert telemetry.snapshot()["kvstore.allreduce_us"] == \
        snap["allreduce_us"]


# ---------------------------------------------------------------------------
# serve: request timeline + /metrics
# ---------------------------------------------------------------------------
def test_server_timeline_and_metrics_text():
    import jax.numpy as jnp
    from incubator_mxnet_tpu import serve
    W = np.linspace(-1, 1, 6).reshape(3, 2).astype(np.float32)
    model = serve.CallableModel(lambda x: jnp.tanh(x @ W), (1, 2),
                                [((3,), "float32")])
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        for _ in range(4):
            srv.predict(np.ones(3, np.float32))
        tl = srv.timeline()
        assert tl["exec_ms"] > 0
        assert tl["queue_wait_ms"] >= 0
        assert tl["queue_wait_pct"] + tl["exec_pct"] == pytest.approx(
            100.0, abs=0.1)
        text = srv.metrics_text()
    assert "# TYPE mx_span_duration_us histogram" in text
    assert "mx_serve_batches" in text                 # process group
    assert 'mx_server_queue_depth{server="serve"}' in text
    assert "mx_server_exec_ms_total" in text


def test_metrics_http_endpoint():
    import urllib.request
    srv = telemetry.start_metrics_server(0)
    try:
        port = srv.server_address[1]
        body = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics").read().decode()
        assert "# TYPE mx_span_duration_us histogram" in body
        js = json.loads(urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics.json").read().decode())
        assert "dispatch.dispatch" in js
        with pytest.raises(Exception):
            urllib.request.urlopen(f"http://127.0.0.1:{port}/nope")
    finally:
        srv.shutdown()


# ---------------------------------------------------------------------------
# hermetic bench runner
# ---------------------------------------------------------------------------
def _run_bench(args, env_extra=None, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu", **(env_extra or {}))
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py")] + args,
        capture_output=True, text=True, timeout=timeout, cwd=REPO, env=env)
    line = r.stdout.strip().splitlines()[-1]
    return r.returncode, json.loads(line)


def test_bench_quick_dispatch_subprocess_smoke():
    """Tier-1 smoke: the per-phase subprocess runner end to end on the
    cheapest phase — preflight records backend_ok, the phase lands, and
    its registry snapshot rides along."""
    rc, out = _run_bench(["--quick", "--phases", "dispatch"])
    assert rc == 0
    assert out["backend_ok"] is True
    assert out["per_dispatch_latency_us_sync"] > 0
    assert out["per_dispatch_latency_us_chained"] > 0
    assert "phase_errors" not in out
    assert "dispatch.dispatch" in out["phase_telemetry"]["dispatch"] or \
        out["phase_telemetry"]["dispatch"]   # snapshot shipped


def test_bench_phase_crash_yields_partial_results():
    """Acceptance: a forced crash (the BENCH_r04 dtype class, fault-
    injected) in one phase still produces a JSON line with that phase
    marked `error` and the other phases populated."""
    rc, out = _run_bench(
        ["--quick", "--phases", "dispatch,eager"],
        env_extra={"MXNET_BENCH_FAULT_PHASE": "eager:dtype"})
    assert rc == 0
    assert out["backend_ok"] is True
    assert out["per_dispatch_latency_us_sync"] > 0      # dispatch landed
    assert "bfloat16" in out["phase_errors"]["eager"]   # dtype class
    assert "TypeError" in out["phase_errors"]["eager"]


def test_bench_phase_hard_exit_is_contained():
    """A phase that dies without a traceback (os._exit) is still just one
    phase_errors entry."""
    rc, out = _run_bench(
        ["--quick", "--phases", "dispatch,eager"],
        env_extra={"MXNET_BENCH_FAULT_PHASE": "eager:exit"})
    assert rc == 0
    assert out["per_dispatch_latency_us_sync"] > 0
    assert "eager" in out["phase_errors"]


def test_bench_phase_timeout_kills_only_that_phase():
    rc, out = _run_bench(
        ["--quick", "--phases", "eager,dispatch"],
        env_extra={"MXNET_BENCH_FAULT_PHASE": "eager:hang",
                   "MXNET_BENCH_PHASE_TIMEOUT": "15"})
    assert rc == 0
    assert "TimeoutOrKilled" in out["phase_errors"]["eager"]
    assert out["per_dispatch_latency_us_sync"] > 0


def test_bench_single_phase_child_contract():
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--phase", "dispatch", "--quick"],
        capture_output=True, text=True, timeout=300, cwd=REPO, env=env)
    assert r.returncode == 0
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["phase"] == "dispatch" and out["ok"] is True
    assert out["result"]["per_dispatch_latency_us_sync"] > 0
    # unknown phase: rc 2, structured error
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--phase", "nope"],
        capture_output=True, text=True, timeout=60, cwd=REPO, env=env)
    assert r.returncode == 2
    assert json.loads(r.stdout.strip().splitlines()[-1])["ok"] is False


# ---------------------------------------------------------------------------
# benchdiff
# ---------------------------------------------------------------------------
def _benchdiff(args, timeout=120):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "benchdiff.py")]
        + args, capture_output=True, text=True, timeout=timeout, cwd=REPO)


def test_benchdiff_self_test_passes():
    """Tier-1 smoke: the committed synthetic behavior check."""
    r = _benchdiff(["--self-test"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "FAIL" not in r.stdout


def test_benchdiff_exit_codes(tmp_path):
    ok = {"backend_ok": True, "value": 1000.0,
          "serve_requests_per_sec_c32": 50.0}
    reg = dict(ok, value=800.0)                      # -20% regression
    for name, payload in (("BENCH_r01.json", ok), ("BENCH_r02.json", reg)):
        with open(tmp_path / name, "w") as f:
            json.dump(payload, f)
    r = _benchdiff(["--dir", str(tmp_path)])
    assert r.returncode == 1
    assert "REGRESSION value" in r.stdout
    # same rounds, ok direction
    with open(tmp_path / "BENCH_r03.json", "w") as f:
        json.dump(dict(ok, value=990.0), f)
    r = _benchdiff(["--old", str(tmp_path / "BENCH_r02.json"),
                    "--new", str(tmp_path / "BENCH_r03.json")])
    assert r.returncode == 0
    # missing files
    r = _benchdiff(["--dir", str(tmp_path / "empty")])
    assert r.returncode == 2
    r = _benchdiff(["--old", "/nonexistent.json",
                    "--new", "/nonexistent.json"])
    assert r.returncode == 2


def test_benchdiff_dead_backend_is_skipped_not_failed(tmp_path):
    ok = {"backend_ok": True, "value": 1000.0}
    dead = {"backend_ok": False, "value": 0.0, "error": "backend dead"}
    for name, payload in (("BENCH_r01.json", ok), ("BENCH_r02.json", dead)):
        with open(tmp_path / name, "w") as f:
            json.dump(payload, f)
    r = _benchdiff(["--dir", str(tmp_path), "--json"])
    assert r.returncode == 0
    rep = json.loads(r.stdout)
    assert rep["status"] == "skipped"
    assert rep["reason"] == "backend_dead_new"


def test_benchdiff_compares_committed_trend_rounds():
    """The real repo trend: r04 (no JSON) / r05 (dead backend) must read
    as skipped — the exact false-signal classes this tool exists for."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import benchdiff
    finally:
        sys.path.pop(0)
    rounds = benchdiff.find_rounds(REPO)
    assert len(rounds) >= 5
    r4 = benchdiff.load_round(os.path.join(REPO, "BENCH_r04.json"))
    assert benchdiff.backend_dead(r4)
    r5 = benchdiff.load_round(os.path.join(REPO, "BENCH_r05.json"))
    assert benchdiff.backend_dead(r5)
    r3 = benchdiff.load_round(os.path.join(REPO, "BENCH_r03.json"))
    assert not benchdiff.backend_dead(r3)
    rep = benchdiff.compare(r3, r5)
    assert rep["status"] == "skipped"
