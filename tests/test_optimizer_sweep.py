"""Optimizer sweep matrix (VERDICT-r1 Weak #8: the reference sweeps every
optimizer across dtype/mp/fused dimensions — tests/python/unittest/
test_optimizer.py). Each registered optimizer is exercised:

  * basic descent: a convex quadratic's loss must drop
  * fused vs unfused: the multi-tensor fused path must match per-param
  * multi-precision: fp16 weights with fp32 master copies must step
  * lr schedulers compose with updates
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu.ndarray import NDArray

ALL_OPTS = sorted(opt_mod._REGISTRY)


def _quadratic_step_all(opt, n_steps=12, dtype="float32"):
    """Minimize sum((w - 3)^2) over two parameter tensors with the
    per-param update path; returns (first_loss, last_loss, weights)."""
    mx.seed(0)
    ws = [mx.np.array(np.full((4, 3), 0.0, dtype)),
          mx.np.array(np.zeros((7,), dtype))]
    states = [opt.create_state_multi_precision(i, w)
              for i, w in enumerate(ws)]
    losses = []
    for _ in range(n_steps):
        loss = sum(float(((w.astype("float32") - 3.0) ** 2)
                         .sum().asnumpy()) for w in ws)
        losses.append(loss)
        grads = [(2.0 * (w.astype("float32") - 3.0)).astype(w.dtype)
                 for w in ws]
        for i, (w, g) in enumerate(zip(ws, grads)):
            opt.update_multi_precision(i, w, g, states[i])
    return losses[0], losses[-1], ws


# trust-ratio (lamb/lans) and accumulated-delta (adadelta) rules take tiny
# first steps on a zero-init quadratic — they descend, just slowly
_SLOW = {"lamb", "lans", "adadelta"}


def _floor(name, strong):
    return 0.999 if name in _SLOW else strong


@pytest.mark.parametrize("name", ALL_OPTS)
def test_optimizer_descends(name):
    opt = opt_mod.create(name, learning_rate=0.05)
    first, last, _ = _quadratic_step_all(opt)
    assert last < first * _floor(name, 0.9), f"{name}: {first} -> {last}"


@pytest.mark.parametrize("name", ALL_OPTS)
def test_optimizer_multi_precision(name):
    opt = opt_mod.create(name, learning_rate=0.05, multi_precision=True)
    first, last, ws = _quadratic_step_all(opt, dtype="float16")
    assert last < first * _floor(name, 0.95), f"{name}: {first} -> {last}"
    for w in ws:
        assert str(w.dtype) == "float16"


@pytest.mark.parametrize(
    "name", [n for n in ALL_OPTS
             if opt_mod._REGISTRY[n]._fused_safe])
def test_fused_matches_unfused(name):
    """fused_update_all must produce the same weights as per-param
    update() (same seed, same grads)."""
    shapes = [(5, 4), (9,), (2, 3, 2)]
    rng = np.random.RandomState(3)
    init = [rng.randn(*s).astype(np.float32) for s in shapes]
    grads_seq = [[rng.randn(*s).astype(np.float32) * 0.1 for s in shapes]
                 for _ in range(4)]

    def run(fused):
        opt = opt_mod.create(name, learning_rate=0.02)
        ws = [mx.np.array(a.copy()) for a in init]
        states = [opt.create_state_multi_precision(i, w)
                  for i, w in enumerate(ws)]
        for step_grads in grads_seq:
            gs = [mx.np.array(g) for g in step_grads]
            idx = list(range(len(ws)))
            if fused:
                items = [(i, ws[i], gs[i], states[i]) for i in idx]
                assert opt.fused_update_all(items), "fused path declined"
            else:
                for i in idx:
                    opt.update_multi_precision(i, ws[i], gs[i], states[i])
        return [w.asnumpy() for w in ws]

    got_f = run(True)
    got_u = run(False)
    for a, b, s in zip(got_f, got_u, shapes):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-5,
                                   err_msg=f"{name} shape {s}")


@pytest.mark.parametrize("sched_name,kwargs", [
    ("FactorScheduler", dict(step=3, factor=0.5)),
    ("MultiFactorScheduler", dict(step=[2, 4], factor=0.5)),
    ("PolyScheduler", dict(max_update=10)),
    ("CosineScheduler", dict(max_update=10)),
])
def test_scheduler_composes_with_update(sched_name, kwargs):
    from incubator_mxnet_tpu import lr_scheduler
    sched = getattr(lr_scheduler, sched_name)(base_lr=0.1, **kwargs)
    opt = opt_mod.create("sgd", learning_rate=0.1, lr_scheduler=sched)
    w = mx.np.array(np.zeros((3,), np.float32))
    st = opt.create_state_multi_precision(0, w)
    lrs = []
    for _ in range(6):
        g = mx.np.array(np.ones((3,), np.float32))
        opt.update_multi_precision(0, w, g, st)
        lrs.append(opt._get_lr(0))
    assert lrs[0] >= lrs[-1]            # schedulers only decay here
    assert len(set(np.round(lrs, 8))) > 1


def test_unknown_optimizer_error_type():
    with pytest.raises(mx.MXNetError, match="unknown optimizer"):
        opt_mod.create("definitely_not_real")
