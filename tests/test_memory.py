"""mx.inspect.memory — device-memory observability (ISSUE 15).

Covers: memory plans on every live compiled surface (FusedTrainStep,
FusedInferStep, ExportedModel, ContinuousEngine prefill+decode, elastic
bucketed collectives) + the PR-7-style degradation contract; the
donation proof flipping on a donate=off A/B; the attributed live-buffer
census (tag/register, weakref lifecycle, census_diff) and leakcheck
(planted per-round leak caught, real train loop clean); the
StepTimeline peak_hbm_bytes lane; the MemoryMonitor host_rss fallback
(satellite 1); the device_memory_info typed sentinel (satellite 2); the
kvpool.slab_bytes gauge vs census parity (satellite 3); OOM forensics
(on_oom dump contents, enable/disable knob, crashtest --oom
SIGKILL-parity-pattern slow run); the memscope CLI; the bench memory
phase + benchdiff gate; and the committed mem_r15.json artifact.

Metric-literal census (mxlint telemetry-metric-untested): `mem.plans`,
`mem.census_runs`, `mem.tagged_bytes`, `mem.untagged_bytes`,
`mem.peak_hbm_bytes`, `mem.oom_dumps`, `kvpool.slab_bytes` are asserted
by name below.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, telemetry
from incubator_mxnet_tpu import inspect as mxinspect
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.gluon.contrib import FusedInferStep, FusedTrainStep
from incubator_mxnet_tpu.inspect import memory as mem

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tiny_train_step(bs=4, donate=True):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    x = mx.np.array(np.random.RandomState(0).randn(bs, 8).astype(np.float32))
    y = mx.np.array(np.random.RandomState(1).randn(bs, 4).astype(np.float32))
    loss = gluon.loss.L2Loss()
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    step = FusedTrainStep(net, lambda n, a, b: loss(n(a), b).mean(), opt,
                          donate=donate)
    donated = sum(p.data()._arr.nbytes
                  for p in net.collect_params().values()
                  if p.grad_req != "null")
    return step, x, y, donated


# ---------------------------------------------------------------------------
# memory plans: surfaces + degradation + donation proof
# ---------------------------------------------------------------------------
def test_memory_plan_fused_train_step_and_metric():
    before = telemetry.REGISTRY.snapshot().get("mem.plans", 0)
    step, x, y, donated = _tiny_train_step()
    plan = mxinspect.memory_plan(step, x, y, name="tiny_train")
    assert plan["name"] == "tiny_train"
    assert plan["source"] == "memory_analysis" and plan["complete"]
    for key in ("argument_size", "output_size", "temp_size",
                "alias_size", "generated_code_size", "peak_bytes"):
        assert isinstance(plan[key], int) and plan[key] >= 0
    # donated weight+state buffers must be covered by aliasing
    assert plan["alias_size"] >= donated
    assert plan["peak_bytes"] == (plan["argument_size"]
                                  + plan["output_size"]
                                  + plan["temp_size"]
                                  - plan["alias_size"])
    assert telemetry.REGISTRY.snapshot()["mem.plans"] == before + 1
    # the plan landed in the active-plans table the OOM dump reports
    assert "tiny_train" in mxinspect.active_plans()
    # json-safe (no CompiledMemoryStats / proto blobs leak through)
    json.dumps(plan)


def test_assert_donation_flips_on_donate_off_ab():
    step, x, y, donated = _tiny_train_step(donate=True)
    plan = mxinspect.memory_plan(step, x, y)
    assert mxinspect.assert_donation(plan, donated) >= donated
    step2, x2, y2, donated2 = _tiny_train_step(donate=False)
    plan2 = mxinspect.memory_plan(step2, x2, y2)
    with pytest.raises(MXNetError, match="donation"):
        mxinspect.assert_donation(plan2, donated2)


def test_memory_plan_fused_infer_step():
    net = gluon.nn.Dense(4, in_units=4)
    net.initialize()
    net.hybridize()
    step = FusedInferStep(net)
    plan = mxinspect.memory_plan(step, mx.np.ones((2, 4)))
    assert plan["source"] == "memory_analysis"
    assert plan["argument_size"] > 0 and plan["peak_bytes"] > 0


def test_memory_plan_exported_model(tmp_path):
    from incubator_mxnet_tpu import deploy

    net = gluon.nn.Dense(3, in_units=6)
    net.initialize()
    net.hybridize()
    x = mx.np.zeros((2, 6), dtype="float32")
    net(x)
    prefix = str(tmp_path / "net")
    net.export(prefix, example_inputs=x)
    model = deploy.ExportedModel(f"{prefix}-0000")
    plan = mxinspect.memory_plan(model)
    assert plan["source"] == "memory_analysis"
    # the bucket program's arguments include the weight buffers
    pbytes = sum(b.nbytes for b in model._pbufs)
    assert plan["argument_size"] >= pbytes
    # planning pre-populated the jit cache; run still works
    out = model.run(np.ones((2, 6), np.float32))
    assert np.asarray(out).shape == (2, 3)


def test_memory_plan_continuous_engine_and_zero_retrace():
    from incubator_mxnet_tpu import serve

    cfg = serve.DecoderConfig(vocab=32, embed=16, layers=2, heads=2,
                              head_dim=8, max_len=32)
    model = serve.CachedDecoder(cfg)
    with serve.ContinuousEngine(model, max_slots=4, decode_steps=2,
                                prefill_window=16) as eng:
        eng.generate([1, 2, 3], max_new_tokens=4)
        plans = eng.memory_plans()
        for name in ("prefill", "decode"):
            assert plans[name]["source"] == "memory_analysis"
            # the KV slab pair dominates the arguments of both programs
            assert plans[name]["argument_size"] >= eng.pool.nbytes()
        # both programs donate the slab: aliasing covers k+v
        assert plans["decode"]["alias_size"] >= eng.pool.nbytes()
        # lowering at the warmup avals must not have retraced anything
        eng.assert_no_retraces()
        eng.generate([4, 5], max_new_tokens=3)
        eng.assert_no_retraces()


def test_memory_plan_elastic_collectives():
    import jax.numpy as jnp
    from incubator_mxnet_tpu.fault import elastic

    def loss_fn(p, batch):
        return jnp.mean(batch["c"] @ p["w"])

    params = {"w": np.arange(24, dtype=np.float32)}
    tr = elastic.ElasticTrainer(loss_fn, params, optimizer="sgd", dp=4,
                                learning_rate=0.1)
    tr.step({"c": np.random.rand(8, 24).astype(np.float32)})
    plans = tr.memory_plans()
    kinds = {p["name"].split(".")[1].split("[")[0]
             for p in plans.values() if p["source"] != "unavailable"}
    # both halves of the ZeRO data path are planned
    assert {"reduce_scatter", "allgather"} <= kinds
    for p in plans.values():
        assert p["source"] == "memory_analysis", p


def test_memory_plan_degradation_contract():
    # memory_analysis missing -> HLO-shape lower bound
    class _NoStats:
        def as_text(self):
            return (
                "HloModule m\n\n"
                "ENTRY %main (p0: f32[8,8]) -> f32[8,8] {\n"
                "  %p0 = f32[8,8]{1,0} parameter(0)\n"
                "  ROOT %r = f32[8,8]{1,0} add(f32[8,8]{1,0} %p0, "
                "f32[8,8]{1,0} %p0)\n"
                "}\n")

        def cost_analysis(self):
            raise RuntimeError("no cost analysis either")

    plan = mxinspect.plan_from_compiled(_NoStats(), name="shapes")
    assert plan["source"] == "hlo_shapes" and plan["complete"] is False
    assert plan["argument_size"] == 8 * 8 * 4
    assert plan["output_size"] == 8 * 8 * 4
    assert plan["temp_size"] == 0
    assert plan["peak_bytes"] == 2 * 8 * 8 * 4
    # donation cannot be PROVEN from a shape lower bound: typed refusal
    with pytest.raises(MXNetError, match="cannot prove donation"):
        mxinspect.assert_donation(plan, 1)

    # unparseable text too -> all-zero plan, flagged, never a crash
    class _Garbage:
        def as_text(self):
            raise RuntimeError("text unavailable")

    plan2 = mxinspect.plan_from_compiled(_Garbage(), name="nothing")
    assert plan2["source"] == "unavailable" and plan2["peak_bytes"] == 0


def test_roofline_report_embeds_memory_plan():
    import jax.numpy as jnp
    rep = mxinspect.inspect_step(lambda x: (x @ x).sum(),
                                 jnp.ones((32, 32), jnp.float32))
    assert rep["memory"]["source"] == "memory_analysis"
    assert rep["memory"]["argument_size"] >= 32 * 32 * 4


# ---------------------------------------------------------------------------
# census + leakcheck
# ---------------------------------------------------------------------------
def test_register_tag_and_census_attribution():
    import jax.numpy as jnp
    a = jnp.zeros((128, 64))
    b = jnp.ones((32, 32))
    mxinspect.register(a, owner="test_owner_a")
    with mxinspect.tag("test_owner_b"):
        assert mxinspect.current_tag() == "test_owner_b"
        mxinspect.register({"nested": [b]})
    assert mxinspect.current_tag() is None
    before = telemetry.REGISTRY.snapshot().get("mem.census_runs", 0)
    c = mxinspect.census()
    assert c["owners"]["test_owner_a"]["bytes"] == a.nbytes
    assert c["owners"]["test_owner_b"]["bytes"] == b.nbytes
    assert c["total_bytes"] >= c["tagged_bytes"] > 0
    assert c["untagged_bytes"] == c["total_bytes"] - c["tagged_bytes"]
    snap = telemetry.REGISTRY.snapshot()
    assert snap["mem.census_runs"] == before + 1
    assert snap["mem.tagged_bytes"] == c["tagged_bytes"]
    assert snap["mem.untagged_bytes"] == c["untagged_bytes"]
    json.dumps(c)


def test_register_owner_validation_and_weakref_lifecycle():
    import jax.numpy as jnp
    with pytest.raises(MXNetError, match="owner"):
        mxinspect.register(jnp.zeros((2,)), owner="Bad.Owner")
    with pytest.raises(MXNetError, match="owner"):
        mxinspect.register(jnp.zeros((2,)))     # no ambient tag either
    x = jnp.zeros((64, 64))
    mxinspect.register(x, owner="shortlived")
    assert mxinspect.census()["owners"]["shortlived"]["bytes"] == x.nbytes
    del x
    # the weakref entry died with the array: the owner vanishes
    assert "shortlived" not in mxinspect.census()["owners"]


def test_census_diff():
    import jax.numpy as jnp
    before = mxinspect.census()
    grown = jnp.zeros((256, 256))
    mxinspect.register(grown, owner="diff_owner")
    after = mxinspect.census()
    d = mxinspect.census_diff(before, after)
    assert d["owners"]["diff_owner"]["bytes"] == grown.nbytes
    assert d["total_bytes"] >= grown.nbytes


def test_leakcheck_catches_planted_leak_and_passes_clean_loop():
    import jax.numpy as jnp
    leaked = []

    def leaky():
        leaked.append(jnp.zeros((128, 128)))

    with pytest.raises(mxinspect.MemoryLeakError) as ei:
        mxinspect.leakcheck(leaky, rounds=3)
    assert ei.value.report["leak"] and ei.value.report["growth_bytes"] > 0

    # the REAL train loop: donated buffers swap, nothing accumulates
    step, x, y, _ = _tiny_train_step()
    rep = mxinspect.leakcheck(lambda: step(x, y), rounds=3)
    assert rep["leak"] is False
    assert rep["growth_mb"] < 1.0


# ---------------------------------------------------------------------------
# timeline lane + monitor + device info (satellites 1 + 2)
# ---------------------------------------------------------------------------
def test_steptimeline_peak_hbm_lane():
    step, x, y, _ = _tiny_train_step()
    tl = telemetry.StepTimeline(name="memtest.step")
    for _ in range(3):
        with tl.step():
            step(x, y)
    rep = tl.report()
    # CPU backend: memory_stats is None, so the honest source is host RSS
    assert rep["peak_hbm_bytes"] > 0
    assert rep["mem_source"] in ("device", "host_rss")
    assert telemetry.REGISTRY.snapshot()["mem.peak_hbm_bytes"] >= \
        rep["peak_hbm_bytes"] > 0


def test_memory_monitor_host_rss_fallback_and_counter_source():
    import time
    from incubator_mxnet_tpu import profiler

    b, source = profiler.read_memory_sample()
    assert source in ("device", "host_rss") and b > 0
    with profiler.MemoryMonitor(interval=0.005) as mon:
        time.sleep(0.03)
    assert len(mon.samples) >= 1
    # on the CPU test backend the pre-fix reading was a flat 0; now the
    # samples are process RSS with an honest provenance stamp
    for ts, nbytes, src in mon.samples:
        assert nbytes > 0 and src in ("device", "host_rss")
    assert mon.peak_bytes > 0
    assert mon.source in ("device", "host_rss")
    # a monitor-only loop (no StepTimeline) moves the cataloged gauge too
    assert telemetry.REGISTRY.snapshot()["mem.peak_hbm_bytes"] >= \
        mon.peak_bytes
    # the Chrome counter events carry the stamp too
    from incubator_mxnet_tpu.profiler import _events, _lock
    with _lock:
        lanes = [e for e in _events if e["name"] == "device_memory"]
    assert lanes and all("source" in e["args"] for e in lanes)


def test_device_memory_info_typed_sentinel(monkeypatch):
    from incubator_mxnet_tpu import device as dev_mod

    info = dev_mod.device_memory_info()
    # CPU backend: memory_stats() is None -> an explicit don't-know,
    # not fake (0, 0) headroom
    assert info.known is False and info.free == 0 and info.total == 0
    assert tuple(info) == (0, 0, False)     # tuple-compatible

    class _FakeDev:
        def memory_stats(self):
            return {"bytes_limit": 1000, "bytes_in_use": 250}

    class _FakeDevNone:
        def memory_stats(self):
            return None

    monkeypatch.setattr(dev_mod.Device, "jax_device",
                        property(lambda self: _FakeDev()))
    info = dev_mod.device_memory_info()
    assert info == dev_mod.MemoryInfo(750, 1000, True)
    monkeypatch.setattr(dev_mod.Device, "jax_device",
                        property(lambda self: _FakeDevNone()))
    assert dev_mod.device_memory_info().known is False

    # the capi shim (deploy.py) reports (used, limit) and no longer
    # treats the tuple as a dict (the satellite's latent AttributeError)
    from incubator_mxnet_tpu.deploy import _capi_memory_info
    monkeypatch.setattr(dev_mod.Device, "jax_device",
                        property(lambda self: _FakeDev()))
    assert _capi_memory_info(0) == (250, 1000)
    monkeypatch.setattr(dev_mod.Device, "jax_device",
                        property(lambda self: _FakeDevNone()))
    assert _capi_memory_info(0) == (0, 0)


# ---------------------------------------------------------------------------
# kvpool slab gauge (satellite 3)
# ---------------------------------------------------------------------------
def test_kvpool_slab_gauge_matches_census_owner_bytes():
    from incubator_mxnet_tpu.serve.kv_pool import KVCachePool

    pool = KVCachePool(max_slots=4, layers=2, max_len=16, heads=2,
                       head_dim=8)
    gauge = telemetry.REGISTRY.snapshot()["kvpool.slab_bytes"]
    assert gauge == pool.nbytes() == pool.stats()["slab_bytes"]
    c = mxinspect.census()
    assert c["owners"]["kv_pool"]["bytes"] == pool.nbytes()
    assert c["owners"]["kv_pool"]["count"] == 2          # k + v
    # reallocate (the engine's post-donation-failure path) re-registers
    pool.reallocate()
    c = mxinspect.census()
    assert c["owners"]["kv_pool"]["bytes"] == pool.nbytes()


# ---------------------------------------------------------------------------
# OOM forensics
# ---------------------------------------------------------------------------
_OOM = RuntimeError("RESOURCE_EXHAUSTED: Out of memory while trying to "
                    "allocate 34359738368 bytes")


def test_is_oom_error_shapes():
    assert mxinspect.is_oom_error(_OOM)
    assert mxinspect.is_oom_error(MemoryError())
    assert mxinspect.is_oom_error(RuntimeError("xla: Resource exhausted"))
    assert not mxinspect.is_oom_error(ValueError("shape mismatch"))
    assert not mxinspect.is_oom_error(None)


def test_on_oom_dump_names_top_owner(tmp_path, monkeypatch):
    import jax.numpy as jnp
    monkeypatch.setenv("MXNET_MEM_OOM_DUMP", str(tmp_path))
    bomb = jnp.zeros((512, 512, 4))
    mxinspect.register(bomb, owner="planted")
    before = telemetry.REGISTRY.snapshot().get("mem.oom_dumps", 0)
    step, x, y, _ = _tiny_train_step()
    mxinspect.memory_plan(step, x, y, name="planted_plan")
    path = mxinspect.on_oom(_OOM, where="test.step")
    assert path and os.path.exists(path)
    dump = json.load(open(path))
    assert dump["reason"] == "test.step"
    assert "RESOURCE_EXHAUSTED" in dump["error"]
    owners = dump["census"]["owners"]
    assert owners["planted"]["bytes"] == bomb.nbytes
    # the planted slab tops every NAMED owner (the whole-suite process
    # may carry arbitrary untagged leftovers; the strict top-entry
    # assertion runs in the clean-process crashtest --oom harness)
    named = {k: v["bytes"] for k, v in owners.items() if k != "untagged"}
    assert max(named, key=named.get) == "planted"
    assert "planted_plan" in dump["plans"]
    assert isinstance(dump["flightrec"], list)
    assert dump["device_memory"]["known"] in (True, False)
    assert telemetry.REGISTRY.snapshot()["mem.oom_dumps"] == before + 1
    # non-OOM errors never dump; the knob disables entirely
    assert mxinspect.on_oom(ValueError("not oom")) is None
    monkeypatch.setenv("MXNET_MEM_OOM_DUMP", "0")
    assert mxinspect.on_oom(_OOM) is None


def test_serve_engine_survives_oom_and_dumps(tmp_path, monkeypatch):
    """A RESOURCE_EXHAUSTED step inside the continuous engine leaves the
    black box AND the engine keeps serving (slab reallocation path)."""
    from incubator_mxnet_tpu import serve

    monkeypatch.setenv("MXNET_MEM_OOM_DUMP", str(tmp_path))
    cfg = serve.DecoderConfig(vocab=32, embed=16, layers=1, heads=2,
                              head_dim=8, max_len=16)
    model = serve.CachedDecoder(cfg)
    with serve.ContinuousEngine(model, max_slots=2, decode_steps=1,
                                prefill_window=8) as eng:
        eng.generate([1, 2], max_new_tokens=2)    # healthy first
        orig = eng._prefill_prog

        def _boom(*a, **k):
            eng._prefill_prog = orig              # heal for the retry
            raise RuntimeError("RESOURCE_EXHAUSTED: Out of memory "
                               "(injected)")

        eng._prefill_prog = _boom
        with pytest.raises(Exception):
            eng.generate([3, 4], max_new_tokens=2)
        out = eng.generate([5, 6], max_new_tokens=2)   # keeps serving
        assert out.dtype == np.int32
    dumps = [f for f in os.listdir(tmp_path) if f.startswith("oomdump-")]
    assert dumps, "engine OOM left no black box"


# ---------------------------------------------------------------------------
# subprocess acceptance: clean-process census fractions, CLI, bench phase
# ---------------------------------------------------------------------------
def _run(args, timeout=600, env_extra=None):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    if env_extra:
        env.update(env_extra)
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def test_memscope_cli_model_json(tmp_path):
    out = tmp_path / "scope.json"
    r = _run([os.path.join(REPO, "tools", "memscope.py"), "--model",
              "tiny", "--json", str(out)])
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(out.read_text())
    assert rep["mode"] == "model" and rep["donation_ok"]
    assert rep["plans"][0]["source"] == "memory_analysis"
    assert rep["device_memory"]["known"] is False      # CPU honesty
    assert "census" in rep


def test_memscope_cli_serve_census_attribution(tmp_path):
    """Acceptance: in a clean process the serve-continuous resident set
    is >= 80% attributed to named owners (kv_pool + decoder_params)."""
    out = tmp_path / "serve.json"
    r = _run([os.path.join(REPO, "tools", "memscope.py"), "--serve",
              "--json", str(out)], timeout=900)
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(out.read_text())
    c = rep["census"]
    assert c["tagged_fraction"] >= 0.8, c
    assert "kv_pool" in c["owners"] and "decoder_params" in c["owners"]
    assert rep["kv_slab_mb"] > 0


def test_elastic_census_attribution_subprocess():
    """Acceptance: the elastic bench model's resident set is >= 80%
    attributed (optimizer_shards + elastic_params) in a clean process."""
    code = (
        "import os\n"
        "os.environ['JAX_PLATFORMS'] = 'cpu'\n"
        "import numpy as np, jax, jax.numpy as jnp\n"
        "jax.config.update('jax_platforms', 'cpu')\n"
        "import incubator_mxnet_tpu as mx\n"
        "from incubator_mxnet_tpu.fault import elastic\n"
        "from incubator_mxnet_tpu import inspect as mxi\n"
        "def loss_fn(p, b):\n"
        "    return jnp.mean(b['c'] @ p['w']) + jnp.mean(p['v'] ** 2)\n"
        "params = {'w': np.random.rand(512, 8).astype(np.float32),\n"
        "          'v': np.random.rand(256).astype(np.float32)}\n"
        "tr = elastic.ElasticTrainer(loss_fn, params, optimizer='adam',\n"
        "                            dp=4, learning_rate=0.01)\n"
        "tr.step({'c': np.random.rand(8, 512).astype(np.float32)})\n"
        "c = mxi.census()\n"
        "print('FRACTION', c['tagged_fraction'])\n"
        "assert c['tagged_fraction'] >= 0.8, c\n"
        "assert 'optimizer_shards' in c['owners']\n"
        "assert 'elastic_params' in c['owners']\n"
        "print('OK')\n")
    r = _run(["-c", code], env_extra={
        "XLA_FLAGS": "--xla_force_host_platform_device_count=8"})
    assert r.returncode == 0 and "OK" in r.stdout, r.stdout + r.stderr


def test_bench_memory_quick_phase():
    r = _run([os.path.join(REPO, "bench.py"), "--phase", "memory",
              "--quick"], timeout=900)
    assert r.returncode == 0, r.stdout + r.stderr
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["ok"], line
    res = line["result"]
    for key in ("train_peak_hbm_mb", "serve_kv_slab_mb",
                "mem_plan_vs_measured_ratio", "leakcheck_growth_mb"):
        assert isinstance(res[key], (int, float)), key
    assert res["train_peak_hbm_mb"] > 0
    assert res["serve_kv_slab_mb"] > 0
    assert res["mem_plan_vs_measured_ratio"] > 0
    assert res["mem_leakcheck_leak"] is False
    assert res["mem_census_tagged_fraction"] >= 0.8
    assert res["mem_train_plan_source"] == "memory_analysis"


def test_benchdiff_gates_memory_keys():
    sys.path.insert(0, os.path.join(REPO, "tools"))
    try:
        import benchdiff
    finally:
        sys.path.pop(0)
    for key in ("train_peak_hbm_mb", "serve_kv_slab_mb",
                "mem_plan_vs_measured_ratio", "leakcheck_growth_mb"):
        assert benchdiff.TREND_KEYS[key] == "lower"
    base = {"backend_ok": True, "train_peak_hbm_mb": 100.0}
    rep = benchdiff.compare(base, dict(base, train_peak_hbm_mb=150.0))
    assert rep["status"] == "regression"
    assert rep["regressions"][0]["key"] == "train_peak_hbm_mb"


def test_committed_mem_artifact_acceptance():
    path = os.path.join(REPO, "benchmark", "results", "mem_r15.json")
    with open(path) as f:
        art = json.load(f)
    for key in ("train_peak_hbm_mb", "serve_kv_slab_mb",
                "mem_plan_vs_measured_ratio", "leakcheck_growth_mb"):
        assert isinstance(art[key], (int, float)), key
    assert art["mem_leakcheck_leak"] is False
    # the phase census is GLOBAL (train inputs and jit leftovers count as
    # honest untagged); the >= 0.8 attribution acceptance is on the
    # serve-continuous and elastic bench models, asserted by the
    # clean-process tests above (memscope --serve, elastic subprocess)
    assert art["mem_census_tagged_fraction"] >= 0.5
    assert art["mem_train_plan_source"] == "memory_analysis"
    # honesty stamps: the committed round says what machine measured it
    assert art["platform"] == "cpu"
    assert art["backend_ok"] is True


@pytest.mark.slow
def test_crashtest_oom_forensics():
    """The planted allocation bomb under run_elastic leaves an OOM dump
    naming the planted owner as the top census entry (the
    SIGKILL-parity-pattern harness; see tools/crashtest.py --oom)."""
    r = _run([os.path.join(REPO, "tools", "crashtest.py"), "--oom",
              "--steps", "8", "--ckpt-every", "3", "--kill-at", "4"],
             timeout=900)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "OOM forensics OK" in r.stdout
