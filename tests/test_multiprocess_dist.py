"""CI-run 2-process distributed test (VERDICT-r1 Next #5: the dist_sync
claim must be verified by an automated run, ≙ the reference's
tests/nightly/dist_sync_kvstore.py launched under `--launcher local`).

Spawns 2 REAL processes on localhost through tools/launch.py (the
framework's own launcher) over the CPU platform, running
tests/nightly/dist_sync_spmd.py — cross-process allreduce values, DP
gradient equivalence, and the kvstore dist path.
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_two_process_dist_sync_via_launcher():
    env = dict(os.environ)
    site = [p for p in sys.path if p.endswith("site-packages")]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + site)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)   # one device per process
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", "2", "--env", "JAX_PLATFORMS=cpu",
         sys.executable,
         os.path.join(REPO, "tests", "nightly", "dist_sync_spmd.py")],
        capture_output=True, text=True, timeout=540, env=env, cwd=REPO)
    assert r.returncode == 0, \
        f"rc={r.returncode}\nstdout={r.stdout[-3000:]}\nstderr={r.stderr[-3000:]}"
    # BOTH ranks must print the exact marker — a silent rank-1 failure must
    # fail the test (VERDICT-r2 Weak #6)
    assert r.stdout.count("dist sync semantics OK") == 2, r.stdout[-2000:]
