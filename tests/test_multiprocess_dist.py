"""CI-run multi-process distributed tests (≙ the reference's
tests/nightly/dist_sync_kvstore.py launched under `--launcher local`).

Spawns REAL processes on localhost through tools/launch.py (the framework's
own launcher) over the CPU platform:

- n=2: tests/nightly/dist_sync_spmd.py — cross-process allreduce values, DP
  gradient equivalence, the kvstore dist path, and packed-wire compression
  byte accounting (VERDICT-r1 Next #5).
- n=8: tests/nightly/dist_flagship_dp.py — flagship-transformer DP grads
  through compressed + uncompressed kvstore paths, per-rank numerics and
  cross-rank parameter identity asserted (VERDICT-r3 Next #6).
"""
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run_launcher(n, script, marker, timeout=540):
    """Launch `script` under tools/launch.py with n local processes and
    assert EVERY rank printed `marker` — a silent failure on any rank must
    fail the test (VERDICT-r2 Weak #6)."""
    env = dict(os.environ)
    site = [p for p in sys.path if p.endswith("site-packages")]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + site)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)   # one device per process
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "launch.py"),
         "-n", str(n), "--env", "JAX_PLATFORMS=cpu",
         sys.executable, os.path.join(REPO, "tests", "nightly", script)],
        capture_output=True, text=True, timeout=timeout, env=env, cwd=REPO)
    # The jax.distributed coordinator has a rare C++ teardown race under
    # CPU saturation: every rank finishes its work (all markers printed),
    # then process exit aborts with exactly "terminate called without an
    # active exception". Tolerate ONLY that shape — any other nonzero rc,
    # or a missing marker, still fails.
    benign_teardown = (
        r.returncode != 0 and r.stdout.count(marker) == n
        and r.stderr.strip() == "terminate called without an active exception")
    assert r.returncode == 0 or benign_teardown, \
        f"rc={r.returncode}\nstdout={r.stdout[-3000:]}\nstderr={r.stderr[-3000:]}"
    assert r.stdout.count(marker) == n, r.stdout[-2000:]


def test_two_process_dist_sync_via_launcher():
    _run_launcher(2, "dist_sync_spmd.py", "dist sync semantics OK")


def test_two_process_barrier_timeout_names_missing_rank():
    """Rank 1 skips the barrier; rank 0's MXNET_KVSTORE_BARRIER_TIMEOUT
    must fire a typed BarrierTimeout NAMING rank 1 (attribution through
    the jax.distributed coordinator KV store)."""
    _run_launcher(2, "dist_barrier_timeout.py",
                  "barrier timeout peer-skip OK", timeout=240)


@pytest.mark.slow  # nightly-grade: 8 jax processes on one core, ~60s
def test_eight_process_flagship_dp():
    """n=8 flagship DP: real transformer grads through the compressed +
    uncompressed kvstore dist paths, per-rank numerics asserted
    (≙ reference tests/nightly/dist_sync_kvstore.py with --launcher local,
    scaled past its n=4)."""
    _run_launcher(8, "dist_flagship_dp.py", "flagship DP dist OK")
