"""serve.decode: sampled + speculative decoding and the paged-attention
kernel over the slotted KV pool.

Contracts under test (ISSUE 17 acceptance):
  * temperature/top-k/top-p sampling is ARRAY DATA: mixed greedy/sampled
    traffic shares one compiled decode program (zero retraces), and a
    sampled request is deterministic in its seed — the engine matches the
    scheduling-free seeded reference token-for-token because the draw key
    is a pure function of (seed, cache position), never of wave schedule
  * `_sample_tokens` draws from the right distribution (chi-square over
    >= 10k draws against known logits) and top-k/top-p truncate support
    exactly
  * speculative decoding emits EXACTLY the tokens plain decode would
    (exact-verification acceptance), for greedy and sampled lanes alike,
    with per-lane acceptance counts as in-scan data — acceptance-rate
    variance across lanes never retraces, and eos inside an accepted
    draft block keeps exact token accounting
  * the Pallas paged-attention kernel (interpret mode on CPU CI) matches
    the masked-einsum reference to float tolerance, reads int8 slabs via
    per-position dequant scales, and slot poison-fill cannot leak across
    lanes through the kernel's clamped block reads
  * int8 KV halves slab bytes (slots_per_gb >= 2x float32) without
    changing tokens vs the int8 reference, and the quantized pool shape
    shows up in the engine's memory plans
"""
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from incubator_mxnet_tpu import profiler, serve
from incubator_mxnet_tpu.ops import fused as F
from incubator_mxnet_tpu.ops import pallas_kernels as PK
from incubator_mxnet_tpu.serve.continuous import _sample_tokens, _seed_key

CFG = dict(vocab=64, embed=32, layers=2, heads=4, head_dim=8, max_len=48)


@pytest.fixture(scope="module")
def decoder():
    """One small CachedDecoder + a weight-sharing reference twin (its own
    jits, so reference calls never touch the engine's compile caches)."""
    cfg = serve.DecoderConfig(**CFG)
    model = serve.CachedDecoder(cfg, seed=3)
    ref = serve.CachedDecoder(cfg, params=model.params)
    return model, ref


@pytest.fixture(scope="module")
def spec_engine(decoder):
    """Shared speculative engine (draft=2): spec-vs-plain token equality
    and acceptance-variance tests reuse one warmup. Built on a PRIVATE
    weight-sharing model so other tests compiling programs on the shared
    model cannot pollute this engine's retrace counter."""
    model, _ = decoder
    twin = serve.CachedDecoder(serve.DecoderConfig(**CFG),
                               params=model.params)
    eng = serve.ContinuousEngine(twin, max_slots=4, decode_steps=2,
                                 draft_tokens=2).start()
    yield eng
    eng.close()


@pytest.fixture(scope="module")
def int8_engine(decoder):
    """Shared int8-KV speculative engine: quantized slab + draft path
    (private weight-sharing model, same reason as spec_engine). The
    prefill window is SMALLER than max_len so slot positions past the
    window keep stale bytes — the poison-isolation test relies on the
    decode mask being the only guard."""
    model, _ = decoder
    twin = serve.CachedDecoder(serve.DecoderConfig(**CFG),
                               params=model.params)
    eng = serve.ContinuousEngine(twin, max_slots=4, decode_steps=2,
                                 draft_tokens=2, prefill_window=16,
                                 kv_dtype="int8").start()
    yield eng
    eng.close()


def _workload(n, seed=0, vocab=64, max_new_hi=20):
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, vocab, size=rng.randint(2, 12)).tolist(),
             int(rng.randint(1, max_new_hi))) for _ in range(n)]


# ---------------------------------------------------------------------------
# sampling as data: engine == seeded reference, one program for all lanes
# ---------------------------------------------------------------------------
def test_mixed_greedy_sampled_matches_reference_zero_retraces(decoder):
    """Greedy and sampled requests interleave in ONE compiled program;
    every sampled lane reproduces the seeded reference exactly (the draw
    key depends on (seed, position), not on which wave served it)."""
    model, ref = decoder
    work = _workload(8, seed=1)
    sampling = [
        {} if i % 2 == 0
        else {"temperature": 3.0, "top_k": 8, "seed": 100 + i}
        for i in range(len(work))]
    before = profiler.serve_stats()
    with serve.ContinuousEngine(model, max_slots=4, decode_steps=3) as eng:
        warm_ccs = eng.compile_cache_size()
        warm_programs = profiler.serve_stats()["programs_compiled"]
        futs = [eng.submit(p, m, **kw)
                for (p, m), kw in zip(work, sampling)]
        outs = [f.result(timeout=120) for f in futs]
        assert eng.assert_no_retraces() == 0
        assert eng.compile_cache_size() == warm_ccs
        assert profiler.serve_stats()["programs_compiled"] == warm_programs
    for (p, m), kw, o in zip(work, sampling, outs):
        np.testing.assert_array_equal(
            o, ref.reference_generate(p, m, **kw),
            err_msg=f"engine diverged for prompt {p} sampling {kw}")
        assert len(o) == m
    # only temperature > 0 lanes count as sampled
    sampled_max_new = sum(m for (_, m), kw in zip(work, sampling) if kw)
    after = profiler.serve_stats()
    delta = after["decode_sampled_tokens"] - before["decode_sampled_tokens"]
    assert 0 < delta <= sampled_max_new


def test_seed_determinism_and_divergence(decoder):
    """Same seed -> identical tokens; across seeds at high temperature
    the outputs actually diverge (the PRNG is live, not a greedy alias)."""
    _, ref = decoder
    prompt, m = [9, 4, 33, 2], 12
    a = ref.reference_generate(prompt, m, temperature=8.0, seed=7)
    b = ref.reference_generate(prompt, m, temperature=8.0, seed=7)
    np.testing.assert_array_equal(a, b)
    outs = {tuple(int(t) for t in
                  ref.reference_generate(prompt, m, temperature=8.0,
                                         seed=s))
            for s in range(10)}
    assert len(outs) >= 4, f"only {len(outs)} distinct outputs at T=8"


def test_sample_tokens_distribution_chi_square():
    """>= 10k draws from fixed logits land on the known distribution
    (chi-square, df=7), greedy lanes return argmax, and top-k / top-p
    truncate the support exactly."""
    probs = np.array([0.4, 0.3, 0.1, 0.1, 0.05, 0.03, 0.01, 0.01])
    n = 20000
    logits = jnp.asarray(np.tile(np.log(probs), (n, 1)),
                         dtype=jnp.float32)
    keys = jnp.asarray(np.tile(_seed_key(123), (n, 1)))
    positions = jnp.arange(n, dtype=jnp.int32)
    ones = jnp.ones((n,), dtype=jnp.float32)
    zeros_i = jnp.zeros((n,), dtype=jnp.int32)

    draws = np.asarray(_sample_tokens(logits, ones, zeros_i, ones, keys,
                                      positions))
    counts = np.bincount(draws, minlength=len(probs))
    chi2 = float(np.sum((counts - n * probs) ** 2 / (n * probs)))
    assert chi2 < 30.0, f"chi2={chi2:.2f} counts={counts.tolist()}"

    greedy = np.asarray(_sample_tokens(
        logits, jnp.zeros((n,), jnp.float32), zeros_i, ones, keys,
        positions))
    assert (greedy == int(np.argmax(probs))).all()

    topk = np.asarray(_sample_tokens(
        logits, ones, jnp.full((n,), 2, jnp.int32), ones, keys,
        positions))
    assert set(np.unique(topk)) == {0, 1}
    # nucleus 0.69 keeps exactly {0.4, 0.3}: csum passes 0.69 at token 1
    topp = np.asarray(_sample_tokens(
        logits, ones, zeros_i, jnp.full((n,), 0.69, jnp.float32), keys,
        positions))
    assert set(np.unique(topp)) == {0, 1}


def test_submit_validates_sampling_params(decoder):
    model, _ = decoder
    eng = serve.ContinuousEngine(model, max_slots=2)   # never started
    with pytest.raises(serve.ServeError, match="temperature"):
        eng.submit([1, 2], 4, temperature=-0.5)
    with pytest.raises(serve.ServeError, match="top_k"):
        eng.submit([1, 2], 4, temperature=1.0, top_k=-1)
    with pytest.raises(serve.ServeError, match="top_p"):
        eng.submit([1, 2], 4, temperature=1.0, top_p=0.0)
    with pytest.raises(serve.ServeError, match="top_p"):
        eng.submit([1, 2], 4, temperature=1.0, top_p=1.5)


# ---------------------------------------------------------------------------
# speculative decoding: exact verification, acceptance counters, eos
# ---------------------------------------------------------------------------
def test_spec_decode_token_exact_vs_plain_reference(decoder, spec_engine):
    """The whole point of exact-verification: speculative decode is a
    pure SPEED change. Greedy and sampled lanes through the draft+verify
    engine emit byte-identical tokens to the plain (draft=0) reference,
    and the acceptance counters actually move."""
    _, ref = decoder
    work = _workload(10, seed=2)
    sampling = [
        {} if i % 3 else {"temperature": 3.0, "top_k": 8, "seed": 50 + i}
        for i in range(len(work))]
    before = profiler.serve_stats()
    futs = [spec_engine.submit(p, m, **kw)
            for (p, m), kw in zip(work, sampling)]
    outs = [f.result(timeout=120) for f in futs]
    assert spec_engine.assert_no_retraces() == 0
    for (p, m), kw, o in zip(work, sampling, outs):
        np.testing.assert_array_equal(
            o, ref.reference_generate(p, m, **kw),
            err_msg=f"spec engine diverged for prompt {p} sampling {kw}")
    after = profiler.serve_stats()
    acc = after["decode_draft_accepted"] - before["decode_draft_accepted"]
    rej = after["decode_draft_rejected"] - before["decode_draft_rejected"]
    assert acc > 0, "no draft tokens accepted on a repetitive workload"
    assert acc + rej > 0
    st = spec_engine.stats()
    assert st["draft_tokens"] == 2
    assert 0.0 < st["draft_acceptance"] <= 1.0
    assert json.dumps(st)


def test_spec_reference_matches_plain_reference(decoder):
    """reference_generate(draft_tokens=k) — the one-wave-at-a-time
    speculative oracle — is itself token-exact against plain decode."""
    _, ref = decoder
    for prompt, m in _workload(4, seed=9, max_new_hi=14):
        plain = ref.reference_generate(prompt, m)
        for k in (1, 3):
            np.testing.assert_array_equal(
                plain, ref.reference_generate(prompt, m, draft_tokens=k),
                err_msg=f"draft={k} diverged for prompt {prompt}")


def test_spec_eos_mid_draft_block_exact_accounting(decoder):
    """eos emitted INSIDE an accepted draft block truncates the block
    (tokens after eos are discarded), frees the lane, and matches the
    plain-decode eos contract exactly."""
    model, ref = decoder
    prompt, max_new = [7, 3, 19], 16
    base = ref.reference_generate(prompt, max_new)
    eos = int(base[len(base) // 2])
    expect = ref.reference_generate(prompt, max_new, eos_id=eos)
    assert len(expect) < len(base)
    np.testing.assert_array_equal(
        expect,
        ref.reference_generate(prompt, max_new, eos_id=eos,
                               draft_tokens=2))
    eng = serve.ContinuousEngine(model, max_slots=2, decode_steps=3,
                                 eos_id=eos, draft_tokens=2).start()
    try:
        out = eng.generate(prompt, max_new, timeout=120)
        assert eng.assert_no_retraces() == 0
    finally:
        eng.close()
    np.testing.assert_array_equal(out, expect)
    assert out[-1] == eos


def test_spec_acceptance_variance_never_retraces(decoder, spec_engine):
    """Lanes accepting 0..k draft tokens per wave is pure DATA: ragged
    traffic with wildly different acceptance behavior replays the same
    two compiled programs."""
    _, ref = decoder
    warm_ccs = spec_engine.compile_cache_size()
    warm_programs = profiler.serve_stats()["programs_compiled"]
    work = _workload(14, seed=11, max_new_hi=16)
    futs = [spec_engine.submit(p, m) for p, m in work]
    outs = [f.result(timeout=120) for f in futs]
    assert spec_engine.assert_no_retraces() == 0
    assert spec_engine.compile_cache_size() == warm_ccs
    assert profiler.serve_stats()["programs_compiled"] == warm_programs
    for (p, m), o in zip(work, outs):
        np.testing.assert_array_equal(o, ref.reference_generate(p, m))


# ---------------------------------------------------------------------------
# paged-attention kernel: interpret-mode exactness, routing counters
# ---------------------------------------------------------------------------
def test_paged_attention_kernel_matches_ref_interpret():
    """Pallas kernel (interpret mode) vs the masked-einsum reference over
    a multi-block slab (T=48 -> 16-wide blocks): float32 and int8+scales,
    chunk widths 1 (plain decode) and 3 (speculative verify)."""
    S, H, D, T, L = 4, 4, 8, 48, 2
    rng = np.random.RandomState(0)
    k_slab = jnp.asarray(rng.randn(S + 1, L, T, H, D).astype(np.float32))
    v_slab = jnp.asarray(rng.randn(S + 1, L, T, H, D).astype(np.float32))
    k_codes = jnp.asarray(rng.randint(-127, 128, (S + 1, L, T, H, D),
                                      dtype=np.int64).astype(np.int8))
    v_codes = jnp.asarray(rng.randint(-127, 128, (S + 1, L, T, H, D),
                                      dtype=np.int64).astype(np.int8))
    k_scale = jnp.asarray(
        (rng.rand(S + 1, L, T) * 0.1 + 0.01).astype(np.float32))
    v_scale = jnp.asarray(
        (rng.rand(S + 1, L, T) * 0.1 + 0.01).astype(np.float32))
    for C in (1, 3):
        q = jnp.asarray(rng.randn(S, C, H, D).astype(np.float32))
        lengths = jnp.asarray([1, 7, T - C, 16], dtype=jnp.int32)
        layer = 1           # non-zero: the slab's layer stride is live
        out = PK.paged_attention_fwd(q, k_slab, v_slab, lengths,
                                     layer, interpret=True)
        assert out is not None
        np.testing.assert_allclose(
            out, F.paged_attention_ref(q, k_slab, v_slab, lengths,
                                       layer),
            rtol=2e-5, atol=2e-5)
        out8 = PK.paged_attention_fwd(q, k_codes, v_codes, lengths,
                                      layer, k_scale=k_scale,
                                      v_scale=v_scale, interpret=True)
        assert out8 is not None
        np.testing.assert_allclose(
            out8, F.paged_attention_ref(q, k_codes, v_codes, lengths,
                                        layer, k_scale=k_scale,
                                        v_scale=v_scale),
            rtol=2e-5, atol=2e-5)


def test_paged_attention_routing_and_counters():
    """fused.paged_attention routes to the Pallas kernel under interpret
    (pallas_calls) and to the reference off-TPU (fallback_calls); the
    per-trace dispatch counter moves either way."""
    S, C, H, D, T = 2, 1, 4, 8, 16
    rng = np.random.RandomState(1)
    q = jnp.asarray(rng.randn(S, C, H, D).astype(np.float32))
    slab = jnp.asarray(rng.randn(S + 1, 1, T, H, D).astype(np.float32))
    lengths = jnp.asarray([3, 9], dtype=jnp.int32)

    F.fused_stats(reset=True)
    ref_out = F.paged_attention(q, slab, slab, lengths, 0)
    st = F.fused_stats(reset=True)
    assert st["paged_attention_calls"] == 1
    assert st["fallback_calls"] == 1 and st["pallas_calls"] == 0

    prev = F.set_interpret(True)
    try:
        k_out = F.paged_attention(q, slab, slab, lengths, 0)
    finally:
        F.set_interpret(prev)
    st = F.fused_stats(reset=True)
    assert st["paged_attention_calls"] == 1
    assert st["pallas_calls"] == 1 and st["fallback_calls"] == 0
    np.testing.assert_allclose(k_out, ref_out, rtol=2e-5, atol=2e-5)


def test_engine_on_interpret_kernel_path_poison_isolation():
    """End-to-end engine traffic THROUGH the Pallas kernel (interpret
    mode, CPU CI): outputs match the reference running on the same
    routing, slot poison-fill never leaks into any lane (the kernel's
    clamped block reads honor [0, cur_len)), and pallas_calls prove the
    kernel actually ran."""
    prev = F.set_interpret(True)
    F.fused_stats(reset=True)
    try:
        # model built INSIDE the scope: kernel routing is decided at
        # trace time, so both engine and reference trace the kernel path
        model = serve.CachedDecoder(serve.DecoderConfig(**CFG), seed=3)
        work = _workload(4, seed=5, max_new_hi=8)
        with serve.ContinuousEngine(model, max_slots=2, decode_steps=2,
                                    prefill_window=16) as eng:
            eng.pool.poison(1e9)
            futs = [eng.submit(p, m) for p, m in work]
            outs = [f.result(timeout=120) for f in futs]
        expect = [model.reference_generate(p, m, window=16)
                  for p, m in work]
        st = F.fused_stats(reset=True)
        assert st["pallas_calls"] > 0
        assert st["paged_attention_calls"] > 0
    finally:
        F.set_interpret(prev)
    for (p, m), o, e in zip(work, outs, expect):
        np.testing.assert_array_equal(
            o, e, err_msg=f"poison leaked through the kernel for {p}")


# ---------------------------------------------------------------------------
# int8 KV: token parity, density, poison isolation, memory plans
# ---------------------------------------------------------------------------
def test_int8_engine_matches_int8_reference(decoder, int8_engine):
    """int8 slab + speculative decode: engine tokens equal the int8
    reference (same quantized math, scheduling-free)."""
    _, ref = decoder
    work = _workload(8, seed=4)
    sampling = [
        {} if i % 2 else {"temperature": 3.0, "top_k": 8, "seed": 70 + i}
        for i in range(len(work))]
    futs = [int8_engine.submit(p, m, **kw)
            for (p, m), kw in zip(work, sampling)]
    outs = [f.result(timeout=120) for f in futs]
    assert int8_engine.assert_no_retraces() == 0
    for (p, m), kw, o in zip(work, sampling, outs):
        np.testing.assert_array_equal(
            o, ref.reference_generate(p, m, kv_dtype="int8", **kw),
            err_msg=f"int8 engine diverged for prompt {p} sampling {kw}")
    assert int8_engine.stats()["pool"]["dtype"] == "int8"


def test_int8_pool_doubles_slots_per_gb(decoder, int8_engine):
    model, _ = decoder
    fp32 = model.new_pool(max_slots=4)
    ratio = int8_engine.pool.slots_per_gb() / fp32.slots_per_gb()
    assert ratio >= 2.0, f"int8 density ratio {ratio:.2f} < 2x"


def test_int8_pool_poison_isolation(decoder, int8_engine):
    """Slot reuse on a QUANTIZED pool: poisoned codes+scales in every
    uninitialized position (the fixture's prefill window leaves positions
    past 16 untouched) never reach any lane's output — through the
    SPECULATIVE verify path too, since the fixture drafts."""
    _, ref = decoder
    work = _workload(6, seed=6, max_new_hi=10)
    int8_engine.pool.poison(1e9)
    futs = [int8_engine.submit(p, m) for p, m in work]
    outs = [f.result(timeout=120) for f in futs]
    assert int8_engine.assert_no_retraces() == 0
    for (p, m), o in zip(work, outs):
        np.testing.assert_array_equal(
            o, ref.reference_generate(p, m, window=16, kv_dtype="int8"),
            err_msg=f"int8 poison leaked for prompt {p}")


def test_memory_plans_cover_quantized_spec_programs(int8_engine):
    """memory_plans() lowers the EXACT warmup avals — int8 slab +
    per-position scale pairs and the speculative token-history page —
    so the PR-15 plan surface keeps working on the new program family."""
    plans = int8_engine.memory_plans()
    assert set(plans) == {"prefill", "decode"}
    for key, plan in plans.items():
        assert plan["name"].endswith(key)
        assert plan.get("complete") in (True, False)


# ---------------------------------------------------------------------------
# fleet wire: sampling params ride the request message
# ---------------------------------------------------------------------------
def test_fleet_submit_validates_and_stub_wire_compat(tmp_path):
    """Fleet.submit validates sampling params router-side, and a sampled
    request survives the wire to a stub replica (which ignores sampling
    but must ACCEPT the message — protocol compatibility with engines
    that predate the knobs)."""
    spec = {"version": "v1", "stub": True, "stub_delay_ms": 2.0}
    fleet = serve.Fleet(spec, replicas=1, heartbeat_ms=200,
                        workdir=str(tmp_path))
    fleet.start()
    try:
        with pytest.raises(serve.ServeError, match="temperature"):
            fleet.submit([1, 2], 4, temperature=-1.0)
        with pytest.raises(serve.ServeError, match="top_p"):
            fleet.submit([1, 2], 4, temperature=1.0, top_p=0.0)
        greedy = fleet.generate([3, 1, 4], max_new_tokens=6, timeout=60)
        sampled = fleet.generate([3, 1, 4], max_new_tokens=6, timeout=60,
                                 temperature=3.0, top_k=8, seed=42)
    finally:
        fleet.close()
    # the stub's deterministic pattern ignores sampling: identical output
    # proves the extra wire fields were carried and tolerated
    np.testing.assert_array_equal(greedy, sampled)


# ---------------------------------------------------------------------------
# committed artifact: the ISSUE-17 acceptance numbers
# ---------------------------------------------------------------------------
def test_committed_decode_artifact_acceptance():
    """The committed r17 artifact holds the ISSUE-17 acceptance: >= 1.5x
    decode tokens/s from speculative decoding on the r14 workload
    (wall-clock in the single-stream latency-bound arm — speculation's
    deployment regime — plus the acceptance-weighted per-wave ceiling)
    at token-exact quality, zero retraces on every arm, and int8 KV at
    >= 2x slots-per-GB — with an honest paged_pallas_active stamp."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "results",
        "decode_r17.json")
    data = json.load(open(path))
    assert data["backend_ok"] is True
    assert data["meta"]["concurrency"] == 32
    assert data["meta"]["draft_tokens"] >= 2
    # the realized wall-clock win in the latency-bound arm, and the
    # acceptance-weighted tokens-per-verify-wave ceiling (what a
    # memory-bound accelerator converts to wall-clock at saturation)
    assert data["serve_decode_speedup_spec"] >= 1.5
    assert data["serve_decode_tokens_per_verify_wave"] >= 1.5
    assert data["latency_spec"]["decode_tokens_per_sec"] \
        > data["latency_plain"]["decode_tokens_per_sec"]
    assert data["serve_decode_tokens_per_sec_spec"] \
        == data["latency_spec"]["decode_tokens_per_sec"]
    assert data["spec_token_exact"] is True
    assert data["spec_token_exact_checked"] >= 4
    for arm in ("plain", "spec", "spec_int8", "latency_plain",
                "latency_spec"):
        assert data[arm]["retraces_after_warmup"] == 0, arm
    assert 0.0 < data["spec"]["draft_acceptance"] <= 1.0
    kv = data["kv_slots_per_gb"]
    assert kv["ratio"] >= 2.0
    assert kv["int8"] > kv["float32"]
    # honesty stamp: CPU CI must not claim the TPU kernel ran compiled,
    # and the note must say which regime the committed speedup comes from
    assert isinstance(data["paged_pallas_active"], bool)
    assert "single-stream" in data["note"]
