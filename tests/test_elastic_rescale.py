"""Elastic restart: rescale a sharded training checkpoint onto a
DIFFERENT mesh size and keep training (the recipe the reference's
ps-lite elasticity story never shipped; VERDICT §2.3 elastic row)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import checkpoint as ckpt


def _mesh(devs, dp, tp, sp=1):
    import jax
    from jax.sharding import Mesh
    n = dp * sp * tp
    return Mesh(np.array(devs[:n]).reshape(dp, sp, tp),
                ("dp", "sp", "tp"))


def test_rescale_roundtrip_and_shrink(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the forced 8-device mesh")
    mesh8 = _mesh(devs, 4, 2)
    rng = np.random.RandomState(0)
    state = {
        "w": jax.device_put(rng.randn(8, 16).astype(np.float32),
                            NamedSharding(mesh8, P("tp", None))),
        "m": jax.device_put(rng.randn(8, 16).astype(np.float32),
                            NamedSharding(mesh8, P("tp", None))),
        "step": jax.device_put(np.float32(7.0),
                               NamedSharding(mesh8, P())),
    }
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, state, step=3)

    specs = {"w": P("tp", None), "m": P("tp", None), "step": None}
    mesh4 = _mesh(devs, 2, 2)
    tree, step = ckpt.rescale_sharded(d, mesh4, specs)
    assert step == 3
    for k in ("w", "m"):
        np.testing.assert_array_equal(np.asarray(tree[k]),
                                      np.asarray(state[k]))
        assert tree[k].sharding.mesh.devices.size == 4
    assert float(tree["step"]) == 7.0

    # grow back
    tree8, _ = ckpt.rescale_sharded(d, mesh8, specs)
    assert tree8["w"].sharding.mesh.devices.size == 8
    np.testing.assert_array_equal(np.asarray(tree8["w"]),
                                  np.asarray(state["w"]))


def test_flagship_training_resumes_on_smaller_mesh(tmp_path):
    """The full recipe: save flagship params+opt sharded under dp=4,tp=2;
    restart on dp=2,tp=2 and run a REAL train step — losses stay finite
    and the resharded weights are bit-identical before the step."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.models import transformer as tfm

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the forced 8-device mesh")
    cfg = tfm.TransformerConfig(vocab_size=128, num_layers=1, d_model=32,
                                num_heads=4, d_ff=64, max_seq_len=32,
                                dtype="float32")
    mesh8 = _mesh(devs, 2, 2, sp=2)
    with mesh8:
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        pspecs = tfm.param_shardings(cfg, mesh8)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh8, s)),
            params, pspecs,
            is_leaf=lambda x: not isinstance(x, (dict, list)))
        opt = tfm.init_opt_state(params)
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, {"params": params, "opt": opt}, step=11)
    flat_before = jax.tree_util.tree_leaves(params)

    mesh4 = _mesh(devs, 2, 2, sp=1)
    pspecs4 = tfm.param_shardings(cfg, mesh4)
    # the transformer opt state is an (m, v) pair of param-shaped trees
    # (orbax restores tuples as lists, so the spec uses a list too)
    tree, step = ckpt.rescale_sharded(
        d, mesh4, {"params": pspecs4, "opt": [pspecs4, pspecs4]})
    assert step == 11
    flat_after = jax.tree_util.tree_leaves(tree["params"])
    for a, b in zip(flat_before, flat_after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with mesh4:
        step_fn = tfm.make_train_step(cfg, mesh4)
        tokens = np.random.RandomState(1).randint(
            0, cfg.vocab_size, (4, 17)).astype(np.int32)
        batch = {"tokens": jax.device_put(
            tokens, NamedSharding(mesh4, P("dp", None)))}
        t = jax.device_put(np.int32(11), NamedSharding(mesh4, P()))
        opt4 = tuple(tree["opt"])   # orbax restores the (m, v) pair as list
        new_params, new_opt, loss = step_fn(tree["params"], opt4, batch, t)
        assert np.isfinite(float(loss))


# ---------------------------------------------------------------------------
# Repartition spec leaves (ZeRO shard views; ISSUE-12 edge cases)
# ---------------------------------------------------------------------------
def _dp_mesh(dp):
    import jax
    from jax.sharding import Mesh
    devs = jax.devices()
    if len(devs) < max(dp, 8):
        pytest.skip("needs the forced 8-device mesh")
    return Mesh(np.array(devs[:dp]), ("dp",))


def test_repartition_uneven_shard_counts_dp3_to_2(tmp_path):
    """dp=3 -> 2: the saved (3, L) view does not divide the new dp —
    Repartition must drop the OLD padding and re-pad for the new dp."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.optimizer.sharded import to_shards

    numel = 10                      # -> (3, 4) padded, 2 pad elements
    flat = np.arange(numel, dtype=np.float32) + 1.0
    mesh3 = _dp_mesh(3)
    state = {"m": jax.device_put(to_shards(flat, 3),
                                 NamedSharding(mesh3, P("dp", None)))}
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, state, step=1)

    mesh2 = _dp_mesh(2)
    tree, step = ckpt.rescale_sharded(
        d, mesh2, {"m": ckpt.Repartition(numel)})
    assert step == 1
    got = tree["m"]
    assert got.shape == (2, 5)      # re-padded for dp=2
    assert got.sharding.mesh.devices.size == 2
    view = np.asarray(got).reshape(-1)
    np.testing.assert_array_equal(view[:numel], flat)
    np.testing.assert_array_equal(view[numel:], 0)   # fresh padding


def test_repartition_preserves_dtype_and_scalar_leaves(tmp_path):
    """dtype preservation (f16 shards stay f16) and scalar/0-d leaves
    riding replicated next to Repartition leaves."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.optimizer.sharded import to_shards

    mesh8 = _dp_mesh(8)
    flat16 = (np.arange(12, dtype=np.float16) / 8).astype(np.float16)
    state = {
        "m16": jax.device_put(to_shards(flat16, 8),
                              NamedSharding(mesh8, P("dp", None))),
        "count": jax.device_put(np.float32(17.0),
                                NamedSharding(mesh8, P())),
    }
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, state, step=2)

    mesh4 = _dp_mesh(4)
    tree, _ = ckpt.rescale_sharded(
        d, mesh4, {"m16": ckpt.Repartition(12), "count": None})
    assert np.asarray(tree["m16"]).dtype == np.float16
    assert tree["m16"].shape == (4, 3)
    np.testing.assert_array_equal(
        np.asarray(tree["m16"]).reshape(-1)[:12], flat16)
    assert float(tree["count"]) == 17.0     # 0-d leaf: replicated restore


def test_repartition_validates_numel_and_axis(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.optimizer.sharded import to_shards

    mesh2 = _dp_mesh(2)
    state = {"m": jax.device_put(to_shards(np.ones(6, np.float32), 2),
                                 NamedSharding(mesh2, P("dp", None)))}
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, state, step=1)
    with pytest.raises(mx.MXNetError, match="exceeds"):
        ckpt.rescale_sharded(d, mesh2, {"m": ckpt.Repartition(99)})
    with pytest.raises(mx.MXNetError, match="axis"):
        ckpt.rescale_sharded(d, mesh2,
                             {"m": ckpt.Repartition(6, axis="tp")})
