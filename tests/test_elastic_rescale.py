"""Elastic restart: rescale a sharded training checkpoint onto a
DIFFERENT mesh size and keep training (the recipe the reference's
ps-lite elasticity story never shipped; VERDICT §2.3 elastic row)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import checkpoint as ckpt


def _mesh(devs, dp, tp, sp=1):
    import jax
    from jax.sharding import Mesh
    n = dp * sp * tp
    return Mesh(np.array(devs[:n]).reshape(dp, sp, tp),
                ("dp", "sp", "tp"))


def test_rescale_roundtrip_and_shrink(tmp_path):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the forced 8-device mesh")
    mesh8 = _mesh(devs, 4, 2)
    rng = np.random.RandomState(0)
    state = {
        "w": jax.device_put(rng.randn(8, 16).astype(np.float32),
                            NamedSharding(mesh8, P("tp", None))),
        "m": jax.device_put(rng.randn(8, 16).astype(np.float32),
                            NamedSharding(mesh8, P("tp", None))),
        "step": jax.device_put(np.float32(7.0),
                               NamedSharding(mesh8, P())),
    }
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, state, step=3)

    specs = {"w": P("tp", None), "m": P("tp", None), "step": None}
    mesh4 = _mesh(devs, 2, 2)
    tree, step = ckpt.rescale_sharded(d, mesh4, specs)
    assert step == 3
    for k in ("w", "m"):
        np.testing.assert_array_equal(np.asarray(tree[k]),
                                      np.asarray(state[k]))
        assert tree[k].sharding.mesh.devices.size == 4
    assert float(tree["step"]) == 7.0

    # grow back
    tree8, _ = ckpt.rescale_sharded(d, mesh8, specs)
    assert tree8["w"].sharding.mesh.devices.size == 8
    np.testing.assert_array_equal(np.asarray(tree8["w"]),
                                  np.asarray(state["w"]))


def test_flagship_training_resumes_on_smaller_mesh(tmp_path):
    """The full recipe: save flagship params+opt sharded under dp=4,tp=2;
    restart on dp=2,tp=2 and run a REAL train step — losses stay finite
    and the resharded weights are bit-identical before the step."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu.models import transformer as tfm

    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the forced 8-device mesh")
    cfg = tfm.TransformerConfig(vocab_size=128, num_layers=1, d_model=32,
                                num_heads=4, d_ff=64, max_seq_len=32,
                                dtype="float32")
    mesh8 = _mesh(devs, 2, 2, sp=2)
    with mesh8:
        params = tfm.init_params(jax.random.PRNGKey(0), cfg)
        pspecs = tfm.param_shardings(cfg, mesh8)
        params = jax.tree_util.tree_map(
            lambda p, s: jax.device_put(p, NamedSharding(mesh8, s)),
            params, pspecs,
            is_leaf=lambda x: not isinstance(x, (dict, list)))
        opt = tfm.init_opt_state(params)
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, {"params": params, "opt": opt}, step=11)
    flat_before = jax.tree_util.tree_leaves(params)

    mesh4 = _mesh(devs, 2, 2, sp=1)
    pspecs4 = tfm.param_shardings(cfg, mesh4)
    # the transformer opt state is an (m, v) pair of param-shaped trees
    # (orbax restores tuples as lists, so the spec uses a list too)
    tree, step = ckpt.rescale_sharded(
        d, mesh4, {"params": pspecs4, "opt": [pspecs4, pspecs4]})
    assert step == 11
    flat_after = jax.tree_util.tree_leaves(tree["params"])
    for a, b in zip(flat_before, flat_after):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    with mesh4:
        step_fn = tfm.make_train_step(cfg, mesh4)
        tokens = np.random.RandomState(1).randint(
            0, cfg.vocab_size, (4, 17)).astype(np.int32)
        batch = {"tokens": jax.device_put(
            tokens, NamedSharding(mesh4, P("dp", None)))}
        t = jax.device_put(np.int32(11), NamedSharding(mesh4, P()))
        opt4 = tuple(tree["opt"])   # orbax restores the (m, v) pair as list
        new_params, new_opt, loss = step_fn(tree["params"], opt4, batch, t)
        assert np.isfinite(float(loss))
