"""gluon.rnn tests (≙ reference tests/python/unittest/test_gluon_rnn.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon import rnn


def test_lstm_cell_shapes():
    cell = rnn.LSTMCell(8, input_size=4)
    cell.initialize()
    x = mx.np.array(np.random.randn(2, 4).astype(np.float32))
    out, states = cell(x, cell.begin_state(2))
    assert out.shape == (2, 8)
    assert len(states) == 2


def test_gru_rnn_cells():
    for cell in (rnn.GRUCell(6, input_size=3), rnn.RNNCell(6, input_size=3)):
        cell.initialize()
        x = mx.np.array(np.random.randn(2, 3).astype(np.float32))
        out, states = cell(x, cell.begin_state(2))
        assert out.shape == (2, 6)
        assert len(states) == 1


def test_unroll_merge():
    cell = rnn.GRUCell(5, input_size=3)
    cell.initialize()
    seq = mx.np.array(np.random.randn(2, 7, 3).astype(np.float32))
    merged, states = cell.unroll(7, seq, layout="NTC")
    assert merged.shape == (2, 7, 5)
    outs, _ = cell.unroll(7, seq, layout="NTC", merge_outputs=False)
    assert len(outs) == 7 and outs[0].shape == (2, 5)


def test_sequential_cell():
    stack = rnn.SequentialRNNCell()
    stack.add(rnn.LSTMCell(4, input_size=3))
    stack.add(rnn.LSTMCell(5, input_size=4))
    stack.initialize()
    x = mx.np.array(np.random.randn(2, 3).astype(np.float32))
    out, states = stack(x, stack.begin_state(2))
    assert out.shape == (2, 5)
    assert len(states) == 4


def test_residual_dropout_cells():
    cell = rnn.ResidualCell(rnn.GRUCell(3, input_size=3))
    cell.initialize()
    x = mx.np.array(np.random.randn(2, 3).astype(np.float32))
    out, _ = cell(x, cell.begin_state(2))
    assert out.shape == (2, 3)

    d = rnn.DropoutCell(0.5)
    out2, _ = d(x, [])
    assert out2.shape == x.shape


def test_fused_lstm_fwd_bwd():
    lstm = rnn.LSTM(16, num_layers=2)
    lstm.initialize()
    seq = mx.np.array(np.random.randn(5, 3, 6).astype(np.float32))
    with mx.autograd.record():
        out = lstm(seq)
        out.sum().backward()
    assert out.shape == (5, 3, 16)
    g = lstm.l0_i2h_weight.grad()
    assert np.isfinite(g.asnumpy()).all() and abs(g.asnumpy()).sum() > 0


def test_fused_bidirectional_states():
    lstm = rnn.LSTM(8, bidirectional=True, layout="NTC")
    lstm.initialize()
    seq = mx.np.array(np.random.randn(3, 5, 4).astype(np.float32))
    out, states = lstm(seq, lstm.begin_state(3))
    assert out.shape == (3, 5, 16)
    assert states[0].shape == (2, 3, 8)
    assert states[1].shape == (2, 3, 8)


def test_fused_vs_cell_unroll_match():
    """Fused GRU layer must match the composable GRUCell scan numerically."""
    gru = rnn.GRU(4, input_size=3)
    gru.initialize()
    cell = rnn.GRUCell(4, input_size=3)
    cell.initialize()
    cell.i2h_weight.set_data(gru.l0_i2h_weight.data())
    cell.h2h_weight.set_data(gru.l0_h2h_weight.data())
    cell.i2h_bias.set_data(gru.l0_i2h_bias.data())
    cell.h2h_bias.set_data(gru.l0_h2h_bias.data())
    seq = mx.np.array(np.random.randn(6, 2, 3).astype(np.float32))
    fused = gru(seq).asnumpy()
    merged, _ = cell.unroll(6, seq, layout="TNC")
    np.testing.assert_allclose(fused, merged.asnumpy(), rtol=1e-5, atol=1e-6)


def test_rnn_relu_mode():
    net = rnn.RNN(8, activation="relu")
    net.initialize()
    seq = mx.np.array(np.random.randn(4, 2, 3).astype(np.float32))
    assert net(seq).shape == (4, 2, 8)
