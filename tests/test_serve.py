"""mx.serve: dynamic-batching inference server over exported artifacts.

Contracts under test (ISSUE 3 acceptance):
  * batched results are bit-identical to direct ExportedModel.run
  * a mixed-batch-size request stream performs ZERO recompiles after
    warmup (compile/dispatch counters: `programs_compiled` and the jit
    compile-cache size both stay flat)
  * overload sheds or rejects per policy instead of deadlocking, proven
    under MXNET_FAULT_SPEC injection (env-armed subprocess + fault.scope)
  * deadlines fail fast with typed errors; execution faults fail the batch
    but not the server
  * ExportedModel.run is safe to share across worker threads (the
    jit-call concurrency contract deploy.py documents)
"""
import json
import os
import subprocess
import sys
import threading
import time
from concurrent.futures import wait

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, profiler, serve
from incubator_mxnet_tpu.gluon import nn


@pytest.fixture(scope="module")
def exported(tmp_path_factory):
    """One small block exported at buckets {1, 2, 4} + the live block."""
    d = tmp_path_factory.mktemp("serve_artifacts")
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=6), nn.Dense(3))
    net.initialize()
    net.hybridize()
    model = serve.BucketedModel.export_block(net, (6,), [1, 2, 4], str(d),
                                             name="mlp")
    return net, model


def _rows(n, dim=6, seed=0):
    rng = np.random.RandomState(seed)
    return [rng.rand(dim).astype(np.float32) for _ in range(n)]


def _callable_model(dim=3, buckets=(1, 2, 4)):
    import jax.numpy as jnp
    W = np.linspace(-1, 1, dim * 2).reshape(dim, 2).astype(np.float32)
    return serve.CallableModel(lambda x: jnp.tanh(x @ W), buckets,
                               [((dim,), "float32")]), W


# ---------------------------------------------------------------------------
# correctness
# ---------------------------------------------------------------------------
def test_batched_matches_direct_run(exported):
    net, model = exported
    with serve.Server(model, batch_timeout_ms=5.0) as srv:
        xs = _rows(11)
        futs = [srv.submit(x) for x in xs]
        for x, f in zip(xs, futs):
            ref = net(mx.np.array(x[None])).asnumpy()[0]
            np.testing.assert_allclose(f.result(timeout=30), ref,
                                       rtol=1e-5, atol=1e-6)
        st = srv.stats()
        assert st["replies"] == 11
        assert st["buckets"] == [1, 2, 4]


def test_concurrent_submitters_all_served(exported):
    net, model = exported
    with serve.Server(model, batch_timeout_ms=2.0, max_queue=512) as srv:
        results = {}
        lock = threading.Lock()

        def client(tid):
            xs = _rows(8, seed=tid)
            outs = [srv.predict(x, timeout=30) for x in xs]
            with lock:
                results[tid] = (xs, outs)

        threads = [threading.Thread(target=client, args=(t,))
                   for t in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=60)
        assert len(results) == 8
        for xs, outs in results.values():
            for x, o in zip(xs, outs):
                ref = net(mx.np.array(x[None])).asnumpy()[0]
                np.testing.assert_allclose(o, ref, rtol=1e-5, atol=1e-6)


def test_multi_input_model():
    import jax.numpy as jnp
    model = serve.CallableModel(lambda a, b: a * 2.0 + b, (1, 2),
                                [((3,), "float32"), ((3,), "float32")],
                                single_output=True)
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        a, b = np.ones(3, np.float32), np.arange(3, dtype=np.float32)
        np.testing.assert_allclose(srv.predict(a, b), a * 2 + b)


def test_bfloat16_rows_batch_and_pad():
    """bf16 exports serve correctly: row casts and pad-row allocation go
    through the bf16-aware dtype mapping, not raw numpy dtype strings."""
    import jax.numpy as jnp
    model = serve.CallableModel(lambda x: x * 2.0, (1, 2, 4),
                                [((3,), "bfloat16")])
    with serve.Server(model, batch_timeout_ms=2.0) as srv:
        xs = _rows(3, dim=3)                     # float32 in, cast to bf16
        outs = [srv.predict(x, timeout=30) for x in xs]
        for x, o in zip(xs, outs):
            assert str(o.dtype) == "bfloat16"
            np.testing.assert_allclose(o.astype(np.float32), x * 2.0,
                                       rtol=2e-2)


def test_input_validation(exported):
    _, model = exported
    with serve.Server(model) as srv:
        with pytest.raises(serve.ServeError, match="sample shape"):
            srv.submit(np.zeros((2, 6), np.float32))   # batched input
        with pytest.raises(serve.ServeError, match="takes 1 inputs"):
            srv.submit(np.zeros(6, np.float32), np.zeros(6, np.float32))


def test_pick_bucket():
    assert serve.pick_bucket(1, [1, 2, 4]) == 1
    assert serve.pick_bucket(3, [1, 2, 4]) == 4
    assert serve.pick_bucket(4, [1, 2, 4]) == 4
    assert serve.pick_bucket(5, [1, 2, 4]) is None


# ---------------------------------------------------------------------------
# zero-retrace steady state (the compile/dispatch-counter acceptance)
# ---------------------------------------------------------------------------
def test_mixed_batch_stream_zero_recompiles_after_warmup(exported):
    net, model = exported
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        warm_ccs = model.compile_cache_size()
        assert warm_ccs == 3          # one program per bucket, compiled
        warm_programs = srv.stats()["programs_compiled"]
        assert warm_programs == 3
        # mixed-size bursts: 1, 3, 2, 4, 1, 2 ... pad onto {1,2,4}
        for burst in (1, 3, 2, 4, 1, 2, 3, 4, 1):
            futs = [srv.submit(x) for x in _rows(burst, seed=burst)]
            wait(futs, timeout=30)
            assert all(f.exception() is None for f in futs)
        st = srv.stats()
        assert st["compile_cache_size"] == warm_ccs, \
            "steady-state serving retraced a bucket program"
        assert st["programs_compiled"] == warm_programs
        # occupancy histogram saw more than one bucket
        assert len(st["batch_occupancy"]) >= 2


# ---------------------------------------------------------------------------
# overload: admission control, shed/reject policies, deadlines
# ---------------------------------------------------------------------------
def test_reject_newest_policy_fails_fast():
    model, _ = _callable_model()
    srv = serve.Server(model, max_queue=2, batch_timeout_ms=50.0,
                       overload_policy="reject").start()
    try:
        with fault.scope("serve.execute:*:stall:0.15"):
            admitted = []
            rejected = 0
            for x in _rows(20, dim=3):
                try:
                    admitted.append(srv.submit(x))
                except serve.QueueFullError as e:
                    assert e.policy == "reject"
                    rejected += 1
            assert rejected > 0
        # server keeps serving: drain succeeds, no deadlock
        srv.close(drain=True)
        done = [f for f in admitted if f.exception() is None]
        assert done, "no admitted request was ever served"
        assert srv.stats()["rejected"] == rejected
    finally:
        srv.close()


def test_shed_oldest_policy_fails_queued_requests():
    model, _ = _callable_model()
    srv = serve.Server(model, max_queue=2, batch_timeout_ms=50.0,
                       overload_policy="shed").start()
    try:
        with fault.scope("serve.execute:*:stall:0.15"):
            futs = [srv.submit(x) for x in _rows(12, dim=3)]
        srv.close(drain=True)
        shed = [f for f in futs if isinstance(f.exception(),
                                              serve.QueueFullError)]
        served = [f for f in futs if f.exception() is None]
        assert shed and served
        assert all(e.exception().policy == "shed" for e in shed)
        assert srv.stats()["shed"] == len(shed)
    finally:
        srv.close()


def test_deadline_expires_in_queue():
    model, _ = _callable_model()
    srv = serve.Server(model, batch_timeout_ms=1.0).start()
    try:
        with fault.scope("serve.execute:1:stall:0.25"):
            f1 = srv.submit(np.ones(3, np.float32))   # occupies the batcher
            time.sleep(0.02)
            f2 = srv.submit(np.ones(3, np.float32), deadline_ms=50)
            with pytest.raises(serve.RequestTimeout):
                f2.result(timeout=10)
            assert f1.result(timeout=10) is not None
        assert srv.stats()["timeouts"] == 1
    finally:
        srv.close()


def test_overload_no_deadlock_under_env_fault_spec(tmp_path):
    """The acceptance wording verbatim: overload sheds/rejects per policy
    under MXNET_FAULT_SPEC (armed via the env var, fresh process)."""
    prog = r"""
import numpy as np
from incubator_mxnet_tpu import serve
import jax.numpy as jnp
model = serve.CallableModel(lambda x: x * 2.0, [1, 2],
                            [((3,), "float32")])
srv = serve.Server(model, max_queue=2, batch_timeout_ms=20.0,
                   overload_policy="shed").start()
futs = [srv.submit(np.ones(3, np.float32)) for _ in range(12)]
srv.close(drain=True)
shed = sum(isinstance(f.exception(), serve.QueueFullError) for f in futs)
served = sum(f.exception() is None for f in futs)
assert shed > 0 and served > 0, (shed, served)
print("SHED", shed, "SERVED", served)
"""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu",
               MXNET_FAULT_SPEC="serve.execute:*:stall:0.1")
    r = subprocess.run([sys.executable, "-c", prog], env=env,
                       capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "SHED" in r.stdout


def test_execute_fault_fails_batch_not_server():
    model, W = _callable_model()
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        with fault.scope("serve.execute:1:error"):
            f = srv.submit(np.ones(3, np.float32))
            with pytest.raises(fault.InjectedFault):
                f.result(timeout=10)
        # server still alive and correct afterwards
        x = np.full(3, 0.5, np.float32)
        np.testing.assert_allclose(srv.predict(x, timeout=10),
                                   np.tanh(x @ W), rtol=1e-5)
        st = srv.stats()
        assert st["errors"] == 1 and st["replies"] == 1


def test_closed_server_rejects_submissions():
    model, _ = _callable_model()
    srv = serve.Server(model).start()
    srv.close()
    with pytest.raises(serve.ServerClosed):
        srv.submit(np.ones(3, np.float32))


def test_close_without_drain_fails_pending():
    model, _ = _callable_model()
    srv = serve.Server(model, batch_timeout_ms=100.0, max_queue=64).start()
    with fault.scope("serve.execute:*:stall:0.2"):
        futs = [srv.submit(x) for x in _rows(6, dim=3)]
        srv.close(drain=False)
    failed = [f for f in futs if isinstance(f.exception(),
                                            serve.ServerClosed)]
    assert failed, "non-draining close left requests pending"


# ---------------------------------------------------------------------------
# regression (ISSUE 14 satellite): pad-row mask. Batches are zero-padded
# up to their bucket, so output rows [n:] are pad garbage — the execute
# path must slice them off explicitly, and an output that does not carry
# the batch dim (no row<->request correspondence: indexing it would hand
# requesters data mixing in pad rows) must fail TYPED, never reply.
# ---------------------------------------------------------------------------
def test_pad_rows_never_leak_into_replies():
    # fn(x) maps zero pad rows to the sentinel 5.0 — if any pad row
    # leaked into a reply, the requester would see 5s instead of its
    # own transform
    model = serve.CallableModel(lambda x: x * 2.0 + 5.0, (4,),
                                [((3,), "float32")])
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        xs = _rows(7, dim=3, seed=21)
        outs = [srv.predict(x, timeout=30) for x in xs]
        for x, o in zip(xs, outs):
            assert o.shape == (3,)
            np.testing.assert_allclose(o, x * 2.0 + 5.0, rtol=1e-6)


def test_batch_reducing_output_fails_typed_not_garbage():
    # a model that reduces over the batch axis: its output has NO pad
    # mask (every element mixes the zero pad rows in) — the server must
    # fail the batch with a typed error instead of slicing nonsense
    model = serve.CallableModel(lambda x: x.sum(axis=0), (2,),
                                [((3,), "float32")])
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        f = srv.submit(np.ones(3, np.float32))
        with pytest.raises(serve.ServeError, match="pad"):
            f.result(timeout=30)
        # the server survives the failed batch
        assert srv.stats()["errors"] == 1


# ---------------------------------------------------------------------------
# metrics + observability
# ---------------------------------------------------------------------------
def test_metrics_surface(exported):
    _, model = exported
    serve.stats(reset=True)
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        futs = [srv.submit(x) for x in _rows(9)]
        wait(futs, timeout=30)
        st = srv.stats()
    assert st["requests"] == 9 and st["replies"] == 9
    assert st["p50_ms"] is not None and st["p99_ms"] is not None
    assert st["p50_ms"] <= st["p99_ms"]
    assert st["requests_per_sec"] > 0
    occ = st["batch_occupancy"]
    assert sum(r["rows"] for r in occ.values()) == 9
    for b, r in occ.items():
        assert 0 < r["mean_occupancy"] <= 1.0
    # process-wide counter surface (profiler-style), also via profiler
    agg = profiler.serve_stats()
    assert agg["replies"] >= 9
    assert json.dumps(st)      # snapshot is plain json-able data


def test_chrome_trace_serve_lane(exported, tmp_path):
    _, model = exported
    profiler.start()
    try:
        with serve.Server(model, batch_timeout_ms=1.0) as srv:
            wait([srv.submit(x) for x in _rows(5)], timeout=30)
    finally:
        profiler.stop()
    f = str(tmp_path / "trace.json")
    profiler.dump(filename=f)
    events = json.load(open(f))["traceEvents"]
    lane = [e for e in events if e["name"] == "serve.batch"]
    assert lane, "no serve.batch events in the Chrome trace"
    assert all(e["cat"] == "serve" for e in lane)
    assert all("bucket" in e["args"] and "occupancy" in e["args"]
               for e in lane)


# ---------------------------------------------------------------------------
# deploy.py concurrency contract (satellite)
# ---------------------------------------------------------------------------
def test_exported_model_run_thread_safe(exported):
    net, model = exported
    m1 = model._models[1]
    m1.warmup()
    ccs0 = m1.compile_cache_size()
    xs = _rows(8, seed=11)
    refs = [net(mx.np.array(x[None])).asnumpy()[0] for x in xs]
    errs = []

    def hammer(tid):
        try:
            for _ in range(25):
                got = m1.run(xs[tid][None])
                np.testing.assert_allclose(got[0], refs[tid],
                                           rtol=1e-5, atol=1e-6)
        except Exception as e:
            errs.append(e)

    threads = [threading.Thread(target=hammer, args=(t,)) for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs
    assert m1.compile_cache_size() == ccs0, \
        "concurrent run() retraced the exported program"


# ---------------------------------------------------------------------------
# CI smoke: the load generator produces valid JSON in --quick mode
# ---------------------------------------------------------------------------
def test_serve_bench_quick_smoke(tmp_path):
    out = tmp_path / "serve_quick.json"
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "serve_bench.py")
    r = subprocess.run(
        [sys.executable, script, "--quick", "--duration", "1.0",
         "--out", str(out)],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(out.read_text())
    assert data["meta"]["quick"] is True
    assert data["meta"]["concurrency"] == 32
    for mode in ("serial", "batched"):
        assert data[mode]["requests_per_sec"] > 0
        assert data[mode]["p99_ms"] >= data[mode]["p50_ms"]
    # steady state stayed on the warmed bucket programs
    assert (data["batched"]["compile_cache_size_final"]
            == data["batched"]["compile_cache_size_after_warmup"])
    # the artifact reports through the telemetry registry and carries the
    # backend preflight verdict benchdiff keys on
    assert data["backend_ok"] is True
    assert data["telemetry"]["serve.batches"] > 0


# ---------------------------------------------------------------------------
# regression (mxlint lock-shared-mutation): SERVE_STATS increments are a
# read-modify-write — off-lock they lose counts under thread contention,
# and serve_stats(reset=True) could eat increments landing between its
# snapshot and its zeroing. Both now run under metrics._STATS_LOCK.
# ---------------------------------------------------------------------------
def test_serve_stats_counters_exact_under_contention():
    from incubator_mxnet_tpu.serve.metrics import ServeMetrics

    n_threads, n_iter = 8, 500
    before = profiler.serve_stats()
    m = ServeMetrics()
    errs = []

    def hammer():
        try:
            for _ in range(n_iter):
                m.count("requests")
                m.observe_batch(bucket=2, occupancy=1, exec_ms=0.0,
                                queue_depth=0)
        except BaseException as e:   # pragma: no cover - diagnostics
            errs.append(e)

    threads = [threading.Thread(target=hammer) for _ in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs

    total = n_threads * n_iter
    snap = m.snapshot()
    assert snap["requests"] == total
    assert snap["batches"] == total
    assert snap["padded_rows"] == total          # one pad row per batch
    after = profiler.serve_stats()
    assert after["requests"] - before["requests"] == total
    assert after["batches"] - before["batches"] == total
    assert after["padded_rows"] - before["padded_rows"] == total


def test_serve_stats_reset_is_atomic_with_snapshot():
    from incubator_mxnet_tpu.serve import metrics as sm

    profiler.serve_stats(reset=True)
    stop = threading.Event()
    sent = [0]

    def incrementer():
        m = sm.ServeMetrics()
        n = 0
        while not stop.is_set():
            m.count("replies")
            n += 1
        sent[0] = n

    t = threading.Thread(target=incrementer)
    t.start()
    try:
        # snapshot+zero is one atomic step, so every increment lands in
        # EXACTLY one reset window: the windowed sums must add up to the
        # incrementer's own call count — the pre-fix racy reset lost the
        # increments that arrived between its copy and its zeroing
        collected = 0
        for _ in range(200):
            collected += profiler.serve_stats(reset=True)["replies"]
    finally:
        stop.set()
        t.join(timeout=60)
    collected += profiler.serve_stats(reset=True)["replies"]
    assert collected == sent[0]
    assert profiler.serve_stats()["replies"] == 0
