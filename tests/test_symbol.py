"""mx.symbol: legacy graph API + serialized symbol.json parity
(≙ reference tests/python/unittest/test_symbol.py + the
legacy_json_util.cc format contract).

The format check runs against a REAL reference artifact
(tests/python/mkl/data/*_model1.json, a VGG16 graph) when the reference
tree is present.
"""
import json
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import symbol as sym

REF_JSON = ("/root/reference/tests/python/mkl/data/"
            "test_mkldnn_test_mkldnn_model_model1.json")


def _small_net():
    data = sym.var("data")
    c1 = sym.Convolution(data=data, kernel=(3, 3), num_filter=8,
                         pad=(1, 1), name="c1")
    bn = sym.BatchNorm(data=c1, fix_gamma=False, name="bn1")
    act = sym.Activation(data=bn, act_type="relu", name="r1")
    p = sym.Pooling(data=act, global_pool=True, pool_type="avg",
                    kernel=(1, 1), name="gap")
    f = sym.Flatten(data=p, name="flat")
    fc = sym.FullyConnected(data=f, num_hidden=10, name="fc")
    return sym.softmax(data=fc, name="sm")


def test_builder_introspection():
    s = _small_net()
    args = s.list_arguments()
    assert args[0] == "data"
    assert "c1_weight" in args and "c1_bias" in args
    assert "bn1_gamma" in args and "bn1_beta" in args
    assert s.list_auxiliary_states() == ["bn1_moving_mean",
                                         "bn1_moving_var"]
    assert s.list_outputs() == ["sm_output"]


def test_infer_shape_small():
    s = _small_net()
    arg_shapes, out_shapes, aux_shapes = s.infer_shape(data=(2, 3, 16, 16))
    d = dict(zip(s.list_arguments(), arg_shapes))
    assert d["c1_weight"] == (8, 3, 3, 3)
    assert d["c1_bias"] == (8,)
    assert d["fc_weight"] == (10, 8)
    assert out_shapes == [(2, 10)]
    assert aux_shapes == [(8,), (8,)]


def test_json_roundtrip_format():
    s = _small_net()
    j = s.tojson()
    d = json.loads(j)
    # the exact top-level contract of legacy_json_util.cc
    assert set(d) == {"nodes", "arg_nodes", "node_row_ptr", "heads",
                      "attrs"}
    assert d["attrs"]["mxnet_version"][0] == "int"
    for n in d["nodes"]:
        assert set(n) <= {"op", "name", "attrs", "inputs"}
        for v in n.get("attrs", {}).values():
            assert isinstance(v, str)   # ALL attr values stringified
        for i in n["inputs"]:
            assert len(i) == 3
    s2 = sym.load_json(j)
    assert s2.list_arguments() == s.list_arguments()
    assert s2.list_auxiliary_states() == s.list_auxiliary_states()
    assert json.loads(s2.tojson()) == d


def test_executor_matches_and_grads():
    import jax
    s = _small_net()
    arg_shapes, _, aux_shapes = s.infer_shape(data=(2, 3, 8, 8))
    rng = np.random.RandomState(0)
    names = s.list_arguments() + s.list_auxiliary_states()
    shapes = list(arg_shapes) + list(aux_shapes)
    vals = {}
    for nm, shp in zip(names, shapes):
        if nm == "data":
            vals[nm] = rng.randn(2, 3, 8, 8).astype(np.float32)
        elif "moving_var" in nm:
            vals[nm] = np.ones(shp, np.float32)
        else:
            vals[nm] = (rng.randn(*shp) * 0.1).astype(np.float32)
    run = s.bind_fn()
    out = run(vals)[0]
    assert out.shape == (2, 10)
    np.testing.assert_allclose(np.asarray(out).sum(axis=1), 1.0, rtol=1e-5)

    # the executor is a pure jax function: jit + grad straight through
    jout = jax.jit(lambda v: run(v)[0])(vals)
    np.testing.assert_allclose(np.asarray(jout), np.asarray(out),
                               rtol=2e-5, atol=1e-6)
    g = jax.grad(lambda v: run(v)[0][:, 0].sum())(vals)
    assert g["c1_weight"].shape == vals["c1_weight"].shape
    assert float(np.abs(np.asarray(g["c1_weight"])).sum()) > 0


def test_compose_and_internals():
    x = sym.var("x")
    net = sym.FullyConnected(data=x, num_hidden=4, name="fc1")
    y = sym.var("y")
    net2 = net.compose(x=y)
    assert "y" in net2.list_arguments()
    assert "x" not in net2.list_arguments()
    internals = _small_net().get_internals()
    assert internals.num_outputs >= 6
    out = internals["c1_output"]
    assert out.name == "c1"


def test_attrs():
    a = sym.var("w", lr_mult=2.0)
    assert a.attr("lr_mult") == "2.0"
    s = sym.FullyConnected(data=a, num_hidden=3, name="fc")
    assert s.attr("num_hidden") == "3"
    assert "fc" in s.attr_dict()


@pytest.mark.skipif(not os.path.exists(REF_JSON),
                    reason="reference artifact not present")
def test_reference_vgg16_artifact_parses_and_runs():
    s = sym.load(REF_JSON)
    args = s.list_arguments()
    assert len(args) == 34 and args[0] == "data"
    arg_shapes, out_shapes, _ = s.infer_shape(data=(1, 3, 224, 224),
                                              softmax_label=(1,))
    assert out_shapes == [(1, 1000)]
    d = dict(zip(args, arg_shapes))
    assert d["conv1_1_weight"] == (64, 3, 3, 3)
    rng = np.random.RandomState(0)
    vals = {}
    for nm, shp in zip(args, arg_shapes):
        if nm == "data":
            vals[nm] = rng.randn(1, 3, 224, 224).astype(np.float32)
        elif shp is not None and nm != "softmax_label":
            vals[nm] = (rng.randn(*shp) * 0.01).astype(np.float32)
    out = s.bind_fn()(vals)[0]
    assert out.shape == (1, 1000)
    np.testing.assert_allclose(float(np.asarray(out).sum()), 1.0, rtol=1e-4)
    # emit→reparse→re-execute parity
    s2 = sym.load_json(s.tojson())
    out2 = s2.bind_fn()(vals)[0]
    np.testing.assert_allclose(np.asarray(out2), np.asarray(out), rtol=1e-6)


def test_symbolblock_imports_legacy_artifact(tmp_path):
    """End-to-end VERDICT-r3 Next #7: save symbol.json + reference-format
    .params, SymbolBlock.imports loads both, forward matches the raw
    executor, and the block hybridizes."""
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon.model_zoo.model_store import \
        save_params_file

    s = _small_net()
    sym_file = str(tmp_path / "m-symbol.json")
    s.save(sym_file)

    arg_shapes, _, aux_shapes = s.infer_shape(data=(2, 3, 8, 8))
    rng = np.random.RandomState(1)
    params = {}
    for nm, shp in zip(s.list_arguments(), arg_shapes):
        if nm == "data":
            continue
        params["arg:" + nm] = (rng.randn(*shp) * 0.1).astype(np.float32)
    for nm, shp in zip(s.list_auxiliary_states(), aux_shapes):
        params["aux:" + nm] = (np.ones(shp, np.float32) if "var" in nm
                               else np.zeros(shp, np.float32))
    params_file = str(tmp_path / "m-0000.params")
    save_params_file(params_file, params)

    net = gluon.SymbolBlock.imports(sym_file, ["data"], params_file)
    x = mx.np.array(rng.randn(2, 3, 8, 8).astype(np.float32))
    got = net(x).asnumpy()

    vals = {k.split(":", 1)[-1]: v for k, v in params.items()}
    vals["data"] = x.asnumpy()
    ref = np.asarray(s.bind_fn()(vals)[0])
    np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    net.hybridize()
    got_h = net(x).asnumpy()
    np.testing.assert_allclose(got_h, ref, rtol=1e-5, atol=1e-6)
