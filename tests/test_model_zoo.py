"""Model zoo + flagship transformer tests (≙ reference
tests/python/unittest/test_gluon_model_zoo.py). Small inputs on the CPU mesh;
the heavier full-res sweep lives in bench/driver runs."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon.model_zoo import vision


@pytest.mark.parametrize("name", ["resnet18_v1", "resnet18_v2",
                                  "mobilenet0.25", "squeezenet1.1"])
def test_zoo_forward(name):
    net = vision.get_model(name, classes=10)
    net.initialize()
    x = mx.np.array(np.random.randn(1, 3, 64, 64).astype(np.float32))
    y = net(x)
    assert y.shape == (1, 10)


def test_resnet50_structure():
    net = vision.resnet50_v1(classes=7)
    net.initialize()
    params = net.collect_params()
    # bottleneck resnet50: 53 conv layers + fc
    n_conv = sum(1 for k in params if k.endswith("weight") and
                 len(params[k].shape or ()) == 4)
    assert n_conv == 53
    x = mx.np.array(np.random.randn(1, 3, 96, 96).astype(np.float32))
    assert net(x).shape == (1, 7)


def test_zoo_train_step():
    from incubator_mxnet_tpu import gluon
    net = vision.get_model("resnet18_v1", classes=4)
    net.initialize()
    net.hybridize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1})
    x = mx.np.array(np.random.randn(2, 3, 32, 32).astype(np.float32))
    y = mx.np.array(np.array([0, 1]))
    before = net.output.weight.data().asnumpy().copy()
    with mx.autograd.record():
        L = loss_fn(net(x), y).mean()
    L.backward()
    trainer.step(2)
    after = net.output.weight.data().asnumpy()
    assert not np.allclose(before, after)
    assert np.isfinite(after).all()


def test_get_model_unknown():
    with pytest.raises(mx.MXNetError):
        vision.get_model("resnet9000")


def test_transformer_forward_and_grad():
    import jax
    from incubator_mxnet_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=64, num_layers=2, d_model=32,
                                num_heads=4, d_ff=64, max_seq_len=16,
                                dtype="float32")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    tokens = np.random.randint(0, 64, (2, 9)).astype(np.int32)
    logits = tfm.forward(params, tokens, cfg)
    assert logits.shape == (2, 9, 64)
    loss, grads = jax.value_and_grad(
        lambda p: tfm.loss_fn(p, {"tokens": tokens}, cfg))(params)
    assert np.isfinite(float(loss))
    flat = jax.tree_util.tree_leaves(grads)
    assert all(np.isfinite(np.asarray(g)).all() for g in flat)


def test_transformer_train_step_reduces_loss():
    import jax
    from incubator_mxnet_tpu.models import transformer as tfm
    cfg = tfm.TransformerConfig(vocab_size=32, num_layers=1, d_model=32,
                                num_heads=4, d_ff=64, max_seq_len=16,
                                dtype="float32")
    params = tfm.init_params(jax.random.PRNGKey(0), cfg)
    opt = tfm.init_opt_state(params)
    step_fn = tfm.make_train_step(cfg, learning_rate=1e-2)
    tokens = np.tile(np.arange(9, dtype=np.int32), (4, 1))  # memorizable
    batch = {"tokens": tokens}
    losses = []
    for i in range(10):
        params, opt, loss = step_fn(params, opt, batch, np.int32(i))
        losses.append(float(loss))
    assert losses[-1] < losses[0]
