"""Typed-error parity (≙ reference include/mxnet/base.h error taxonomy +
python/mxnet/error.py: MXNetError subclasses that ALSO subclass the
matching builtin, so `except ValueError` and `except mx.MXNetError` both
catch). VERDICT-r1 Weak #8 called out the absence of these tests.
"""
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import base


def test_hierarchy_dual_inheritance():
    assert issubclass(base.MXNetError, RuntimeError)
    assert issubclass(base.ValueError_, base.MXNetError)
    assert issubclass(base.ValueError_, ValueError)
    assert issubclass(base.TypeError_, TypeError)
    assert issubclass(base.IndexError_, IndexError)
    assert issubclass(base.AttributeError_, AttributeError)
    assert issubclass(base.NotImplementedError_, NotImplementedError)
    assert issubclass(base.InternalError, base.MXNetError)


def test_catch_as_builtin_or_mxnet():
    with pytest.raises(ValueError):
        raise base.ValueError_("boom")
    with pytest.raises(mx.MXNetError):
        raise base.ValueError_("boom")


def test_framework_raises_typed_errors():
    # unknown optimizer -> MXNetError with the catalog in the message
    from incubator_mxnet_tpu import optimizer as opt_mod
    with pytest.raises(mx.MXNetError, match="sgd"):
        opt_mod.create("nope")

    # sparse storage consistently refused with MXNetError
    with pytest.raises(mx.MXNetError, match="sparse|TPU"):
        mx.np.zeros((2, 2)).tostype("row_sparse")

    # deploy artifacts missing -> MXNetError naming the path
    from incubator_mxnet_tpu.deploy import ExportedModel
    with pytest.raises(mx.MXNetError, match="missing"):
        ExportedModel("/nonexistent/prefix-0000")

    # np reshape 0-dim misuse points at the legacy API
    with pytest.raises(mx.MXNetError, match="mx.nd.reshape"):
        mx.np.zeros((3, 4)).reshape((0, -1))


def test_shape_errors_surface_at_dispatch():
    a = mx.np.zeros((2, 3))
    b = mx.np.zeros((4, 5))
    with pytest.raises(Exception) as ei:
        (a + b).asnumpy()
    assert "2, 3" in str(ei.value).replace("(", "").replace(")", "") \
        or "broadcast" in str(ei.value).lower()


def test_len_of_scalar_is_typeerror():
    s = mx.np.array(1.0)
    with pytest.raises(TypeError):
        len(s)
