"""mxlint fixture: seeded trace-safety violations. NEVER imported — the
analyzer parses it; tests/test_lint.py asserts each rule fires exactly
where expected and that suppressions silence them."""
import os
import random
import time
from time import time as now

import jax
import numpy as np
from jax import random as jxrandom
from numpy import asarray as as_np

STATE = {"calls": 0}
ACC = []


def helper(x):
    # reached transitively from kernel(): still flagged
    return np.asarray(x)                              # trace-host-capture


def kernel(x, scale):
    bad = float(scale)                                # trace-host-capture
    host = x.item()                                   # trace-host-capture
    now = time.time()                                 # trace-impure-host
    noise = random.random()                           # trace-impure-host
    flag = os.environ.get("MXNET_FIXTURE_FLAG")       # trace-impure-host
    later = now()                                     # trace-impure-host (from-import)
    arr2 = as_np(x)                                   # trace-host-capture (from-import)
    key = jxrandom.PRNGKey(0)                         # clean: jax.random, NOT stdlib
    STATE["calls"] += 1                               # trace-closure-mutation
    ACC.append(bad)                                   # trace-closure-mutation
    time.sleep(0)  # mxlint: disable=trace-impure-host -- suppressed on purpose
    return helper(x) * (now + noise + (1 if flag else 0))


jitted = jax.jit(kernel)


def make_step(buffers):
    def step(x):
        total = 0.0

        def add(v):
            nonlocal total
            total += v                                # trace-closure-mutation
            return v

        buffers.append(x)                             # trace-closure-mutation
        return add(x)

    return jax.jit(step)


def clean_host_code(x):
    # NOT jit-reachable: none of these may be flagged
    _ = float(x)
    _ = time.time()
    STATE["calls"] += 1
    return x
