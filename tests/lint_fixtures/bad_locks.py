"""mxlint fixture: seeded lock-discipline violations. NEVER imported."""
import threading

WORK_STATS = {"items": 0, "drops": 0}

_LOCK_A = threading.Lock()
_LOCK_B = threading.Lock()


class Worker:
    def __init__(self):
        self._lock = threading.Lock()
        self._results = []
        self._count = 0
        self._thread = threading.Thread(target=self._run, daemon=True)

    def _run(self):
        while True:
            if self._count < 0:
                break
            self._results.append(1)          # lock-shared-mutation (thread)
            WORK_STATS["items"] += 1         # lock-shared-mutation (global)

    def snapshot(self):
        with self._lock:
            return list(self._results), self._count

    def reset(self):
        self._results.clear()                # lock-shared-mutation (consumer)
        with self._lock:
            self._count = 0                  # locked: clean

    def bump(self):
        self._count += 1                     # lock-shared-mutation (consumer)

    def drop(self):
        self._results.append(2)  # mxlint: disable=lock-shared-mutation -- seeded suppression
        with self._lock:
            WORK_STATS["drops"] += 1         # locked: clean


def path_ab():
    with _LOCK_A:
        with _LOCK_B:                        # edge A -> B
            return 1


def path_ba():
    with _LOCK_B:
        with _LOCK_A:                        # edge B -> A: lock-order-cycle
            return 2
