"""mxlint fixture: seeded retrace-hazard violations. NEVER imported —
the analyzer parses it; tests/test_lint.py asserts each rule fires
exactly where expected and that the padded/steady idioms stay silent."""
import jax
import jax.numpy as jnp

prog = jax.jit(lambda toks, n: toks * n, static_argnums=(1,))


def decode_program(width):
    def _decode(params, toks):
        return toks

    return jax.jit(_decode)


class Engine:
    def __init__(self, model):
        self._decode = decode_program(8)

    # -- retrace-shape-from-data ------------------------------------------
    def shape_leak_loop(self, params, queue):
        while True:
            batch = queue.get()
            toks = jnp.zeros((len(batch), 8))        # BAD: data-driven dim
            out = self._decode(params, toks)

    def shape_attr_leak(self, params, queue):
        for req in queue:
            buf = req.tokens
            out = self._decode(params, buf.shape[0])  # BAD: .shape arg

    def padded_is_clean(self, params, queue, width):
        while True:
            batch = queue.get()
            toks = jnp.zeros((16, width))             # clean: fixed shape
            out = self._decode(params, toks)

    # -- retrace-unstable-static-arg --------------------------------------
    def static_from_data(self, params, queue):
        while True:
            batch = queue.get()
            n = len(batch)
            out = prog(params, n)                     # BAD: varying static

    def static_constant_is_clean(self, params, queue):
        while True:
            batch = queue.get()
            out = prog(params, 16)                    # clean: literal

    # -- retrace-unordered-pytree -----------------------------------------
    def unordered_tree(self, params, queue):
        for req in queue:
            tree = {k: req[k] for k in set(req.keys())}   # BAD: set order
            out = self._decode(params, tree)

    def sorted_tree_is_clean(self, params, queue):
        for req in queue:
            tree = {k: req[k] for k in sorted(req.keys())}  # clean
            out = self._decode(params, tree)


def unhashable_static_outside_loop(params):
    # fires everywhere, not only in steady loops: TypeError at call time
    return prog(params, [1, 2, 3])                    # BAD: list static
