"""mxlint fixture: a miniature package for the registry-consistency pass.
NEVER imported — parsed only."""

POINTS = {
    "alpha.save": "wired and documented: clean",
    "beta.load": "registered but never injected -> fault-point-unwired "
                 "(and undocumented)",
    "gamma.run": "wired but missing from RESILIENCE.md -> "
                 "fault-point-undocumented",
}

PIPE_STATS = {"hits": 0, "misses": 0}


def get_env(name, default=None):
    return default


def inject(point, value=None):
    return value


def f():
    get_env("MXNET_FIXTURE_DOCUMENTED")
    get_env("MXNET_FIXTURE_SECRET")      # env-undocumented (and, because
    #                                      this module is knob-wired:
    #                                      tune-env-undeclared)
    get_env("MXNET_FIXTURE_KNOB")        # declared knob env: clean
    inject("alpha.save")
    inject("gamma.run")
    inject("delta.crash")                # fault-point-unregistered


# --- tune knob catalog (mx.tune.space shape; parsed only) ------------------
KNOBS = {
    "fix.good": {"kind": "int", "default": 1, "choices": [1, 2],
                 "env": "MXNET_FIXTURE_KNOB", "phase": "p",
                 "wire": "pkg/mod.py"},      # declared + documented: clean
    "fix.secret": {"kind": "bool", "default": True,
                   "choices": [True, False], "env": None, "phase": "p",
                   "wire": None},            # -> tune-knob-undocumented
}

NON_TUNABLE_ENV = {"MXNET_FIXTURE_DOCUMENTED"}


def stats_group(family, initial, lock=None):
    return initial


def counter(name, help=""):
    return name


TELE_STATS = stats_group("tele", {"good": 0, "lonely": 0})

# family never quoted with its dotted prefix in tests -> stats-family-
# untested (the key "hits" itself IS covered via PIPE_STATS's test)
COLD_STATS = stats_group("cold", {"hits": 0})


def g():
    counter("tele.obj_documented")
    counter("tele.obj_untested")     # documented, never in tests


class _mem:
    """Stands in for mx.inspect.memory (never imported — parsed only)."""

    @staticmethod
    def register(tree, owner=None):
        return tree

    @staticmethod
    def tag(owner):
        return owner


def h():
    _mem.register([], owner="fixture_owner_good")
    _mem.register([], owner="fixture_owner_secret")   # mem-owner-undocumented
    with _mem.tag("fixture_tag_owner"):
        _mem.register([])
