"""Fixture test file: exercises one PIPE_STATS key but not the other, both
TELE_STATS keys, the documented object metric, and (for the fault-coverage
rules) two of the three registered fault points plus one ghost point."""

MXNET_FAULT_SPEC = "alpha.save:1:error"     # drills a registered point
BAD_SPEC = "zeta.ghost:1:error"             # names a point that is NOT
                                            # registered -> inert spec


def check_hits():
    assert "hits"


def check_tele():
    assert "good" and "lonely"
    assert "tele.obj_documented"
    assert "tele.good" and "tele.lonely"    # dotted family coverage


def check_faults(inject):
    inject("gamma.run")                     # quoted-point drill
