"""Fixture test file: exercises one PIPE_STATS key but not the other, both
TELE_STATS keys, and the documented object metric."""


def check_hits():
    assert "hits"


def check_tele():
    assert "good" and "lonely"
    assert "tele.obj_documented"
