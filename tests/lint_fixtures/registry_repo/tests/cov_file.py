"""Fixture test file: exercises one PIPE_STATS key but not the other."""


def check_hits():
    assert "hits"
