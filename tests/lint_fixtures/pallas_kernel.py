"""Fixture: Pallas TPU kernel bodies inside a registered (jit-reachable)
op must be trace-safety CLEAN — `pl.program_id` reads, `@pl.when`-nested
scratch-ref initializers (`ref[:] = ...` through the enclosing kernel's
parameters), accumulator stores and `.astype` casts are device-side
Pallas idioms, not host captures or frozen closure state. The module
also seeds genuinely-bad patterns in the same kernel nest to prove the
carve-out stays narrow, plus one justified suppression.

NOT imported by tests — parsed by the analyzer only (like bad_trace.py).
"""
import os

import jax
import jax.numpy as jnp
import jax.experimental.pallas as pl

from incubator_mxnet_tpu.ops.registry import register_op


def fused_apply(x, scale):
    """A registered op building its Pallas kernel the way ops/fused.py /
    ops/pallas_attention.py do: kernel + @pl.when init nested inside the
    jit-reachable builder."""

    def kernel(x_ref, scale_ref, o_ref, acc_ref):
        i = pl.program_id(0)                      # device-side, clean

        @pl.when(i == 0)
        def _init():
            # scratch-ref store through the ENCLOSING KERNEL'S PARAMETER:
            # the Pallas idiom the carve-out exists for — must NOT fire
            acc_ref[:] = jnp.zeros_like(acc_ref)

        acc_ref[:] += x_ref[...].astype(jnp.float32)   # clean accumulate
        o_ref[...] = (acc_ref[:] * scale_ref[...]).astype(o_ref.dtype)

    return pl.pallas_call(
        kernel,
        grid=(x.shape[0] // 8,),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x, scale)


register_op("lintfix.fused_apply", fused_apply)


_HOST_SIDE_ACC = []


def bad_kernel_host_state(x):
    """Negative controls: the carve-out must not swallow real hazards in
    the same nesting shape."""

    def kernel(x_ref, o_ref):
        # mutator METHOD call on module state: still trace-closure-mutation
        _HOST_SIDE_ACC.append(1)
        # env read inside a kernel: still trace-impure-host
        if os.environ.get("MXNET_LINTFIX_FAKE"):
            o_ref[...] = x_ref[...] * 2.0
        captured = []

        def inner():
            # store into an enclosing LOCAL (not a parameter): still fires
            captured[0] = 1.0
            # suppressed with justification: reported nowhere
            host = x_ref[...].tolist()  # mxlint: disable=trace-host-capture -- fixture: justified-suppression demo
            return host

        inner()
        o_ref[...] = x_ref[...]

    return pl.pallas_call(
        kernel,
        grid=(1,),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
    )(x)


register_op("lintfix.bad_kernel", bad_kernel_host_state)


def bad_plain_closure_param(history):
    """Subscript store through an enclosing function's PARAMETER with no
    `pallas_call` anywhere in the nest: the classic trace-frozen mutation
    (runs once at trace time, then state silently stops updating) — the
    carve-out must NOT apply outside real Pallas kernel builds."""

    def step(x):
        history[0] = 1.0
        return x

    return jax.jit(step)(history)


register_op("lintfix.bad_plain_closure", bad_plain_closure_param)
