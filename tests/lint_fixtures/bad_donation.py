"""mxlint fixture: seeded donation-safety violations. NEVER imported —
the analyzer parses it; tests/test_lint.py asserts each rule fires
exactly where expected, that the clean idioms stay silent, and that
suppressions work."""
import jax

step = jax.jit(lambda w, g: w - g, donate_argnums=(0,))


def decode_program(width):
    def _decode(params, k_cache, v_cache, toks):
        return toks, k_cache, v_cache

    return jax.jit(_decode, donate_argnums=(1, 2))


class Engine:
    def __init__(self, model):
        self._decode = decode_program(8)

    # -- donation-use-after-donate ----------------------------------------
    def use_after_donate(self, params, toks):
        kb, vb = self.pool.buffers()
        out, k2, v2 = self._decode(params, kb, vb, toks)
        return out, kb                    # BAD: kb read after donation

    def redonate_in_loop(self, params, toks):
        # buffers fetched ONCE outside the steady loop: iteration 2
        # donates the arrays iteration 1 already consumed
        kb, vb = self.pool.buffers()
        for _ in range(4):
            out, k2, v2 = self._decode(params, kb, vb, toks)   # BAD: kb, vb
        return out

    def rebind_is_clean(self, params, toks):
        kb, vb = self.pool.buffers()
        out, kb, vb = self._decode(params, kb, vb, toks)
        return out, kb                    # clean: kb rebound from output

    def branches_are_exclusive(self, params, toks, kb, vb, draft):
        # sibling returns must not cross-poison each other
        if draft > 0:
            return self._decode(params, kb, vb, draft)
        return self._decode(params, kb, vb, toks)

    def suppressed_use(self, params, toks):
        kb, vb = self.pool.buffers()
        out, k2, v2 = self._decode(params, kb, vb, toks)
        return kb  # mxlint: disable=donation-use-after-donate -- on purpose

    # -- donation-unrestored-on-error -------------------------------------
    def swallow_without_restore(self, params, toks, kb, vb):
        try:
            out, kb, vb = self._decode(params, kb, vb, toks)
        except Exception:                 # BAD: swallows, no restore
            out = None
        return out

    def swallow_via_helper(self, params, toks):
        # the donated call is one level down; the handler still swallows
        try:
            out = self.run_wave(params, toks)
        except Exception:                 # BAD: transitive donated call
            out = None
        return out

    def run_wave(self, params, toks):
        kb, vb = self.pool.buffers()
        out, kb, vb = self._decode(params, kb, vb, toks)
        return out

    def restore_is_clean(self, params, toks, kb, vb):
        try:
            out, kb, vb = self._decode(params, kb, vb, toks)
        except Exception:                 # clean: restores the pool
            self.pool.reallocate()
            out = None
        return out

    def reraise_is_clean(self, params, toks, kb, vb):
        try:
            out, kb, vb = self._decode(params, kb, vb, toks)
        except Exception as e:            # clean: re-raises
            raise RuntimeError("decode died") from e
        return out

    def narrow_handler_is_clean(self, params, toks, kb, vb):
        try:
            out, kb, vb = self._decode(params, kb, vb, toks)
        except KeyError:                  # clean: cannot swallow a
            out = None                    # compiled program's failure
        return out


def module_level_use(w, g):
    w2 = step(w, g)
    return w + w2                         # BAD: w read after donation
