"""sparse_grad=True Embedding: the supported touched-rows training path
(VERDICT-r3 Next #9, ≙ the reference's row_sparse embedding gradient +
Trainer row-sparse pull, python/mxnet/gluon/trainer.py:325, with
lazy_update semantics: untouched rows receive no decay/momentum aging).
"""
import numpy as np

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn

V, D = 100, 8


def _train_once(sparse, opt_args):
    mx.seed(7)
    emb = nn.Embedding(V, D, sparse_grad=sparse)
    emb.initialize()
    w0 = emb.weight.data().asnumpy().copy()
    tr = gluon.Trainer(emb.collect_params(), "sgd", opt_args)
    tokens = mx.np.array(np.array([[3, 7, 3], [50, 7, 99]], np.int32))
    with mx.autograd.record():
        L = (emb(tokens) ** 2).sum()
    L.backward()
    g = emb.weight.grad().asnumpy().copy()
    tr.step(1)
    return emb, tr, tokens, w0, g


def test_lazy_touched_rows_update():
    opt = {"learning_rate": 0.5, "momentum": 0.9, "wd": 0.1}
    emb, tr, tokens, w0, g = _train_once(True, opt)
    w1 = emb.weight.data().asnumpy()
    touched = np.unique([3, 7, 50, 99])
    untouched = np.setdiff1d(np.arange(V), touched)
    # LAZY: untouched rows bit-identical — no wd decay, no momentum aging
    np.testing.assert_array_equal(w1[untouched], w0[untouched])
    # touched rows: the optimizer's own momentum+wd rule on the row block
    expect = w0.copy()
    gg = g + 0.1 * w0
    expect[touched] -= 0.5 * gg[touched]
    np.testing.assert_allclose(w1[touched], expect[touched],
                               rtol=1e-5, atol=1e-6)

    # second step: momentum state rows persisted and re-applied
    with mx.autograd.record():
        L = (emb(tokens) ** 2).sum()
    L.backward()
    tr.step(1)
    w2 = emb.weight.data().asnumpy()
    np.testing.assert_array_equal(w2[untouched], w0[untouched])
    assert not np.allclose(w2[touched], w1[touched])


def test_dense_vs_sparse_without_decay_match():
    """With wd=0 and no momentum, the sparse path equals the dense path on
    touched rows (and trivially on untouched: grads are zero there)."""
    opt = {"learning_rate": 0.3}
    emb_s, _, _, w0s, _ = _train_once(True, opt)
    emb_d, _, _, w0d, _ = _train_once(False, opt)
    np.testing.assert_array_equal(w0s, w0d)   # same seeded init
    np.testing.assert_allclose(emb_s.weight.data().asnumpy(),
                               emb_d.weight.data().asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_hybridized_falls_back_to_dense():
    """Under a jit trace the indices are symbolic; the trainer must fall
    back to the dense update rather than leak tracers."""
    net = nn.HybridSequential()
    net.add(nn.Embedding(V, D, sparse_grad=True), nn.Dense(4))
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    tokens = mx.np.array(np.array([[1, 2], [3, 4]], np.int32))
    for _ in range(2):
        with mx.autograd.record():
            L = (net(tokens) ** 2).sum()
        L.backward()
        tr.step(1)
    assert np.isfinite(float(L.asnumpy()))


def test_kvstore_row_sparse_pull():
    w = np.random.RandomState(0).randn(V, D).astype(np.float32)
    kv = mx.kv.create("local")
    kv.init(1, mx.np.array(w))
    rows = np.array([2, 30, 99])
    out = mx.np.zeros((3, D))
    kv.row_sparse_pull(1, out=out, row_ids=mx.np.array(rows))
    np.testing.assert_allclose(out.asnumpy(), w[rows])
    # full-shape out: requested rows written, others untouched
    full = mx.np.array(np.full((V, D), -1.0, np.float32))
    kv.row_sparse_pull(1, out=full, row_ids=mx.np.array(rows))
    got = full.asnumpy()
    np.testing.assert_allclose(got[rows], w[rows])
    assert (got[np.setdiff1d(np.arange(V), rows)] == -1.0).all()
