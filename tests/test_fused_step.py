"""FusedTrainStep: one-XLA-program training must match the eager tape path.

≙ the reference's fused RNN training capability (src/operator/rnn.cc) —
here generalized: fwd + loss + bwd + clip + optimizer update in one jit.
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, optimizer as opt_mod
from incubator_mxnet_tpu.gluon import nn, rnn
from incubator_mxnet_tpu.gluon.contrib import FusedTrainStep


def _mlp(seed=0):
    mx.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(4,
                                                                  in_units=16))
    net.initialize()
    return net


def test_fused_step_matches_eager_sgd():
    x = mx.np.array(np.random.randn(8, 8).astype(np.float32))
    y = mx.np.array(np.random.randn(8, 4).astype(np.float32))
    loss_fn = gluon.loss.L2Loss()

    # eager tape path
    net_a = _mlp(1)
    tr = gluon.Trainer(net_a.collect_params(), "sgd",
                       {"learning_rate": 0.1, "momentum": 0.9})
    for _ in range(3):
        with mx.autograd.record():
            L = loss_fn(net_a(x), y).mean()
        L.backward()
        tr.step(1, ignore_stale_grad=True)

    # fused path, same seed -> identical init
    net_b = _mlp(1)
    opt = opt_mod.create("sgd", learning_rate=0.1, momentum=0.9)
    step = FusedTrainStep(net_b, lambda net, x, y: loss_fn(net(x), y).mean(),
                          opt)
    for _ in range(3):
        L2 = step(x, y)
    assert np.isfinite(float(L2.asnumpy()))
    pa, pb = net_a.collect_params(), net_b.collect_params()
    for k in pa:
        np.testing.assert_allclose(pa[k].data().asnumpy(),
                                   pb[k].data().asnumpy(),
                                   rtol=1e-5, atol=1e-6)


def test_fused_step_adam_with_extras_and_clip():
    """Adam (traced t), pass-through extras (recurrent states), grad clip."""
    mx.seed(7)
    net = rnn.LSTM(16, 1, input_size=8)
    net.initialize()
    x = mx.np.array(np.random.randn(5, 4, 8).astype(np.float32))
    states = net.begin_state(4)
    _ = net(x, states)  # resolve shapes
    opt = opt_mod.create("adam", learning_rate=1e-2)

    def fn(net, x, h, c):
        out, (h2, c2) = net(x, [h, c])
        return (out * out).mean(), h2, c2

    step = FusedTrainStep(net, fn, opt, clip_global_norm=1.0)
    h, c = states
    losses = []
    for _ in range(4):
        L, h, c = step(x, h, c)
        losses.append(float(L.asnumpy()))
    assert all(np.isfinite(v) for v in losses)
    assert losses[-1] < losses[0]  # optimizes
    assert h.shape == (1, 4, 16)


def test_fused_step_batchnorm_aux_updates():
    """BN running stats (grad_req='null' params) update through the step."""
    mx.seed(3)
    net = nn.HybridSequential()
    net.add(nn.Dense(8, in_units=4), nn.BatchNorm(in_channels=8))
    net.initialize()
    loss_fn = gluon.loss.L2Loss()
    x = mx.np.array(np.random.randn(16, 4).astype(np.float32) * 3 + 1)
    y = mx.np.array(np.zeros((16, 8), np.float32))
    before = {k: p.data().asnumpy().copy()
              for k, p in net.collect_params().items()
              if "running" in k}
    assert before
    step = FusedTrainStep(net, lambda net, x, y: loss_fn(net(x), y).mean(),
                          "sgd")
    step(x, y)
    after = {k: p.data().asnumpy()
             for k, p in net.collect_params().items() if "running" in k}
    changed = any(np.abs(before[k] - after[k]).max() > 1e-7 for k in before)
    assert changed, "running stats did not update"


def test_fused_step_requires_initialized_net():
    net = nn.Dense(4)  # deferred in_units
    net.initialize()
    with pytest.raises(mx.MXNetError, match="initialized"):
        FusedTrainStep(net, lambda n, x: n(x).sum(), "sgd")


def test_fused_step_honors_param_multipliers():
    """lr_mult/wd_mult on Parameters must flow into the fused update the
    same way gluon.Trainer resolves them (via optimizer.param_dict)."""
    x = mx.np.array(np.random.randn(8, 8).astype(np.float32))
    y = mx.np.array(np.random.randn(8, 4).astype(np.float32))
    loss_fn = gluon.loss.L2Loss()

    def freeze_mults(net):
        for name, p in net.collect_params().items():
            if name.endswith("bias"):
                p.lr_mult = 0.0   # biases must not move at all

    net_a = _mlp(3)
    freeze_mults(net_a)
    tr = gluon.Trainer(net_a.collect_params(), "sgd",
                       {"learning_rate": 0.1})
    with mx.autograd.record():
        L = loss_fn(net_a(x), y).mean()
    L.backward()
    tr.step(1, ignore_stale_grad=True)

    net_b = _mlp(3)
    freeze_mults(net_b)
    step = FusedTrainStep(net_b, lambda n, xx, yy: loss_fn(n(xx), yy).mean(),
                          opt_mod.create("sgd", learning_rate=0.1))
    step(x, y)

    for (name, pa), (_, pb) in zip(sorted(net_a.collect_params().items()),
                                   sorted(net_b.collect_params().items())):
        np.testing.assert_allclose(pa.data().asnumpy(),
                                   pb.data().asnumpy(), rtol=1e-6,
                                   atol=1e-6, err_msg=name)
        if name.endswith("bias"):
            # and specifically: unchanged from init
            net_c = _mlp(3)
            init = dict(net_c.collect_params().items())[name]
            np.testing.assert_allclose(pb.data().asnumpy(),
                                       init.data().asnumpy(), rtol=0,
                                       atol=0, err_msg=name)


def test_remat_policies_numerically_identical():
    """remat trades FLOPs for residual HBM traffic — it must never change
    the math. All three policies produce identical losses and weights;
    bench.py A/Bs their THROUGHPUT on the attached chip."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu import optimizer as opt_mod
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.gluon.contrib import FusedTrainStep

    def make():
        mx.seed(5)
        net = nn.HybridSequential()
        net.add(nn.Conv2D(8, 3, padding=1, layout="NHWC"),
                nn.BatchNorm(axis=3), nn.Activation("relu"),
                nn.Flatten(), nn.Dense(10))
        net.initialize()
        net.hybridize()
        return net

    x = mx.np.array(np.random.RandomState(0).rand(4, 8, 8, 3)
                    .astype(np.float32))
    y = mx.np.array(np.random.RandomState(1).randint(0, 10, (4,)))
    L = gluon.loss.SoftmaxCrossEntropyLoss()
    outs = {}
    for remat in (None, "full", "dots"):
        net = make()
        net(x)
        step = FusedTrainStep(net, lambda n, a, b: L(n(a), b).sum(),
                              opt_mod.create("sgd", learning_rate=0.1),
                              remat=remat)
        for _ in range(3):
            loss = step(x, y)
        outs[remat] = (float(loss.asnumpy()),
                       list(net.collect_params().values())[0]
                       .data().asnumpy())
    for k in ("full", "dots"):
        np.testing.assert_allclose(outs[k][0], outs[None][0], rtol=1e-5)
        np.testing.assert_allclose(outs[k][1], outs[None][1],
                                   rtol=1e-5, atol=1e-6)
    with pytest.raises(mx.MXNetError):
        FusedTrainStep(make(), lambda n, a, b: L(n(a), b).sum(),
                       opt_mod.create("sgd"), remat="bogus")
