"""Gluon Block/HybridBlock/Parameter/Trainer tests.

Modeled on the reference suite tests/python/unittest/test_gluon.py (SURVEY §4):
parameter lifecycle, deferred shape inference, hybridize parity vs eager,
save/load round-trips, trainer updates, loss/metric values.
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn


def test_parameter_basic():
    p = gluon.Parameter(shape=(3, 4))
    p.initialize()
    assert p.data().shape == (3, 4)
    assert p.grad().shape == (3, 4)
    p.zero_grad()
    assert abs(p.grad().asnumpy()).sum() == 0


def test_parameter_deferred_error():
    p = gluon.Parameter(shape=(0, 4))
    p.initialize()
    with pytest.raises(gluon.DeferredInitializationError):
        p.data()
    p.shape = (2, 4)
    p._finish_deferred_init()
    assert p.data().shape == (2, 4)


def test_dense_shape_inference():
    net = nn.Dense(5)
    net.initialize()
    x = mx.np.array(np.ones((2, 7), np.float32))
    y = net(x)
    assert y.shape == (2, 5)
    assert net.weight.shape == (5, 7)


def test_collect_params_names():
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    params = net.collect_params()
    assert "0.weight" in params and "1.bias" in params


def test_sequential_forward_and_repr():
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=4), nn.Dense(2, in_units=8))
    net.initialize()
    x = mx.np.array(np.random.randn(3, 4).astype(np.float32))
    y = net(x)
    assert y.shape == (3, 2)
    assert "Dense" in repr(net)


def test_hybridize_matches_eager():
    np.random.seed(0)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="tanh", in_units=6), nn.Dense(3, in_units=16))
    net.initialize()
    x = mx.np.array(np.random.randn(5, 6).astype(np.float32))
    y_eager = net(x).asnumpy()
    net.hybridize()
    y_hybrid = net(x).asnumpy()
    np.testing.assert_allclose(y_eager, y_hybrid, rtol=2e-5, atol=2e-6)


def test_hybridize_gradients_match():
    net = nn.Dense(4, in_units=3)
    net.initialize()
    x = mx.np.array(np.random.randn(2, 3).astype(np.float32))
    with mx.autograd.record():
        y = (net(x) ** 2).sum()
    y.backward()
    g_eager = net.weight.grad().asnumpy()
    net.hybridize()
    with mx.autograd.record():
        y = (net(x) ** 2).sum()
    y.backward()
    np.testing.assert_allclose(g_eager, net.weight.grad().asnumpy(),
                               rtol=2e-5, atol=2e-6)


def test_batchnorm_running_stats_update():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    x = mx.np.array(np.random.randn(8, 4).astype(np.float32) * 3 + 1)
    with mx.autograd.record():
        bn(x)
    rm = bn.running_mean.data().asnumpy()
    assert abs(rm).sum() > 0  # moved toward batch mean


def test_batchnorm_hybrid_running_stats():
    bn = nn.BatchNorm(in_channels=4)
    bn.initialize()
    bn.hybridize()
    x = mx.np.array(np.random.randn(8, 4).astype(np.float32) * 2 + 5)
    with mx.autograd.record():
        bn(x)  # trains → stats update through functionalized aux outputs
    rm = bn.running_mean.data().asnumpy()
    assert abs(rm).sum() > 0


def test_conv2d_forward_shape():
    conv = nn.Conv2D(8, kernel_size=3, padding=1, in_channels=3)
    conv.initialize()
    x = mx.np.array(np.random.randn(2, 3, 16, 16).astype(np.float32))
    assert conv(x).shape == (2, 8, 16, 16)


def test_conv2d_deferred_in_channels():
    conv = nn.Conv2D(4, kernel_size=3)
    conv.initialize()
    x = mx.np.array(np.random.randn(2, 5, 8, 8).astype(np.float32))
    y = conv(x)
    assert y.shape == (2, 4, 6, 6)
    assert conv.weight.shape == (4, 5, 3, 3)


def test_pooling_layers():
    x = mx.np.array(np.random.randn(2, 3, 8, 8).astype(np.float32))
    assert nn.MaxPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.AvgPool2D(2)(x).shape == (2, 3, 4, 4)
    assert nn.GlobalAvgPool2D()(x).shape == (2, 3, 1, 1)
    assert nn.GlobalMaxPool2D()(x).shape == (2, 3, 1, 1)


def test_embedding():
    emb = nn.Embedding(10, 6)
    emb.initialize()
    idx = mx.np.array(np.array([[1, 2], [3, 4]]))
    assert emb(idx).shape == (2, 2, 6)


def test_save_load_parameters(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net.initialize()
    f = str(tmp_path / "params.npz")
    net.save_parameters(f)
    net2 = nn.HybridSequential()
    net2.add(nn.Dense(4, in_units=3), nn.Dense(2, in_units=4))
    net2.load_parameters(f)
    for (n1, p1), (n2, p2) in zip(sorted(net.collect_params().items()),
                                  sorted(net2.collect_params().items())):
        np.testing.assert_array_equal(p1.data().asnumpy(), p2.data().asnumpy())


def test_trainer_sgd_step():
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init="ones")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.5})
    x = mx.np.array(np.ones((4, 2), np.float32))
    with mx.autograd.record():
        loss = net(x).sum()
    loss.backward()
    trainer.step(4)
    # grad of sum(x@wT) wrt w = sum over batch of x = [4,4]; rescale 1/4 -> [1,1]
    np.testing.assert_allclose(net.weight.data().asnumpy(),
                               np.array([[0.5, 0.5]], np.float32))


def test_trainer_save_load_states(tmp_path):
    net = nn.Dense(2, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.np.array(np.ones((2, 2), np.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    trainer.step(2)
    f = str(tmp_path / "trainer.states")
    trainer.save_states(f)
    trainer.load_states(f)
    assert trainer._optimizer.num_update == 1


def test_stale_grad_raises():
    net = nn.Dense(1, in_units=2)
    net.initialize()
    trainer = gluon.Trainer(net.collect_params(), "sgd")
    with pytest.raises(mx.MXNetError):
        trainer.step(1)  # no backward ran


def test_losses_values():
    pred = mx.np.array(np.array([[1.0, 2.0], [0.5, 0.5]], np.float32))
    label = mx.np.array(np.array([[1.5, 2.5], [0.0, 0.0]], np.float32))
    l1 = gluon.loss.L1Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(l1, [0.5, 0.5], rtol=1e-6)
    l2 = gluon.loss.L2Loss()(pred, label).asnumpy()
    np.testing.assert_allclose(l2, [0.125, 0.125], rtol=1e-6)


def test_softmax_ce_loss():
    pred = mx.np.array(np.array([[10.0, -10.0], [-10.0, 10.0]], np.float32))
    label = mx.np.array(np.array([0, 1]))
    L = gluon.loss.SoftmaxCrossEntropyLoss()(pred, label).asnumpy()
    assert (L < 1e-6).all()


def test_ctc_loss_known_value():
    # uniform distribution over 5 classes, T=4: compare against a simple
    # reference value computed by brute force enumeration
    N, T, C, L = 1, 4, 5, 2
    logits = mx.np.zeros((N, T, C))
    labels = mx.np.array(np.array([[1, 2]]))
    loss = gluon.loss.CTCLoss()(logits, labels).asnumpy()
    # brute-force: all alignments of 'blank-extended' [_,1,_,2,_] over 4 steps
    # p(path)=5^-4 each; count valid paths = 7 ([1,1,2,2],[1,2,2,_]...)
    import itertools
    valid = 0
    for path in itertools.product(range(C), repeat=T):
        # collapse: remove repeats then blanks(0)
        collapsed = []
        prev = None
        for s in path:
            if s != prev:
                collapsed.append(s)
            prev = s
        collapsed = [s for s in collapsed if s != 0]
        if collapsed == [1, 2]:
            valid += 1
    expected = -np.log(valid * (1.0 / C) ** T)
    np.testing.assert_allclose(loss[0], expected, rtol=1e-4)


def test_metrics():
    from incubator_mxnet_tpu.gluon import metric
    acc = metric.Accuracy()
    pred = mx.np.array(np.array([[0.1, 0.9], [0.8, 0.2]], np.float32))
    label = mx.np.array(np.array([1, 1]))
    acc.update(label, pred)
    assert acc.get()[1] == 0.5
    comp = metric.create(["accuracy", "cross-entropy"])
    comp.update(label, pred)
    names, vals = comp.get()
    assert "accuracy" in names

    mae = metric.MAE()
    mae.update(mx.np.array(np.array([1.0, 2.0], np.float32)),
               mx.np.array(np.array([1.5, 2.5], np.float32)))
    assert abs(mae.get()[1] - 0.5) < 1e-6


def test_optimizer_adam_converges():
    w = mx.np.array(np.array([5.0], np.float32))
    w.attach_grad()
    opt = mx.optimizer.create("adam", learning_rate=0.5)
    state = opt.create_state(0, w)
    for _ in range(120):
        with mx.autograd.record():
            loss = (w * w).sum()
        loss.backward()
        opt.update(0, w, w.grad, state)
    assert abs(w.asnumpy()[0]) < 0.1


@pytest.mark.parametrize("name", ["sgd", "nag", "adagrad", "adadelta", "adam",
                                  "adamw", "adamax", "nadam", "rmsprop",
                                  "ftml", "ftrl", "lamb", "lans", "lars",
                                  "signum", "adabelief", "dcasgd", "sgld"])
def test_all_optimizers_smoke(name):
    w = mx.np.array(np.array([[1.0, -2.0], [3.0, 0.5]], np.float32))
    w.attach_grad()
    opt = mx.optimizer.create(name, learning_rate=0.01)
    state = opt.create_state_multi_precision(0, w)
    before = w.asnumpy().copy()
    with mx.autograd.record():
        loss = (w * w).sum()
    loss.backward()
    opt.update_multi_precision(0, w, w.grad, state)
    assert not np.allclose(before, w.asnumpy())
    assert np.isfinite(w.asnumpy()).all()


def test_lr_schedulers():
    s = mx.lr_scheduler.FactorScheduler(step=10, factor=0.5, base_lr=1.0)
    assert s(1) == 1.0
    assert s(11) == 0.5
    c = mx.lr_scheduler.CosineScheduler(max_update=100, base_lr=1.0)
    assert abs(c(100)) < 1e-9
    p = mx.lr_scheduler.PolyScheduler(max_update=100, base_lr=1.0)
    assert p(0) == 1.0


def test_kvstore_push_pull():
    kv = mx.kvstore.create("local")
    v = mx.np.ones((2, 3))
    kv.init(3, v)
    out = mx.np.zeros((2, 3))
    kv.push(3, [v, v, v])  # simulate 3 devices
    kv.pull(3, out)
    np.testing.assert_allclose(out.asnumpy(), 3 * np.ones((2, 3)), rtol=1e-6)


def test_kvstore_updater():
    kv = mx.kvstore.create("device")
    opt = mx.optimizer.create("sgd", learning_rate=1.0)
    kv.set_updater(mx.optimizer.get_updater(opt))
    w = mx.np.ones((2,))
    kv.init(0, w)
    g = mx.np.ones((2,))
    kv.push(0, g)
    out = mx.np.zeros((2,))
    kv.pull(0, out)
    np.testing.assert_allclose(out.asnumpy(), np.zeros(2), atol=1e-6)


def test_initializers():
    from incubator_mxnet_tpu import initializer as init
    rng = np.random.default_rng(0)
    x = init.Xavier()( "w", (64, 32), np.float32, rng)
    assert x.shape == (64, 32) and x.std() > 0
    o = init.Orthogonal()("w", (16, 16), np.float32, rng)
    eye = o @ o.T / (init.Orthogonal().scale ** 2)
    np.testing.assert_allclose(eye, np.eye(16), atol=1e-4)
    z = init.Zero()("w", (3,), np.float32, rng)
    assert (z == 0).all()
    c = init.Constant(2.5)("w", (3,), np.float32, rng)
    assert (c == 2.5).all()
    b = init.create("normal")
    assert isinstance(b, init.Normal)


def test_share_parameters():
    a = nn.Dense(4, in_units=3)
    b = nn.Dense(4, in_units=3)
    a.initialize()
    b.initialize()
    b.share_parameters(a.collect_params())
    np.testing.assert_array_equal(a.weight.data().asnumpy(),
                                  b.weight.data().asnumpy())


def test_block_hooks():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    calls = []
    h = net.register_forward_hook(lambda blk, ins, out: calls.append(1))
    net(mx.np.ones((1, 2)))
    assert calls == [1]
    h.detach()
    net(mx.np.ones((1, 2)))
    assert calls == [1]


def test_layernorm_groupnorm_values():
    x = mx.np.array(np.random.randn(4, 8).astype(np.float32))
    ln = nn.LayerNorm(in_channels=8)
    ln.initialize()
    y = ln(x).asnumpy()
    np.testing.assert_allclose(y.mean(axis=-1), np.zeros(4), atol=1e-5)
    np.testing.assert_allclose(y.std(axis=-1), np.ones(4), atol=1e-2)

    xg = mx.np.array(np.random.randn(2, 6, 4, 4).astype(np.float32))
    gn = nn.GroupNorm(num_groups=3, in_channels=6)
    gn.initialize()
    assert gn(xg).shape == (2, 6, 4, 4)


def test_param_init_reproducible_crc():
    """Regression: param init must be reproducible under a fixed seed
    (crc32 name key, not salted hash())."""
    mx.seed(1234)
    p1 = gluon.Parameter(shape=(4, 4), name="w")
    p1._structural_name = "blk.w"
    p1.initialize()
    mx.seed(1234)
    p2 = gluon.Parameter(shape=(4, 4), name="w")
    p2._structural_name = "blk.w"
    p2.initialize()
    np.testing.assert_array_equal(p1.data().asnumpy(), p2.data().asnumpy())


def test_trainer_rescale_grad_tracks_batch_size():
    """Regression: changing batch_size between steps must change the
    effective grad scaling (kernel cache keyed on rescale)."""
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init="zeros")
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 1.0})
    x = mx.np.array(np.ones((4, 2), np.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    trainer.step(4)   # grad [4,4] /4 -> step -1 each
    w1 = net.weight.data().asnumpy().copy()
    with mx.autograd.record():
        net(x).sum().backward()
    trainer.step(8)   # grad [4,4] /8 -> step -0.5 each
    w2 = net.weight.data().asnumpy()
    np.testing.assert_allclose(w1, [[-1.0, -1.0]], rtol=1e-6)
    np.testing.assert_allclose(w2 - w1, [[-0.5, -0.5]], rtol=1e-6)


def test_pool_ceil_mode():
    """Regression: ceil_mode must extend the output (reference
    pooling_convention='full')."""
    x = mx.np.array(np.random.randn(1, 1, 7, 7).astype(np.float32))
    floor_out = nn.MaxPool2D(2, 2)(x)
    ceil_out = nn.MaxPool2D(2, 2, ceil_mode=True)(x)
    assert floor_out.shape == (1, 1, 3, 3)
    assert ceil_out.shape == (1, 1, 4, 4)
    # ceil avg without pad counting must divide by real window sizes
    ones = mx.np.ones((1, 1, 5, 5))
    avg = nn.AvgPool2D(2, 2, ceil_mode=True, count_include_pad=False)(ones)
    np.testing.assert_allclose(avg.asnumpy(), np.ones((1, 1, 3, 3)), rtol=1e-6)


def test_npx_cond_with_ndarray_inputs():
    """Regression: cond with a multi-element NDArray input must not crash on
    truthiness."""
    from incubator_mxnet_tpu import numpy_extension as npx
    x = mx.np.array(np.array([1.0, 2.0], np.float32))
    out = npx.cond(mx.np.array(np.array(True)),
                   lambda v: v + 1, lambda v: v - 1, inputs=x)
    np.testing.assert_allclose(out.asnumpy(), [2.0, 3.0])


def test_fused_update_matches_per_param():
    """Multi-tensor fused update must equal per-param kernels exactly."""
    def build():
        mx.seed(7)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
        net.initialize()
        return net

    def run(net, force_per_param):
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        if force_per_param:
            tr._optimizer._fused_safe = False
        x = mx.np.array(np.ones((4, 4), np.float32))
        for _ in range(3):
            with mx.autograd.record():
                (net(x) ** 2).sum().backward()
            tr.step(4)
        return {k: p.data().asnumpy() for k, p in net.collect_params().items()}

    w_fused = run(build(), False)
    w_plain = run(build(), True)
    for k in w_fused:
        np.testing.assert_allclose(w_fused[k], w_plain[k], rtol=1e-6,
                                   atol=1e-7)


import contextlib


@contextlib.contextmanager
def _immediate_updates():
    """Pin the standalone jitted fused-update path: these tests inspect the
    optimizer's _jitted cache, which engine op-bulking bypasses (the update
    then joins the deferred segment instead)."""
    from incubator_mxnet_tpu import engine
    prev = engine.set_bulk_size(0)
    try:
        yield
    finally:
        engine.set_bulk_size(prev)


def _with_immediate_updates(fn):
    import functools

    @functools.wraps(fn)
    def wrapper(*a, **k):
        with _immediate_updates():
            return fn(*a, **k)
    return wrapper


@_with_immediate_updates
def test_fused_update_honors_hyperparam_change():
    """Regression: mutating momentum mid-training must affect the fused path
    (hyperparams are part of the jit cache key)."""
    def run(drop_momentum_at, force_per_param=False):
        mx.seed(11)
        net = nn.Dense(4, in_units=3, use_bias=False)
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "sgd",
                           {"learning_rate": 0.1, "momentum": 0.9})
        if force_per_param:
            tr._optimizer._fused_safe = False
        x = mx.np.array(np.ones((2, 3), np.float32))
        for step in range(4):
            if step == drop_momentum_at:
                tr._optimizer.momentum = 0.0
            with mx.autograd.record():
                (net(x) ** 2).sum().backward()
            tr.step(2)
        return net.weight.data().asnumpy()

    w_fused = run(2)
    w_plain = run(2, force_per_param=True)
    np.testing.assert_allclose(w_fused, w_plain, rtol=1e-6, atol=1e-7)


@_with_immediate_updates
def test_fused_update_lr_schedule_no_retrace():
    """Regression: a per-step lr schedule must reuse ONE fused executable
    (lr is a traced arg, not a cache-key component)."""
    sched = mx.lr_scheduler.FactorScheduler(step=1, factor=0.5, base_lr=0.4)
    net = nn.Dense(2, in_units=2, use_bias=False)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd",
                       {"lr_scheduler": sched})
    x = mx.np.array(np.ones((2, 2), np.float32))
    for _ in range(5):
        with mx.autograd.record():
            net(x).sum().backward()
        tr.step(2)
    fused_keys = [k for k in tr._optimizer._jitted
                  if isinstance(k, tuple) and k[0] == "fused_all"]
    assert len(fused_keys) == 1, fused_keys


@_with_immediate_updates
def test_fused_update_rescale_no_retrace_and_correct():
    """Regression: varying batch size must neither retrace the fused update
    nor apply a stale rescale."""
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init="zeros")
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 1.0})
    for bs in (4, 8, 4, 16):
        x = mx.np.array(np.ones((bs, 2), np.float32))
        with mx.autograd.record():
            net(x).sum().backward()
        tr.step(bs)  # each step: grad [bs,bs]/bs -> -1 per element
    np.testing.assert_allclose(net.weight.data().asnumpy(),
                               [[-4.0, -4.0]], rtol=1e-6)
    fused_keys = [k for k in tr._optimizer._jitted
                  if isinstance(k, tuple) and k[0] == "fused_all"]
    assert len(fused_keys) == 1, fused_keys


def test_ignore_stale_grad_skips():
    """Regression: stale-grad params must be SKIPPED, not re-updated."""
    net = nn.Dense(1, in_units=2, use_bias=False)
    net.initialize(init="ones")
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 1.0})
    x = mx.np.array(np.ones((2, 2), np.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    tr.step(2)
    w_after = net.weight.data().asnumpy().copy()
    tr.step(2, ignore_stale_grad=True)  # no new backward: must be a no-op
    np.testing.assert_array_equal(net.weight.data().asnumpy(), w_after)


def test_custom_optimizer_override_not_fused():
    """Subclasses overriding update() must keep the per-param path."""
    calls = []

    class MySGD(mx.optimizer.SGD):
        def update(self, index, weight, grad, state):
            calls.append(index)
            super().update(index, weight, grad, state)

    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), MySGD(learning_rate=0.1))
    x = mx.np.array(np.ones((2, 2), np.float32))
    with mx.autograd.record():
        net(x).sum().backward()
    tr.step(2)
    assert calls  # the override actually ran


def test_avgpool_hybrid_backward():
    """Regression: vjp through a jitted avg-pool (reduce_window with array
    init broke linearization in jax 0.9 — init must be a literal)."""
    pool = nn.AvgPool2D(2, 2)
    pool.hybridize()
    x = mx.np.array(np.random.randn(2, 1, 8, 8).astype(np.float32))
    x.attach_grad()
    with mx.autograd.record():
        pool(x).sum().backward()
    np.testing.assert_allclose(x.grad.asnumpy(),
                               0.25 * np.ones((2, 1, 8, 8)), rtol=1e-6)


def test_fused_adam_matches_per_param():
    """Adam-family fused path (traced step count) must equal per-param
    updates exactly across multiple steps."""
    def run(force_per_param):
        mx.seed(21)
        net = nn.HybridSequential()
        net.add(nn.Dense(8, in_units=4), nn.Dense(2, in_units=8))
        net.initialize()
        tr = gluon.Trainer(net.collect_params(), "adam",
                           {"learning_rate": 0.01})
        if force_per_param:
            tr._optimizer._fused_safe = False
        x = mx.np.array(np.ones((4, 4), np.float32))
        for _ in range(4):
            with mx.autograd.record():
                (net(x) ** 2).sum().backward()
            tr.step(4)
        return {k: p.data().asnumpy()
                for k, p in net.collect_params().items()}

    w_fused = run(False)
    w_plain = run(True)
    for k in w_fused:
        np.testing.assert_allclose(w_fused[k], w_plain[k], rtol=1e-5,
                                   atol=1e-6)


@_with_immediate_updates
def test_fused_adam_single_trace():
    """The fused Adam path must reuse ONE executable across steps (t is a
    traced argument, not a cache-key component)."""
    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 0.01})
    x = mx.np.array(np.ones((2, 2), np.float32))
    for _ in range(5):
        with mx.autograd.record():
            net(x).sum().backward()
        tr.step(2)
    fused_keys = [k for k in tr._optimizer._jitted
                  if isinstance(k, tuple) and k[0] == "fused_all"]
    assert len(fused_keys) == 1, fused_keys


def test_naive_engine_blocking_dispatch():
    """MXNET_ENGINE_TYPE=NaiveEngine: every op dispatch blocks until its
    result is materialized (engine.set_naive toggles at runtime)."""
    from incubator_mxnet_tpu import engine
    prev = engine.set_naive(True)
    try:
        assert engine.is_naive()
        a = mx.np.ones((4, 4))
        b = (a @ a) + 1  # dispatches through ops.registry.invoke
        np.testing.assert_allclose(b.asnumpy(), np.full((4, 4), 5.0))
        # tape path blocks too
        x = mx.np.ones((3,))
        x.attach_grad()
        with mx.autograd.record():
            y = (x * x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.asnumpy(), 2 * np.ones(3))
    finally:
        engine.set_naive(prev)
    assert engine.is_naive() == prev


def test_optimize_for_rejects_unknown_backend():
    """Reference semantics: optimize_for with an unregistered backend is an
    error, not a silent no-op."""
    net = nn.Dense(4, in_units=4)
    net.initialize()
    x = mx.np.ones((2, 4))
    with pytest.raises(mx.MXNetError, match="not registered"):
        net.optimize_for(x, backend="TensorRT")
    net.optimize_for(x, backend="xla")  # known backend works
    assert net._active  # hybridized
