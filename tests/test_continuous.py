"""serve.continuous: iteration-level batching over slotted KV-cache pools.

Contracts under test (ISSUE 14 acceptance):
  * mixed ragged traffic through the engine produces token-for-token the
    same outputs as a scheduling-free single-slot reference decode
  * ZERO retraces after warmup over any join/leave pattern, observed via
    the PR-3 `programs_compiled` counter AND `compile_cache_size()`
  * KV-slot lifecycle: claim/free under concurrent hammering, typed
    `SlotsFullError` on exhaustion, and slot REUSE cannot read a prior
    request's cache (poison-fill + value check — the mask contract)
  * deadline-aware admission: waiting deadline-holders get slots before
    FIFO order; a deadline that expires while WAITING fails fast
  * one request = ONE trace across its N iterations (serve.request root
    with serve.prefill / serve.decode children, same trace id)
  * `MXNET_COMPILE_CACHE_DIR` makes a warm replica skip compilation
  * PR-3 pad-row mask regression: outputs that cannot be pad-masked
    fail typed instead of leaking pad garbage (tests/test_serve.py side
    covers the server; here the engine never pads replies by design)

ISSUE 19 additions (shared-prefix KV cache + chunked prefill; cache
bookkeeping unit tests live in tests/test_prefix_cache.py):
  * prompts longer than `prefill_window` stream through window-sized
    chunks (extent ladder), token-exact and zero-retrace
  * a prefix-cache hit copies cached KV and prefills ONLY the suffix:
    billing, EDF post-cache-cost ranking, and poison-fill isolation of
    the pinned cache rows all hold; hit / int8-hit outputs match the
    explicit `reference_generate(cached_prefix_len=...)` oracle
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

from incubator_mxnet_tpu import profiler, serve
from incubator_mxnet_tpu.serve.kv_pool import KVPOOL_STATS


CFG = dict(vocab=64, embed=32, layers=2, heads=4, head_dim=8, max_len=48)


@pytest.fixture(scope="module")
def decoder():
    """One small CachedDecoder + a weight-sharing reference twin (its own
    jits, so reference calls never touch the engine's compile caches)."""
    cfg = serve.DecoderConfig(**CFG)
    model = serve.CachedDecoder(cfg, seed=3)
    ref = serve.CachedDecoder(cfg, params=model.params)
    return model, ref


def _workload(n, seed=0, vocab=64, max_new_hi=20):
    rng = np.random.RandomState(seed)
    return [(rng.randint(1, vocab, size=rng.randint(2, 12)).tolist(),
             int(rng.randint(1, max_new_hi))) for _ in range(n)]


# ---------------------------------------------------------------------------
# correctness + zero retraces
# ---------------------------------------------------------------------------
def test_engine_matches_reference_and_never_retraces(decoder):
    model, ref = decoder
    work = _workload(16)
    before = profiler.serve_stats()
    with serve.ContinuousEngine(model, max_slots=4, decode_steps=3) as eng:
        warm_ccs = eng.compile_cache_size()
        warm_programs = profiler.serve_stats()["programs_compiled"]
        futs = [eng.submit(p, m) for p, m in work]
        outs = [f.result(timeout=120) for f in futs]
        st = eng.stats()
        # join/leave churned the mixed batch every iteration; the two
        # compiled programs must have been enough for all of it
        assert eng.assert_no_retraces() == 0
        assert eng.compile_cache_size() == warm_ccs
        assert profiler.serve_stats()["programs_compiled"] == warm_programs
    for (p, m), o in zip(work, outs):
        np.testing.assert_array_equal(
            o, ref.reference_generate(p, m),
            err_msg=f"engine output diverged for prompt {p} max_new {m}")
        assert len(o) == m
    # decode_* counter family moved (stats-key + catalog contract):
    # "decode_iterations", "decode_tokens", "decode_prefill_tokens",
    # "decode_admitted", "decode_retired" aggregate process-wide
    after = profiler.serve_stats()
    assert after["decode_retired"] - before["decode_retired"] == 16
    assert after["decode_admitted"] - before["decode_admitted"] == 16
    assert after["decode_tokens"] - before["decode_tokens"] \
        == sum(m for _, m in work) - 16      # first tokens come from prefill
    assert after["decode_prefill_tokens"] - before["decode_prefill_tokens"] \
        == sum(len(p) for p, _ in work)
    assert after["decode_iterations"] > before["decode_iterations"]
    assert st["decode_tokens_per_sec"] > 0
    assert st["ttft_p50_ms"] is not None
    assert json.dumps(st)


def test_multi_step_decode_equals_single_step(decoder):
    """decode_steps is pure amortization: K=1 and K=6 produce identical
    tokens (the scan replays the exact single-step math)."""
    model, ref = decoder
    work = _workload(6, seed=5)
    outs = {}
    for steps in (1, 6):
        with serve.ContinuousEngine(model, max_slots=2,
                                    decode_steps=steps) as eng:
            outs[steps] = [eng.generate(p, m, timeout=120)
                           for p, m in work]
    for a, b in zip(outs[1], outs[6]):
        np.testing.assert_array_equal(a, b)


def test_eos_stops_generation_and_frees_early(decoder):
    model, ref = decoder
    prompt, max_new = [7, 3, 19], 16
    base = ref.reference_generate(prompt, max_new)
    # pick a token the model actually emits mid-sequence as the eos
    eos = int(base[len(base) // 2])
    expect = ref.reference_generate(prompt, max_new, eos_id=eos)
    assert len(expect) < len(base)
    eng = serve.ContinuousEngine(model, max_slots=2, decode_steps=4,
                                 eos_id=eos).start()
    try:
        out = eng.generate(prompt, max_new, timeout=120)
    finally:
        eng.close()
    np.testing.assert_array_equal(out, expect)
    assert out[-1] == eos


def test_eos_mid_wave_keeps_exact_token_accounting(decoder):
    """Regression: eos zeroes a lane's remaining budget in-scan, so
    deriving per-lane emission from the steps_left delta OVERCOUNTED
    (inflating cache_len, appending garbage 0-tokens, and keeping the
    slot past eos). The scan now counts emitted tokens exactly."""
    model, ref = decoder
    prompt, max_new = [7, 3, 19], 16
    base = ref.reference_generate(prompt, max_new)
    eos = int(base[len(base) // 2])
    expect = ref.reference_generate(prompt, max_new, eos_id=eos)
    # decode_steps far larger than the post-eos remainder: eos fires
    # mid-wave with budget left
    eng = serve.ContinuousEngine(model, max_slots=2, decode_steps=8,
                                 eos_id=eos).start()
    try:
        out = eng.generate(prompt, max_new, timeout=120)
        st = eng.stats()
    finally:
        eng.close()
    np.testing.assert_array_equal(out, expect)
    # exact accounting: the only decode tokens are the reply minus the
    # prefill-emitted first token — no phantom post-eos tokens
    assert st["decode_tokens"] == len(out) - 1
    assert st["replies"] == 1 and st["pool"]["in_use"] == 0


def test_page_full_token_count_is_decode_steps_invariant(decoder):
    """Regression: the per-wave page-space cap allowed one token more
    than _finished/reference at a full page, so the token COUNT depended
    on decode_steps. K must be pure amortization."""
    cfg = serve.DecoderConfig(**dict(CFG, max_len=12))
    model = serve.CachedDecoder(cfg, seed=3)
    ref = serve.CachedDecoder(cfg, params=model.params)
    prompt, max_new = [7, 3, 19], 30           # page-limited, not count-
    expect = ref.reference_generate(prompt, max_new)
    for steps in (1, 7):
        with serve.ContinuousEngine(model, max_slots=2,
                                    decode_steps=steps) as eng:
            out = eng.generate(prompt, max_new, timeout=120)
        np.testing.assert_array_equal(
            out, expect, err_msg=f"decode_steps={steps} diverged at "
            f"page-full from the reference")


def test_step_failure_after_donation_engine_keeps_serving(decoder):
    """Regression: the compiled steps DONATE the pool buffers; an
    exception raised mid-execution (after donation) used to leave
    pool.k/v invalidated, killing every later wave. The failure path
    now reallocates the slab."""
    model, ref = decoder
    eng = serve.ContinuousEngine(model, max_slots=2,
                                 decode_steps=2).start()
    real = eng._decode_prog

    def boom_after_donation(params, k, v, *rest):
        real(params, k, v, *rest)    # consumes (donates) k and v
        raise RuntimeError("transient failure after donation")

    try:
        eng._decode_prog = boom_after_donation
        f = eng.submit([1, 2, 3], 6)
        with pytest.raises(serve.ServeError, match="engine step failed"):
            f.result(timeout=60)
        eng._decode_prog = real
        # the engine must keep serving correct results on fresh buffers
        out = eng.generate([4, 5], 5, timeout=60)
        st = eng.stats()
    finally:
        eng.close()
    np.testing.assert_array_equal(out, ref.reference_generate([4, 5], 5))
    assert st["errors"] == 1 and st["replies"] == 1


def test_long_prompt_streams_in_window_sized_chunks(decoder):
    """PR-14 rejected prompts longer than `prefill_window`; chunked
    prefill streams them window-sized slices per wave instead (through
    the warmed extent ladder), token-exact and zero-retrace, while
    short prompts keep using the cheap windowed head program."""
    model, ref = decoder
    long_prompt = list(range(1, 40))          # 39 tokens = 3 chunks @ 16
    with serve.ContinuousEngine(model, max_slots=2,
                                prefill_window=16) as eng:
        out = eng.generate(long_prompt, 4, timeout=120)
        short = eng.generate([1, 2, 3], 4, timeout=60)
        assert eng.assert_no_retraces() == 0
    np.testing.assert_array_equal(
        out, ref.reference_generate(long_prompt, 4, window=16),
        err_msg="chunked prefill diverged from the reference")
    np.testing.assert_array_equal(
        short, ref.reference_generate([1, 2, 3], 4, window=16))


# ---------------------------------------------------------------------------
# shared-prefix KV cache (engine integration; unit tests in
# tests/test_prefix_cache.py)
# ---------------------------------------------------------------------------
def test_prefix_cache_hit_is_token_exact_and_bills_suffix_only(decoder):
    """A second request sharing a cached prefix gets its KV via the row
    copy and prefills ONLY the suffix: `decode_prefill_tokens` (the
    MXNET_SERVE_PREFILL_BUDGET billing basis) moves by the suffix
    length, and the output still matches the explicit hit-path
    reference (`cached_prefix_len`)."""
    model, ref = decoder
    shared = list(range(1, 25))               # 24 tokens = 3 blocks of 8
    with serve.ContinuousEngine(model, max_slots=2, prefill_window=16,
                                prefix_block=8,
                                prefix_cache_slots=2) as eng:
        cold = eng.generate(shared + [30, 31], 6, timeout=120)
        before = profiler.serve_stats()["decode_prefill_tokens"]
        hot = eng.generate(shared + [32, 33], 6, timeout=120)
        after = profiler.serve_stats()["decode_prefill_tokens"]
        st = eng.stats()
        assert eng.prefix_hit_count() == 1
        assert eng.assert_no_retraces() == 0
    # 24 of the hit's 26 prompt tokens came from the copy: the budget
    # was billed 2 suffix tokens, not the full prompt
    assert after - before == 2
    assert st["prefix_hit_rate"] == 0.5       # 1 hit, 1 cold miss
    assert st["prefill_cached_token_share"] > 0.4
    assert st["prefix_cache"]["entries"] == 1
    np.testing.assert_array_equal(
        cold, ref.reference_generate(shared + [30, 31], 6, window=16))
    np.testing.assert_array_equal(
        hot, ref.reference_generate(shared + [32, 33], 6, window=16,
                                    cached_prefix_len=24),
        err_msg="prefix-cache hit diverged from the hit-path reference")


def test_prefix_cache_hit_token_exact_int8(decoder):
    """Same contract on a quantized pool: the row copy moves codes AND
    scales, so a hit dequantizes bit-identically to cold provenance."""
    model, ref = decoder
    shared = list(range(3, 19))               # 16 tokens = 2 blocks of 8
    with serve.ContinuousEngine(model, max_slots=2, prefill_window=16,
                                prefix_block=8, prefix_cache_slots=2,
                                kv_dtype="int8") as eng:
        cold = eng.generate(shared + [33], 5, timeout=120)
        hot = eng.generate(shared + [34, 35], 5, timeout=120)
        assert eng.prefix_hit_count() == 1
        assert eng.assert_no_retraces() == 0
    np.testing.assert_array_equal(
        cold, ref.reference_generate(shared + [33], 5, window=16,
                                     kv_dtype="int8"))
    np.testing.assert_array_equal(
        hot, ref.reference_generate(shared + [34, 35], 5, window=16,
                                    kv_dtype="int8", cached_prefix_len=16))


def test_shared_prefix_poison_isolation(decoder):
    """Poison every slab row EXCEPT the cache's pinned rows after the
    prefix is published: a later hit reads only the cache row (copied
    into its slot) and its own suffix KV, so the output must match the
    hit-path reference bit-for-bit — nothing a prior tenant wrote, and
    nothing beyond the copied prefix, is reachable."""
    model, ref = decoder
    eng = serve.ContinuousEngine(model, max_slots=1, prefill_window=16,
                                 prefix_block=8, prefix_cache_slots=1,
                                 decode_steps=2).start()
    try:
        shared = list(range(2, 18))           # 16 tokens = 2 blocks
        eng.generate(shared + [30], 6, timeout=120)    # publishes [0,16)
        cache_rows = set(eng.pool.in_use())   # only the cache's claim
        assert len(cache_rows) == 1
        for s in range(eng.pool.max_slots + 1):        # incl. garbage
            if s not in cache_rows:
                eng.pool.poison_slot(s, 1e9)
        hot = eng.generate(shared + [31, 32], 6, timeout=120)
        assert eng.prefix_hit_count() == 1
    finally:
        eng.close()
    np.testing.assert_array_equal(
        hot, ref.reference_generate(shared + [31, 32], 6, window=16,
                                    cached_prefix_len=16),
        err_msg="a poisoned row leaked into a shared-prefix hit")


def test_admission_budget_uses_post_cache_cost(decoder):
    """The EDF grant bills waiters at their POST-CACHE prefill cost: a
    fully-cached long prompt (1-token suffix) fits a nearly-exhausted
    `prefill_budget` and is admitted PAST an earlier-submitted cold
    prompt whose full-window cost does not — the budget sees the
    suffix, not the prompt length (pre-PR-19 both billed full-window
    and the cold one, being first, would have won the slot)."""
    model, _ = decoder
    eng = serve.ContinuousEngine(model, max_slots=2, prefill_lanes=2,
                                 prefill_window=16, prefix_block=8,
                                 prefix_cache_slots=1, prefill_budget=8,
                                 decode_steps=1).start()
    order = []
    lock = threading.Lock()
    try:
        shared = list(range(1, 17))           # 16 tokens = 2 blocks
        eng.generate(shared + [20], 2, timeout=120)    # publish prefix
        held = [eng.pool.claim(), eng.pool.claim()]    # block admission
        first = eng.submit([40, 41, 42, 43], 2)        # cost 4 (>=1 grant)
        cold = eng.submit(list(range(30, 44)), 2)      # cost 14 > budget
        hot = eng.submit(shared + [21], 2)             # cost 1, fits

        def watch(name, fut):
            fut.result(timeout=120)
            with lock:
                order.append(name)

        ts = [threading.Thread(target=watch, args=(n, f))
              for n, f in (("first", first), ("cold", cold),
                           ("hot", hot))]
        for t in ts:
            t.start()
        time.sleep(0.05)                      # all three demonstrably wait
        for s in held:
            eng.pool.free(s)
        for t in ts:
            t.join(timeout=120)
    finally:
        eng.close()
    # wave 1 admits `first` (the >=1 grant, 4 of 8 budget) and `hot`
    # (1 token fits the 4 left); `cold` (14) waits for the next wave
    assert order.index("hot") < order.index("cold"), \
        f"suffix-cost waiter was not granted a slot first: {order}"


# ---------------------------------------------------------------------------
# KV-slot lifecycle
# ---------------------------------------------------------------------------
def test_kv_pool_claim_free_and_typed_exhaustion():
    pool = serve.KVCachePool(max_slots=3, layers=1, max_len=8, heads=2,
                             head_dim=4, allocate=False)
    before = serve.kvpool_stats()
    slots = [pool.claim() for _ in range(3)]
    assert sorted(slots) == [0, 1, 2]
    assert pool.free_count() == 0
    with pytest.raises(serve.SlotsFullError):
        pool.claim()
    # SlotsFullError is a typed ServeError (admission can catch it)
    assert issubclass(serve.SlotsFullError, serve.ServeError)
    pool.free(slots[0])
    assert pool.free_count() == 1
    with pytest.raises(serve.ServeError, match="double free"):
        pool.free(slots[0])
    after = serve.kvpool_stats()
    # "claims" / "frees" / "exhausted" process-wide counters moved
    assert after["claims"] - before["claims"] == 3
    assert after["frees"] - before["frees"] == 1
    assert after["exhausted"] - before["exhausted"] == 1
    assert KVPOOL_STATS["claims"] >= 3
    st = pool.stats()
    assert st["in_use"] == 2 and st["free"] == 1 and st["max_slots"] == 3


def test_kv_pool_concurrent_claim_free_hammer():
    """8 threads churn claim/free; bookkeeping stays exact: no slot is
    ever handed to two holders, counts balance, capacity is respected."""
    pool = serve.KVCachePool(max_slots=4, layers=1, max_len=8, heads=2,
                             head_dim=4, allocate=False)
    errs, held_twice = [], []
    lock = threading.Lock()
    held = set()

    def hammer(tid):
        rng = np.random.RandomState(tid)
        try:
            for _ in range(300):
                try:
                    s = pool.claim()
                except serve.SlotsFullError:
                    continue
                with lock:
                    if s in held:
                        held_twice.append(s)
                    held.add(s)
                if rng.rand() < 0.5:
                    time.sleep(0)
                with lock:
                    held.discard(s)
                pool.free(s)
        except BaseException as e:   # pragma: no cover - diagnostics
            errs.append(e)

    threads = [threading.Thread(target=hammer, args=(t,))
               for t in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=120)
    assert not errs, errs
    assert not held_twice, f"slots double-claimed: {held_twice}"
    assert pool.free_count() == 4 and pool.in_use() == []


def test_slot_reuse_cannot_read_prior_request_cache(decoder):
    """Poison-fill + value check: fill the WHOLE slab with a sentinel,
    then run a request through a reused slot — output must match the
    fresh-pool reference bit-for-bit, proving no read escapes the
    current request's [0, cur_len] window (prefill_window < max_len, so
    the page is NOT fully overwritten at claim: only the mask protects
    the tail)."""
    model, ref = decoder
    eng = serve.ContinuousEngine(model, max_slots=1, prefill_window=16,
                                 decode_steps=2).start()
    try:
        # tenant 1 dirties slot 0 with its own KV
        eng.generate([9, 8, 7, 6], 10, timeout=60)
        assert eng.pool.in_use() == []
        # now poison EVERYTHING the compiled programs could read
        eng.pool.poison(1e9)
        out = eng.generate([1, 2, 3], 8, timeout=60)
    finally:
        eng.close()
    np.testing.assert_array_equal(
        out, ref.reference_generate([1, 2, 3], 8, window=16),
        err_msg="reused slot leaked a prior tenant's cache into decode")


def test_requests_queue_when_slots_full_then_complete(decoder):
    model, ref = decoder
    work = _workload(10, seed=9)
    with serve.ContinuousEngine(model, max_slots=2,
                                decode_steps=2) as eng:
        futs = [eng.submit(p, m) for p, m in work]
        outs = [f.result(timeout=120) for f in futs]
        st = eng.stats()
    assert st["pool"]["in_use"] == 0
    assert st["replies"] == 10
    for (p, m), o in zip(work, outs):
        np.testing.assert_array_equal(o, ref.reference_generate(p, m))


# ---------------------------------------------------------------------------
# SLO-aware admission
# ---------------------------------------------------------------------------
def test_deadline_aware_slot_grant_beats_fifo(decoder):
    """With the pool exhausted, a LATER-submitted request holding a
    deadline is granted the next slot before an earlier deadline-less
    one. The slot is held by a DIRECT pool claim (no request timing to
    race): admission can only happen after the test frees it."""
    model, _ = decoder
    eng = serve.ContinuousEngine(model, max_slots=1, prefill_lanes=1,
                                 decode_steps=1).start()
    order = []
    lock = threading.Lock()
    try:
        held = eng.pool.claim()                    # engine cannot admit
        fifo = eng.submit([1, 2], 4)               # waiting, no deadline
        slo = eng.submit([3, 4], 4, deadline_ms=30000)   # waiting, SLO

        def watch(name, fut):
            fut.result(timeout=120)
            with lock:
                order.append(name)

        ts = [threading.Thread(target=watch, args=(n, f))
              for n, f in (("fifo", fifo), ("slo", slo))]
        for t in ts:
            t.start()
        time.sleep(0.05)                           # both demonstrably wait
        eng.pool.free(held)
        for t in ts:
            t.join(timeout=120)
    finally:
        eng.close()
    assert order and order[0] == "slo", \
        f"deadline-holder was not granted the slot first: {order}"


def test_deadline_expires_while_waiting_for_slot(decoder):
    model, _ = decoder
    before = profiler.serve_stats()["timeouts"]
    eng = serve.ContinuousEngine(model, max_slots=1, prefill_lanes=1,
                                 decode_steps=1).start()
    try:
        held = eng.pool.claim()                    # engine cannot admit
        doomed = eng.submit([1, 2], 4, deadline_ms=15)
        with pytest.raises(serve.RequestTimeout, match="KV slot"):
            doomed.result(timeout=60)
        eng.pool.free(held)
        # the engine keeps serving after the expiry
        assert eng.generate([3, 3], 3, timeout=60).size == 3
    finally:
        eng.close()
    assert profiler.serve_stats()["timeouts"] == before + 1


def test_queue_full_rejects_typed(decoder):
    model, _ = decoder
    eng = serve.ContinuousEngine(model, max_slots=1, prefill_lanes=1,
                                 max_queue=2, decode_steps=1).start()
    try:
        futs = [eng.submit([5, 5], 40)]
        rejected = 0
        for _ in range(12):
            try:
                futs.append(eng.submit([1, 2], 2))
            except serve.QueueFullError as e:
                assert e.policy == "reject"
                rejected += 1
        assert rejected > 0
        for f in futs:
            f.result(timeout=120)
    finally:
        eng.close()


def test_closed_engine_rejects_and_drains(decoder):
    model, ref = decoder
    eng = serve.ContinuousEngine(model, max_slots=2).start()
    futs = [eng.submit(p, m) for p, m in _workload(6, seed=2)]
    eng.close(drain=True)
    assert all(f.exception() is None for f in futs)
    with pytest.raises(serve.ServerClosed):
        eng.submit([1, 2], 4)


def test_submit_during_drain_raises_typed_replica_draining(decoder):
    """DRAINING is not CLOSED: while the engine is still finishing its
    resident requests before a restart, submit() must raise the typed
    ReplicaDraining (a ServerClosed subclass the fleet router re-routes
    silently), and revert to plain ServerClosed once the drain is done."""
    model, _ = decoder
    eng = serve.ContinuousEngine(model, max_slots=2, decode_steps=2).start()
    resident = eng.submit([1, 2, 3], 10)
    eng.begin_drain()
    assert eng.draining
    with pytest.raises(serve.ReplicaDraining, match="draining"):
        eng.submit([4], 2)
    assert issubclass(serve.ReplicaDraining, serve.ServerClosed)
    # the resident lane still finishes: drain never cancels admitted work
    assert resident.result(timeout=120).size == 10
    eng.close()
    assert not eng.draining
    try:
        eng.submit([4], 2)
        pytest.fail("closed engine accepted a request")
    except serve.ReplicaDraining:
        pytest.fail("closed engine must raise plain ServerClosed")
    except serve.ServerClosed:
        pass


def test_drain_completes_when_waiting_lane_expires_mid_drain(decoder):
    """A waiting request whose deadline fires DURING the drain must not
    wedge close(drain=True): the loop drops the expired waiter and exits."""
    model, _ = decoder
    eng = serve.ContinuousEngine(model, max_slots=1, prefill_lanes=1,
                                 decode_steps=1).start()
    held = eng.pool.claim()            # the waiter can never be admitted
    doomed = eng.submit([3], 4, deadline_ms=300)
    t0 = time.time()
    eng.close(drain=True, timeout=30)
    dt = time.time() - t0
    # gated by the 300ms deadline, not wedged and not instant
    assert 0.2 <= dt < 10, dt
    with pytest.raises(serve.RequestTimeout, match="KV slot"):
        doomed.result(timeout=1)
    eng.pool.free(held)


# ---------------------------------------------------------------------------
# tracing: one request = one trace across N iterations
# ---------------------------------------------------------------------------
def test_one_trace_across_iterations(decoder, tmp_path):
    model, _ = decoder
    profiler.start()
    try:
        with serve.ContinuousEngine(model, max_slots=2,
                                    decode_steps=2) as eng:
            futs = [eng.submit([3, 1, 4], 9), eng.submit([2, 7], 7)]
            for f in futs:
                f.result(timeout=120)
            st = eng.stats()
            assert st["decode_iterations"] >= 2
    finally:
        profiler.stop()
    f = str(tmp_path / "trace.json")
    profiler.dump(filename=f)
    events = json.load(open(f))["traceEvents"]
    roots = [e for e in events if e["name"] == "serve.request"
             and "tokens" in e.get("args", {})]
    assert len(roots) == 2
    tids = {e["args"]["trace_id"] for e in roots}
    assert len(tids) == 2, "each request must be its own trace"
    for root in roots:
        tid = root["args"]["trace_id"]
        span_id = root["args"]["span_id"]
        prefill = [e for e in events if e["name"] == "serve.prefill"
                   and e["args"].get("trace_id") == tid]
        decode = [e for e in events if e["name"] == "serve.decode"
                  and e["args"].get("trace_id") == tid]
        # admission->first-token and first->last-token (N iterations)
        # both hang off the SAME request root: one trace, N iterations
        assert len(prefill) == 1 and len(decode) == 1
        assert prefill[0]["args"]["parent_span_id"] == span_id
        assert decode[0]["args"]["parent_span_id"] == span_id
        assert decode[0]["args"]["tokens"] == root["args"]["tokens"]
    # the engine's wave lanes recorded too (collector was active)
    assert any(e["name"] == "serve.decode_batch" for e in events)
    assert any(e["name"] == "serve.prefill_batch" for e in events)


# ---------------------------------------------------------------------------
# persistent compilation cache: warm replica skips compile
# ---------------------------------------------------------------------------
_REPLICA_PROG = r"""
import sys
from incubator_mxnet_tpu import serve
cfg = serve.DecoderConfig(vocab=64, embed=32, layers=2, heads=4,
                          head_dim=8, max_len=48)
model = serve.CachedDecoder(cfg, seed=11)
eng = serve.ContinuousEngine(model, max_slots=2).start()
out = eng.generate([1, 2, 3], 5, timeout=60)
eng.close()
print("WARMUP_S", eng.warmup_s)
print("TOKENS", ",".join(str(t) for t in out))
"""


def test_compile_cache_dir_warms_second_replica(tmp_path):
    """Two FRESH processes sharing one MXNET_COMPILE_CACHE_DIR — the
    real replica semantics: the first compiles and serializes, the
    second deserializes. (In-process clear_caches() would corrupt live
    compiled programs elsewhere in the suite; replicas are processes.)"""
    d = str(tmp_path / "cc")
    os.makedirs(d)
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               MXNET_COMPILE_CACHE_DIR=d)

    def replica():
        r = subprocess.run([sys.executable, "-c", _REPLICA_PROG],
                           env=env, capture_output=True, text=True,
                           timeout=300)
        assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
        warm_s = float(r.stdout.split("WARMUP_S")[1].split()[0])
        toks = r.stdout.split("TOKENS")[1].split()[0]
        return warm_s, toks

    cold, toks_cold = replica()
    assert len(os.listdir(d)) > 0, \
        "no executables persisted to MXNET_COMPILE_CACHE_DIR"
    warm, toks_warm = replica()
    # same executables -> same tokens; the warm replica deserializes
    # instead of compiling. On a busy CI host we only assert it is NOT
    # SLOWER (the committed bench artifact carries the measured speedup)
    assert toks_cold == toks_warm
    assert warm <= cold * 1.2, (cold, warm)


# ---------------------------------------------------------------------------
# bench smoke + committed artifact acceptance
# ---------------------------------------------------------------------------
def test_serve_bench_autoregressive_quick_smoke(tmp_path):
    out = tmp_path / "autoreg.json"
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "serve_bench.py")
    r = subprocess.run(
        [sys.executable, script, "--autoregressive", "--quick",
         "--duration", "1.0", "--out", str(out)],
        capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(out.read_text())
    assert data["backend_ok"] is True
    assert data["meta"]["mode"] == "autoregressive"
    assert data["continuous"]["decode_tokens_per_sec"] > 0
    assert data["continuous"]["retraces_after_warmup"] == 0
    assert data["static"]["decode_tokens_per_sec"] > 0
    assert data["serve_decode_tokens_per_sec"] > 0
    assert data["serve_ttft_p99_ms"] > 0
    assert data["compile_cache_entries"] > 0


def test_committed_continuous_artifact_acceptance():
    """The committed r14 artifact holds the ISSUE-14 acceptance: >= 2x
    decode tokens/s over the static batcher at concurrency 32, zero
    retraces, and a measurable warm-replica compile skip."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "results",
        "serve_continuous_r14.json")
    data = json.load(open(path))
    assert data["backend_ok"] is True
    assert data["meta"]["concurrency"] == 32
    assert data["serve_continuous_speedup_vs_static"] >= 2.0
    assert data["continuous"]["retraces_after_warmup"] == 0
    # continuous TTFT tail beats static's by construction
    assert data["continuous"]["ttft_p99_ms"] \
        < data["static"]["ttft_p99_ms"]
    assert data["serve_compile_cache_warm_speedup"] > 1.2
    rows = data["autoreg_open_loop"]
    assert len(rows) >= 4
    offered = [r["offered_rps"] for r in rows]
    assert offered == sorted(offered)
    # the sweep crosses saturation: decode tokens/s stops tracking the
    # offered load at the top rates
    assert rows[-1]["achieved_rps"] < 0.9 * rows[-1]["offered_rps"]


def test_committed_prefill_artifact_acceptance():
    """The committed r19 artifact holds the ISSUE-19 acceptance: >= 1.5x
    prefill tokens/s from prefix caching on the shared-prefix workload
    at token-exact quality, zero retraces on every arm, and short-
    request TTFT p99 under long-prompt interference bounded <= 2x the
    no-long-prompt baseline — with an honest CPU provenance note."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "results",
        "prefill_r19.json")
    data = json.load(open(path))
    assert data["backend_ok"] is True
    assert data["meta"]["mode"] == "shared_prefix"
    assert data["serve_prefill_speedup_cached"] >= 1.5
    assert data["cache_on"]["prefill_tokens_per_sec"] \
        > data["cache_off"]["prefill_tokens_per_sec"]
    assert data["prefill_cached_token_share"] >= 0.5
    assert data["cache_on"]["prefix_hit_rate"] > 0.9
    assert data["prefill_token_exact"] is True
    assert data["prefill_token_exact_checked"] >= 4
    # the long-prompt interference bound: chunked prefill keeps short
    # requests' TTFT p99 within 2x of the longs-free baseline
    assert data["interference_ttft_p99_blowup"] <= 2.0
    assert data["serve_ttft_p99_ms_interference"] \
        <= 2.0 * data["serve_ttft_p99_ms_no_longs"]
    for arm in ("cache_off", "cache_on", "shorts_alone",
                "shorts_with_longs"):
        assert data[arm]["retraces_after_warmup"] == 0, arm
        assert data[arm].get("errors") == {}, arm
    # the cached arm's uplift is real ingest: both arms bill the FULL
    # prompt length client-side (the note must say so)
    assert "suffix" in data["note"]
    assert data["meta"]["workload"]["shared_prefix_len"] \
        >= 2 * data["meta"]["workload"]["prefix_block"]
