"""SSD-300/VGG16 preset + detection mAP metric (ROADMAP items, ≙ the
reference example/ssd model + VOC mAP evaluation)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon.metric import MeanAveragePrecision
from incubator_mxnet_tpu.gluon.model_zoo.detection import (SSD300,
                                                           ssd_300_vgg16,
                                                           ssd_anchor_sizes)


def test_anchor_schedule():
    sizes = ssd_anchor_sizes()
    assert len(sizes) == 6
    assert sizes[0][0] == pytest.approx(0.1)
    assert all(s2 > s1 > 0 for s1, s2 in sizes)


def test_ssd300_canonical_anchor_count():
    """The defining invariant of SSD-300: 8732 anchors."""
    net = ssd_300_vgg16(classes=20)
    net.initialize()
    x = mx.np.zeros((1, 3, 300, 300))
    anchors, cls_preds, loc_preds = net(x)
    assert anchors.shape == (1, 8732, 4)
    assert cls_preds.shape == (1, 8732, 21)
    assert loc_preds.shape == (1, 8732 * 4)


def test_ssd300_detect_and_targets():
    net = ssd_300_vgg16(classes=3)
    net.initialize()
    x = mx.np.array(
        np.random.RandomState(0).randn(2, 3, 300, 300).astype(np.float32))
    out = net.detect(x)
    assert out.shape[0] == 2 and out.shape[2] == 6
    # training targets from ground truth
    labels = mx.np.array(np.array(
        [[[0, 0.1, 0.1, 0.4, 0.4]], [[2, 0.5, 0.5, 0.9, 0.9]]],
        np.float32))
    anchors, cls_preds, loc_preds = net(x)
    loc_t, loc_m, cls_t = net.targets(anchors, labels, cls_preds)
    assert loc_t.shape == (2, 8732 * 4)
    assert cls_t.shape == (2, 8732)
    assert int((cls_t.asnumpy() > 0).sum()) > 0   # some anchors matched


def test_map_metric_perfect_and_mixed():
    m = MeanAveragePrecision(iou_thresh=0.5)
    gt = mx.np.array(np.array([[[0, 0.1, 0.1, 0.5, 0.5],
                                [1, 0.6, 0.6, 0.9, 0.9]]], np.float32))
    perfect = mx.np.array(np.array(
        [[[0, 0.9, 0.1, 0.1, 0.5, 0.5],
          [1, 0.8, 0.6, 0.6, 0.9, 0.9]]], np.float32))
    m.update(gt, perfect)
    assert m.get()[1] == pytest.approx(1.0)

    m.reset()
    # class 0: one perfect + one false positive at higher score
    mixed = mx.np.array(np.array(
        [[[0, 0.95, 0.7, 0.7, 0.8, 0.8],     # FP (wrong place)
          [0, 0.90, 0.1, 0.1, 0.5, 0.5],     # TP
          [1, 0.80, 0.6, 0.6, 0.9, 0.9]]], np.float32))
    m.update(gt, mixed)
    # class 0 AP: precision at its only TP is 1/2, recall 1 -> AP 0.5
    # class 1 AP: 1.0  =>  mAP 0.75
    assert m.get()[1] == pytest.approx(0.75)
    aps = m.get_class_aps()
    assert aps[0] == pytest.approx(0.5)
    assert aps[1] == pytest.approx(1.0)


def test_map_metric_missed_gt_counts_against_recall():
    m = MeanAveragePrecision()
    gt = mx.np.array(np.array([[[0, 0.1, 0.1, 0.5, 0.5],
                                [0, 0.6, 0.6, 0.9, 0.9]]], np.float32))
    one_hit = mx.np.array(np.array(
        [[[0, 0.9, 0.1, 0.1, 0.5, 0.5]]], np.float32))
    m.update(gt, one_hit)
    # 1 TP of 2 gts, no FPs: integral AP = recall 0.5 at precision 1
    assert m.get()[1] == pytest.approx(0.5)
