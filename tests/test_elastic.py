"""mx.fault.elastic + mx.optimizer.sharded (ISSUE 12 acceptance): ZeRO
optimizer-state sharding over the dp mesh axis, bucketed reduce-scatter /
all-gather through the kvstore timeline, manifest-committed per-shard
checkpoints, bit-exact resume onto the same AND a smaller dp mesh under
fault injection, straggler attribution, and graceful mesh shrink."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import checkpoint as ckpt
from incubator_mxnet_tpu import fault
from incubator_mxnet_tpu import kvstore as kv
from incubator_mxnet_tpu import telemetry
from incubator_mxnet_tpu.fault import elastic
from incubator_mxnet_tpu.optimizer import sharded as shz

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


def _need8():
    import jax
    if len(jax.devices()) < 8:
        pytest.skip("needs the forced 8-device mesh")
    return jax.devices()


# ---------------------------------------------------------------------------
# shard math
# ---------------------------------------------------------------------------
def test_shard_math_roundtrip_and_uneven_repartition():
    a = np.arange(10, dtype=np.float32).reshape(2, 5)
    v3 = shz.to_shards(a, 3)                 # numel 10 -> (3, 4), padded
    assert v3.shape == (3, 4)
    np.testing.assert_array_equal(
        shz.from_shards(v3, 10, (2, 5)), a)
    v2 = shz.repartition(v3, 10, 2)          # uneven 3 -> 2
    assert v2.shape == (2, 5)
    np.testing.assert_array_equal(shz.from_shards(v2, 10, (2, 5)), a)
    assert v2.dtype == np.float32


def test_shard_math_preserves_dtype_and_scalars():
    for dt in (np.float16, np.float64, np.int32):
        a = (np.arange(7) + 1).astype(dt)
        v = shz.repartition(shz.to_shards(a, 4), 7, 5)
        assert v.dtype == dt
        np.testing.assert_array_equal(shz.from_shards(v, 7), a)
    s = shz.to_shards(np.float32(3.5), 4)    # 0-d: one real element
    assert s.shape == (4, 1)
    assert shz.from_shards(s, 1, ()) == np.float32(3.5)


# ---------------------------------------------------------------------------
# bucketed collectives (the kvstore ZeRO data path)
# ---------------------------------------------------------------------------
def _mesh(dp):
    import jax
    devs = _need8()
    return jax.sharding.Mesh(np.array(devs[:dp]), ("dp",))


def _stack(mesh, per_replica):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    spec = P("dp", *([None] * (per_replica.ndim - 1)))
    return jax.device_put(per_replica, NamedSharding(mesh, spec))


def test_reduce_scatter_buckets_values_and_stats():
    mesh = _mesh(8)
    rng = np.random.RandomState(0)
    grads = [rng.randn(8, 10).astype(np.float32),
             rng.randn(8, 3, 3).astype(np.float32),
             # second dtype bucket (f16 — jax would demote a f64 to f32)
             rng.randn(8, 5).astype(np.float16)]
    base = kv.KV_STATS.snapshot()
    outs = kv.reduce_scatter_buckets([_stack(mesh, g) for g in grads],
                                     mesh, scale=1.0 / 8)
    for g, o in zip(grads, outs):
        n = int(np.prod(g.shape[1:]))
        L = -(-n // 8)
        assert o.shape == (8, L)
        assert np.asarray(o).dtype == g.dtype
        got = np.asarray(o).reshape(-1)[:n]
        np.testing.assert_allclose(
            got, g.reshape(8, -1).astype(np.float64).mean(axis=0)
            .astype(g.dtype), rtol=5e-3 if g.dtype == np.float16
            else 1e-5)
        # padding rows are exact zeros (moment shards stay clean)
        np.testing.assert_array_equal(np.asarray(o).reshape(-1)[n:], 0)
    snap = kv.KV_STATS.snapshot()
    assert snap["reduce_scatter_buckets"] >= base["reduce_scatter_buckets"] + 2
    assert snap["reduce_scatter_us"] > base["reduce_scatter_us"]
    assert snap["reduce_scatter_bytes"] >= base["reduce_scatter_bytes"] + (
        10 * 4 + 9 * 4 + 5 * 2)


def test_allgather_buckets_values_and_stats():
    mesh = _mesh(8)
    a = np.arange(20, dtype=np.float32).reshape(4, 5)
    shard = _stack(mesh, shz.to_shards(a, 8))
    base = kv.KV_STATS.snapshot()
    outs = kv.allgather_buckets([shard], [(20, (4, 5))], mesh)
    np.testing.assert_array_equal(np.asarray(outs[0]), a)
    snap = kv.KV_STATS.snapshot()
    assert snap["allgather_buckets"] > base["allgather_buckets"]
    assert snap["allgather_us"] > base["allgather_us"]
    assert snap["allgather_bytes"] >= base["allgather_bytes"] + 20 * 4


def test_collective_fault_points_fire():
    mesh = _mesh(4)
    g = _stack(mesh, np.ones((4, 6), np.float32))
    with fault.scope("kvstore.reduce_scatter:1:ioerror"):
        with pytest.raises(IOError):
            kv.reduce_scatter_buckets([g], mesh)
    s = _stack(mesh, shz.to_shards(np.ones(6, np.float32), 4))
    with fault.scope("kvstore.allgather:1:timeout"):
        with pytest.raises(TimeoutError):
            kv.allgather_buckets([s], [(6, (6,))], mesh)


# ---------------------------------------------------------------------------
# ShardedOptimizer: memory + parity against the dense rules
# ---------------------------------------------------------------------------
def _mlp_problem(dim=12, batch=32):
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    params = {"w1": rng.randn(dim, 8).astype(np.float32) / 3,
              "b1": np.zeros(8, np.float32),
              "w2": rng.randn(8, 1).astype(np.float32) / 3}

    def loss_fn(p, b):
        h = jnp.tanh(b["x"] @ p["w1"] + p["b1"])
        return jnp.mean((h @ p["w2"] - b["y"]) ** 2)

    def batch_fn(step):
        r = np.random.RandomState(1000 + step)
        return {"x": r.randn(batch, dim).astype(np.float32),
                "y": r.randn(batch, 1).astype(np.float32)}
    return params, loss_fn, batch_fn


def _dense_reference(params, loss_fn, batch_fn, optimizer, steps,
                     **opt_kwargs):
    """The unsharded trajectory: full-gradient + plain Optimizer.update."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu import optimizer as opt_mod
    from incubator_mxnet_tpu.ndarray import array as nd_array
    o = opt_mod.create(optimizer, **opt_kwargs)
    ref = {k: v.copy() for k, v in params.items()}
    states = {}
    names = sorted(ref)
    for s in range(steps):
        b = {k: jnp.asarray(v) for k, v in batch_fn(s).items()}
        g = jax.grad(lambda pl: loss_fn(dict(zip(names, pl)), b))(
            [jnp.asarray(ref[n]) for n in names])
        for n, gi in zip(names, g):
            wnd, gnd = nd_array(ref[n]), nd_array(np.asarray(gi))
            if n not in states:
                states[n] = o.create_state(n, wnd)
            o.update(n, wnd, gnd, states[n])
            ref[n] = wnd.asnumpy()
    return ref


@pytest.mark.parametrize("opt_name,opt_kwargs", [
    ("sgd", {"momentum": 0.9, "learning_rate": 0.05}),
    ("adam", {"learning_rate": 0.01}),      # exercises the traced-t path
])
def test_sharded_trainer_matches_dense_optimizer(opt_name, opt_kwargs):
    _need8()
    params, loss_fn, batch_fn = _mlp_problem()
    tr = elastic.ElasticTrainer(loss_fn, params, optimizer=opt_name,
                                dp=8, **opt_kwargs)
    for s in range(5):
        tr.step(batch_fn(s))
    ref = _dense_reference(params, loss_fn, batch_fn, opt_name, 5,
                           **opt_kwargs)
    got = tr.state_arrays()
    for n in ref:
        np.testing.assert_allclose(got[n], ref[n], rtol=2e-5, atol=2e-6)


def test_state_memory_per_replica_drops_linearly_with_dp():
    _need8()
    params, loss_fn, _ = _mlp_problem(dim=64)
    mems = {}
    for dp in (2, 8):
        tr = elastic.ElasticTrainer(loss_fn, params, optimizer="sgd",
                                    dp=dp, momentum=0.9)
        mems[dp] = tr.mem_per_replica_bytes()
    # ZeRO acceptance: per-replica state scales ~1/dp (exact here —
    # shard padding is the only slack and these shapes divide evenly)
    assert mems[2] / mems[8] == pytest.approx(4.0, rel=0.05)
    assert telemetry.snapshot()["elastic.mem_per_replica_bytes"] == mems[8]


def test_sharded_optimizer_rejects_unshardable_rules():
    mesh = _mesh(2)
    from incubator_mxnet_tpu.optimizer.sharded import ShardedOptimizer
    with pytest.raises(mx.MXNetError, match="fused_safe"):
        ShardedOptimizer("nadam", mesh)   # per-step host state (m_schedule)


# ---------------------------------------------------------------------------
# collective retry / straggler watchdog
# ---------------------------------------------------------------------------
def test_transient_collective_error_is_retried_and_counted():
    _need8()
    params, loss_fn, batch_fn = _mlp_problem()
    tr = elastic.ElasticTrainer(loss_fn, params, optimizer="sgd", dp=4,
                                momentum=0.9, collective_retries=2)
    base = telemetry.snapshot().get("elastic.collective_retries", 0)
    # transient: the FIRST bucket dispatch fails once, the retry clears
    fault.install("kvstore.reduce_scatter", "ioerror", at=1)
    tr.step(batch_fn(0))
    assert telemetry.snapshot()["elastic.collective_retries"] == base + 1


def test_persistent_collective_error_exhausts_retry_budget():
    _need8()
    params, loss_fn, batch_fn = _mlp_problem()
    tr = elastic.ElasticTrainer(loss_fn, params, optimizer="sgd", dp=4,
                                momentum=0.9, collective_retries=1)
    fault.install("kvstore.reduce_scatter", "ioerror", at=1,
                  persistent=True)
    with pytest.raises(IOError):
        tr.step(batch_fn(0))


def test_straggler_report_healthy_and_stalled():
    mesh = _mesh(4)
    rep = elastic.straggler_report(mesh, probe_timeout=10.0)
    assert [r["rank"] for r in rep] == [0, 1, 2, 3]
    assert all(r["ok"] for r in rep)

    def wedged(rank, device):
        if rank == 2:
            time.sleep(60)
    rep = elastic.straggler_report(mesh, probe_timeout=0.3,
                                   probe_fn=wedged)
    assert [r["rank"] for r in rep if not r["ok"]] == [2]


def test_collective_stall_raises_straggler_timeout_naming_rank():
    _need8()
    params, loss_fn, batch_fn = _mlp_problem()

    def wedged(rank, device):
        if rank == 1:
            time.sleep(60)
    tr = elastic.ElasticTrainer(loss_fn, params, optimizer="sgd", dp=4,
                                momentum=0.9, collective_timeout=0.4,
                                collective_retries=0, probe_fn=wedged)
    fault.install("kvstore.reduce_scatter", "stall", at=1, arg=5)
    with pytest.raises(elastic.StragglerTimeout) as ei:
        tr.step(batch_fn(0))
    assert ei.value.stalled_ranks == [1]
    assert "rank" in str(ei.value)
    assert any(r["rank"] == 1 and not r["ok"] for r in ei.value.report)


# ---------------------------------------------------------------------------
# run_elastic: crash -> bit-exact resume (same mesh, quadratic model)
# ---------------------------------------------------------------------------
def _run(params, loss_fn, batch_fn, d, dp, steps, **kw):
    kw.setdefault("momentum", 1.0)
    kw.setdefault("learning_rate", 0.25)
    return elastic.run_elastic(loss_fn, params, batch_fn, d, steps,
                               optimizer="sgd", dp=dp, ckpt_every=3, **kw)


def _lattice_problem():
    """Linear-in-w loss with integer data on an exact f32 lattice: every
    reduction order (dp=8 vs dp=4 group sums) yields IDENTICAL bits, so
    cross-mesh parity tests the checkpoint/repartition protocol, not
    float summation order (same trick as tools/crashtest.py --elastic)."""
    import jax.numpy as jnp

    def loss_fn(p, batch):
        return jnp.mean(batch["c"] @ p["w"]) + 0.0 * jnp.sum(p["v"])

    def batch_fn(step):
        r = np.random.RandomState(7 + step)
        return {"c": r.randint(-8, 9, (64, 12)).astype(np.float32)}

    params = {"w": (np.arange(12, dtype=np.float32) - 6) / 4.0,
              "v": np.ones((3, 5), np.float32)}
    return params, loss_fn, batch_fn


def _assert_state_parity(ref_run, got_run):
    rp, gp = ref_run.params(), got_run.params()
    ro, go = ref_run.opt_state(), got_run.opt_state()
    for n in rp:
        np.testing.assert_array_equal(rp[n], gp[n])
        np.testing.assert_array_equal(ro[n], go[n])


def test_crash_resume_same_mesh_bit_exact_params_and_opt(tmp_path):
    _need8()
    params, loss_fn, batch_fn = _mlp_problem()
    kw = dict(momentum=0.9, learning_rate=0.05)
    ref = _run(params, loss_fn, batch_fn, str(tmp_path / "ref"), 8, 10,
               **kw)
    d = str(tmp_path / "crash")
    # ioerror (NOT InjectedFault): a plain crash, not simulated worker
    # loss — the run must die, not shrink
    fault.install("elastic.step", "ioerror", at=6)
    with pytest.raises(IOError):
        _run(params, loss_fn, batch_fn, d, 8, 10, **kw)
    fault.clear()
    assert ckpt.latest_step(d) == 3    # last committed before the crash
    res = _run(params, loss_fn, batch_fn, d, 8, 10, **kw)
    assert res.resumed_from == 3
    assert res.resumed_dp == 8
    _assert_state_parity(ref, res)


def test_crash_resume_smaller_mesh_bit_exact(tmp_path):
    _need8()
    params, loss_fn, batch_fn = _lattice_problem()
    ref = _run(params, loss_fn, batch_fn, str(tmp_path / "ref"), 8, 10)
    d = str(tmp_path / "crash")
    fault.install("elastic.step", "ioerror", at=6)
    with pytest.raises(IOError):
        _run(params, loss_fn, batch_fn, d, 8, 10)
    fault.clear()
    base_resumes = telemetry.snapshot().get("elastic.resumes", 0)
    res = _run(params, loss_fn, batch_fn, d, 4, 10)   # ELASTIC restart
    assert res.resumed_from == 3
    assert res.resumed_dp == 4
    assert res.trainer.dp == 4
    _assert_state_parity(ref, res)
    snap = telemetry.snapshot()
    assert snap["elastic.resumes"] == base_resumes + 1
    assert snap["elastic.resume_latency_us"] > 0
    assert snap["elastic.dp"] == 4


def test_elastic_resume_fault_point_retries(tmp_path):
    _need8()
    params, loss_fn, batch_fn = _lattice_problem()
    d = str(tmp_path / "ck")
    _run(params, loss_fn, batch_fn, d, 8, 6)
    fault.install("elastic.resume", "ioerror", at=1)   # transient
    res = _run(params, loss_fn, batch_fn, d, 8, 6)
    assert res.resumed_from == 6


def test_graceful_shrink_on_worker_loss_preserves_parity(tmp_path):
    _need8()
    params, loss_fn, batch_fn = _lattice_problem()
    ref = _run(params, loss_fn, batch_fn, str(tmp_path / "ref"), 8, 10)
    base = telemetry.snapshot().get("elastic.mesh_shrinks", 0)
    # InjectedFault mid-run = simulated unrecoverable worker loss: the
    # run must shrink the mesh and finish, not die
    fault.install("kvstore.allgather", "error", at=9)
    res = _run(params, loss_fn, batch_fn, str(tmp_path / "shrink"), 8, 10)
    fault.clear()
    assert res.shrinks == 1
    assert res.dp_history == [8, 4]
    assert res.trainer.dp == 4
    _assert_state_parity(ref, res)
    assert telemetry.snapshot()["elastic.mesh_shrinks"] == base + 1


def test_recurring_worker_loss_keeps_degrading_to_min_dp(tmp_path):
    """A worker that STAYS dead fails the shrunk trainer's own first
    allgather too: the recovery must keep shrinking toward min_dp and
    only then re-raise — not die on the first failed shrink."""
    _need8()
    params, loss_fn, batch_fn = _lattice_problem()
    fault.install("kvstore.allgather", "error", at=9, persistent=True)
    with pytest.raises(fault.InjectedFault):
        _run(params, loss_fn, batch_fn, str(tmp_path / "d"), 8, 10,
             min_dp=2)
    fault.clear()
    # every allowed size was attempted before giving up: 8 -> 4 -> 2
    # (the log records the attempts; dp 1 < min_dp stops the loop)


def test_worker_loss_below_min_dp_reraises(tmp_path):
    _need8()
    params, loss_fn, batch_fn = _lattice_problem()
    fault.install("kvstore.allgather", "error", at=3)
    with pytest.raises(fault.InjectedFault):
        _run(params, loss_fn, batch_fn, str(tmp_path / "d"), 8, 6,
             min_dp=8)


def test_skip_nonfinite_is_crash_consistent(tmp_path, caplog):
    _need8()
    import logging
    params, loss_fn, batch_fn = _lattice_problem()
    d = str(tmp_path / "skip")
    # poison the loss at step 2 (nan), then crash at step hit 5
    fault.install("elastic.loss", "nan", at=2)
    fault.install("elastic.step", "ioerror", at=5)
    with pytest.raises(IOError):
        _run(params, loss_fn, batch_fn, d, 8, 10)
    fault.clear()
    entry = ckpt.latest_entry(d)
    assert entry["extra"]["elastic_run"]["skipped_nonfinite"] == 1
    with caplog.at_level(logging.INFO, logger="mxnet.fault"):
        res = _run(params, loss_fn, batch_fn, d, 8, 10)
    # the resumed run CONTINUES the count instead of resetting it ...
    assert res.skipped_nonfinite == 1
    # ... and the event log shows the restored accounting
    assert any("elastic.resumed" in r.getMessage()
               for r in caplog.records)
    # the skipped step never advanced the state: one fewer update than
    # steps (momentum=1.0 makes each update's delta distinct)
    ref_skip = _run(params, loss_fn, batch_fn, str(tmp_path / "r2"), 8, 10)
    # reference run had no skip: trajectories must DIFFER
    assert not np.array_equal(ref_skip.params()["w"], res.params()["w"])


# ---------------------------------------------------------------------------
# telemetry surface
# ---------------------------------------------------------------------------
def test_elastic_metric_names_registered_and_live():
    _need8()
    params, loss_fn, batch_fn = _mlp_problem()
    tr = elastic.ElasticTrainer(loss_fn, params, optimizer="sgd", dp=4,
                                momentum=0.9)
    base_steps = telemetry.snapshot().get("elastic.steps", 0)
    tr.step(batch_fn(0))
    snap = telemetry.snapshot()
    for name in ("elastic.steps", "elastic.resumes",
                 "elastic.mesh_shrinks", "elastic.skipped_nonfinite",
                 "elastic.collective_retries",
                 "elastic.resume_latency_us",
                 "elastic.mem_per_replica_bytes", "elastic.dp"):
        assert name in snap, name
    assert snap["elastic.steps"] == base_steps + 1
    assert snap["elastic.dp"] == 4
    # span lanes: kv.reduce_scatter / kv.allgather / elastic.step all
    # recorded through the span histogram
    assert snap.get('span.count{name="kv.reduce_scatter"}', 0) > 0
    assert snap.get('span.count{name="kv.allgather"}', 0) > 0
    assert snap.get('span.count{name="elastic.step"}', 0) > 0


def test_step_timeline_gains_zero_collective_lanes():
    _need8()
    params, loss_fn, batch_fn = _mlp_problem()
    tr = elastic.ElasticTrainer(loss_fn, params, optimizer="sgd", dp=4,
                                momentum=0.9)
    tl = telemetry.StepTimeline(name="elastic.tl")
    for s in range(2):
        with tl.step():
            tr.step(batch_fn(s))
    rep = tl.report()
    assert rep["reduce_scatter_us"] > 0
    assert rep["allgather_us"] > 0
    assert rep["reduce_scatter_buckets"] > 0
    assert rep["allgather_buckets"] > 0
    # compute is the remainder AFTER the new lanes
    assert rep["compute_us"] <= rep["total_us"] - rep["reduce_scatter_us"] \
        - rep["allgather_us"] + 1.0


# ---------------------------------------------------------------------------
# kvstore barrier timeout (unit wiring; the 2-process end-to-end run is
# tests/test_multiprocess_dist.py::test_two_process_barrier_timeout_...)
# ---------------------------------------------------------------------------
def test_barrier_timeout_typed_error_names_missing_ranks(monkeypatch):
    store = kv.create("dist_sync")
    monkeypatch.setattr(store, "_dist_active", lambda: True)
    monkeypatch.setattr(store, "_barrier_announce", lambda seq: None)
    monkeypatch.setattr(store, "_barrier_sync",
                        lambda seq: time.sleep(30))
    monkeypatch.setattr(store, "_barrier_missing_ranks", lambda seq: [2])
    monkeypatch.setenv("MXNET_KVSTORE_BARRIER_TIMEOUT", "0.3")
    t0 = time.time()
    with pytest.raises(kv.BarrierTimeout) as ei:
        store.barrier()
    assert time.time() - t0 < 5.0
    assert ei.value.missing_ranks == [2]
    assert "rank(s) 2 never arrived" in str(ei.value)


def test_barrier_legacy_timeout_alias_still_works(monkeypatch):
    store = kv.create("dist_sync")
    monkeypatch.setattr(store, "_dist_active", lambda: True)
    monkeypatch.setattr(store, "_barrier_announce", lambda seq: None)
    monkeypatch.setattr(store, "_barrier_sync",
                        lambda seq: time.sleep(30))
    monkeypatch.setattr(store, "_barrier_missing_ranks", lambda seq: [])
    monkeypatch.delenv("MXNET_KVSTORE_BARRIER_TIMEOUT", raising=False)
    monkeypatch.setenv("MXNET_KV_BARRIER_TIMEOUT", "0.3")
    with pytest.raises(kv.BarrierTimeout, match="unknown"):
        store.barrier()


def test_barrier_without_timeout_or_dist_is_noop():
    store = kv.create("local")
    store.barrier()    # single process: local waitall only, no timeout


# ---------------------------------------------------------------------------
# bench phase + crashtest harness
# ---------------------------------------------------------------------------
def test_bench_elastic_quick_phase():
    """Tier-1 smoke (the ISSUE-12 satellite): the elastic phase rides the
    hermetic bench runner and emits the gated trend scalars."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--phase", "elastic", "--quick"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["ok"] is True, out
    res = out["result"]
    assert res["elastic_mem_per_replica_mb"] > 0
    assert 0.0 <= res["elastic_overlap_fraction"] <= 1.0
    assert res["elastic_resume_latency_ms"] > 0
    assert res["elastic_rescale_resume_latency_ms"] > 0
    # ZeRO promise, measured: per-replica state memory linear in dp
    assert res["elastic_mem_linearity"] == pytest.approx(1.0, abs=0.1)


def test_committed_elastic_artifact_meets_acceptance():
    """The committed 8-way CPU-mesh round: linear memory scaling and an
    overlap fraction no worse than the overlap_r07 baseline."""
    path = os.path.join(REPO, "benchmark", "results",
                        "elastic_r12_cpu8.json")
    with open(path) as f:
        art = json.load(f)
    assert art["backend_ok"] is True
    assert art["meta"]["devices"] == 8
    per = art["mem"]["per_replica_bytes"]
    # ~linear drop 1 -> 8 (exact here: shapes divide evenly)
    assert per["1"] / per["8"] == pytest.approx(8.0, rel=0.1)
    assert art["elastic_mem_linearity"] == pytest.approx(1.0, abs=0.1)
    with open(os.path.join(REPO, "benchmark", "results",
                           "overlap_r07_cpu8.json")) as f:
        baseline = json.load(f)["overlap"]["hidden_comm_fraction"]
    assert art["elastic_overlap_fraction"] >= baseline - 1e-9


@pytest.mark.slow
def test_crashtest_elastic_sigkill_parity_same_mesh(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "crashtest.py"),
         "--elastic", "--steps", "12", "--ckpt-every", "3",
         "--kill-at", "8", "--dir", str(tmp_path)],
        capture_output=True, text=True, timeout=570,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "elastic parity OK" in proc.stdout


@pytest.mark.slow
def test_crashtest_elastic_sigkill_parity_smaller_mesh(tmp_path):
    """The full ISSUE-12 acceptance: real SIGKILL mid-epoch, restart onto
    HALF the dp mesh, params + optimizer-state shards bit-exact."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "crashtest.py"),
         "--elastic", "--steps", "12", "--ckpt-every", "3",
         "--kill-at", "8", "--resume-dp", "4", "--dir", str(tmp_path)],
        capture_output=True, text=True, timeout=570,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "elastic parity OK" in proc.stdout
    assert "dp 8 -> 4" in proc.stdout
