"""Contrib detection ops (≙ reference tests for bounding_box.cc / roi_align)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import npx


def test_box_iou_known_values():
    a = mx.np.array(np.array([[0, 0, 2, 2]], np.float32))
    b = mx.np.array(np.array([[1, 1, 3, 3], [0, 0, 2, 2],
                              [10, 10, 11, 11]], np.float32))
    iou = npx.box_iou(a, b).asnumpy()
    np.testing.assert_allclose(iou[0], [1 / 7, 1.0, 0.0], rtol=1e-5)


def test_box_iou_center_format():
    # both in center format: identical center boxes → IoU 1
    a = mx.np.array(np.array([[1, 1, 2, 2]], np.float32))
    b = mx.np.array(np.array([[1, 1, 2, 2], [2, 1, 2, 2]], np.float32))
    iou = npx.box_iou(a, b, format="center").asnumpy()
    np.testing.assert_allclose(iou[0], [1.0, 1 / 3], rtol=1e-5)


def test_box_nms_suppression():
    # [cls, score, x1, y1, x2, y2]
    boxes = np.array([
        [0, 0.9, 0, 0, 10, 10],
        [0, 0.8, 1, 1, 10.5, 10.5],   # high overlap with first → suppressed
        [0, 0.7, 20, 20, 30, 30],     # far away → kept
        [1, 0.6, 0.5, 0.5, 10, 10],   # different class → kept (id-aware)
    ], np.float32)
    out = npx.box_nms(mx.np.array(boxes), overlap_thresh=0.5,
                      id_index=0).asnumpy()
    scores = out[:, 1]
    assert scores[0] == pytest.approx(0.9)
    assert scores[1] == -1.0
    assert sorted(scores[scores > 0].tolist()) == \
        pytest.approx([0.6, 0.7, 0.9])


def test_box_nms_force_suppress():
    boxes = np.array([
        [0, 0.9, 0, 0, 10, 10],
        [1, 0.8, 0.5, 0.5, 10, 10],
    ], np.float32)
    out = npx.box_nms(mx.np.array(boxes), overlap_thresh=0.5, id_index=0,
                      force_suppress=True).asnumpy()
    assert out[1, 1] == -1.0


def test_box_nms_batched():
    boxes = np.tile(np.array([[[0, 0.9, 0, 0, 10, 10],
                               [0, 0.8, 1, 1, 10, 10]]], np.float32),
                    (3, 1, 1))
    out = npx.box_nms(mx.np.array(boxes), overlap_thresh=0.5).asnumpy()
    assert out.shape == (3, 2, 6)
    assert (out[:, 1, 1] == -1.0).all()


def test_roi_align_matches_manual_bilinear():
    """ROI over the whole image with 1x1 bins: each output samples the
    bilinear value at (i+0.5, j+0.5), clamped at borders."""
    data = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    rois = np.array([[0, 0, 0, 4, 4]], np.float32)
    out = npx.roi_align(mx.np.array(data), mx.np.array(rois),
                        pooled_size=4, spatial_scale=1.0,
                        sample_ratio=1).asnumpy()
    img = data[0, 0]

    def bil(y, x):
        y = min(max(y, 0.0), 3.0)
        x = min(max(x, 0.0), 3.0)
        y0, x0 = int(np.floor(y)), int(np.floor(x))
        y1, x1 = min(y0 + 1, 3), min(x0 + 1, 3)
        wy, wx = y - y0, x - x0
        return ((img[y0, x0] * (1 - wx) + img[y0, x1] * wx) * (1 - wy)
                + (img[y1, x0] * (1 - wx) + img[y1, x1] * wx) * wy)

    expected = np.array([[bil(i + 0.5, j + 0.5) for j in range(4)]
                         for i in range(4)], np.float32)
    np.testing.assert_allclose(out[0, 0], expected, rtol=1e-5)


def test_roi_align_scale_and_grad():
    import jax
    from incubator_mxnet_tpu.ops import contrib as c
    data = np.random.randn(2, 3, 8, 8).astype(np.float32)
    rois = np.array([[0, 0, 0, 16, 16], [1, 4, 4, 12, 12]], np.float32)
    out = c.roi_align(data, rois, pooled_size=2, spatial_scale=0.5)
    assert out.shape == (2, 3, 2, 2)
    g = jax.grad(lambda d: c.roi_align(d, rois, 2, 0.5).sum())(data)
    assert np.isfinite(np.asarray(g)).all()
    assert np.abs(np.asarray(g)).sum() > 0


def test_bilinear_resize2d():
    x = mx.np.array(np.random.randn(1, 3, 4, 4).astype(np.float32))
    y = npx.bilinear_resize2d(x, 8, 8)
    assert y.shape == (1, 3, 8, 8)
    # corners preserved under linear resize up
    np.testing.assert_allclose(y.asnumpy()[..., 0, 0], x.asnumpy()[..., 0, 0],
                               rtol=1e-4)


def test_sequence_last_and_reverse():
    x = np.arange(24, dtype=np.float32).reshape(4, 3, 2)  # (T, N, C)
    lens = mx.np.array(np.array([2, 4, 1], np.float32))
    last = npx.sequence_last(mx.np.array(x), lens, use_sequence_length=True)
    expect = np.stack([x[1, 0], x[3, 1], x[0, 2]])
    np.testing.assert_array_equal(last.asnumpy(), expect)

    rev = npx.sequence_reverse(mx.np.array(x), lens, use_sequence_length=True)
    r = rev.asnumpy()
    np.testing.assert_array_equal(r[0, 0], x[1, 0])   # within len: reversed
    np.testing.assert_array_equal(r[2, 0], x[2, 0])   # beyond len: untouched
    np.testing.assert_array_equal(r[:, 1], x[::-1, 1])  # full reverse

    plain = npx.sequence_reverse(mx.np.array(x))
    np.testing.assert_array_equal(plain.asnumpy(), x[::-1])


def test_library_extension(tmp_path):
    ext = tmp_path / "my_ext.py"
    ext.write_text('''
def register_ops(mx):
    import incubator_mxnet_tpu.operator as op_mod

    class Twice(op_mod.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], in_data[0] * 2)
        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0], out_grad[0] * 2)

    @op_mod.register("twice_ext")
    class TwiceProp(op_mod.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Twice()
''')
    from incubator_mxnet_tpu import library, operator as op_mod
    library.load(str(ext), verbose=False)
    out = op_mod.invoke("twice_ext", mx.np.array(np.array([3.0], np.float32)))
    np.testing.assert_allclose(out.asnumpy(), [6.0])
