"""Broad mx.np coverage vs host NumPy (≙ tests/python/unittest/
test_numpy_op.py ~10k LoC of per-op numeric checks — here a parametrized
sweep over the generated wrapper surface plus targeted semantics checks)."""
import numpy as onp
import pytest

import incubator_mxnet_tpu as mx

mxnp = mx.np


def _ref(name):
    return getattr(onp, name)


_UNARY = ["negative", "absolute", "sign", "rint", "square", "sqrt", "exp",
          "expm1", "log", "log2", "log10", "log1p", "sin", "cos", "tan",
          "arcsin", "arctan", "sinh", "cosh", "tanh", "arcsinh",
          "arctanh", "ceil", "floor", "trunc", "reciprocal", "cbrt",
          "deg2rad", "rad2deg"]

_BINARY = ["add", "subtract", "multiply", "true_divide", "power", "maximum",
           "minimum", "hypot", "arctan2", "logaddexp", "copysign",
           "fmod", "floor_divide"]

_REDUCE = ["sum", "prod", "mean", "std", "var", "min", "max", "argmin",
           "argmax", "cumsum", "cumprod"]

_LOGIC = ["equal", "not_equal", "less", "less_equal", "greater",
          "greater_equal", "logical_and", "logical_or", "logical_xor"]


@pytest.mark.parametrize("name", _UNARY)
def test_unary_matches_numpy(name):
    x = onp.random.uniform(0.1, 0.9, (3, 4)).astype(onp.float32)
    got = getattr(mxnp, name)(mxnp.array(x)).asnumpy()
    want = _ref(name)(x.astype(onp.float64)).astype(onp.float32)
    onp.testing.assert_allclose(got, want, rtol=2e-5, atol=2e-6)


@pytest.mark.parametrize("name", _BINARY)
def test_binary_matches_numpy(name):
    a = onp.random.uniform(0.1, 2.0, (3, 4)).astype(onp.float32)
    b = onp.random.uniform(0.1, 2.0, (4,)).astype(onp.float32)  # broadcast
    got = getattr(mxnp, name)(mxnp.array(a), mxnp.array(b)).asnumpy()
    want = _ref(name)(a.astype(onp.float64), b.astype(onp.float64))
    onp.testing.assert_allclose(got, want.astype(onp.float32), rtol=2e-5,
                                atol=2e-6)


@pytest.mark.parametrize("name", _REDUCE)
def test_reduce_matches_numpy(name):
    x = onp.random.uniform(-1, 1, (3, 5)).astype(onp.float32)
    got = getattr(mxnp, name)(mxnp.array(x), axis=1).asnumpy()
    want = _ref(name)(x.astype(onp.float64), axis=1)
    onp.testing.assert_allclose(got, onp.asarray(want, got.dtype), rtol=2e-5,
                                atol=1e-5)


@pytest.mark.parametrize("name", _LOGIC)
def test_logic_matches_numpy(name):
    a = onp.random.randint(0, 3, (4, 4)).astype(onp.float32)
    b = onp.random.randint(0, 3, (4, 4)).astype(onp.float32)
    got = getattr(mxnp, name)(mxnp.array(a), mxnp.array(b)).asnumpy()
    want = _ref(name)(a, b)
    onp.testing.assert_array_equal(got, want)


def test_manipulation_family():
    x = onp.arange(24, dtype=onp.float32).reshape(2, 3, 4)
    nd = mxnp.array(x)
    onp.testing.assert_array_equal(mxnp.transpose(nd, (2, 0, 1)).asnumpy(),
                                   x.transpose(2, 0, 1))
    onp.testing.assert_array_equal(mxnp.moveaxis(nd, 0, -1).asnumpy(),
                                   onp.moveaxis(x, 0, -1))
    onp.testing.assert_array_equal(
        mxnp.concatenate([nd, nd], axis=1).asnumpy(),
        onp.concatenate([x, x], axis=1))
    onp.testing.assert_array_equal(mxnp.stack([nd, nd]).asnumpy(),
                                   onp.stack([x, x]))
    onp.testing.assert_array_equal(mxnp.flip(nd, axis=2).asnumpy(),
                                   onp.flip(x, axis=2))
    onp.testing.assert_array_equal(mxnp.roll(nd, 2, axis=2).asnumpy(),
                                   onp.roll(x, 2, axis=2))
    onp.testing.assert_array_equal(mxnp.tile(nd, (1, 2, 1)).asnumpy(),
                                   onp.tile(x, (1, 2, 1)))
    parts = mxnp.split(nd, 2, axis=2)
    onp.testing.assert_array_equal(parts[0].asnumpy(),
                                   onp.split(x, 2, axis=2)[0])
    onp.testing.assert_array_equal(mxnp.pad(nd, ((0, 0), (1, 1), (0, 0))).asnumpy(),
                                   onp.pad(x, ((0, 0), (1, 1), (0, 0))))


def test_linalg_family():
    a = onp.random.randn(3, 4).astype(onp.float32)
    b = onp.random.randn(4, 5).astype(onp.float32)
    onp.testing.assert_allclose(
        mxnp.matmul(mxnp.array(a), mxnp.array(b)).asnumpy(), a @ b,
        rtol=2e-5, atol=1e-5)
    onp.testing.assert_allclose(
        mxnp.einsum("ij,jk->ik", mxnp.array(a), mxnp.array(b)).asnumpy(),
        onp.einsum("ij,jk->ik", a, b), rtol=2e-5, atol=1e-5)
    onp.testing.assert_allclose(
        mxnp.tensordot(mxnp.array(a), mxnp.array(b), axes=1).asnumpy(),
        onp.tensordot(a, b, axes=1), rtol=2e-5, atol=1e-5)
    sq = onp.random.randn(4, 4).astype(onp.float32) + 4 * onp.eye(4, dtype=onp.float32)
    onp.testing.assert_allclose(mxnp.trace(mxnp.array(sq)).asnumpy(),
                                onp.trace(sq), rtol=1e-6)


def test_np_linalg_submodule():
    from incubator_mxnet_tpu.numpy import linalg
    sq = onp.random.randn(4, 4).astype(onp.float32)
    spd = sq @ sq.T + 4 * onp.eye(4, dtype=onp.float32)
    L = linalg.cholesky(mxnp.array(spd)).asnumpy()
    onp.testing.assert_allclose(L @ L.T, spd, rtol=1e-4, atol=1e-4)
    inv = linalg.inv(mxnp.array(spd)).asnumpy()
    onp.testing.assert_allclose(inv @ spd, onp.eye(4), rtol=1e-3, atol=1e-3)
    n = linalg.norm(mxnp.array(sq)).asnumpy()
    onp.testing.assert_allclose(n, onp.linalg.norm(sq), rtol=1e-5)
    w = linalg.svd(mxnp.array(sq))
    assert len(w) == 3


def test_indexing_family():
    x = onp.arange(20, dtype=onp.float32).reshape(4, 5)
    nd = mxnp.array(x)
    idx = mxnp.array(onp.array([0, 2]))
    onp.testing.assert_array_equal(mxnp.take(nd, idx, axis=0).asnumpy(),
                                   onp.take(x, [0, 2], axis=0))
    onp.testing.assert_array_equal(
        mxnp.where(nd > 10, nd, mxnp.zeros(())).asnumpy(),
        onp.where(x > 10, x, 0))
    onp.testing.assert_array_equal(mxnp.argsort(nd, axis=1).asnumpy(),
                                   onp.argsort(x, axis=1))
    onp.testing.assert_array_equal(mxnp.sort(-nd, axis=1).asnumpy(),
                                   onp.sort(-x, axis=1))
    u = mxnp.unique(mxnp.array(onp.array([3, 1, 3, 2])))
    onp.testing.assert_array_equal(u.asnumpy(), [1, 2, 3])


def test_ndarray_advanced_indexing():
    x = mxnp.array(onp.arange(12, dtype=onp.float32).reshape(3, 4))
    # boolean mask
    m = x > 5
    assert (x.asnumpy()[x.asnumpy() > 5] == x[m].asnumpy()).all()
    # integer array indexing
    got = x[mxnp.array(onp.array([0, 2]))].asnumpy()
    onp.testing.assert_array_equal(got, x.asnumpy()[[0, 2]])
    # setitem with slice
    x[1:3, 0] = -1
    assert (x.asnumpy()[1:3, 0] == -1).all()


def test_view_write_through():
    """Basic-index views write through to the base (≙ reference zero-copy
    Slice views, ndarray.h)."""
    x = mxnp.zeros((4, 4))
    v = x[1]
    v[:] = 7
    assert (x.asnumpy()[1] == 7).all()
    x[2] = 3  # base write visible through fresh views
    assert (x[2].asnumpy() == 3).all()


def test_random_family():
    mx.seed(0)
    r = mxnp.random
    s = r.normal(0, 1, size=(10000,))
    assert abs(float(s.asnumpy().mean())) < 0.05
    u = r.uniform(2, 3, size=(1000,)).asnumpy()
    assert u.min() >= 2 and u.max() <= 3
    ri = r.randint(0, 10, size=(1000,)).asnumpy()
    assert ri.min() >= 0 and ri.max() < 10
    c = r.choice(5, size=(100,)).asnumpy()
    assert set(c.astype(int)) <= set(range(5))
    sh = mxnp.array(onp.arange(10, dtype=onp.float32))
    p = r.permutation(sh).asnumpy()
    assert sorted(p.tolist()) == list(range(10))


def test_custom_operator():
    """mx.operator.CustomOp protocol (≙ python/mxnet/operator.py)."""
    from incubator_mxnet_tpu import operator as op_mod

    class Square(op_mod.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            self.assign(out_data[0], req[0], in_data[0] * in_data[0])

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0], 2 * in_data[0] * out_grad[0])

    @op_mod.register("square_custom")
    class SquareProp(op_mod.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Square()

    x = mxnp.array(onp.array([1.0, 2.0, 3.0], onp.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = op_mod.invoke("square_custom", x)
    y.backward()
    onp.testing.assert_allclose(y.asnumpy(), [1, 4, 9], rtol=1e-6)
    onp.testing.assert_allclose(x.grad.asnumpy(), [2, 4, 6], rtol=1e-6)
