"""Round-3 op-tail coverage: grouped transposed conv, top-k / expert-choice
MoE routing, and the la_op family (reference src/operator/tensor/la_op.cc).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import parallel
from incubator_mxnet_tpu.numpy import linalg as L


# ---------------------------------------------------------------------------
# grouped transposed convolution (ops/nn.py conv_transpose)
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("layout", ["NCHW", "NHWC"])
def test_grouped_deconv_matches_per_group(layout):
    from incubator_mxnet_tpu.ops import nn as onn
    import jax.numpy as jnp
    rng = np.random.RandomState(0)
    g, cin, cout = 2, 4, 6
    if layout == "NCHW":
        x = rng.randn(2, cin, 8, 8).astype(np.float32)
        w = rng.randn(cin, cout // g, 3, 3).astype(np.float32)
        ch = 1
    else:
        x = rng.randn(2, 8, 8, cin).astype(np.float32)
        w = rng.randn(3, 3, cout // g, cin).astype(np.float32)
        ch = 3
    y = np.asarray(onn.conv_transpose(jnp.asarray(x), jnp.asarray(w),
                                      stride=2, padding=1, groups=g,
                                      layout=layout))
    # reference semantics: per-group single deconv over channel slices
    xs = np.split(x, g, axis=ch)
    ws = np.split(w, g, axis=0 if layout == "NCHW" else 3)
    refs = [np.asarray(onn.conv_transpose(jnp.asarray(xg), jnp.asarray(wg),
                                          stride=2, padding=1, groups=1,
                                          layout=layout))
            for xg, wg in zip(xs, ws)]
    np.testing.assert_allclose(y, np.concatenate(refs, axis=ch),
                               rtol=1e-5, atol=1e-5)


def test_grouped_deconv_gluon_layer():
    from incubator_mxnet_tpu.gluon import nn
    net = nn.Conv2DTranspose(8, 4, strides=2, padding=1, groups=2,
                             in_channels=4)
    net.initialize()
    x = mx.np.array(np.random.RandomState(1).randn(2, 4, 8, 8)
                    .astype(np.float32))
    y = net(x)
    assert y.shape == (2, 8, 16, 16)


# ---------------------------------------------------------------------------
# MoE routing variants (8-device mesh via conftest)
# ---------------------------------------------------------------------------
def _run_moe(router, top_k=1, capacity=None):
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from incubator_mxnet_tpu.parallel.moe import (moe_dispatch,
                                                  moe_dispatch_expert_choice)
    E, T, D = 4, 8, 4
    rng = np.random.RandomState(2)
    x = rng.randn(E * T, D).astype(np.float32)
    logits = rng.randn(E * T, E).astype(np.float32)

    def expert_fn_of(rank_mul):
        def f(tokens):
            return tokens * rank_mul
        return f

    m = parallel.Mesh({"ep": 4})

    def inner(xl, ll):
        rank = jax.lax.axis_index("ep")
        mul = (rank + 1).astype(jnp.float32)
        if router == "expert_choice":
            y, aux = moe_dispatch_expert_choice(
                xl, ll, lambda t: t * mul, axis_name="ep",
                capacity=capacity)
        else:
            y, aux = moe_dispatch(xl, ll, lambda t: t * mul,
                                  axis_name="ep", capacity=capacity,
                                  top_k=top_k)
        return y, aux

    f = parallel.shard_map(inner, m,
                           in_specs=(P("ep", None), P("ep", None)),
                           out_specs=(P("ep", None), P()),
                           check_rep=False)
    with m:
        y, aux = f(x, logits)
    return x, logits, np.asarray(y), float(np.asarray(aux).reshape(-1)[0])


def test_moe_top2_matches_dense_routing():
    """top-2 with ample capacity == dense computation: sum of the two best
    experts' outputs weighted by renormalized gates."""
    x, logits, y, aux = _run_moe("top_k", top_k=2, capacity=64)
    E = 4
    p = np.exp(logits - logits.max(1, keepdims=True))
    p = p / p.sum(1, keepdims=True)
    top2 = np.argsort(-p, axis=1)[:, :2]
    ref = np.zeros_like(x)
    for t in range(x.shape[0]):
        g = p[t, top2[t]]
        g = g / g.sum()
        for j, e in enumerate(top2[t]):
            ref[t] += g[j] * x[t] * (e + 1)   # expert e multiplies by e+1
    np.testing.assert_allclose(y, ref, rtol=1e-4, atol=1e-5)
    assert aux > 0


def test_moe_top2_capacity_overflow_passthrough():
    """Under a tiny capacity, tokens whose every choice overflowed pass
    through unchanged; kept choices still contribute."""
    x, logits, y, aux = _run_moe("top_k", top_k=2, capacity=1)
    # every row is either a gated combination (scaled) or exact passthrough;
    # at least one of each must occur at capacity=1
    same = np.isclose(y, x, atol=1e-6).all(axis=1)
    assert same.any() and (~same).any()


def test_moe_expert_choice_balanced():
    """Expert-choice: every expert processes exactly C tokens (perfect
    balance) and unchosen tokens pass through."""
    x, logits, y, aux = _run_moe("expert_choice", capacity=2)
    assert aux == 0.0
    same = np.isclose(y, x, atol=1e-6).all(axis=1)
    # each of the 4 ranks picks top-C local tokens for each of 4 experts:
    # at most R * E * C = 32 tokens transformed in total
    assert (~same).sum() <= 4 * 4 * 2


# ---------------------------------------------------------------------------
# la_op family (≙ src/operator/tensor/la_op.cc)
# ---------------------------------------------------------------------------
def test_la_syrk_trmm_trsm():
    rng = np.random.RandomState(3)
    A = mx.np.array(rng.randn(4, 4).astype(np.float32))
    B = mx.np.array(rng.randn(4, 3).astype(np.float32))
    a, b = A.asnumpy(), B.asnumpy()
    np.testing.assert_allclose(L.syrk(A, alpha=2.0).asnumpy(),
                               2.0 * a @ a.T, rtol=1e-5)
    np.testing.assert_allclose(L.syrk(A, transpose=True).asnumpy(),
                               a.T @ a, rtol=1e-5)
    np.testing.assert_allclose(L.trmm(A, B).asnumpy(),
                               np.tril(a) @ b, rtol=1e-5)
    X = L.trsm(A, B).asnumpy()
    np.testing.assert_allclose(np.tril(a) @ X, b, rtol=1e-3, atol=1e-4)


def test_la_potrf_potri_gelqf_syevd_gemm2():
    rng = np.random.RandomState(4)
    M = rng.randn(5, 5).astype(np.float32)
    S = M @ M.T + 5 * np.eye(5, dtype=np.float32)
    A = mx.np.array(S)
    Lc = L.potrf(A)
    np.testing.assert_allclose(Lc.asnumpy() @ Lc.asnumpy().T, S,
                               rtol=1e-3, atol=1e-3)
    np.testing.assert_allclose(L.potri(Lc).asnumpy(), np.linalg.inv(S),
                               rtol=1e-2, atol=1e-3)

    R = mx.np.array(rng.randn(3, 5).astype(np.float32))
    lo, q = L.gelqf(R)
    np.testing.assert_allclose(lo.asnumpy() @ q.asnumpy(), R.asnumpy(),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(q.asnumpy() @ q.asnumpy().T, np.eye(3),
                               rtol=1e-4, atol=1e-4)

    U, lam = L.syevd(A)
    u, la_ = U.asnumpy(), lam.asnumpy()
    np.testing.assert_allclose(u.T @ np.diag(la_) @ u, S,
                               rtol=1e-3, atol=1e-3)

    X = mx.np.array(rng.randn(2, 4).astype(np.float32))
    Y = mx.np.array(rng.randn(3, 4).astype(np.float32))
    np.testing.assert_allclose(
        L.gemm2(X, Y, transpose_b=True, alpha=0.5).asnumpy(),
        0.5 * X.asnumpy() @ Y.asnumpy().T, rtol=1e-5)


def test_la_ops_differentiable():
    """la_ops ride the tape like every other invoke-dispatched op."""
    A = mx.np.array(np.eye(3, dtype=np.float32) * 2.0)
    A.attach_grad()
    with mx.autograd.record():
        y = L.syrk(A).sum()
    y.backward()
    assert A.grad is not None and float(np.abs(A.grad.asnumpy()).sum()) > 0
