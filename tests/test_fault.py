"""mx.fault: fault injection, crash-consistent checkpoint commits, retry /
watchdog, and the auto-resume training driver (ISSUE 1 acceptance: an
injected IOError or SIGKILL at any point during a save never loses the
previous committed checkpoint, and a restarted run_resilient reproduces the
uninterrupted run's final parameters — same mesh and halved mesh)."""
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import checkpoint as ckpt
from incubator_mxnet_tpu import fault

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(autouse=True)
def _clean_faults():
    fault.clear()
    yield
    fault.clear()


# ---------------------------------------------------------------------------
# spec / registry
# ---------------------------------------------------------------------------
def test_spec_parsing():
    rules = fault.parse_spec(
        "checkpoint.save:2:ioerror, a.b:3+:stall:0.5 ,x:*:nan")
    assert [(r.point, r.at, r.persistent, r.kind) for r in rules] == [
        ("checkpoint.save", 2, False, "ioerror"),
        ("a.b", 3, True, "stall"),
        ("x", 1, True, "nan")]
    assert rules[1].arg == "0.5"
    with pytest.raises(mx.MXNetError):
        fault.parse_spec("missing.kind:1")
    with pytest.raises(mx.MXNetError):
        fault.parse_spec("p:1:frobnicate")


def test_inject_nth_hit_only():
    fault.install("demo.point", "ioerror", at=2)
    fault.inject("demo.point")  # hit 1: no fire
    with pytest.raises(IOError):
        fault.inject("demo.point")  # hit 2
    fault.inject("demo.point")  # hit 3: non-persistent rule is done
    assert fault.hits("demo.point") == 3


def test_scope_restores_rules():
    with fault.scope("p:1:error"):
        assert len(fault.active_rules()) == 1
        with pytest.raises(fault.InjectedFault):
            fault.inject("p")
    assert fault.active_rules() == []
    fault.inject("p")  # disarmed


# ---------------------------------------------------------------------------
# crash-consistent checkpoints
# ---------------------------------------------------------------------------
def test_atomic_save_checkpoint_preserves_previous(tmp_path):
    p = ckpt.save_checkpoint(str(tmp_path / "c"), {"w": np.arange(4.)},
                             step=5)
    with fault.scope("checkpoint.save:1:ioerror"):
        with pytest.raises(IOError):
            ckpt.save_checkpoint(p, {"w": np.zeros(4)}, step=9)
    params, step = ckpt.load_checkpoint(p)
    assert step == 5
    np.testing.assert_array_equal(params["w"].asnumpy(), np.arange(4.))


def test_load_checkpoint_missing_raises_clear_error(tmp_path):
    missing = str(tmp_path / "nope")
    with pytest.raises(mx.MXNetError, match="nope.npz"):
        ckpt.load_checkpoint(missing)
    # the raw path must be listed too
    with pytest.raises(mx.MXNetError, match="tried"):
        ckpt.load_checkpoint(missing)


def test_ioerror_mid_save_sharded_preserves_latest_step(tmp_path):
    import jax.numpy as jnp
    d = str(tmp_path / "sh")
    ckpt.save_sharded(d, {"w": jnp.arange(8.)}, step=1)
    assert ckpt.latest_step(d) == 1
    with fault.scope("checkpoint.save_sharded:1:ioerror"):
        with pytest.raises(IOError):
            ckpt.save_sharded(d, {"w": jnp.zeros(8)}, step=2)
    # the crashed save is invisible: manifest still points at step 1 ...
    assert ckpt.latest_step(d) == 2 - 1
    tree, step = ckpt.load_sharded(d)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(tree["w"]), np.arange(8.))
    # ... and the next save garbage-collects the orphaned partial
    ckpt.save_sharded(d, {"w": jnp.full(8, 3.0)}, step=3)
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]
    assert ckpt.latest_step(d) == 3


def test_sharded_retention_keep_last(tmp_path):
    import jax.numpy as jnp
    d = str(tmp_path / "sh")
    for s in (1, 2, 3, 4):
        ckpt.save_sharded(d, {"w": jnp.full(4, float(s))}, step=s,
                          keep_last=2)
    assert ckpt.latest_step(d) == 4
    kept = sorted(n for n in os.listdir(d) if n.isdigit())
    assert kept == ["3", "4"]
    # evicted steps are gone from the manifest, not just the filesystem
    tree, step = ckpt.load_sharded(d)
    assert step == 4


def test_commit_gc_removes_atomic_output_orphans(tmp_path):
    # a SIGKILL between mkstemp and os.replace leaves a '.<name>*.tmp'
    # file; the next commit must garbage-collect it
    d = tmp_path / "npz"
    d.mkdir()
    orphan = d / ".ckpt-2.npzab12cd.tmp"
    orphan.write_bytes(b"partial")
    ckpt.save_checkpoint(str(d / "ckpt-1"), {"w": np.ones(2)}, step=1)
    ckpt.commit_step(str(d), 1, kind="npz", path="ckpt-1.npz")
    assert not orphan.exists()
    assert ckpt.latest_step(str(d)) == 1


def test_latest_step_legacy_dir_without_manifest(tmp_path):
    d = tmp_path / "legacy"
    (d / "7").mkdir(parents=True)
    (d / "12").mkdir()
    assert ckpt.latest_step(str(d)) == 12


# ---------------------------------------------------------------------------
# retry / watchdog
# ---------------------------------------------------------------------------
def test_retrying_recovers_then_exhausts():
    calls = []

    @fault.retrying(max_attempts=3, backoff=0.001)
    def flaky(fail_times):
        calls.append(1)
        if len(calls) <= fail_times:
            raise IOError("transient")
        return "ok"

    assert flaky(2) == "ok"
    assert len(calls) == 3
    calls.clear()
    with pytest.raises(IOError):
        flaky(99)
    assert len(calls) == 3  # bounded


def test_watchdog_aborts_stalled_region():
    t0 = time.time()
    with pytest.raises(fault.WatchdogTimeout):
        with fault.watchdog(0.2):
            time.sleep(5)
    assert time.time() - t0 < 2.0


def test_watchdog_noop_when_fast():
    with fault.watchdog(5.0):
        pass


def test_watchdog_nesting_restores_outer_timer():
    # an inner watchdog must not disarm the outer one (run_resilient's
    # per-step watchdog nests around the kvstore barrier's)
    t0 = time.time()
    with pytest.raises(fault.WatchdogTimeout, match="outer"):
        with fault.watchdog(0.4, "outer"):
            with fault.watchdog(0.2):
                pass  # fast inner region
            time.sleep(5)  # outer deadline must still fire
    assert time.time() - t0 < 2.0


# ---------------------------------------------------------------------------
# wired injection points
# ---------------------------------------------------------------------------
def test_engine_flush_injection_surfaces_at_wait_point():
    a = mx.nd.array(np.ones(4))
    b = a + 1
    with fault.scope("engine.flush:1:ioerror"):
        from incubator_mxnet_tpu.ops import segment
        if segment.current_size() == 0:
            pytest.skip("bulking disabled; nothing pending to flush")
        with pytest.raises(IOError):
            b.asnumpy()


def test_kvstore_push_pull_injection():
    kv = mx.kv.create("local")
    kv.init("w", mx.nd.array(np.ones(4)))
    with fault.scope("kvstore.push:1:ioerror"):
        with pytest.raises(IOError):
            kv.push("w", mx.nd.array(np.ones(4)))
    out = mx.nd.array(np.zeros(4))
    with fault.scope("kvstore.pull:1:timeout"):
        with pytest.raises(TimeoutError):
            kv.pull("w", out=out)


# ---------------------------------------------------------------------------
# PrefetchingIter worker failures
# ---------------------------------------------------------------------------
class _FlakyIter(mx.io.DataIter):
    """Yields `n` batches; raises `exc` when the cursor reaches `fail_at`
    (once per epoch unless `always`)."""

    def __init__(self, n=6, fail_at=None, exc=IOError, always=False):
        super().__init__(batch_size=2)
        self.n, self.fail_at, self.exc, self.always = n, fail_at, exc, always
        self.i = 0
        self.fired = False

    def reset(self):
        self.i, self.fired = 0, False

    def next(self):
        if (self.fail_at is not None and self.i == self.fail_at
                and (self.always or not self.fired)):
            self.fired = True
            raise self.exc(f"boom at {self.i}")
        if self.i >= self.n:
            raise StopIteration
        self.i += 1
        return mx.io.DataBatch(
            data=[mx.nd.array(np.full((2, 3), self.i))], label=None)


def test_prefetching_iter_reraises_worker_exception():
    # a non-transient worker death must raise in the consumer, not end the
    # epoch silently (the reference's thread just died)
    it = mx.io.PrefetchingIter(_FlakyIter(fail_at=2, exc=ValueError,
                                          always=True))
    got = []
    with pytest.raises(ValueError, match="boom"):
        for batch in it:
            got.append(batch)
    assert len(got) == 2


def test_prefetching_iter_restarts_on_transient_error():
    # one transient IOError mid-epoch: bounded in-place restart delivers
    # every remaining batch
    it = mx.io.PrefetchingIter(_FlakyIter(n=6, fail_at=3, exc=IOError))
    assert len(list(it)) == 6


def test_prefetching_iter_transient_budget_exhausts():
    it = mx.io.PrefetchingIter(_FlakyIter(n=6, fail_at=3, exc=IOError,
                                          always=True), max_restarts=2)
    with pytest.raises(IOError):
        list(it)


def test_prefetching_iter_normal_epoch_and_reset():
    src = _FlakyIter(n=4)
    it = mx.io.PrefetchingIter(src)
    assert len(list(it)) == 4
    it.reset()
    assert len(list(it)) == 4


def test_dataloader_fetch_retries_transient_error():
    from incubator_mxnet_tpu.gluon.data import DataLoader, ArrayDataset
    ds = ArrayDataset(np.arange(12, dtype=np.float32).reshape(6, 2))
    loader = DataLoader(ds, batch_size=2)
    with fault.scope("dataloader.fetch:2:ioerror"):  # transient: one hit
        batches = list(loader)
    assert len(batches) == 3


def test_dataloader_stalled_worker_surfaces_timeout():
    from incubator_mxnet_tpu.gluon.data import DataLoader

    class _StallDataset:
        def __len__(self):
            return 4

        def __getitem__(self, i):
            if i == 2:
                time.sleep(3)
            return np.float32(i)

    loader = DataLoader(_StallDataset(), batch_size=2, num_workers=1,
                        timeout=0.5)
    t0 = time.time()
    with pytest.raises(mx.MXNetError, match="stalled"):
        list(loader)
    assert time.time() - t0 < 2.5  # surfaced, not hung on the worker join


def test_estimator_resume_shortens_epoch_budget(tmp_path):
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn
    from incubator_mxnet_tpu.gluon.contrib.estimator import (
        CheckpointHandler, Estimator)

    def make():
        net = nn.Dense(1, in_units=3)
        net.initialize()
        est = Estimator(net, gluon.loss.L2Loss())
        return net, est

    x = np.random.RandomState(0).randn(8, 3).astype(np.float32)
    y = np.random.RandomState(1).randn(8, 1).astype(np.float32)
    data = gluon.data.DataLoader(
        gluon.data.ArrayDataset(mx.nd.array(x), mx.nd.array(y)),
        batch_size=4)
    d = str(tmp_path / "est")
    _, est = make()
    h = CheckpointHandler(d, epoch_period=1)
    est.fit(data, epochs=2, event_handlers=[h])
    assert os.path.exists(os.path.join(d, "model-epoch2.params.npz"))

    # resume: 3-epoch budget minus the 2 already done = exactly 1 more
    _, est2 = make()
    h2 = CheckpointHandler(d, epoch_period=1, resume_from_checkpoint=True)
    est2.fit(data, epochs=3, event_handlers=[h2])
    assert est2._resume_epoch == 2
    assert os.path.exists(os.path.join(d, "model-epoch3.params.npz"))
    assert not os.path.exists(os.path.join(d, "model-epoch4.params.npz"))

    # a later fit on the same estimator WITHOUT a resume handler must not
    # inherit the stale resume offset (would silently train 0 epochs)
    from incubator_mxnet_tpu.gluon.contrib.estimator import EpochEnd

    class _Count(EpochEnd):
        epochs = 0

        def epoch_end(self, estimator, *args, **kwargs):
            self.epochs += 1

    counter = _Count()
    est2.fit(data, epochs=1, event_handlers=[counter])
    assert counter.epochs == 1


# ---------------------------------------------------------------------------
# run_resilient
# ---------------------------------------------------------------------------
def _mesh(devs, dp, tp):
    from jax.sharding import Mesh
    return Mesh(np.array(devs[:dp * tp]).reshape(dp, tp), ("dp", "tp"))


def _sharded_state(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    w = (np.arange(32, dtype=np.float32).reshape(8, 4) + 1.0) / 10.0
    return {"w": jax.device_put(w, NamedSharding(mesh, P("tp", None)))}


def _step_fn(state, step):
    import jax.numpy as jnp
    w = state["w"]
    loss = jnp.mean(w * w)
    return {"w": w * 0.9 + 0.01}, loss


def test_run_resilient_kill_resume_parity_same_and_halved_mesh(tmp_path):
    import jax
    from jax.sharding import PartitionSpec as P
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the forced 8-device mesh")
    mesh8 = _mesh(devs, 4, 2)
    state = _sharded_state(mesh8)

    ref = fault.run_resilient(_step_fn, state, str(tmp_path / "ref"), 10,
                              ckpt_every=3)
    ref_w = np.asarray(ref.state["w"])

    # crash (injected, deterministic) at the 6th step, then resume on the
    # SAME mesh: final params must match the uninterrupted run exactly
    d = str(tmp_path / "crash")
    fault.install("resilient.step", "error", at=6)
    with pytest.raises(fault.InjectedFault):
        fault.run_resilient(_step_fn, state, d, 10, ckpt_every=3,
                            max_step_retries=0)
    fault.clear()
    assert ckpt.latest_step(d) == 3  # last committed before the crash
    resumed = fault.run_resilient(_step_fn, state, d, 10, ckpt_every=3)
    assert resumed.resumed_from == 3
    np.testing.assert_array_equal(np.asarray(resumed.state["w"]), ref_w)

    # crash again, resume onto a HALVED mesh via rescale_sharded
    d2 = str(tmp_path / "crash2")
    fault.install("resilient.step", "error", at=6)
    with pytest.raises(fault.InjectedFault):
        fault.run_resilient(_step_fn, state, d2, 10, ckpt_every=3,
                            max_step_retries=0)
    fault.clear()
    mesh4 = _mesh(devs, 2, 2)
    resumed4 = fault.run_resilient(_step_fn, state, d2, 10, ckpt_every=3,
                                   mesh=mesh4, specs={"w": P("tp", None)})
    assert resumed4.resumed_from == 3
    got = resumed4.state["w"]
    assert got.sharding.mesh.devices.size == 4
    np.testing.assert_array_equal(np.asarray(got), ref_w)


def test_run_resilient_skips_nonfinite_loss(tmp_path):
    import jax
    state = _sharded_state(_mesh(jax.devices(), 1, 1))
    fault.install("resilient.loss", "nan", at=2)
    run = fault.run_resilient(_step_fn, state, str(tmp_path / "n"), 5,
                              ckpt_every=100)
    assert run.skipped_nonfinite == 1
    # the poisoned step advanced the index but not the state: 4 real updates
    w = np.asarray(state["w"])
    for _ in range(4):
        w = w * 0.9 + 0.01
    np.testing.assert_allclose(np.asarray(run.state["w"]), w, rtol=1e-6)


def test_run_resilient_watchdog_fires_on_stalled_step(tmp_path):
    import jax
    state = _sharded_state(_mesh(jax.devices(), 1, 1))
    fault.install("resilient.step", "stall", at=2, arg=10)
    t0 = time.time()
    with pytest.raises(fault.WatchdogTimeout):
        fault.run_resilient(_step_fn, state, str(tmp_path / "w"), 5,
                            watchdog_seconds=0.3, max_step_retries=0)
    assert time.time() - t0 < 5.0


def test_run_resilient_step_retry_recovers(tmp_path):
    import jax
    state = _sharded_state(_mesh(jax.devices(), 1, 1))
    fault.install("resilient.step", "ioerror", at=2)  # transient: one hit
    run = fault.run_resilient(_step_fn, state, str(tmp_path / "r"), 4,
                              ckpt_every=100, max_step_retries=2,
                              retry_backoff=0.001)
    assert run.step == 4
    assert run.step_retries == 1


def test_run_resilient_npz_mode_resume(tmp_path):
    # host-local (non-orbax) state goes through the same manifest protocol
    def step_fn(state, step):
        w = np.asarray(state["w"].asnumpy()
                       if hasattr(state["w"], "asnumpy") else state["w"])
        return {"w": w * 0.5}, float(w.sum())

    init = {"w": np.arange(6, dtype=np.float64)}
    d = str(tmp_path / "npz")
    fault.install("resilient.step", "error", at=4)
    with pytest.raises(fault.InjectedFault):
        fault.run_resilient(step_fn, init, d, 6, ckpt_every=2,
                            sharded=False, max_step_retries=0)
    fault.clear()
    run = fault.run_resilient(step_fn, init, d, 6, ckpt_every=2,
                              sharded=False)
    assert run.resumed_from == 2
    np.testing.assert_array_equal(run.state["w"],
                                  np.arange(6, dtype=np.float64) * 0.5 ** 6)


def test_run_resilient_persists_skip_counter_across_crash(tmp_path,
                                                          caplog):
    """ISSUE-12 satellite regression: pre-PR a resume RESET
    skipped_nonfinite; now the count is committed with each manifest
    entry and restored, and the resumed run's event log shows it."""
    import logging

    def step_fn(state, step):
        w = np.asarray(state["w"])
        return {"w": w * 0.5}, float(w.sum())

    init = {"w": np.arange(4, dtype=np.float64) + 1.0}
    d = str(tmp_path / "skip")
    fault.install("resilient.loss", "nan", at=2)   # skip at step 1
    fault.install("resilient.step", "error", at=5)  # die at step 4
    with pytest.raises(fault.InjectedFault):
        fault.run_resilient(step_fn, init, d, 8, ckpt_every=2,
                            sharded=False, max_step_retries=0)
    fault.clear()
    entry = ckpt.latest_entry(d)
    assert entry["step"] == 4
    assert entry["extra"]["resilient"]["skipped_nonfinite"] == 1
    with caplog.at_level(logging.INFO, logger="mxnet.fault"):
        run = fault.run_resilient(step_fn, init, d, 8, ckpt_every=2,
                                  sharded=False)
    assert run.resumed_from == 4
    # the counter CONTINUES from the committed value instead of resetting
    assert run.skipped_nonfinite == 1
    resumed = [r.getMessage() for r in caplog.records
               if "resilient.resumed" in r.getMessage()]
    assert resumed and '"skipped_nonfinite": 1' in resumed[0]


def test_run_resilient_rng_state_is_crash_consistent(tmp_path):
    """With rng= passed, random draws replay identically after a crash:
    the RNG state is committed with each checkpoint and rewound to the
    restored step on resume."""
    def make_step(rng):
        def step_fn(state, step):
            w = np.asarray(state["w"])
            return {"w": w * 0.5 + rng.standard_normal()}, float(w.sum())
        return step_fn

    init = {"w": np.zeros(3, np.float64)}
    rng_ref = np.random.default_rng(42)
    ref = fault.run_resilient(make_step(rng_ref), init,
                              str(tmp_path / "ref"), 7, ckpt_every=2,
                              sharded=False, rng=rng_ref)

    d = str(tmp_path / "crash")
    rng_a = np.random.default_rng(42)
    fault.install("resilient.step", "error", at=6)
    with pytest.raises(fault.InjectedFault):
        fault.run_resilient(make_step(rng_a), init, d, 7, ckpt_every=2,
                            sharded=False, max_step_retries=0, rng=rng_a)
    fault.clear()
    # restart with a FRESH generator: its state must be rewound to the
    # committed step's snapshot, not the seed
    rng_b = np.random.default_rng(42)
    run = fault.run_resilient(make_step(rng_b), init, d, 7, ckpt_every=2,
                              sharded=False, rng=rng_b)
    assert run.resumed_from == 4
    np.testing.assert_array_equal(run.state["w"], ref.state["w"])


def test_rng_state_encode_roundtrip_both_kinds():
    # RandomState (MT19937 tuple) and Generator (bit_generator dict)
    rs = np.random.RandomState(7)
    rs.randn(3)
    snap = fault.rng_state_encode(rs)
    rs2 = np.random.RandomState(0)
    fault.rng_state_restore(rs2, snap)
    np.testing.assert_array_equal(rs.randn(4), rs2.randn(4))

    gen = np.random.default_rng(9)
    gen.standard_normal(3)
    snap = fault.rng_state_encode(gen)
    assert json.loads(json.dumps(snap)) is not None   # JSON-safe
    gen2 = np.random.default_rng(0)
    fault.rng_state_restore(gen2, snap)
    np.testing.assert_array_equal(gen.standard_normal(4),
                                  gen2.standard_normal(4))

    # non-PCG bit generators carry ndarray state (MT19937's 624-word
    # key): the encode must still be JSON-safe and round-trip exactly
    mt = np.random.Generator(np.random.MT19937(5))
    mt.standard_normal(2)
    snap = fault.rng_state_encode(mt)
    snap = json.loads(json.dumps(snap))   # through a real JSON boundary
    mt2 = np.random.Generator(np.random.MT19937(0))
    fault.rng_state_restore(mt2, snap)
    np.testing.assert_array_equal(mt.standard_normal(3),
                                  mt2.standard_normal(3))
    # kind mismatch is a loud error, not silent corruption
    with pytest.raises(mx.MXNetError, match="RandomState"):
        fault.rng_state_restore(np.random.default_rng(0),
                                fault.rng_state_encode(rs))


# ---------------------------------------------------------------------------
# nightly: real SIGKILL via tools/crashtest.py
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_crashtest_sigkill_parity(tmp_path):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "crashtest.py"),
         "--steps", "14", "--ckpt-every", "3", "--kill-at", "8",
         "--dir", str(tmp_path)],
        capture_output=True, text=True, timeout=570,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "parity OK" in proc.stdout


# ---------------------------------------------------------------------------
# fault-coverage drills: every POINTS entry must be named by a spec literal
# in at least one test (mxlint `fault-point-untested` keeps this honest)
# ---------------------------------------------------------------------------
def test_checkpoint_load_injected_ioerror_is_side_effect_free(tmp_path):
    p = ckpt.save_checkpoint(str(tmp_path / "c"), {"w": np.arange(4.)},
                             step=3)
    with fault.scope("checkpoint.load:1:ioerror"):
        with pytest.raises(IOError):
            ckpt.load_checkpoint(p)
    # the failed load touched nothing: a plain retry returns the committed
    # checkpoint bit-exactly
    params, step = ckpt.load_checkpoint(p)
    assert step == 3
    np.testing.assert_array_equal(params["w"].asnumpy(), np.arange(4.))


def test_io_prefetch_injected_transient_fault_restarts_in_place():
    # the worker injects io.prefetch BEFORE each fetch; one transient hit
    # must burn a restart from the budget, not a batch from the source
    it = mx.io.PrefetchingIter(_FlakyIter(n=5))
    with fault.scope("io.prefetch:2:ioerror"):
        got = list(it)
        assert fault.hits("io.prefetch") >= 2  # the failed hit plus retry
    assert len(got) == 5


def test_io_prefetch_persistent_fault_exhausts_restart_budget():
    it = mx.io.PrefetchingIter(_FlakyIter(n=5), max_restarts=1)
    with fault.scope("io.prefetch:*:ioerror"):
        with pytest.raises(IOError):
            list(it)


def test_kvstore_collective_injected_fault_fails_fast():
    # collectives are deliberately NOT retried (a lone re-entrant would
    # pair with its peers' NEXT collective); the injected fault must
    # surface immediately and a clean retry must still work
    from incubator_mxnet_tpu.kvstore import KVStore
    with fault.scope("kvstore.collective:1:error"):
        with pytest.raises(fault.InjectedFault):
            KVStore._cross_process_sum(mx.nd.array(np.ones(4)))
        assert fault.hits("kvstore.collective") == 1
    out = KVStore._cross_process_sum(mx.nd.array(np.arange(4.)))
    assert float(np.asarray(out.asnumpy()).sum()) == 6.0


def test_estimator_checkpoint_retries_transient_io_fault(tmp_path):
    from incubator_mxnet_tpu.gluon.contrib.estimator import CheckpointHandler

    class _Net:
        def save_parameters(self, path):
            with open(path, "w") as f:
                f.write("params")

    class _Est:
        net = _Net()
        trainer = None

    h = CheckpointHandler(str(tmp_path / "ckpts"), model_prefix="m")
    h.train_begin(_Est())
    with fault.scope("estimator.checkpoint:1:ioerror"):
        h.epoch_end(_Est())  # first attempt fails, the retry must land
        assert fault.hits("estimator.checkpoint") >= 2
    assert os.path.exists(
        os.path.join(str(tmp_path / "ckpts"), "m-epoch1.params.npz"))
