"""Convergence gate + pretrained-weight story.

≙ the reference's tests/python/train/ (small end-to-end convergence tests
kept in CI) and model_store pretrained loading. The digit-classification
dataset is sklearn's bundled load_digits (offline, 1797 8x8 images) — the
MNIST-MLP convergence criterion (≥97% train accuracy) transfers directly.
"""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn


def _digits():
    from sklearn.datasets import load_digits
    d = load_digits()
    X = (d.data / 16.0).astype(np.float32)
    Y = d.target.astype(np.int32)
    return X, Y


def test_mlp_digits_converges_97():
    """MLP digit classification to >=97% accuracy through the EAGER tape +
    Trainer path (also gates bulked-dispatch training correctness)."""
    X, Y = _digits()
    net = nn.HybridSequential()
    net.add(nn.Dense(64, activation="relu"), nn.Dense(10))
    net.initialize()
    loss_fn = gluon.loss.SoftmaxCrossEntropyLoss()
    trainer = gluon.Trainer(net.collect_params(), "adam",
                            {"learning_rate": 3e-3})
    xs, ys = mx.np.array(X), mx.np.array(Y)
    bs = 256
    rng = np.random.RandomState(0)
    for epoch in range(60):
        order = rng.permutation(len(X))
        for i in range(0, len(X) - bs + 1, bs):
            idx = order[i:i + bs]
            xb, yb = mx.np.array(X[idx]), mx.np.array(Y[idx])
            with mx.autograd.record():
                L = loss_fn(net(xb), yb).mean()
            L.backward()
            trainer.step(bs)
    pred = net(xs).asnumpy().argmax(1)
    acc = float((pred == Y).mean())
    assert acc >= 0.97, f"accuracy {acc:.4f} < 0.97"


def test_pretrained_roundtrip_resnet18(tmp_path):
    """model_zoo pretrained=True loads offline weights and reproduces the
    source net's logits exactly (eval mode)."""
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    src = vision.resnet18_v1(layout="NHWC")
    src.initialize()
    x = mx.np.array(
        np.random.RandomState(0).randn(1, 32, 32, 3).astype(np.float32))
    src(x)  # resolve shapes
    root = tmp_path / "models"
    os.makedirs(root)
    src.save_parameters(str(root / "resnet18_v1.npz"))

    net = vision.resnet18_v1(layout="NHWC", pretrained=True, root=str(root))
    np.testing.assert_allclose(net(x).asnumpy(), src(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_params_format_roundtrip(tmp_path):
    """Reference .params binary format: our writer's output parses back
    bit-exactly, and the converter produces a loadable npz zoo entry."""
    from incubator_mxnet_tpu.gluon.model_zoo import model_store as ms
    arrays = {
        "features.0.weight": np.random.RandomState(1)
            .randn(8, 3, 3, 3).astype(np.float32),
        "features.0.bias": np.zeros((8,), np.float32),
        "output.weight": np.random.RandomState(2)
            .randn(10, 8).astype(np.float16),
        "steps": np.arange(5, dtype=np.int64),
    }
    p = str(tmp_path / "m.params")
    ms.save_params_file(p, arrays)
    back = ms.load_params_file(p)
    assert set(back) == set(arrays)
    for k in arrays:
        assert back[k].dtype == arrays[k].dtype
        np.testing.assert_array_equal(back[k], arrays[k])

    npz = ms.convert_params_to_npz(p, str(tmp_path / "m.npz"),
                                   name_map={"steps": "step_count"})
    with np.load(npz) as f:
        assert "step_count" in f.files
        np.testing.assert_array_equal(f["features.0.bias"],
                                      arrays["features.0.bias"])


def test_pretrained_from_params_file(tmp_path):
    """pretrained=True also accepts the reference's .params container."""
    from incubator_mxnet_tpu.gluon.model_zoo import vision, model_store as ms
    src = vision.alexnet()
    src.initialize()
    x = mx.np.array(
        np.random.RandomState(3).randn(1, 3, 224, 224).astype(np.float32))
    src(x)
    arrays = {name: p.data().asnumpy()
              for name, p in src.collect_params().items()}
    root = tmp_path / "models"
    os.makedirs(root)
    ms.save_params_file(str(root / "alexnet.params"), arrays)

    net = vision.alexnet(pretrained=True, root=str(root))
    np.testing.assert_allclose(net(x).asnumpy(), src(x).asnumpy(),
                               rtol=1e-5, atol=1e-6)


def test_missing_pretrained_gives_actionable_error(tmp_path):
    from incubator_mxnet_tpu.gluon.model_zoo import vision
    with pytest.raises(mx.MXNetError, match="convert_model"):
        vision.resnet18_v1(pretrained=True, root=str(tmp_path / "empty"))


def test_params_bf16_and_v3_scalar_records(tmp_path):
    """bf16 (type_flag 12) payloads widen to f32, and a V3 (np-semantics)
    0-d record carries ctx/dtype/data — the stream must stay in sync so
    the FOLLOWING array parses correctly."""
    import struct
    from incubator_mxnet_tpu.gluon.model_zoo import model_store as ms

    f32 = np.array([1.5, -2.25, 3.0, 0.5], np.float32)
    bf16_u16 = (f32.view(np.uint32) >> 16).astype(np.uint16)  # exact in bf16
    after = np.arange(6, dtype=np.float32).reshape(2, 3)

    out = bytearray()
    out += struct.pack("<QQ", 0x112, 0)
    out += struct.pack("<Q", 3)
    # record 1: V2, bf16 flag 12
    out += struct.pack("<Ii", 0xF993FAC9, 0)
    out += struct.pack("<i", 1) + struct.pack("<q", 4)
    out += struct.pack("<ii", 1, 0) + struct.pack("<i", 12)
    out += bf16_u16.tobytes()
    # record 2: V3, ndim==0 scalar WITH ctx/dtype/one f32 element
    out += struct.pack("<Ii", 0xF993FACA, 0)
    out += struct.pack("<i", 0)
    out += struct.pack("<ii", 1, 0) + struct.pack("<i", 0)
    out += struct.pack("<f", 7.25)
    # record 3: ordinary V2 f32 (2,3) — corrupted if record 2 desyncs
    out += struct.pack("<Ii", 0xF993FAC9, 0)
    out += struct.pack("<i", 2) + struct.pack("<qq", 2, 3)
    out += struct.pack("<ii", 1, 0) + struct.pack("<i", 0)
    out += after.tobytes()
    out += struct.pack("<Q", 3)
    for nm in ("bf", "scalar", "after"):
        b = nm.encode()
        out += struct.pack("<Q", len(b)) + b
    p = str(tmp_path / "mixed.params")
    with open(p, "wb") as f:
        f.write(bytes(out))

    back = ms.load_params_file(p)
    np.testing.assert_array_equal(back["bf"], f32)
    assert back["scalar"].shape == ()
    assert back["scalar"] == np.float32(7.25)
    np.testing.assert_array_equal(back["after"], after)


def test_auto_name_map_round_trip(tmp_path):
    """ROADMAP item: map a reference-zoo-style checkpoint (foreign flat
    scoped names) onto the framework's structural names by order+shape
    alignment; pretrained load reproduces the source logits."""
    from incubator_mxnet_tpu.gluon.model_zoo import vision, model_store as ms
    mx.seed(11)
    name = "alexnet"
    src_net = vision.alexnet()
    src_net.initialize()
    x = mx.np.array(
        np.random.RandomState(5).randn(1, 3, 224, 224).astype(np.float32))
    src_net(x)
    ref = src_net(x).asnumpy()
    foreign = {f"zoo0_param{i}_w": p.data().asnumpy()
               for i, (nm, p) in
               enumerate(src_net.collect_params().items())}
    pfile = str(tmp_path / "zoo.params")
    ms.save_params_file(pfile, foreign)
    amap = ms.auto_name_map(pfile, name)
    ms.convert_params_to_npz(pfile, str(tmp_path / f"{name}.npz"), amap)
    net = getattr(vision, name)(pretrained=True, root=str(tmp_path))
    np.testing.assert_allclose(net(x).asnumpy(), ref, rtol=1e-4, atol=1e-5)

    # wrong architecture must be rejected, not silently mis-mapped
    with pytest.raises(mx.MXNetError,
                       match="architecture mismatch|shape mismatch"):
        ms.auto_name_map(pfile, "resnet18_v1")
