"""gluon.probability.transformation (≙ reference transformation.py):
invertibility, log-det correctness vs numerics/scipy, composition, and
TransformedDistribution change-of-variables."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon import probability as P


def test_lognormal_matches_scipy():
    from scipy import stats
    ln = P.TransformedDistribution(P.Normal(0.0, 1.0), P.ExpTransform())
    y = mx.np.array(np.array([0.3, 0.5, 1.0, 2.0, 5.0], np.float32))
    np.testing.assert_allclose(ln.log_prob(y).asnumpy(),
                               stats.lognorm.logpdf(y.asnumpy(), 1.0),
                               rtol=1e-5)
    s = ln.sample((2000,))
    assert float(s.asnumpy().min()) > 0     # support is positive reals


@pytest.mark.parametrize("t", [
    P.ExpTransform(),
    P.AffineTransform(1.5, -2.0),
    P.PowerTransform(3.0),
    P.SigmoidTransform(),
])
def test_roundtrip_and_numeric_log_det(t):
    x = mx.np.array(np.array([0.2, 0.9, 1.7], np.float32))
    y = t(x)
    np.testing.assert_allclose(t.inv(y).asnumpy(), x.asnumpy(),
                               rtol=1e-4, atol=1e-5)
    eps = 1e-3
    y2 = t(mx.np.array(x.asnumpy() + eps))
    num = np.log(np.abs((y2.asnumpy() - y.asnumpy()) / eps))
    np.testing.assert_allclose(t.log_det_jacobian(x).asnumpy(), num,
                               atol=2e-2)


def test_compose_log_det_is_sum():
    a, b = P.AffineTransform(0.0, 3.0), P.ExpTransform()
    chain = P.ComposeTransform([a, b])
    x = mx.np.array(np.array([-0.5, 0.1], np.float32))
    mid = a(x)
    expect = a.log_det_jacobian(x).asnumpy() \
        + b.log_det_jacobian(mid).asnumpy()
    np.testing.assert_allclose(chain.log_det_jacobian(x).asnumpy(), expect,
                               rtol=1e-5)


def test_non_bijective_rejected():
    with pytest.raises(mx.MXNetError, match="bijective"):
        P.TransformedDistribution(P.Normal(0.0, 1.0), P.SoftmaxTransform())
    with pytest.raises(mx.MXNetError, match="not bijective"):
        P.AbsTransform().log_det_jacobian(mx.np.array(np.ones(2)))


def test_softmax_transform_simplex():
    t = P.SoftmaxTransform()
    x = mx.np.array(np.random.RandomState(0).randn(4, 6).astype(np.float32))
    y = t(x).asnumpy()
    np.testing.assert_allclose(y.sum(axis=-1), 1.0, rtol=1e-5)
