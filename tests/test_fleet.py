"""serve.fleet: multi-replica serving with failover and drain-and-swap.

Contracts under test (ISSUE 16 acceptance):
  * per-replica metrics ports derive from the inherited MXNET_METRICS_PORT
    (base + replica index) — the port-collision regression — and the
    router learns the bound port from each replica's hello
  * replica SIGKILL mid-traffic: in-flight requests re-enqueue onto the
    survivors under the retry budget (zero client-visible failures) and
    the supervisor respawns the replica warm
  * all four fault points (`fleet.dispatch`, `fleet.heartbeat`,
    `fleet.respawn`, `fleet.swap`) injectable via MXNET_FAULT_SPEC with
    deterministic outcomes: transparent retry, hung-replica kill+respawn,
    bounded restarts with original-error resurfacing, typed swap abort
  * rolling drain-and-swap drops ZERO requests and flips the served
    version; `ReplicaDraining` is routed around, never client-visible
  * one trace per request even when the request survives a retry hop
  * real fleet: outputs byte-exact vs reference_generate, hellos report
    the persistent-compilation warmup, and `assert_no_retraces` holds
    fleet-wide from replica-reported pong counters

Stub replicas ({"stub": true} specs) keep the router/supervisor tests
jax-free and fast; the real-engine fixture proves the end-to-end path.
"""
import json
import os
import signal
import socket
import threading
import time
import urllib.request

import subprocess
import sys

import numpy as np
import pytest

from incubator_mxnet_tpu import fault, profiler, serve, telemetry


REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

CFG = dict(vocab=64, embed=32, layers=2, heads=4, head_dim=8, max_len=48)

STUB_SPEC = {"version": "v1", "stub": True, "stub_delay_ms": 5.0}


def _stub_tokens(prompt, max_new, version):
    """The stub replica's deterministic token function (mirrors
    serve.replica._StubEngine) — lets tests prove WHICH version served."""
    vtag = sum(version.encode()) % 997
    base = int(np.sum(prompt)) % 997
    return [(base * 31 + i + vtag) % 97 for i in range(max_new)]


def _free_port_base(n=2, tries=50):
    """A base port such that base..base+n-1 are all currently bindable."""
    for _ in range(tries):
        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        base = s.getsockname()[1]
        s.close()
        if base + n >= 65500:
            continue
        ok = True
        for i in range(1, n):
            t = socket.socket()
            try:
                t.bind(("127.0.0.1", base + i))
            except OSError:
                ok = False
            finally:
                t.close()
            if not ok:
                break
        if ok:
            return base
    pytest.skip("could not find consecutive free ports")


def _wait(pred, timeout, msg):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return
        time.sleep(0.05)
    pytest.fail(f"timed out after {timeout}s waiting for {msg}")


def _serving(fleet):
    return sum(1 for r in fleet.stats()["replicas"]
               if r["state"] == "serving")


@pytest.fixture(scope="module")
def stub_fleet(tmp_path_factory):
    """2 stub replicas with a fast heartbeat; MXNET_METRICS_PORT is set
    only across start() so the children inherit it (the satellite-1
    port-derivation regression) without leaking into other tests."""
    base = _free_port_base(2)
    wd = tmp_path_factory.mktemp("stub_fleet")
    old = os.environ.get("MXNET_METRICS_PORT")
    os.environ["MXNET_METRICS_PORT"] = str(base)
    try:
        fleet = serve.Fleet(STUB_SPEC, replicas=2, heartbeat_ms=100,
                            retry_budget=2, drain_timeout_ms=10000,
                            heartbeat_misses=2, max_restarts=2,
                            workdir=str(wd)).start()
    finally:
        if old is None:
            os.environ.pop("MXNET_METRICS_PORT", None)
        else:
            os.environ["MXNET_METRICS_PORT"] = old
    yield fleet, base
    fleet.close()


# ---------------------------------------------------------------------------
# satellite 1: metrics-port derivation regression
# ---------------------------------------------------------------------------
def test_metrics_ports_derive_from_env_base_plus_index(stub_fleet):
    """Two replicas inheriting one MXNET_METRICS_PORT must NOT collide:
    each derives base + replica index, and the router learns the bound
    port from the hello (not by re-deriving)."""
    fleet, base = stub_fleet
    reps = fleet.stats()["replicas"]
    ports = {r["replica"]: r["metrics_port"] for r in reps}
    assert ports == {0: base, 1: base + 1}, ports
    for i, port in ports.items():
        txt = urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10).read().decode()
        assert "mx_" in txt, f"replica {i} port {port} served no metrics"


def test_stub_fleet_serves_and_reports_live_replicas(stub_fleet):
    fleet, _ = stub_fleet
    futs = [fleet.submit([1, 2, 3], max_new_tokens=4) for _ in range(8)]
    for f in futs:
        assert f.result(timeout=30).tolist() == \
            _stub_tokens([1, 2, 3], 4, "v1")
    st = fleet.stats()
    assert st["replicas_live"] == 2
    assert st["version"] == "v1"


def test_prefix_affinity_pins_shared_prefix_to_one_replica(stub_fleet):
    """Requests sharing a block-quantized prefix route to the replica
    that served the prefix first (its prefix cache is warm there):
    after the first dispatch records the mapping, every follow-up
    counts `fleet.affinity_hits`. Sub-block prompts carry no affinity
    key and never touch the counter."""
    fleet, _ = stub_fleet
    prompt = list(range(1, 21))               # 20 tokens = 1 block of 16
    before = serve.fleet_stats()["affinity_hits"]
    for _ in range(4):                        # sequential: no load races
        fleet.submit(prompt, max_new_tokens=2).result(timeout=30)
    assert serve.fleet_stats()["affinity_hits"] - before == 3
    # shorter than one block (19//16 == 1 needs 17+ tokens): no key
    before = serve.fleet_stats()["affinity_hits"]
    fleet.submit([1, 2, 3], max_new_tokens=2).result(timeout=30)
    fleet.submit([1, 2, 3], max_new_tokens=2).result(timeout=30)
    assert serve.fleet_stats()["affinity_hits"] == before


# ---------------------------------------------------------------------------
# fault points: fleet.dispatch / fleet.heartbeat (fleet.respawn and
# fleet.swap below; the respawn-exhaustion test runs LAST — it
# permanently fails replica 0)
# ---------------------------------------------------------------------------
def test_dispatch_fault_is_retried_transparently(stub_fleet):
    fleet, _ = stub_fleet
    before = serve.fleet_stats()["retries"]
    with fault.scope("fleet.dispatch:1:error"):
        toks = fleet.submit([5, 6], max_new_tokens=3).result(timeout=30)
        assert fault.hits("fleet.dispatch") >= 1
    assert toks.tolist() == _stub_tokens([5, 6], 3, fleet.version)
    assert serve.fleet_stats()["retries"] >= before + 1


def test_one_trace_per_request_across_retry_hop(stub_fleet, tmp_path):
    """A request that survives a dispatch retry is still ONE trace: the
    router re-uses the same request root, recording a single
    fleet.request span whose `attempts` count exposes the hop."""
    fleet, _ = stub_fleet
    profiler.start()
    try:
        with fault.scope("fleet.dispatch:1:error"):
            fleet.submit([7, 7], max_new_tokens=2).result(timeout=30)
        fleet.submit([8], max_new_tokens=2).result(timeout=30)
    finally:
        profiler.stop()
    f = str(tmp_path / "trace.json")
    profiler.dump(filename=f)
    events = json.load(open(f))["traceEvents"]
    roots = [e for e in events if e["name"] == "fleet.request"]
    assert len(roots) == 2
    tids = {e["args"]["trace_id"] for e in roots}
    assert len(tids) == 2, "each fleet request must be its own trace"
    attempts = sorted(e["args"]["attempts"] for e in roots)
    assert attempts == [1, 2], attempts


def test_sigkill_failover_reenqueues_inflight_onto_survivor(stub_fleet):
    """Replica death with work in flight: every future still resolves
    (re-dispatched under the retry budget), the failover and retries are
    counted, and the supervisor respawns the replica."""
    fleet, _ = stub_fleet
    before = serve.fleet_stats()
    pid0 = fleet.stats()["replicas"][0]["pid"]
    futs = [fleet.submit([9, i], max_new_tokens=4) for i in range(16)]
    os.kill(pid0, signal.SIGKILL)
    for i, f in enumerate(futs):
        assert f.result(timeout=60).tolist() == \
            _stub_tokens([9, i], 4, fleet.version)
    after = serve.fleet_stats()
    assert after["failovers"] >= before["failovers"] + 1
    assert after["retries"] >= before["retries"] + 1
    _wait(lambda: _serving(fleet) == 2, 30, "respawn after SIGKILL")
    assert after["respawns"] >= before["respawns"] or \
        serve.fleet_stats()["respawns"] >= before["respawns"] + 1
    assert fleet.stats()["replicas"][0]["pid"] != pid0


def test_heartbeat_fault_declares_replica_hung_then_respawns(stub_fleet):
    """Persistent fleet.heartbeat failures count as missed heartbeats;
    past the miss budget the replica is killed and respawned."""
    fleet, _ = stub_fleet
    before = serve.fleet_stats()["respawns"]
    with fault.scope("fleet.heartbeat:1+:error"):
        _wait(lambda: serve.fleet_stats()["respawns"] >= before + 1,
              30, "hung-replica respawn")
        assert fault.hits("fleet.heartbeat") >= 2  # heartbeat_misses
    _wait(lambda: _serving(fleet) == 2, 60, "fleet recovery")
    toks = fleet.submit([3], max_new_tokens=2).result(timeout=30)
    assert toks.tolist() == _stub_tokens([3], 2, fleet.version)


# ---------------------------------------------------------------------------
# drain-and-swap: zero drops, version flip, typed abort
# ---------------------------------------------------------------------------
def test_rolling_swap_drops_zero_requests_and_flips_version(stub_fleet):
    fleet, _ = stub_fleet
    before = serve.fleet_stats()
    stop, errors, served = threading.Event(), [], [0]

    def pump():
        while not stop.is_set():
            try:
                fleet.submit([2, 7], max_new_tokens=3).result(timeout=60)
                served[0] += 1
            except Exception as e:          # noqa: BLE001 - test collects
                errors.append(e)

    t = threading.Thread(target=pump)
    t.start()
    try:
        fleet.swap(dict(STUB_SPEC, version="v2"))
    finally:
        stop.set()
        t.join()
    assert not errors, f"swap dropped {len(errors)}: {errors[:3]}"
    assert served[0] > 0
    assert fleet.version == "v2"
    assert all(r["version"] == "v2" for r in fleet.stats()["replicas"])
    after = serve.fleet_stats()
    assert after["swaps"] == before["swaps"] + 1
    assert after["drain_ms"] > before["drain_ms"]
    # v2 actually serves (the stub token function is version-keyed)
    toks = fleet.submit([1], max_new_tokens=2).result(timeout=30)
    assert toks.tolist() == _stub_tokens([1], 2, "v2")


def test_swap_fault_aborts_typed_and_fleet_keeps_serving(stub_fleet):
    fleet, _ = stub_fleet
    with fault.scope("fleet.swap:1:error"):
        with pytest.raises(serve.FleetError, match="aborted at replica"):
            fleet.swap(dict(STUB_SPEC, version="v9"))
    assert fleet.version == "v2"            # unchanged by the abort
    _wait(lambda: _serving(fleet) == 2, 60, "recovery after swap abort")
    toks = fleet.submit([4], max_new_tokens=2).result(timeout=30)
    assert toks.tolist() == _stub_tokens([4], 2, "v2")


# must stay LAST in the stub module: replica 0 ends permanently failed
def test_respawn_fault_exhausts_bounded_restarts(stub_fleet):
    """PR-9 restart protocol at fleet scope: persistent respawn failures
    bill consecutive restarts; past max_restarts the replica is marked
    `failed` (no hot-loop) and the fleet serves degraded on the
    survivor."""
    fleet, _ = stub_fleet
    pid0 = fleet.stats()["replicas"][0]["pid"]
    with fault.scope("fleet.respawn:1+:error"):
        os.kill(pid0, signal.SIGKILL)
        _wait(lambda: fleet.stats()["replicas"][0]["state"] == "failed",
              30, "replica 0 to exhaust its restart budget")
        assert fault.hits("fleet.respawn") >= 2
    r0 = fleet.stats()["replicas"][0]
    assert r0["consecutive_restarts"] > 2   # max_restarts exceeded
    toks = fleet.submit([6], max_new_tokens=2).result(timeout=30)
    assert toks.tolist() == _stub_tokens([6], 2, fleet.version)
    assert serve.fleet_stats()["replicas_live"] == 1


# ---------------------------------------------------------------------------
# real engines: reference-exact outputs, warm hellos, zero retraces
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def real_fleet(tmp_path_factory):
    cache = tmp_path_factory.mktemp("fleet_cc")
    wd = tmp_path_factory.mktemp("real_fleet")
    spec = {"version": "v1", "config": CFG, "seed": 0,
            "engine": {"max_slots": 4, "decode_steps": 2,
                       "prefill_window": 16}}
    old_cc = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    old_mp = os.environ.pop("MXNET_METRICS_PORT", None)
    os.environ["MXNET_COMPILE_CACHE_DIR"] = str(cache)
    try:
        fleet = serve.Fleet(spec, replicas=2, heartbeat_ms=250,
                            workdir=str(wd)).start()
    finally:
        if old_cc is None:
            os.environ.pop("MXNET_COMPILE_CACHE_DIR", None)
        else:
            os.environ["MXNET_COMPILE_CACHE_DIR"] = old_cc
        if old_mp is not None:
            os.environ["MXNET_METRICS_PORT"] = old_mp
    yield fleet
    fleet.close()


def test_real_fleet_matches_reference_and_reports_warm_hello(real_fleet):
    model = serve.CachedDecoder(serve.DecoderConfig(**CFG), seed=0)
    prompts = [[3, 1, 4, 1], [5, 9, 2], [6, 5, 3, 5, 8], [2, 7]]
    futs = [real_fleet.submit(p, max_new_tokens=6) for p in prompts]
    for p, f in zip(prompts, futs):
        np.testing.assert_array_equal(
            f.result(timeout=120), model.reference_generate(p, 6),
            err_msg=f"fleet output diverged for prompt {p}")
    for r in real_fleet.stats()["replicas"]:
        assert r["warmup_s"] is not None and r["warmup_s"] > 0
        assert r["compile_cache_size"] >= 1
        assert r["metrics_port"] is None    # env unset -> no server


def test_real_fleet_zero_retraces_fleet_wide(real_fleet):
    # pongs carry each engine's retraces_after_warmup counter
    _wait(lambda: real_fleet.retraces_after_warmup() >= 0, 10,
          "a heartbeat pong from every replica")
    assert real_fleet.retraces_after_warmup() == 0
    assert real_fleet.assert_no_retraces() == 0


# ---------------------------------------------------------------------------
# observability surface: stats-group keys + replica-state gauge
# ---------------------------------------------------------------------------
def test_fleet_stats_group_and_replica_state_gauge(real_fleet):
    assert set(serve.FLEET_STATS) == {
        "replicas_live", "failovers", "retries", "respawns", "swaps",
        "drain_ms", "profile_divergence", "affinity_hits"}
    snap = telemetry.REGISTRY.snapshot()
    for key in ("fleet.replicas_live", "fleet.failovers", "fleet.retries",
                "fleet.respawns", "fleet.swaps", "fleet.drain_ms",
                "fleet.affinity_hits"):
        assert key in snap, key
    # serve.replica_state is a labeled gauge: one series per replica,
    # level 2 == serving
    assert sum(k.startswith("serve.replica_state") for k in snap) == 2
    assert snap['serve.replica_state{replica="0"}'] == 2
    assert snap['serve.replica_state{replica="1"}'] == 2


# ---------------------------------------------------------------------------
# nightly: real SIGKILL under open-loop Poisson traffic, and a real
# rolling swap under sustained load
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_crashtest_fleet_sigkill_under_poisson_traffic(tmp_path):
    """ISSUE 16 acceptance: SIGKILL one of two replicas mid-stream under
    the PR-13 open-loop generator — zero client-visible failures, kill
    window p99 within 3x steady, warm respawn via the compile cache."""
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "crashtest.py"),
         "--fleet", "--rate", "20", "--window", "5",
         "--dir", str(tmp_path)],
        capture_output=True, text=True, timeout=570,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "fleet SIGKILL parity OK" in proc.stdout
    assert "0 client-visible failures" in proc.stdout


@pytest.mark.slow
def test_real_rolling_swap_under_sustained_load(tmp_path):
    """Rolling drain-and-swap across real replicas while clients pump:
    zero drops, the new version's outputs are reference-exact, and the
    fleet-wide zero-retrace contract holds on the swapped fleet."""
    cache = tmp_path / "cc"
    cache.mkdir()
    old = os.environ.get("MXNET_COMPILE_CACHE_DIR")
    os.environ["MXNET_COMPILE_CACHE_DIR"] = str(cache)
    spec = {"version": "v1", "config": CFG, "seed": 0,
            "engine": {"max_slots": 4, "decode_steps": 2,
                       "prefill_window": 16}}
    try:
        fleet = serve.Fleet(spec, replicas=2, heartbeat_ms=250,
                            workdir=str(tmp_path / "fleet")).start()
    finally:
        if old is None:
            os.environ.pop("MXNET_COMPILE_CACHE_DIR", None)
        else:
            os.environ["MXNET_COMPILE_CACHE_DIR"] = old
    try:
        stop, errors, served = threading.Event(), [], [0]

        def pump():
            while not stop.is_set():
                try:
                    fleet.submit([2, 7], max_new_tokens=4).result(
                        timeout=120)
                    served[0] += 1
                except Exception as e:      # noqa: BLE001 - test collects
                    errors.append(e)

        threads = [threading.Thread(target=pump) for _ in range(3)]
        for t in threads:
            t.start()
        try:
            fleet.swap(dict(spec, version="v2", seed=1))
        finally:
            stop.set()
            for t in threads:
                t.join()
        assert not errors, f"swap dropped {len(errors)}: {errors[:3]}"
        assert served[0] > 0
        assert fleet.version == "v2"
        model = serve.CachedDecoder(serve.DecoderConfig(**CFG), seed=1)
        got = fleet.submit([3, 3], max_new_tokens=4).result(timeout=120)
        np.testing.assert_array_equal(got, model.reference_generate(
            [3, 3], 4))
        _wait(lambda: fleet.retraces_after_warmup() >= 0, 10,
              "post-swap pongs")
        assert fleet.assert_no_retraces() == 0
    finally:
        fleet.close()


# ---------------------------------------------------------------------------
# bench phase + committed artifact
# ---------------------------------------------------------------------------
def test_bench_fleet_quick_phase():
    """Tier-1 smoke (the ISSUE-16 satellite): the fleet phase rides the
    hermetic bench runner and emits the gated trend scalars (stub
    replicas — the router/failover/swap machinery end to end, no jax
    compile)."""
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "bench.py"),
         "--phase", "fleet", "--quick"],
        capture_output=True, text=True, timeout=600, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["ok"] is True, out
    res = out["result"]
    assert res["fleet_vs_single_speedup"] > 0
    assert res["fleet_p99_ms_steady"] > 0
    assert res["fleet_p99_ms_during_kill"] > 0
    # the two floor metrics: a SIGKILL and a rolling swap both ran and
    # neither cost a single client-visible request
    assert res["fleet_kill_failures"] == 0
    assert res["fleet_swap_dropped_requests"] == 0
    assert res["fleet_kill_failovers"] >= 1
    assert res["fleet_kill_respawns"] >= 1


def test_committed_fleet_artifact_acceptance():
    """The committed r16 real-engine round holds the ISSUE-16
    acceptance: a SIGKILL mid-burst and a rolling version swap each cost
    ZERO client-visible requests, the kill-window p99 stays within 3x of
    the steady window, and the respawn rejoined warm. (The capacity
    ratio is recorded but not asserted >1: the committed round is
    honestly stamped host_cores=1, where two CPU-bound replicas contend
    for one core — see meta.note.)"""
    path = os.path.join(REPO, "benchmark", "results", "fleet_r16.json")
    with open(path) as f:
        art = json.load(f)
    assert art["backend_ok"] is True
    assert art["meta"]["replicas"] == 2
    assert art["meta"]["stub"] is False        # real engines, committed
    assert art["kill"]["sent"] == art["kill"]["completed"]
    assert art["fleet_kill_failures"] == 0
    assert art["kill"]["failovers"] >= 1       # the SIGKILL caught
    assert art["kill"]["retries"] >= 1         # in-flight work
    assert art["kill"]["respawns"] >= 1
    assert art["fleet_p99_ms_during_kill"] \
        <= 3.0 * max(art["fleet_p99_ms_steady"], 25.0)
    assert art["fleet_swap_dropped_requests"] == 0
    assert art["swap"]["version_after"] == "v2"
    assert art["swap"]["served_during"] > 0    # swap rolled under load
    assert art["fleet_vs_single_speedup"] > 0
    if art["meta"]["host_cores"] < art["meta"]["replicas"]:
        assert "note" in art["meta"]           # contention honestly noted
