"""Regressions for the round-4 advisor findings (ADVICE.md r4):

1. trainer.py — under a dist kvstore the allreduced dense grad carries rows
   touched only on OTHER workers; the touched-rows sparse update must not
   drop them (fall back to the dense update).
2. trainer.py — `_last_tokens` must be cleared on every step path, not only
   inside `_row_sparse_update` (leak + stale-row update otherwise).
3. checkpoint.py — `rescale_sharded` must preserve tuple pytree nodes in
   the filled spec (treedef mismatch otherwise).
"""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn

V, D = 40, 4


class _FakeDistKV:
    """Minimal kvstore double with a dist type: pushpull is identity (one
    worker), so the trainer behaves as if the allreduce already ran."""
    type = "dist_sync"

    def init(self, key, value):
        pass

    def pushpull(self, key, values, out=None):
        pass

    def set_gradient_compression(self, params):
        pass


def _sparse_step(kvstore):
    mx.seed(11)
    emb = nn.Embedding(V, D, sparse_grad=True)
    emb.initialize()
    w0 = emb.weight.data().asnumpy().copy()
    tr = gluon.Trainer(emb.collect_params(), "sgd", {"learning_rate": 0.5},
                       kvstore=kvstore, update_on_kvstore=False)
    tokens = mx.np.array(np.array([[1, 5], [9, 5]], np.int32))
    with mx.autograd.record():
        L = (emb(tokens) ** 2).sum()
    L.backward()
    g = emb.weight.grad().asnumpy().copy()
    tr.step(1)
    return emb, w0, g


def test_dist_kvstore_sparse_grad_uses_dense_update():
    emb, w0, g = _sparse_step(_FakeDistKV())
    # dense fallback: ALL rows get w -= lr/bs * g (g is zero off the
    # touched rows here, but the mechanism must be the dense one — under a
    # real dist store g also carries other workers' rows)
    np.testing.assert_allclose(emb.weight.data().asnumpy(), w0 - 0.5 * g,
                               rtol=1e-5, atol=1e-6)
    assert emb.weight._last_tokens is None


def test_local_sparse_path_still_lazy():
    emb, w0, g = _sparse_step(None)
    np.testing.assert_allclose(emb.weight.data().asnumpy(), w0 - 0.5 * g,
                               rtol=1e-5, atol=1e-6)
    assert emb.weight._last_tokens is None


def test_last_tokens_cleared_on_update_on_kvstore_path():
    class _KV(_FakeDistKV):
        def set_updater(self, updater):
            pass

        def push(self, key, values):
            pass

        def pull(self, key, out):
            pass

    mx.seed(11)
    emb = nn.Embedding(V, D, sparse_grad=True)
    emb.initialize()
    tr = gluon.Trainer(emb.collect_params(), "sgd", {"learning_rate": 0.5},
                       kvstore=_KV(), update_on_kvstore=True)
    tokens = mx.np.array(np.array([[1, 5]], np.int32))
    for _ in range(3):
        with mx.autograd.record():
            L = (emb(tokens) ** 2).sum()
        L.backward()
        tr.step(1)
        # no unbounded pile-up across steps (advisor finding #2)
        assert emb.weight._last_tokens is None


def test_last_tokens_cleared_when_stale_grad_ignored():
    mx.seed(11)
    emb = nn.Embedding(V, D, sparse_grad=True)
    dense = nn.Dense(3)
    emb.initialize()
    dense.initialize()
    params = (list(emb.collect_params().values())
              + list(dense.collect_params().values()))
    tr = gluon.Trainer(params, "sgd", {"learning_rate": 0.5})
    x = mx.np.array(np.random.rand(2, 4).astype(np.float32))
    stale_tokens = mx.np.array(np.array([[1, 5]], np.int32))
    # emb forwards under record (tokens get recorded on the parameter) but
    # only the dense loss is backwarded — emb's grad stays stale and the
    # step must DROP the recorded tokens, not bank them for a later update
    with mx.autograd.record():
        _ = emb(stale_tokens)
        L = (dense(x) ** 2).sum()
    L.backward()
    tr.step(1, ignore_stale_grad=True)
    assert emb.weight._last_tokens is None
    w1 = emb.weight.data().asnumpy().copy()

    # next sparse step sees ONLY its own tokens: rows 1/5 stay untouched
    fresh_tokens = mx.np.array(np.array([[9, 12]], np.int32))
    with mx.autograd.record():
        L = (emb(fresh_tokens) ** 2).sum()
    L.backward()
    tr.step(1, ignore_stale_grad=True)
    w2 = emb.weight.data().asnumpy()
    np.testing.assert_array_equal(w2[[1, 5]], w1[[1, 5]])
    assert not np.allclose(w2[[9, 12]], w1[[9, 12]])


def test_rescale_sharded_tuple_nodes(tmp_path):
    import jax
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P
    from incubator_mxnet_tpu import checkpoint as ckpt

    devs = jax.devices()
    if len(devs) < 4:
        pytest.skip("needs the forced multi-device mesh")
    mesh4 = Mesh(np.array(devs[:4]).reshape(2, 2), ("dp", "tp"))
    rng = np.random.RandomState(0)
    state = {"opt": (jax.device_put(rng.randn(4, 8).astype(np.float32),
                                    NamedSharding(mesh4, P("tp", None))),
                     jax.device_put(np.float32(3.0),
                                    NamedSharding(mesh4, P())))}
    d = str(tmp_path / "ck")
    ckpt.save_sharded(d, state, step=1)
    mesh2 = Mesh(np.array(devs[:2]).reshape(2, 1), ("dp", "tp"))
    # spec omits the tuple internals (None = replicated): fill_missing must
    # rebuild the same container type the checkpoint metadata has
    tree, step = ckpt.rescale_sharded(d, mesh2, {"opt": None})
    assert step == 1
    np.testing.assert_array_equal(np.asarray(tree["opt"][0]),
                                  np.asarray(state["opt"][0]))
    assert float(tree["opt"][1]) == 3.0
