"""mxlint (ISSUE 5): the analyzer gates tier-1.

Three layers:
  1. the REPO IS CLEAN — `run_all` over the live package with the
     committed baseline yields zero new findings and zero stale baseline
     entries, so any new violation fails the build;
  2. each pass family detects its seeded fixture violations
     (tests/lint_fixtures/) exactly where expected, and suppressions /
     the baseline silence them;
  3. the CLI contract: `python -m tools.mxlint --quick --json` emits
     machine-readable findings and exit status 0 on the clean tree.

The analyzer is import-light (stdlib ast only), so these tests cost
parse time, not jax time.
"""
import json
import os
import subprocess
import sys

import pytest

from incubator_mxnet_tpu import analysis
from incubator_mxnet_tpu.analysis import (donation_safety, lock_discipline,
                                          registry_consistency,
                                          retrace_hazard, trace_safety)
from incubator_mxnet_tpu.analysis.core import Baseline, Module

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def _fixture_module(name):
    path = os.path.join(FIXTURES, name)
    with open(path) as f:
        src = f.read()
    return Module(path, os.path.join("tests", "lint_fixtures", name), src)


def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# ---------------------------------------------------------------------------
# 1. the live repo is clean under the committed baseline
# ---------------------------------------------------------------------------
def test_repo_is_clean_under_baseline():
    new, baselined, stale = analysis.run_all(
        root=REPO,
        baseline=os.path.join(REPO, analysis.DEFAULT_BASELINE))
    assert not new, "new mxlint findings:\n" + "\n".join(
        f"  {f.path}:{f.line}: [{f.rule}] {f.message}" for f in new)
    assert not stale, ("baseline entries whose finding no longer exists — "
                       "delete them from tools/mxlint_baseline.json:\n"
                       + "\n".join(f"  {s}" for s in stale))
    # the baseline documents intentional patterns; it must stay small
    assert len(baselined) < 30


def test_every_rule_name_is_registered():
    for fam in analysis.PASS_FAMILIES.values():
        for rule in fam.RULES:
            assert rule in analysis.ALL_RULES
    assert len(set(analysis.ALL_RULES)) == len(analysis.ALL_RULES)


# ---------------------------------------------------------------------------
# 2a. trace-safety fixtures
# ---------------------------------------------------------------------------
def test_trace_safety_fixture_findings():
    mod = _fixture_module("bad_trace.py")
    by = _by_rule(trace_safety.run([mod]))

    cap = {(f.scope, f.symbol) for f in by["trace-host-capture"]}
    assert ("kernel", "float(scale)") in cap
    assert ("kernel", ".item") in cap
    assert ("helper", "np.asarray") in cap     # transitive reachability

    imp = {(f.scope, f.symbol) for f in by["trace-impure-host"]}
    assert ("kernel", "time.time") in imp
    assert ("kernel", "random.random") in imp
    assert ("kernel", "os.environ.get") in imp
    # `from time import time as now` resolves to the stdlib and fires ...
    assert ("kernel", "numpy.asarray") in \
        {(f.scope, f.symbol) for f in by["trace-host-capture"]}
    assert any(f.symbol == "time.time" and "now()" in f.message
               for f in by["trace-impure-host"])
    # ... while `from jax import random as jxrandom` is NOT the stdlib
    assert not any("jxrandom" in f.symbol or "PRNGKey" in f.symbol
                   for fs in by.values() for f in fs)
    # the suppressed time.sleep(0) must NOT appear
    assert not any(f.symbol == "time.sleep"
                   for f in by["trace-impure-host"])

    mut = {(f.scope, f.symbol) for f in by["trace-closure-mutation"]}
    assert ("kernel", "STATE") in mut
    assert ("kernel", "ACC.append") in mut
    assert ("make_step.step", "buffers.append") in mut
    assert ("make_step.step.add", "total") in mut   # nonlocal rebind

    # nothing in the non-jit function may fire
    assert not any(f.scope == "clean_host_code"
                   for fs in by.values() for f in fs)


def test_trace_safety_line_anchoring():
    mod = _fixture_module("bad_trace.py")
    findings = trace_safety.run([mod])
    for f in findings:
        line = mod.lines[f.line - 1]
        # every finding points at a line that names its symbol — either
        # the canonical token or the local alias quoted in the message
        # (`now() (= time.time) ...`)
        token = f.symbol.split("(")[0].split(".")[-1] or f.symbol
        local = f.message.split("(")[0].strip()
        assert token in line or (local and local in line), (f, line)


def test_trace_safety_pallas_kernel_fixture():
    """Pallas kernel bodies registered as op kernels are trace-safety
    clean: pl.program_id, .astype, scratch-ref stores through the
    kernel's own params, and — the carve-out — @pl.when-nested
    initializers writing `ref[:] = ...` through the ENCLOSING kernel's
    parameters. Real hazards in the same nesting shape still fire, and a
    justified suppression is honored."""
    mod = _fixture_module("pallas_kernel.py")
    by = _by_rule(trace_safety.run([mod]))

    # the clean kernel nest produces NO findings at all
    clean_scopes = ("fused_apply", "fused_apply.kernel",
                    "fused_apply.kernel._init")
    assert not any(f.scope in clean_scopes
                   for fs in by.values() for f in fs), \
        [f for fs in by.values() for f in fs if f.scope in clean_scopes]

    # negative controls: the carve-out is narrow
    mut = {(f.scope, f.symbol)
           for f in by.get("trace-closure-mutation", [])}
    assert ("bad_kernel_host_state.kernel",
            "_HOST_SIDE_ACC.append") in mut       # module-state mutator
    assert ("bad_kernel_host_state.kernel.inner",
            "captured") in mut                    # enclosing LOCAL store
    # subscript store through an enclosing PARAMETER in a nest with no
    # pallas_call: the carve-out is anchored on real Pallas builds only
    assert ("bad_plain_closure_param.step", "history") in mut
    imp = {(f.scope, f.symbol) for f in by.get("trace-impure-host", [])}
    assert ("bad_kernel_host_state.kernel", "os.environ.get") in imp
    # the justified suppression silences the .tolist() host capture
    assert not any(f.symbol == ".tolist"
                   for f in by.get("trace-host-capture", []))


def test_trace_safety_live_pallas_modules_clean():
    """The live kernel modules (ops/pallas_kernels.py, ops/fused.py,
    ops/pallas_attention.py) carry no trace-safety findings even when
    their kernels are treated as jit-reachable roots — the contract the
    register_op registrations in numpy_extension rely on."""
    for rel in ("incubator_mxnet_tpu/ops/pallas_kernels.py",
                "incubator_mxnet_tpu/ops/fused.py",
                "incubator_mxnet_tpu/ops/pallas_attention.py"):
        path = os.path.join(REPO, rel)
        with open(path) as f:
            src = f.read()
        # force every top-level function into the reachable set by
        # appending register_op roots for each def
        import re as _re
        names = _re.findall(r"^def (\w+)", src, _re.M)
        forced = src + "\nfrom incubator_mxnet_tpu.ops.registry import " \
            "register_op as _lint_reg\n" + "".join(
                f"_lint_reg('lint.{n}', {n})\n" for n in names)
        mod = Module(path, rel, forced)
        findings = [f for f in trace_safety.run([mod])
                    if not mod.suppressed(f.rule, f.line)]
        assert not findings, (rel, findings)


# ---------------------------------------------------------------------------
# 2b. lock-discipline fixtures
# ---------------------------------------------------------------------------
def test_lock_discipline_fixture_findings():
    mod = _fixture_module("bad_locks.py")
    by = _by_rule(lock_discipline.run([mod]))

    shared = {(f.scope, f.symbol) for f in by["lock-shared-mutation"]}
    assert ("Worker._run", "self._results") in shared      # thread side
    assert ("Worker.reset", "self._results") in shared     # consumer side
    assert ("Worker.bump", "self._count") in shared        # off-lock
    assert ("Worker._run", "WORK_STATS") in shared         # stats global
    # locked mutations are clean
    assert ("Worker.reset", "self._count") not in shared
    assert ("Worker.drop", "WORK_STATS") not in shared
    # the suppressed append in drop() must not fire
    assert ("Worker.drop", "self._results") not in shared
    # __init__ is exempt
    assert not any(s.endswith(".__init__") for s, _ in shared)

    cycles = by.get("lock-order-cycle", [])
    assert len(cycles) == 1
    assert "_LOCK_A" in cycles[0].message and "_LOCK_B" in cycles[0].message


def test_lock_discipline_no_cycle_without_opposite_order():
    mod = _fixture_module("bad_locks.py")
    # drop the B->A function: the cycle disappears, shared findings stay
    src = mod.source[:mod.source.index("def path_ba")]
    clipped = Module(mod.path, mod.relpath, src)
    by = _by_rule(lock_discipline.run([clipped]))
    assert "lock-order-cycle" not in by
    assert by["lock-shared-mutation"]


# ---------------------------------------------------------------------------
# 2c. registry-consistency fixtures (miniature repo tree)
# ---------------------------------------------------------------------------
def test_registry_consistency_fixture_findings():
    root = os.path.join(FIXTURES, "registry_repo")
    mods = analysis.load_modules(root, files=["pkg/mod.py"])
    by = _by_rule(registry_consistency.run(mods, root))

    assert {f.symbol for f in by["env-undocumented"]} == \
        {"MXNET_FIXTURE_SECRET"}
    assert {f.symbol for f in by["env-doc-stale"]} == {"MXNET_FIXTURE_GONE"}
    assert {f.symbol for f in by["fault-point-unwired"]} == {"beta.load"}
    assert {f.symbol for f in by["fault-point-undocumented"]} == \
        {"beta.load", "gamma.run"}
    assert {f.symbol for f in by["fault-point-unregistered"]} == \
        {"delta.crash"}
    assert {f.symbol for f in by["fault-doc-stale"]} == {"old.gone"}
    # fault coverage, both directions: alpha.save is drilled by the spec
    # literal in tests/cov_file.py and gamma.run by its quoted-point
    # mention; beta.load is never named -> untested. The fixture's
    # BAD_SPEC names an unregistered point -> inert spec.
    assert {f.symbol for f in by["fault-point-untested"]} == {"beta.load"}
    assert {f.symbol for f in by["fault-test-unknown-point"]} == \
        {"zeta.ghost"}
    assert {f.symbol for f in by["stats-key-untested"]} == {"misses"}
    # COLD_STATS' family never appears with its dotted prefix in any
    # test; "tele." does (cov_file.py), so only "cold" fires
    assert {f.symbol for f in by["stats-family-untested"]} == {"cold"}
    # telemetry surface: stats_group adoptions + literal object metrics vs
    # the OBSERVABILITY.md catalog (both directions) and tests
    assert {f.symbol for f in by["telemetry-metric-undocumented"]} == \
        {"tele.lonely"}
    assert {f.symbol for f in by["telemetry-doc-stale"]} == {"tele.ghost"}
    assert {f.symbol for f in by["telemetry-metric-untested"]} == \
        {"tele.obj_untested"}
    # memory census owners (mx.inspect.memory): literal owner= keywords
    # and mem.tag(...) first args vs the section-scoped "Census owners"
    # table, both directions — flat tokens never collide with the dotted
    # metric catalog above
    assert {f.symbol for f in by["mem-owner-undocumented"]} == \
        {"fixture_owner_secret"}
    assert {f.symbol for f in by["mem-owner-doc-stale"]} == \
        {"fixture_owner_ghost"}
    assert "fixture_tag_owner" not in {
        f.symbol for f in by["mem-owner-undocumented"]}
    # tune knob catalog (mx.tune): the KNOBS literal vs the section-scoped
    # TUNING.md "Knob catalog" table, both directions, plus MXNET_* reads
    # in knob-wired modules that are neither declared knob envs nor in
    # NON_TUNABLE_ENV
    assert {f.symbol for f in by["tune-knob-undocumented"]} == \
        {"fix.secret"}
    assert {f.symbol for f in by["tune-doc-stale"]} == {"fix.ghost"}
    assert "fix.off_section" not in {
        f.symbol for f in by["tune-doc-stale"]}
    assert {f.symbol for f in by["tune-env-undeclared"]} == \
        {"MXNET_FIXTURE_SECRET"}
    assert "MXNET_FIXTURE_KNOB" not in {
        f.symbol for f in by["tune-env-undeclared"]}


def test_stats_group_adoption_still_yields_stats_keys():
    """A `X_STATS = stats_group("x", {...})` adoption declares the same
    key surface as a bare dict literal: stats-key-untested still fires on
    unexercised keys (regression for the telemetry migration)."""
    root = os.path.join(FIXTURES, "registry_repo")
    mods = analysis.load_modules(root, files=["pkg/mod.py"])
    dicts = registry_consistency._stats_dicts(mods)
    by_name = {d[0]: d for d in dicts}
    assert "TELE_STATS" in by_name and "PIPE_STATS" in by_name
    assert set(by_name["TELE_STATS"][1]) == {"good", "lonely"}
    assert by_name["TELE_STATS"][4] == "tele"      # adopted family name
    assert by_name["PIPE_STATS"][4] is None        # bare dict: no family


# ---------------------------------------------------------------------------
# 2d. baseline workflow
# ---------------------------------------------------------------------------
def test_baseline_partitions_and_detects_stale():
    mod = _fixture_module("bad_locks.py")
    findings = lock_discipline.run([mod])
    target = next(f for f in findings if f.scope == "Worker.bump")
    bl = Baseline({target.ident: "intentional for the test",
                   "lock-shared-mutation:gone.py:X.y:self._z": "stale"})
    new, baselined, stale = bl.split(findings)
    assert target not in new and target in baselined
    assert stale == ["lock-shared-mutation:gone.py:X.y:self._z"]
    assert len(new) == len(findings) - 1


def test_baseline_ident_is_line_number_free():
    mod = _fixture_module("bad_trace.py")
    f = trace_safety.run([mod])[0]
    assert str(f.line) not in f.ident.split(":")  # stable across line drift
    # prepending a comment shifts every line; idents must not change
    shifted = Module(mod.path, mod.relpath, "# shim\n# shim\n" + mod.source)
    idents = {x.ident for x in trace_safety.run([mod])}
    idents_shifted = {x.ident for x in trace_safety.run([shifted])}
    assert idents == idents_shifted


def test_suppression_must_start_the_comment():
    """Prose that merely mentions the syntax is not a suppression."""
    mod = _fixture_module("bad_trace.py")
    src = mod.source.replace(
        "now = time.time()",
        "now = time.time()  # TODO: maybe mxlint: disable=trace-impure-host")
    assert src != mod.source
    prosey = Module(mod.path, mod.relpath, src)
    by = _by_rule(trace_safety.run([prosey]))
    assert ("kernel", "time.time") in \
        {(f.scope, f.symbol) for f in by["trace-impure-host"]}


def test_file_level_suppression():
    mod = _fixture_module("bad_trace.py")
    src = ("# mxlint: disable-file=trace-impure-host\n" + mod.source)
    silenced = Module(mod.path, mod.relpath, src)
    by = _by_rule(trace_safety.run([silenced]))
    assert "trace-impure-host" not in by
    assert "trace-host-capture" in by      # other rules unaffected


# ---------------------------------------------------------------------------
# 3. CLI contract
# ---------------------------------------------------------------------------
def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxlint", *args],
        capture_output=True, text=True, timeout=300, cwd=REPO)


def test_cli_quick_json_smoke():
    r = _run_cli("--quick", "--json")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    assert data["counts"]["new"] == 0
    assert data["scope"] == "quick"
    assert set(data["passes"]) == set(analysis.PASS_FAMILIES)
    for f in data["baselined"]:
        assert {"rule", "path", "line", "message", "ident"} <= set(f)


def test_cli_full_run_is_clean():
    r = _run_cli("--json")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    assert data["counts"]["new"] == 0
    assert data["counts"]["stale_baseline"] == 0
    assert data["scope"] == "full"


def test_cli_changed_mode_runs():
    r = _run_cli("--changed", "--json")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    # registry passes always run repo-wide, even with no changed files
    assert data["scope"] == "changed"


def test_partial_scope_never_reports_stale_baseline():
    """A --quick/--changed scope skips files whose baselined findings
    therefore aren't produced — that must NOT read as 'finding fixed'."""
    new, baselined, stale = analysis.run_all(
        root=REPO, files=["incubator_mxnet_tpu/serve/metrics.py"],
        baseline=os.path.join(REPO, analysis.DEFAULT_BASELINE))
    assert not new
    assert stale == []

    r = _run_cli("--quick", "--write-baseline")
    assert r.returncode == 2     # partial scope must refuse to rewrite


def test_cli_exit_one_on_violation(tmp_path):
    # a synthetic repo with one seeded violation: exit status must be 1
    pkg = tmp_path / "incubator_mxnet_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "bad.py").write_text(
        "import jax\nimport time\n\n"
        "def k(x):\n    return x + time.time()\n\n"
        "j = jax.jit(k)\n")
    (tmp_path / "docs").mkdir()
    r = _run_cli("--root", str(tmp_path), "--no-baseline", "--json")
    assert r.returncode == 1, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    assert data["counts"]["new"] == 1
    assert data["findings"][0]["rule"] == "trace-impure-host"


# ---------------------------------------------------------------------------
# donation-safety fixture
# ---------------------------------------------------------------------------
def _run_suppressed(pass_mod, mod):
    """Pass output minus inline suppressions (run_all's central filter)."""
    return [f for f in pass_mod.run([mod])
            if not mod.suppressed(f.rule, f.line)]


def test_donation_safety_fixture_findings():
    mod = _fixture_module("bad_donation.py")
    by = _by_rule(_run_suppressed(donation_safety, mod))

    use = {(f.scope, f.symbol) for f in by["donation-use-after-donate"]}
    assert ("Engine.use_after_donate", "kb") in use
    # buffers fetched once outside the loop: iteration 2 re-donates dead
    # arrays (both positions)
    assert ("Engine.redonate_in_loop", "kb") in use
    assert ("Engine.redonate_in_loop", "vb") in use
    # a module-level program donated via `step(w, g)` then `w` read
    assert ("module_level_use", "w") in use
    # negatives: rebinding from output / exclusive branches / suppression
    scopes = {f.scope for f in by["donation-use-after-donate"]}
    assert "Engine.rebind_is_clean" not in scopes
    assert "Engine.branches_are_exclusive" not in scopes
    assert "Engine.suppressed_use" not in scopes

    err = {(f.scope, f.symbol) for f in by["donation-unrestored-on-error"]}
    assert ("Engine.swallow_without_restore", "self._decode") in err
    # the donated call one helper down still counts (the PR-14 shape)
    assert ("Engine.swallow_via_helper", "self.run_wave()") in err
    err_scopes = {f.scope for f in by["donation-unrestored-on-error"]}
    assert "Engine.restore_is_clean" not in err_scopes
    assert "Engine.reraise_is_clean" not in err_scopes
    assert "Engine.narrow_handler_is_clean" not in err_scopes


def test_retrace_hazard_fixture_findings():
    mod = _fixture_module("bad_retrace.py")
    by = _by_rule(_run_suppressed(retrace_hazard, mod))

    shape = {(f.scope, f.symbol) for f in by["retrace-shape-from-data"]}
    assert ("Engine.shape_leak_loop", "zeros:len(...)") in shape
    assert ("Engine.shape_attr_leak", "arg1:buf.shape") in shape
    assert "Engine.padded_is_clean" not in {s for s, _ in shape}

    static = {(f.scope, f.symbol)
              for f in by["retrace-unstable-static-arg"]}
    assert ("Engine.static_from_data", "static1") in static
    # unhashable literals fire OUTSIDE steady loops too (TypeError class)
    assert ("unhashable_static_outside_loop", "static1") in static
    assert "Engine.static_constant_is_clean" not in {s for s, _ in static}

    tree = {f.scope for f in by["retrace-unordered-pytree"]}
    assert "Engine.unordered_tree" in tree
    assert "Engine.sorted_tree_is_clean" not in tree


# ---------------------------------------------------------------------------
# hand-reverted real bugs (ISSUE 20 acceptance): re-introduce each PR-14
# bug class in a SCRATCH copy of the live engine source; the pass must
# flag the scratch copy while the live file stays clean
# ---------------------------------------------------------------------------
def _scratch_engine(replacing, replacement):
    path = os.path.join(REPO, "incubator_mxnet_tpu", "serve",
                        "continuous.py")
    with open(path) as f:
        src = f.read()
    assert replacing in src, "hand-revert anchor drifted; update the test"
    return Module(path, os.path.join("incubator_mxnet_tpu", "serve",
                                     "continuous.py"),
                  src.replace(replacing, replacement))


def test_donation_safety_flags_reverted_pr14_pool_bug():
    # the PR-14 bug: the engine loop's exception handler forgot
    # pool.reallocate(), leaving donated KV slabs dead for every later
    # wave. Reverting the fix must produce exactly the finding class
    # this pass was built for — anchored at the loop's broad handler.
    mod = _scratch_engine("self.pool.reallocate()", "pass")
    by = _by_rule(_run_suppressed(donation_safety, mod))
    hits = [f for f in by.get("donation-unrestored-on-error", ())
            if f.scope.endswith("._loop")]
    assert hits, "reverted pool.reallocate() bug was not flagged"
    # the live file (reallocate present) is clean in that scope
    live = _fixture_live_engine()
    by_live = _by_rule(_run_suppressed(donation_safety, live))
    assert not [f for f in by_live.get("donation-unrestored-on-error", ())
                if f.scope.endswith("._loop")]


def test_retrace_hazard_flags_planted_shape_drift():
    # the PR-14-adjacent drift: sizing the prefill batch from len(cold)
    # instead of the fixed lane count retraces every distinct batch size
    mod = _scratch_engine("toks = _np.zeros((P, W), dtype=_np.int32)",
                          "toks = _np.zeros((len(cold), W), "
                          "dtype=_np.int32)")
    by = _by_rule(_run_suppressed(retrace_hazard, mod))
    assert any(f.symbol == "zeros:len(...)"
               for f in by.get("retrace-shape-from-data", ()))
    live = _fixture_live_engine()
    by_live = _by_rule(_run_suppressed(retrace_hazard, live))
    assert not by_live.get("retrace-shape-from-data")


def _fixture_live_engine():
    path = os.path.join(REPO, "incubator_mxnet_tpu", "serve",
                        "continuous.py")
    with open(path) as f:
        src = f.read()
    return Module(path, os.path.join("incubator_mxnet_tpu", "serve",
                                     "continuous.py"), src)


def test_cli_timing_budget():
    """The full analysis run must fit its CI budget (ISSUE 20): the
    analyzer re-parses the whole package per run, so an accidentally
    quadratic pass shows up here long before it stalls the tier-1
    suite. --timing enforces the 30s default budget (exit 1 when over)
    and prints the wall time for the log."""
    r = _run_cli("--timing")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    assert "mxlint --timing: full run" in r.stdout
    assert "OVER BUDGET" not in r.stdout
    # a deliberately impossible budget must fail loudly, proving the
    # guard is live (not a formatting-only flag)
    r = _run_cli("--timing", "--budget-s", "0.001")
    assert r.returncode == 1
    assert "OVER BUDGET" in r.stdout
