"""mxlint (ISSUE 5): the analyzer gates tier-1.

Three layers:
  1. the REPO IS CLEAN — `run_all` over the live package with the
     committed baseline yields zero new findings and zero stale baseline
     entries, so any new violation fails the build;
  2. each pass family detects its seeded fixture violations
     (tests/lint_fixtures/) exactly where expected, and suppressions /
     the baseline silence them;
  3. the CLI contract: `python -m tools.mxlint --quick --json` emits
     machine-readable findings and exit status 0 on the clean tree.

The analyzer is import-light (stdlib ast only), so these tests cost
parse time, not jax time.
"""
import json
import os
import subprocess
import sys

import pytest

from incubator_mxnet_tpu import analysis
from incubator_mxnet_tpu.analysis import (lock_discipline,
                                          registry_consistency,
                                          trace_safety)
from incubator_mxnet_tpu.analysis.core import Baseline, Module

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
FIXTURES = os.path.join(REPO, "tests", "lint_fixtures")


def _fixture_module(name):
    path = os.path.join(FIXTURES, name)
    with open(path) as f:
        src = f.read()
    return Module(path, os.path.join("tests", "lint_fixtures", name), src)


def _by_rule(findings):
    out = {}
    for f in findings:
        out.setdefault(f.rule, []).append(f)
    return out


# ---------------------------------------------------------------------------
# 1. the live repo is clean under the committed baseline
# ---------------------------------------------------------------------------
def test_repo_is_clean_under_baseline():
    new, baselined, stale = analysis.run_all(
        root=REPO,
        baseline=os.path.join(REPO, analysis.DEFAULT_BASELINE))
    assert not new, "new mxlint findings:\n" + "\n".join(
        f"  {f.path}:{f.line}: [{f.rule}] {f.message}" for f in new)
    assert not stale, ("baseline entries whose finding no longer exists — "
                       "delete them from tools/mxlint_baseline.json:\n"
                       + "\n".join(f"  {s}" for s in stale))
    # the baseline documents intentional patterns; it must stay small
    assert len(baselined) < 30


def test_every_rule_name_is_registered():
    for fam in analysis.PASS_FAMILIES.values():
        for rule in fam.RULES:
            assert rule in analysis.ALL_RULES
    assert len(set(analysis.ALL_RULES)) == len(analysis.ALL_RULES)


# ---------------------------------------------------------------------------
# 2a. trace-safety fixtures
# ---------------------------------------------------------------------------
def test_trace_safety_fixture_findings():
    mod = _fixture_module("bad_trace.py")
    by = _by_rule(trace_safety.run([mod]))

    cap = {(f.scope, f.symbol) for f in by["trace-host-capture"]}
    assert ("kernel", "float(scale)") in cap
    assert ("kernel", ".item") in cap
    assert ("helper", "np.asarray") in cap     # transitive reachability

    imp = {(f.scope, f.symbol) for f in by["trace-impure-host"]}
    assert ("kernel", "time.time") in imp
    assert ("kernel", "random.random") in imp
    assert ("kernel", "os.environ.get") in imp
    # `from time import time as now` resolves to the stdlib and fires ...
    assert ("kernel", "numpy.asarray") in \
        {(f.scope, f.symbol) for f in by["trace-host-capture"]}
    assert any(f.symbol == "time.time" and "now()" in f.message
               for f in by["trace-impure-host"])
    # ... while `from jax import random as jxrandom` is NOT the stdlib
    assert not any("jxrandom" in f.symbol or "PRNGKey" in f.symbol
                   for fs in by.values() for f in fs)
    # the suppressed time.sleep(0) must NOT appear
    assert not any(f.symbol == "time.sleep"
                   for f in by["trace-impure-host"])

    mut = {(f.scope, f.symbol) for f in by["trace-closure-mutation"]}
    assert ("kernel", "STATE") in mut
    assert ("kernel", "ACC.append") in mut
    assert ("make_step.step", "buffers.append") in mut
    assert ("make_step.step.add", "total") in mut   # nonlocal rebind

    # nothing in the non-jit function may fire
    assert not any(f.scope == "clean_host_code"
                   for fs in by.values() for f in fs)


def test_trace_safety_line_anchoring():
    mod = _fixture_module("bad_trace.py")
    findings = trace_safety.run([mod])
    for f in findings:
        line = mod.lines[f.line - 1]
        # every finding points at a line that names its symbol — either
        # the canonical token or the local alias quoted in the message
        # (`now() (= time.time) ...`)
        token = f.symbol.split("(")[0].split(".")[-1] or f.symbol
        local = f.message.split("(")[0].strip()
        assert token in line or (local and local in line), (f, line)


def test_trace_safety_pallas_kernel_fixture():
    """Pallas kernel bodies registered as op kernels are trace-safety
    clean: pl.program_id, .astype, scratch-ref stores through the
    kernel's own params, and — the carve-out — @pl.when-nested
    initializers writing `ref[:] = ...` through the ENCLOSING kernel's
    parameters. Real hazards in the same nesting shape still fire, and a
    justified suppression is honored."""
    mod = _fixture_module("pallas_kernel.py")
    by = _by_rule(trace_safety.run([mod]))

    # the clean kernel nest produces NO findings at all
    clean_scopes = ("fused_apply", "fused_apply.kernel",
                    "fused_apply.kernel._init")
    assert not any(f.scope in clean_scopes
                   for fs in by.values() for f in fs), \
        [f for fs in by.values() for f in fs if f.scope in clean_scopes]

    # negative controls: the carve-out is narrow
    mut = {(f.scope, f.symbol)
           for f in by.get("trace-closure-mutation", [])}
    assert ("bad_kernel_host_state.kernel",
            "_HOST_SIDE_ACC.append") in mut       # module-state mutator
    assert ("bad_kernel_host_state.kernel.inner",
            "captured") in mut                    # enclosing LOCAL store
    # subscript store through an enclosing PARAMETER in a nest with no
    # pallas_call: the carve-out is anchored on real Pallas builds only
    assert ("bad_plain_closure_param.step", "history") in mut
    imp = {(f.scope, f.symbol) for f in by.get("trace-impure-host", [])}
    assert ("bad_kernel_host_state.kernel", "os.environ.get") in imp
    # the justified suppression silences the .tolist() host capture
    assert not any(f.symbol == ".tolist"
                   for f in by.get("trace-host-capture", []))


def test_trace_safety_live_pallas_modules_clean():
    """The live kernel modules (ops/pallas_kernels.py, ops/fused.py,
    ops/pallas_attention.py) carry no trace-safety findings even when
    their kernels are treated as jit-reachable roots — the contract the
    register_op registrations in numpy_extension rely on."""
    for rel in ("incubator_mxnet_tpu/ops/pallas_kernels.py",
                "incubator_mxnet_tpu/ops/fused.py",
                "incubator_mxnet_tpu/ops/pallas_attention.py"):
        path = os.path.join(REPO, rel)
        with open(path) as f:
            src = f.read()
        # force every top-level function into the reachable set by
        # appending register_op roots for each def
        import re as _re
        names = _re.findall(r"^def (\w+)", src, _re.M)
        forced = src + "\nfrom incubator_mxnet_tpu.ops.registry import " \
            "register_op as _lint_reg\n" + "".join(
                f"_lint_reg('lint.{n}', {n})\n" for n in names)
        mod = Module(path, rel, forced)
        findings = [f for f in trace_safety.run([mod])
                    if not mod.suppressed(f.rule, f.line)]
        assert not findings, (rel, findings)


# ---------------------------------------------------------------------------
# 2b. lock-discipline fixtures
# ---------------------------------------------------------------------------
def test_lock_discipline_fixture_findings():
    mod = _fixture_module("bad_locks.py")
    by = _by_rule(lock_discipline.run([mod]))

    shared = {(f.scope, f.symbol) for f in by["lock-shared-mutation"]}
    assert ("Worker._run", "self._results") in shared      # thread side
    assert ("Worker.reset", "self._results") in shared     # consumer side
    assert ("Worker.bump", "self._count") in shared        # off-lock
    assert ("Worker._run", "WORK_STATS") in shared         # stats global
    # locked mutations are clean
    assert ("Worker.reset", "self._count") not in shared
    assert ("Worker.drop", "WORK_STATS") not in shared
    # the suppressed append in drop() must not fire
    assert ("Worker.drop", "self._results") not in shared
    # __init__ is exempt
    assert not any(s.endswith(".__init__") for s, _ in shared)

    cycles = by.get("lock-order-cycle", [])
    assert len(cycles) == 1
    assert "_LOCK_A" in cycles[0].message and "_LOCK_B" in cycles[0].message


def test_lock_discipline_no_cycle_without_opposite_order():
    mod = _fixture_module("bad_locks.py")
    # drop the B->A function: the cycle disappears, shared findings stay
    src = mod.source[:mod.source.index("def path_ba")]
    clipped = Module(mod.path, mod.relpath, src)
    by = _by_rule(lock_discipline.run([clipped]))
    assert "lock-order-cycle" not in by
    assert by["lock-shared-mutation"]


# ---------------------------------------------------------------------------
# 2c. registry-consistency fixtures (miniature repo tree)
# ---------------------------------------------------------------------------
def test_registry_consistency_fixture_findings():
    root = os.path.join(FIXTURES, "registry_repo")
    mods = analysis.load_modules(root, files=["pkg/mod.py"])
    by = _by_rule(registry_consistency.run(mods, root))

    assert {f.symbol for f in by["env-undocumented"]} == \
        {"MXNET_FIXTURE_SECRET"}
    assert {f.symbol for f in by["env-doc-stale"]} == {"MXNET_FIXTURE_GONE"}
    assert {f.symbol for f in by["fault-point-unwired"]} == {"beta.load"}
    assert {f.symbol for f in by["fault-point-undocumented"]} == \
        {"beta.load", "gamma.run"}
    assert {f.symbol for f in by["fault-point-unregistered"]} == \
        {"delta.crash"}
    assert {f.symbol for f in by["fault-doc-stale"]} == {"old.gone"}
    assert {f.symbol for f in by["stats-key-untested"]} == {"misses"}
    # telemetry surface: stats_group adoptions + literal object metrics vs
    # the OBSERVABILITY.md catalog (both directions) and tests
    assert {f.symbol for f in by["telemetry-metric-undocumented"]} == \
        {"tele.lonely"}
    assert {f.symbol for f in by["telemetry-doc-stale"]} == {"tele.ghost"}
    assert {f.symbol for f in by["telemetry-metric-untested"]} == \
        {"tele.obj_untested"}
    # memory census owners (mx.inspect.memory): literal owner= keywords
    # and mem.tag(...) first args vs the section-scoped "Census owners"
    # table, both directions — flat tokens never collide with the dotted
    # metric catalog above
    assert {f.symbol for f in by["mem-owner-undocumented"]} == \
        {"fixture_owner_secret"}
    assert {f.symbol for f in by["mem-owner-doc-stale"]} == \
        {"fixture_owner_ghost"}
    assert "fixture_tag_owner" not in {
        f.symbol for f in by["mem-owner-undocumented"]}
    # tune knob catalog (mx.tune): the KNOBS literal vs the section-scoped
    # TUNING.md "Knob catalog" table, both directions, plus MXNET_* reads
    # in knob-wired modules that are neither declared knob envs nor in
    # NON_TUNABLE_ENV
    assert {f.symbol for f in by["tune-knob-undocumented"]} == \
        {"fix.secret"}
    assert {f.symbol for f in by["tune-doc-stale"]} == {"fix.ghost"}
    assert "fix.off_section" not in {
        f.symbol for f in by["tune-doc-stale"]}
    assert {f.symbol for f in by["tune-env-undeclared"]} == \
        {"MXNET_FIXTURE_SECRET"}
    assert "MXNET_FIXTURE_KNOB" not in {
        f.symbol for f in by["tune-env-undeclared"]}


def test_stats_group_adoption_still_yields_stats_keys():
    """A `X_STATS = stats_group("x", {...})` adoption declares the same
    key surface as a bare dict literal: stats-key-untested still fires on
    unexercised keys (regression for the telemetry migration)."""
    root = os.path.join(FIXTURES, "registry_repo")
    mods = analysis.load_modules(root, files=["pkg/mod.py"])
    dicts = registry_consistency._stats_dicts(mods)
    by_name = {d[0]: d for d in dicts}
    assert "TELE_STATS" in by_name and "PIPE_STATS" in by_name
    assert set(by_name["TELE_STATS"][1]) == {"good", "lonely"}
    assert by_name["TELE_STATS"][4] == "tele"      # adopted family name
    assert by_name["PIPE_STATS"][4] is None        # bare dict: no family


# ---------------------------------------------------------------------------
# 2d. baseline workflow
# ---------------------------------------------------------------------------
def test_baseline_partitions_and_detects_stale():
    mod = _fixture_module("bad_locks.py")
    findings = lock_discipline.run([mod])
    target = next(f for f in findings if f.scope == "Worker.bump")
    bl = Baseline({target.ident: "intentional for the test",
                   "lock-shared-mutation:gone.py:X.y:self._z": "stale"})
    new, baselined, stale = bl.split(findings)
    assert target not in new and target in baselined
    assert stale == ["lock-shared-mutation:gone.py:X.y:self._z"]
    assert len(new) == len(findings) - 1


def test_baseline_ident_is_line_number_free():
    mod = _fixture_module("bad_trace.py")
    f = trace_safety.run([mod])[0]
    assert str(f.line) not in f.ident.split(":")  # stable across line drift
    # prepending a comment shifts every line; idents must not change
    shifted = Module(mod.path, mod.relpath, "# shim\n# shim\n" + mod.source)
    idents = {x.ident for x in trace_safety.run([mod])}
    idents_shifted = {x.ident for x in trace_safety.run([shifted])}
    assert idents == idents_shifted


def test_suppression_must_start_the_comment():
    """Prose that merely mentions the syntax is not a suppression."""
    mod = _fixture_module("bad_trace.py")
    src = mod.source.replace(
        "now = time.time()",
        "now = time.time()  # TODO: maybe mxlint: disable=trace-impure-host")
    assert src != mod.source
    prosey = Module(mod.path, mod.relpath, src)
    by = _by_rule(trace_safety.run([prosey]))
    assert ("kernel", "time.time") in \
        {(f.scope, f.symbol) for f in by["trace-impure-host"]}


def test_file_level_suppression():
    mod = _fixture_module("bad_trace.py")
    src = ("# mxlint: disable-file=trace-impure-host\n" + mod.source)
    silenced = Module(mod.path, mod.relpath, src)
    by = _by_rule(trace_safety.run([silenced]))
    assert "trace-impure-host" not in by
    assert "trace-host-capture" in by      # other rules unaffected


# ---------------------------------------------------------------------------
# 3. CLI contract
# ---------------------------------------------------------------------------
def _run_cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "tools.mxlint", *args],
        capture_output=True, text=True, timeout=300, cwd=REPO)


def test_cli_quick_json_smoke():
    r = _run_cli("--quick", "--json")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    assert data["counts"]["new"] == 0
    assert data["scope"] == "quick"
    assert set(data["passes"]) == set(analysis.PASS_FAMILIES)
    for f in data["baselined"]:
        assert {"rule", "path", "line", "message", "ident"} <= set(f)


def test_cli_full_run_is_clean():
    r = _run_cli("--json")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    assert data["counts"]["new"] == 0
    assert data["counts"]["stale_baseline"] == 0
    assert data["scope"] == "full"


def test_cli_changed_mode_runs():
    r = _run_cli("--changed", "--json")
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    # registry passes always run repo-wide, even with no changed files
    assert data["scope"] == "changed"


def test_partial_scope_never_reports_stale_baseline():
    """A --quick/--changed scope skips files whose baselined findings
    therefore aren't produced — that must NOT read as 'finding fixed'."""
    new, baselined, stale = analysis.run_all(
        root=REPO, files=["incubator_mxnet_tpu/serve/metrics.py"],
        baseline=os.path.join(REPO, analysis.DEFAULT_BASELINE))
    assert not new
    assert stale == []

    r = _run_cli("--quick", "--write-baseline")
    assert r.returncode == 2     # partial scope must refuse to rewrite


def test_cli_exit_one_on_violation(tmp_path):
    # a synthetic repo with one seeded violation: exit status must be 1
    pkg = tmp_path / "incubator_mxnet_tpu"
    pkg.mkdir()
    (pkg / "__init__.py").write_text("")
    (pkg / "bad.py").write_text(
        "import jax\nimport time\n\n"
        "def k(x):\n    return x + time.time()\n\n"
        "j = jax.jit(k)\n")
    (tmp_path / "docs").mkdir()
    r = _run_cli("--root", str(tmp_path), "--no-baseline", "--json")
    assert r.returncode == 1, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(r.stdout)
    assert data["counts"]["new"] == 1
    assert data["findings"][0]["rule"] == "trace-impure-host"
