"""Dist-overlap measurement (VERDICT Next #5): the bucketed-allreduce /
backward interleave hides a measurable fraction of comm on the 8-CPU
virtual mesh.

Runs benchmark/overlap_bench.py --quick in a fresh process (clean XLA pool,
no interference from the rest of the suite's device state) and asserts the
hidden-comm fraction is positive — the claim the committed artifact
benchmark/results/overlap_r07_cpu8.json records for the full run.
"""
import json
import os
import subprocess
import sys


def test_overlap_bench_hidden_comm_positive(tmp_path):
    out = tmp_path / "overlap_quick.json"
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "overlap_bench.py")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # the bench sets its own 8-device flag when absent; the conftest may
    # already have set it in this env — both paths give 8 devices
    r = subprocess.run(
        [sys.executable, script, "--quick", "--out", str(out)],
        env=env, capture_output=True, text=True, timeout=600)
    assert r.returncode == 0, f"stdout={r.stdout}\nstderr={r.stderr}"
    data = json.loads(out.read_text())
    assert data["meta"]["devices"] == 8
    ov = data["overlap"]
    assert ov["backward_ms"] > 0 and ov["comm_ms"] > 0
    # the event-based hidden fraction: some of the bucketed reduction
    # provably executed while the async-dispatched backward was still in
    # flight. Were dispatch synchronous, this would be exactly 0.
    assert ov["hidden_comm_fraction"] > 0.0, ov
    assert len(ov["trials"]) >= 3
    # wall-clock deltas ride along (noise-bounded on a 2-core host; no
    # assertion beyond presence)
    assert "wallclock_hidden_fraction" in ov


def test_committed_overlap_artifact_retires_loopback_numbers():
    """The r7 artifact exists, carries the per-bucket timeline, and its
    measured hidden fraction is positive (the loopback bandwidth file it
    retires had no overlap measurement at all)."""
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "results",
        "overlap_r07_cpu8.json")
    data = json.load(open(path))
    assert data["overlap"]["hidden_comm_fraction"] > 0
    tl = data["bucketed_allreduce"]["per_bucket_timeline"]
    assert len(tl) == data["bucketed_allreduce"]["n_buckets"]
    assert all(row["ms"] > 0 for row in tl)
