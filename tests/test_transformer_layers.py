"""gluon transformer layers + StableHLO export tests."""
import os

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn


def test_multihead_attention_shapes_and_grad():
    attn = nn.MultiHeadAttention(units=32, num_heads=4)
    attn.initialize()
    x = mx.np.array(np.random.randn(2, 10, 32).astype(np.float32))
    with mx.autograd.record():
        out = attn(x)
        out.sum().backward()
    assert out.shape == (2, 10, 32)
    g = attn.query_proj.weight.grad()
    assert np.isfinite(g.asnumpy()).all() and np.abs(g.asnumpy()).sum() > 0


def test_mha_causal_masks_future():
    attn = nn.MultiHeadAttention(units=16, num_heads=2)
    attn.initialize()
    x = np.random.randn(1, 6, 16).astype(np.float32)
    full = attn(mx.np.array(x), causal=True).asnumpy()
    # truncating the future must not change earlier positions under causal
    trunc = attn(mx.np.array(x[:, :4]), causal=True).asnumpy()
    np.testing.assert_allclose(full[:, :4], trunc, rtol=1e-4, atol=1e-5)


def test_mha_cross_attention():
    attn = nn.MultiHeadAttention(units=16, num_heads=2)
    attn.initialize()
    q = mx.np.array(np.random.randn(2, 5, 16).astype(np.float32))
    kv = mx.np.array(np.random.randn(2, 9, 16).astype(np.float32))
    out = attn(q, kv, kv)
    assert out.shape == (2, 5, 16)


def test_encoder_cell_hybridized_parity():
    cell = nn.TransformerEncoderCell(units=32, hidden_size=64, num_heads=4,
                                     dropout=0.0)
    cell.initialize()
    x = mx.np.array(np.random.randn(2, 7, 32).astype(np.float32))
    ref = cell(x).asnumpy()
    cell.hybridize()
    got = cell(x).asnumpy()
    got2 = cell(x).asnumpy()
    np.testing.assert_allclose(ref, got, rtol=2e-4, atol=2e-5)
    np.testing.assert_allclose(ref, got2, rtol=2e-4, atol=2e-5)


def test_decoder_cell():
    cell = nn.TransformerDecoderCell(units=16, hidden_size=32, num_heads=2,
                                     dropout=0.0)
    cell.initialize()
    x = mx.np.array(np.random.randn(1, 5, 16).astype(np.float32))
    mem = mx.np.array(np.random.randn(1, 8, 16).astype(np.float32))
    out = cell(x, mem)
    assert out.shape == (1, 5, 16)


def test_positional_embedding():
    pe = nn.PositionalEmbedding(max_length=32, units=8)
    pe.initialize()
    x = mx.np.zeros((2, 10, 8))
    out = pe(x)
    assert out.shape == (2, 10, 8)
    with pytest.raises(mx.MXNetError):
        pe(mx.np.zeros((1, 64, 8)))


def test_encoder_stack_trains():
    net = nn.HybridSequential()
    net.add(nn.TransformerEncoderCell(16, 32, 2, dropout=0.0),
            nn.TransformerEncoderCell(16, 32, 2, dropout=0.0))
    net.initialize()
    net.hybridize()
    tr = gluon.Trainer(net.collect_params(), "adam", {"learning_rate": 1e-2})
    x = mx.np.array(np.random.randn(2, 6, 16).astype(np.float32))
    tgt = mx.np.array(np.random.randn(2, 6, 16).astype(np.float32))
    first = None
    for _ in range(15):
        with mx.autograd.record():
            L = ((net(x) - tgt) ** 2).mean()
        L.backward()
        tr.step(2)
        if first is None:
            first = float(L.asnumpy())
    assert float(L.asnumpy()) < first


def test_export_stablehlo(tmp_path):
    net = nn.HybridSequential()
    net.add(nn.Dense(8, activation="relu", in_units=4), nn.Dense(2))
    net.initialize()
    net.hybridize()
    x = mx.np.array(np.random.randn(2, 4).astype(np.float32))
    net(x)
    files = net.export(str(tmp_path / "model"), example_inputs=x)
    assert isinstance(files, tuple) and len(files) == 4
    params_file, hlo_file = files[0], files[1]
    # without example_inputs: params only, still a tuple
    (only_params,) = net.export(str(tmp_path / "model2"))
    assert os.path.exists(only_params)
    assert os.path.exists(params_file)
    assert os.path.exists(hlo_file)
    text = open(hlo_file).read()
    assert "stablehlo" in text and "dot_general" in text


def test_decoder_self_mask():
    """Regression: decoder accepts a padding self-mask combined with causal;
    NDArray kwargs to npx ops unwrap correctly."""
    cell = nn.TransformerDecoderCell(16, 32, 2, dropout=0.0)
    cell.initialize()
    x = mx.np.array(np.random.randn(1, 4, 16).astype(np.float32))
    mem = mx.np.array(np.random.randn(1, 6, 16).astype(np.float32))
    mask = mx.np.array(np.ones((4, 4), bool))
    out = cell(x, mem, self_mask=mask)
    assert out.shape == (1, 4, 16)
    # padding mask actually masks: zero out last position for all queries
    pad_mask = np.ones((4, 4), bool)
    pad_mask[:, 3] = False
    out_masked = cell(x, mem, self_mask=mx.np.array(pad_mask)).asnumpy()
    # first rows (which never attended pos 3 due to causal) are unchanged
    np.testing.assert_allclose(out.asnumpy()[:, :3], out_masked[:, :3],
                               rtol=1e-5, atol=1e-6)
    assert not np.allclose(out.asnumpy()[:, 3], out_masked[:, 3])
