"""Control flow ops + exception semantics (≙ reference
tests/python/unittest/test_contrib_control_flow.py + test_exc_handling.py)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import npx


def test_foreach_basic():
    data = mx.np.array(np.arange(6, dtype=np.float32).reshape(3, 2))
    init = mx.np.zeros((2,))

    def body(x, state):
        new = state + x
        return new, new

    outs, final = npx.foreach(body, data, init)
    np.testing.assert_allclose(final.asnumpy(), [6.0, 9.0])  # cumsum end
    np.testing.assert_allclose(outs.asnumpy()[-1], [6.0, 9.0])
    np.testing.assert_allclose(outs.asnumpy()[0], [0.0, 1.0])


def test_foreach_multi_state():
    data = mx.np.array(np.ones((4, 2), np.float32))
    s0 = [mx.np.zeros((2,)), mx.np.ones((2,))]

    def body(x, states):
        a, b = states
        return a + b, [a + x, b * 2]

    outs, fin = npx.foreach(body, data, s0)
    assert outs.shape == (4, 2)
    np.testing.assert_allclose(fin[0].asnumpy(), [4.0, 4.0])
    np.testing.assert_allclose(fin[1].asnumpy(), [16.0, 16.0])


def test_foreach_grad():
    """foreach is differentiable (lax.scan vjp) through the tape."""
    data = mx.np.array(np.array([[1.0], [2.0], [3.0]], np.float32))
    data.attach_grad()

    def body(x, state):
        new = state * x
        return new, new

    with mx.autograd.record():
        outs, final = npx.foreach(body, data, mx.np.ones((1,)))
        loss = final.sum()  # = prod(data)
    loss.backward()
    # d(prod)/dx_i = prod / x_i
    np.testing.assert_allclose(data.grad.asnumpy().ravel(),
                               [6.0, 3.0, 2.0], rtol=1e-5)


def test_while_loop():
    def cond(i, s):
        return i < 5

    def body(i, s):
        return i + 1, s * 2

    _, (i_fin, s_fin) = npx.while_loop(
        cond, body, [mx.np.array(0.0), mx.np.array(1.0)])
    assert float(i_fin.asnumpy()) == 5.0
    assert float(s_fin.asnumpy()) == 32.0


def test_cond():
    x = mx.np.array(np.array([2.0], np.float32))
    out = npx.cond(mx.np.array(True), lambda v: v * 10, lambda v: v - 10,
                   inputs=x)
    np.testing.assert_allclose(out.asnumpy(), [20.0])
    out = npx.cond(mx.np.array(False), lambda v: v * 10, lambda v: v - 10,
                   inputs=x)
    np.testing.assert_allclose(out.asnumpy(), [-8.0])


def test_cond_grad():
    x = mx.np.array(np.array([3.0], np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = npx.cond(mx.np.array(True), lambda v: v * v, lambda v: v,
                     inputs=x)
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), [6.0])


def test_foreach_in_hybrid_block():
    """Control flow inside a hybridized block compiles into the cached op."""
    from incubator_mxnet_tpu import gluon
    from incubator_mxnet_tpu.gluon import nn

    class CumulativeNet(gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.proj = nn.Dense(4, in_units=4)

        def forward(self, seq):
            def body(x, state):
                new = state + self.proj(x)
                return new, new
            outs, final = npx.foreach(body, seq, mx.np.zeros((2, 4)))
            return final

    net = CumulativeNet()
    net.initialize()
    seq = mx.np.array(np.random.randn(5, 2, 4).astype(np.float32))
    ref = net(seq).asnumpy()
    net.hybridize()
    got = net(seq).asnumpy()
    got2 = net(seq).asnumpy()  # cached path
    np.testing.assert_allclose(ref, got, rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(ref, got2, rtol=1e-5, atol=1e-6)


# ---------------------------------------------------------------------------
# error semantics (≙ test_exc_handling.py: typed errors surface in python)
# ---------------------------------------------------------------------------
def test_error_hierarchy():
    assert issubclass(mx.MXNetError, RuntimeError)
    from incubator_mxnet_tpu.base import ValueError_, TypeError_
    assert issubclass(ValueError_, ValueError)
    assert issubclass(ValueError_, mx.MXNetError)
    assert issubclass(TypeError_, TypeError)


def test_shape_error_surfaces():
    a = mx.np.ones((2, 3))
    b = mx.np.ones((4, 5))
    with pytest.raises(Exception):
        (a @ b).wait_to_read()


def test_ambiguous_truth_raises():
    with pytest.raises(mx.MXNetError):
        bool(mx.np.ones((2, 2)))


def test_backward_without_record_raises():
    x = mx.np.ones((2,))
    x.attach_grad()
    y = x * 2  # not recorded
    with pytest.raises(mx.MXNetError):
        y.backward()


def test_unknown_optimizer_metric_initializer():
    with pytest.raises(mx.MXNetError):
        mx.optimizer.create("definitely_not_real")
    with pytest.raises(mx.MXNetError):
        mx.metric.create("definitely_not_real")
    from incubator_mxnet_tpu import initializer
    with pytest.raises(mx.MXNetError):
        initializer.create("definitely_not_real")


def test_sync_batchnorm_cross_device_stats():
    """SyncBatchNorm inside shard_map reduces batch stats over dp
    (≙ contrib SyncBatchNorm's cross-device barrier semantics)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.ops import nn as _nn

    rng = np.random.default_rng(0)
    x = rng.standard_normal((8, 4)).astype(np.float32) * 3 + 1
    gamma = np.ones(4, np.float32)
    beta = np.zeros(4, np.float32)
    rm = np.zeros(4, np.float32)
    rv = np.ones(4, np.float32)

    mesh = parallel.Mesh({"dp": 8})

    def fn(xs):
        out, nm, nv = _nn.batch_norm(xs, gamma, beta, rm, rv, training=True,
                                     axis=-1, sync_axis_name="dp")
        return out

    f = parallel.shard_map(fn, mesh, in_specs=P("dp", None),
                           out_specs=P("dp", None))
    with mesh:
        out_sync = np.asarray(jax.jit(f)(x))
    # synced BN over the full batch == single-device BN on the whole batch
    ref, _, _ = _nn.batch_norm(x, gamma, beta, rm, rv, training=True, axis=-1)
    np.testing.assert_allclose(out_sync, np.asarray(ref), rtol=2e-4,
                               atol=2e-4)
