"""Detection op tail tests (≙ reference tests/python/unittest/test_operator
MultiBox*/Proposal/deformable cases, src/operator/contrib/*).

Each op is validated against an independent pure-numpy re-implementation of
the reference C++ semantics (not against the jax code under test).
"""
import os
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import npx


def _np_multibox_prior(h, w, sizes, ratios, steps=(-1, -1),
                       offsets=(0.5, 0.5), clip=False):
    """Literal transcription of MultiBoxPriorForward (multibox_prior.cc)."""
    step_y = steps[0] if steps[0] > 0 else 1.0 / h
    step_x = steps[1] if steps[1] > 0 else 1.0 / w
    out = []
    for r in range(h):
        cy = (r + offsets[0]) * step_y
        for c in range(w):
            cx = (c + offsets[1]) * step_x
            sr0 = np.sqrt(ratios[0])
            for s in sizes:
                bw = s * h / w * sr0 / 2
                bh = s / sr0 / 2
                out.append([cx - bw, cy - bh, cx + bw, cy + bh])
            for rr in ratios[1:]:
                sr = np.sqrt(rr)
                bw = sizes[0] * h / w * sr / 2
                bh = sizes[0] / sr / 2
                out.append([cx - bw, cy - bh, cx + bw, cy + bh])
    out = np.asarray(out, np.float32)
    if clip:
        out = np.clip(out, 0, 1)
    return out[None]


def test_multibox_prior_matches_reference_math():
    x = mx.np.zeros((1, 8, 6, 9))  # NCHW: H=6, W=9
    sizes, ratios = (0.4, 0.2), (1.0, 2.0, 0.5)
    got = npx.multibox_prior(x, sizes=sizes, ratios=ratios).asnumpy()
    want = _np_multibox_prior(6, 9, sizes, ratios)
    assert got.shape == (1, 6 * 9 * 4, 4)     # K = 2 + 3 - 1
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)


def test_multibox_prior_clip_steps_offsets():
    x = mx.np.zeros((2, 3, 4, 4))
    got = npx.multibox_prior(x, sizes=(0.9,), ratios=(1.0,), clip=True,
                             steps=(0.3, 0.3), offsets=(0.0, 0.0)).asnumpy()
    want = _np_multibox_prior(4, 4, (0.9,), (1.0,), steps=(0.3, 0.3),
                              offsets=(0.0, 0.0), clip=True)
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    assert got.min() >= 0 and got.max() <= 1


def _iou(a, b):
    iw = max(0.0, min(a[2], b[2]) - max(a[0], b[0]))
    ih = max(0.0, min(a[3], b[3]) - max(a[1], b[1]))
    i = iw * ih
    u = ((a[2] - a[0]) * (a[3] - a[1])
         + (b[2] - b[0]) * (b[3] - b[1]) - i)
    return 0.0 if u <= 0 else i / u


def _np_multibox_target(anchors, labels, overlap=0.5):
    """Reference matching (multibox_target.cc:95-287), no mining."""
    A, G = len(anchors), len(labels)
    valid = 0
    for g in range(G):
        if labels[g][0] == -1:
            break
        valid += 1
    flags = np.full(A, -1)
    match = np.full(A, -1)
    gt_done = [False] * valid
    # bipartite
    while not all(gt_done):
        best = (1e-6, -1, -1)
        for a in range(A):
            if flags[a] == 1:
                continue
            for g in range(valid):
                if gt_done[g]:
                    continue
                iou = _iou(anchors[a], labels[g][1:5])
                if iou > best[0]:
                    best = (iou, a, g)
        if best[1] < 0:
            break
        flags[best[1]] = 1
        match[best[1]] = best[2]
        gt_done[best[2]] = True
    # threshold
    for a in range(A):
        if flags[a] == 1:
            continue
        ious = [_iou(anchors[a], labels[g][1:5]) for g in range(valid)]
        if not ious:
            continue
        g = int(np.argmax(ious))
        match[a] = g
        if ious[g] > overlap:
            flags[a] = 1
    cls_t = np.zeros(A, np.float32)
    for a in range(A):
        if flags[a] == 1:
            cls_t[a] = labels[match[a]][0] + 1
    return flags, match, cls_t


def test_multibox_target_matching_parity():
    rng = np.random.RandomState(0)
    anchors = np.clip(np.sort(rng.uniform(0, 1, (12, 2, 2)), axis=1)
                      .transpose(0, 2, 1).reshape(12, 4), 0, 1)
    anchors = anchors[:, [0, 2, 1, 3]].astype(np.float32)
    anchors.sort(axis=-1)  # ensure xmin<xmax etc. loosely
    anchors = _np_multibox_prior(3, 4, (0.4, 0.7), (1.0,))[0]  # (12,4)
    labels = np.array([[[1, 0.1, 0.1, 0.4, 0.45],
                        [0, 0.55, 0.5, 0.9, 0.95],
                        [-1, -1, -1, -1, -1]]], np.float32)
    cls_pred = np.zeros((1, 3, len(anchors)), np.float32)

    loc_t, loc_m, cls_t = npx.multibox_target(
        mx.np.array(anchors[None]), mx.np.array(labels),
        mx.np.array(cls_pred))
    flags, match, cls_ref = _np_multibox_target(anchors, labels[0])
    np.testing.assert_allclose(cls_t.asnumpy()[0], cls_ref)
    # masks: 4 ones per positive anchor
    lm = loc_m.asnumpy()[0].reshape(-1, 4)
    np.testing.assert_allclose(lm[:, 0], (flags == 1).astype(np.float32))

    # encode roundtrip: decoding the loc target with the matched anchor
    # must recover the gt box
    lt = loc_t.asnumpy()[0].reshape(-1, 4)
    for a in range(len(anchors)):
        if flags[a] != 1:
            continue
        g = labels[0][match[a]][1:5]
        al, at, ar, ab = anchors[a]
        aw, ah = ar - al, ab - at
        ax, ay = (al + ar) / 2, (at + ab) / 2
        ox = lt[a][0] * 0.1 * aw + ax
        oy = lt[a][1] * 0.1 * ah + ay
        ow = np.exp(lt[a][2] * 0.2) * aw
        oh = np.exp(lt[a][3] * 0.2) * ah
        np.testing.assert_allclose(
            [ox - ow / 2, oy - oh / 2, ox + ow / 2, oy + oh / 2], g,
            rtol=1e-4, atol=1e-5)


def test_multibox_target_negative_mining():
    anchors = _np_multibox_prior(4, 4, (0.3,), (1.0,))[0]   # (16,4)
    labels = np.array([[[2, 0.05, 0.05, 0.35, 0.35],
                        [-1, -1, -1, -1, -1]]], np.float32)
    # higher logits on even anchors -> they should be picked as negatives
    cls_pred = np.zeros((1, 4, 16), np.float32)
    cls_pred[0, 1, ::2] = 5.0
    _, _, cls_t = npx.multibox_target(
        mx.np.array(anchors[None]), mx.np.array(labels),
        mx.np.array(cls_pred), negative_mining_ratio=3.0,
        negative_mining_thresh=0.5)
    ct = cls_t.asnumpy()[0]
    n_pos = int((ct > 0).sum())
    n_neg = int((ct == 0).sum())
    n_ign = int((ct == -1).sum())
    assert n_pos >= 1
    assert n_neg == min(3 * n_pos, 16 - n_pos)
    assert n_pos + n_neg + n_ign == 16
    # mined negatives are the high-logit anchors
    neg_idx = np.where(ct == 0)[0]
    assert all(i % 2 == 0 for i in neg_idx)


def test_multibox_detection_decode_and_nms():
    anchors = _np_multibox_prior(2, 2, (0.5,), (1.0,))      # (1,4,4)
    A = 4
    cls_prob = np.zeros((1, 3, A), np.float32)
    cls_prob[0, 1, 0] = 0.9    # class 1 strong at anchor 0
    cls_prob[0, 1, 1] = 0.8    # overlapping duplicate, should be suppressed
    cls_prob[0, 2, 2] = 0.7    # class 2 at anchor 2 survives (other class)
    cls_prob[0, 0, 3] = 1.0    # background
    loc_pred = np.zeros((1, A * 4), np.float32)
    # shift anchor 1 onto anchor 0 so they overlap
    anc = anchors[0].copy()
    anc[1] = anc[0] + np.float32([0.02, 0.02, 0.02, 0.02])
    out = npx.multibox_detection(
        mx.np.array(cls_prob), mx.np.array(loc_pred),
        mx.np.array(anc[None]), nms_threshold=0.5).asnumpy()[0]
    ids = out[:, 0]
    # rows sorted by score: [cls1 0.9], [cls2 0.7] kept; dup suppressed
    assert ids[0] == 0.0 and abs(out[0, 1] - 0.9) < 1e-6
    assert ids[1] == 1.0 and abs(out[1, 1] - 0.7) < 1e-6
    assert (ids[2:] == -1).all()
    # decoded box at zero deltas == anchor
    np.testing.assert_allclose(out[0, 2:6], anc[0], rtol=1e-5, atol=1e-6)


def test_multibox_detection_force_suppress_and_threshold():
    anc = _np_multibox_prior(2, 2, (0.5,), (1.0,))[0]
    anc[1] = anc[0] + 0.01
    cls_prob = np.zeros((1, 3, 4), np.float32)
    cls_prob[0, 1, 0] = 0.9
    cls_prob[0, 2, 1] = 0.8   # different class, overlapping
    cls_prob[0, 1, 2] = 0.005  # below threshold -> background
    loc_pred = np.zeros((1, 16), np.float32)
    out = npx.multibox_detection(
        mx.np.array(cls_prob), mx.np.array(loc_pred), mx.np.array(anc[None]),
        force_suppress=True, nms_threshold=0.5).asnumpy()[0]
    assert out[0, 0] == 0.0          # top box kept
    assert (out[1:, 0] == -1).all()  # cross-class suppressed + low score


def test_proposal_shapes_and_ordering():
    rng = np.random.RandomState(0)
    K, H, W = 6, 5, 5  # 2 scales x 3 ratios
    cls_prob = rng.uniform(0, 1, (1, 2 * K, H, W)).astype(np.float32)
    bbox_pred = (rng.randn(1, 4 * K, H, W) * 0.1).astype(np.float32)
    im_info = np.array([[80.0, 80.0, 1.0]], np.float32)
    rois, scores = npx.proposal(
        mx.np.array(cls_prob), mx.np.array(bbox_pred),
        mx.np.array(im_info), rpn_pre_nms_top_n=60, rpn_post_nms_top_n=20,
        scales=(4, 8), ratios=(0.5, 1, 2), feature_stride=16,
        rpn_min_size=4, output_score=True)
    rois = rois.asnumpy()
    scores = scores.asnumpy()
    assert rois.shape == (20, 5) and scores.shape == (20, 1)
    assert (rois[:, 0] == 0).all()
    # boxes clipped to image
    assert rois[:, 1:].min() >= 0 and rois[:, 1:].max() <= 79.0
    assert (rois[:, 3] >= rois[:, 1]).all() and (rois[:, 4] >= rois[:, 2]).all()
    # scores descending where valid
    s = scores[:, 0]
    assert (np.diff(s) <= 1e-6).all()


def test_deformable_convolution_zero_offset_equals_conv():
    import jax
    rng = np.random.RandomState(1)
    x = rng.randn(2, 4, 9, 9).astype(np.float32)
    wgt = (rng.randn(6, 4, 3, 3) * 0.2).astype(np.float32)
    off = np.zeros((2, 2 * 9, 7, 7), np.float32)
    out = npx.deformable_convolution(
        mx.np.array(x), mx.np.array(off), mx.np.array(wgt),
        kernel=(3, 3)).asnumpy()
    want = jax.lax.conv_general_dilated(
        x, wgt, window_strides=(1, 1), padding="VALID",
        dimension_numbers=("NCHW", "OIHW", "NCHW"))
    np.testing.assert_allclose(out, np.asarray(want), rtol=1e-4, atol=1e-4)


def test_deformable_convolution_integer_offset_shifts_sampling():
    x = np.zeros((1, 1, 6, 6), np.float32)
    x[0, 0, 2, 3] = 1.0
    wgt = np.zeros((1, 1, 1, 1), np.float32)
    wgt[0, 0, 0, 0] = 1.0
    # offset (dy=+1, dx=+2) at every output position -> out[y][x]=x[y+1][x+2]
    off = np.zeros((1, 2, 6, 6), np.float32)
    off[0, 0] = 1.0
    off[0, 1] = 2.0
    out = npx.deformable_convolution(
        mx.np.array(x), mx.np.array(off), mx.np.array(wgt),
        kernel=(1, 1)).asnumpy()
    want = np.zeros_like(x)
    want[0, 0, 1, 1] = 1.0
    np.testing.assert_allclose(out, want, atol=1e-6)


def test_deformable_convolution_differentiable():
    rng = np.random.RandomState(0)
    x = mx.np.array(rng.randn(1, 2, 6, 6).astype(np.float32))
    off = mx.np.array((rng.randn(1, 2 * 9, 4, 4) * 0.3).astype(np.float32))
    wgt = mx.np.array((rng.randn(3, 2, 3, 3) * 0.1).astype(np.float32))
    x.attach_grad()
    off.attach_grad()
    wgt.attach_grad()
    with mx.autograd.record():
        y = npx.deformable_convolution(x, off, wgt, kernel=(3, 3))
        L = (y * y).sum()
    L.backward()
    assert float(np.abs(x.grad.asnumpy()).sum()) > 0
    assert float(np.abs(off.grad.asnumpy()).sum()) > 0
    assert float(np.abs(wgt.grad.asnumpy()).sum()) > 0


def test_psroi_pooling_position_sensitivity():
    # channels encode (out_channel, bin) identity: pooled value for output
    # channel c at bin (i,j) must come from input channel (c*G+i)*G+j
    O, G, P = 2, 2, 2
    B, H, W = 1, 8, 8
    C = O * G * G
    data = np.zeros((B, C, H, W), np.float32)
    for c in range(C):
        data[0, c] = c  # constant per channel
    rois = np.array([[0, 0, 0, 7, 7]], np.float32)
    out = npx.psroi_pooling(
        mx.np.array(data), mx.np.array(rois), spatial_scale=1.0,
        output_dim=O, pooled_size=P, group_size=G).asnumpy()
    assert out.shape == (1, O, P, P)
    for c in range(O):
        for i in range(P):
            for j in range(P):
                expect = (c * G + i) * G + j
                np.testing.assert_allclose(out[0, c, i, j], expect,
                                           rtol=1e-5)


def test_psroi_pooling_roi_batch_index():
    data = np.zeros((2, 4, 6, 6), np.float32)
    data[1] = 3.0
    rois = np.array([[1, 0, 0, 5, 5]], np.float32)
    out = npx.psroi_pooling(mx.np.array(data), mx.np.array(rois),
                            spatial_scale=1.0, output_dim=1,
                            pooled_size=2, group_size=2).asnumpy()
    np.testing.assert_allclose(out, np.full((1, 1, 2, 2), 3.0))


def test_multibox_detection_background_id():
    """background_id != 0 must be honored (the reference declares the
    param; here it works): ids renumber with the bg class removed."""
    anc = _np_multibox_prior(2, 2, (0.5,), (1.0,))[0]
    cls_prob = np.zeros((1, 3, 4), np.float32)
    cls_prob[0, 0, 0] = 0.9     # class 0 = foreground now
    cls_prob[0, 2, 1] = 0.8     # class 2 = foreground
    cls_prob[0, 1, 2] = 1.0     # class 1 = background -> not a detection
    out = npx.multibox_detection(
        mx.np.array(cls_prob), mx.np.array(np.zeros((1, 16), np.float32)),
        mx.np.array(anc[None]), background_id=1,
        nms_threshold=0.9).asnumpy()[0]
    ids = sorted(out[out[:, 0] >= 0][:, 0])
    assert ids == [0.0, 1.0]    # class0 -> id0, class2 -> id1


def test_anchor_reuse_across_train_steps():
    """Pre-r5 regression: npx.multibox_prior taped its feature-map input,
    so anchors computed once inside record crashed the SECOND backward
    (the first backward severed their tape node). Anchors are shape-only
    — they must be constants."""
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu import gluon, npx
    from incubator_mxnet_tpu.gluon import nn

    net = nn.Conv2D(8, 3, padding=1)
    net.initialize()
    tr = gluon.Trainer(net.collect_params(), "sgd", {"learning_rate": 0.1})
    anchors = None
    for _ in range(3):
        x = mx.np.array(np.random.rand(2, 3, 8, 8).astype(np.float32))
        with mx.autograd.record():
            f = net(x)
            if anchors is None:
                anchors = npx.multibox_prior(f, sizes=(0.3,), ratios=(1.0,))
            L = (f * anchors.sum()).sum()
        L.backward()
        tr.step(2)
    assert anchors._entry is None     # detached: not on any tape


def test_detection_training_learns_map():
    """VERDICT-r4 Weak #8: the detection tail must WORK, not just run —
    a short synthetic SSD training run must lift held-out VOC07 mAP@0.5
    well above its untrained level (full trajectory artifact:
    benchmark/results/detection_eval_r5.json)."""
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "detection_eval",
        os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "benchmark", "detection_eval.py"))
    m = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(m)
    traj = m.run(steps=41, eval_every=40)
    assert traj[-1]["voc07_mAP@0.5"] > 0.6, traj
    assert traj[-1]["voc07_mAP@0.5"] > traj[0]["voc07_mAP@0.5"] + 0.3, traj
