"""Flash attention (pallas, interpret mode on CPU) + ring attention tests."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _ref_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = np.einsum("bqd,bkd->bqk", q.astype(np.float64),
                  k.astype(np.float64)) / np.sqrt(d)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((tq, tk), bool), k=tk - tq)
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bqk,bkd->bqd", p, v.astype(np.float64))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_interpret(causal):
    from incubator_mxnet_tpu.ops.pallas_attention import flash_attention
    rng = np.random.default_rng(0)
    q = rng.standard_normal((2, 256, 64), np.float32)
    k = rng.standard_normal((2, 256, 64), np.float32)
    v = rng.standard_normal((2, 256, 64), np.float32)
    out = np.asarray(flash_attention(q, k, v, causal=causal, block_q=128,
                                     block_k=128, interpret=True))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_flash_attention_fallback_path():
    from incubator_mxnet_tpu.ops.pallas_attention import flash_attention
    rng = np.random.default_rng(1)
    q = rng.standard_normal((1, 100, 32), np.float32)  # ragged → fallback
    k = rng.standard_normal((1, 100, 32), np.float32)
    v = rng.standard_normal((1, 100, 32), np.float32)
    out = np.asarray(flash_attention(q, k, v, block_q=64, block_k=64))
    np.testing.assert_allclose(out, _ref_attention(q, k, v), rtol=2e-3,
                               atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Ring attention over sp=4 must equal single-device full attention."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel import ring_attention

    rng = np.random.default_rng(2)
    B, T, D = 2, 64, 32
    q = rng.standard_normal((B, T, D), np.float32)
    k = rng.standard_normal((B, T, D), np.float32)
    v = rng.standard_normal((B, T, D), np.float32)

    mesh = parallel.Mesh({"sp": 4})
    f = parallel.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                       causal=causal),
        mesh, in_specs=P(None, "sp", None), out_specs=P(None, "sp", None))
    with mesh:
        out = np.asarray(jax.jit(f)(q, k, v))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_ring_attention_long_sequence_grad():
    """Differentiable: grads must flow through the ring."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel import ring_attention

    rng = np.random.default_rng(3)
    q = rng.standard_normal((1, 32, 16), np.float32)
    k = rng.standard_normal((1, 32, 16), np.float32)
    v = rng.standard_normal((1, 32, 16), np.float32)
    mesh = parallel.Mesh({"sp": 4})

    def loss(q, k, v):
        f = parallel.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
            mesh, in_specs=P(None, "sp", None),
            out_specs=P(None, "sp", None))
        return jnp.sum(f(q, k, v) ** 2)

    def ref_loss(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bqk,bkd->bqd", p, v) ** 2)

    with mesh:
        g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=2e-3,
                               atol=2e-3)


def test_npx_sdpa():
    from incubator_mxnet_tpu import npx
    rng = np.random.default_rng(4)
    q = mx.np.array(rng.standard_normal((2, 4, 16, 8), np.float32))
    out = npx.scaled_dot_product_attention(q, q, q, causal=True)
    assert out.shape == (2, 4, 16, 8)
