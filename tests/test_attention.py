"""Flash attention (pallas, interpret mode on CPU) + ring attention tests."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _ref_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = np.einsum("bqd,bkd->bqk", q.astype(np.float64),
                  k.astype(np.float64)) / np.sqrt(d)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((tq, tk), bool), k=tk - tq)
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bqk,bkd->bqd", p, v.astype(np.float64))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_interpret(causal):
    from incubator_mxnet_tpu.ops.pallas_attention import flash_attention
    rng = np.random.default_rng(0)
    q = rng.standard_normal((2, 256, 64), np.float32)
    k = rng.standard_normal((2, 256, 64), np.float32)
    v = rng.standard_normal((2, 256, 64), np.float32)
    out = np.asarray(flash_attention(q, k, v, causal=causal, block_q=128,
                                     block_k=128, interpret=True))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_flash_attention_fallback_path():
    from incubator_mxnet_tpu.ops.pallas_attention import flash_attention
    rng = np.random.default_rng(1)
    q = rng.standard_normal((1, 100, 32), np.float32)  # ragged → fallback
    k = rng.standard_normal((1, 100, 32), np.float32)
    v = rng.standard_normal((1, 100, 32), np.float32)
    out = np.asarray(flash_attention(q, k, v, block_q=64, block_k=64))
    np.testing.assert_allclose(out, _ref_attention(q, k, v), rtol=2e-3,
                               atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Ring attention over sp=4 must equal single-device full attention."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel import ring_attention

    rng = np.random.default_rng(2)
    B, T, D = 2, 64, 32
    q = rng.standard_normal((B, T, D), np.float32)
    k = rng.standard_normal((B, T, D), np.float32)
    v = rng.standard_normal((B, T, D), np.float32)

    mesh = parallel.Mesh({"sp": 4})
    f = parallel.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                       causal=causal),
        mesh, in_specs=P(None, "sp", None), out_specs=P(None, "sp", None))
    with mesh:
        out = np.asarray(jax.jit(f)(q, k, v))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_ring_attention_long_sequence_grad():
    """Differentiable: grads must flow through the ring."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel import ring_attention

    rng = np.random.default_rng(3)
    q = rng.standard_normal((1, 32, 16), np.float32)
    k = rng.standard_normal((1, 32, 16), np.float32)
    v = rng.standard_normal((1, 32, 16), np.float32)
    mesh = parallel.Mesh({"sp": 4})

    def loss(q, k, v):
        f = parallel.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
            mesh, in_specs=P(None, "sp", None),
            out_specs=P(None, "sp", None))
        return jnp.sum(f(q, k, v) ** 2)

    def ref_loss(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bqk,bkd->bqd", p, v) ** 2)

    with mesh:
        g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=2e-3,
                               atol=2e-3)


def test_npx_sdpa():
    from incubator_mxnet_tpu import npx
    rng = np.random.default_rng(4)
    q = mx.np.array(rng.standard_normal((2, 4, 16, 8), np.float32))
    out = npx.scaled_dot_product_attention(q, q, q, causal=True)
    assert out.shape == (2, 4, 16, 8)


def test_flash_attention_gradient():
    """Regression: flash attention must be differentiable (custom_vjp with
    blockwise-scan backward)."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.pallas_attention import (flash_attention,
                                                          _blockwise)
    rng = np.random.default_rng(5)
    q = rng.standard_normal((2, 128, 32), np.float32)
    k = rng.standard_normal((2, 128, 32), np.float32)
    v = rng.standard_normal((2, 128, 32), np.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64,
                                       block_k=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(32)
        mask = jnp.tril(jnp.ones((128, 128), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bqk,bkd->bqd", p, v) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-2,
                                   atol=5e-2)


def test_blockwise_matches_reference():
    from incubator_mxnet_tpu.ops.pallas_attention import (_blockwise,
                                                          _reference)
    rng = np.random.default_rng(6)
    q = rng.standard_normal((2, 96, 16), np.float32)
    out_b = np.asarray(_blockwise(q, q, q, 0.25, True, block_k=32))
    out_r = np.asarray(_reference(q, q, q, 0.25, True))
    np.testing.assert_allclose(out_b, out_r, rtol=2e-3, atol=2e-3)


def test_flash_attention_cross_length_causal():
    """Regression: causal masking is END-aligned (decode shapes tq < tk must
    match the sdpa tril(k=tk-tq) convention)."""
    from incubator_mxnet_tpu.ops.pallas_attention import (_blockwise,
                                                          _reference,
                                                          flash_attention)
    rng = np.random.default_rng(7)
    q = rng.standard_normal((1, 1, 16), np.float32)   # single decode query
    k = rng.standard_normal((1, 64, 16), np.float32)
    v = rng.standard_normal((1, 64, 16), np.float32)
    ref = np.asarray(_reference(q, k, v, 0.25, True))
    blk = np.asarray(_blockwise(q, k, v, 0.25, True, block_k=16))
    np.testing.assert_allclose(blk, ref, rtol=2e-3, atol=2e-3)
    fa = np.asarray(flash_attention(q, k, v, causal=True, block_q=1,
                                    block_k=16, interpret=True))
    np.testing.assert_allclose(fa, ref, rtol=2e-3, atol=2e-3)
