"""Flash attention (pallas, interpret mode on CPU) + ring attention tests."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx


def _ref_attention(q, k, v, causal=False):
    d = q.shape[-1]
    s = np.einsum("bqd,bkd->bqk", q.astype(np.float64),
                  k.astype(np.float64)) / np.sqrt(d)
    if causal:
        tq, tk = s.shape[-2], s.shape[-1]
        mask = np.tril(np.ones((tq, tk), bool), k=tk - tq)
        s = np.where(mask, s, -1e30)
    p = np.exp(s - s.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    return np.einsum("bqk,bkd->bqd", p, v.astype(np.float64))


@pytest.mark.parametrize("causal", [False, True])
def test_flash_attention_interpret(causal):
    from incubator_mxnet_tpu.ops.pallas_attention import flash_attention
    rng = np.random.default_rng(0)
    q = rng.standard_normal((2, 256, 64), np.float32)
    k = rng.standard_normal((2, 256, 64), np.float32)
    v = rng.standard_normal((2, 256, 64), np.float32)
    out = np.asarray(flash_attention(q, k, v, causal=causal, block_q=128,
                                     block_k=128, interpret=True))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_flash_attention_fallback_path():
    from incubator_mxnet_tpu.ops.pallas_attention import flash_attention
    rng = np.random.default_rng(1)
    q = rng.standard_normal((1, 100, 32), np.float32)  # ragged → fallback
    k = rng.standard_normal((1, 100, 32), np.float32)
    v = rng.standard_normal((1, 100, 32), np.float32)
    out = np.asarray(flash_attention(q, k, v, block_q=64, block_k=64))
    np.testing.assert_allclose(out, _ref_attention(q, k, v), rtol=2e-3,
                               atol=2e-3)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_full(causal):
    """Ring attention over sp=4 must equal single-device full attention."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel import ring_attention

    rng = np.random.default_rng(2)
    B, T, D = 2, 64, 32
    q = rng.standard_normal((B, T, D), np.float32)
    k = rng.standard_normal((B, T, D), np.float32)
    v = rng.standard_normal((B, T, D), np.float32)

    mesh = parallel.Mesh({"sp": 4})
    f = parallel.shard_map(
        lambda q, k, v: ring_attention(q, k, v, axis_name="sp",
                                       causal=causal),
        mesh, in_specs=P(None, "sp", None), out_specs=P(None, "sp", None))
    with mesh:
        out = np.asarray(jax.jit(f)(q, k, v))
    ref = _ref_attention(q, k, v, causal)
    np.testing.assert_allclose(out, ref, rtol=2e-3, atol=2e-3)


def test_ring_attention_long_sequence_grad():
    """Differentiable: grads must flow through the ring."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel import ring_attention

    rng = np.random.default_rng(3)
    q = rng.standard_normal((1, 32, 16), np.float32)
    k = rng.standard_normal((1, 32, 16), np.float32)
    v = rng.standard_normal((1, 32, 16), np.float32)
    mesh = parallel.Mesh({"sp": 4})

    def loss(q, k, v):
        f = parallel.shard_map(
            lambda a, b, c: ring_attention(a, b, c, axis_name="sp"),
            mesh, in_specs=P(None, "sp", None),
            out_specs=P(None, "sp", None))
        return jnp.sum(f(q, k, v) ** 2)

    def ref_loss(q, k, v):
        d = q.shape[-1]
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(d)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bqk,bkd->bqd", p, v) ** 2)

    with mesh:
        g = jax.grad(loss)(q, k, v)
    g_ref = jax.grad(ref_loss)(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref), rtol=2e-3,
                               atol=2e-3)


def test_npx_sdpa():
    from incubator_mxnet_tpu import npx
    rng = np.random.default_rng(4)
    q = mx.np.array(rng.standard_normal((2, 4, 16, 8), np.float32))
    out = npx.scaled_dot_product_attention(q, q, q, causal=True)
    assert out.shape == (2, 4, 16, 8)


def test_flash_attention_gradient():
    """Regression: flash attention must be differentiable (custom_vjp with
    blockwise-scan backward)."""
    import jax
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops.pallas_attention import (flash_attention,
                                                          _blockwise)
    rng = np.random.default_rng(5)
    q = rng.standard_normal((2, 128, 32), np.float32)
    k = rng.standard_normal((2, 128, 32), np.float32)
    v = rng.standard_normal((2, 128, 32), np.float32)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=True, block_q=64,
                                       block_k=64, interpret=True) ** 2)

    def loss_ref(q, k, v):
        s = jnp.einsum("bqd,bkd->bqk", q, k) / np.sqrt(32)
        mask = jnp.tril(jnp.ones((128, 128), bool))
        s = jnp.where(mask, s, -1e30)
        p = jax.nn.softmax(s, axis=-1)
        return jnp.sum(jnp.einsum("bqk,bkd->bqd", p, v) ** 2)

    g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b in zip(g, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), rtol=5e-2,
                                   atol=5e-2)


def test_blockwise_matches_reference():
    from incubator_mxnet_tpu.ops.pallas_attention import (_blockwise,
                                                          _reference)
    rng = np.random.default_rng(6)
    q = rng.standard_normal((2, 96, 16), np.float32)
    out_b = np.asarray(_blockwise(q, q, q, 0.25, True, block_k=32))
    out_r = np.asarray(_reference(q, q, q, 0.25, True))
    np.testing.assert_allclose(out_b, out_r, rtol=2e-3, atol=2e-3)


def test_flash_attention_cross_length_causal():
    """Regression: causal masking is END-aligned (decode shapes tq < tk must
    match the sdpa tril(k=tk-tq) convention)."""
    from incubator_mxnet_tpu.ops.pallas_attention import (_blockwise,
                                                          _reference,
                                                          flash_attention)
    rng = np.random.default_rng(7)
    q = rng.standard_normal((1, 1, 16), np.float32)   # single decode query
    k = rng.standard_normal((1, 64, 16), np.float32)
    v = rng.standard_normal((1, 64, 16), np.float32)
    ref = np.asarray(_reference(q, k, v, 0.25, True))
    blk = np.asarray(_blockwise(q, k, v, 0.25, True, block_k=16))
    np.testing.assert_allclose(blk, ref, rtol=2e-3, atol=2e-3)
    fa = np.asarray(flash_attention(q, k, v, causal=True, block_q=1,
                                    block_k=16, interpret=True))
    np.testing.assert_allclose(fa, ref, rtol=2e-3, atol=2e-3)


def _fa_grads(fn, q, k, v):
    import jax
    import jax.numpy as jnp

    def loss(q, k, v):
        o = fn(q, k, v)
        return jnp.sum(o * jnp.cos(o))   # nontrivial cotangent
    return jax.grad(loss, argnums=(0, 1, 2))(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_backward_kernel_matches_reference(causal):
    """The Pallas dq/dkv kernels (interpret mode) must match gradients of
    the dense einsum reference."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops import pallas_attention as pa
    rng = np.random.RandomState(0)
    bh, t, d = 2, 256, 64
    q = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.5)
    k = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.5)
    v = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.5)

    got = _fa_grads(lambda a, b, c: pa.flash_attention(
        a, b, c, causal=causal, interpret=True, block_q=128, block_k=128),
        q, k, v)
    want = _fa_grads(lambda a, b, c: pa._reference(
        a, b, c, 1.0 / np.sqrt(d), causal), q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3,
                                   err_msg=f"d{name} causal={causal}")


def test_flash_backward_rectangular_kv():
    """Decode-style Tq < Tk (end-aligned causal) through the kernels."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops import pallas_attention as pa
    rng = np.random.RandomState(1)
    bh, tq, tk, d = 2, 128, 256, 32
    q = jnp.asarray(rng.randn(bh, tq, d).astype(np.float32) * 0.5)
    k = jnp.asarray(rng.randn(bh, tk, d).astype(np.float32) * 0.5)
    v = jnp.asarray(rng.randn(bh, tk, d).astype(np.float32) * 0.5)
    got = _fa_grads(lambda a, b, c: pa.flash_attention(
        a, b, c, causal=True, interpret=True, block_q=128, block_k=128),
        q, k, v)
    want = _fa_grads(lambda a, b, c: pa._reference(
        a, b, c, 1.0 / np.sqrt(d), True), q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-3, atol=2e-3, err_msg=name)


def test_auto_blocks_fit_budget():
    from incubator_mxnet_tpu.ops.pallas_attention import _auto_blocks
    for tq, tk, d in [(512, 512, 64), (4096, 4096, 128), (8192, 8192, 256),
                      (128, 8192, 64), (1024, 1024, 512)]:
        bq, bk = _auto_blocks(tq, tk, d)
        assert tq % bq == 0 and tk % bk == 0
        assert bq >= 8 and bk >= 8
        # working set within ~2x of an 8MB half-VMEM budget
        ws = (bq * d * 4 * 3 + bk * d * 4 * 4 + bq * bk * 8)
        assert ws <= 16 * 1024 * 1024


@pytest.mark.skipif(
    __import__("jax").devices()[0].platform == "cpu",
    reason="compiled (non-interpret) Pallas kernels need a real TPU")
def test_flash_kernels_compiled_on_tpu():
    """Non-interpreted kernel correctness on silicon — fwd AND bwd."""
    import jax.numpy as jnp
    from incubator_mxnet_tpu.ops import pallas_attention as pa
    rng = np.random.RandomState(2)
    bh, t, d = 4, 1024, 64
    q = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.3)
    k = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.3)
    v = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.3)
    o = pa.flash_attention(q, k, v, causal=True)
    ref = pa._reference(q, k, v, 1.0 / np.sqrt(d), True)
    np.testing.assert_allclose(np.asarray(o), np.asarray(ref), rtol=2e-3,
                               atol=2e-3)
    got = _fa_grads(lambda a, b, c: pa.flash_attention(a, b, c, causal=True),
                    q, k, v)
    want = _fa_grads(lambda a, b, c: pa._reference(
        a, b, c, 1.0 / np.sqrt(d), True), q, k, v)
    for g, w, name in zip(got, want, "qkv"):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=3e-3, atol=3e-3, err_msg=name)


def test_auto_blocks_divide_non_pow2_lengths():
    """Lengths like 1536/384 must still run the kernel (divisor blocks),
    not regress to the dense fallback."""
    from incubator_mxnet_tpu.ops.pallas_attention import _auto_blocks
    for tq, tk in [(1536, 1536), (384, 384), (1536, 512), (768, 3072)]:
        bq, bk = _auto_blocks(tq, tk, 64)
        assert tq % bq == 0 and tk % bk == 0, (tq, tk, bq, bk)
        assert bq >= 128 and bk >= 128


def test_ring_attention_flash_matches_dense():
    """Ring-over-flash-kernels (fwd + custom ring bwd) must match the
    dense full-sequence attention AND its gradients on the 8-dev mesh."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, PartitionSpec as P
    from incubator_mxnet_tpu import parallel
    from incubator_mxnet_tpu.parallel import ring as ring_mod
    from incubator_mxnet_tpu.ops import pallas_attention as pa

    devs = np.array(jax.devices()[:4])
    mesh = Mesh(devs, ("sp",))
    rng = np.random.RandomState(0)
    bh, t, d = 2, 256, 32          # 64 per shard
    q = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.4)
    k = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.4)
    v = jnp.asarray(rng.randn(bh, t, d).astype(np.float32) * 0.4)

    for causal in (False, True):
        ring_fn = parallel.shard_map(
            lambda a, b, c: ring_mod.ring_attention(
                a, b, c, axis_name="sp", causal=causal, use_flash=True),
            mesh, in_specs=(P(None, "sp", None),) * 3,
            out_specs=P(None, "sp", None))

        def loss_ring(a, b, c):
            o = ring_fn(a, b, c)
            return jnp.sum(o * jnp.cos(o))

        def loss_ref(a, b, c):
            o = pa._reference(a, b, c, 1.0 / np.sqrt(d), causal)
            return jnp.sum(o * jnp.cos(o))

        o_ring = ring_fn(q, k, v)
        o_ref = pa._reference(q, k, v, 1.0 / np.sqrt(d), causal)
        np.testing.assert_allclose(np.asarray(o_ring), np.asarray(o_ref),
                                   rtol=3e-3, atol=3e-3,
                                   err_msg=f"fwd causal={causal}")
        g_ring = jax.grad(loss_ring, argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for gr, gf, nm in zip(g_ring, g_ref, "qkv"):
            np.testing.assert_allclose(
                np.asarray(gr), np.asarray(gf), rtol=4e-3, atol=4e-3,
                err_msg=f"d{nm} causal={causal}")
