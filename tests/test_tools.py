"""Tooling tier (§2.6): bandwidth, flakiness_checker, gen_api_docs, and
the convert_model CLI all run end-to-end in-suite."""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np

import incubator_mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"tool_{name}", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bandwidth_measures_collectives():
    bw = _load_tool("bandwidth")
    rows = bw.measure([0.5], reps=2)
    assert len(rows) == 1
    row = rows[0]
    assert row["h2d_gbps"] > 0 and row["d2h_gbps"] > 0
    # the suite runs on the forced 8-device mesh: collective rows present
    if row["devices"] > 1:
        for k in ("allreduce_gbps", "allgather_gbps",
                  "reduce_scatter_gbps"):
            assert row[k] > 0, (k, row)


def test_flakiness_checker_normalize():
    fc = _load_tool("flakiness_checker")
    assert fc.normalize("tests/test_gluon.py::test_x") \
        == "tests/test_gluon.py::test_x"
    assert fc.normalize("test_gluon.test_x") \
        == os.path.join("tests", "test_gluon.py") + "::test_x"


def test_gen_api_docs_emits_pages(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gen_api_docs.py"),
         "--out", str(tmp_path)],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "SKIP" not in r.stdout, r.stdout  # every module must render
    pages = os.listdir(tmp_path)
    assert "README.md" in pages and len(pages) > 25
    nn_page = (tmp_path / "gluon_nn.md").read_text()
    assert "Conv2D" in nn_page and "BatchNorm" in nn_page


def test_convert_model_cli_auto_map(tmp_path):
    from incubator_mxnet_tpu.gluon.model_zoo import vision, model_store
    mx.seed(9)
    net = vision.alexnet()
    net.initialize()
    x = mx.np.zeros((1, 3, 224, 224))
    net(x)
    foreign = {f"zoo_p{i}": p.data().asnumpy()
               for i, (_, p) in enumerate(net.collect_params().items())}
    pfile = str(tmp_path / "zoo.params")
    model_store.save_params_file(pfile, foreign)
    out = str(tmp_path / "alexnet.npz")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "convert_model.py"),
         pfile, out, "--auto-map", "alexnet"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "auto-map" in r.stdout
    with np.load(out) as f:
        assert len(f.files) == len(foreign)
