"""Tooling tier (§2.6): bandwidth, flakiness_checker, gen_api_docs, and
the convert_model CLI all run end-to-end in-suite."""
import importlib.util
import json
import os
import subprocess
import sys

import numpy as np

import incubator_mxnet_tpu as mx

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


from capi_utils import subprocess_env as _cpu_env   # shared CPU-pinned env


def _load_tool(name):
    spec = importlib.util.spec_from_file_location(
        f"tool_{name}", os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_bandwidth_measures_collectives():
    bw = _load_tool("bandwidth")
    rows = bw.measure([0.5], reps=2)
    assert len(rows) == 1
    row = rows[0]
    assert row["h2d_gbps"] > 0 and row["d2h_gbps"] > 0
    # the suite runs on the forced 8-device mesh: collective rows present
    if row["devices"] > 1:
        for k in ("allreduce_gbps", "allgather_gbps",
                  "reduce_scatter_gbps"):
            assert row[k] > 0, (k, row)


def test_flakiness_checker_normalize():
    fc = _load_tool("flakiness_checker")
    assert fc.normalize("tests/test_gluon.py::test_x") \
        == "tests/test_gluon.py::test_x"
    assert fc.normalize("test_gluon.test_x") \
        == os.path.join("tests", "test_gluon.py") + "::test_x"


def test_gen_api_docs_emits_pages(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "gen_api_docs.py"),
         "--out", str(tmp_path)],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "SKIP" not in r.stdout, r.stdout  # every module must render
    pages = os.listdir(tmp_path)
    assert "README.md" in pages and len(pages) > 25
    nn_page = (tmp_path / "gluon_nn.md").read_text()
    assert "Conv2D" in nn_page and "BatchNorm" in nn_page


def test_convert_model_cli_auto_map(tmp_path):
    from incubator_mxnet_tpu.gluon.model_zoo import vision, model_store
    mx.seed(9)
    net = vision.alexnet()
    net.initialize()
    x = mx.np.zeros((1, 3, 224, 224))
    net(x)
    foreign = {f"zoo_p{i}": p.data().asnumpy()
               for i, (_, p) in enumerate(net.collect_params().items())}
    pfile = str(tmp_path / "zoo.params")
    model_store.save_params_file(pfile, foreign)
    out = str(tmp_path / "alexnet.npz")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "convert_model.py"),
         pfile, out, "--auto-map", "alexnet"],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stderr[-2000:]
    assert "auto-map" in r.stdout
    with np.load(out) as f:
        assert len(f.files) == len(foreign)


def test_parse_log_extracts_metrics(tmp_path):
    """≙ reference tools/parse_log.py: epoch metrics + speed out of mixed
    log styles."""
    import runpy
    mod = runpy.run_path(os.path.join(REPO, "tools", "parse_log.py"))
    assert mod["_self_test"]()
    f = tmp_path / "t.log"
    f.write_text("Epoch[0] Speed: 100.0 samples/sec accuracy=0.25\n"
                 "Epoch[1] Speed: 120.0 samples/sec accuracy=0.75\n")
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "parse_log.py"),
         str(f), "--format", "csv"],
        capture_output=True, text=True, env=_cpu_env(), timeout=120)
    assert out.returncode == 0
    assert "0,0.25,100" in out.stdout.replace(" ", "")


def test_diagnose_runs(tmp_path):
    """tools/diagnose.py prints env + package + device sections without
    crashing, even when the accelerator is unreachable."""
    env = _cpu_env()
    env["DIAGNOSE_FORCE_CPU"] = "1"   # keep the probe off the real chip
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "diagnose.py")],
        capture_output=True, text=True, env=env, timeout=300)
    assert r.returncode == 0, r.stderr
    for section in ("Python Info", "Package Versions", "Framework",
                    "Devices"):
        assert section in r.stdout


def test_name_and_attr_scopes():
    """mx.name.Prefix / NameManager and mx.attribute.AttrScope drive
    symbol naming + attributes (≙ name.py / attribute.py)."""
    import incubator_mxnet_tpu as mx
    with mx.name.Prefix("enc_"):
        s = mx.sym.FullyConnected(mx.sym.Variable("data"), num_hidden=4)
        assert s.name.startswith("enc_fullyconnected")
    with mx.name.NameManager():
        a = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu")
        b = mx.sym.Activation(mx.sym.Variable("y"), act_type="relu")
        assert a.name == "activation0" and b.name == "activation1"
    # reference Prefix semantics: the prefix applies to EXPLICIT names too
    with mx.name.Prefix("zzz_"):
        s = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu",
                              name="mine")
        assert s.name == "zzz_mine"
    with mx.attribute.AttrScope(__group__="backbone"):
        with mx.attribute.AttrScope(lr_mult="0.1"):
            s = mx.sym.Activation(mx.sym.Variable("x"), act_type="relu")
    attrs = s.list_attr()
    assert attrs.get("__group__") == "backbone"
    assert attrs.get("lr_mult") == "0.1"
    # scope attrs reach Variables and auto-created param slots, and a
    # scope key colliding with an op PARAM stays metadata (no_bias must
    # not drop the bias slot)
    with mx.attribute.AttrScope(lr_mult="0.5", no_bias="True"):
        v = mx.sym.Variable("w")
        fc = mx.sym.FullyConnected(mx.sym.Variable("d"), num_hidden=4)
    assert v.list_attr().get("lr_mult") == "0.5"
    assert any(n.endswith("_bias") for n in fc.list_arguments()), \
        fc.list_arguments()
    import pytest as _pytest
    with _pytest.raises(mx.MXNetError):
        mx.attribute.AttrScope(bad=3)
