"""Estimator, probability, and native-IO tests (≙ reference
tests/python/unittest/test_gluon_estimator.py, test_gluon_probability_v2.py)."""
import os
import struct

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn


# ---------------------------------------------------------------------------
# estimator
# ---------------------------------------------------------------------------
def _toy_loader(n=64, d=8, batch=16):
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    rng = np.random.default_rng(0)
    X = rng.standard_normal((n, d)).astype(np.float32)
    Y = (X.sum(1) > 0).astype(np.int32)
    return DataLoader(ArrayDataset(X, Y), batch_size=batch)


def _toy_net():
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu"), nn.Dense(2))
    net.initialize()
    return net


def test_estimator_fit_and_handlers():
    from incubator_mxnet_tpu.gluon.contrib import estimator as est
    net = _toy_net()
    e = est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss(),
                      trainer=gluon.Trainer(net.collect_params(), "adam",
                                            {"learning_rate": 0.05}))
    events = []

    class Spy(est.EpochBegin, est.EpochEnd, est.BatchEnd):
        def epoch_begin(self, estimator, **kw):
            events.append("eb")

        def epoch_end(self, estimator, **kw):
            events.append("ee")

        def batch_end(self, estimator, **kw):
            events.append("b")

    e.fit(_toy_loader(), epochs=2, event_handlers=[Spy()])
    assert events.count("eb") == 2 and events.count("ee") == 2
    assert events.count("b") == 8
    name, acc = e.train_metrics[0].get()
    assert name == "accuracy" and 0 <= acc <= 1


def test_estimator_early_stopping_and_checkpoint(tmp_path):
    from incubator_mxnet_tpu.gluon import metric
    from incubator_mxnet_tpu.gluon.contrib import estimator as est
    net = _toy_net()
    e = est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())
    loss_metric = e.train_metrics[-1]
    early = est.EarlyStoppingHandler(loss_metric, patience=0, mode="min")
    ckpt = est.CheckpointHandler(str(tmp_path), save_best=False)
    e.fit(_toy_loader(), epochs=5, event_handlers=[early, ckpt])
    files = os.listdir(tmp_path)
    assert any(f.endswith(".params.npz") for f in files)


def test_estimator_max_batches():
    from incubator_mxnet_tpu.gluon.contrib import estimator as est
    net = _toy_net()
    e = est.Estimator(net, gluon.loss.SoftmaxCrossEntropyLoss())
    e.fit(_toy_loader(), batches=3)
    # StoppingHandler halted inside the first epoch
    assert e.stop_training


# ---------------------------------------------------------------------------
# probability
# ---------------------------------------------------------------------------
def test_normal_logprob_matches_scipy_form():
    from incubator_mxnet_tpu.gluon import probability as pr
    n = pr.Normal(loc=0.0, scale=1.0)
    lp = float(n.log_prob(mx.np.zeros(())).asnumpy())
    assert abs(lp - (-0.5 * np.log(2 * np.pi))) < 1e-5


def test_normal_sampling_moments():
    from incubator_mxnet_tpu.gluon import probability as pr
    mx.seed(42)
    n = pr.Normal(loc=2.0, scale=3.0)
    s = n.sample((20000,)).asnumpy()
    assert abs(s.mean() - 2.0) < 0.1
    assert abs(s.std() - 3.0) < 0.1


def test_kl_normal_closed_form():
    from incubator_mxnet_tpu.gluon import probability as pr
    p = pr.Normal(1.0, 2.0)
    q = pr.Normal(0.0, 1.0)
    kl = float(pr.kl_divergence(p, q).asnumpy())
    expected = np.log(1 / 2.0) + (4 + 1) / 2.0 - 0.5
    assert abs(kl - expected) < 1e-5


def test_bernoulli_categorical():
    from incubator_mxnet_tpu.gluon import probability as pr
    b = pr.Bernoulli(prob=mx.np.array([0.3]))
    lp = b.log_prob(mx.np.array([1.0])).asnumpy()
    np.testing.assert_allclose(lp, np.log(0.3), rtol=1e-5)
    with pytest.raises(mx.MXNetError):
        pr.Bernoulli()
    c = pr.Categorical(logit=mx.np.array(np.zeros((4,), np.float32)))
    lp = float(c.log_prob(mx.np.array(2)).asnumpy())
    assert abs(lp - np.log(0.25)) < 1e-5


def test_gamma_beta_dirichlet():
    from incubator_mxnet_tpu.gluon import probability as pr
    mx.seed(3)
    g = pr.Gamma(shape=3.0, scale=2.0)
    s = g.sample((5000,)).asnumpy()
    assert abs(s.mean() - 6.0) < 0.3
    d = pr.Dirichlet(mx.np.array([1.0, 1.0, 1.0]))
    samp = d.sample((100,)).asnumpy()
    np.testing.assert_allclose(samp.sum(-1), np.ones(100), rtol=1e-5)


def test_mvn_logprob():
    from incubator_mxnet_tpu.gluon import probability as pr
    cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    m = pr.MultivariateNormal(loc=mx.np.zeros((2,)), cov=mx.np.array(cov))
    lp = float(m.log_prob(mx.np.zeros((2,))).asnumpy())
    expected = -0.5 * np.log((2 * np.pi) ** 2 * np.linalg.det(cov))
    assert abs(lp - expected) < 1e-4


def test_stochastic_block_collects_losses():
    from incubator_mxnet_tpu.gluon import probability as pr

    class VAEBlock(pr.StochasticBlock):
        def forward(self, x):
            self.add_loss(x.sum())
            return x * 2

    blk = VAEBlock()
    out = blk(mx.np.ones((2, 2)))
    assert len(blk.losses) == 1
    assert float(blk.losses[0].asnumpy()) == 4.0


# ---------------------------------------------------------------------------
# native recordio
# ---------------------------------------------------------------------------
def test_native_recordio_matches_python(tmp_path):
    from incubator_mxnet_tpu import recordio
    from incubator_mxnet_tpu.native import load_recordio, NativeRecordFile
    if load_recordio() is None:
        pytest.skip("no C++ toolchain")
    path = str(tmp_path / "t.rec")
    w = recordio.MXRecordIO(path, "w")
    payloads = [b"hello", b"B" * 1000,
                b"A" * 5 + struct.pack("<I", 0x3ed7230a) + b"C" * 7]
    for p in payloads:
        w.write(p)
    w.close()
    nr = NativeRecordFile(path)
    assert len(nr) == 3
    for i, p in enumerate(payloads):
        assert nr.read(i) == p
    batch = nr.read_batch([0, 2], stride=8)
    assert batch.shape == (2, 8)
    assert batch[0].tobytes()[:5] == b"hello"
    nr.close()


# ---------------------------------------------------------------------------
# checkpoint / visualization
# ---------------------------------------------------------------------------
def test_checkpoint_roundtrip(tmp_path):
    from incubator_mxnet_tpu import checkpoint
    net = _toy_net()
    trainer = gluon.Trainer(net.collect_params(), "sgd",
                            {"learning_rate": 0.1, "momentum": 0.9})
    x = mx.np.ones((2, 8))
    with mx.autograd.record():
        net(x).sum().backward()
    trainer.step(2)
    path = str(tmp_path / "ckpt.npz")
    checkpoint.save_checkpoint(path, net, step=7, trainer=trainer)
    net2 = _toy_net()
    trainer2 = gluon.Trainer(net2.collect_params(), "sgd",
                             {"learning_rate": 0.1, "momentum": 0.9})
    params, step = checkpoint.load_checkpoint(path, net=net2,
                                              trainer=trainer2)
    assert step == 7
    for k, p in net.collect_params().items():
        np.testing.assert_array_equal(
            p.data().asnumpy(), net2.collect_params()[k].data().asnumpy())


def test_checkpoint_extensionless_path_and_underscore_keys(tmp_path):
    """Regression: np.savez silently appends .npz (breaking save->load on
    extension-less paths), and '__' in a param name used to collide with
    the '/' separator encoding."""
    from incubator_mxnet_tpu import checkpoint
    params = {"encoder__block_1": {"w__bias": mx.np.ones((2, 2)),
                                   "_private": mx.np.zeros((3,))}}
    path = checkpoint.save_checkpoint(str(tmp_path / "ckpt"), params, step=4)
    assert path.endswith(".npz")
    loaded, step = checkpoint.load_checkpoint(str(tmp_path / "ckpt"))
    assert step == 4
    assert set(loaded) == {"encoder__block_1/w__bias",
                           "encoder__block_1/_private"}
    np.testing.assert_array_equal(
        loaded["encoder__block_1/w__bias"].asnumpy(), np.ones((2, 2)))


def test_checkpoint_legacy_v1_format_loads(tmp_path):
    """v1 files (no __fmt__ marker, '/'->'__' keys) still load correctly."""
    from incubator_mxnet_tpu import checkpoint
    path = str(tmp_path / "old.npz")
    np.savez(path, __step__=np.asarray(3),
             **{"encoder__w": np.ones((2, 2))})
    loaded, step = checkpoint.load_checkpoint(path)
    assert step == 3
    assert set(loaded) == {"encoder/w"}


def test_sharded_checkpoint_restore_with_target_resharding(tmp_path):
    """load_sharded(target=...) must honor the target tree's shardings
    (orbax args API) instead of silently ignoring it."""
    from incubator_mxnet_tpu import checkpoint
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        pytest.skip("orbax unavailable")
    tree = {"w": jnp.arange(16.0).reshape(8, 2)}
    checkpoint.save_sharded(str(tmp_path / "s"), tree, step=1)
    devs = jax.devices("cpu")[:4]
    mesh = jax.sharding.Mesh(np.array(devs), ("dp",))
    sharding = NamedSharding(mesh, P("dp", None))
    target = {"w": jax.device_put(jnp.zeros((8, 2)), sharding)}
    restored, step = checkpoint.load_sharded(str(tmp_path / "s"),
                                             target=target)
    assert step == 1
    np.testing.assert_array_equal(np.asarray(restored["w"]),
                                  np.arange(16.0).reshape(8, 2))
    assert restored["w"].sharding.is_equivalent_to(sharding, 2)


def test_sharded_checkpoint_roundtrip(tmp_path):
    from incubator_mxnet_tpu import checkpoint
    import jax.numpy as jnp
    try:
        import orbax.checkpoint  # noqa: F401
    except ImportError:
        pytest.skip("orbax unavailable")
    tree = {"layer": {"w": jnp.arange(6.0).reshape(2, 3),
                      "b": jnp.zeros(3)},
            "step_count": jnp.asarray(5)}
    checkpoint.save_sharded(str(tmp_path / "sharded"), tree, step=3)
    assert checkpoint.latest_step(str(tmp_path / "sharded")) == 3
    restored, step = checkpoint.load_sharded(str(tmp_path / "sharded"))
    assert step == 3
    np.testing.assert_array_equal(np.asarray(restored["layer"]["w"]),
                                  np.arange(6.0).reshape(2, 3))


def test_visualization(tmp_path):
    from incubator_mxnet_tpu import visualization
    net = _toy_net()
    dot = visualization.plot_network(net, save_path=str(tmp_path / "g.dot"))
    assert "digraph" in dot and "Dense" in dot
    assert (tmp_path / "g.dot").exists()


def test_opperf_harness_smoke():
    """The per-op benchmark harness must run and produce rows (opperf
    parity, /root/reference/benchmark/opperf)."""
    import sys, os
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__)))))
    from benchmark import opperf
    res = opperf.run(categories=["optimizer"])
    rows = res["optimizer"]
    assert len(rows) == 2
    for r in rows:
        assert "error" not in r, r
        assert r["jit_us"] > 0
