"""INT8 quantization + gradient compression tests (≙ reference
tests/python/quantization/ + tests/nightly/dist_sync_kvstore.py:232-372)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.contrib import quantization as q


def test_quantize_dequantize_roundtrip():
    x = mx.np.array(np.random.randn(4, 8).astype(np.float32))
    qd, mn, mxr = q.quantize_v2(x)
    assert str(qd.dtype) == "int8"
    back = q.dequantize(qd, mn, mxr)
    step = max(abs(mn), mxr) / 127
    assert float(abs(back.asnumpy() - x.asnumpy()).max()) <= step * 1.01


def test_quantize_with_calib_range():
    x = mx.np.array(np.array([0.1, 5.0, -0.2], np.float32))
    qd, mn, mxr = q.quantize_v2(x, -1.0, 1.0)
    a = qd.asnumpy()
    assert a[1] == 127  # clipped at calibrated range


def test_quantize_net_dense_close_to_fp32():
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(8, in_units=32))
    net.initialize()
    x = mx.np.array(np.random.randn(4, 16).astype(np.float32))
    ref = net(x).asnumpy()
    calib = DataLoader(ArrayDataset(x.asnumpy()), batch_size=4)
    q.quantize_net(net, calib_data=calib)
    got = net(x).asnumpy()
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_net_conv():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, in_channels=2))
    net.initialize()
    x = mx.np.array(np.random.randn(1, 2, 8, 8).astype(np.float32))
    ref = net(x).asnumpy()
    q.quantize_net(net)
    got = net(x).asnumpy()
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_kl_threshold_reasonable():
    hist = np.zeros(2048)
    hist[:1024] = 100  # mass concentrated in lower half
    hist[2047] = 1     # single outlier
    thr = q._kl_threshold(hist, amax=8.0)
    assert 2.0 < thr <= 8.0  # clipped well below the outlier


def test_gradient_compression_2bit():
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("2bit", threshold=0.5)
    g = mx.np.array(np.array([1.0, 0.2, -0.7, 0.0], np.float32))
    out = gc.compress("k", g)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0, -0.5, 0.0])
    # error feedback: residual accumulates toward eventual transmission
    out2 = gc.compress("k", g)
    np.testing.assert_allclose(out2.asnumpy(), [0.5, 0.0, -0.5, 0.0])
    out3 = gc.compress("k", g)
    # after 3 pushes of 0.2, residual 0.6 > threshold → fires
    assert out3.asnumpy()[1] == 0.5


def test_gradient_compression_1bit():
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("1bit", threshold=0.25)
    g = mx.np.array(np.array([0.9, -0.1], np.float32))
    out = gc.compress("k", g)
    np.testing.assert_allclose(out.asnumpy(), [0.25, -0.25])


def test_kvstore_compression_integration():
    kv = mx.kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", mx.np.zeros((3,)))
    kv.push("w", mx.np.array(np.array([2.0, 0.1, -3.0], np.float32)))
    out = mx.np.zeros((3,))
    kv.pull("w", out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0, -0.5])


def test_compression_convergence_preserved():
    """Error feedback ⇒ mean of compressed grads ≈ mean of true grads."""
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("2bit", threshold=0.1)
    rng = np.random.default_rng(0)
    true_sum = np.zeros(16, np.float32)
    sent_sum = np.zeros(16, np.float32)
    for _ in range(200):
        g = rng.normal(0, 0.05, 16).astype(np.float32)
        true_sum += g
        sent_sum += gc.compress("k", mx.np.array(g)).asnumpy()
    np.testing.assert_allclose(sent_sum, true_sum, atol=0.25)
