"""INT8 quantization + gradient compression tests (≙ reference
tests/python/quantization/ + tests/nightly/dist_sync_kvstore.py:232-372)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.contrib import quantization as q


def test_quantize_dequantize_roundtrip():
    x = mx.np.array(np.random.randn(4, 8).astype(np.float32))
    qd, mn, mxr = q.quantize_v2(x)
    assert str(qd.dtype) == "int8"
    back = q.dequantize(qd, mn, mxr)
    step = max(abs(mn), mxr) / 127
    assert float(abs(back.asnumpy() - x.asnumpy()).max()) <= step * 1.01


def test_quantize_with_calib_range():
    x = mx.np.array(np.array([0.1, 5.0, -0.2], np.float32))
    qd, mn, mxr = q.quantize_v2(x, -1.0, 1.0)
    a = qd.asnumpy()
    assert a[1] == 127  # clipped at calibrated range


def test_quantize_net_dense_close_to_fp32():
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(8, in_units=32))
    net.initialize()
    x = mx.np.array(np.random.randn(4, 16).astype(np.float32))
    ref = net(x).asnumpy()
    calib = DataLoader(ArrayDataset(x.asnumpy()), batch_size=4)
    q.quantize_net(net, calib_data=calib)
    got = net(x).asnumpy()
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_net_conv():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, in_channels=2))
    net.initialize()
    x = mx.np.array(np.random.randn(1, 2, 8, 8).astype(np.float32))
    ref = net(x).asnumpy()
    q.quantize_net(net)
    got = net(x).asnumpy()
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_kl_threshold_reasonable():
    hist = np.zeros(2048)
    hist[:1024] = 100  # mass concentrated in lower half
    hist[2047] = 1     # single outlier
    thr = q._kl_threshold(hist, amax=8.0)
    assert 2.0 < thr <= 8.0  # clipped well below the outlier


def test_gradient_compression_2bit():
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("2bit", threshold=0.5)
    g = mx.np.array(np.array([1.0, 0.2, -0.7, 0.0], np.float32))
    out = gc.compress("k", g)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0, -0.5, 0.0])
    # error feedback: residual accumulates toward eventual transmission
    out2 = gc.compress("k", g)
    np.testing.assert_allclose(out2.asnumpy(), [0.5, 0.0, -0.5, 0.0])
    out3 = gc.compress("k", g)
    # after 3 pushes of 0.2, residual 0.6 > threshold → fires
    assert out3.asnumpy()[1] == 0.5


def test_gradient_compression_1bit():
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("1bit", threshold=0.25)
    g = mx.np.array(np.array([0.9, -0.1], np.float32))
    out = gc.compress("k", g)
    np.testing.assert_allclose(out.asnumpy(), [0.25, -0.25])


def test_kvstore_compression_integration():
    kv = mx.kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", mx.np.zeros((3,)))
    kv.push("w", mx.np.array(np.array([2.0, 0.1, -3.0], np.float32)))
    out = mx.np.zeros((3,))
    kv.pull("w", out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0, -0.5])


def test_compression_convergence_preserved():
    """Error feedback ⇒ mean of compressed grads ≈ mean of true grads."""
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("2bit", threshold=0.1)
    rng = np.random.default_rng(0)
    true_sum = np.zeros(16, np.float32)
    sent_sum = np.zeros(16, np.float32)
    for _ in range(200):
        g = rng.normal(0, 0.05, 16).astype(np.float32)
        true_sum += g
        sent_sum += gc.compress("k", mx.np.array(g)).asnumpy()
    np.testing.assert_allclose(sent_sum, true_sum, atol=0.25)


def test_int8_dense_flatten_false_3d():
    """Regression: Int8Dense must contract the LAST axis like fp32 dense."""
    net = nn.HybridSequential()
    net.add(nn.Dense(6, in_units=5, flatten=False))
    net.initialize()
    x = mx.np.array(np.random.randn(2, 3, 5).astype(np.float32))
    ref = net(x).asnumpy()
    q.quantize_net(net)
    got = net(x).asnumpy()
    assert got.shape == ref.shape == (2, 3, 6)
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05


def test_int8_conv_nhwc_bias():
    """Regression: Int8Conv2D bias must follow the layout's channel axis."""
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, in_channels=2, layout="NHWC"))
    net.initialize()
    x = mx.np.array(np.random.randn(1, 8, 8, 2).astype(np.float32))
    ref = net(x).asnumpy()
    q.quantize_net(net)
    got = net(x).asnumpy()
    assert got.shape == ref.shape
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05


def test_entropy_calibration_range_growth():
    """Regression: growing amax across batches must rebin, not mix ranges."""
    c = q.CalibrationCollector(mode="entropy", num_bins=64)
    hook = c._make_hook("l")
    hook(None, (mx.np.array(np.random.uniform(0, 1, 1000).astype(np.float32)),), None)
    hook(None, (mx.np.array(np.random.uniform(0, 10, 1000).astype(np.float32)),), None)
    st = c.stats["l"]
    assert st["amax"] == pytest.approx(10.0, rel=0.01)
    assert st["hist"].sum() == pytest.approx(2000, abs=2)
    thr = c.threshold("l")
    assert 0 < thr <= 10.0


def test_quantize_net_hybridized():
    """Regression: quantize_net must work on hybridized nets (calibration
    bypasses the cached graph; int8 layers trace cleanly under jit)."""
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.np.array(np.random.randn(4, 8).astype(np.float32))
    ref = net(x).asnumpy()  # build the cache first
    calib = DataLoader(ArrayDataset(x.asnumpy()), batch_size=4)
    q.quantize_net(net, calib_data=calib)
    got = net(x).asnumpy()
    got2 = net(x).asnumpy()  # second call exercises the re-built cache
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel
    np.testing.assert_allclose(got, got2, rtol=1e-6)


def test_custom_op_sees_is_train():
    from incubator_mxnet_tpu import operator as op_mod
    seen = []

    class Probe(op_mod.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            seen.append(is_train)
            self.assign(out_data[0], req[0], in_data[0])

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0], out_grad[0])

    @op_mod.register("probe_train")
    class ProbeProp(op_mod.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Probe()

    x = mx.np.ones((2,))
    op_mod.invoke("probe_train", x)
    with mx.autograd.record():
        op_mod.invoke("probe_train", x)
    assert seen == [False, True]


def test_trainer_compression_without_kvstore_raises():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          compression_params={"type": "2bit",
                                              "threshold": 0.5})
    x = mx.np.ones((2, 2))
    with mx.autograd.record():
        net(x).sum().backward()
    with pytest.raises(mx.MXNetError):
        tr.step(2)


def test_requantize_uses_calibrated_range():
    q32 = mx.np.array(np.array([2 ** 30, -(2 ** 30)], np.int64).astype(np.int32))
    q8, mn, mxr = q.requantize(q32, -4.0, 4.0)
    # 2^30 = half of int32 range → half of the calibrated range → ~64
    np.testing.assert_allclose(q8.asnumpy(), [64, -64], atol=1)


# ---------------------------------------------------------------------------
# quantized op family (≙ src/operator/quantization/quantized_*.cc)
# ---------------------------------------------------------------------------

def _quant(xn):
    from incubator_mxnet_tpu.contrib import quantization as q
    qx, mn, mx_ = q.quantize_v2(mx.np.array(xn))
    # auto-calibrated ranges come back as 0-d NDArrays (device-computed,
    # no host sync in the op path); tests want Python floats
    return q, qx, float(mn), float(mx_)


def test_quantized_act_relu():
    xn = np.random.RandomState(0).randn(4, 8).astype(np.float32)
    q, qx, mn, mx_ = _quant(xn)
    qy, omn, omx = q.quantized_act(qx, mn, mx_)
    y = q.dequantize(qy, omn, omx).asnumpy()
    np.testing.assert_allclose(y, np.maximum(xn, 0), atol=2 * mx_ / 127)
    assert omn == 0.0


def test_quantized_pooling_max_and_avg():
    xn = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
    q, qx, mn, mx_ = _quant(xn)
    for ptype, ref in (("max", None), ("avg", None)):
        qy, omn, omx = q.quantized_pooling(qx, mn, mx_, pool_type=ptype,
                                           kernel=(2, 2))
        y = q.dequantize(qy, omn, omx).asnumpy()
        import jax.numpy as jnp
        from incubator_mxnet_tpu.ops import nn as _nn
        want = np.asarray(_nn.pooling(jnp.asarray(xn), (2, 2),
                                      pool_type=ptype))
        np.testing.assert_allclose(y, want, atol=3 * mx_ / 127)


def test_quantized_concat_rescales_to_widest():
    a = np.random.RandomState(2).randn(2, 3).astype(np.float32)
    b = 4.0 * np.random.RandomState(3).randn(2, 5).astype(np.float32)
    q, qa, amn, amx = _quant(a)
    _, qb, bmn, bmx = _quant(b)
    qy, omn, omx = q.quantized_concat([qa, qb], [(amn, amx), (bmn, bmx)],
                                      axis=1)
    y = q.dequantize(qy, omn, omx).asnumpy()
    want = np.concatenate([a, b], axis=1)
    np.testing.assert_allclose(y, want, atol=3 * omx / 127)


def test_quantized_elemwise_add_mul():
    rng = np.random.RandomState(4)
    a = rng.randn(3, 4).astype(np.float32)
    b = rng.randn(3, 4).astype(np.float32)
    q, qa, amn, amx = _quant(a)
    _, qb, bmn, bmx = _quant(b)
    qs, smn, smx = q.quantized_elemwise_add(qa, (amn, amx), qb, (bmn, bmx))
    np.testing.assert_allclose(q.dequantize(qs, smn, smx).asnumpy(), a + b,
                               atol=4 * smx / 127)
    qm, mmn, mmx = q.quantized_elemwise_mul(qa, (amn, amx), qb, (bmn, bmx))
    np.testing.assert_allclose(q.dequantize(qm, mmn, mmx).asnumpy(), a * b,
                               atol=4 * mmx / 127)


def test_quantized_batch_norm():
    rng = np.random.RandomState(5)
    xn = rng.randn(2, 4, 5, 5).astype(np.float32)
    gamma = rng.uniform(0.5, 1.5, 4).astype(np.float32)
    beta = rng.randn(4).astype(np.float32)
    mu = rng.randn(4).astype(np.float32) * 0.1
    var = rng.uniform(0.5, 2.0, 4).astype(np.float32)
    q, qx, mn, mx_ = _quant(xn)
    want = ((xn - mu[None, :, None, None])
            / np.sqrt(var[None, :, None, None] + 1e-5)
            * gamma[None, :, None, None] + beta[None, :, None, None])
    amax = float(np.abs(want).max())
    qy, omn, omx = q.quantized_batch_norm(
        qx, mn, mx_, mx.np.array(gamma), mx.np.array(beta),
        mx.np.array(mu), mx.np.array(var), min_calib=-amax, max_calib=amax)
    y = q.dequantize(qy, omn, omx).asnumpy()
    np.testing.assert_allclose(y, want, atol=4 * amax / 127)


def test_quantized_embedding():
    rng = np.random.RandomState(6)
    w = rng.randn(10, 6).astype(np.float32)
    q, qw, wmn, wmx = _quant(w)
    idx = mx.np.array(np.array([1, 3, 9], np.int32))
    y = q.quantized_embedding(idx, qw, wmn, wmx).asnumpy()
    np.testing.assert_allclose(y, w[[1, 3, 9]], atol=2 * wmx / 127)


def test_quantized_fully_connected_chain():
    """int8-in/int8-out chaining: fc -> relu -> fc stays on int codes."""
    rng = np.random.RandomState(7)
    xn = rng.randn(4, 8).astype(np.float32)
    w1 = rng.randn(16, 8).astype(np.float32) * 0.3
    w2 = rng.randn(5, 16).astype(np.float32) * 0.3
    ref = np.maximum(xn @ w1.T, 0) @ w2.T

    q, qx, xmn, xmx = _quant(xn)
    _, qw1, w1mn, w1mx = _quant(w1)
    _, qw2, w2mn, w2mx = _quant(w2)
    h_real = xn @ w1.T
    h_amax = float(np.abs(h_real).max())
    qh, hmn, hmx = q.quantized_fully_connected(
        qx, (xmn, xmx), qw1, (w1mn, w1mx),
        min_calib=-h_amax, max_calib=h_amax)
    qh, hmn, hmx = q.quantized_act(qh, hmn, hmx)
    out = q.quantized_fully_connected(qh, (hmn, hmx), qw2, (w2mn, w2mx))
    np.testing.assert_allclose(out.asnumpy(), ref, rtol=0.15,
                               atol=0.15 * np.abs(ref).max())


def test_fold_batch_norm_pass():
    """conv+bn fold must preserve the inference function exactly."""
    from incubator_mxnet_tpu.contrib.quantization import fold_batch_norm
    net = nn.HybridSequential()
    net.add(nn.Conv2D(8, 3, padding=1, use_bias=False),
            nn.BatchNorm(),
            nn.Activation("relu"),
            nn.Dense(4))
    net.initialize()
    x = mx.np.array(np.random.RandomState(8).randn(2, 3, 6, 6)
                    .astype(np.float32))
    net(x)  # shape inference
    # give BN non-trivial running stats
    bn = net._children["1"]
    bn.running_mean.set_data(mx.np.array(
        np.random.RandomState(9).randn(8).astype(np.float32) * 0.2))
    bn.running_var.set_data(mx.np.array(
        np.random.RandomState(10).uniform(0.5, 2.0, 8).astype(np.float32)))
    bn.gamma.set_data(mx.np.array(
        np.random.RandomState(11).uniform(0.5, 1.5, 8).astype(np.float32)))
    bn.beta.set_data(mx.np.array(
        np.random.RandomState(12).randn(8).astype(np.float32)))
    with mx.autograd.predict_mode():
        before = net(x).asnumpy()
    n = fold_batch_norm(net)
    assert n == 1
    with mx.autograd.predict_mode():
        after = net(x).asnumpy()
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-4)


def test_quantize_net_folds_bn_by_default():
    from incubator_mxnet_tpu.contrib.quantization import quantize_net
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, use_bias=False), nn.BatchNorm(),
            nn.Activation("relu"), nn.Dense(3))
    net.initialize()
    x = mx.np.array(np.random.RandomState(13).randn(2, 3, 6, 6)
                    .astype(np.float32))
    net(x)
    with mx.autograd.predict_mode():
        ref = net(x).asnumpy()
    quantize_net(net, calib_data=[(x,)], num_batches=1)
    with mx.autograd.predict_mode():
        out = net(x).asnumpy()
    # int8 end-to-end stays close to fp32
    assert np.abs(out - ref).max() < 0.25 * max(np.abs(ref).max(), 1.0)
    assert "Identity" in repr(net._children["1"])


def test_fold_bn_attribute_registered_and_act_guard():
    """Fold must also clear attribute references (custom forward calling
    self.bn) and must NOT fold across a conv's baked activation."""
    from incubator_mxnet_tpu.contrib.quantization import fold_batch_norm

    class Custom(mx.gluon.HybridBlock):
        def __init__(self):
            super().__init__()
            self.conv = nn.Conv2D(4, 3, padding=1, use_bias=False)
            self.bn = nn.BatchNorm()

        def forward(self, x):
            return self.bn(self.conv(x))

    net = Custom()
    net.initialize()
    x = mx.np.array(np.random.RandomState(20).randn(2, 3, 6, 6)
                    .astype(np.float32))
    net(x)
    net.bn.running_mean.set_data(mx.np.array(
        np.random.RandomState(21).randn(4).astype(np.float32) * 0.3))
    net.bn.running_var.set_data(mx.np.array(
        np.random.RandomState(22).uniform(0.5, 2.0, 4).astype(np.float32)))
    with mx.autograd.predict_mode():
        before = net(x).asnumpy()
    # custom (non-sequential) blocks fold only when the caller asserts the
    # dataflow with aggressive=True
    assert fold_batch_norm(net) == 0
    assert fold_batch_norm(net, aggressive=True) == 1
    with mx.autograd.predict_mode():
        after = net(x).asnumpy()
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-4)

    # baked activation between conv and BN -> must refuse to fold
    act_net = nn.HybridSequential()
    act_net.add(nn.Conv2D(4, 3, padding=1, activation="relu"),
                nn.BatchNorm())
    act_net.initialize()
    act_net(x)
    assert fold_batch_norm(act_net) == 0


def test_fold_bn_nhwc_layout():
    from incubator_mxnet_tpu.contrib.quantization import fold_batch_norm
    net = nn.HybridSequential()
    net.add(nn.Conv2D(5, 3, padding=1, layout="NHWC", use_bias=False),
            nn.BatchNorm(axis=3))
    net.initialize()
    x = mx.np.array(np.random.RandomState(23).randn(2, 6, 6, 3)
                    .astype(np.float32))
    net(x)
    bn = net._children["1"]
    bn.running_mean.set_data(mx.np.array(
        np.random.RandomState(24).randn(5).astype(np.float32) * 0.2))
    bn.running_var.set_data(mx.np.array(
        np.random.RandomState(25).uniform(0.5, 2.0, 5).astype(np.float32)))
    with mx.autograd.predict_mode():
        before = net(x).asnumpy()
    assert fold_batch_norm(net) == 1
    with mx.autograd.predict_mode():
        after = net(x).asnumpy()
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-4)


def test_fold_bn_relu_keeps_activation():
    from incubator_mxnet_tpu.contrib.quantization import fold_batch_norm
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, use_bias=False), nn.BatchNormReLU())
    net.initialize()
    x = mx.np.array(np.random.RandomState(30).randn(2, 3, 6, 6)
                    .astype(np.float32))
    net(x)
    bn = net._children["1"]
    bn.running_mean.set_data(mx.np.array(
        np.random.RandomState(31).randn(4).astype(np.float32) * 0.3))
    bn.running_var.set_data(mx.np.array(
        np.random.RandomState(32).uniform(0.5, 2.0, 4).astype(np.float32)))
    with mx.autograd.predict_mode():
        before = net(x).asnumpy()
    assert (before >= 0).all()          # BatchNormReLU clamps
    assert fold_batch_norm(net) == 1
    with mx.autograd.predict_mode():
        after = net(x).asnumpy()
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-4)
    assert "ReLU" in repr(net._children["1"])


def test_fold_bn_axis_mismatch_refused():
    from incubator_mxnet_tpu.contrib.quantization import fold_batch_norm
    net = nn.HybridSequential()
    # NHWC conv (channel axis 3) + default BatchNorm(axis=1): must refuse
    net.add(nn.Conv2D(6, 3, padding=1, layout="NHWC", use_bias=False),
            nn.BatchNorm())
    net.initialize()
    x = mx.np.array(np.random.RandomState(33).randn(2, 6, 6, 3)
                    .astype(np.float32))
    net(x)
    assert fold_batch_norm(net) == 0


def test_fold_bn_negative_axis_normalized():
    from incubator_mxnet_tpu.contrib.quantization import fold_batch_norm
    net = nn.HybridSequential()
    net.add(nn.Conv2D(5, 3, padding=1, layout="NHWC", use_bias=False),
            nn.BatchNorm(axis=-1))       # -1 == 3 for 4-D input
    net.initialize()
    x = mx.np.array(np.random.RandomState(40).randn(2, 6, 6, 3)
                    .astype(np.float32))
    net(x)
    with mx.autograd.predict_mode():
        before = net(x).asnumpy()
    assert fold_batch_norm(net) == 1
    with mx.autograd.predict_mode():
        after = net(x).asnumpy()
    np.testing.assert_allclose(after, before, rtol=1e-4, atol=1e-4)


def test_gradient_compression_packed_wire():
    """The wire payload is bit-packed uint32 words (≙ the reference's
    gradient_compression.cc word packing): 16 values/word at 2 bits,
    32 values/word at 1 bit; unpack+sum reconstructs the quantized sum."""
    import math
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression

    rng = np.random.RandomState(7)
    for ctype, thr, vpw in (("2bit", 0.5, 16), ("1bit", 0.25, 32)):
        n = 1000
        grads = [rng.randn(n).astype(np.float32) for _ in range(3)]
        workers = [GradientCompression(ctype, threshold=thr)
                   for _ in range(3)]
        payloads = [w.compress_packed("k", mx.np.array(g))
                    for w, g in zip(workers, grads)]
        # payload size: the whole point — ceil(n/vpw) words, not n floats
        for p in payloads:
            assert str(p.dtype) == "uint32"
            assert p.size == math.ceil(n / vpw)
            assert p.size * 4 * (vpw // 4) <= n * 4  # ≥(vpw/4)x smaller
        stack = np.stack([np.asarray(p) for p in payloads])
        got = workers[0].decompress_sum(stack, (n,)).asnumpy()
        # reference semantics: sum of each worker's quantized grad
        expect = np.zeros(n, np.float32)
        for g in grads:
            if ctype == "2bit":
                expect += np.where(g >= thr, thr,
                                   np.where(g <= -thr, -thr, 0.0))
            else:
                expect += np.where(g >= 0, thr, -thr)
        np.testing.assert_allclose(got, expect, rtol=1e-6, atol=1e-6)
        # error feedback: residual carries the quantization error
        r0 = workers[0]._residuals["k"].asnumpy()
        q0 = (np.where(grads[0] >= thr, thr,
                       np.where(grads[0] <= -thr, -thr, 0.0))
              if ctype == "2bit" else
              np.where(grads[0] >= 0, thr, -thr))
        np.testing.assert_allclose(r0, grads[0] - q0, rtol=1e-5, atol=1e-6)


def test_gradient_compression_mixed_paths():
    """compress() after compress_packed() on one instance (the jit caches
    for the two paths share a dict and must not shadow each other)."""
    import numpy as np
    import incubator_mxnet_tpu as mx
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("2bit", threshold=0.5)
    g = mx.np.array(np.array([0.7, -0.7, 0.1], np.float32))
    gc.compress_packed("a", g)
    out = gc.compress("b", g)
    np.testing.assert_allclose(out.asnumpy(), [0.5, -0.5, 0.0])


def test_quantize_v2_auto_is_segment_safe():
    """Auto-calibration must not host-sync inside the op path: a chain of
    auto quantize_v2 calls stays DEFERRED in the bulking segment until the
    caller actually reads a value (VERDICT-r3 Weak #4)."""
    from incubator_mxnet_tpu.contrib import quantization as q
    from incubator_mxnet_tpu.ops import segment

    xs = [mx.np.array(np.random.RandomState(i).randn(8).astype(np.float32))
          for i in range(4)]
    with mx.engine.bulk(32):
        outs = [q.quantize_v2(x) for x in xs]
        seg = segment._current(create=False)
        # all 4 quantize ops (and their range outputs) still enqueued
        assert seg is not None and seg.ops is not None and len(seg.ops) >= 4
    for x, (qd, mn, mxr) in zip(xs, outs):
        amax = max(abs(x.asnumpy()).max(), 1e-12)
        np.testing.assert_allclose(float(mxr), amax, rtol=1e-6)
        np.testing.assert_allclose(
            qd.asnumpy(),
            np.clip(np.round(x.asnumpy() * 127.0 / amax), -127, 127))
