"""INT8 quantization + gradient compression tests (≙ reference
tests/python/quantization/ + tests/nightly/dist_sync_kvstore.py:232-372)."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.contrib import quantization as q


def test_quantize_dequantize_roundtrip():
    x = mx.np.array(np.random.randn(4, 8).astype(np.float32))
    qd, mn, mxr = q.quantize_v2(x)
    assert str(qd.dtype) == "int8"
    back = q.dequantize(qd, mn, mxr)
    step = max(abs(mn), mxr) / 127
    assert float(abs(back.asnumpy() - x.asnumpy()).max()) <= step * 1.01


def test_quantize_with_calib_range():
    x = mx.np.array(np.array([0.1, 5.0, -0.2], np.float32))
    qd, mn, mxr = q.quantize_v2(x, -1.0, 1.0)
    a = qd.asnumpy()
    assert a[1] == 127  # clipped at calibrated range


def test_quantize_net_dense_close_to_fp32():
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    net = nn.HybridSequential()
    net.add(nn.Dense(32, activation="relu", in_units=16),
            nn.Dense(8, in_units=32))
    net.initialize()
    x = mx.np.array(np.random.randn(4, 16).astype(np.float32))
    ref = net(x).asnumpy()
    calib = DataLoader(ArrayDataset(x.asnumpy()), batch_size=4)
    q.quantize_net(net, calib_data=calib)
    got = net(x).asnumpy()
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_quantize_net_conv():
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, in_channels=2))
    net.initialize()
    x = mx.np.array(np.random.randn(1, 2, 8, 8).astype(np.float32))
    ref = net(x).asnumpy()
    q.quantize_net(net)
    got = net(x).asnumpy()
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel


def test_kl_threshold_reasonable():
    hist = np.zeros(2048)
    hist[:1024] = 100  # mass concentrated in lower half
    hist[2047] = 1     # single outlier
    thr = q._kl_threshold(hist, amax=8.0)
    assert 2.0 < thr <= 8.0  # clipped well below the outlier


def test_gradient_compression_2bit():
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("2bit", threshold=0.5)
    g = mx.np.array(np.array([1.0, 0.2, -0.7, 0.0], np.float32))
    out = gc.compress("k", g)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0, -0.5, 0.0])
    # error feedback: residual accumulates toward eventual transmission
    out2 = gc.compress("k", g)
    np.testing.assert_allclose(out2.asnumpy(), [0.5, 0.0, -0.5, 0.0])
    out3 = gc.compress("k", g)
    # after 3 pushes of 0.2, residual 0.6 > threshold → fires
    assert out3.asnumpy()[1] == 0.5


def test_gradient_compression_1bit():
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("1bit", threshold=0.25)
    g = mx.np.array(np.array([0.9, -0.1], np.float32))
    out = gc.compress("k", g)
    np.testing.assert_allclose(out.asnumpy(), [0.25, -0.25])


def test_kvstore_compression_integration():
    kv = mx.kvstore.create("device")
    kv.set_gradient_compression({"type": "2bit", "threshold": 0.5})
    kv.init("w", mx.np.zeros((3,)))
    kv.push("w", mx.np.array(np.array([2.0, 0.1, -3.0], np.float32)))
    out = mx.np.zeros((3,))
    kv.pull("w", out)
    np.testing.assert_allclose(out.asnumpy(), [0.5, 0.0, -0.5])


def test_compression_convergence_preserved():
    """Error feedback ⇒ mean of compressed grads ≈ mean of true grads."""
    from incubator_mxnet_tpu.kvstore.gradient_compression import \
        GradientCompression
    gc = GradientCompression("2bit", threshold=0.1)
    rng = np.random.default_rng(0)
    true_sum = np.zeros(16, np.float32)
    sent_sum = np.zeros(16, np.float32)
    for _ in range(200):
        g = rng.normal(0, 0.05, 16).astype(np.float32)
        true_sum += g
        sent_sum += gc.compress("k", mx.np.array(g)).asnumpy()
    np.testing.assert_allclose(sent_sum, true_sum, atol=0.25)


def test_int8_dense_flatten_false_3d():
    """Regression: Int8Dense must contract the LAST axis like fp32 dense."""
    net = nn.HybridSequential()
    net.add(nn.Dense(6, in_units=5, flatten=False))
    net.initialize()
    x = mx.np.array(np.random.randn(2, 3, 5).astype(np.float32))
    ref = net(x).asnumpy()
    q.quantize_net(net)
    got = net(x).asnumpy()
    assert got.shape == ref.shape == (2, 3, 6)
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05


def test_int8_conv_nhwc_bias():
    """Regression: Int8Conv2D bias must follow the layout's channel axis."""
    net = nn.HybridSequential()
    net.add(nn.Conv2D(4, 3, padding=1, in_channels=2, layout="NHWC"))
    net.initialize()
    x = mx.np.array(np.random.randn(1, 8, 8, 2).astype(np.float32))
    ref = net(x).asnumpy()
    q.quantize_net(net)
    got = net(x).asnumpy()
    assert got.shape == ref.shape
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05


def test_entropy_calibration_range_growth():
    """Regression: growing amax across batches must rebin, not mix ranges."""
    c = q.CalibrationCollector(mode="entropy", num_bins=64)
    hook = c._make_hook("l")
    hook(None, (mx.np.array(np.random.uniform(0, 1, 1000).astype(np.float32)),), None)
    hook(None, (mx.np.array(np.random.uniform(0, 10, 1000).astype(np.float32)),), None)
    st = c.stats["l"]
    assert st["amax"] == pytest.approx(10.0, rel=0.01)
    assert st["hist"].sum() == pytest.approx(2000, abs=2)
    thr = c.threshold("l")
    assert 0 < thr <= 10.0


def test_quantize_net_hybridized():
    """Regression: quantize_net must work on hybridized nets (calibration
    bypasses the cached graph; int8 layers trace cleanly under jit)."""
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8), nn.Dense(4))
    net.initialize()
    net.hybridize()
    x = mx.np.array(np.random.randn(4, 8).astype(np.float32))
    ref = net(x).asnumpy()  # build the cache first
    calib = DataLoader(ArrayDataset(x.asnumpy()), batch_size=4)
    q.quantize_net(net, calib_data=calib)
    got = net(x).asnumpy()
    got2 = net(x).asnumpy()  # second call exercises the re-built cache
    rel = np.abs(got - ref).max() / (np.abs(ref).max() + 1e-9)
    assert rel < 0.05, rel
    np.testing.assert_allclose(got, got2, rtol=1e-6)


def test_custom_op_sees_is_train():
    from incubator_mxnet_tpu import operator as op_mod
    seen = []

    class Probe(op_mod.CustomOp):
        def forward(self, is_train, req, in_data, out_data, aux):
            seen.append(is_train)
            self.assign(out_data[0], req[0], in_data[0])

        def backward(self, req, out_grad, in_data, out_data, in_grad, aux):
            self.assign(in_grad[0], req[0], out_grad[0])

    @op_mod.register("probe_train")
    class ProbeProp(op_mod.CustomOpProp):
        def create_operator(self, ctx, shapes, dtypes):
            return Probe()

    x = mx.np.ones((2,))
    op_mod.invoke("probe_train", x)
    with mx.autograd.record():
        op_mod.invoke("probe_train", x)
    assert seen == [False, True]


def test_trainer_compression_without_kvstore_raises():
    net = nn.Dense(2, in_units=2)
    net.initialize()
    tr = mx.gluon.Trainer(net.collect_params(), "sgd",
                          compression_params={"type": "2bit",
                                              "threshold": 0.5})
    x = mx.np.ones((2, 2))
    with mx.autograd.record():
        net(x).sum().backward()
    with pytest.raises(mx.MXNetError):
        tr.step(2)


def test_requantize_uses_calibrated_range():
    q32 = mx.np.array(np.array([2 ** 30, -(2 ** 30)], np.int64).astype(np.int32))
    q8, mn, mxr = q.requantize(q32, -4.0, 4.0)
    # 2^30 = half of int32 range → half of the calibrated range → ~64
    np.testing.assert_allclose(q8.asnumpy(), [64, -64], atol=1)
