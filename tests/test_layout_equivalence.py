"""NCHW vs NHWC layout equivalence for the gluon conv/pool/norm family
(VERDICT-r4 Weak #4: the NCHW paths in ops/nn.py had thin direct
coverage). Each layer is built in both layouts with IDENTICAL weights;
outputs and input gradients must match after transposition — forward and
backward, eager and hybridized."""
import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon
from incubator_mxnet_tpu.gluon import nn


def _to_nhwc(x):
    return np.transpose(x, (0, 2, 3, 1))


def _from_nhwc(x):
    return np.transpose(x, (0, 3, 1, 2))


LAYERS = [
    ("conv", lambda lo: nn.Conv2D(6, 3, padding=1, layout=lo,
                                  in_channels=4)),
    ("conv_stride", lambda lo: nn.Conv2D(6, 3, strides=2, layout=lo,
                                         in_channels=4)),
    ("conv_dilated", lambda lo: nn.Conv2D(6, 3, dilation=2, padding=2,
                                          layout=lo, in_channels=4)),
    ("conv_grouped", lambda lo: nn.Conv2D(8, 3, padding=1, groups=2,
                                          layout=lo, in_channels=4)),
    ("deconv", lambda lo: nn.Conv2DTranspose(6, 4, strides=2, padding=1,
                                             layout=lo, in_channels=4)),
    ("maxpool", lambda lo: nn.MaxPool2D(2, layout=lo)),
    ("avgpool", lambda lo: nn.AvgPool2D(3, strides=2, padding=1,
                                        layout=lo)),
    ("globalpool", lambda lo: nn.GlobalAvgPool2D(layout=lo)),
    ("batchnorm", lambda lo: nn.BatchNorm(axis=1 if lo == "NCHW" else 3,
                                          in_channels=4)),
]


def _copy_params(src, dst, layout_src, layout_dst):
    """Copy weights between layout variants (conv kernels need the
    OIHW <-> HWIO permutation the layouts imply)."""
    sp, dp = src.collect_params(), dst.collect_params()
    for (k, ps), (_, pd) in zip(sorted(sp.items()), sorted(dp.items())):
        a = ps.data().asnumpy()
        if a.ndim == 4 and layout_src != layout_dst:
            if layout_src == "NCHW":        # OIHW -> HWIO
                a = np.transpose(a, (2, 3, 1, 0))
            else:                           # HWIO -> OIHW
                a = np.transpose(a, (3, 2, 0, 1))
        pd.data()[:] = mx.np.array(a)


@pytest.mark.parametrize("name,make", LAYERS, ids=[x[0] for x in LAYERS])
@pytest.mark.parametrize("hybrid", [False, True], ids=["eager", "jit"])
def test_layout_equivalence(name, make, hybrid):
    mx.seed(3)
    x_nchw = np.random.RandomState(0).randn(2, 4, 8, 8).astype(np.float32)

    a = make("NCHW")
    a.initialize()
    b = make("NHWC")
    b.initialize()
    xa = mx.np.array(x_nchw)
    xb = mx.np.array(_to_nhwc(x_nchw))
    a(xa)
    b(xb)         # resolve shapes
    _copy_params(a, b, "NCHW", "NHWC")
    if hybrid:
        a.hybridize()
        b.hybridize()

    xa.attach_grad()
    xb.attach_grad()
    with mx.autograd.record():
        ya = a(xa)
        La = (ya * ya).sum()      # layout-independent quadratic loss
    La.backward()
    with mx.autograd.record():
        yb = b(xb)
        Lb = (yb * yb).sum()
    Lb.backward()

    np.testing.assert_allclose(_to_nhwc(ya.asnumpy()), yb.asnumpy(),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(float(La.asnumpy()), float(Lb.asnumpy()),
                               rtol=1e-4)
    np.testing.assert_allclose(_to_nhwc(xa.grad.asnumpy()),
                               xb.grad.asnumpy(), rtol=1e-4, atol=1e-5)
