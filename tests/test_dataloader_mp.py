"""DataLoader multiprocessing workers + shared-memory batch rebuild
(the last §2.4 partial: ≙ reference dataloader.py:47-88,514 worker_loop +
CPUSharedStorageManager). Workers are SPAWNED with JAX pinned to CPU;
batches travel as shared-memory blocks the parent uploads and unlinks."""
import glob

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader


class _SquareDataset:
    """Picklable dataset with a Python (GIL-bound) transform."""

    def __init__(self, n):
        self._x = np.arange(n * 6, dtype=np.float32).reshape(n, 6)

    def __len__(self):
        return len(self._x)

    def __getitem__(self, i):
        return self._x[i] ** 2, np.float32(i)


def test_process_workers_match_serial():
    ds = _SquareDataset(23)
    serial = list(DataLoader(ds, batch_size=5, num_workers=0))
    mp_loader = DataLoader(ds, batch_size=5, num_workers=2,
                           thread_pool=False)
    got = list(mp_loader)
    assert len(got) == len(serial) == 5
    for (sx, sy), (gx, gy) in zip(serial, got):
        np.testing.assert_array_equal(sx.asnumpy(), gx.asnumpy())
        np.testing.assert_array_equal(sy.asnumpy(), gy.asnumpy())


def test_process_workers_two_epochs_and_cleanup():
    before = len(glob.glob("/dev/shm/psm_*"))
    ds = ArrayDataset(np.arange(40, dtype=np.float32).reshape(10, 4))
    loader = DataLoader(ds, batch_size=4, num_workers=2, thread_pool=False)
    for _ in range(2):
        total = 0
        for b in loader:
            total += b.shape[0]
        assert total == 10
    after = len(glob.glob("/dev/shm/psm_*"))
    # every block the workers created was unlinked by the parent
    assert after <= before


class _BoomDataset:
    def __len__(self):
        return 4

    def __getitem__(self, i):
        if i == 2:
            raise ValueError("boom at 2")
        return np.zeros(3, np.float32)


def test_worker_errors_propagate():
    loader = DataLoader(_BoomDataset(), batch_size=2, num_workers=2,
                        thread_pool=False)
    with pytest.raises(ValueError, match="boom at 2"):
        list(loader)
