"""Op-bulking (deferred segment) semantics.

Covers the engine's bulking path (ops/segment.py): deferral + flush-on-
materialize, replay-cache reuse across loop iterations, autograd over bulked
ops (incl. in-place mutation between forward and backward), re-entrant custom
Functions, cross-thread waitall coverage, and the disable knobs.

Reference anchors: engine bulking API include/mxnet/engine.h:310-317,
cached-op bulking src/imperative/cached_op.h:330, WaitForAll semantics
src/engine/threaded_engine.h.
"""
import threading

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import engine
from incubator_mxnet_tpu.ops import segment


def test_defers_and_flushes_on_materialize():
    a = mx.np.array(np.ones((8, 8), np.float32))
    b = a * 2.0 + 1.0
    c = b.sum()
    assert segment.current_size() >= 2          # pending, not executed
    assert c.shape == () and b.shape == (8, 8)  # metadata without flush
    assert segment.current_size() >= 2
    assert float(c.asnumpy()) == 8 * 8 * 3.0    # flush happens here
    assert segment.current_size() == 0


def test_replay_cache_reused_across_iterations():
    x = mx.np.array(np.arange(16, dtype=np.float32).reshape(4, 4))
    before = len(segment._replay_cache)
    results = []
    for i in range(5):
        y = ((x + 1.0) * 2.0).sum()
        results.append(float(y.asnumpy()))
    after = len(segment._replay_cache)
    assert after - before <= 1                  # one compiled replay, reused
    assert all(r == results[0] for r in results)


def test_bulked_autograd_matches_immediate():
    xs = np.random.RandomState(0).randn(6, 6).astype(np.float32)

    def run():
        x = mx.np.array(xs)
        x.attach_grad()
        with mx.autograd.record():
            y = ((x * x + 3.0) * x).sum()
        y.backward()
        return x.grad.asnumpy()

    g_bulked = run()
    prev = engine.set_bulk_size(0)
    try:
        g_imm = run()
    finally:
        engine.set_bulk_size(prev)
    np.testing.assert_allclose(g_bulked, 3 * xs * xs + 3.0, rtol=1e-5)
    np.testing.assert_allclose(g_bulked, g_imm, rtol=1e-6)


def test_inplace_mutation_between_fwd_and_bwd():
    """Backward must see the values the forward saw (residual snapshot),
    even though bulked nodes re-linearize instead of capturing vjp closures."""
    x = mx.np.array(np.full((4,), 3.0, np.float32))
    x.attach_grad()
    with mx.autograd.record():
        y = (x * x).sum()
    x[:] = mx.np.zeros((4,))     # mutate AFTER forward, BEFORE backward
    y.backward()
    np.testing.assert_allclose(x.grad.asnumpy(), np.full((4,), 6.0))


def test_custom_function_under_bulking():
    class Square(mx.autograd.Function):
        def forward(self, x):
            self.save_for_backward(x)
            return x * x

        def backward(self, dy):
            (x,) = self._saved
            return 2.0 * x * dy

    x = mx.np.array(np.arange(4, dtype=np.float32))
    x.attach_grad()
    for _ in range(2):   # twice: the one-shot closures must not poison caches
        with mx.autograd.record():
            y = Square()(x).sum()
        y.backward()
        np.testing.assert_allclose(x.grad.asnumpy(),
                                   2 * np.arange(4, dtype=np.float32))


def test_waitall_covers_other_threads():
    done = {}

    def worker():
        a = mx.np.array(np.ones((4,), np.float32))
        done["out"] = a + 41.0     # left pending in the worker's segment

    t = threading.Thread(target=worker)
    t.start()
    t.join()
    mx.waitall()                   # must flush the worker's segment too
    d = done["out"]._data
    assert not isinstance(d, segment._LazyVal) or d.value is not None
    np.testing.assert_allclose(done["out"].asnumpy(), 42.0)


def test_trace_time_errors_surface_at_call_site():
    a = mx.np.array(np.ones((3, 4), np.float32))
    b = mx.np.array(np.ones((5, 4), np.float32))
    with pytest.raises(Exception):
        mx.np.matmul(a, b)         # shape error: eval_shape fails -> eager
    # raises at the call, not at a later flush


def test_bulk_size_zero_is_immediate():
    prev = engine.set_bulk_size(0)
    try:
        a = mx.np.array(np.ones((2, 2), np.float32))
        b = a + 1.0
        assert segment.current_size() == 0
        assert not isinstance(b._data, segment._LazyVal)
    finally:
        engine.set_bulk_size(prev)


def test_amp_autocast_in_bulked_path():
    from incubator_mxnet_tpu import amp
    amp.init("bfloat16")
    try:
        a = mx.np.array(np.ones((16, 16), np.float32))
        w = mx.np.array(np.ones((16, 16), np.float32))
        out = mx.npx.fully_connected(a, w, no_bias=True, flatten=False)
        assert str(out.dtype) == "bfloat16"
    finally:
        amp.uninit()


def test_grad_adopt_keeps_update_deferred():
    """grad[:] = ct and full-slice param updates share buffers without
    materializing, so the whole train step stays in one segment."""
    x = mx.np.array(np.ones((4, 4), np.float32))
    w = mx.np.array(np.full((4, 4), 2.0, np.float32))
    w.attach_grad()
    with mx.autograd.record():
        L = (x @ w).sum()
    L.backward()
    w[:] = w - 0.1 * w.grad
    assert segment.current_size() > 0         # still pending
    np.testing.assert_allclose(w.asnumpy(), np.full((4, 4), 2.0 - 0.4))
