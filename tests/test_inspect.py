"""mx.inspect — HLO roofline profiler and fusion-level offender attribution
(ISSUE 7).

Covers: the HLO text parser on handwritten modules (fusion flops summed
from called computations, dot/conv contraction formulas, boundary-byte
dedup), kernel-unit discovery through call/while wrappers, calibration
resolution (explicit path > MXNET_INSPECT_CALIB > committed artifact with
a platform guard > spec fallback), the cost-analysis degradation contract
(missing bytes keys / raising backends -> flops-only ranking, never a
crash), inspection of every framework surface (jitted fn, FusedTrainStep,
FusedInferStep, deploy.ExportedModel), fusion-class grouping + coverage,
measured-mode fallback on CPU, the registry metrics, and the CLI/bench
smokes (`tools/offenders.py --quick`, `benchmark/opperf.py --quick`,
`bench.py --quick --phases offenders`) plus the committed ResNet-18
artifact's acceptance numbers.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import gluon, telemetry
from incubator_mxnet_tpu import optimizer as opt_mod
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.gluon.contrib import FusedInferStep, FusedTrainStep
from incubator_mxnet_tpu.inspect import hlo, report, roofline
from incubator_mxnet_tpu import inspect as mxinspect

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# HLO text parser
# ---------------------------------------------------------------------------
HLO_TEXT = """\
HloModule test_module, entry_computation_layout={(f32[128,256]{1,0})->f32[]}

%fused_computation (param_0: f32[128,256], param_1: f32[128,256]) -> f32[128,256] {
  %param_0 = f32[128,256]{1,0} parameter(0)
  %param_1 = f32[128,256]{1,0} parameter(1)
  %multiply.1 = f32[128,256]{1,0} multiply(f32[128,256]{1,0} %param_0, f32[128,256]{1,0} %param_1)
  ROOT %add.1 = f32[128,256]{1,0} add(f32[128,256]{1,0} %multiply.1, f32[128,256]{1,0} %param_1)
}

%wrapped_comp (p0: f32[2,8,8,3], p1: f32[3,3,3,16]) -> f32[2,8,8,16] {
  %p0 = f32[2,8,8,3]{3,2,1,0} parameter(0)
  %p1 = f32[3,3,3,16]{3,2,1,0} parameter(1)
  ROOT %convolution.1 = f32[2,8,8,16]{3,2,1,0} convolution(f32[2,8,8,3]{3,2,1,0} %p0, f32[3,3,3,16]{3,2,1,0} %p1), window={size=3x3 pad=1_1x1_1}, dim_labels=b01f_01io->b01f
}

ENTRY %main (a: f32[128,256], b: f32[64,128], c: f32[128,256]) -> (f32[128,256], f32[64,256]) {
  %a = f32[128,256]{1,0} parameter(0)
  %b = f32[64,128]{1,0} parameter(1)
  %c = f32[128,256]{1,0} parameter(2)
  %x = f32[2,8,8,3]{3,2,1,0} parameter(3)
  %k = f32[3,3,3,16]{3,2,1,0} parameter(4)
  %fusion = f32[128,256]{1,0} fusion(f32[128,256]{1,0} %a, f32[128,256]{1,0} %c), kind=kLoop, calls=%fused_computation, metadata={op_name="jit(step)/mul_add" source_file="model.py"}
  %call.1 = f32[2,8,8,16]{3,2,1,0} call(f32[2,8,8,3]{3,2,1,0} %x, f32[3,3,3,16]{3,2,1,0} %k), to_apply=%wrapped_comp
  %dot.1 = f32[64,256]{1,0} dot(f32[64,128]{1,0} %b, f32[128,256]{1,0} %fusion), lhs_contracting_dims={1}, rhs_contracting_dims={0}
  ROOT %tuple.1 = (f32[128,256]{1,0}, f32[64,256]{1,0}) tuple(f32[128,256]{1,0} %fusion, f32[64,256]{1,0} %dot.1)
}
"""


def test_parse_shape_and_bytes():
    assert hlo.parse_shape("f32[128,256]{1,0}") == ("f32", (128, 256))
    assert hlo.parse_shape("bf16[]") == ("bf16", ())
    assert hlo.shape_bytes(("f32", (128, 256))) == 128 * 256 * 4
    assert hlo.shape_bytes(("bf16", ())) == 2
    # tuple shapes sum their leaves
    tup = hlo.parse_shape("(f32[4,4]{1,0}, s32[8]{0})")
    assert hlo.shape_bytes(tup) == 4 * 4 * 4 + 8 * 4
    assert hlo.parse_shape("garbage") is None
    assert hlo.shape_bytes(None) == 0


def test_parse_module_structure():
    m = hlo.parse_module(HLO_TEXT)
    assert m.name == "test_module"
    assert m.entry_name == "main"
    assert set(m.computations) == {"main", "fused_computation",
                                   "wrapped_comp"}
    fusion = next(i for i in m.entry.instructions if i.opcode == "fusion")
    assert fusion.operands == ["a", "c"]
    assert fusion.called == ["fused_computation"]
    assert fusion.op_name == "jit(step)/mul_add"
    root = m.entry.root
    assert root.opcode == "tuple" and root.is_root


def test_fusion_flops_sum_called_computation():
    m = hlo.parse_module(HLO_TEXT)
    fusion = next(i for i in m.entry.instructions if i.opcode == "fusion")
    # multiply (128*256) + add (128*256) inside the called computation
    assert roofline.instr_flops(fusion, m) == 2 * 128 * 256


def test_dot_and_conv_flop_formulas():
    m = hlo.parse_module(HLO_TEXT)
    dot = next(i for i in m.entry.instructions if i.opcode == "dot")
    # 2 * out(64*256) * contract(128)
    assert roofline.instr_flops(dot, m) == 2.0 * 64 * 256 * 128
    conv = next(i for i in m.computations["wrapped_comp"].instructions
                if i.opcode == "convolution")
    # 2 * out(2*8*8*16) * kernel taps per output (3*3*3*16 / o=16 = 27)
    assert roofline.instr_flops(conv, m) == 2.0 * (2 * 8 * 8 * 16) * 27
    assert conv.dim_labels == "b01f_01io->b01f"


def test_unit_cost_dedups_repeated_operand_reads():
    text = """\
HloModule dedup
ENTRY %main (a: f32[64,64]) -> f32[64,64] {
  %a = f32[64,64]{1,0} parameter(0)
  ROOT %multiply.1 = f32[64,64]{1,0} multiply(f32[64,64]{1,0} %a, f32[64,64]{1,0} %a)
}
"""
    m = hlo.parse_module(text)
    sq = m.entry.root
    cost = roofline.unit_cost(sq, m)
    buf = 64 * 64 * 4
    assert cost["in_bytes"] == buf          # %a read twice = one buffer
    assert cost["out_bytes"] == buf
    assert cost["bytes"] == 2 * buf


def test_parse_module_without_name_sigils():
    """Newer XLA ToString forms drop the '%' sigil; operand attribution
    (and therefore boundary bytes) must survive, not silently collapse
    to output-only bytes."""
    bare = HLO_TEXT.replace("%", "")
    m_sig = hlo.parse_module(HLO_TEXT)
    m_bare = hlo.parse_module(bare)
    for comp in m_sig.computations:
        sig = m_sig.computations[comp].instructions
        bare_i = m_bare.computations[comp].instructions
        assert [i.operands for i in sig] == [i.operands for i in bare_i]
    f_sig = next(i for i in m_sig.entry.instructions
                 if i.opcode == "fusion")
    f_bare = next(i for i in m_bare.entry.instructions
                  if i.opcode == "fusion")
    cost_sig = roofline.unit_cost(f_sig, m_sig)
    cost_bare = roofline.unit_cost(f_bare, m_bare)
    assert cost_bare["in_bytes"] == cost_sig["in_bytes"] > 0
    assert cost_bare["flops"] == cost_sig["flops"]


def test_kernel_units_descend_call_wrappers():
    m = hlo.parse_module(HLO_TEXT)
    units = roofline.kernel_units(m)
    # fusion + dot at top level, conv inside the %call wrapper; the call
    # itself, parameters, and the tuple are not kernel launches
    assert sorted(u.opcode for u in units) == ["convolution", "dot",
                                               "fusion"]


# ---------------------------------------------------------------------------
# calibration resolution + classification
# ---------------------------------------------------------------------------
def test_classify_against_ridge():
    assert roofline.classify(10.0, 5.0) == "compute"
    assert roofline.classify(2.0, 5.0) == "memory"


def test_load_calibration_explicit_path_and_ridge(tmp_path):
    p = tmp_path / "calib.json"
    p.write_text(json.dumps({"peak_flops": 1e12,
                             "peak_bytes_per_sec": 1e11,
                             "platform": "tpu"}))
    cal = roofline.load_calibration(path=str(p))
    # explicit paths are trusted even across platforms
    assert cal["peak_flops"] == 1e12
    assert cal["ridge_flop_per_byte"] == 10.0


def test_load_calibration_env_override(tmp_path, monkeypatch):
    p = tmp_path / "calib.json"
    p.write_text(json.dumps({"peak_flops": 2e12,
                             "peak_bytes_per_sec": 1e11}))
    monkeypatch.setenv("MXNET_INSPECT_CALIB", str(p))
    assert roofline.load_calibration()["peak_flops"] == 2e12


def test_load_calibration_platform_guard(tmp_path, monkeypatch):
    """A committed artifact calibrated on a different backend must not set
    this run's ridge; malformed artifacts are skipped, not fatal."""
    p = tmp_path / "roofline_calib.json"
    p.write_text(json.dumps({"peak_flops": 9e13,
                             "peak_bytes_per_sec": 1e12,
                             "platform": "not_this_platform"}))
    monkeypatch.setattr(roofline, "CALIB_PATH", str(p))
    cal = roofline.load_calibration(platform="cpu")
    assert cal["source"] == "spec-fallback"
    assert cal["peak_flops"] == roofline.DEFAULT_CALIBRATIONS[
        "cpu"]["peak_flops"]
    p.write_text("{not json")
    assert roofline.load_calibration(
        platform="cpu")["source"] == "spec-fallback"


def _flat_calib():
    return {"peak_flops": 1e12, "peak_bytes_per_sec": 1e11,
            "ridge_flop_per_byte": 10.0, "source": "test"}


def test_analyze_module_ranking_and_totals():
    m = hlo.parse_module(HLO_TEXT)
    records, totals = roofline.analyze_module(m, calib=_flat_calib())
    assert totals["units"] == 3
    assert totals["flops"] > 0 and totals["bytes"] > 0
    # ranked by est_time descending; shares sum to ~1
    times = [r["est_time_s"] for r in records]
    assert times == sorted(times, reverse=True)
    assert abs(sum(r["time_share"] for r in records) - 1.0) < 1e-6
    for r in records:
        assert r["bound"] in ("compute", "memory")
        if r["intensity"] is not None:
            assert (r["intensity"] >= 10.0) == (r["bound"] == "compute")
    assert 0.0 <= totals["memory_bound_byte_share"] <= 1.0


# ---------------------------------------------------------------------------
# cost-analysis degradation contract (satellite)
# ---------------------------------------------------------------------------
class _FakeCompiled:
    def __init__(self, text, ca):
        self._text, self._ca = text, ca

    def as_text(self):
        return self._text

    def cost_analysis(self):
        if isinstance(self._ca, Exception):
            raise self._ca
        return self._ca


def test_cost_analysis_summary_variants():
    ok = report._roofline.cost_analysis_summary(
        _FakeCompiled("", {"flops": 12.0, "bytes accessed": 34.0}))
    assert ok == {"flops": 12.0, "bytes_accessed": 34.0,
                  "bytes_estimated": True}
    # older jax returns [dict]
    lst = roofline.cost_analysis_summary(
        _FakeCompiled("", [{"flops": 5.0}]))
    assert lst["flops"] == 5.0
    assert lst["bytes_accessed"] is None and not lst["bytes_estimated"]
    # raising backends degrade to all-None, never crash
    bad = roofline.cost_analysis_summary(
        _FakeCompiled("", RuntimeError("unsupported")))
    assert bad["flops"] is None and not bad["bytes_estimated"]


def test_inspect_compiled_without_cost_analysis_uses_hlo_model():
    rep = mxinspect.inspect_compiled(
        _FakeCompiled(HLO_TEXT, RuntimeError("no cost analysis here")),
        name="fake", calib=_flat_calib())
    assert rep["ranking"] == "est_time"          # HLO shapes carried bytes
    assert rep["bytes_estimated"] is True
    assert rep["cost_analysis"]["flops"] is None
    assert rep["n_units"] == 3 and rep["offenders"]


def test_flops_only_degradation_when_bytes_unknowable():
    """No parseable shapes AND no cost analysis -> flops-only ranking,
    flagged, not a crash (the acceptance contract for exotic backends)."""
    text = """\
HloModule opaque
ENTRY %main (p: f32[8]) -> f32[8] {
  %p = f32[8]{0} parameter(0)
  ROOT %custom-call.1 = garbage custom-call(%p), custom_call_target="x"
}
"""
    rep = mxinspect.inspect_compiled(
        _FakeCompiled(text, RuntimeError("nope")), calib=_flat_calib())
    assert rep["ranking"] == "flops_only"
    assert rep["bytes_estimated"] is False
    assert rep["est_step_mfu_ceiling"] == 0.0    # no modelled work


def test_inspect_hlo_text_offline_no_backend():
    rep = mxinspect.inspect_hlo_text(HLO_TEXT, name="dump",
                                     calib=_flat_calib())
    assert rep["name"] == "dump"
    assert rep["n_units"] == 3
    assert rep["cost_analysis"]["flops"] is None


# ---------------------------------------------------------------------------
# grouping + rendering
# ---------------------------------------------------------------------------
def test_class_name_deinstances():
    assert report.class_name("multiply_multiply_fusion.18.clone") == \
        "multiply_multiply_fusion"
    assert report.class_name("loop_add_fusion.remat.3") == \
        "loop_add_fusion"
    assert report.class_name("dot.1") == "dot"
    assert report.class_name("fusion") == "fusion"


def test_offender_groups_fold_instances():
    text = """\
HloModule grouped
ENTRY %main (a: f32[256,256], b: f32[256,256]) -> f32[256,256] {
  %a = f32[256,256]{1,0} parameter(0)
  %b = f32[256,256]{1,0} parameter(1)
  %add_fusion.1 = f32[256,256]{1,0} add(f32[256,256]{1,0} %a, f32[256,256]{1,0} %b)
  %add_fusion.2 = f32[256,256]{1,0} add(f32[256,256]{1,0} %add_fusion.1, f32[256,256]{1,0} %b)
  %add_fusion.2.clone = f32[256,256]{1,0} add(f32[256,256]{1,0} %add_fusion.2, f32[256,256]{1,0} %a)
  ROOT %dot.7 = f32[256,256]{1,0} dot(f32[256,256]{1,0} %add_fusion.2.clone, f32[256,256]{1,0} %b), lhs_contracting_dims={1}, rhs_contracting_dims={0}
}
"""
    rep = mxinspect.inspect_hlo_text(text, calib=_flat_calib())
    groups = {g["class"]: g for g in rep["offender_groups"]}
    assert groups["add_fusion"]["count"] == 3
    assert groups["dot"]["count"] == 1
    assert rep["n_groups"] == 2
    assert rep["offender_top1_share"] == rep["offender_groups"][0][
        "time_share"]
    # coverage over 2 groups is total
    assert abs(rep["topk_time_coverage"] - 1.0) < 1e-5


def test_render_markdown_tables():
    rep = mxinspect.inspect_hlo_text(HLO_TEXT, name="md",
                                     calib=_flat_calib())
    text = mxinspect.render_markdown(rep)
    assert "# Offender attribution — md" in text
    assert "| # | fusion class |" in text
    assert "`dot" in text and "memory" in text or "compute" in text
    assert "MFU ceiling" in text


def test_dump_json_atomic(tmp_path):
    rep = mxinspect.inspect_hlo_text(HLO_TEXT, calib=_flat_calib())
    out = tmp_path / "rep.json"
    mxinspect.dump_json(rep, str(out))
    assert json.loads(out.read_text())["n_units"] == 3
    assert not os.path.exists(str(out) + ".tmp")


# ---------------------------------------------------------------------------
# live surfaces: jitted fn, FusedTrainStep, FusedInferStep, ExportedModel
# ---------------------------------------------------------------------------
def test_inspect_jitted_fn_and_registry_metrics():
    import jax.numpy as jnp

    before = telemetry.REGISTRY.snapshot()
    rep = mxinspect.inspect_step(lambda x: (x @ x).sum(),
                                 jnp.ones((64, 64), jnp.float32))
    assert rep["n_units"] >= 1
    assert rep["ranking"] == "est_time"
    assert rep["totals"]["flops"] >= 2 * 64 ** 3   # the matmul at least
    assert 0.0 < rep["est_step_mfu_ceiling"] <= 1.0
    snap = telemetry.REGISTRY.snapshot()
    assert snap["inspect.runs"] == before.get("inspect.runs", 0) + 1
    assert snap["inspect.units"] >= before.get("inspect.units", 0) + 1
    assert snap["inspect.top1_share"] == rep["offender_top1_share"]
    assert snap["inspect.memory_bound_byte_share"] == \
        rep["memory_bound_byte_share"]
    assert snap["inspect.mfu_ceiling"] == rep["est_step_mfu_ceiling"]
    # the analysis ran under a span lane
    assert telemetry.REGISTRY.snapshot().get(
        'span.count{name="inspect.analyze"}', 0) >= 1


def _tiny_train_step(bs=4):
    net = gluon.nn.HybridSequential()
    net.add(gluon.nn.Dense(16, activation="relu", in_units=8),
            gluon.nn.Dense(4, in_units=16))
    net.initialize()
    net.hybridize()
    x = mx.np.array(np.random.RandomState(0).randn(bs, 8).astype(np.float32))
    y = mx.np.array(np.random.RandomState(1).randn(bs, 4).astype(np.float32))
    loss = gluon.loss.L2Loss()
    opt = opt_mod.create("sgd", learning_rate=0.1)
    step = FusedTrainStep(net, lambda n, a, b: loss(n(a), b).mean(), opt)
    return step, x, y


def test_inspect_fused_train_step():
    step, x, y = _tiny_train_step()
    rep = mxinspect.inspect_step(step, x, y, name="tiny_train")
    assert rep["name"] == "tiny_train"
    assert rep["n_units"] >= 2                  # fwd+bwd+update fusions
    assert rep["bytes_estimated"] is True
    assert rep["offender_groups"][0]["time_share"] > 0
    # the lowered() refactor keeps flops_per_call working (MFU numerator)
    assert step.flops_per_call(x, y) > 0
    # and the step itself still trains after inspection
    assert np.isfinite(float(step(x, y).asnumpy()))


def test_inspect_fused_infer_step_and_seeding():
    net = gluon.nn.Dense(4, in_units=4)
    net.initialize()
    net.hybridize()
    step = FusedInferStep(net)
    with pytest.raises(MXNetError):
        step.lowered()                          # unseeded, no input
    x = mx.np.ones((2, 4))
    rep = mxinspect.inspect_step(step, x)
    assert rep["n_units"] >= 1


def test_inspect_exported_model(tmp_path):
    from incubator_mxnet_tpu import deploy

    net = gluon.nn.Dense(3, in_units=6)
    net.initialize()
    net.hybridize()
    x = mx.np.zeros((2, 6), dtype="float32")
    net(x)
    prefix = str(tmp_path / "net")
    net.export(prefix, example_inputs=x)
    model = deploy.ExportedModel(f"{prefix}-0000")
    rep = mxinspect.inspect_step(model)
    assert rep["n_units"] >= 1
    # inspection pre-populated the jit cache; run still works
    out = model.run(np.ones((2, 6), np.float32))
    assert np.asarray(out).shape == (2, 3)


def test_top_k_env_knob(monkeypatch):
    monkeypatch.setenv("MXNET_INSPECT_TOP_K", "2")
    rep = mxinspect.inspect_hlo_text(HLO_TEXT, calib=_flat_calib())
    assert rep["top_k"] == 2
    assert len(rep["offenders"]) <= 2
    assert len(rep["offender_groups"]) <= 2
    assert rep["totals"]["units"] == 3          # totals stay whole-module


def test_measured_mode_degrades_honestly_on_cpu():
    """CPU containers cannot attribute a device trace: measured stays
    False with a reason, wall timing is still reported, and the
    cost-model numbers stand."""
    import jax.numpy as jnp

    x = jnp.ones((32, 32), jnp.float32)
    rep = mxinspect.inspect_step(
        lambda a: (a @ a).sum(), x,
        measured=True, execute=lambda: (x @ x).sum().block_until_ready())
    assert rep["measured"] is False
    assert "measured_unavailable_reason" in rep
    assert rep["measured_wall_ms"] > 0


def test_lower_any_rejects_unknown():
    with pytest.raises(MXNetError):
        mxinspect.lower_any(object())


def test_inspect_lowered_and_compiled_stages_agree():
    """A jax.stages.Lowered must be compiled before parsing (its as_text
    is StableHLO, not optimized HLO) — both stages and the jitted wrapper
    itself must yield the same non-degenerate analysis."""
    import jax
    import jax.numpy as jnp

    f = jax.jit(lambda a: (a @ a).sum())
    x = jnp.ones((32, 32), jnp.float32)
    rep_lowered = mxinspect.inspect_step(f.lower(x))
    rep_compiled = mxinspect.inspect_step(f.lower(x).compile())
    rep_jitted = mxinspect.inspect_step(f, x)
    assert rep_lowered["n_units"] >= 1
    assert rep_lowered["totals"]["flops"] >= 2 * 32 ** 3
    assert rep_lowered["n_units"] == rep_compiled["n_units"] \
        == rep_jitted["n_units"]
    assert rep_lowered["totals"]["flops"] == rep_compiled["totals"][
        "flops"]


def test_exported_model_lowered_input_validation(tmp_path):
    from incubator_mxnet_tpu import deploy

    net = gluon.nn.Dense(3, in_units=6)
    net.initialize()
    net.hybridize()
    x = mx.np.zeros((2, 6), dtype="float32")
    net(x)
    prefix = str(tmp_path / "net")
    net.export(prefix, example_inputs=x)
    model = deploy.ExportedModel(f"{prefix}-0000")
    # passing a spec-matching input (by analogy with every other surface)
    rep = mxinspect.inspect_step(model, np.ones((2, 6), np.float32))
    assert rep["n_units"] >= 1
    # wrong shape / wrong arity: descriptive errors, not a retrace
    with pytest.raises(MXNetError, match="does not match"):
        model.lowered(np.ones((4, 6), np.float32))
    with pytest.raises(MXNetError, match="expects"):
        model.lowered(np.ones((2, 6), np.float32),
                      np.ones((2, 6), np.float32))


def test_callable_cost_accepts_prejitted_fn():
    import jax
    import jax.numpy as jnp

    x = jnp.ones((64, 64), jnp.float32)
    plain = roofline.callable_cost(lambda a: a @ a, x,
                                   calib=_flat_calib())
    jitted = roofline.callable_cost(jax.jit(lambda a: a @ a), x,
                                    calib=_flat_calib())
    assert jitted["est_flops"] == plain["est_flops"]
    assert jitted["est_flops"] >= 2 * 64 ** 3
    assert jitted["bound"] in ("compute", "memory")


# ---------------------------------------------------------------------------
# CLI + bench + committed artifacts (satellites)
# ---------------------------------------------------------------------------
def _run(args, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    return subprocess.run([sys.executable] + args, capture_output=True,
                          text=True, timeout=timeout, cwd=REPO, env=env)


def test_offenders_cli_quick_json(tmp_path):
    out = tmp_path / "off.json"
    r = _run([os.path.join(REPO, "tools", "offenders.py"), "--quick",
              "--json", str(out)])
    assert r.returncode == 0, r.stdout + r.stderr
    rep = json.loads(out.read_text())
    assert rep["name"] == "tiny_train_bs4"
    assert rep["n_units"] > 0 and rep["offender_groups"]
    for key in ("offender_top1_share", "memory_bound_byte_share",
                "est_step_mfu_ceiling", "top10_byte_coverage"):
        assert key in rep
    assert rep["calibration"]["ridge_flop_per_byte"] > 0


def test_offenders_cli_hlo_file_offline(tmp_path):
    dump = tmp_path / "dump.txt"
    dump.write_text(HLO_TEXT)
    r = _run([os.path.join(REPO, "tools", "offenders.py"),
              "--hlo-file", str(dump), "--markdown", "-"])
    assert r.returncode == 0, r.stdout + r.stderr
    assert "Offender attribution" in r.stdout


def test_opperf_quick_json_smoke(tmp_path):
    """Satellite: opperf gains roofline columns + tier-1 coverage."""
    out = tmp_path / "opperf.json"
    r = _run([os.path.join(REPO, "benchmark", "opperf.py"), "--quick",
              "--json", str(out)])
    assert r.returncode == 0, r.stdout + r.stderr
    data = json.loads(out.read_text())
    assert data["quick"] is True
    assert data["calibration"]["ridge_flop_per_byte"] > 0
    rows = [row for rows in data["results"].values() for row in rows
            if "error" not in row]
    assert rows, "every opperf row errored"
    costed = [row for row in rows if row.get("est_flops") is not None]
    assert costed, "no opperf row carried roofline columns"
    for row in costed:
        assert row["est_bytes"] is None or row["est_bytes"] > 0
        if row.get("intensity") is not None:
            assert row["bound"] in ("compute", "memory")
    # gemm ops must rank more arithmetic-intense than norm ops
    gemm = [r_ for r_ in data["results"].get("gemm", [])
            if r_.get("intensity")]
    norm = [r_ for r_ in data["results"].get("norm", [])
            if r_.get("intensity")]
    if gemm and norm:
        assert max(g["intensity"] for g in gemm) > \
            min(n["intensity"] for n in norm)


def test_bench_offenders_quick_phase():
    """Satellite: the offenders phase rides the hermetic bench runner and
    emits exactly the keys benchdiff gates."""
    r = _run([os.path.join(REPO, "bench.py"), "--quick",
              "--phases", "offenders"])
    assert r.returncode == 0, r.stdout + r.stderr
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert "phase_errors" not in out
    assert 0.0 < out["offender_top1_share"] <= 1.0
    assert 0.0 <= out["memory_bound_byte_share"] <= 1.0
    assert 0.0 < out["est_step_mfu_ceiling"] <= 1.0
    assert out["offenders_n_units"] > 0
    assert out["offenders_top3"][0]["bound"] in ("compute", "memory")


def test_committed_resnet18_artifact_acceptance():
    """The acceptance numbers of the committed ResNet-18 offender
    artifact: top-10 classes cover >= 80% of estimated step bytes, every
    group is roofline-tagged consistently with the calibrated ridge."""
    path = os.path.join(REPO, "benchmark", "results",
                        "offenders_resnet18_r09.json")
    rep = json.load(open(path))
    assert rep["top10_byte_coverage"] >= 0.8
    assert rep["ranking"] == "est_time"
    ridge = rep["calibration"]["ridge_flop_per_byte"]
    assert ridge > 0
    for g in rep["offender_groups"]:
        assert g["bound"] in ("compute", "memory")
        if g["intensity"] is not None:
            assert (g["intensity"] >= ridge) == (g["bound"] == "compute")
    for key in ("offender_top1_share", "memory_bound_byte_share",
                "est_step_mfu_ceiling"):
        assert 0.0 <= rep[key] <= 1.0


def test_committed_roofline_calibration_artifact():
    path = os.path.join(REPO, "benchmark", "results",
                        "roofline_calib.json")
    cal = json.load(open(path))
    assert cal["format_version"] == 1
    assert cal["peak_flops"] > 0 and cal["peak_bytes_per_sec"] > 0
    assert cal["platform"]
    assert cal["probes"]["membw"]["triad_gbps"] > 0
