"""Examples stay runnable: import each self-contained example and run a
tiny configuration (the reference CI's example smoke tier). Keeps the
examples from rotting as the framework evolves."""
import importlib.util
import os

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"example_{name}", os.path.join(REPO, "examples", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.slow  # nightly-grade convergence run (~30s)
def test_actor_critic_learns():
    m = _load("actor_critic")
    # run() now seeds the global numpy stream too (action sampling), so
    # the rollout is deterministic regardless of test order; seed 1 is a
    # fast learner (~82 running length at 40 episodes vs the ~10 start)
    final = m.run(episodes=40, seed=1)
    assert final > 12   # started ~10; policy must be improving


def test_sn_gan_trains():
    m = _load("sn_gan")
    pts, d_losses = m.run(steps=60)
    assert np.isfinite(pts).all()
    assert pts.std() > 0.1            # no mode collapse to a point
    assert np.isfinite(d_losses).all()


def test_sn_gan_rejects_hybridize():
    import incubator_mxnet_tpu as mx
    m = _load("sn_gan")
    layer = m.SNDense(4, 3)
    layer.initialize()
    with pytest.raises(mx.MXNetError, match="eager-only"):
        layer.hybridize()


@pytest.mark.slow  # nightly-grade convergence run (~25s)
def test_tree_lstm_converges():
    m = _load("tree_lstm")
    losses = m.run(epochs=4, n_trees=30)
    assert losses[-1] < losses[0] * 0.7, losses
