"""mx.telemetry.trace — end-to-end request tracing, crash flight recorder,
and the open-loop tail-latency harness (ISSUE 13).

Covers: TraceContext mint/serialize/attach semantics and deterministic
head sampling; span nesting carried ACROSS thread hops (the DeviceFeed
feeder regression — feed.stage must nest under the consumer's step); the
one-trace-per-request acceptance on serve (caller → batcher thread
boundary with correct parentage, batch span linking its members);
shm-worker decode lanes landing in the consuming iterator's Chrome trace;
the flight-recorder ring/spool/dump contract (capacity knob, fault-logger
chokepoint, watchdog + overload wiring, JSONL SIGKILL spool); the top-K
slowest-requests timeline table and trace.*/flightrec.* exposure in
metrics_text; open-loop knee detection + the serve_bench --open-loop
smoke; the committed serve_openloop_r13.json acceptance; and the
SIGKILL-parity crashtest --flightrec run (slow-marked).
"""
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import incubator_mxnet_tpu as mx  # noqa: F401  (package init: jax config)
from incubator_mxnet_tpu import fault, profiler, telemetry
from incubator_mxnet_tpu.telemetry import trace

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TINY_REC = os.path.join(REPO, "tests", "data", "tiny_imagerec.rec")


# ---------------------------------------------------------------------------
# trace context
# ---------------------------------------------------------------------------
def test_context_mint_child_and_serialize_round_trip():
    root = trace.new_context("req.root")
    assert root is not None and root.parent_span_id is None
    child = trace.child_context(root, "req.stage")
    assert child.trace_id == root.trace_id
    assert child.span_id != root.span_id
    assert child.parent_span_id == root.span_id
    assert child.parent_name == "req.root"
    # process-boundary round trip
    back = trace.TraceContext.from_dict(
        json.loads(json.dumps(child.to_dict())))
    assert (back.trace_id, back.span_id, back.parent_span_id) \
        == (child.trace_id, child.span_id, child.parent_span_id)
    assert trace.TraceContext.from_dict(None) is None
    assert trace.TraceContext.from_dict({}) is None


def test_attach_detach_and_cross_thread_current_span():
    got = {}
    with telemetry.span("consumer.step"):
        ctx = trace.current_context()
        assert ctx is not None and ctx.name == "consumer.step"

        def worker():
            assert telemetry.current_span() is None  # fresh thread: empty
            token = trace.attach(ctx)
            got["name"] = telemetry.current_span()
            trace.detach(token)
            got["after"] = telemetry.current_span()

        t = threading.Thread(target=worker)
        t.start()
        t.join()
    assert got == {"name": "consumer.step", "after": None}
    assert trace.current_context() is None


def test_trace_sampling_deterministic(monkeypatch):
    # rate 0: every root sampled out, counted in trace.sampled_out
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0")
    before = telemetry.snapshot()["trace.sampled_out"]
    assert trace.new_context("x") is None
    assert telemetry.snapshot()["trace.sampled_out"] == before + 1
    # a sampled-out root span still records its histogram, just no ids
    with telemetry.span("sampled.out.span") as sp:
        assert sp.context is None
    assert telemetry.snapshot()[
        'span.count{name="sampled.out.span"}'] >= 1
    # rate 0.5: exactly half of a long run of roots mint
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "0.5")
    minted = sum(trace.new_context("y") is not None for _ in range(100))
    assert minted == 50
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "1.0")
    assert trace.new_context("z") is not None
    # the counters exercised above exist under their registered names
    snap = telemetry.snapshot()
    assert "trace.traces" in snap and "trace.attaches" in snap
    assert "trace.spans" in snap


def test_trace_and_flightrec_counter_groups():
    """The hot-path counters are LOCK-FREE stats groups (the documented
    DISPATCH_STATS pattern — a registry-lock inc() convoyed 32 submitter
    threads): every key exists, surfaces under its dotted name, and
    snapshot(reset) is conservation-safe."""
    for key in ("traces", "spans", "attaches", "sampled_out"):
        assert key in trace.TRACE_STATS
    for key in ("events", "dropped", "dumps"):
        assert key in trace.FLIGHTREC_STATS
    before = telemetry.snapshot()["trace.traces"]
    assert trace.new_context("group.probe") is not None
    assert telemetry.snapshot()["trace.traces"] == before + 1
    telemetry.flightrec_record("test", "group.probe")
    assert telemetry.snapshot()["flightrec.events"] >= 1


def test_span_ids_in_chrome_args_and_exception_safety(tmp_path):
    profiler._events.clear()
    profiler.start()
    try:
        with pytest.raises(RuntimeError):
            with telemetry.span("outer.traced"):
                with telemetry.span("inner.traced"):
                    raise RuntimeError("boom")
        # the stack healed: a fresh span is a root again
        assert telemetry.current_span() is None
    finally:
        profiler.stop()
    by = {e["name"]: e for e in profiler._events}
    o, i = by["outer.traced"], by["inner.traced"]
    assert i["args"]["trace_id"] == o["args"]["trace_id"]
    assert i["args"]["parent_span_id"] == o["args"]["span_id"]
    assert i["args"]["parent"] == "outer.traced"


# ---------------------------------------------------------------------------
# DeviceFeed: nesting survives the feeder-thread hop (the satellite bugfix)
# ---------------------------------------------------------------------------
def test_device_feed_stage_spans_nest_under_consumer_step(tmp_path):
    from incubator_mxnet_tpu.io import DeviceFeed

    def source():
        for i in range(4):
            yield np.full((2, 3), i, np.float32)

    profiler._events.clear()
    profiler.start()
    try:
        with telemetry.span("train.step.feedtest"):
            feed = DeviceFeed(source(), depth=2)
            for batch in feed:
                pass
    finally:
        profiler.stop()
    stage = [e for e in profiler._events if e["name"] == "feed.stage"]
    consumed = [e for e in profiler._events if e["name"] == "io.feed"]
    root = [e for e in profiler._events
            if e["name"] == "train.step.feedtest"][0]
    assert stage and consumed
    # the regression: feeder-thread spans used to start a fresh stack and
    # render parentless — now they carry the consumer's trace id
    for e in stage + consumed:
        assert e["args"].get("trace_id") == root["args"]["trace_id"], \
            f"{e['name']} rendered outside the consumer's trace"
    assert stage[0]["args"]["parent"] == "train.step.feedtest"
    # and the hop was counted
    assert telemetry.snapshot()["trace.attaches"] >= 1


# ---------------------------------------------------------------------------
# serve: one request = one trace across the thread boundary (acceptance)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def tiny_server():
    from incubator_mxnet_tpu import serve

    def fn(x):
        import jax.numpy as jnp
        return jnp.sum(x, axis=1)

    model = serve.CallableModel(fn, [1, 2, 4], [((8,), "float32")])
    with serve.Server(model, batch_timeout_ms=1.0) as srv:
        yield srv


def test_serve_one_submit_renders_one_trace(tiny_server):
    profiler._events.clear()
    profiler.start()
    try:
        with telemetry.span("client.call"):
            tiny_server.predict(np.ones(8, np.float32))
    finally:
        profiler.stop()
    evs = [e for e in profiler._events if e["cat"] == "serve"]
    by = {}
    for e in evs:
        by.setdefault(e["name"], []).append(e)
    root = [e for e in profiler._events if e["name"] == "client.call"][0]
    tid_root = root["args"]["trace_id"]
    req = by["serve.request"][-1]
    # ONE trace: every stage of this request shares the client's trace id
    assert req["args"]["trace_id"] == tid_root
    stages = ("serve.enqueue", "serve.queue_wait", "serve.execute",
              "serve.reply")
    for name in stages:
        e = by[name][-1]
        assert e["args"]["trace_id"] == tid_root, name
        # correct parentage: each stage hangs under the request root span
        assert e["args"]["parent_span_id"] == req["args"]["span_id"], name
        assert e["args"]["parent"] == "serve.request", name
    # the request root itself hangs under the caller's span
    assert req["args"]["parent_span_id"] == root["args"]["span_id"]
    # and the spans CROSS the thread boundary: enqueue on the caller
    # thread, execute on the batcher thread
    assert by["serve.enqueue"][-1]["tid"] != by["serve.execute"][-1]["tid"]
    # the batch span links its member requests
    batch = by["serve.batch"][-1]
    assert tid_root in batch["args"].get("member_traces", "")


def test_serve_timeline_slowest_table_and_metrics_text(tiny_server,
                                                       monkeypatch):
    # an explicitly-set sample rate forces request-root minting even with
    # no profiler/spool attached (trace.collector_active) — the cheap way
    # to get trace ids into the slowest table in production
    monkeypatch.setenv("MXNET_TRACE_SAMPLE", "1.0")
    trace._expire_env_memo()   # the knob is TTL-cached (50ms)
    for _ in range(3):
        tiny_server.predict(np.ones(8, np.float32), deadline_ms=5000)
    st = tiny_server.stats()
    slow = st["timeline"]["slowest"]
    assert slow, "top-K slowest table is empty after replies"
    assert len(slow) <= 8
    totals = [r["total_ms"] for r in slow]
    assert totals == sorted(totals, reverse=True)
    row = slow[0]
    for key in ("trace_id", "total_ms", "queue_wait_ms", "exec_ms",
                "batch_size", "deadline_margin_ms"):
        assert key in row
    assert row["trace_id"]           # traced by default (sample rate 1)
    assert row["queue_wait_ms"] >= 0 and row["exec_ms"] >= 0
    # at least one row carries a deadline margin (the deadline_ms calls)
    assert any(r["deadline_margin_ms"] is not None for r in slow)
    # metrics_text exposes the new counter families
    text = tiny_server.metrics_text()
    for needle in ("mx_trace_traces", "mx_trace_spans",
                   "mx_flightrec_events"):
        assert needle in text, needle


# ---------------------------------------------------------------------------
# shm-worker decode lanes join the consuming iterator's trace (acceptance)
# ---------------------------------------------------------------------------
def test_imagerec_worker_lanes_in_consumer_trace(tmp_path):
    from incubator_mxnet_tpu.io import ImageRecordIter

    it = ImageRecordIter(path_imgrec=TINY_REC, data_shape=(32, 32, 3),
                         batch_size=3, resize=36, workers=1, lookahead=1,
                         round_batch=False, prefetch=True)
    try:
        profiler._events.clear()
        profiler.start()
        try:
            with telemetry.span("train.step.rectest"):
                # deeper than the lookahead so at least one batch is
                # SUBMITTED inside the consumer's span (construction-time
                # submits predate it by design)
                for _ in range(4):
                    it.next()
        finally:
            profiler.stop()
    finally:
        it.close()
    root = [e for e in profiler._events
            if e["name"] == "train.step.rectest"][0]
    lanes = [e for e in profiler._events if e["name"] == "io.worker.decode"]
    assert lanes, "no decode-worker lane events in the Chrome trace"
    in_trace = [e for e in lanes
                if e["args"].get("trace_id") == root["args"]["trace_id"]]
    assert in_trace, ("worker decode lanes never joined the consuming "
                      "iterator's trace")
    assert "worker" in in_trace[0]["args"]


# ---------------------------------------------------------------------------
# flight recorder
# ---------------------------------------------------------------------------
@pytest.fixture
def fresh_flightrec(monkeypatch):
    trace.FLIGHTREC._reset_for_tests()
    yield trace.FLIGHTREC
    trace.FLIGHTREC._reset_for_tests()


def test_flightrec_ring_capacity_and_dropped(fresh_flightrec, monkeypatch):
    monkeypatch.setenv("MXNET_FLIGHTREC_EVENTS", "16")
    before = telemetry.snapshot()["flightrec.dropped"]
    before_ev = telemetry.snapshot()["flightrec.events"]
    for i in range(40):
        telemetry.flightrec_record("test", "ring.probe", i=i)
    evs = telemetry.flightrec_events()
    assert len(evs) == 16
    assert [e["i"] for e in evs] == list(range(24, 40))  # newest retained
    assert telemetry.snapshot()["flightrec.dropped"] == before + 24
    assert telemetry.snapshot()["flightrec.events"] == before_ev + 40


def test_flightrec_spool_and_dump(fresh_flightrec, monkeypatch, tmp_path):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    telemetry.flightrec_record("test", "spool.probe", detail="x")
    with telemetry.span("spooled.span", step=3):
        time.sleep(0.06)     # past the 50ms close-event duration floor
    with telemetry.span("fast.span"):
        pass                 # under the floor: open spooled, close not
    spool = fresh_flightrec.spool_path
    assert spool and os.path.exists(spool)
    lines = [json.loads(l) for l in open(spool) if l.strip()]
    assert lines[0]["name"] == "spool.probe"
    opens = [l for l in lines if l["kind"] == "span_open"]
    closes = [l for l in lines if l["kind"] == "span"]
    assert opens and opens[0]["name"] == "spooled.span"
    assert opens[0]["step"] == 3
    assert closes and closes[0]["name"] == "spooled.span"
    assert closes[0]["dur_us"] >= 50e3
    # the duration floor: fast spans record their OPEN (the in-flight
    # marker) but not a close event
    assert any(o["name"] == "fast.span" for o in opens)
    assert not any(c["name"] == "fast.span" for c in closes)
    # dump: one JSON black box, atomic, counted
    before = telemetry.snapshot()["flightrec.dumps"]
    path = telemetry.flightrec_dump(reason="unit")
    assert path and os.path.dirname(path) == str(tmp_path)
    with open(path) as f:
        payload = json.load(f)
    assert payload["reason"] == "unit"
    assert payload["pid"] == os.getpid()
    assert payload["n_events"] == len(payload["events"]) > 0
    assert telemetry.snapshot()["flightrec.dumps"] == before + 1


def test_flightrec_no_files_without_dir(fresh_flightrec, monkeypatch):
    monkeypatch.delenv("MXNET_FLIGHTREC_DIR", raising=False)
    telemetry.flightrec_record("test", "quiet.probe")
    assert fresh_flightrec.spool_path is None
    # rate-limited dumps are no-ops without the dir (no surprise files)
    assert telemetry.flightrec_maybe_dump("unit") is None


def test_fault_log_events_feed_flightrec(fresh_flightrec):
    fault.clear()
    fault.install("resilient.step", "error", at=1)
    try:
        with pytest.raises(fault.InjectedFault):
            fault.inject("resilient.step")
    finally:
        fault.clear()
    evs = [e for e in telemetry.flightrec_events()
           if e["name"] == "fault.injected"]
    assert evs, "fault injection never reached the flight recorder"
    assert evs[-1]["point"] == "resilient.step"
    assert evs[-1]["kind"] == "fault"          # envelope kind preserved
    assert evs[-1]["f_kind"] == "error"        # the rule's kind, prefixed


def test_watchdog_timeout_dumps_flightrec(fresh_flightrec, monkeypatch,
                                          tmp_path):
    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))
    with pytest.raises(fault.WatchdogTimeout):
        with fault.watchdog(0.05):
            time.sleep(0.4)
    dump = os.path.join(str(tmp_path), f"flightrec-{os.getpid()}.json")
    assert os.path.exists(dump), "watchdog expiry left no black box"
    with open(dump) as f:
        payload = json.load(f)
    assert any(e["kind"] == "watchdog" for e in payload["events"])


def test_serve_overload_shed_records_and_dumps(fresh_flightrec,
                                               monkeypatch, tmp_path):
    from incubator_mxnet_tpu import serve

    monkeypatch.setenv("MXNET_FLIGHTREC_DIR", str(tmp_path))

    class SlowModel:
        # host-side slow model (a sleep inside a jitted fn would only
        # fire at trace time): every batch takes 50ms, so rapid submits
        # overflow the 1-deep queue and the shed policy fires
        batch_sizes = [1]
        row_specs = [((4,), "float32")]
        single_output = True

        def run_batch(self, bucket, arrs):
            time.sleep(0.05)
            return (np.zeros((bucket, 1), np.float32),)

        def warmup(self):
            pass

        def compile_cache_size(self):
            return 1

    with serve.Server(SlowModel(), max_queue=1, overload_policy="shed",
                      batch_timeout_ms=0.1) as srv:
        for i in range(8):
            try:
                srv.submit(np.ones(4, np.float32))
            except serve.QueueFullError:
                pass
    sheds = [e for e in telemetry.flightrec_events()
             if e["kind"] == "serve.shed"]
    assert sheds, "overload shedding never reached the flight recorder"
    dump = os.path.join(str(tmp_path), f"flightrec-{os.getpid()}.json")
    assert os.path.exists(dump), "overload shedding left no black box"


# ---------------------------------------------------------------------------
# open-loop harness
# ---------------------------------------------------------------------------
def _load_serve_bench():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "serve_bench_mod", os.path.join(REPO, "benchmark",
                                        "serve_bench.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_detect_knee_on_synthetic_sweep():
    sb = _load_serve_bench()

    def row(rate, achieved, p99, drop=0.0):
        return {"offered_rps": rate, "achieved_rps": achieved,
                "p99_ms": p99, "completed": int(achieved),
                "drop_rate": drop}

    rows = [row(20, 20, 10), row(40, 40, 12), row(80, 79, 14),
            row(160, 110, 400, drop=0.3), row(320, 112, 900, drop=0.6)]
    knee = sb.detect_knee(rows)
    assert knee["knee_rps"] == 80
    assert knee["knee_p99_ms"] == 14
    # p99 at 0.8 x 80 = 64 req/s: interpolated between the 40 and 80 rows
    assert 12 < knee["p99_ms_at_0p8_knee"] < 14
    # saturated from the very first rate: honest no-knee report
    sat = sb.detect_knee([row(20, 5, 5000, drop=0.7)])
    assert sat["knee_rps"] is None and sat["saturated_from_first_rate"]
    assert sb.detect_knee([]) is None


def test_serve_bench_open_loop_smoke(tmp_path):
    out = str(tmp_path / "ol.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "benchmark", "serve_bench.py"),
         "--quick", "--open-loop", "--rates", "25,50,100",
         "--duration", "0.6", "--out", out],
        capture_output=True, text=True, timeout=300, cwd=REPO,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert r.returncode == 0, r.stdout + r.stderr
    with open(out) as f:
        data = json.load(f)
    assert data["backend_ok"] is True
    assert data["meta"]["mode"] == "open_loop"
    rows = data["open_loop"]["rows"]
    assert [row["offered_rps"] for row in rows] == [25.0, 50.0, 100.0]
    for row in rows:
        # drop accounting present on every rate row
        assert {"dropped", "drops_by_kind", "drop_rate",
                "p50_ms", "p99_ms", "p999_ms"} <= set(row)
        assert row["sent"] == row["completed"] + row["dropped"] \
            + row["undrained"]
    assert data["open_loop"]["knee"] is not None


def test_committed_openloop_artifact_acceptance():
    path = os.path.join(REPO, "benchmark", "results",
                        "serve_openloop_r13.json")
    with open(path) as f:
        data = json.load(f)
    assert data["backend_ok"] is True
    rows = data["open_loop"]["rows"]
    offered = [r["offered_rps"] for r in rows]
    # a monotone offered-load sweep with drop accounting on every row
    assert len(offered) >= 5 and offered == sorted(offered)
    assert all("drop_rate" in r and "drops_by_kind" in r for r in rows)
    knee = data["open_loop"]["knee"]
    assert knee["knee_rps"] is not None
    assert data["serve_knee_rps"] == knee["knee_rps"]
    assert data["serve_p99_ms_at_0p8_knee"] == knee["p99_ms_at_0p8_knee"]
    # the sweep actually crossed the knee: at least one rate saturated
    assert any(r["offered_rps"] > knee["knee_rps"] for r in rows), \
        "sweep never exceeded the detected knee — knee not demonstrated"
    # tracing overhead A/B rides the artifact when present
    if "serve_trace_overhead_pct" in data:
        assert data["serve_trace_overhead_pct"] <= 2.0


def test_benchdiff_gates_openloop_keys():
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "benchdiff_mod", os.path.join(REPO, "tools", "benchdiff.py"))
    bd = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(bd)
    assert bd.TREND_KEYS["serve_knee_rps"] == "higher"
    assert bd.TREND_KEYS["serve_p99_ms_at_0p8_knee"] == "lower"
    base = {"backend_ok": True, "serve_knee_rps": 100.0,
            "serve_p99_ms_at_0p8_knee": 40.0}
    rep = bd.compare(base, dict(base, serve_knee_rps=70.0))
    assert rep["status"] == "regression"
    assert rep["regressions"][0]["key"] == "serve_knee_rps"


# ---------------------------------------------------------------------------
# SIGKILL parity (slow): crashtest --flightrec
# ---------------------------------------------------------------------------
@pytest.mark.slow
def test_crashtest_flightrec_sigkill_parity(tmp_path):
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "crashtest.py"),
         "--flightrec", "--steps", "10", "--ckpt-every", "3",
         "--kill-at", "6", "--dir", str(tmp_path)],
        capture_output=True, text=True, timeout=600, cwd=REPO)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "flight recorder OK" in r.stdout
    assert "in-flight elastic.step" in r.stdout
