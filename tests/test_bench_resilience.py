"""bench.py resilience (VERDICT-r4 Weak #1): the bench must survive a flaky
backend — partial results flush per phase, failed phases are recorded and
skipped, a resumed worker re-runs only what's missing, and assemble() yields
a valid JSON dict from ANY subset of raw metrics."""
import json
import os

import bench


def test_assemble_empty_is_valid_line():
    out = bench.assemble({})
    assert out["metric"] == "resnet50_train_images_per_sec_bs32"
    assert out["value"] == 0.0
    assert out["unit"] == "images/sec"
    assert out["vs_baseline"] == 0.0


def test_assemble_partial_derives_only_available():
    out = bench.assemble({"train_bs32_images_per_sec": 2600.0})
    assert out["value"] == 2600.0
    assert out["vs_baseline"] > 8.0
    assert "mfu_bs32" in out
    assert "mfu_vs_attainable_bs32" not in out  # no calibration ran
    out2 = bench.assemble({"train_bs32_images_per_sec": 2600.0,
                           "calib_attainable_bf16_tflops": 176.5})
    assert abs(out2["mfu_vs_attainable_bs32"]
               - 2600.0 * bench.FLOPS_TRAIN_PER_IMG / 1e12 / 176.5) < 1e-3


def test_worker_records_failures_and_resumes(tmp_path, capsys, monkeypatch):
    calls = []

    def ok_a():
        calls.append("a")
        return {"metric_a": 1}

    def boom():
        calls.append("b")
        raise RuntimeError("backend fell over")

    def ok_c():
        calls.append("c")
        return {"metric_c": 3}

    path = str(tmp_path / "partial.json")
    monkeypatch.setattr(bench, "PHASES",
                        [("a", ok_a), ("b", boom), ("c", ok_c)])
    assert bench.run_worker(path) == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["metric_a"] == 1 and line["metric_c"] == 3
    assert "backend fell over" in line["phase_errors"]["b"]
    saved = json.load(open(path))
    assert sorted(saved["_phases_done"]) == ["a", "c"]

    # resume: a and c are cached; only b re-runs (and now succeeds)
    calls.clear()
    monkeypatch.setattr(
        bench, "PHASES",
        [("a", ok_a), ("b", lambda: {"metric_b": 2}), ("c", ok_c)])
    assert bench.run_worker(path) == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert calls == []  # lambda isn't in calls; a/c never re-ran
    assert line["metric_a"] == 1 and line["metric_b"] == 2
    assert line["metric_c"] == 3


def test_orchestrator_emits_diagnostic_json_when_backend_dead(monkeypatch,
                                                              capsys,
                                                              tmp_path):
    monkeypatch.setattr(bench, "probe_backend",
                        lambda: (False, {"probe_attempts": 5,
                                         "probe_failures": []}))
    monkeypatch.setattr(bench, "cpu_smoke", lambda: {"cpu_smoke": "ok"})
    assert bench.main() == 0
    line = json.loads(capsys.readouterr().out.strip().splitlines()[-1])
    assert line["value"] == 0.0
    assert "error" in line and "unavailable" in line["error"]
    assert line["probe_attempts"] == 5
    assert line["cpu_smoke"] == "ok"


def test_isolated_runner_resumes_from_partial(tmp_path):
    """run_phases_isolated skips phases already recorded in the partial
    file (an orchestrator death loses at most the in-flight phase) and
    reports unknown phase names as errors instead of dying."""
    path = str(tmp_path / "partial.json")
    with open(path, "w") as f:
        json.dump({"_phases_done": [n for n, _ in bench.PHASES],
                   "metric_a": 1}, f)
    partial, errors = bench.run_phases_isolated(
        names=["dispatch", "bogus"], partial_path=path)
    assert partial["metric_a"] == 1          # cached, no subprocess spawned
    assert "unknown phase" in errors["bogus"]
    assert "dispatch" not in errors


def test_phase_list_ordering_is_loadbearing():
    # eager before the big fused programs, calibration last (device-session
    # residue slows subsequent eager-class programs; bisected in r3)
    names = [n for n, _ in bench.PHASES]
    assert names.index("eager") < names.index("train32")
    assert names.index("calib") > names.index("infer")
