"""PR2 eager-dispatch fast path: per-op dispatch records, compiled-kernel
caches, cached VJP taping, dispatch-stats counters — plus the satellite
regressions (sparse retain ordering, ONNX NMS boundary, bench default-policy
row, put_along_axis divergence warning).

Semantics contract under test: AMP autocast, autograd taping (incl. the
cached VJP), views, lazy/bulked inputs and MXNET_ENGINE_TYPE=NaiveEngine all
produce IDENTICAL results through the fast path, and the counters report
plausible hit rates (ISSUE 2 acceptance).
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import amp, autograd, engine, profiler
from incubator_mxnet_tpu.ops import registry, segment


@pytest.fixture
def immediate():
    """Bulking off: every invoke takes the immediate (fast) path."""
    prev = engine.set_bulk_size(0)
    yield
    engine.set_bulk_size(prev)


def _chain(x):
    y = (x * 2.0 + 1.0) * x
    z = mx.npx.relu(y - 0.5)
    return (z.sum() + y.mean()) * 1.5


# ---------------------------------------------------------------------------
# identical results through every engine configuration
# ---------------------------------------------------------------------------
def test_fast_path_matches_bulked_and_naive():
    xs = np.random.RandomState(0).randn(6, 6).astype(np.float32)

    def run():
        return float(_chain(mx.np.array(xs)).asnumpy())

    ref = run()                         # bulked (default)
    prev = engine.set_bulk_size(0)
    try:
        imm = run()                     # immediate fast path
        registry.set_dispatch_jit(False)
        try:
            plain = run()               # immediate, fast path disabled
        finally:
            registry.set_dispatch_jit(True)
    finally:
        engine.set_bulk_size(prev)
    prev_naive = engine.set_naive(True)
    try:
        naive = run()                   # NaiveEngine (block per op)
    finally:
        engine.set_naive(prev_naive)
    np.testing.assert_allclose([imm, plain, naive], [ref] * 3, rtol=1e-6)


def test_fast_path_autograd_matches_bulked(immediate):
    xs = np.random.RandomState(1).randn(5, 5).astype(np.float32)

    def run():
        x = mx.np.array(xs)
        x.attach_grad()
        with autograd.record():
            loss = _chain(x)
        loss.backward()
        return x.grad.asnumpy()

    g_imm = run()
    prev = engine.set_bulk_size(4096)
    try:
        g_bulk = run()
    finally:
        engine.set_bulk_size(0)
        engine.set_bulk_size(prev)      # restore via fixture anyway
    np.testing.assert_allclose(g_imm, g_bulk, rtol=1e-5, atol=1e-6)


def test_fast_path_views_and_mixed_lazy_inputs():
    # a view arg + a still-pending (lazy) arg + a concrete arg in one invoke
    a = mx.np.array(np.arange(16, dtype=np.float32).reshape(4, 4))
    pending = a * 3.0                   # deferred under default bulking
    view = a[1:3]                       # basic-index view of a
    out = (pending[1:3] + view).sum()
    expect = (np.arange(16, dtype=np.float32).reshape(4, 4) * 3.0
              )[1:3] + np.arange(16, dtype=np.float32).reshape(4, 4)[1:3]
    np.testing.assert_allclose(float(out.asnumpy()), expect.sum(), rtol=1e-6)
    # write through the view, then dispatch again: refresh must be seen
    view[:] = 0.0
    np.testing.assert_allclose((a[1:3] * 1.0).asnumpy(), 0.0)


def test_fast_path_amp_autocast_matches(immediate):
    xs = np.random.RandomState(2).rand(8, 8).astype(np.float32)
    ws = np.random.RandomState(3).rand(8, 8).astype(np.float32)
    amp.init("bfloat16")
    try:
        y = mx.np.dot(mx.np.array(xs), mx.np.array(ws))   # BF16_FUNCS
        assert str(y.dtype) == "bfloat16"
        z = mx.np.exp(mx.np.array(xs))                    # FP32_FUNCS
        assert str(z.dtype) == "float32"
    finally:
        amp.uninit()
    np.testing.assert_allclose(
        y.asnumpy().astype(np.float32), xs @ ws, rtol=2e-2, atol=2e-2)


def test_dispatch_record_amp_class_fallback():
    # record metadata covers names the amp lists don't know: contrib
    # roi_align registered 'unsafe' → _amp_dtype pins fp32 under autocast
    info = registry.get_op("npx.roi_align")
    assert info.amp == "unsafe"
    amp.init("bfloat16")
    try:
        assert registry._amp_dtype("roi_align", info) == "float32"
        # list names still win over records (user overrides intact)
        d = registry.get_op("npx.relu")
        assert registry._amp_dtype("relu", d) == "bfloat16"
    finally:
        amp.uninit()
    assert registry._amp_dtype("roi_align", info) is None


# ---------------------------------------------------------------------------
# counters + caches
# ---------------------------------------------------------------------------
def test_dispatch_stats_plausible_hit_rates(immediate):
    x = mx.np.array(np.ones((8, 8), np.float32))
    (x + 1.0).asnumpy()                 # prime compile outside the window
    profiler.dispatch_stats(reset=True)
    for _ in range(10):
        ((x + 1.0) * 2.0).asnumpy()
    s = profiler.dispatch_stats()
    assert s["dispatch"] == 20
    assert s["fast_path"] == 20         # every op keyed + compiled
    assert s["jit_cache_hit"] >= 18     # at most one miss for the new op
    assert s["bulked"] == 0
    # same dict via the engine facade
    assert engine.stats()["dispatch"] == s["dispatch"]


def test_recording_no_python_vjp_retrace(immediate):
    x = mx.np.array(np.random.RandomState(4).rand(6, 6).astype(np.float32))
    x.attach_grad()

    def step():
        with autograd.record():
            y = ((x * x + 3.0) * x).sum()
        y.backward()

    step()                              # builds + traces the VJP kernels
    profiler.dispatch_stats(reset=True)
    for _ in range(5):
        step()
    s = profiler.dispatch_stats()
    assert s["vjp_trace"] == 0          # no python jax.vjp retrace on repeats
    assert s["vjp_cache_hit"] > 0 and s["vjp_cache_miss"] == 0
    np.testing.assert_allclose(
        x.grad.asnumpy(), 3.0 * x.asnumpy() ** 2 + 3.0, rtol=1e-5)


def test_unjittable_fn_blacklisted_and_correct(immediate):
    calls = {"n": 0}

    def hostish(a):
        # concretizes under trace → jit probe fails → eager fallback
        calls["n"] += 1
        return a + float(np.asarray(a).sum())

    from incubator_mxnet_tpu.ops.registry import invoke
    x = mx.np.array(np.ones((2, 2), np.float32))
    profiler.dispatch_stats(reset=True)
    r1 = invoke(hostish, (x,), name="hostish").asnumpy()
    r2 = invoke(hostish, (x,), name="hostish").asnumpy()
    np.testing.assert_allclose(r1, 5.0)
    np.testing.assert_allclose(r2, 5.0)
    s = profiler.dispatch_stats()
    assert s["eager_fallback"] >= 2     # probe fell back, then stayed eager
    assert s["fast_path"] == 0


def test_user_error_does_not_blacklist_fast_path(immediate):
    a = mx.np.array(np.ones((4, 4), np.float32))
    w = mx.np.array(np.ones((4, 3), np.float32))
    mx.np.dot(a, w).asnumpy()           # compile + prime the kernel
    with pytest.raises(Exception):      # genuine user error re-raises
        mx.np.dot(a, mx.np.array(np.ones((5, 5), np.float32))).asnumpy()
    profiler.dispatch_stats(reset=True)
    mx.np.dot(a, w).asnumpy()           # same key must STILL be fast
    s = profiler.dispatch_stats()
    assert s["fast_path"] == 1 and s["eager_fallback"] == 0


def test_contrib_records_are_raw_kernels(immediate):
    # apply_op dispatch over the registered contrib record must tape the
    # PURE kernel (a wrapper would re-enter invoke with tracers at backward)
    from incubator_mxnet_tpu.ops import contrib
    info = registry.get_op("npx.box_iou")
    assert info.fn is contrib.box_iou
    b1 = mx.np.array(np.array([[0., 0., 2., 2.]], np.float32))
    b2 = mx.np.array(np.array([[1., 1., 3., 3.]], np.float32))
    b1.attach_grad()
    with autograd.record():
        loss = registry.apply_op("npx.box_iou", b1, b2).sum()
    loss.backward()
    np.testing.assert_allclose(float(loss.asnumpy()), 1.0 / 7.0, rtol=1e-5)
    assert np.isfinite(b1.grad.asnumpy()).all()


def test_key_cache_and_record_keys():
    # registered records precompute a stable key at register_op time
    def my_kernel(x):
        return x * 2.0

    registry.register_op("test.dispatch_key_op", my_kernel)
    info = registry.get_op("test.dispatch_key_op")
    assert info.key is not None
    r = registry.apply_op("test.dispatch_key_op",
                          mx.np.array(np.ones((2, 2), np.float32)))
    np.testing.assert_allclose(r.asnumpy(), 2.0)
    # derive_key_cached memoizes closure-less callables
    f = segment.derive_key  # any module-level function without closure
    segment.DISPATCH_STATS["key_cache_hit"] = 0
    k1 = segment.derive_key_cached(f)
    k2 = segment.derive_key_cached(f)
    assert k1 == k2 and segment.DISPATCH_STATS["key_cache_hit"] >= 1


def test_set_dispatch_jit_knob(immediate):
    prev = registry.set_dispatch_jit(False)
    try:
        profiler.dispatch_stats(reset=True)
        x = mx.np.array(np.ones((4, 4), np.float32))
        (x + 1.0).asnumpy()
        s = profiler.dispatch_stats()
        assert s["fast_path"] == 0 and s["eager_fallback"] == 1
    finally:
        registry.set_dispatch_jit(prev)


# ---------------------------------------------------------------------------
# satellite regressions
# ---------------------------------------------------------------------------
def test_sparse_retain_sorts_kept_rows():
    from incubator_mxnet_tpu.ndarray import sparse
    data = np.arange(8, dtype=np.float32).reshape(4, 2)
    rows = np.array([1, 3, 5, 7])
    r = sparse.row_sparse_array((data, rows), shape=(9, 2))
    # unsorted (and duplicated) request must still yield a valid RSP
    kept = r.retain(mx.np.array(np.array([7, 1, 5, 7])))
    kept.check_format()
    np.testing.assert_array_equal(kept._indices_np, [1, 5, 7])
    dense = np.zeros((9, 2), np.float32)
    dense[[1, 5, 7]] = data[[0, 2, 3]]
    np.testing.assert_allclose(kept.asnumpy(), dense)


def test_onnx_nms_keeps_boxes_at_score_threshold():
    from incubator_mxnet_tpu.onnx._runtime import _nms_numpy
    boxes = np.array([[[0, 0, 1, 1], [5, 5, 6, 6], [10, 10, 11, 11]]],
                     np.float32)
    scores = np.array([[[0.9, 0.5, 0.4]]], np.float32)
    sel = _nms_numpy(boxes, scores, -1, 0.5, 0.5)
    # score == threshold is KEPT (ONNX semantics: score > spec's
    # score_threshold filter uses >=-at-boundary like onnxruntime)
    assert sel.shape == (2, 3)
    assert set(sel[:, 2].tolist()) == {0, 1}


def test_bench_sweep_emits_default_policy_row(monkeypatch):
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench
    monkeypatch.setattr(
        bench, "bench_resnet50_train",
        lambda remat=None, **kw: {"none": 100.0, "dots": 90.0,
                                  "full": 110.0}[remat or "none"])
    row = bench._sweep_remat("train_bs32", (None, "dots", "full"))
    assert row["train_bs32_images_per_sec"] == 110.0          # sweep max
    assert row["train_bs32_remat_choice"] == "full"
    assert row["train_bs32_images_per_sec_default"] == 100.0  # remat=None


def test_put_along_axis_warns_on_raw_array():
    arr = mx.np.array(np.zeros((2, 3), np.float32))
    idx = mx.np.array(np.array([[1], [0]], np.int64))
    out = mx.np.put_along_axis(arr, idx, mx.np.array([[7.0], [8.0]]), 1)
    np.testing.assert_allclose(arr.asnumpy(), out.asnumpy())  # written back
    assert arr.asnumpy()[0, 1] == 7.0
    with pytest.warns(UserWarning, match="cannot mutate"):
        raw = np.zeros((2, 3), np.float32)
        out2 = mx.np.put_along_axis(raw, np.array([[1], [0]]),
                                    np.array([[7.0], [8.0]], np.float32), 1)
    assert raw[0, 1] == 0.0                                   # NOT mutated
    assert out2.asnumpy()[0, 1] == 7.0


# ---------------------------------------------------------------------------
# CI smoke: the benchmark produces valid JSON in --quick mode
# ---------------------------------------------------------------------------
def test_dispatch_bench_quick_smoke(tmp_path):
    out = tmp_path / "dispatch_quick.json"
    script = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "benchmark", "dispatch_bench.py")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    r = subprocess.run([sys.executable, script, "--quick", "--iters", "2",
                        "--out", str(out)],
                       capture_output=True, text=True, timeout=420, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    data = json.loads(out.read_text())
    assert data["meta"]["quick"] is True
    assert "per_op" in data and "model_step" in data
    for cfg in ("bulked", "immediate", "naive"):
        assert data["per_op"][cfg]["sync_us"] > 0
    # post-PR2 trees expose the counters in the artifact
    assert data["dispatch_stats"]["dispatch"] > 0
