"""npx.rnn — the fused flat-parameter RNN op (≙ _npx.rnn,
src/operator/rnn.cc), verified weight-for-weight against torch.nn.LSTM /
GRU / RNN, which share the reference's gate orders (LSTM [i,f,g,o],
GRU [r,z,n]) and flat-layout conventions."""
import numpy as np
import pytest
import torch

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import npx

T, N, C, H = 5, 3, 6, 4


def _flat_params(tmod, num_layers, bidirectional):
    """Pack a torch RNN module's weights into the reference flat layout:
    all W_i2h/W_h2h blocks layer-major (direction inner), then all
    b_i2h/b_h2h pairs."""
    D = 2 if bidirectional else 1
    ws, bs = [], []
    for layer in range(num_layers):
        for d in range(D):
            sfx = f"_l{layer}" + ("_reverse" if d else "")
            ws.append(getattr(tmod, f"weight_ih{sfx}").detach().numpy()
                      .ravel())
            ws.append(getattr(tmod, f"weight_hh{sfx}").detach().numpy()
                      .ravel())
    for layer in range(num_layers):
        for d in range(D):
            sfx = f"_l{layer}" + ("_reverse" if d else "")
            bs.append(getattr(tmod, f"bias_ih{sfx}").detach().numpy())
            bs.append(getattr(tmod, f"bias_hh{sfx}").detach().numpy())
    return np.concatenate(ws + bs).astype(np.float32)


@pytest.mark.parametrize("mode,L,bi", [
    ("lstm", 1, False), ("lstm", 2, False), ("lstm", 2, True),
    ("gru", 2, True), ("rnn_tanh", 1, False), ("rnn_relu", 2, False),
])
def test_npx_rnn_matches_torch(mode, L, bi):
    torch.manual_seed(3)
    D = 2 if bi else 1
    kind = {"lstm": torch.nn.LSTM, "gru": torch.nn.GRU,
            "rnn_tanh": lambda *a, **k: torch.nn.RNN(
                *a, nonlinearity="tanh", **k),
            "rnn_relu": lambda *a, **k: torch.nn.RNN(
                *a, nonlinearity="relu", **k)}[mode]
    tmod = kind(C, H, num_layers=L, bidirectional=bi)
    x = np.random.RandomState(0).randn(T, N, C).astype(np.float32)
    h0 = np.random.RandomState(1).randn(L * D, N, H).astype(np.float32)
    c0 = np.random.RandomState(2).randn(L * D, N, H).astype(np.float32)

    with torch.no_grad():
        if mode == "lstm":
            want, (hn, cn) = tmod(torch.tensor(x),
                                  (torch.tensor(h0), torch.tensor(c0)))
        else:
            want, hn = tmod(torch.tensor(x), torch.tensor(h0))

    flat = _flat_params(tmod, L, bi)
    out = npx.rnn(mx.np.array(x), mx.np.array(flat), mx.np.array(h0),
                  state_cell=mx.np.array(c0) if mode == "lstm" else None,
                  mode=mode, state_size=H, num_layers=L, bidirectional=bi,
                  state_outputs=True)
    got, got_h = out[0].asnumpy(), out[1].asnumpy()
    np.testing.assert_allclose(got, want.numpy(), rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(got_h, hn.numpy(), rtol=1e-4, atol=1e-5)
    if mode == "lstm":
        np.testing.assert_allclose(out[2].asnumpy(), cn.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_npx_rnn_differentiable():
    rng = np.random.RandomState(7)
    tmod = torch.nn.LSTM(C, H)
    flat = mx.np.array(_flat_params(tmod, 1, False))
    flat.attach_grad()
    x = mx.np.array(rng.randn(T, N, C).astype(np.float32))
    h0 = mx.np.array(np.zeros((1, N, H), np.float32))
    c0 = mx.np.array(np.zeros((1, N, H), np.float32))
    with mx.autograd.record():
        out = npx.rnn(x, flat, h0, state_cell=c0, mode="lstm",
                      state_size=H, num_layers=1)
        L = (out ** 2).sum()
    L.backward()
    g = flat.grad.asnumpy()
    assert np.isfinite(g).all() and np.abs(g).max() > 0
