"""io.DeviceFeed — async host→device input pipeline (ISSUE 4).

Contract under test: device-fed training is bitwise-identical to host-fed
(the feed only moves bytes earlier), feeder failures re-raise the ORIGINAL
exception in the consumer with a bounded consecutive-restart budget
(PrefetchingIter semantics), sharding-aware placement over a dp mesh,
transparent estimator/DataLoader opt-in via MXNET_PREFETCH_TO_DEVICE, the
FusedTrainStep redundant-transfer skip, and the io_bench --overlap smoke.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import incubator_mxnet_tpu as mx
from incubator_mxnet_tpu import fault, gluon, io as mxio
from incubator_mxnet_tpu import optimizer as opt_mod, parallel, profiler
from incubator_mxnet_tpu.gluon import nn
from incubator_mxnet_tpu.gluon.contrib import FusedTrainStep
from incubator_mxnet_tpu.io.device_feed import DeviceFeed, maybe_device_put


def _batches(n=4, b=8, din=8, dout=4, seed=0):
    rng = np.random.RandomState(seed)
    return [(rng.randn(b, din).astype(np.float32),
             rng.randn(b, dout).astype(np.float32)) for _ in range(n)]


def _mlp(seed=0):
    mx.seed(seed)
    net = nn.HybridSequential()
    net.add(nn.Dense(16, activation="relu", in_units=8),
            nn.Dense(4, in_units=16))
    net.initialize()
    return net


# ---------------------------------------------------------------------------
# staging basics
# ---------------------------------------------------------------------------
def test_device_feed_stages_batches_committed():
    import jax
    data = _batches(5)
    feed = DeviceFeed(data, depth=2)
    out = list(feed)
    assert len(out) == 5
    for (hx, hy), staged in zip(data, out):
        x, y = staged
        assert isinstance(x, mx.nd.NDArray)
        assert isinstance(x._arr, jax.Array) and x._arr.committed
        np.testing.assert_array_equal(x.asnumpy(), hx)
        np.testing.assert_array_equal(y.asnumpy(), hy)
    # a second epoch re-iterates the source
    assert len(list(feed)) == 5
    assert len(feed) == 5


def test_device_feed_databatch_and_passthrough():
    it = mxio.NDArrayIter(np.random.rand(20, 3).astype(np.float32),
                          np.arange(20, dtype=np.float32), batch_size=5)
    n = 0
    for b in DeviceFeed(it):
        n += 1
        assert isinstance(b, mxio.DataBatch)
        assert b.data[0]._arr.committed and b.label[0]._arr.committed
        assert b.pad == 0
    assert n == 4
    # non-array leaves pass through untouched
    feed = DeviceFeed([{"x": np.ones(2, np.float32), "tag": "a", "n": 3}])
    out = list(feed)[0]
    assert out["tag"] == "a" and out["n"] == 3
    assert out["x"]._arr.committed


def test_device_feed_preserves_namedtuple_batches():
    from collections import namedtuple
    Batch = namedtuple("Batch", ["x", "y"])
    src = [Batch(np.ones((4, 2), np.float32), np.zeros(4, np.float32))]
    out = list(DeviceFeed(src))[0]
    assert type(out) is Batch               # field access survives staging
    assert out.x._arr.committed and out.y._arr.committed


def test_device_feed_depth_validation_and_env(monkeypatch):
    with pytest.raises(mx.MXNetError, match="depth"):
        DeviceFeed([], depth=0)
    monkeypatch.setenv("MXNET_DEVICE_FEED_DEPTH", "3")
    assert DeviceFeed([])._depth == 3


def test_device_feed_honors_consumer_device_scope():
    """The consumer thread's `with mx.cpu(i):` scope decides placement —
    the feeder thread's (empty) thread-local stacks must not."""
    import jax
    want = jax.local_devices(backend="cpu")[1]   # 8 forced host devices
    with mx.cpu(1):
        out = list(DeviceFeed([np.ones((4, 2), np.float32)]))[0]
    assert out._arr.committed
    assert tuple(out._arr.sharding.device_set) == (want,)


def test_device_feed_reset_passthrough():
    it = mxio.NDArrayIter(np.arange(12, dtype=np.float32).reshape(12, 1),
                          batch_size=4)
    feed = DeviceFeed(it)
    assert len(list(feed)) == 3
    feed.reset()     # forwards to NDArrayIter.reset -> epoch 2 has batches
    assert len(list(feed)) == 3


# ---------------------------------------------------------------------------
# parity: device-fed == host-fed, bitwise
# ---------------------------------------------------------------------------
def test_device_fed_fused_step_bitwise_parity():
    data = _batches(6, seed=3)
    loss_fn = gluon.loss.L2Loss()

    def make_step(net):
        return FusedTrainStep(
            net, lambda n, x, y: loss_fn(n(x), y).mean(),
            opt_mod.create("sgd", learning_rate=0.1, momentum=0.9))

    net_a = _mlp(1)
    step = make_step(net_a)
    for x, y in data:                       # host-fed
        step(mx.np.array(x), mx.np.array(y))

    net_b = _mlp(1)
    step = make_step(net_b)
    for x, y in DeviceFeed(data):           # device-fed
        step(x, y)

    pa, pb = net_a.collect_params(), net_b.collect_params()
    assert set(pa) == set(pb)
    for k in pa:
        a, b = pa[k].data().asnumpy(), pb[k].data().asnumpy()
        np.testing.assert_array_equal(a, b, err_msg=k)  # BITWISE


# ---------------------------------------------------------------------------
# failure semantics
# ---------------------------------------------------------------------------
def test_feeder_death_surfaces_original_exception():
    class Boom(RuntimeError):
        pass

    def source():
        yield np.zeros(3, np.float32)
        raise Boom("feeder died")

    feed = DeviceFeed(source())
    it = iter(feed)
    next(it)
    with pytest.raises(Boom, match="feeder died"):
        next(it)


def test_feeder_restart_budget():
    profiler.feed_stats(reset=True)
    # persistent transient fault: budget of 2 consecutive restarts is
    # consumed, the 3rd hit re-raises the ORIGINAL IOError in the consumer
    with fault.scope("io.device_feed:*:ioerror"):
        feed = DeviceFeed([np.zeros(2, np.float32)] * 3, max_restarts=2)
        with pytest.raises(IOError, match="injected ioerror"):
            list(feed)
    s = profiler.feed_stats()
    assert s["restarts"] == 2
    assert s["failures"] == 1
    # a single transient hit is retried in place: nothing lost
    with fault.scope("io.device_feed:2:ioerror"):
        feed = DeviceFeed([np.zeros(2, np.float32)] * 3, max_restarts=2)
        assert len(list(feed)) == 3


# ---------------------------------------------------------------------------
# sharding over a dp mesh
# ---------------------------------------------------------------------------
def test_prefetch_to_device_dp_sharding():
    mesh = parallel.make_mesh(dp=8)
    with mesh:
        feed = mxio.prefetch_to_device(
            [np.random.rand(16, 4).astype(np.float32) for _ in range(3)])
        outs = list(feed)
    assert len(outs) == 3
    want = mesh.sharding("dp", None)
    for b in outs:
        assert b._arr.sharding.is_equivalent_to(want, 2)
    # helper returns None with no mesh / no dp axis
    assert parallel.data_sharding(2) is None


# ---------------------------------------------------------------------------
# FusedTrainStep input staging (satellite: redundant-transfer skip)
# ---------------------------------------------------------------------------
def test_fused_step_skips_committed_inputs():
    data = _batches(3, seed=5)
    loss_fn = gluon.loss.L2Loss()
    net = _mlp(2)
    step = FusedTrainStep(net, lambda n, x, y: loss_fn(n(x), y).mean(),
                          opt_mod.create("sgd", learning_rate=0.1))
    profiler.feed_stats(reset=True)
    for x, y in DeviceFeed(data):
        step(x, y)
    s = profiler.feed_stats()
    # the feed transferred each leaf once; the step re-transferred NOTHING
    assert s["host_transfers"] == 6       # 3 batches x 2 leaves, feed-side
    assert s["device_put_skipped"] == 6   # step-side: all skips
    # raw numpy fed straight to the step counts as a real transfer
    profiler.feed_stats(reset=True)
    step(data[0][0], data[0][1])
    s = profiler.feed_stats()
    assert s["host_transfers"] == 2 and s["device_put_skipped"] == 0


def test_maybe_device_put_counters():
    import jax
    import jax.numpy as jnp
    profiler.feed_stats(reset=True)
    a = maybe_device_put(np.ones(4, np.float32))       # host -> transfer
    assert a.committed
    b = maybe_device_put(a)                            # committed -> skip
    assert b is a
    c = maybe_device_put(jnp.ones(4))                  # uncommitted -> pin
    assert c.committed
    s = profiler.feed_stats()
    assert (s["host_transfers"], s["device_put_skipped"],
            s["recommitted"]) == (1, 1, 1)


# ---------------------------------------------------------------------------
# transparent opt-in: estimator.fit + DataLoader
# ---------------------------------------------------------------------------
def test_estimator_env_optin(monkeypatch):
    monkeypatch.setenv("MXNET_PREFETCH_TO_DEVICE", "1")
    net = _mlp(4)
    est = gluon.contrib.estimator.Estimator(
        net, gluon.loss.L2Loss(),
        train_metrics=gluon.metric.Loss("l"),
        trainer=gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.05}))
    data = [(mx.np.array(x), mx.np.array(y)) for x, y in _batches(3)]
    profiler.feed_stats(reset=True)
    est.fit(train_data=data, epochs=2)
    s = profiler.feed_stats()
    assert s["batches_consumed"] == 6     # fit consumed through the feed
    assert s["epochs"] == 2


def test_dataloader_prefetch_to_device():
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    ds = ArrayDataset(np.arange(24, dtype=np.float32).reshape(12, 2),
                      np.arange(12, dtype=np.float32))
    dl = DataLoader(ds, batch_size=4, prefetch_to_device=True)
    assert dl._feeds_device
    profiler.feed_stats(reset=True)
    seen = list(dl)
    assert len(seen) == 3
    for x, y in seen:
        assert x._arr.committed and y._arr.committed
    assert profiler.feed_stats()["batches_fed"] == 3
    # off by default: plain host batches, no feeder involvement
    dl = DataLoader(ds, batch_size=4)
    assert not dl._feeds_device


def test_estimator_respects_explicit_loader_optout(monkeypatch):
    """DataLoader(prefetch_to_device=False) is an explicit opt-out the
    env-driven estimator wrap must not override."""
    from incubator_mxnet_tpu.gluon.data import ArrayDataset, DataLoader
    monkeypatch.setenv("MXNET_PREFETCH_TO_DEVICE", "1")
    ds = ArrayDataset(np.random.rand(12, 8).astype(np.float32),
                      np.random.rand(12, 4).astype(np.float32))
    dl = DataLoader(ds, batch_size=4, prefetch_to_device=False)
    assert dl._prefetch_opt_out
    net = _mlp(6)
    est = gluon.contrib.estimator.Estimator(
        net, gluon.loss.L2Loss(),
        train_metrics=gluon.metric.Loss("l"),
        trainer=gluon.Trainer(net.collect_params(), "sgd",
                              {"learning_rate": 0.05}))
    profiler.feed_stats(reset=True)
    est.fit(train_data=dl, epochs=1)
    assert profiler.feed_stats()["batches_consumed"] == 0  # no feed involved


# ---------------------------------------------------------------------------
# satellite: PrefetchingIter composition fixes
# ---------------------------------------------------------------------------
def test_prefetching_iter_multi_iter_message_names_wrapper():
    it = mxio.NDArrayIter(np.zeros((8, 2), np.float32), batch_size=4)
    it2 = mxio.NDArrayIter(np.zeros((8, 2), np.float32), batch_size=4)
    with pytest.raises(mx.MXNetError, match="DeviceFeed"):
        mxio.PrefetchingIter([it, it2])


def test_prefetching_iter_len_passthrough():
    it = mxio.NDArrayIter(np.zeros((10, 2), np.float32), batch_size=4)
    assert len(it) == 3                    # pad: ceil(10/4)
    pf = mxio.PrefetchingIter(it)
    assert len(pf) == 3
    assert pf.provide_data == it.provide_data
    # composes with DeviceFeed (feeds DataBatches through) and epoch loops
    feed = DeviceFeed(pf)
    assert len(feed) == 3
    assert sum(1 for _ in feed) == 3


# ---------------------------------------------------------------------------
# stats + trace lane
# ---------------------------------------------------------------------------
def test_feed_stats_occupancy_and_stall_accounting():
    profiler.feed_stats(reset=True)
    feed = DeviceFeed(_batches(4), depth=2)
    list(feed)
    s = profiler.feed_stats()
    assert s["batches_fed"] == 4 and s["batches_consumed"] == 4
    assert s["occupancy_samples"] == 4     # REAL batches only, no sentinel
    assert 0.0 < s["occupancy_mean"] <= 3.0
    assert s["stall_data_us"] >= 0.0 and s["stall_compute_us"] >= 0.0
    # reset zeroes
    s = profiler.feed_stats(reset=True)
    assert profiler.feed_stats()["batches_fed"] == 0


def test_feed_chrome_trace_lane(tmp_path):
    profiler.start()
    try:
        list(DeviceFeed(_batches(2)))
    finally:
        profiler.stop()
    out = str(tmp_path / "trace.json")
    profiler.dump(filename=out)
    with open(out) as f:
        names = {e["name"] for e in json.load(f)["traceEvents"]}
    assert "io.feed" in names and "feed.stage" in names


# ---------------------------------------------------------------------------
# io_bench --overlap --quick smoke (tier-1; the committed artifact pair
# benchmark/results/feed_r08_{before,after}.json is the full-mode run)
# ---------------------------------------------------------------------------
def test_io_bench_overlap_quick_smoke():
    here = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    r = subprocess.run(
        [sys.executable, os.path.join(here, "benchmark", "io_bench.py"),
         "--overlap", "--quick"],
        capture_output=True, text=True, timeout=300, cwd=here)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    for k in ("data_ms", "compute_ms", "host_fed_step_ms",
              "device_fed_step_ms", "device_fed_vs_max",
              "hidden_input_fraction", "trials"):
        assert k in out, k
    assert out["data_ms"] > 0 and out["compute_ms"] > 0
    assert 0.0 <= out["hidden_input_fraction"] <= 1.0
    assert len(out["trials"]) >= 1
    # the artifact carries the backend preflight verdict + registry state
    assert out["backend_ok"] is True
    assert out["telemetry"]["feed.batches_consumed"] > 0
