"""mx.sanitize — the runtime twin of the mxlint compiled-contract
passes (ISSUE 20). Planted violations must trip with TYPED errors;
real clean loops (engine, elastic) must stay silent; everything is off
by default with a zero-cost wrapper."""
import numpy as np
import pytest

from incubator_mxnet_tpu import sanitize, serve
from incubator_mxnet_tpu.base import MXNetError
from incubator_mxnet_tpu.serve.kv_pool import KVCachePool


CFG = dict(vocab=64, embed=32, layers=2, heads=4, head_dim=8, max_len=48)


@pytest.fixture(autouse=True)
def _isolate():
    yield
    sanitize.clear()


def _prog(donate=(0,)):
    import jax
    return sanitize.maybe_wrap_donated(
        jax.jit(lambda w, g: w - g, donate_argnums=donate),
        donate, "step")


# ---------------------------------------------------------------------------
# mode plumbing: off by default, zero-cost when off
# ---------------------------------------------------------------------------
def test_off_by_default_wrapper_is_identity():
    import jax
    assert sanitize.modes() == frozenset()
    f = jax.jit(lambda x: x, donate_argnums=(0,))
    assert sanitize.maybe_wrap_donated(f, (0,), "t") is f


def test_mode_parsing(monkeypatch):
    monkeypatch.setenv("MXNET_SANITIZE", "donation, retrace")
    assert sanitize.modes() == frozenset({"donation", "retrace"})
    monkeypatch.setenv("MXNET_SANITIZE", "all")
    assert sanitize.modes() == frozenset({"donation", "retrace", "slot"})
    monkeypatch.setenv("MXNET_SANITIZE", "turbo")
    with pytest.raises(MXNetError, match="unknown mode"):
        sanitize.modes()


def test_scope_overrides_and_restores():
    assert not sanitize.enabled("donation")
    with sanitize.scope("donation"):
        assert sanitize.enabled("donation")
        assert not sanitize.enabled("slot")
    assert not sanitize.enabled("donation")


# ---------------------------------------------------------------------------
# donation mode
# ---------------------------------------------------------------------------
def test_donation_use_after_donate_trips_with_provenance():
    import jax.numpy as jnp
    with sanitize.scope("donation"):
        step = _prog()
        w = jnp.ones((4,))
        step(w, jnp.ones((4,)))
        with pytest.raises(sanitize.DonationViolation) as ei:
            step(w, jnp.ones((4,)))          # w was consumed above
        msg = str(ei.value)
        assert "argument 0" in msg and "`step`" in msg
        assert isinstance(ei.value, MXNetError)


def test_donation_rebind_from_output_is_silent():
    import jax.numpy as jnp
    with sanitize.scope("donation"):
        step = _prog()
        w = jnp.ones((4,))
        for _ in range(4):
            w = step(w, jnp.ones((4,)))      # clean: rebinds each wave
        assert float(w[0]) == -3.0


def test_donation_deletes_consumed_buffer_like_tpu_would():
    # CPU donation is a no-op; the sanitizer makes the donated leaf die
    # for real, so the silent-on-CPU bug class fails in CI too
    import jax.numpy as jnp
    with sanitize.scope("donation"):
        step = _prog()
        w = jnp.ones((4,))
        step(w, jnp.ones((4,)))
        assert w.is_deleted()


# ---------------------------------------------------------------------------
# retrace mode
# ---------------------------------------------------------------------------
def test_retrace_poll_noop_until_armed():
    import jax.numpy as jnp
    with sanitize.scope("retrace"):
        step = _prog()
        step(jnp.ones((4,)), jnp.ones((4,)))
        sanitize.poll("never armed")         # silent


def test_retrace_growth_after_arm_trips_with_drift():
    import jax.numpy as jnp
    with sanitize.scope("retrace"):
        step = _prog()
        step(jnp.ones((4,)), jnp.ones((4,)))
        sanitize.arm()
        sanitize.poll("steady")              # silent: no growth
        step(jnp.ones((8,)), jnp.ones((8,)))  # shape drift -> recompile
        with pytest.raises(sanitize.RetraceViolation) as ei:
            sanitize.poll("steady")
        msg = str(ei.value)
        assert "`step`" in msg and "(4,)" in msg and "(8,)" in msg


def test_retrace_new_program_variant_after_arm_trips():
    import jax.numpy as jnp
    with sanitize.scope("retrace"):
        step = _prog()
        step(jnp.ones((4,)), jnp.ones((4,)))
        sanitize.arm()
        late = _prog()                       # a variant born after warmup
        late(jnp.ones((2,)), jnp.ones((2,)))
        with pytest.raises(sanitize.RetraceViolation, match="NEW program"):
            sanitize.poll("steady")


def test_steady_state_context_manager():
    import jax.numpy as jnp
    with sanitize.scope("retrace"):
        step = _prog()
        step(jnp.ones((4,)), jnp.ones((4,)))
        with sanitize.steady_state("region"):
            step(jnp.ones((4,)), jnp.ones((4,)))   # same shape: fine
        with pytest.raises(sanitize.RetraceViolation):
            with sanitize.steady_state("region"):
                step(jnp.ones((16,)), jnp.ones((16,)))


# ---------------------------------------------------------------------------
# slot mode: the canary row
# ---------------------------------------------------------------------------
def test_slot_canary_silent_then_trips_on_corruption():
    pool = KVCachePool(2, layers=1, max_len=8, heads=2, head_dim=4)
    canary = sanitize.SlotCanary(pool)
    canary.check("wave")                     # sentinel intact
    canary.check("wave")
    # a program writing through the slot masks would look like this:
    pool.k = pool.k.at[canary.slot].set(0.0)
    # the probe is pipelined one wave deep: the corrupt probe is read
    # on the NEXT check, so the trip surfaces at most one wave late
    with pytest.raises(sanitize.SlotCanaryError) as ei:
        canary.check("wave")
        canary.check("wave")
    assert f"slot {canary.slot}" in str(ei.value)
    canary.rearm()
    canary.check("wave")                     # re-poisoned: clean again
    canary.check("wave")
    canary.release()


def test_slot_canary_survives_reallocate_via_rearm():
    pool = KVCachePool(2, layers=1, max_len=8, heads=2, head_dim=4)
    canary = sanitize.SlotCanary(pool)
    pool.reallocate()                        # slab replaced wholesale
    canary.rearm()
    canary.check("after-reallocate")
    canary.check("after-reallocate")
    canary.release()


# ---------------------------------------------------------------------------
# engine integration: silent on a clean loop, typed errors on breaches
# ---------------------------------------------------------------------------
def _engine(model, **kw):
    kw.setdefault("max_slots", 4)
    kw.setdefault("decode_steps", 3)
    return serve.ContinuousEngine(model, **kw)


def _workload(eng, n=6, seed=0):
    rng = np.random.RandomState(seed)
    futs = [eng.submit(rng.randint(1, 64,
                                   size=rng.randint(2, 12)).tolist(),
                       int(rng.randint(1, 10))) for _ in range(n)]
    return [f.result(timeout=120) for f in futs]


def test_engine_clean_loop_silent_under_all_modes():
    with sanitize.scope("all"):
        model = serve.CachedDecoder(serve.DecoderConfig(**CFG), seed=3)
        with _engine(model) as eng:
            assert eng._canary is not None   # slot mode claimed its row
            outs = _workload(eng)
            assert all(len(o) >= 1 for o in outs)
            assert eng._canary.waves > 0     # checked every decode wave
            assert eng.compile_cache_size() == eng._warm_cache_size


def test_engine_slot_canary_catches_out_of_mask_write():
    with sanitize.scope("slot"):
        model = serve.CachedDecoder(serve.DecoderConfig(**CFG), seed=3)
        with _engine(model) as eng:
            _workload(eng, n=2)
            # corrupt the canary row the way a mask-escaping scatter
            # would; the NEXT decode wave's check fails its requests
            # with the typed error, then the engine recovers
            eng.pool.k = eng.pool.k.at[eng._canary.slot].set(0.0)
            f = eng.submit([1, 2, 3], 6)
            with pytest.raises(sanitize.SlotCanaryError):
                f.result(timeout=120)
            # handler reallocated + re-poisoned: engine keeps serving
            out = eng.submit([4, 5, 6], 4).result(timeout=120)
            assert len(out) >= 1


def test_engine_retrace_sentinel_catches_post_warmup_variant():
    with sanitize.scope("retrace"):
        model = serve.CachedDecoder(serve.DecoderConfig(**CFG), seed=3)
        with _engine(model) as eng:
            _workload(eng, n=2)
            # a bypassing caller compiles a prefill width the warmup
            # never saw — exactly the drift the static pass hunts
            import jax.numpy as jnp
            side = model.new_pool(2)
            kb, vb = side.buffers()
            model.prefill(kb, vb,
                          jnp.ones((1, 7), dtype=jnp.int32),
                          jnp.full((1,), 7, dtype=jnp.int32),
                          jnp.zeros((1,), dtype=jnp.int32))
            f = eng.submit([1, 2, 3], 6)
            with pytest.raises(sanitize.RetraceViolation):
                f.result(timeout=120)


# ---------------------------------------------------------------------------
# elastic integration: clean training loop stays silent
# ---------------------------------------------------------------------------
def test_elastic_clean_loop_silent_under_retrace():
    from incubator_mxnet_tpu.fault.elastic import ElasticTrainer

    def loss_fn(params, batch):
        import jax.numpy as jnp
        x, y = batch
        pred = x @ params["w"]
        return jnp.mean((pred - y) ** 2)

    rng = np.random.RandomState(0)
    params = {"w": rng.randn(4, 2).astype(np.float32)}
    with sanitize.scope("retrace"):
        tr = ElasticTrainer(loss_fn, params=params, optimizer="sgd")
        batch = (rng.randn(8, 4).astype(np.float32),
                 rng.randn(8, 2).astype(np.float32))
        losses = [tr.step(batch) for _ in range(3)]
        assert all(np.isfinite(l) for l in losses)
