"""Shared helpers for compiled C-ABI consumer tests (test_c_api.py and
tests/nightly/test_cpp_resnet50.py): build flags and the subprocess
environment that forces the CPU platform for the embedded runtime."""
import os
import subprocess
import sys
import sysconfig

from incubator_mxnet_tpu.native import build_capi, capi_header_dir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def subprocess_env():
    env = dict(os.environ)
    site = [p for p in sys.path if p.endswith("site-packages")]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + site)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # no virtual mesh needed; keep compiles fast
    libdir = sysconfig.get_config_var("LIBDIR")
    env["LD_LIBRARY_PATH"] = os.pathsep.join(
        [os.path.dirname(build_capi()), libdir,
         env.get("LD_LIBRARY_PATH", "")])
    return env


def compile_consumer(src, out):
    lib = build_capi()
    compiler = "g++" if src.endswith(".cc") else "gcc"
    cmd = [compiler, "-O1", src, "-o", out, f"-I{capi_header_dir()}",
           lib, f"-Wl,-rpath,{os.path.dirname(lib)}"]
    if src.endswith(".cc"):
        cmd += ["-std=c++17", "-pthread"]
    subprocess.run(cmd, check=True, capture_output=True)
    return out
