"""serve.prefix_cache: ref-counted shared-prefix KV cache bookkeeping.

Contracts under test (ISSUE 19 acceptance):
  * block-quantized longest-prefix match, capped at len(prompt)-1 so at
    least one suffix token always remains to prefill
  * a hash hit is NEVER trusted: the stored token block is compared
    against the prompt, a mismatch counts `prefix.collisions` and falls
    through to shorter prefixes / recompute — wrong KV is impossible by
    construction (forced via the `_hash_override` test hook)
  * ref-counted pinning: LRU eviction can never reclaim an entry whose
    refcount > 0, `clear()` refuses with live refs, and releasing an
    unheld entry is a typed `PrefixCacheError` (double release)
  * `PREFIX_STATS` counter catalog: "hits", "misses", "cached_tokens",
    "evictions", "collisions" (docs/OBSERVABILITY.md `prefix.*`)

Pure host bookkeeping — no jax, no engine; the engine-level integration
(row copies, suffix prefill, budget billing) lives in
tests/test_continuous.py.
"""
import numpy as np
import pytest

from incubator_mxnet_tpu import serve
from incubator_mxnet_tpu.serve import prefix_cache as pc
from incubator_mxnet_tpu.serve.prefix_cache import (
    PREFIX_STATS, PrefixCache, PrefixCacheError, prefix_stats,
    rolling_hash)


def _prompt(*tokens):
    return np.asarray(tokens, dtype=np.int32)


# ---------------------------------------------------------------------------
# hashing + block-quantized match
# ---------------------------------------------------------------------------
def test_rolling_hash_is_prefix_consistent_and_order_sensitive():
    toks = [5, 9, 1, 7]
    assert rolling_hash(toks) == rolling_hash(np.asarray(toks))
    assert rolling_hash(toks) != rolling_hash([9, 5, 1, 7])
    # leading token id 0 must not hash like the empty prefix
    assert rolling_hash([0]) != rolling_hash([])


def test_match_returns_longest_verified_block_prefix():
    cache = PrefixCache(block=4, rows=[10, 11])
    p = _prompt(*range(1, 11))                    # 10 tokens
    short_row = cache.insert(p[:4])               # 4-token entry
    row = cache.insert(p)                         # 8 of 10 tokens
    assert {short_row, row} == {10, 11}
    assert [e[0] for e in cache.entries()] == [4, 8]
    before = prefix_stats()
    entry, n = cache.match(p)
    assert entry is not None and n == 8 and entry.refs == 1
    # a prompt equal to an entry's tokens may reuse at most len-1 of
    # them (one suffix token must remain to prefill), so the walk
    # falls back to the SHORTER cached entry
    e2, n2 = cache.match(p[:8])
    assert n2 == 4 and e2.row == short_row
    after = prefix_stats()
    assert after["hits"] - before["hits"] == 2
    assert after["cached_tokens"] - before["cached_tokens"] == 12
    cache.release(entry)
    cache.release(e2)
    # shorter-than-one-block prompts can never match (and misses count)
    assert cache.match(_prompt(1, 2, 3)) == (None, 0)
    assert prefix_stats()["misses"] - after["misses"] == 1


def test_match_acquire_false_is_a_free_peek():
    cache = PrefixCache(block=2, rows=[0])
    cache.insert(_prompt(1, 2, 3, 4))
    before = prefix_stats()
    entry, n = cache.match(_prompt(1, 2, 3, 4, 5), acquire=False)
    assert n == 4 and entry.refs == 0
    after = prefix_stats()
    assert after["hits"] == before["hits"]
    assert after["cached_tokens"] == before["cached_tokens"]


# ---------------------------------------------------------------------------
# hash-collision safety (the _hash_override hook)
# ---------------------------------------------------------------------------
def test_hash_collision_is_verified_rejected_and_counted():
    cache = PrefixCache(block=4, rows=[7])
    cache._hash_override = lambda tokens: 42      # every block collides
    assert cache.insert(_prompt(1, 2, 3, 4)) == 7
    before = prefix_stats()
    # same hash bucket, different tokens: verify MUST reject the entry
    # and fall through to a miss (recompute), never reuse wrong KV
    entry, n = cache.match(_prompt(9, 9, 9, 9, 5))
    assert (entry, n) == (None, 0)
    after = prefix_stats()
    assert after["collisions"] - before["collisions"] == 1
    assert after["misses"] - before["misses"] == 1
    # the true owner of the bucket still hits, through the collision
    entry, n = cache.match(_prompt(1, 2, 3, 4, 5))
    assert n == 4 and entry.row == 7
    cache.release(entry)


def test_collision_on_insert_appends_to_chain_not_overwrites():
    cache = PrefixCache(block=2, rows=[0, 1])
    cache._hash_override = lambda tokens: 13
    assert cache.insert(_prompt(1, 2)) is not None
    assert cache.insert(_prompt(3, 4)) is not None   # same bucket
    ea, na = cache.match(_prompt(1, 2, 9))
    eb, nb = cache.match(_prompt(3, 4, 9))
    assert na == nb == 2 and ea.row != eb.row
    cache.release(ea)
    cache.release(eb)


# ---------------------------------------------------------------------------
# ref-counted pinning vs LRU eviction
# ---------------------------------------------------------------------------
def test_lru_evicts_only_unpinned_and_refuses_when_all_pinned():
    cache = PrefixCache(block=2, rows=[0, 1])
    pa = _prompt(1, 2)
    pb = _prompt(3, 4)
    assert cache.insert(pa) is not None
    assert cache.insert(pb) is not None
    # pin A (the LRU-older entry); publishing C must evict B, never A
    ea, _ = cache.match(_prompt(1, 2, 9))
    before = prefix_stats()
    rc = cache.insert(_prompt(5, 6))
    assert rc is not None
    assert prefix_stats()["evictions"] - before["evictions"] == 1
    lens_rows = cache.entries()
    assert (2, ea.row, 1) in lens_rows
    assert cache.match(_prompt(3, 4, 9)) == (None, 0)   # B is gone
    # pin C too: every row referenced -> insert REFUSES, no eviction
    ec, _ = cache.match(_prompt(5, 6, 9))
    before = prefix_stats()
    assert cache.insert(_prompt(7, 8)) is None
    assert prefix_stats()["evictions"] == before["evictions"]
    cache.release(ea)
    cache.release(ec)


def test_reinsert_of_cached_prefix_touches_lru_instead_of_duplicating():
    cache = PrefixCache(block=2, rows=[0, 1])
    assert cache.insert(_prompt(1, 2)) is not None
    assert cache.insert(_prompt(3, 4)) is not None
    # re-publish A: no new row, but A becomes most-recently-used...
    assert cache.insert(_prompt(1, 2)) is None
    # ...so the next eviction takes B
    assert cache.insert(_prompt(5, 6)) is not None
    assert cache.match(_prompt(1, 2, 9), acquire=False)[1] == 2
    assert cache.match(_prompt(3, 4, 9), acquire=False) == (None, 0)


# ---------------------------------------------------------------------------
# lifecycle misuse is typed
# ---------------------------------------------------------------------------
def test_double_release_raises_typed_prefix_cache_error():
    cache = PrefixCache(block=2, rows=[0])
    cache.insert(_prompt(1, 2))
    entry, _ = cache.match(_prompt(1, 2, 3))
    cache.release(entry)
    with pytest.raises(PrefixCacheError, match="double release"):
        cache.release(entry)
    # typed: admission/retire paths catch it as a ServeError
    assert issubclass(PrefixCacheError, serve.ServeError)


def test_clear_refuses_with_live_refs_then_reclaims_rows():
    cache = PrefixCache(block=2, rows=[4, 5])
    cache.insert(_prompt(1, 2))
    entry, _ = cache.match(_prompt(1, 2, 3))
    with pytest.raises(PrefixCacheError, match="live reference"):
        cache.clear()
    cache.release(entry)
    cache.clear()
    assert cache.entries() == []
    # both rows are claimable again
    assert cache.insert(_prompt(1, 2)) is not None
    assert cache.insert(_prompt(3, 4)) is not None


# ---------------------------------------------------------------------------
# stats plumbing
# ---------------------------------------------------------------------------
def test_prefix_stats_group_keys_and_reset():
    snap = prefix_stats()
    assert set(snap) == {"hits", "misses", "cached_tokens", "evictions",
                         "collisions"}
    assert PREFIX_STATS is not None
    # snapshot+reset is atomic (the serve_stats contract)
    prefix_stats(reset=True)
    z = prefix_stats()
    assert all(v == 0 for v in z.values())


def test_cache_stats_snapshot_tracks_residency_and_refs():
    cache = PrefixCache(block=4, rows=[0, 1, 2])
    cache.insert(_prompt(*range(1, 9)))
    entry, _ = cache.match(_prompt(*range(1, 10)))
    st = cache.stats()
    assert st == {"block": 4, "capacity": 3, "entries": 1,
                  "resident_tokens": 8, "live_refs": 1}
    cache.release(entry)
    assert cache.stats()["live_refs"] == 0


def test_prefix_family_dotted_telemetry_surface():
    """Every PREFIX_STATS counter surfaces under the dotted `prefix.*`
    telemetry names (the mxlint `stats-family-untested` coverage rule
    requires the family's dotted export to be pinned by a test)."""
    from incubator_mxnet_tpu import telemetry
    before = telemetry.snapshot()
    for name in ("prefix.hits", "prefix.misses", "prefix.cached_tokens",
                 "prefix.evictions", "prefix.collisions"):
        assert name in before, name
    cache = PrefixCache(block=2, rows=[0])
    cache.insert(_prompt(1, 2, 3, 4))
    entry, n = cache.match(_prompt(1, 2, 3, 4, 5))  # acquiring lookup
    if entry is not None:
        cache.release(entry)
    after = telemetry.snapshot()
    # a live lookup moved the family's dotted counters, not just the dict
    assert (after["prefix.hits"] + after["prefix.misses"]
            > before["prefix.hits"] + before["prefix.misses"])
